"""Build hook for the native core (reference analog: setup.py driving the
CMake build — SURVEY.md §2.5, scaled to this dependency-free core).

``pip install .`` compiles ``horovod_tpu/native/libhvd_tpu_core.so`` from
``horovod_tpu/native/src`` with the system g++ — no Python headers needed
(the core is a flat C API loaded via ctypes, not a CPython extension).
The build is marked optional: on a machine with no C++ toolchain the
install still succeeds and the framework uses its Python fallback
controller (single-process) or the lazy in-tree `make` (dev checkouts).
"""

import os
import subprocess

from setuptools import Extension, setup
from setuptools.command.build_ext import build_ext

_SRC_DIR = os.path.join("horovod_tpu", "native", "src")
_SOURCES = ["message.cc", "controller.cc", "c_api.cc"]
_CXXFLAGS = ["-O2", "-fPIC", "-std=c++17", "-Wall", "-Wextra", "-pthread"]


class NativeCoreExtension(Extension):
    def __init__(self):
        super().__init__(
            "horovod_tpu.native.libhvd_tpu_core",
            sources=[os.path.join(_SRC_DIR, s) for s in _SOURCES],
        )
        self.optional = True  # no toolchain -> pure-python install


class BuildNativeCore(build_ext):
    def get_ext_filename(self, fullname):
        # a plain shared library, dlopened by ctypes: no CPython ABI
        # suffix — the loader looks for exactly "libhvd_tpu_core.so"
        if fullname.split(".")[-1] == "libhvd_tpu_core":
            return os.path.join(*fullname.split(".")[:-1],
                                "libhvd_tpu_core.so")
        return super().get_ext_filename(fullname)

    def build_extension(self, ext):
        if not isinstance(ext, NativeCoreExtension):
            return super().build_extension(ext)
        out = self.get_ext_fullpath(ext.name)
        os.makedirs(os.path.dirname(out), exist_ok=True)
        cxx = os.environ.get("CXX", "g++")
        cmd = [cxx, *_CXXFLAGS, "-shared", "-o", out, *ext.sources]
        self.announce(" ".join(cmd), level=2)
        # failures must surface as CCompilerError: that is the ONLY family
        # setuptools' optional-extension filter swallows — a raw
        # FileNotFoundError (no g++) would abort the whole install instead
        # of degrading to the documented pure-python fallback
        try:
            subprocess.run(cmd, check=True)
        except (OSError, subprocess.SubprocessError) as e:
            from distutils.errors import CCompilerError

            raise CCompilerError(f"native core build failed: {e}") from e


setup(
    ext_modules=[NativeCoreExtension()],
    cmdclass={"build_ext": BuildNativeCore},
)
