#!/usr/bin/env python
"""Continuous-batching serving benchmark (PERF.md rounds 8 + 9).

Generates synthetic OPEN-LOOP loads — requests arrive on their own
clock, independent of completions, the way real traffic does — and
drives them through ``horovod_tpu.serving``:

  continuous   the ServingEngine: iteration-level admit/evict over the
               paged KV cache (Orca-style), requests staged to device
               through the DevicePrefetcher while steps compute;
  static       the pre-Orca baseline (``ServingEngine.run_static``):
               fixed request batches held until every member finishes,
               contiguous worst-case KV reservations.  Batches start
               only once all members have ARRIVED (honest open-loop
               head-of-line blocking);
  prefix_off / prefix_on
               the round-9 shared-prefix A/B: N requests over K prompt
               templates (the shared-system-prompt production shape)
               on ONE shared engine, prefix cache toggled between legs
               — same params, same compiled tier programs, so the A/B
               isolates the CACHE.  Emits TTFT p50/p99,
               ``prefix_hit_rate`` and ``prefill_tokens_computed``;
  unchunked / chunked
               the round-9 burst A/B: a steady decode load with a
               long-prompt burst injected mid-run, once on an engine
               that prefills whole prompts and once on one that
               streams them in ``HVD_TPU_SERVE_PREFILL_CHUNK``-token
               chunks packed beside the decode batch.  Emits the
               steady requests' inter-token decode-gap p50/p99 and the
               spike ratio — chunking's claim is the flat p99;
  multichip    the round-10 tensor-sharded A/B (--shards, default 8,
               smoke 2): one model head-sharded over the virtual ICI
               mesh vs the single-device engine on the same templated
               load — token-identity asserted, per-chip decode read
               bytes and psum stream both modeled AND measured from
               the lowered StableHLO (modeled == measured or the leg
               fails).  The full run writes MULTICHIP_r06.json.
  spec_base_* / spec_on_*
               the round-15 speculative-decoding A/B: the same load
               driven through a plain engine and one with the
               prompt-lookup drafter on (fresh engines — the spec
               menu differs), once on a TEMPLATE-HEAVY load (periodic
               prompts, the n-gram drafter's home turf) and once on
               ADVERSARIAL-RANDOM text (the drafter's worst case —
               the bit-identity guarantee is the claim there).  Emits
               ``acceptance_rate``, drafted/accepted/rolled-back
               token counts, ``tokens_per_step`` and the verify-span
               trace columns; byte-identical outputs asserted per
               load before reporting.

Greedy sampling everywhere, so the bench asserts token-for-token
identical outputs across every A/B before it reports a single number
(the oracle from tests/test_serving.py, run on the bench's own load —
including bit-identical streams with the prefix cache on vs off).

Every leg emits ONE bench-style JSON line on stdout (human summary on
stderr).  Scheduling, caching and chunking wins are CPU-measurable —
they are steps/tokens saved, not FLOPs saved — so the smoke legs run
in CI; the ``kv_model`` leg carries the modeled per-decode-step K/V
read bytes (paged + GQA + window + page-tier gather vs a contiguous
max-seq MHA cache), pinning the memory-traffic claim that needs a chip
to measure in wall-clock (re-run there when the axon tunnel returns).

Usage:
  serve_bench.py                # full CPU-host run (more requests)
  serve_bench.py --smoke        # tiny CI leg (see .github/workflows)
  serve_bench.py --requests N --rate R --batch B --seed S
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

# expose the virtual multichip world BEFORE jax can be imported (the
# MULTICHIP sharded leg needs the devices; the single-device legs are
# unaffected — they run on device 0): raw parse, same bootstrap as
# collective_bench/transformer_bench
try:  # contract-ok: env -- bootstrap runs before the package's env_int is importable
    _WORLD = max(1, int(os.environ.get("HVD_TPU_BENCH_WORLD", "") or 8))
except ValueError:
    _WORLD = 8
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={_WORLD}"
    ).strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from horovod_tpu.models.transformer import (  # noqa: E402
    Transformer, TransformerConfig,
)
from horovod_tpu.ops.comm_model import (  # noqa: E402
    measured_tier_bytes, modeled_serve_psum_bytes, serve_gather_read_bytes,
)
from horovod_tpu.serving import (  # noqa: E402
    Request, ServeConfig, ServingEngine, modeled_decode_read_bytes,
)
from horovod_tpu import trace  # noqa: E402
from horovod_tpu.trace import export as trace_export  # noqa: E402


def _percentile(xs, p):
    return float(np.percentile(np.asarray(xs, np.float64), p)) if xs else 0.0


def build_load(rs, n, *, p_lo, p_hi, gen_short, gen_long, frac_long):
    """The skewed load continuous batching exists for: most requests
    generate a few tokens, a minority generate many — in a static batch
    the minority holds every slot hostage."""
    load = []
    for _ in range(n):
        plen = int(rs.randint(p_lo, p_hi + 1))
        if rs.random_sample() < frac_long:
            gen = int(gen_long)
        else:
            gen = int(rs.randint(1, gen_short + 1))
        prompt = rs.randint(1, 120, size=plen).astype(np.int32)
        load.append((prompt, gen))
    return load


def build_prefix_load(rs, n, *, templates, t_len, s_lo, s_hi, gen):
    """N requests over K shared prompt templates — the dominant
    production shape (shared system prompts, few-shot headers) the
    prefix cache exists for."""
    temps = [rs.randint(1, 120, size=t_len).astype(np.int32)
             for _ in range(templates)]
    load = []
    for _ in range(n):
        t = temps[rs.randint(templates)]
        suffix = rs.randint(
            1, 120, size=rs.randint(s_lo, s_hi + 1)).astype(np.int32)
        load.append((np.concatenate([t, suffix]), int(rs.randint(1, gen + 1))))
    return load


def _ttfts(token_log):
    first = {}
    for rid, emit, arr in token_log:
        if rid not in first:
            first[rid] = emit - arr
    return list(first.values())


def _leg_stats(leg, token_log, wall_s, results):
    lats = [emit - arr for (_rid, emit, arr) in token_log]
    ttfts = _ttfts(token_log)
    # wall-clock leg extents so bench rows correlate with trace dumps /
    # flight bundles from the same run (epoch seconds, the export axis)
    t_end = time.time()
    return {
        "bench": "serve",
        "leg": leg,
        "requests": len(results),
        "tokens": len(token_log),
        "wall_s": round(wall_s, 4),
        "t_start": round(t_end - wall_s, 3),
        "t_end": round(t_end, 3),
        "throughput_tokens_per_s": round(len(token_log) / wall_s, 2),
        "p50_token_latency_s": round(_percentile(lats, 50), 4),
        "p99_token_latency_s": round(_percentile(lats, 99), 4),
        "ttft_p50_s": round(_percentile(ttfts, 50), 4),
        "ttft_p99_s": round(_percentile(ttfts, 99), 4),
    }


def run_continuous(eng, load, interarrival, leg="continuous", id_base=0):
    """One open-loop continuous leg; ``load`` is [(prompt, gen)] or
    [(prompt, gen, due_offset_s)] for non-uniform arrival (bursts)."""
    eng.token_log = []
    hits0 = eng.scheduler.prefix_hit_blocks
    look0 = eng.scheduler.prefix_lookup_blocks
    comp0 = eng.prefill_tokens_computed
    trace_t0 = trace.now()
    t0 = time.perf_counter()

    def source():
        for i, item in enumerate(load):
            prompt, gen = item[0], item[1]
            due = t0 + (item[2] if len(item) > 2 else i * interarrival)
            now = time.perf_counter()
            if due > now:
                time.sleep(due - now)
            # arrival = the open-loop injection time (due), NOT the
            # yield time: when staging backpressure pulls the generator
            # late, that queueing delay belongs IN the latency — the
            # static leg stamps due, and the A/B must match
            yield Request(id=id_base + i, prompt=prompt, max_new_tokens=gen,
                          arrival=due)

    eng.attach_source(source())
    results = eng.run()
    wall = time.perf_counter() - t0
    results = {rid - id_base: results[rid]
               for rid in (id_base + i for i in range(len(load)))}
    row = _leg_stats(leg, eng.token_log, wall, results)
    row["kv_occupancy"] = round(eng.allocator.peak_occupancy, 4)
    row["evictions"] = eng.scheduler.evictions
    row["compiled_programs"] = eng.program_count
    lookups = eng.scheduler.prefix_lookup_blocks - look0
    hits = eng.scheduler.prefix_hit_blocks - hits0
    row["prefix_hit_rate"] = round(hits / lookups, 4) if lookups else 0.0
    row["prefill_tokens_computed"] = eng.prefill_tokens_computed - comp0
    # per-request TTFT decomposition from the leg's OWN spans (queued +
    # prefill chunks + first decode must sum to the measured TTFT —
    # docs/TRACING.md; the CI smoke asserts the tolerance).  Requests
    # whose early spans the ring already overwrote are skipped.
    if trace.enabled():
        recs = trace.snapshot(since=trace_t0)
        decomp = [d for d in
                  (trace_export.request_decomposition(recs, id_base + i)
                   for i in range(len(load))) if d is not None]
        row["ttft_decomp_requests"] = len(decomp)
        row["ttft_decomp_max_err_s"] = (
            round(max(d["err_s"] for d in decomp), 4) if decomp else None)
    return row, results


def run_static(eng, load, interarrival, batch):
    eng.token_log = []
    comp0 = eng.prefill_tokens_computed
    t0 = time.perf_counter()
    results = {}
    for at in range(0, len(load), batch):
        chunk = []
        for i in range(at, min(at + batch, len(load))):
            prompt, gen = load[i]
            due = t0 + i * interarrival
            now = time.perf_counter()
            if due > now:  # the batch waits for its slowest arrival
                time.sleep(due - now)
            chunk.append(Request(id=i, prompt=prompt, max_new_tokens=gen,
                                 arrival=due))
        results.update(eng.run_static(chunk, batch))
    wall = time.perf_counter() - t0
    row = _leg_stats("static", eng.token_log, wall, results)
    row["kv_occupancy"] = round(eng.allocator.peak_occupancy, 4)
    row["evictions"] = 0
    row["compiled_programs"] = eng.program_count
    row["prefix_hit_rate"] = 0.0
    row["prefill_tokens_computed"] = eng.prefill_tokens_computed - comp0
    return row, results


def _decode_gaps(token_log, steady_ids):
    """Inter-token gaps of the steady requests — the latency a decode
    user feels while someone else's long prompt streams in."""
    last = {}
    gaps = []
    for rid, emit, _arr in token_log:
        if rid in steady_ids and rid in last:
            gaps.append(emit - last[rid])
        last[rid] = emit
    return gaps


def run_burst_leg(cfg, params, serve_cfg, steady, burst, steady_ids, leg):
    """One chunked-vs-unchunked burst leg on a FRESH engine (the chunk
    tier menu differs between the two, so programs can't be shared the
    way the prefix A/B shares them).  The steady load runs once WITHOUT
    the burst first — the same engine's no-burst decode-gap p99 is the
    denominator of the flatness claim (``flatness_x``: how much the
    burst moved the steady requests' p99 inter-token latency)."""
    eng = ServingEngine(cfg, params, serve=serve_cfg)
    warmed = eng.warmup()
    run_continuous(eng, steady, None, leg="baseline", id_base=500000)
    nb_gaps = _decode_gaps(
        eng.token_log, {500000 + i for i in range(len(steady))})
    p99_nb = _percentile(nb_gaps, 99)
    row, results = run_continuous(eng, steady + burst, None, leg=leg)
    gaps = _decode_gaps(eng.token_log, steady_ids)
    p50, p99 = _percentile(gaps, 50), _percentile(gaps, 99)
    row["p50_decode_gap_s"] = round(p50, 4)
    row["p99_decode_gap_s"] = round(p99, 4)
    row["p99_decode_gap_noburst_s"] = round(p99_nb, 4)
    row["decode_gap_spike_x"] = round(p99 / p50, 2) if p50 else 0.0
    row["flatness_x"] = round(p99 / p99_nb, 2) if p99_nb else 0.0
    row["compile_free"] = row.pop("compiled_programs") == warmed
    return row, results


def kv_model_leg(cfg, serve_cfg, context_len, page_tiers):
    ctx_pages = -(-context_len // serve_cfg.block_size)
    tier = next((t for t in page_tiers if t >= ctx_pages), page_tiers[-1])
    m = modeled_decode_read_bytes(
        context_len,
        block_size=serve_cfg.block_size,
        num_heads=cfg.num_heads,
        num_kv_heads=cfg.num_kv_heads or cfg.num_heads,
        head_dim=cfg.head_dim,
        num_layers=cfg.num_layers,
        window=cfg.window,
        dtype_bytes=jnp.dtype(cfg.dtype).itemsize,
        max_seq_len=cfg.max_seq_len,
        gather_pages=tier if cfg.window is None else None,
    )
    full_width = modeled_decode_read_bytes(
        context_len,
        block_size=serve_cfg.block_size,
        num_heads=cfg.num_heads,
        num_kv_heads=cfg.num_kv_heads or cfg.num_heads,
        head_dim=cfg.head_dim,
        num_layers=cfg.num_layers,
        window=cfg.window,
        dtype_bytes=jnp.dtype(cfg.dtype).itemsize,
        max_seq_len=cfg.max_seq_len,
    )
    return {
        "bench": "serve",
        "leg": "kv_model",
        "t_start": round(time.time(), 3),
        "t_end": round(time.time(), 3),
        "context_len": context_len,
        "kv_occupancy": None,  # schema parity with the measured legs
        "throughput_tokens_per_s": None,
        "p99_token_latency_s": None,
        # kernel reads (the _kb_range block-skip term) AND the gather
        # copy this engine materializes first — now bounded by the live
        # max-context PAGE TIER instead of max_blocks (the round-8
        # honest second term, closed); gathered_bytes_untiered keeps
        # the old max_blocks-wide number for comparison
        "paged_read_bytes_per_decode_step": m["paged_bytes"],
        "gathered_bytes_per_decode_step": m["gathered_bytes"],
        "gathered_bytes_untiered": full_width["gathered_bytes"],
        "full_read_bytes_per_decode_step": m["full_bytes"],
        "pages_read": m["pages_read"],
        "pages_gathered": m["pages_gathered"],
        "read_reduction_x": round(m["full_bytes"] / m["paged_bytes"], 2),
        "gather_reduction_x": round(m["full_bytes"] / m["gathered_bytes"], 2),
    }


def run_multichip_leg(shards, n_requests, seed, write_json):
    """The tensor-sharded A/B (ISSUE 12): ONE model over ``shards``
    chips of the ICI mesh — kv heads + the paged pool head-sharded,
    Megatron FFN, one psum per sublayer — against a single-device
    engine on the SAME templated load.  The oracle (token-identical
    streams) is asserted before any number is reported; the byte
    columns carry modeled AND StableHLO-measured per-chip decode reads
    and psum stream (the PR-7 modeled == measured idiom), which is the
    CPU-measurable form of the claim (per-chip HBM decode reads cut by
    the shard factor — the wall-clock twin needs a chip)."""
    kv = max(2, shards)  # kv heads are the shard seam: kv % shards == 0
    cfg = TransformerConfig(
        vocab_size=128, num_layers=2, num_heads=2 * kv, num_kv_heads=kv,
        head_dim=16, max_seq_len=96, dtype=jnp.float32,
        attention_impl="dot", causal=True)
    serve = dict(block_size=8, num_blocks=0, token_budget=256, watermark=2,
                 prefill_tiers=(32,), decode_tiers=(1, 2, 4),
                 prefill_chunk=8)
    params = params_for(cfg)
    rs = np.random.RandomState(seed + 2)
    load = build_prefix_load(rs, n_requests, templates=4, t_len=24,
                             s_lo=2, s_hi=8, gen=6)

    def drive(eng):
        t0 = time.perf_counter()
        ids = [eng.submit(p, max_new_tokens=g) for p, g in load]
        out = eng.run()
        return [out[r] for r in ids], time.perf_counter() - t0

    single = ServingEngine(cfg, params, serve=ServeConfig(**serve))
    single.warmup()
    ref_out, _ = drive(single)
    eng = ServingEngine(cfg, params,
                        serve=ServeConfig(shards=shards, **serve))
    warmed = eng.warmup()
    out, wall = drive(eng)
    for i, (a, b) in enumerate(zip(out, ref_out)):  # the standing oracle
        if not np.array_equal(a, b):
            print(f"MULTICHIP ORACLE MISMATCH on request {i}",
                  file=sys.stderr)
            return None

    # modeled == measured on the decode program the engine dispatches:
    # per-chip page-gather reads and the per-step psum stream, at the
    # largest decode tier over a half-max-context page tier
    bt = max(eng.decode_tiers)
    ctx_ref = cfg.max_seq_len // 2
    pt = next(t for t in eng.page_tiers
              if t >= -(-ctx_ref // serve["block_size"]))
    rows = {}
    for name, e, s in (("shard1", single, 1), ("sharded", eng, shards)):
        txt = e.lowered_decode_text(batch_tier=bt, pages=pt)
        m = modeled_decode_read_bytes(
            ctx_ref, block_size=serve["block_size"],
            num_heads=cfg.num_heads, num_kv_heads=kv,
            head_dim=cfg.head_dim, num_layers=cfg.num_layers,
            dtype_bytes=4, max_seq_len=cfg.max_seq_len,
            gather_pages=pt, shards=s)
        psum = modeled_serve_psum_bytes(
            bt, 1, cfg.d_model, cfg.num_layers, s, "float32")
        measured_reads = serve_gather_read_bytes(txt)["gather_bytes"]
        measured_psum = measured_tier_bytes(txt, [0] * s)["ici_bytes"]
        if measured_reads != bt * m["gathered_bytes"] or \
                measured_psum != psum["stream_bytes"]:
            print(f"MULTICHIP MODEL MISMATCH ({name}): reads "
                  f"{measured_reads} vs {bt * m['gathered_bytes']}, psum "
                  f"{measured_psum} vs {psum['stream_bytes']}",
                  file=sys.stderr)
            return None
        rows[name] = (m, psum, measured_reads, measured_psum)
    m, psum, meas_r, meas_p = rows["sharded"]
    m1, _, meas_r1, _ = rows["shard1"]
    toks = sum(len(t) for t in out)
    row = {
        "bench": "serve",
        "leg": "multichip",
        "t_start": round(time.time() - wall, 3),
        "t_end": round(time.time(), 3),
        "n_devices": jax.device_count(),
        "shard_factor": shards,
        "requests": len(load),
        "tokens": toks,
        "wall_s": round(wall, 4),
        "throughput_tokens_per_s": round(toks / wall, 2),
        "compile_free": eng.program_count == warmed,
        "kv_occupancy": round(eng.allocator.peak_occupancy, 4),
        "prefix_hit_rate": round(
            eng.scheduler.prefix_hit_blocks
            / max(eng.scheduler.prefix_lookup_blocks, 1), 4),
        # per-chip decode reads at (bt, page tier): the Pope et al.
        # HBM-bound stream the shard factor divides
        "per_chip_decode_read_bytes_modeled": bt * m["gathered_bytes"],
        "per_chip_decode_read_bytes_measured": meas_r,
        "shard1_decode_read_bytes_modeled": bt * m1["gathered_bytes"],
        "shard1_decode_read_bytes_measured": meas_r1,
        "read_reduction_x": round(meas_r1 / meas_r, 2),
        # the price of the reduction: one psum per sublayer on ICI
        "psum_bytes_per_step_modeled": psum["stream_bytes"],
        "psum_bytes_per_step_measured": meas_p,
        "psum_count_per_step": psum["psum_count"],
        "pool_bytes_per_shard": eng.pool_bytes_per_shard,
        "shard_psum_bytes_total": eng.shard_psum_bytes,
    }
    if write_json:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "MULTICHIP_r06.json")
        with open(path, "w") as f:
            json.dump({"n_devices": jax.device_count(), "ok": True,
                       "leg": row}, f, indent=2)
            f.write("\n")
        print(f"wrote {path}", file=sys.stderr)
    return row


def build_spec_loads(rs, n, *, motif, tiles, gen):
    """The speculative A/B's two loads.  TEMPLATE-HEAVY: prompts are a
    short motif tiled several times (the repetitive agent/template
    traffic prompt-lookup drafting exists for — trailing n-grams recur,
    so drafts come from the sequence's own history).  ADVERSARIAL-
    RANDOM: i.i.d. uniform tokens — the drafter's worst case, and the
    leg's claim is that outputs are STILL bit-identical (speculation
    can waste compute, never move values; what acceptance survives
    here comes from the generated tail, not the prompt)."""
    motifs = [rs.randint(1, 120, size=motif).astype(np.int32)
              for _ in range(3)]
    template = []
    for _ in range(n):
        m = motifs[int(rs.randint(len(motifs)))]
        prompt = np.tile(m, tiles)[:int(motif * tiles - rs.randint(3))]
        template.append((prompt.astype(np.int32),
                         int(rs.randint(gen // 2, gen + 1))))
    random_load = [
        (rs.randint(1, 120, size=int(rs.randint(8, motif * tiles))
                    ).astype(np.int32),
         int(rs.randint(gen // 2, gen + 1)))
        for _ in range(n)]
    return template, random_load


def run_spec_leg(cfg, params, serve_cfg, load, leg, id_base):
    """One speculative A/B leg on a FRESH engine (spec on adds the
    verify-width programs to the menu, so the engines can't share a
    warmup the way the prefix A/B does).  Arrivals are immediate
    (interarrival 0): the A/B measures steps, not pacing."""
    eng = ServingEngine(cfg, params, serve=serve_cfg)
    warmed = eng.warmup()
    trace_t0 = trace.now()
    row, res = run_continuous(eng, load, 0.0, leg=leg, id_base=id_base)
    row["compile_free"] = row.pop("compiled_programs") == warmed
    row["drafted_tokens"] = eng.spec_drafted_tokens
    row["accepted_tokens"] = eng.spec_accepted_tokens
    row["rolled_back_tokens"] = eng.spec_rolled_back_tokens
    row["acceptance_rate"] = round(
        eng.spec_accepted_tokens / eng.spec_drafted_tokens, 4) \
        if eng.spec_drafted_tokens else 0.0
    # tokens emitted per verified row: 1 (the verifier's bonus or
    # correction token) + the accepted run — the speculative claim in
    # one number (1.0 exactly on the baseline legs)
    row["tokens_per_step"] = round(
        1.0 + eng.spec_accepted_tokens / eng.spec_verified_rows, 3) \
        if eng.spec_verified_rows else 1.0
    if trace.enabled():
        spans = [r for r in trace.snapshot(since=trace_t0)
                 if r[0] == "serve.spec_verify"]
        rollbacks = [r for r in trace.snapshot(since=trace_t0)
                     if r[0] == "serve.spec_rollback"]
        row["spec_verify_spans"] = len(spans)
        row["spec_verify_total_s"] = round(
            sum(r[2] or 0.0 for r in spans), 4)
        row["spec_rollback_events"] = len(rollbacks)
    return row, res


def run_spec_legs(args):
    """The round-15 speculative A/B: spec off vs on, on template-heavy
    and adversarial-random loads (build_spec_loads).  Asserts the
    bit-identity oracle per load before reporting."""
    if args.smoke:
        n, gen, motif, tiles, k = 14, 48, 6, 5, 6
    else:
        n, gen, motif, tiles, k = 40, 80, 8, 6, 6
    cfg = TransformerConfig(
        vocab_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=motif * tiles + gen + 16,
        dtype=jnp.float32, attention_impl="dot", causal=True)
    params = params_for(cfg)
    # one decode tier and 16-token blocks keep each fresh engine's
    # warmup menu tiny (the A/B pays it twice per load); generation
    # dominates the leg, which is what speculation accelerates
    serve_kw = dict(
        block_size=16, num_blocks=0, token_budget=4 * cfg.max_seq_len,
        watermark=2, prefill_tiers=(motif * tiles + 2,),
        decode_tiers=(4,), prefill_chunk=0)
    rs = np.random.RandomState(args.seed + 3)
    template, random_load = build_spec_loads(
        rs, n, motif=motif, tiles=tiles, gen=gen)

    rows = []
    for name, load, base in (("template", template, 600000),
                             ("random", random_load, 700000)):
        base_row, base_res = run_spec_leg(
            cfg, params, ServeConfig(**serve_kw), load,
            f"spec_base_{name}", base)
        spec_row, spec_res = run_spec_leg(
            cfg, params, ServeConfig(spec=True, spec_k=k, **serve_kw),
            load, f"spec_on_{name}", base + 50000)
        for i in range(n):  # drafts move compute, never values
            if not np.array_equal(base_res[i], spec_res[i]):
                print(f"SPEC ORACLE MISMATCH ({name}) on request {i}",
                      file=sys.stderr)
                return None
        spec_row["speedup_vs_base"] = round(
            spec_row["throughput_tokens_per_s"]
            / max(base_row["throughput_tokens_per_s"], 1e-9), 2)
        rows += [base_row, spec_row]
    return rows


def _drive_router(router, load, arrivals, t0=None):
    """Open-loop drive of a FleetRouter: submit each request at its
    arrival offset, stepping the fleet in between (the router is
    single-threaded by design — this loop IS the front end)."""
    t0 = time.perf_counter() if t0 is None else t0
    n = len(load)
    gids = [None] * n
    i = 0
    while True:
        now = time.perf_counter()
        while i < n and now >= t0 + arrivals[i]:
            prompt, gen = load[i]
            gids[i] = router.submit(prompt, gen, arrival=t0 + arrivals[i])
            i += 1
            now = time.perf_counter()
        busy = router.step()
        if i >= n and not busy and not router._placed:
            break
        if not busy and i < n:
            time.sleep(max(0.0, t0 + arrivals[i] - time.perf_counter()))
    return gids, time.perf_counter() - t0


def _fleet_row(leg, router, gids, wall):
    ttfts = router.all_ttfts()
    hits, lookups = router.prefix_stats()
    toks = sum(len(router.results[g]) for g in gids)
    peaks = [r.peak_queue_depth for r in router.replicas + router.retired]
    return {
        "bench": "serve",
        "leg": leg,
        "t_start": round(time.time() - wall, 3),
        "t_end": round(time.time(), 3),
        "requests": len(gids),
        "tokens": toks,
        "wall_s": round(wall, 4),
        "throughput_tokens_per_s": round(toks / wall, 2),
        "replicas": len(router.replicas) + len(router.retired),
        "ttft_p50_s": round(_percentile(ttfts, 50), 4),
        "ttft_p99_s": round(_percentile(ttfts, 99), 4),
        "prefix_hit_rate": round(hits / lookups, 4) if lookups else 0.0,
        "per_replica_peak_queue_depth": peaks,
        "routed": dict(router.route_counts),
        "compile_free": router.all_compile_free(),
    }


def run_fleet_legs(args):
    """The PR-13 fleet A/B (docs/FLEET.md): N in-process replicas
    under a ramping open-loop load over shared templates, routed
    round-robin vs prefix-affinity (fresh replicas per leg, same
    params, same load), plus an SLO-driven scale leg (start at 1
    replica, the queue-depth policy grows the fleet under the ramp,
    drains it back as load falls).  Every leg asserts the standing
    oracle — placement moves time, never tokens — and zero
    post-warmup compiles on EVERY replica before reporting."""
    from horovod_tpu.fleet.policy import Target, TargetTrackingPolicy
    from horovod_tpu.fleet.router import FleetRouter

    if args.smoke:
        n, replicas, templates, t_len, s_hi, gen = 72, 2, 6, 48, 8, 6
        rate_lo, rate_hi = 100.0, 1200.0
    else:
        n, replicas, templates, t_len, s_hi, gen = 160, 3, 8, 96, 12, 8
        rate_lo, rate_hi = 60.0, 900.0
    cfg = TransformerConfig(
        vocab_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=2 * t_len, dtype=jnp.float32,
        attention_impl="dot", causal=True)
    params = params_for(cfg)
    serve_kw = dict(block_size=16, num_blocks=0, token_budget=256,
                    watermark=2, prefill_tiers=(t_len + 16,),
                    decode_tiers=(1, 2, 4), prefill_chunk=16)

    def build_engine():
        return ServingEngine(cfg, params, serve=ServeConfig(**serve_kw))

    rs = np.random.RandomState(args.seed)
    temps = [rs.randint(1, 120, size=t_len).astype(np.int32)
             for _ in range(templates)]
    load = []
    for _ in range(n):
        t = temps[int(rs.randint(templates))]
        sfx = rs.randint(1, 120,
                         size=int(rs.randint(2, s_hi + 1))).astype(np.int32)
        load.append((np.concatenate([t, sfx]),
                     int(rs.randint(1, gen + 1))))
    # the load RAMP: interarrival shrinks linearly rate_lo -> rate_hi,
    # so queueing builds through the leg — the regime where placement
    # (and, in the scale leg, capacity) decides the TTFT tail
    arrivals = []
    t = 0.0
    for i in range(n):
        rate = rate_lo + (rate_hi - rate_lo) * i / max(n - 1, 1)
        t += 1.0 / rate
        arrivals.append(t)

    rows = []
    outs = {}
    for mode, leg in (("round_robin", "fleet_rr"),
                      ("affinity", "fleet_affinity")):
        router = FleetRouter(build_engine, replicas=replicas, mode=mode)
        gids, wall = _drive_router(router, load, arrivals)
        rows.append(_fleet_row(leg, router, gids, wall))
        outs[leg] = [router.results[g] for g in gids]
    for i, (a, b) in enumerate(zip(outs["fleet_rr"],
                                   outs["fleet_affinity"])):
        if not np.array_equal(a, b):  # placement moves time, not values
            print(f"FLEET ORACLE MISMATCH on request {i}", file=sys.stderr)
            return None
    rr, aff = rows[0], rows[1]
    aff["affinity_vs_rr"] = {
        "hit_rate_x": round(aff["prefix_hit_rate"]
                            / max(rr["prefix_hit_rate"], 1e-9), 3),
        "ttft_p99_x": round(rr["ttft_p99_s"]
                            / max(aff["ttft_p99_s"], 1e-9), 3),
    }

    # -- the SLO-driven scale leg: start at 1 accepting replica with
    # warm spares parked; the queue-depth policy grows the fleet under
    # the ramp (unpark = instant, the engines compiled before t0) and
    # drains it back once the queue empties at the tail
    policy = TargetTrackingPolicy(
        [Target("queue_depth", 3.0)], min_size=1, max_size=replicas,
        deadband=0.1, scale_in_at=0.3, hysteresis=40, cooldown_s=0.3)
    router = FleetRouter(build_engine, replicas=1, mode="affinity",
                         policy=policy, spares=replicas - 1)
    gids, wall = _drive_router(router, load, arrivals)
    # idle tail: keep ticking the policy so the empty queue scales the
    # fleet back in and the drain/retire path runs for real
    tail_deadline = time.perf_counter() + 3.0
    while time.perf_counter() < tail_deadline and (
            router.size > 1
            or any(r.state == "draining" for r in router.replicas)):
        router.step()
    row = _fleet_row("fleet_scale", router, gids, wall)
    row["scale_out_events"] = sum(
        1 for d, _ in router.scale_events if d == "out")
    row["scale_in_events"] = sum(
        1 for d, _ in router.scale_events if d == "in")
    row["max_replicas"] = max([1] + [s for d, s in router.scale_events
                                     if d == "out"])
    row["final_replicas"] = router.size
    row["retired_replicas"] = len(router.retired)
    for i, out in enumerate(outs["fleet_rr"]):
        if not np.array_equal(out, router.results[gids[i]]):
            print(f"FLEET SCALE ORACLE MISMATCH on request {i}",
                  file=sys.stderr)
            return None
    rows.append(row)

    # -- the ISSUE-18 recovery leg: same load, one replica killed
    # mid-ramp.  One strike ejects (HVD_TPU_FLEET_REPLICA_ERRORS=1);
    # in-flight work migrates warm off the live KV export, queued work
    # re-disperses cold, hedging is armed.  The oracle stays
    # token-identical vs the fault-free legs and the row carries the
    # recovery columns CI asserts (migration_ms, hedge_rate).
    os.environ["HVD_TPU_FLEET_REPLICA_ERRORS"] = "1"
    os.environ["HVD_TPU_SERVE_HEDGE"] = "1"
    try:
        router = FleetRouter(build_engine, replicas=replicas,
                             mode="affinity")
        victim = router.replicas[0]
        orig_step = victim.engine.step
        state = {"n": 0}

        def flaky_step(*a, **k):
            state["n"] += 1
            if state["n"] == 25:  # mid-ramp: the victim is mid-decode
                raise RuntimeError("bench-injected replica loss")
            return orig_step(*a, **k)

        victim.engine.step = flaky_step
        gids, wall = _drive_router(router, load, arrivals)
    finally:
        os.environ.pop("HVD_TPU_FLEET_REPLICA_ERRORS", None)
        os.environ.pop("HVD_TPU_SERVE_HEDGE", None)
    row = _fleet_row("fleet_recovery", router, gids, wall)
    row["migrations"] = len(router.recovery)
    row["migrations_warm"] = sum(
        1 for x in router.recovery if x["path"] == "warm")
    row["migration_ms"] = round(router.migration_ms(), 3)
    row["hedge_rate"] = round(router.hedge_rate(), 4)
    if not router.recovery:
        print("FLEET RECOVERY LEG: the ejection migrated nothing",
              file=sys.stderr)
        return None
    for i, out in enumerate(outs["fleet_rr"]):
        if not np.array_equal(out, router.results[gids[i]]):
            print(f"FLEET RECOVERY ORACLE MISMATCH on request {i}",
                  file=sys.stderr)
            return None
    rows.append(row)
    return rows


def _fleet_decode_gaps(router):
    """p99 inter-token gap across the fleet, from each engine's own
    token log (request ids are engine-local, and gaps are intra-id, so
    per-engine logs compose without remapping).  In the disaggregated
    fleet a request's first token lands in the prefill engine's log
    and the rest in the decode engine's — the one-token prefill-side
    entry contributes no gap, which is exactly right: the metric is
    the cadence a decode user FEELS, and the handoff pause shows up as
    the decode engine's first intra-id gap measured from arrival."""
    gaps = []
    for r in router.replicas + router.retired:
        if r.engine is not None and r.engine.token_log:
            gaps.extend(_decode_gaps(
                r.engine.token_log,
                {rid for rid, _e, _a in r.engine.token_log}))
    return gaps


def _arm_token_logs(router):
    for r in router.replicas:
        r.engine.token_log = []


def run_disagg_legs(args):
    """The disaggregated prefill/decode A/B (ROADMAP item 2,
    docs/FLEET.md): the same templated open-loop load through a
    classic mixed fleet of N replicas and a two-tier fleet that puts
    a prefill replica IN FRONT of the same N as a decode tier —
    iso-decode-capacity, the Splitwise framing: the claim under test
    is that offloading prompt work to a prefill tier keeps the decode
    cadence flat without costing aggregate tokens/s, so the A/B holds
    the decode fleet fixed and disaggregation adds its tier the way a
    deployment would.  The load is decode-heavy (long generations
    under a prompt-arrival ramp — prompts keep landing while earlier
    requests decode, the interference regime chunking only bounds).
    Asserted before a single number prints: token-identity across the
    legs, zero post-warmup compiles on BOTH tiers, and the warm
    handoff bytes' modeled == measured equality (comm_model idiom).
    With ``--shards 2`` a second pair reruns both legs with every
    tier tensor-sharded over 2 virtual chips and re-asserts identity
    against its own sharded mixed baseline."""
    from horovod_tpu.fleet.router import FleetRouter
    from horovod_tpu.ops.comm_model import modeled_kvsnap_bytes

    if args.smoke:
        n, decode_replicas, templates, t_len, s_hi = 48, 2, 6, 48, 8
        gen_lo, gen_hi = 12, 24
        rate_lo, rate_hi = 60.0, 400.0
    else:
        n, decode_replicas, templates, t_len, s_hi = 160, 3, 8, 96, 12
        gen_lo, gen_hi = 16, 32
        rate_lo, rate_hi = 40.0, 300.0
    cfg = TransformerConfig(
        vocab_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=2 * t_len, dtype=jnp.float32,
        attention_impl="dot", causal=True)
    params = params_for(cfg)
    serve_kw = dict(block_size=16, num_blocks=0, token_budget=256,
                    watermark=2, prefill_tiers=(t_len + 16,),
                    decode_tiers=(1, 2, 4), prefill_chunk=16)

    rs = np.random.RandomState(args.seed)
    temps = [rs.randint(1, 120, size=t_len).astype(np.int32)
             for _ in range(templates)]
    load = []
    for _ in range(n):
        t = temps[int(rs.randint(templates))]
        sfx = rs.randint(1, 120,
                         size=int(rs.randint(2, s_hi + 1))).astype(np.int32)
        load.append((np.concatenate([t, sfx]),
                     int(rs.randint(gen_lo, gen_hi + 1))))
    arrivals = []
    t = 0.0
    for i in range(n):
        rate = rate_lo + (rate_hi - rate_lo) * i / max(n - 1, 1)
        t += 1.0 / rate
        arrivals.append(t)

    def legs_for(shards, suffix):
        def build(role="both"):
            return ServingEngine(
                cfg, params, serve=ServeConfig(shards=shards, **serve_kw),
                role=role)

        # mixed baseline: the decode tier's size, classic single tier
        router = FleetRouter(build, replicas=decode_replicas,
                             mode="affinity")
        _arm_token_logs(router)
        gids, wall = _drive_router(router, load, arrivals)
        mixed = _fleet_row(f"fleet_mixed{suffix}", router, gids, wall)
        mixed["p99_decode_gap_s"] = round(
            _percentile(_fleet_decode_gaps(router), 99), 4)
        mixed_out = [router.results[g] for g in gids]

        # the disaggregated fleet: 1 prefill + N decode
        router = FleetRouter(build, replicas=decode_replicas,
                             mode="affinity", prefill_replicas=1)
        _arm_token_logs(router)
        gids, wall = _drive_router(router, load, arrivals)
        row = _fleet_row(f"fleet_disagg{suffix}", router, gids, wall)
        row["p99_decode_gap_s"] = round(
            _percentile(_fleet_decode_gaps(router), 99), 4)
        row["handoffs"] = router.handoffs["warm"] + router.handoffs["cold"]
        row["handoffs_warm"] = router.handoffs["warm"]
        hand_ms = [x["ms"] for x in router.handoff_records]
        row["handoff_ms_p50"] = round(_percentile(hand_ms, 50), 3)
        row["handoff_ms_p99"] = round(_percentile(hand_ms, 99), 3)
        row["migrated_kv_bytes"] = router.migrated_bytes
        modeled = sum(
            modeled_kvsnap_bytes(
                x["blocks"], serve_kw["block_size"], cfg.num_layers,
                cfg.num_kv_heads, cfg.head_dim, "float32")["wire_bytes"]
            for x in router.handoff_records if x["path"] == "warm")
        row["migrated_kv_bytes_modeled"] = modeled
        pre = [r for r in router.replicas + router.retired
               if r.tier == "prefill"]
        dec = [r for r in router.replicas + router.retired
               if r.tier == "decode"]
        row["compile_free_prefill"] = all(r.compile_free for r in pre)
        row["compile_free_decode"] = all(r.compile_free for r in dec)
        row["compile_free"] = (row["compile_free_prefill"]
                               and row["compile_free_decode"])
        disagg_out = [router.results[g] for g in gids]

        for i, (a, b) in enumerate(zip(mixed_out, disagg_out)):
            if not np.array_equal(a, b):  # tiers move time, not tokens
                print(f"DISAGG ORACLE MISMATCH{suffix} on request {i}",
                      file=sys.stderr)
                return None
        if row["handoffs_warm"] < 1:
            print(f"DISAGG LEG{suffix}: no warm handoff crossed the wire",
                  file=sys.stderr)
            return None
        if row["migrated_kv_bytes"] != modeled:
            print(f"DISAGG KVSNAP BYTES{suffix}: measured "
                  f"{row['migrated_kv_bytes']} != modeled {modeled}",
                  file=sys.stderr)
            return None
        if not row["compile_free"]:
            print(f"DISAGG LEG{suffix}: a tier compiled post-warmup",
                  file=sys.stderr)
            return None
        return [mixed, row]

    rows = legs_for(1, "")
    if rows is None:
        return None
    if args.shards and args.shards > 1:
        more = legs_for(args.shards, f"_shard{args.shards}")
        if more is None:
            return None
        rows += more
    return rows


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CI pass (CPU; scheduling is the claim)")
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--rate", type=float, default=None,
                    help="request arrivals per second (open loop)")
    ap.add_argument("--batch", type=int, default=8,
                    help="static-baseline batch size AND max decode batch")
    ap.add_argument("--shards", type=int, default=None,
                    help="tensor-shard factor of the MULTICHIP leg "
                         "(default 8, smoke 2; 0 skips the leg)")
    ap.add_argument("--fleet", action="store_true",
                    help="run ONLY the fleet router legs (rr vs "
                         "prefix-affinity A/B + SLO scale leg)")
    ap.add_argument("--disagg", action="store_true",
                    help="run ONLY the disaggregated prefill/decode "
                         "A/B (mixed vs two-tier fleet; --shards 2 "
                         "adds a tensor-sharded pair)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    if args.disagg:
        rows = run_disagg_legs(args)
        if rows is None:
            return 1
        for row in rows:
            print(json.dumps(row))
        mixed, dis = rows[0], rows[1]
        print(
            f"disagg 1+{dis['replicas'] - 1}: "
            f"{dis['handoffs_warm']}/{dis['handoffs']} handoffs warm, "
            f"p50 {dis['handoff_ms_p50']}ms, "
            f"{dis['migrated_kv_bytes']} KV B migrated "
            f"(modeled == measured); decode-gap p99 "
            f"{dis['p99_decode_gap_s']}s vs mixed "
            f"{mixed['p99_decode_gap_s']}s at "
            f"{dis['throughput_tokens_per_s']} vs "
            f"{mixed['throughput_tokens_per_s']} tok/s; oracle "
            f"token-identical, prefill/decode compile-free="
            f"{dis['compile_free_prefill']}/{dis['compile_free_decode']}",
            file=sys.stderr)
        return 0

    if args.fleet:
        rows = run_fleet_legs(args)
        if rows is None:
            return 1
        for row in rows:
            print(json.dumps(row))
        rr, aff, sc, rec = rows[0], rows[1], rows[2], rows[3]
        print(
            f"fleet x{rr['replicas']}: affinity hit rate "
            f"{aff['prefix_hit_rate']} vs rr {rr['prefix_hit_rate']} "
            f"({aff['affinity_vs_rr']['hit_rate_x']}x), TTFT p99 "
            f"{aff['ttft_p99_s']}s vs {rr['ttft_p99_s']}s "
            f"({aff['affinity_vs_rr']['ttft_p99_x']}x); scale leg "
            f"peaked at {sc['max_replicas']} replicas "
            f"({sc['scale_out_events']} out / "
            f"{sc['scale_in_events']} in); recovery leg migrated "
            f"{rec['migrations']} requests ({rec['migrations_warm']} warm) "
            f"in {rec['migration_ms']}ms avg at hedge rate "
            f"{rec['hedge_rate']}; oracle token-identical, "
            f"all replicas compile-free={aff['compile_free'] and rr['compile_free'] and sc['compile_free'] and rec['compile_free']}",
            file=sys.stderr)
        return 0

    if args.smoke:
        n = args.requests or 40
        rate = args.rate or 200.0
        cfg = TransformerConfig(
            vocab_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
            head_dim=16, max_seq_len=96, dtype=jnp.float32,
            attention_impl="dot", causal=True)
        gen_long = 56
        n_prefix, t_len, s_hi, chunk = 24, 48, 8, 8
        n_steady, n_burst, burst_len = 4, 3, 88
        steady_gen, burst_at, burst_bt = 60, 0.2, 8
    else:
        n = args.requests or 96
        rate = args.rate or 100.0
        cfg = TransformerConfig(
            vocab_size=512, num_layers=4, num_heads=8, num_kv_heads=2,
            head_dim=32, max_seq_len=256, dtype=jnp.float32,
            attention_impl="dot", causal=True)
        gen_long = 96
        n_prefix, t_len, s_hi, chunk = 64, 128, 16, 32
        n_steady, n_burst, burst_len = 6, 4, 240
        steady_gen, burst_at, burst_bt = 160, 1.0, 12

    rs = np.random.RandomState(args.seed)
    load = build_load(rs, n, p_lo=4, p_hi=24, gen_short=4,
                      gen_long=gen_long, frac_long=0.2)
    interarrival = 1.0 / rate

    serve_cfg = ServeConfig(
        block_size=16, num_blocks=0, token_budget=4 * cfg.max_seq_len,
        watermark=2,
        # one intake tier (all prompts fit 32; the engine appends
        # max_seq_len for post-evict re-prefills) keeps the warmup menu
        # small without changing what the measured legs execute
        prefill_tiers=(32,),
        decode_tiers=tuple(sorted({t for t in (1, 2, 4, 8, 16, 32)
                                   if t < args.batch} | {args.batch})))
    eng = ServingEngine(cfg, params_for(cfg), serve=serve_cfg)

    # pre-compile the WHOLE tier menu: a mid-traffic XLA compile is a
    # multi-second p99 spike, and the bounded menu is what makes
    # warming it tractable (the executable-cache discipline under test)
    t0 = time.perf_counter()
    warmed = eng.warmup()
    print(f"warmup: {warmed} tier programs in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    cont_row, cont_res = run_continuous(eng, load, interarrival)
    eng.allocator.peak_occupancy = 0.0
    stat_row, stat_res = run_static(eng, load, interarrival, args.batch)

    # the oracle, on the bench's own load: same greedy tokens both ways
    for i in range(n):
        if not np.array_equal(cont_res[i], stat_res[i]):
            print(f"ORACLE MISMATCH on request {i}", file=sys.stderr)
            return 1

    cont_row["speedup_vs_static"] = round(
        cont_row["throughput_tokens_per_s"]
        / max(stat_row["throughput_tokens_per_s"], 1e-9), 2)

    # -- round 9: shared-prefix A/B on the SAME engine (same programs) --
    prefix_load = build_prefix_load(
        rs, n_prefix, templates=4, t_len=t_len, s_lo=2, s_hi=s_hi, gen=4)
    prefix_rows = []
    prefix_outs = []
    for leg, enabled, base in (("prefix_off", False, 100000),
                               ("prefix_on", True, 200000)):
        eng.allocator.prefix_cache = enabled
        eng.allocator.clear_cache()
        eng.allocator.peak_occupancy = 0.0
        row, res = run_continuous(eng, prefix_load, interarrival, leg=leg,
                                  id_base=base)
        prefix_rows.append(row)
        prefix_outs.append(res)
    for i in range(n_prefix):  # the prefix-cache bit-identity oracle
        if not np.array_equal(prefix_outs[0][i], prefix_outs[1][i]):
            print(f"PREFIX ORACLE MISMATCH on request {i}", file=sys.stderr)
            return 1
    for row in (cont_row, stat_row, prefix_rows[0], prefix_rows[1]):
        # steady state must be all executable-cache hits
        row["compile_free"] = row.pop("compiled_programs") == warmed

    # -- round 9: chunked-prefill burst A/B (fresh engine per leg) ------
    # the HoL shape: a few LONG-LIVED decoders admitted at t~0, then a
    # burst of long prompts arriving TOGETHER mid-decode — slots and
    # budget are sized so the whole burst admits in one wave, which on
    # the unchunked engine is one monopolizing whole-prompt prefill
    # step stalling every decoder (the round-8 p50 queueing term), and
    # on the chunked engine is a stream of bounded chunks the decode
    # batch rides alongside
    burst_rs = np.random.RandomState(args.seed + 1)
    steady_load = [
        (burst_rs.randint(1, 120, size=8).astype(np.int32),
         steady_gen, i * 0.01) for i in range(n_steady)]
    burst_only = [
        (burst_rs.randint(1, 120, size=burst_len).astype(np.int32),
         2, burst_at) for _ in range(n_burst)]
    steady_ids = set(range(n_steady))
    # one decode tier: every step pads to the full batch either way, so
    # the A/B stays fair while each fresh engine warms a tiny menu
    burst_base = dict(
        block_size=16, num_blocks=0, token_budget=4 * cfg.max_seq_len,
        watermark=2, prefill_tiers=(32,), decode_tiers=(burst_bt,))
    unchunked_row, un_res = run_burst_leg(
        cfg, eng.params, ServeConfig(prefill_chunk=0, **burst_base),
        steady_load, burst_only, steady_ids, "unchunked")
    chunked_row, ch_res = run_burst_leg(
        cfg, eng.params, ServeConfig(prefill_chunk=chunk, **burst_base),
        steady_load, burst_only, steady_ids, "chunked")
    for i in range(n_steady + n_burst):  # chunks move time, not values
        if not np.array_equal(un_res[i], ch_res[i]):
            print(f"CHUNK ORACLE MISMATCH on request {i}", file=sys.stderr)
            return 1

    kv_row = kv_model_leg(cfg, serve_cfg, context_len=cfg.max_seq_len // 2,
                          page_tiers=eng.page_tiers)

    # -- round 10: the tensor-sharded MULTICHIP leg ---------------------
    shards = args.shards if args.shards is not None else (
        2 if args.smoke else 8)
    mc_rows = []
    if shards > 1:
        mc = run_multichip_leg(shards, 12 if args.smoke else 32,
                               args.seed, write_json=not args.smoke)
        if mc is None:
            return 1
        mc_rows.append(mc)

    # -- round 15: the speculative-decoding A/B -------------------------
    spec_rows = run_spec_legs(args)
    if spec_rows is None:
        return 1

    for row in (cont_row, stat_row, prefix_rows[0], prefix_rows[1],
                unchunked_row, chunked_row, kv_row, *mc_rows,
                *spec_rows):
        print(json.dumps(row))
    on, off = prefix_rows[1], prefix_rows[0]
    print(
        f"continuous {cont_row['throughput_tokens_per_s']} tok/s "
        f"(p99 {cont_row['p99_token_latency_s']}s) vs static "
        f"{stat_row['throughput_tokens_per_s']} tok/s "
        f"(p99 {stat_row['p99_token_latency_s']}s) — "
        f"{cont_row['speedup_vs_static']}x; prefix cache TTFT p50 "
        f"{off['ttft_p50_s']}s -> {on['ttft_p50_s']}s at hit rate "
        f"{on['prefix_hit_rate']} ({off['prefill_tokens_computed']} -> "
        f"{on['prefill_tokens_computed']} prefill tokens); burst decode-gap "
        f"p99 {unchunked_row['p99_decode_gap_s']}s unchunked -> "
        f"{chunked_row['p99_decode_gap_s']}s chunked; paged decode reads "
        f"{kv_row['read_reduction_x']}x fewer K/V bytes", file=sys.stderr)
    if mc_rows:
        mc = mc_rows[0]
        print(
            f"multichip x{mc['shard_factor']}: per-chip decode reads "
            f"{mc['shard1_decode_read_bytes_measured']} -> "
            f"{mc['per_chip_decode_read_bytes_measured']} B "
            f"({mc['read_reduction_x']}x, modeled == measured) at "
            f"{mc['psum_bytes_per_step_measured']} psum B/step on ICI; "
            f"oracle token-identical, compile_free={mc['compile_free']}",
            file=sys.stderr)
    sp_t, sp_r = spec_rows[1], spec_rows[3]
    print(
        f"speculative: template-heavy "
        f"{spec_rows[0]['throughput_tokens_per_s']} -> "
        f"{sp_t['throughput_tokens_per_s']} tok/s "
        f"({sp_t['speedup_vs_base']}x) at acceptance "
        f"{sp_t['acceptance_rate']} "
        f"({sp_t['tokens_per_step']} tok/step); adversarial-random "
        f"{sp_r['speedup_vs_base']}x at acceptance "
        f"{sp_r['acceptance_rate']} — bit-identical both ways, "
        f"compile_free={sp_t['compile_free'] and sp_r['compile_free']}",
        file=sys.stderr)
    return 0


def params_for(cfg):
    model = Transformer(cfg)
    rng = jax.random.PRNGKey(0)
    return model.init(rng, jnp.zeros((1, 8), jnp.int32),
                      train=False)["params"]


if __name__ == "__main__":
    sys.exit(main())
