#!/usr/bin/env python
"""Continuous-batching serving benchmark (PERF.md round 8).

Generates a synthetic OPEN-LOOP load — requests arrive on their own
clock, independent of completions, the way real traffic does — and
drives it through ``horovod_tpu.serving`` twice:

  continuous   the ServingEngine: iteration-level admit/evict over the
               paged KV cache (Orca-style), requests staged to device
               through the DevicePrefetcher while steps compute;
  static       the pre-Orca baseline (``ServingEngine.run_static``):
               fixed request batches held until every member finishes,
               contiguous worst-case KV reservations.  Batches start
               only once all members have ARRIVED (honest open-loop
               head-of-line blocking).

Both legs share ONE engine instance — same params, same jitted tier
programs, same pools — so the A/B isolates the SCHEDULING policy, and
both sample greedily, so the bench asserts token-for-token identical
outputs before it reports a single number (the oracle from
tests/test_serving.py, run on the bench's own load).

Every leg emits ONE bench-style JSON line on stdout (human summary on
stderr).  The scheduling win is CPU-measurable — it is steps saved, not
FLOPs saved — so the smoke leg runs in CI; the ``kv_model`` leg carries
the modeled per-decode-step K/V read bytes (paged + GQA + window vs a
contiguous max-seq MHA cache), pinning the memory-traffic claim that
needs a chip to measure in wall-clock (re-run there when the axon
tunnel returns).

Usage:
  serve_bench.py                # full CPU-host run (more requests)
  serve_bench.py --smoke        # tiny CI leg (see .github/workflows)
  serve_bench.py --requests N --rate R --batch B --seed S
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from horovod_tpu.models.transformer import (  # noqa: E402
    Transformer, TransformerConfig,
)
from horovod_tpu.serving import (  # noqa: E402
    Request, ServeConfig, ServingEngine, modeled_decode_read_bytes,
)


def _percentile(xs, p):
    return float(np.percentile(np.asarray(xs, np.float64), p)) if xs else 0.0


def build_load(rs, n, *, p_lo, p_hi, gen_short, gen_long, frac_long):
    """The skewed load continuous batching exists for: most requests
    generate a few tokens, a minority generate many — in a static batch
    the minority holds every slot hostage."""
    load = []
    for _ in range(n):
        plen = int(rs.randint(p_lo, p_hi + 1))
        if rs.random_sample() < frac_long:
            gen = int(gen_long)
        else:
            gen = int(rs.randint(1, gen_short + 1))
        prompt = rs.randint(1, 120, size=plen).astype(np.int32)
        load.append((prompt, gen))
    return load


def _leg_stats(leg, token_log, wall_s, results):
    lats = [emit - arr for (_rid, emit, arr) in token_log]
    return {
        "bench": "serve",
        "leg": leg,
        "requests": len(results),
        "tokens": len(token_log),
        "wall_s": round(wall_s, 4),
        "throughput_tokens_per_s": round(len(token_log) / wall_s, 2),
        "p50_token_latency_s": round(_percentile(lats, 50), 4),
        "p99_token_latency_s": round(_percentile(lats, 99), 4),
    }


def run_continuous(eng, load, interarrival):
    eng.token_log = []
    t0 = time.perf_counter()

    def source():
        for i, (prompt, gen) in enumerate(load):
            due = t0 + i * interarrival
            now = time.perf_counter()
            if due > now:
                time.sleep(due - now)
            # arrival = the open-loop injection time (due), NOT the
            # yield time: when staging backpressure pulls the generator
            # late, that queueing delay belongs IN the latency — the
            # static leg stamps due, and the A/B must match
            yield Request(id=i, prompt=prompt, max_new_tokens=gen,
                          arrival=due)

    eng.attach_source(source())
    results = eng.run()
    wall = time.perf_counter() - t0
    row = _leg_stats("continuous", eng.token_log, wall, results)
    row["kv_occupancy"] = round(eng.allocator.peak_occupancy, 4)
    row["evictions"] = eng.scheduler.evictions
    row["compiled_programs"] = eng.program_count
    return row, results


def run_static(eng, load, interarrival, batch):
    eng.token_log = []
    t0 = time.perf_counter()
    results = {}
    for at in range(0, len(load), batch):
        chunk = []
        for i in range(at, min(at + batch, len(load))):
            prompt, gen = load[i]
            due = t0 + i * interarrival
            now = time.perf_counter()
            if due > now:  # the batch waits for its slowest arrival
                time.sleep(due - now)
            chunk.append(Request(id=i, prompt=prompt, max_new_tokens=gen,
                                 arrival=due))
        results.update(eng.run_static(chunk, batch))
    wall = time.perf_counter() - t0
    row = _leg_stats("static", eng.token_log, wall, results)
    row["kv_occupancy"] = round(eng.allocator.peak_occupancy, 4)
    row["evictions"] = 0
    row["compiled_programs"] = eng.program_count
    return row, results


def kv_model_leg(cfg, serve_cfg, context_len):
    m = modeled_decode_read_bytes(
        context_len,
        block_size=serve_cfg.block_size,
        num_heads=cfg.num_heads,
        num_kv_heads=cfg.num_kv_heads or cfg.num_heads,
        head_dim=cfg.head_dim,
        num_layers=cfg.num_layers,
        window=cfg.window,
        dtype_bytes=jnp.dtype(cfg.dtype).itemsize,
        max_seq_len=cfg.max_seq_len,
    )
    return {
        "bench": "serve",
        "leg": "kv_model",
        "context_len": context_len,
        "kv_occupancy": None,  # schema parity with the measured legs
        "throughput_tokens_per_s": None,
        "p99_token_latency_s": None,
        # kernel reads (the _kb_range block-skip term) AND the gather
        # copy this engine materializes first — see the
        # modeled_decode_read_bytes docstring for why they differ
        "paged_read_bytes_per_decode_step": m["paged_bytes"],
        "gathered_bytes_per_decode_step": m["gathered_bytes"],
        "full_read_bytes_per_decode_step": m["full_bytes"],
        "pages_read": m["pages_read"],
        "pages_gathered": m["pages_gathered"],
        "read_reduction_x": round(m["full_bytes"] / m["paged_bytes"], 2),
        "gather_reduction_x": round(m["full_bytes"] / m["gathered_bytes"], 2),
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CI pass (CPU; scheduling is the claim)")
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--rate", type=float, default=None,
                    help="request arrivals per second (open loop)")
    ap.add_argument("--batch", type=int, default=8,
                    help="static-baseline batch size AND max decode batch")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    if args.smoke:
        n = args.requests or 40
        rate = args.rate or 200.0
        cfg = TransformerConfig(
            vocab_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
            head_dim=16, max_seq_len=96, dtype=jnp.float32,
            attention_impl="dot", causal=True)
        gen_long = 56
    else:
        n = args.requests or 96
        rate = args.rate or 100.0
        cfg = TransformerConfig(
            vocab_size=512, num_layers=4, num_heads=8, num_kv_heads=2,
            head_dim=32, max_seq_len=256, dtype=jnp.float32,
            attention_impl="dot", causal=True)
        gen_long = 96

    rs = np.random.RandomState(args.seed)
    load = build_load(rs, n, p_lo=4, p_hi=24, gen_short=4,
                      gen_long=gen_long, frac_long=0.2)
    interarrival = 1.0 / rate

    serve_cfg = ServeConfig(
        block_size=16, num_blocks=0, token_budget=4 * cfg.max_seq_len,
        watermark=2,
        # one intake tier (all prompts fit 32; the engine appends
        # max_seq_len for post-evict re-prefills) keeps the warmup menu
        # small without changing what the measured legs execute
        prefill_tiers=(32,),
        decode_tiers=tuple(sorted({t for t in (1, 2, 4, 8, 16, 32)
                                   if t < args.batch} | {args.batch})))
    eng = ServingEngine(cfg, params_for(cfg), serve=serve_cfg)

    # pre-compile the WHOLE tier menu: a mid-traffic XLA compile is a
    # multi-second p99 spike, and the bounded menu is what makes
    # warming it tractable (the executable-cache discipline under test)
    t0 = time.perf_counter()
    warmed = eng.warmup()
    print(f"warmup: {warmed} tier programs in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    cont_row, cont_res = run_continuous(eng, load, interarrival)
    cont_res = dict(cont_res)  # engine.results aliases; snapshot it
    eng.allocator.peak_occupancy = 0.0
    stat_row, stat_res = run_static(eng, load, interarrival, args.batch)
    for row in (cont_row, stat_row):
        # steady state must be all executable-cache hits
        row["compile_free"] = row.pop("compiled_programs") == warmed

    # the oracle, on the bench's own load: same greedy tokens both ways
    for i in range(n):
        if not np.array_equal(cont_res[i], stat_res[i]):
            print(f"ORACLE MISMATCH on request {i}", file=sys.stderr)
            return 1

    cont_row["speedup_vs_static"] = round(
        cont_row["throughput_tokens_per_s"]
        / max(stat_row["throughput_tokens_per_s"], 1e-9), 2)
    kv_row = kv_model_leg(cfg, serve_cfg, context_len=cfg.max_seq_len // 2)

    for row in (cont_row, stat_row, kv_row):
        print(json.dumps(row))
    print(
        f"continuous {cont_row['throughput_tokens_per_s']} tok/s "
        f"(p99 {cont_row['p99_token_latency_s']}s) vs static "
        f"{stat_row['throughput_tokens_per_s']} tok/s "
        f"(p99 {stat_row['p99_token_latency_s']}s) — "
        f"{cont_row['speedup_vs_static']}x; paged decode reads "
        f"{kv_row['read_reduction_x']}x fewer K/V bytes", file=sys.stderr)
    return 0


def params_for(cfg):
    model = Transformer(cfg)
    rng = jax.random.PRNGKey(0)
    return model.init(rng, jnp.zeros((1, 8), jnp.int32),
                      train=False)["params"]


if __name__ == "__main__":
    sys.exit(main())
