#!/usr/bin/env python
"""Trace-recorder benchmark: the ISSUE-15 exactness/overhead bars.

Every leg emits ONE bench-style JSON line on stdout (human summary on
stderr) — the flash_bench/guard_bench contract.  Legs:

  * ``trace_oracle`` — the SAME compiled train step driven through
    ``training.fit_epoch`` with tracing ON vs OFF: state and loss must
    be BIT-identical (tracing is host-side bookkeeping; it never
    touches the program).
  * ``trace_collectives`` — StableHLO collective inventory of the
    train step built with tracing on vs off: the lowered text must be
    IDENTICAL (hash-compared), so added collectives are EXACTLY 0 and
    added compiles are structurally 0 — the acceptance bars.
  * ``trace_overhead`` — median per-step wall time, tracing ON vs OFF,
    measured in INTERLEAVED A/B rounds (the guard_bench idiom: drift on
    a contended box cancels out of the ratio).  Bar:
    ``overhead_frac <= 0.02`` at default settings.  Only meaningful in
    the full run (the smoke step is ~ms and aliases timer noise).
  * ``trace_serve`` — a traced serving burst: the ``/trace``-shape
    Chrome export must be VALID trace-event JSON (every event carries
    name/ph/ts; complete events carry dur), steady state stays
    compile-free with tracing on AND off (zero extra programs), greedy
    tokens are identical either way, and the per-request TTFT
    decomposition (queued + prefill chunks + first decode) sums to the
    measured TTFT within tolerance.

Usage:
  trace_bench.py            # full legs — what the CI trace-smoke job runs
  trace_bench.py --smoke    # tiny fast pass: oracle/collectives/export
                            # meaningful, overhead_frac is NOT
"""

import argparse
import hashlib
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import trace, training  # noqa: E402
from horovod_tpu.common.retry import env_int  # noqa: E402
from horovod_tpu.models.transformer import (  # noqa: E402
    Transformer, TransformerConfig,
)
from horovod_tpu.serving.engine import ServeConfig, ServingEngine  # noqa: E402
from horovod_tpu.trace import export as trace_export  # noqa: E402

ITERS = env_int("HVD_TPU_BENCH_ITERS", 20)
WARMUP = env_int("HVD_TPU_BENCH_WARMUP", 3)

_COLLECTIVE_RE = re.compile(
    r"stablehlo\.(all_reduce|all_gather|reduce_scatter|"
    r"collective_permute|all_to_all)")


def _emit(row):
    row["t_end"] = round(time.time(), 3)
    print(json.dumps(row), flush=True)


def _say(msg):
    print(f"[trace_bench] {msg}", file=sys.stderr, flush=True)


def _copy(state):
    return jax.tree_util.tree_map(jnp.copy, state)


def _build(smoke):
    cfg = TransformerConfig(
        vocab_size=256,
        num_layers=2 if smoke else 4,
        num_heads=4 if smoke else 8,
        head_dim=16 if smoke else 32,
        max_seq_len=64 if smoke else 128,
        dtype=jnp.float32,
        attention_impl="dot",
        causal=True,
    )
    model = Transformer(cfg)
    batch = 4 if smoke else 16
    rs = np.random.RandomState(0)
    x = rs.randint(1, cfg.vocab_size, size=(batch, cfg.max_seq_len)
                   ).astype(np.int32)
    y = rs.randint(0, cfg.vocab_size, size=(batch, cfg.max_seq_len)
                   ).astype(np.int32)
    opt = optax.adamw(1e-3)
    state = training.replicate_state(training.create_train_state(
        model, opt, jax.random.PRNGKey(0), x[:1]))
    step = training.data_parallel_train_step(model, opt, guard=False)
    return cfg, step, state, x, y


def _fit(step, state, x, y, n):
    """n steps through fit_epoch (the traced loop) on a list loader."""
    return training.fit_epoch(step, state, [(x, y)] * n)


def run_train_legs(args, t_start):
    _, step, state, x, y = _build(args.smoke)

    # -- trace_oracle: bit-identical state + loss ----------------------------
    trace.configure(enabled=True)
    sa, la = _fit(step, _copy(state), x, y, 3)
    trace.configure(enabled=False)
    sb, lb = _fit(step, _copy(state), x, y, 3)
    trace.configure(enabled=True)
    bit_exact = float(la) == float(lb)
    for pa, pb in zip(jax.tree_util.tree_leaves(sa.params),
                      jax.tree_util.tree_leaves(sb.params)):
        if not np.array_equal(np.asarray(pa), np.asarray(pb)):
            bit_exact = False
    _emit({"bench": "trace_oracle", "steps": 3, "bit_exact": bit_exact,
           "t_start": t_start})
    _say(f"oracle bit_exact={bit_exact}")

    # -- trace_collectives: identical lowered program ------------------------
    def lowered():
        return step.lower(_copy(state), x, y).as_text()

    trace.configure(enabled=True)
    text_on = lowered()
    trace.configure(enabled=False)
    text_off = lowered()
    trace.configure(enabled=True)
    n_on = len(_COLLECTIVE_RE.findall(text_on))
    n_off = len(_COLLECTIVE_RE.findall(text_off))
    same = (hashlib.sha256(text_on.encode()).hexdigest()
            == hashlib.sha256(text_off.encode()).hexdigest())
    _emit({
        "bench": "trace_collectives",
        "collectives_traced": n_on,
        "collectives_untraced": n_off,
        "added_collectives": n_on - n_off,
        "stablehlo_identical": same,
        "t_start": t_start,
    })
    _say(f"collectives traced={n_on} untraced={n_off} identical={same}")

    # -- trace_overhead: interleaved A/B -------------------------------------
    k = 4  # steps per round: the per-epoch base sync amortizes like prod
    sa, sb = _copy(state), _copy(state)
    for _ in range(max(1, WARMUP // 2)):
        trace.configure(enabled=True)
        sa, _ = _fit(step, sa, x, y, k)
        trace.configure(enabled=False)
        sb, _ = _fit(step, sb, x, y, k)
    t_on, t_off = [], []
    for _ in range(max(1, ITERS)):
        trace.configure(enabled=True)
        t0 = time.perf_counter()
        sa, _ = _fit(step, sa, x, y, k)
        jax.block_until_ready(sa.params)
        t1 = time.perf_counter()
        trace.configure(enabled=False)
        sb, _ = _fit(step, sb, x, y, k)
        jax.block_until_ready(sb.params)
        t2 = time.perf_counter()
        t_on.append((t1 - t0) / k)
        t_off.append((t2 - t1) / k)
    trace.configure(enabled=True)
    ms_on = float(np.median(t_on) * 1e3)
    ms_off = float(np.median(t_off) * 1e3)
    overhead = (ms_on - ms_off) / ms_off
    _emit({
        "bench": "trace_overhead",
        "step_ms_traced": round(ms_on, 3),
        "step_ms_untraced": round(ms_off, 3),
        "overhead_frac": round(overhead, 4),
        "iters": ITERS,
        "t_start": t_start,
    })
    _say(f"overhead {overhead * 100:.2f}% ({ms_off:.1f} -> {ms_on:.1f} ms)")


def _valid_chrome(doc) -> bool:
    evs = doc.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        return False
    for e in evs:
        if not isinstance(e.get("name"), str) or "ph" not in e:
            return False
        if e["ph"] in ("X", "i") and "ts" not in e:
            return False
        if e["ph"] == "X" and "dur" not in e:
            return False
    return True


def run_serve_leg(args, t_start):
    cfg = TransformerConfig(
        vocab_size=128, num_layers=1, num_heads=2, head_dim=16,
        max_seq_len=64, dtype=jnp.float32, attention_impl="dot",
        causal=True)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    serve = ServeConfig(decode_tiers=(1, 2, 4), token_budget=512,
                        prefill_chunk=16)
    n_req = 6 if args.smoke else 16
    rs = np.random.RandomState(7)

    def run_burst(eng):
        rids = [eng.submit(rs.randint(1, 100, size=rs.randint(4, 33)),
                           int(rs.randint(2, 6))) for _ in range(n_req)]
        toks = eng.run()
        return rids, {r: toks[r].tolist() for r in rids}

    trace.configure(enabled=True)
    eng_on = ServingEngine(cfg, params, serve=serve)
    eng_on.warmup()
    progs_warm = eng_on.program_count
    since = trace.now()
    rids, toks_on = run_burst(eng_on)
    compile_free_on = eng_on.program_count == progs_warm
    recs = trace.snapshot(since=since)

    trace.configure(enabled=False)
    eng_off = ServingEngine(cfg, params, serve=serve)
    eng_off.warmup()
    rs = np.random.RandomState(7)  # same request stream
    _, toks_off = run_burst(eng_off)
    compile_free_off = eng_off.program_count == progs_warm
    trace.configure(enabled=True)

    tokens_identical = toks_on == toks_off

    doc = trace_export.chrome_trace(since=since, records=recs)
    valid = _valid_chrome(doc)

    decomp = [d for d in (trace_export.request_decomposition(recs, r)
                          for r in rids) if d is not None]
    max_err = max((d["err_s"] for d in decomp), default=None)
    max_rel = max((d["err_s"] / max(d["measured_ttft_s"], 1e-9)
                   for d in decomp), default=None)
    _emit({
        "bench": "trace_serve",
        "requests": n_req,
        "events": len(doc["traceEvents"]),
        "valid_trace_json": valid,
        "tokens_identical": tokens_identical,
        "compile_free_traced": compile_free_on,
        "compile_free_untraced": compile_free_off,
        "programs": progs_warm,
        "ttft_decomp_requests": len(decomp),
        "ttft_decomp_max_err_s": (None if max_err is None
                                  else round(max_err, 4)),
        "ttft_decomp_max_rel_err": (None if max_rel is None
                                    else round(max_rel, 4)),
        "t_start": t_start,
    })
    _say(f"serve valid={valid} tokens_identical={tokens_identical} "
         f"decomp n={len(decomp)} max_err={max_err}")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU-safe pass (CI; overhead_frac not "
                    "meaningful)")
    args = ap.parse_args(argv)

    hvd.init()
    t_start = round(time.time(), 3)
    run_train_legs(args, t_start)
    run_serve_leg(args, t_start)
    return 0


if __name__ == "__main__":
    sys.exit(main())
