#!/usr/bin/env python
"""Input-pipeline benchmark + large-batch sweep (PERF.md round 6).

Three modes, all emitting one JSON document on stdout:

  stages   microbenchmark each pipeline stage standalone: source read,
           worker-pool scaling, prefetch-on/off A/B against a simulated
           compute step (how much overlap is worth);
  ab       end-to-end train-step comparison: device-resident synthetic
           vs pipeline-fed npy (prefetch on and off) — the bench.py
           acceptance A/B (resident vs --data npy) in one process;
  sweep    batch-size sweep of the compiled train step with pipeline
           feeding, recording step time, input wait, host produce/put
           cost, and XLA cost analysis (flops + bytes accessed) per
           batch — the instrumentation behind "name the large-batch
           limiter" (PERF.md).

CPU-host runs use ResNetTiny/64px so the numbers are about the PIPELINE
(decode, staging, overlap); chip runs use the bench.py config
(ResNet-50, 224px, space-to-depth stem) so sweep results line up with
the headline table.
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _EpochFeed  # noqa: E402 — cumulative pipeline stats


def _make_npy(root, n, image_size):
    import atexit
    import shutil

    import numpy as np

    from horovod_tpu import data

    atexit.register(shutil.rmtree, root, ignore_errors=True)
    rng = np.random.RandomState(0)
    inputs = rng.randint(0, 256, size=(n, image_size, image_size, 3),
                         dtype=np.uint8)
    labels = rng.randint(0, 1000, size=(n,)).astype(np.int32)
    data.write_npy_shards(root, inputs, labels, num_shards=4)
    return root


def bench_stages(args):
    import numpy as np

    from horovod_tpu import data
    from horovod_tpu.data import workers as workers_mod

    out = {}
    bs, size = args.batch, args.image_size
    root = _make_npy(tempfile.mkdtemp(prefix="dpb_npy_"), 8 * bs, size)
    src = data.NpyShardSource(root)
    idx = np.arange(bs)

    # raw source read (mmap fancy-index + uint8->f32 decode)
    t0 = time.perf_counter()
    reps = 10
    for r in range(reps):
        x, y = src.batch((idx + r * bs) % len(src))
        x.astype(np.float32)
    out["npy_read_decode_ms_per_batch"] = round(
        (time.perf_counter() - t0) / reps * 1e3, 3)

    syn = data.SyntheticSource(8 * bs, image_size=size)
    t0 = time.perf_counter()
    for r in range(3):
        syn.batch((idx + r * bs) % len(syn))
    out["synthetic_gen_ms_per_batch"] = round(
        (time.perf_counter() - t0) / 3 * 1e3, 3)

    # worker-pool scaling on the decode workload
    def collate(indices):
        x, y = src.batch(indices)
        return x.astype(np.float32) / 255.0, y

    batches = [(idx + r * bs) % len(src) for r in range(16)]
    scaling = {}
    for w in (0, 1, 2, 4):
        t0 = time.perf_counter()
        for _ in workers_mod.map_ordered(collate, batches, num_workers=w,
                                         window=max(2 * w, 2)):
            pass
        scaling[str(w)] = round((time.perf_counter() - t0) / 16 * 1e3, 3)
    out["map_ordered_ms_per_batch_by_workers"] = scaling

    # prefetch A/B against a simulated step: with overlap the loop should
    # cost ~max(produce, step) per item, without it produce + step
    step_ms = args.sim_step_ms

    def run(depth):
        loader = data.DataLoader(src, batch_size=bs, transform=None,
                                 num_workers=2, prefetch_depth=depth,
                                 device_put=False, shuffle=False,
                                 shard=data.ShardSpec(0, 1))
        t0 = time.perf_counter()
        n = 0
        for _ in loader:
            time.sleep(step_ms / 1e3)  # the "compute"
            n += 1
        return round((time.perf_counter() - t0) / n * 1e3, 3), loader.stats()

    for depth in (0, 2):
        per, stats = run(depth)
        out[f"loop_ms_per_batch_prefetch_{depth}"] = per
        out[f"input_wait_ms_mean_prefetch_{depth}"] = stats[
            "input_wait_ms_mean"]
    out["sim_step_ms"] = step_ms
    return out


def _train_setup(on_tpu, batch, image_size):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from horovod_tpu import training
    from horovod_tpu.models.resnet import ResNet50
    from horovod_tpu.models import ResNetTiny

    if on_tpu:
        model = ResNet50(num_classes=1000, dtype=jnp.bfloat16,
                         stem="space_to_depth")
    else:
        model = ResNetTiny(dtype=jnp.bfloat16)
    optimizer = optax.sgd(0.1, momentum=0.9)
    sample = jnp.asarray(
        np.zeros((2, image_size, image_size, 3), np.float32))
    state = training.create_train_state(
        model, optimizer, jax.random.PRNGKey(0), sample)
    state = training.replicate_state(state)
    step = training.data_parallel_train_step(model, optimizer)
    return state, step


def _timed_steps(step, state, feed, iters):
    t0 = time.perf_counter()
    loss = None
    for _ in range(iters):
        state, loss = step(state, *next(feed))
    if loss is not None:
        float(loss)
    return state, time.perf_counter() - t0


def bench_ab(args):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from horovod_tpu import data

    on_tpu = jax.default_backend() != "cpu"
    bs, size = args.batch, args.image_size
    warmup, iters = (5, 20) if on_tpu else (1, 3)
    out = {"backend": jax.default_backend(), "batch": bs,
           "image_size": size}

    # resident baseline
    state, step = _train_setup(on_tpu, bs, size)
    images = jnp.asarray(np.random.RandomState(0).randn(
        bs, size, size, 3).astype(np.float32))
    labels = jnp.asarray(np.random.RandomState(1).randint(
        0, 1000, size=(bs,)))

    def resident():
        while True:
            yield images, labels

    feed = resident()
    state, _ = _timed_steps(step, state, feed, warmup)
    state, dt = _timed_steps(step, state, feed, iters)
    out["resident_img_s"] = round(bs * iters / dt, 1)
    out["resident_step_ms"] = round(dt / iters * 1e3, 2)

    # pipeline-fed npy, prefetch on/off
    root = _make_npy(tempfile.mkdtemp(prefix="dpb_ab_"), 8 * bs, size)
    for depth, tag in ((None, "prefetch_on"), (0, "prefetch_off")):
        loader = data.make_loader(
            "npy", root, batch_size=bs, image_size=size,
            cast="bfloat16" if on_tpu else None, prefetch_depth=depth)
        state, step = _train_setup(on_tpu, bs, size)
        feed_obj = _EpochFeed(loader)
        feed = iter(feed_obj)
        state, _ = _timed_steps(step, state, feed, warmup)
        wait0 = feed_obj.stats().get("input_wait_ms_total", 0.0)
        state, dt = _timed_steps(step, state, feed, iters)
        stats = feed_obj.stats()
        out[f"npy_{tag}_img_s"] = round(bs * iters / dt, 1)
        out[f"npy_{tag}_step_ms"] = round(dt / iters * 1e3, 2)
        out[f"npy_{tag}_input_wait_ms"] = round(
            (stats.get("input_wait_ms_total", 0.0) - wait0) / iters, 3)
        out[f"npy_{tag}_host_produce_ms"] = stats.get(
            "host_produce_ms_mean")
    out["npy_vs_resident_pct"] = round(
        100.0 * out["npy_prefetch_on_img_s"] / out["resident_img_s"], 1)
    return out


def bench_sweep(args):
    """Batch sweep with pipeline feeding + XLA cost analysis — the
    large-batch-limiter instrumentation (PERF.md round 6)."""
    import jax
    import numpy as np

    from horovod_tpu import data

    on_tpu = jax.default_backend() != "cpu"
    size = args.image_size
    warmup, iters = (5, 20) if on_tpu else (1, 3)
    batches = args.batches or ([128, 256, 512, 1024] if on_tpu
                               else [16, 32, 64])
    rows = []
    for bs in batches:
        row = {"batch": bs}
        try:
            root = _make_npy(
                tempfile.mkdtemp(prefix=f"dpb_sweep{bs}_"),
                max(4 * bs, 256), size)
            loader = data.make_loader(
                "npy", root, batch_size=bs, image_size=size,
                cast="bfloat16" if on_tpu else None)
            state, step = _train_setup(on_tpu, bs, size)
            feed_obj = _EpochFeed(loader)
            feed = iter(feed_obj)
            first = next(feed)
            # AOT compile: one program per batch size — the sweep itself
            # proves shapes are static per config (no per-step recompile)
            try:
                compiled = step.lower(state, *first).compile()
                ca = compiled.cost_analysis()
                if isinstance(ca, list):
                    ca = ca[0] if ca else None
                if ca:
                    row["xla_flops_per_step"] = float(ca.get("flops", 0))
                    row["xla_bytes_per_step"] = float(
                        ca.get("bytes accessed", 0))
                step = compiled
            except Exception as e:  # remote backends may refuse AOT
                row["cost_analysis_error"] = str(e)[:120]
            state, loss = step(state, *first)
            state, _ = _timed_steps(step, state, feed, warmup)
            wait0 = feed_obj.stats().get("input_wait_ms_total", 0.0)
            state, dt = _timed_steps(step, state, feed, iters)
            stats = feed_obj.stats()
            row["step_ms"] = round(dt / iters * 1e3, 2)
            row["img_s"] = round(bs * iters / dt, 1)
            row["input_wait_ms"] = round(
                (stats.get("input_wait_ms_total", 0.0) - wait0) / iters, 3)
            row["input_wait_pct"] = round(
                100 * row["input_wait_ms"] / row["step_ms"], 2)
            row["host_produce_ms"] = stats.get("host_produce_ms_mean")
            row["device_put_ms"] = stats.get("device_put_ms_mean")
            if "xla_bytes_per_step" in row and row["step_ms"]:
                # effective HBM bandwidth implied by the counted bytes:
                # counted_bytes / step_time.  Rising above spec bandwidth
                # = the schedule re-reads more than the count (VMEM
                # residency loss); see PERF.md round 6.
                row["implied_gbps"] = round(
                    row["xla_bytes_per_step"] / (row["step_ms"] / 1e3)
                    / 1e9, 1)
        except Exception as e:
            row["error"] = f"{type(e).__name__}: {e}"[:200]
        rows.append(row)
        print(f"[sweep] {row}", file=sys.stderr)
    return {"backend": jax.default_backend(), "image_size": size,
            "rows": rows}


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--mode", default="stages",
                   choices=["stages", "ab", "sweep"])
    p.add_argument("--batch", type=int, default=None)
    p.add_argument("--image-size", type=int, default=None)
    p.add_argument("--batches", type=int, nargs="*", default=None,
                   help="sweep mode batch list")
    p.add_argument("--sim-step-ms", type=float, default=20.0)
    args = p.parse_args()

    import jax

    import horovod_tpu as hvd

    hvd.init()
    on_tpu = jax.default_backend() != "cpu"
    if args.batch is None:
        args.batch = 128 if on_tpu else 32
    if args.image_size is None:
        args.image_size = 224 if on_tpu else 64

    result = {"stages": bench_stages, "ab": bench_ab,
              "sweep": bench_sweep}[args.mode](args)
    result["mode"] = args.mode
    print(json.dumps(result, indent=2))


if __name__ == "__main__":
    main()
