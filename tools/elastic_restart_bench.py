#!/usr/bin/env python
"""Measure elastic exec-restart cost vs state size (VERDICT r3 item 3).

Runs a real elastic job (2 workers on this host), triggers a PLANNED
scale-up to 3 mid-run, then a kill -9 FAILURE recovery, and reports the
per-worker restart cost split the instrumented restart path records
(horovod_tpu/elastic/worker.py): persist (pickle → disk), reboot
(execv → wrapper re-entry: interpreter + jax import + rendezvous +
init), restore (unpickle + apply).  State size is swept via a numpy
ballast array in the elastic state.

Usage::

    python tools/elastic_restart_bench.py [--sizes 1,100,1024]  # MB

Results land in PERF.md ("Round 4: elastic restart cost").
"""

import argparse
import json
import os
import signal
import stat
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "integration", "elastic_worker.py")


def read_logs(logdir):
    events = []
    for name in os.listdir(logdir):
        with open(os.path.join(logdir, name)) as f:
            for line in f:
                ev = json.loads(line)
                ev["worker"] = name
                events.append(ev)
    return events


def wait_for(logdir, pred, timeout):
    deadline = time.time() + timeout
    while time.time() < deadline:
        evs = read_logs(logdir)
        if pred(evs):
            return evs
        time.sleep(0.5)
    raise TimeoutError("condition not reached; last events: %r" % (
        read_logs(logdir)[-5:],))


def run_one(size_bytes: int, do_kill: bool = True):
    tmp = tempfile.mkdtemp(prefix="hvd_restart_bench_")
    hosts = os.path.join(tmp, "hosts.txt")
    with open(hosts, "w") as f:
        f.write("localhost:2\n")
    script = os.path.join(tmp, "discover.sh")
    with open(script, "w") as f:
        f.write(f"#!/bin/sh\ncat {hosts}\n")
    os.chmod(script, os.stat(script).st_mode | stat.S_IEXEC)
    logdir = os.path.join(tmp, "logs")
    os.mkdir(logdir)

    env = os.environ.copy()
    env.update({
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "HVD_TPU_ELASTIC_TIMEOUT": "120",
    })
    env.pop("XLA_FLAGS", None)
    cmd = [sys.executable, "-m", "horovod_tpu.runner",
           "--host-discovery-script", script, "--min-np", "1",
           "--max-np", "3",
           "--", sys.executable, WORKER, logdir, "1", "400",
           str(size_bytes)]
    proc = subprocess.Popen(cmd, env=env, cwd=REPO,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    # boot sync broadcasts the whole state; commits copy it per batch —
    # both scale with size, so the waits must too
    win = 120 + size_bytes / 10e6
    try:
        # let both workers demonstrably train, then scale up (planned)
        wait_for(logdir, lambda evs: sum(
            1 for e in evs if e["event"] == "batch" and e["batch"] >= 3
        ) >= 2, win)
        with open(hosts, "w") as f:
            f.write("localhost:3\n")
        evs = wait_for(logdir, lambda evs: any(
            e["event"] == "restart_stats" for e in evs
        ) and any(e["event"] == "batch" and e["world"] >= 2
                  and e["worker"] == "worker_2.log" for e in evs), 240 + win)
        def stat_key(e):
            return (e["worker"], e["total_s"], e["persist_s"],
                    e["reboot_s"])

        planned = [e for e in evs if e["event"] == "restart_stats"]
        killed = []
        if do_kill:
            pids = sorted({e["pid"] for e in evs if e["event"] == "init"})
            # kill the newest-init pid still alive
            for pid in reversed(pids):
                try:
                    os.kill(pid, 0)
                except OSError:
                    continue
                os.kill(pid, signal.SIGKILL)
                break
            seen = {stat_key(e) for e in planned}
            evs = wait_for(logdir, lambda evs: any(
                e["event"] == "restart_stats" and stat_key(e) not in seen
                for e in evs
            ), 180 + win)
            killed = [e for e in evs if e["event"] == "restart_stats"
                      and stat_key(e) not in seen]
        return planned, killed
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="1,100,1024",
                    help="state ballast sizes in MB, comma-separated")
    ap.add_argument("--no-kill", action="store_true")
    args = ap.parse_args()
    print(f"{'MB':>6} {'kind':>8} {'persist_s':>9} {'reboot_s':>8} "
          f"{'restore_s':>9} {'total_s':>8}")
    for mb in [float(s) for s in args.sizes.split(",")]:
        planned, killed = run_one(int(mb * 1e6), do_kill=not args.no_kill)
        for kind, stats in (("planned", planned), ("failure", killed)):
            for s in stats:
                print(f"{mb:>6.0f} {kind:>8} {s['persist_s']:>9.2f} "
                      f"{s['reboot_s']:>8.2f} {s['restore_s']:>9.2f} "
                      f"{s['total_s']:>8.2f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
