#!/usr/bin/env bash
# Rebuild horovod_tpu/native/libhvd_tpu_core.so from src/ with the full
# warning wall (-Wall -Wextra -Werror): the checked-in binary must only
# ever be produced by a warning-clean compile, so a stale or sloppy
# rebuild can't slip into a commit.  CI/tooling entry point — `pip
# install .` (setup.py build_ext) remains the user-facing build.
#
# Usage: tools/rebuild_native.sh [--sanitize=thread|address] [extra CXXFLAGS...]
#
# --sanitize builds the instrumented twin (libhvd_tpu_core.tsan.so /
# .asan.so — see docs/ANALYSIS.md) next to the production binary
# instead of replacing it.
#
# Pairs with tests/test_native_build.py, which asserts the on-disk .so
# exports exactly the hvdtpu_* C API surface declared in c_api.cc; the
# export check below reuses the same parser
# (horovod_tpu.analysis.c_api via tools/check.py --list-c-symbols), so
# the symbol list lives in exactly one place.
set -euo pipefail

TOOLS_DIR="$(cd "$(dirname "$0")" && pwd)"
cd "$TOOLS_DIR/../horovod_tpu/native/src"

SANITIZE=""
if [[ "${1:-}" == --sanitize=* ]]; then
  SANITIZE="${1#--sanitize=}"
  shift
fi

CXX="${CXX:-g++}"
CXXFLAGS="-O2 -fPIC -std=c++17 -Wall -Wextra -Werror -pthread $*"

case "$SANITIZE" in
  "")
    SO_NAME="libhvd_tpu_core.so"
    make clean >/dev/null
    ;;
  thread)  SO_NAME="libhvd_tpu_core.tsan.so"; rm -f "../$SO_NAME" ;;
  address) SO_NAME="libhvd_tpu_core.asan.so"; rm -f "../$SO_NAME" ;;
  *)
    echo "[rebuild_native] ERROR: --sanitize=$SANITIZE (want thread|address)" >&2
    exit 2
    ;;
esac

echo "[rebuild_native] $CXX $CXXFLAGS SANITIZE=${SANITIZE:-off}" >&2
make CXX="$CXX" CXXFLAGS="$CXXFLAGS" SANITIZE="$SANITIZE"

SO="$(cd .. && pwd)/$SO_NAME"
echo "[rebuild_native] built $SO" >&2
# sanity: every extern "C" symbol declared in c_api.cc must be exported.
# The declared-symbol list comes from the shared C-API parser (the same
# one the contract checker and test_native_build.py use).  Set
# difference via comm over fully-materialized sorted lists — any
# `... | grep -q` probe under pipefail SIGPIPE-flakes once the symtab
# is large (the ASan build statically links a 14 MB runtime).
declared="$(python3 "$TOOLS_DIR/check.py" --list-c-symbols | sort -u)"
# `|| true`: zero exported hvdtpu_ symbols must fall through to the
# report below, not kill the script via pipefail on grep's no-match
exported="$(nm -D --defined-only "$SO" | awk '{print $NF}' \
            | { grep '^hvdtpu_' || true; } | sort -u)"
missing="$(comm -23 <(printf '%s\n' "$declared") \
                    <(printf '%s\n' "$exported"))"
if [ -n "$missing" ]; then
  echo "[rebuild_native] ERROR: symbols declared but not exported:" >&2
  echo "$missing" >&2
  exit 1
fi
echo "[rebuild_native] symbol export check passed" >&2
