#!/usr/bin/env bash
# Rebuild horovod_tpu/native/libhvd_tpu_core.so from src/ with the full
# warning wall (-Wall -Wextra -Werror): the checked-in binary must only
# ever be produced by a warning-clean compile, so a stale or sloppy
# rebuild can't slip into a commit.  CI/tooling entry point — `pip
# install .` (setup.py build_ext) remains the user-facing build.
#
# Usage: tools/rebuild_native.sh [extra CXXFLAGS...]
# Pairs with tests/test_native_build.py, which asserts the committed .so
# exports exactly the hvdtpu_* C API surface declared in c_api.cc.
set -euo pipefail

cd "$(dirname "$0")/../horovod_tpu/native/src"

CXX="${CXX:-g++}"
CXXFLAGS="-O2 -fPIC -std=c++17 -Wall -Wextra -Werror -pthread $*"

echo "[rebuild_native] $CXX $CXXFLAGS" >&2
make clean >/dev/null
make CXX="$CXX" CXXFLAGS="$CXXFLAGS"

SO="$(cd .. && pwd)/libhvd_tpu_core.so"
echo "[rebuild_native] built $SO" >&2
# sanity: every extern "C" symbol declared in c_api.cc must be exported —
# including the hvdtpu_chaos_* / heartbeat surface.  Snapshot the symbol
# table ONCE: under pipefail, `nm | grep -q` flakes when grep's early
# exit SIGPIPEs nm mid-write (false "missing" as the API surface grew).
symtab="$(nm -D --defined-only "$SO")"
missing=$(
  grep -oE '^(int|void|long long|double|const char\*) hvdtpu_[a-z_0-9]+' \
      c_api.cc | awk '{print $NF}' | sort -u |
  while read -r sym; do
    printf '%s\n' "$symtab" | grep -q " $sym\$" || echo "$sym"
  done
)
if [ -n "$missing" ]; then
  echo "[rebuild_native] ERROR: symbols declared but not exported:" >&2
  echo "$missing" >&2
  exit 1
fi
echo "[rebuild_native] symbol export check passed" >&2
