#!/usr/bin/env python
"""Analytic data-parallel scaling model for the headline ResNet-50 bench.

Multi-chip hardware is not reachable from this environment (single-chip
axon tunnel), so the BASELINE north star — >=90% scaling efficiency to
256 chips — cannot be measured directly.  This tool states the model and
the measured inputs it rests on, so the efficiency claim is a checkable
calculation rather than an assertion.  It is a MODEL, labeled as such:
the real number depends on XLA's compute/communication overlap, which
this bounds from both sides.

Model (standard DP ring cost, e.g. the reference's own ring-allreduce
analysis and the scaling-book recipe):
  t_comm(n)  = 2*(n-1)/n * G / B_ici          (bf16 gradient allreduce)
  eff_worst  = t_step / (t_step + t_comm)      (zero overlap)
  eff_best   = t_step / max(t_step, t_comm)    (perfect overlap)
Cross-slice (DCN) terms only enter past one pod slice; v5e slices reach
256 chips on ICI, so the headline range never leaves ICI.

Measured inputs (PERF.md / BENCH_builder_r04.json, v5e single chip):
  t_step = 47.6 ms  (ResNet-50, batch 128/chip, bf16, space-to-depth)
  G      = 25.6M params -> 51.2 MB bf16 on the wire (fp32 would be 102 MB)

Hardware constant (approx., public v5e spec): 1600 Gbit/s ICI per chip
=> B_ici ~= 200 GB/s aggregate; the ring uses it bidirectionally.
"""

import json

T_STEP_S = 0.0476          # measured, v5e batch 128 (PERF.md round 4)
PARAMS = 25.6e6
WIRE_BYTES = PARAMS * 2    # bf16 gradient compression on the wire
B_ICI = 200e9              # ~1600 Gbit/s per v5e chip (approx. public spec)
#: default BucketSchedule bucket (HVD_TPU_OVERLAP_BUCKET_BYTES) for the
#: bucketed-overlap row; ops/comm_model.modeled_overlap_exposed is the
#: canonical simulation — tools/collective_bench.py cross-checks this
#: file's inline twin against it on the overlap leg.
BUCKET_BYTES = 4 * 1024 * 1024


def model(n: int):
    t_comm = 2 * (n - 1) / n * WIRE_BYTES / B_ICI
    worst = T_STEP_S / (T_STEP_S + t_comm)
    best = T_STEP_S / max(T_STEP_S, t_comm)
    return t_comm, worst, best


def overlap_model(n: int, bucket_bytes: int = BUCKET_BYTES):
    """Bucketed backward/overlap row (ops/overlap.py schedule): buckets
    are produced across the backward at a byte-proportional rate, each
    bucket's ring allreduce queues on the serial link, and only what
    outlives the compute is exposed.  Inline twin of
    ``ops.comm_model.modeled_overlap_exposed`` (kept dependency-free so
    this tool stays stdlib-only); returns (t_exposed_s,
    exposed_fraction, efficiency)."""
    if n <= 1:
        return 0.0, 0.0, 1.0
    sizes = [bucket_bytes] * int(WIRE_BYTES // bucket_bytes)
    rem = WIRE_BYTES - bucket_bytes * len(sizes)
    if rem:
        sizes.append(rem)
    ring = 2 * (n - 1) / n / B_ICI
    t_comm = sum(s * ring for s in sizes)
    cum, end = 0.0, 0.0
    for s in sizes:
        cum += s
        ready = T_STEP_S * cum / WIRE_BYTES
        end = max(ready, end) + s * ring
    exposed = max(0.0, end - T_STEP_S)
    frac = exposed / t_comm if t_comm else 0.0
    return exposed, frac, T_STEP_S / (T_STEP_S + exposed)


def main():
    rows = []
    for n in (1, 8, 32, 64, 256):
        t_comm, worst, best = model(n)
        exposed, frac, eff_overlap = overlap_model(n)
        rows.append({
            "chips": n,
            "t_comm_ms": round(t_comm * 1e3, 3),
            "efficiency_no_overlap": round(worst, 4),
            "efficiency_full_overlap": round(best, 4),
            "bucketed_exposed_ms": round(exposed * 1e3, 4),
            "bucketed_exposed_fraction": round(frac, 4),
            "efficiency_bucketed_overlap": round(eff_overlap, 4),
        })
        print(f"n={n:4d}: allreduce {t_comm*1e3:6.3f} ms  "
              f"efficiency {worst:.1%} (no overlap) .. {best:.1%} (full); "
              f"bucketed schedule exposes {frac:.1%} of comm "
              f"-> {eff_overlap:.1%}")
    print()
    worst_comm_ms = max(r["t_comm_ms"] for r in rows)
    print("Even with ZERO compute/comm overlap the model stays above "
          f"{min(r['efficiency_no_overlap'] for r in rows):.1%} — the "
          f"51 MB bf16 gradient ring is ~{worst_comm_ms:.2f} ms against "
          "a 47.6 ms step, so the reference's >=90%-at-256 regime is "
          "bandwidth-trivial for this model on ICI.  The binding risks "
          "are stragglers and input pipeline, not the collective.")
    print(json.dumps({"model": "dp_ring_allreduce", "rows": rows}))


if __name__ == "__main__":
    main()
