#!/usr/bin/env python
"""Analytic data-parallel scaling model for the headline ResNet-50 bench.

Multi-chip hardware is not reachable from this environment (single-chip
axon tunnel), so the BASELINE north star — >=90% scaling efficiency to
256 chips — cannot be measured directly.  This tool states the model and
the measured inputs it rests on, so the efficiency claim is a checkable
calculation rather than an assertion.  It is a MODEL, labeled as such:
the real number depends on XLA's compute/communication overlap, which
this bounds from both sides.

Model (standard DP ring cost, e.g. the reference's own ring-allreduce
analysis and the scaling-book recipe):
  t_comm(n)  = 2*(n-1)/n * G / B_ici          (bf16 gradient allreduce)
  eff_worst  = t_step / (t_step + t_comm)      (zero overlap)
  eff_best   = t_step / max(t_step, t_comm)    (perfect overlap)
Cross-slice (DCN) terms only enter past one pod slice; v5e slices reach
256 chips on ICI, so the headline range never leaves ICI.

Measured inputs (PERF.md / BENCH_builder_r04.json, v5e single chip):
  t_step = 47.6 ms  (ResNet-50, batch 128/chip, bf16, space-to-depth)
  G      = 25.6M params -> 51.2 MB bf16 on the wire (fp32 would be 102 MB)

Hardware constant (approx., public v5e spec): 1600 Gbit/s ICI per chip
=> B_ici ~= 200 GB/s aggregate; the ring uses it bidirectionally.
"""

import json

T_STEP_S = 0.0476          # measured, v5e batch 128 (PERF.md round 4)
PARAMS = 25.6e6
WIRE_BYTES = PARAMS * 2    # bf16 gradient compression on the wire
B_ICI = 200e9              # ~1600 Gbit/s per v5e chip (approx. public spec)


def model(n: int):
    t_comm = 2 * (n - 1) / n * WIRE_BYTES / B_ICI
    worst = T_STEP_S / (T_STEP_S + t_comm)
    best = T_STEP_S / max(T_STEP_S, t_comm)
    return t_comm, worst, best


def main():
    rows = []
    for n in (1, 8, 32, 64, 256):
        t_comm, worst, best = model(n)
        rows.append({
            "chips": n,
            "t_comm_ms": round(t_comm * 1e3, 3),
            "efficiency_no_overlap": round(worst, 4),
            "efficiency_full_overlap": round(best, 4),
        })
        print(f"n={n:4d}: allreduce {t_comm*1e3:6.3f} ms  "
              f"efficiency {worst:.1%} (no overlap) .. {best:.1%} (full)")
    print()
    worst_comm_ms = max(r["t_comm_ms"] for r in rows)
    print("Even with ZERO compute/comm overlap the model stays above "
          f"{min(r['efficiency_no_overlap'] for r in rows):.1%} — the "
          f"51 MB bf16 gradient ring is ~{worst_comm_ms:.2f} ms against "
          "a 47.6 ms step, so the reference's >=90%-at-256 regime is "
          "bandwidth-trivial for this model on ICI.  The binding risks "
          "are stragglers and input pipeline, not the collective.")
    print(json.dumps({"model": "dp_ring_allreduce", "rows": rows}))


if __name__ == "__main__":
    main()
