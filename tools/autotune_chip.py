#!/usr/bin/env python
"""Score the negotiation autotuner on real hardware (VERDICT r3 item 7).

Reference analog: HOROVOD_AUTOTUNE=1 tuning fusion-threshold/cycle-time
against live training traffic (SURVEY.md §2.1 ParameterManager).  This
drives the eager negotiated path with a ResNet-50-shaped gradient
submission pattern (54 tensors, ~25.6M params, conv kernels to BN
scalars) until the hill climb holds, then reports what the tuner chose
and what it bought vs the starting configuration.

Run on the chip (or anywhere)::

    python tools/autotune_chip.py [--seconds 120] [--log autotune.csv]

The committed chip run lives at docs/autotune_v5e.csv with the finding
in PERF.md ("Round 4: autotune on the chip").
"""

import argparse
import os
import sys
import time


def resnet50_grad_sizes():
    """Parameter-tensor sizes of a bottleneck ResNet-50 (fan-out of the
    per-layer grads DistributedOptimizer would submit), largest-first
    like a backward pass emits them."""
    sizes = []
    stages = [(3, 64), (4, 128), (6, 256), (3, 512)]
    in_ch = 64
    sizes.append(64 * 7 * 7 * 3)  # stem
    for blocks, ch in stages:
        for b in range(blocks):
            sizes.append(in_ch * ch)          # 1x1 reduce
            sizes.append(ch * ch * 9)         # 3x3
            sizes.append(ch * ch * 4)         # 1x1 expand
            if b == 0:
                sizes.append(in_ch * ch * 4)  # projection
            in_ch = ch * 4
    sizes.append(2048 * 1000)  # head
    return sizes


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=120.0)
    ap.add_argument("--log", default="autotune.csv")
    args = ap.parse_args()

    os.environ["HVD_TPU_AUTOTUNE"] = "1"
    os.environ["HVD_TPU_AUTOTUNE_LOG"] = args.log
    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd
    from horovod_tpu.common import basics

    hvd.init()
    ctrl = basics._require_init().controller
    print(f"backend={jax.default_backend()} "
          f"start: threshold={ctrl.fusion_threshold()} "
          f"cycle={ctrl.cycle_time_ms()}ms "
          f"tuning={ctrl.autotune_active()}", flush=True)

    grads = [jnp.ones((n,), jnp.float32) for n in resnet50_grad_sizes()]
    total_mb = sum(g.size for g in grads) * 4 / 1e6
    print(f"{len(grads)} grad tensors, {total_mb:.1f} MB/step", flush=True)

    t0 = time.time()
    steps = 0
    step_times = []
    while time.time() - t0 < args.seconds:
        t1 = time.perf_counter()
        # constant names across steps = the DistributedOptimizer pattern,
        # so the ResponseCache bypass engages like real training
        outs = hvd.grouped_allreduce(grads, name="grad")
        jax.block_until_ready(outs)
        step_times.append(time.perf_counter() - t1)
        steps += 1
        if steps % 20 == 0:
            print(f"step {steps}: threshold={ctrl.fusion_threshold()} "
                  f"cycle={ctrl.cycle_time_ms()}ms "
                  f"tuning={ctrl.autotune_active()} "
                  f"last20={sum(step_times[-20:]) / 20 * 1e3:.1f}ms",
                  flush=True)
        if not ctrl.autotune_active() and steps > 20:
            print("tuner holds — converged", flush=True)
            break
    n = len(step_times)
    first = step_times[:max(n // 5, 1)]
    last = step_times[-max(n // 5, 1):]
    print(f"done: {steps} steps in {time.time() - t0:.0f}s; "
          f"final threshold={ctrl.fusion_threshold()} "
          f"cycle={ctrl.cycle_time_ms()}ms; "
          f"first-fifth mean {sum(first) / len(first) * 1e3:.1f}ms "
          f"vs last-fifth {sum(last) / len(last) * 1e3:.1f}ms "
          f"(log: {args.log})", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
