#!/usr/bin/env python
"""Perf probe: honest step timing on the real chip.

Axon caveat: block_until_ready does not synchronize on the remote backend;
only a device->host value fetch does.  So every timing below chains N
dependent steps and fetches the final loss scalar — the same protocol as
bench.py.

Sweeps batch size and input dtype; prints XLA cost-analysis FLOPs so MFU
can be cross-checked against the analytic model-FLOP count.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.models.resnet import ResNet50  # noqa: E402
from horovod_tpu import training  # noqa: E402
from bench import RESNET50_TRAIN_FLOPS_PER_IMG, peak_flops_for_current_gen  # noqa: E402


def run(batch, img_dtype, peak, iters=30, warmup=5):
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    images = jnp.asarray(
        np.random.RandomState(0).randn(batch, 224, 224, 3), dtype=img_dtype
    )
    labels = jnp.asarray(np.random.RandomState(1).randint(0, 1000, size=(batch,)))
    optimizer = optax.sgd(0.1, momentum=0.9)
    state = training.create_train_state(model, optimizer, rng, images[:2])
    state = training.replicate_state(state)
    step = training.data_parallel_train_step(model, optimizer)

    # cost_analysis() is per-device for SPMD-partitioned modules; this
    # probe is a single-chip tool, so require one device for the XLA MFU.
    flops = None
    try:
        step = step.lower(state, images, labels).compile()
        ca = step.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0] if ca else None
        if ca and jax.device_count() == 1:
            flops = float(ca.get("flops", 0)) or None
    except Exception as e:
        print(f"cost_analysis unavailable: {e}", file=sys.stderr)

    for _ in range(warmup):
        state, loss = step(state, images, labels)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = step(state, images, labels)
    float(loss)
    dt = (time.perf_counter() - t0) / iters
    mfu_xla = f"{flops / dt / peak:.3f}" if flops and peak else "n/a"
    mfu_model = (
        f"{batch * RESNET50_TRAIN_FLOPS_PER_IMG / dt / peak:.3f}"
        if peak else "n/a"
    )
    print(
        f"batch={batch:4d} img={img_dtype.__name__:8s} "
        f"step={dt * 1e3:7.2f} ms  {batch / dt:8.0f} img/s  "
        f"xla_flops={flops or 0:.3e}  MFU(xla)={mfu_xla}  MFU(2*MAC)={mfu_model}"
    )
    return dt


def main():
    hvd.init()
    print("backend:", jax.default_backend(), file=sys.stderr)
    peak = peak_flops_for_current_gen()
    if peak is None:
        print("unknown TPU gen: MFU columns disabled", file=sys.stderr)
    run(128, jnp.float32, peak)
    run(128, jnp.bfloat16, peak)
    run(256, jnp.bfloat16, peak)
    run(512, jnp.bfloat16, peak)
    return 0


if __name__ == "__main__":
    sys.exit(main())
