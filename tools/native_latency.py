#!/usr/bin/env python
"""Native-path latency probe: enqueue→result latency of eager collectives.

Run per-rank under the launcher, e.g.:

    tpurun -np 2 python tools/native_latency.py

Measures mean/median/p99 wall latency of a small named allreduce (the
control-plane cost: negotiation cycle + dispatch; the tensor is tiny so
data-plane time is noise).  Compare configs:

    HVD_TPU_CACHE_CAPACITY=0 tpurun -np 2 python tools/native_latency.py
        (every cycle ships full request encodings)
    tpurun -np 2 python tools/native_latency.py
        (steady state ships cache positions — the bit-vector bypass)

Also prints the in-jit path for reference (psum inside a compiled step —
no negotiation at all), the "latency table" of VERDICT r2 item 6.
"""
import os
import statistics
import sys
import time

import jax
import jax.numpy as jnp

import horovod_tpu as hvd


def timeit(fn, iters):
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        lat.append((time.perf_counter() - t0) * 1e3)
    return lat


def main():
    hvd.init()
    iters = int(os.environ.get("LAT_ITERS", "200"))
    x = jnp.ones((16,), jnp.float32)

    # eager path (negotiated, named => cacheable signature)
    def eager():
        out = hvd.allreduce(x, name="lat_probe", op=hvd.Sum)
        jax.block_until_ready(out)

    eager()  # warm: compile + first full negotiation
    lat = timeit(eager, iters)

    # burst: 64 concurrent named submissions per iteration — the gradient
    # bucket pattern where negotiation payload size actually matters
    xs = [jnp.ones((16,), jnp.float32) for _ in range(64)]

    def burst():
        hs = [
            hvd.allreduce_async(a, name=f"lat_burst_{i}", op=hvd.Sum)
            for i, a in enumerate(xs)
        ]
        for h in hs:
            h.wait()

    burst()
    burst_lat = timeit(burst, max(iters // 4, 20))

    # in-jit path: same collective compiled into a program (no controller)
    from horovod_tpu.ops import spmd_ops
    from jax.sharding import PartitionSpec as P

    mesh = hvd.common.basics._require_init().process_set_registry.get(0).mesh
    step = jax.jit(
        jax.shard_map(
            lambda a: spmd_ops.allreduce(a, op=hvd.Sum),
            mesh=mesh, in_specs=P(None), out_specs=P(None), check_vma=False,
        )
    )
    jax.block_until_ready(step(x))
    jit_lat = timeit(lambda: jax.block_until_ready(step(x)), iters)

    if hvd.rank() == 0:
        ctrl = hvd.common.basics._require_init().controller
        cache = os.environ.get("HVD_TPU_CACHE_CAPACITY", "default")
        native = getattr(ctrl, "is_native", False)
        for tag, ls in (("eager", lat), ("burst64", burst_lat),
                        ("in-jit", jit_lat)):
            print(
                f"cache={cache} native={native} path={tag} "
                f"mean={statistics.mean(ls):.3f}ms "
                f"p50={statistics.median(ls):.3f}ms "
                f"p99={sorted(ls)[int(len(ls) * 0.99) - 1]:.3f}ms "
                f"n={len(ls)}"
            )
        if native:
            print(f"cache_hits={ctrl.cache_hits()} "
                  f"cache_misses={ctrl.cache_misses()} "
                  f"last_request_bytes={ctrl.last_request_bytes()}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
