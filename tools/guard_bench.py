#!/usr/bin/env python
"""Integrity-guard benchmark: the ISSUE-14 exactness/overhead bars.

Every leg emits ONE bench-style JSON line on stdout (human summary on
stderr) — the flash_bench/collective_bench contract.  Legs:

  * ``guard_overhead`` — median step wall time of the SAME
    data-parallel train step built guard-off vs guard-on (the on-device
    digest + finite sentinel are the only delta; the cadence host sync
    is amortized by ``HVD_TPU_GUARD_CADENCE``).  The acceptance bar:
    ``overhead_frac <= 0.02`` at the default cadence (CI asserts it).
    CPU-host numbers are interpret-grade for absolute time but the
    RATIO is the claim; the chip leg re-runs when a TPU tunnel returns.
  * ``guard_collectives`` — StableHLO collective inventory (the PR-7
    ``measured_tier_bytes`` idiom's instruction scan) of three
    programs: baseline (guard=False), guard DISABLED via
    ``HVD_TPU_GUARD=0`` (must be the baseline inventory: EXACTLY 0
    added collectives — the acceptance bar), and guard ENABLED (also 0
    added: the digest folds are local; the exchange rides the host
    control plane at cadence).
  * ``guard_oracle`` — the standing exactness discipline: the guarded
    step's state and loss BIT-identical to the unguarded step over
    several steps when no fault fires.

Usage:
  guard_bench.py            # full legs — what the CI guard-smoke job
                            # runs: the overhead ratio is only
                            # meaningful when the step dwarfs timing
                            # noise (~400 ms here vs ~10 ms in smoke)
  guard_bench.py --smoke    # tiny fast pass: oracle + collectives
                            # legs meaningful, overhead_frac is NOT
"""

import argparse
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:  # contract-ok: env -- bootstrap runs before the package's env_int is importable
    _WORLD = max(1, int(os.environ.get("HVD_TPU_BENCH_WORLD", "") or 2))
except ValueError:
    _WORLD = 2
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={_WORLD}"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import training  # noqa: E402
from horovod_tpu.common.retry import env_int  # noqa: E402
from horovod_tpu.models.transformer import (  # noqa: E402
    Transformer, TransformerConfig,
)

ITERS = env_int("HVD_TPU_BENCH_ITERS", 20)
WARMUP = env_int("HVD_TPU_BENCH_WARMUP", 3)

_COLLECTIVE_RE = re.compile(
    r"stablehlo\.(all_reduce|all_gather|reduce_scatter|"
    r"collective_permute|all_to_all)")


def _emit(row):
    print(json.dumps(row), flush=True)


def _say(msg):
    print(f"[guard_bench] {msg}", file=sys.stderr, flush=True)


def _build(smoke):
    cfg = TransformerConfig(
        vocab_size=256,
        num_layers=2 if smoke else 4,
        num_heads=4 if smoke else 8,
        head_dim=16 if smoke else 32,
        max_seq_len=64 if smoke else 128,
        dtype=jnp.float32,
        attention_impl="dot",
        causal=True,
    )
    model = Transformer(cfg)
    batch = 4 if smoke else 16
    rs = np.random.RandomState(0)
    x = rs.randint(1, cfg.vocab_size, size=(batch, cfg.max_seq_len)
                   ).astype(np.int32)
    y = rs.randint(0, cfg.vocab_size, size=(batch, cfg.max_seq_len)
                   ).astype(np.int32)
    opt = optax.adamw(1e-3)
    state = training.replicate_state(training.create_train_state(
        model, opt, jax.random.PRNGKey(0), x[:1]))
    return model, opt, state, x, y


def _loss(logits, labels):
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels).mean()


def _copy(state):
    return jax.tree_util.tree_map(jnp.copy, state)


def _timed_ab(plain, guarded, state, x, y):
    """Median step time of each program, measured in INTERLEAVED A/B
    rounds (one unguarded step, one guarded step, repeat): slow drift
    on a shared/contended box (thermal, noisy neighbors) hits both
    sides of every round equally, so the RATIO — the claim — stays
    stable where back-to-back blocks would alias the drift onto one
    side."""
    sa = _copy(state)
    sb = _copy(state)
    for _ in range(WARMUP):
        sa = plain(sa, x, y)[0]
        sb = guarded(sb, x, y)[0]
    jax.block_until_ready((sa.params, sb.params))
    t_plain, t_guard = [], []
    for _ in range(max(1, ITERS)):
        t0 = time.perf_counter()
        sa = plain(sa, x, y)[0]
        jax.block_until_ready(sa.params)
        t1 = time.perf_counter()
        sb = guarded(sb, x, y)[0]
        jax.block_until_ready(sb.params)
        t2 = time.perf_counter()
        t_plain.append(t1 - t0)
        t_guard.append(t2 - t1)
    return (float(np.median(t_plain) * 1e3),
            float(np.median(t_guard) * 1e3))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU-safe pass (CI)")
    args = ap.parse_args(argv)

    hvd.init()
    model, opt, state, x, y = _build(args.smoke)

    def build_step(guard):
        return training.data_parallel_train_step(
            model, opt, loss_fn=_loss, guard=guard)

    plain = build_step(False)
    guarded = build_step(True)

    # -- guard_oracle: bit-identical state + loss over several steps ---------
    sa, sb = _copy(state), _copy(state)
    bit_exact = True
    for _ in range(3):
        sa, la = plain(sa, x, y)
        sb, lb, _diag = guarded(sb, x, y)
        if float(la) != float(lb):
            bit_exact = False
        for pa, pb in zip(jax.tree_util.tree_leaves(sa.params),
                          jax.tree_util.tree_leaves(sb.params)):
            if not np.array_equal(np.asarray(pa), np.asarray(pb)):
                bit_exact = False
    _emit({"bench": "guard_oracle", "steps": 3, "bit_exact": bit_exact})
    _say(f"oracle bit_exact={bit_exact}")

    # -- guard_collectives: the zero-added-collectives contract --------------
    def inventory(step):
        return len(_COLLECTIVE_RE.findall(
            step.lower(_copy(state), x, y).as_text()))

    n_plain = inventory(plain)
    n_guarded = inventory(guarded)
    # the env-disabled path: guard=None defers to HVD_TPU_GUARD
    os.environ["HVD_TPU_GUARD"] = "0"
    try:
        n_disabled = inventory(build_step(None))
    finally:
        os.environ.pop("HVD_TPU_GUARD", None)
    _emit({
        "bench": "guard_collectives",
        "collectives_baseline": n_plain,
        "collectives_disabled": n_disabled,
        "collectives_guarded": n_guarded,
        "added_collectives_disabled": n_disabled - n_plain,
        "added_collectives_guarded": n_guarded - n_plain,
    })
    _say(f"collectives baseline={n_plain} disabled={n_disabled} "
         f"guarded={n_guarded}")

    # -- guard_overhead ------------------------------------------------------
    ms_plain, ms_guarded = _timed_ab(plain, guarded, state, x, y)
    overhead = (ms_guarded - ms_plain) / ms_plain
    _emit({
        "bench": "guard_overhead",
        "step_ms_unguarded": round(ms_plain, 3),
        "step_ms_guarded": round(ms_guarded, 3),
        "overhead_frac": round(overhead, 4),
        "cadence": env_int("HVD_TPU_GUARD_CADENCE", 16),
        "iters": ITERS, "world": _WORLD,
    })
    _say(f"overhead {overhead * 100:.2f}% "
         f"({ms_plain:.1f} -> {ms_guarded:.1f} ms)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
