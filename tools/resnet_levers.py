#!/usr/bin/env python
"""ResNet-50 perf lever sweep on the chip (VERDICT r3 item 9).

Measures each proposed lever against the round-3 "plateau" (MFU
0.32–0.33 at batch 128, HBM-roofline-bound per PERF.md): batch-size
curve, per-block rematerialization (HBM-for-FLOPs trade), stem choice.
Same timing protocol as bench.py (chained steps, scalar fetch — the
only sync axon honors).

    python tools/resnet_levers.py [--iters 30]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.models.resnet import ResNet50  # noqa: E402
from horovod_tpu import training  # noqa: E402
from bench import peak_flops_for_current_gen  # noqa: E402


def run(batch, stem, remat, peak, iters=30, warmup=5):
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16, stem=stem,
                     remat=remat)
    rng = jax.random.PRNGKey(0)
    images = jnp.asarray(
        np.random.RandomState(0).randn(batch, 224, 224, 3),
        dtype=jnp.float32,
    )
    labels = jnp.asarray(
        np.random.RandomState(1).randint(0, 1000, size=(batch,)))
    optimizer = optax.sgd(0.1, momentum=0.9)
    state = training.create_train_state(model, optimizer, rng, images[:2])
    state = training.replicate_state(state)
    step = training.data_parallel_train_step(model, optimizer)

    flops = bytes_accessed = None
    try:
        step = step.lower(state, images, labels).compile()
        ca = step.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0] if ca else None
        if ca and jax.device_count() == 1:
            flops = float(ca.get("flops", 0)) or None
            bytes_accessed = float(ca.get("bytes accessed", 0)) or None
    except Exception as e:
        print(f"cost_analysis unavailable: {e}", file=sys.stderr)

    for _ in range(warmup):
        state, loss = step(state, images, labels)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = step(state, images, labels)
    final = float(loss)
    dt = (time.perf_counter() - t0) / iters
    assert np.isfinite(final)
    mfu = f"{flops / dt / peak:.4f}" if flops and peak else "n/a"
    gbytes = f"{bytes_accessed / 1e9:6.1f}" if bytes_accessed else "   n/a"
    print(f"batch={batch:4d} stem={stem:14s} remat={int(remat)} "
          f"step={dt * 1e3:7.2f} ms  {batch / dt:7.0f} img/s  "
          f"mfu={mfu}  xla_GB={gbytes}", flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args()
    hvd.init()
    peak = peak_flops_for_current_gen()
    print(f"backend={jax.default_backend()} devices={jax.device_count()} "
          f"peak={peak}", flush=True)
    for batch, stem, remat in [
        (128, "space_to_depth", False),   # round-3/4 bench config
        (128, "space_to_depth", True),    # the HBM-for-FLOPs lever
        (256, "space_to_depth", False),   # the falling curve...
        (256, "space_to_depth", True),    # ...and whether remat fixes it
        (512, "space_to_depth", True),
        (128, "conv", False),             # stem control
    ]:
        run(batch, stem, remat, peak, iters=args.iters)
    return 0


if __name__ == "__main__":
    sys.exit(main())
