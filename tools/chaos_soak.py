#!/usr/bin/env python
"""Chaos soak: prove end-to-end failure recovery under injected faults.

Runs real multi-process elastic training jobs (the same driver + worker
machinery as production ``tpurun``) with ``HVD_TPU_CHAOS`` injecting
faults mid-training, and asserts the jobs complete with EXACT final step
counts — lost or duplicated work is arithmetically visible in the
workers' weight bookkeeping.  Scenarios:

  kill-resume     world of 1 (+1 spare slot); chaos SIGKILLs the worker
                  at commit #K.  The driver blacklists the slot, spawns a
                  replacement, and the replacement — which has no
                  exec-restart snapshot — must auto-resume from the last
                  ``save_state_checkpoint`` and finish with exactly
                  ``batches`` steps.
  corrupt-recover world of 2; chaos flips one bit in a native negotiation
                  frame on rank 1.  The coordinator rejects the MAC, the
                  control plane dies on both ranks, ``commit()``'s
                  liveness poll raises, both workers exec-restart with
                  live snapshots, re-rendezvous, and finish exactly.
  replay          the same HVD_TPU_CHAOS_SEED must reproduce the same
                  injection trace, event for event.
  overhead        chaos OFF must cost one module-bool per injection point
                  (measured and printed; no flaky wall-clock assert).

Local-host note: on machines whose jax cannot run multi-process XLA
collectives on CPU (jax < 0.5), the workers run with
``HVD_TPU_SOAK_LOCAL_SYNC=1`` — the control plane under test
(rendezvous, native negotiation frames + MACs, heartbeats, chaos,
exec-restart, checkpoint auto-resume) is identical; only the cross-worker
state broadcast is skipped.  On a TPU fleet run without it.

Usage: python tools/chaos_soak.py [--batches N] [--seed S]
       [--scenario all|kill-resume|corrupt-recover|replay|overhead]
Exit code 0 = every scenario passed.  Marked `slow` in the test suite
(tests/test_chaos.py wraps it); a full run is a few minutes of real
process churn.
"""

import argparse
import json
import os
import stat
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "integration", "chaos_worker.py")
if REPO not in sys.path:  # `python tools/chaos_soak.py` from anywhere
    sys.path.insert(0, REPO)


def _env(extra=None):
    env = os.environ.copy()
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    env["HVD_TPU_ELASTIC_TIMEOUT"] = "120"
    env["HVD_TPU_SOAK_LOCAL_SYNC"] = "1"
    env.update(extra or {})
    return env


def _discovery(tmp, slots):
    hosts = os.path.join(tmp, "hosts.txt")
    with open(hosts, "w") as f:
        f.write(f"localhost:{slots}\n")
    script = os.path.join(tmp, "discover.sh")
    with open(script, "w") as f:
        f.write(f"#!/bin/sh\ncat {hosts}\n")
    os.chmod(script, os.stat(script).st_mode | stat.S_IEXEC)
    return script


def _read_events(logdir):
    events = []
    for name in sorted(os.listdir(logdir)):
        with open(os.path.join(logdir, name)) as f:
            for line in f:
                ev = json.loads(line)
                ev["worker"] = name
                events.append(ev)
    return events


def _run_job(tmp, *, np_, min_np, max_np, slots, batches, chaos, seed,
             timeout=420):
    logdir = os.path.join(tmp, "logs")
    ckpt = os.path.join(tmp, "ckpt")
    os.makedirs(logdir)
    os.makedirs(ckpt)
    script = _discovery(tmp, slots)
    cmd = [sys.executable, "-m", "horovod_tpu.runner",
           "--host-discovery-script", script,
           "--min-np", str(min_np), "-np", str(np_)]
    if max_np is not None:
        cmd += ["--max-np", str(max_np)]
    cmd += ["--", sys.executable, WORKER, logdir, str(batches), ckpt]
    env = _env({"HVD_TPU_CHAOS": chaos, "HVD_TPU_CHAOS_SEED": str(seed)})
    proc = subprocess.run(cmd, env=env, cwd=REPO, timeout=timeout,
                          capture_output=True, text=True)
    return proc, _read_events(logdir)


def scenario_kill_resume(batches, seed):
    """Worker killed at commit #K; the fresh replacement must resume from
    the checkpoint, not step 0, and finish exactly."""
    kill_at = max(3, batches // 3)
    with tempfile.TemporaryDirectory(prefix="chaos_soak_") as tmp:
        fuse = os.path.join(tmp, "kill.fuse")
        proc, events = _run_job(
            tmp, np_=1, min_np=1, max_np=1, slots=2, batches=batches,
            chaos=f"elastic.commit:kill,at={kill_at},rank=0,fuse={fuse}",
            seed=seed,
        )
        assert proc.returncode == 0, (
            f"job failed rc={proc.returncode}\n{proc.stderr[-4000:]}")
        dones = [e for e in events if e["event"] == "done"]
        assert len(dones) == 1 and abs(dones[0]["weight"] - batches) < 1e-6, \
            f"wrong final count: {dones}"
        assert os.path.exists(fuse), "chaos kill never fired"
        workers = {e["worker"] for e in events if e["event"] == "init"}
        assert len(workers) == 2, f"no replacement spawned: {workers}"
        # the replacement had NO exec-restart snapshot: a boot at step > 0
        # can only come from checkpoint auto-resume
        done_worker = dones[0]["worker"]
        boots = [e for e in events
                 if e["event"] == "boot" and e["worker"] == done_worker]
        assert any(b["step"] >= kill_at - 1 and b["step"] > 0
                   for b in boots), \
            f"replacement did not auto-resume from checkpoint: {boots}"
        return {"kill_at": kill_at, "boots": boots,
                "recovered_steps": dones[0]["step"]}


def scenario_corrupt_recover(batches, seed):
    """One corrupted negotiation frame must fail the control plane
    cleanly on every rank, trigger exec-restart recovery, and still end
    with exact per-worker counts."""
    # enough runway that the failure push reaches every member while it
    # is still committing (recovery propagation is ~0.5 s; see
    # docs/FAULT_TOLERANCE.md on the end-of-job window under jax < 0.5)
    batches = max(batches, 40)
    with tempfile.TemporaryDirectory(prefix="chaos_soak_") as tmp:
        fuse = os.path.join(tmp, "corrupt.fuse")
        proc, events = _run_job(
            tmp, np_=2, min_np=2, max_np=2, slots=2, batches=batches,
            chaos=("transport.frame.send:corrupt,after=150,rank=1,"
                   f"times=1,fuse={fuse}"),
            seed=seed,
        )
        assert proc.returncode == 0, (
            f"job failed rc={proc.returncode}\n{proc.stderr[-4000:]}")
        dones = [e for e in events if e["event"] == "done"]
        assert len(dones) == 2, f"expected 2 finishers: {dones}"
        for d in dones:
            assert abs(d["weight"] - batches) < 1e-6, f"wrong count: {d}"
        assert os.path.exists(fuse), "frame corruption never fired"
        # both workers went through a reset epoch (exec-restart recovery)
        resets = [e for e in events if e["event"] == "reset"]
        assert resets, f"no reset epoch after the corrupted frame: {events}"
        assert "bad MAC" in proc.stderr or "chaos injecting" in \
            proc.stderr, "native chaos left no trace in stderr"
        return {"resets": len(resets)}


def _replay_trace(tmp, tag, seed):
    trace = os.path.join(tmp, f"trace_{tag}.jsonl")
    code = (
        "from horovod_tpu import chaos\n"
        "chaos.install_from_env(rank=0)\n"
        "for _ in range(300):\n"
        "    chaos.point('elastic.commit')\n"
    )
    env = _env({
        "HVD_TPU_CHAOS": "elastic.commit:delay,delay=0,prob=0.1",
        "HVD_TPU_CHAOS_SEED": str(seed),
        "HVD_TPU_CHAOS_LOG": trace,
    })
    subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                   check=True, timeout=120, capture_output=True)
    with open(trace) as f:
        return [json.loads(line) for line in f]


def scenario_replay(seed):
    """Same seed => byte-identical injection trace; different seed =>
    different trace (the draws really are seed-driven)."""
    with tempfile.TemporaryDirectory(prefix="chaos_soak_") as tmp:
        a = _replay_trace(tmp, "a", seed)
        b = _replay_trace(tmp, "b", seed)
        c = _replay_trace(tmp, "c", seed + 1)
        assert a and a == b, "same seed did not replay the same trace"
        assert [e["eval"] for e in a] != [e["eval"] for e in c], \
            "different seeds produced identical traces (seed unused?)"
        return {"fires": len(a)}


def scenario_overhead():
    """Chaos off: point() must be a module-bool check.  Prints the
    measured per-call cost; asserts only the structural property."""
    from horovod_tpu import chaos

    chaos.clear()
    assert not chaos.active
    n = 1_000_000
    t0 = time.perf_counter()
    for _ in range(n):
        if chaos.active:
            chaos.point("training.step")
    per_call_ns = (time.perf_counter() - t0) / n * 1e9
    return {"inactive_point_ns": round(per_call_ns, 1)}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--batches", type=int, default=24)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--scenario", default="all",
                    choices=["all", "kill-resume", "corrupt-recover",
                             "replay", "overhead"])
    args = ap.parse_args(argv)

    runs = {
        "kill-resume": lambda: scenario_kill_resume(args.batches, args.seed),
        "corrupt-recover": lambda: scenario_corrupt_recover(
            args.batches, args.seed),
        "replay": lambda: scenario_replay(args.seed),
        "overhead": scenario_overhead,
    }
    selected = list(runs) if args.scenario == "all" else [args.scenario]
    failed = False
    for name in selected:
        t0 = time.time()
        try:
            detail = runs[name]()
            print(f"[chaos_soak] PASS {name} ({time.time() - t0:.1f}s) "
                  f"{json.dumps(detail)}")
        except (AssertionError, subprocess.TimeoutExpired,
                subprocess.CalledProcessError) as e:
            failed = True
            print(f"[chaos_soak] FAIL {name} ({time.time() - t0:.1f}s): {e}",
                  file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
