#!/usr/bin/env python
"""Chaos soak: prove end-to-end failure recovery under injected faults.

Runs real multi-process elastic training jobs (the same driver + worker
machinery as production ``tpurun``) with ``HVD_TPU_CHAOS`` injecting
faults mid-training, and asserts the jobs complete with EXACT final step
counts — lost or duplicated work is arithmetically visible in the
workers' weight bookkeeping.  Scenarios:

  kill-resume     world of 1 (+1 spare slot); chaos SIGKILLs the worker
                  at commit #K.  The driver blacklists the slot, spawns a
                  replacement, and the replacement — which has no
                  exec-restart snapshot — must auto-resume from the last
                  ``save_state_checkpoint`` and finish with exactly
                  ``batches`` steps.
  corrupt-recover world of 2; chaos flips one bit in a native negotiation
                  frame on rank 1.  The coordinator rejects the MAC, the
                  control plane dies on both ranks, ``commit()``'s
                  liveness poll raises, both workers exec-restart with
                  live snapshots, re-rendezvous, and finish exactly.
  autoscale       world of 2 with spare slots; the fleet autoscaler's
                  timed plan (HVD_TPU_FLEET_PLAN) scales 2 -> peak -> 2
                  through ElasticDriver.request_world_size while chaos
                  SIGKILLs a member mid-run; exact final counts, peak
                  reached, every exec-restart bounded.
  preempt         a chaos kill rule with code=-15 at the fleet.preempt
                  site SIGTERMs rank 1 (a preemption notice); the
                  fleet guard takes a planned snapshot, reports
                  'leaving' and exits 0; the driver books a scale-down
                  (not a failure), the survivor converges exactly, and
                  recovery_seconds{phase="planned"} stays bounded.
  sdc             silent-data-corruption closed loop (guard.py): chaos
                  flips one bit of rank 1's gradient at the guard.grad
                  site (a finite, materially wrong value no crash or
                  MAC can see).  Within one HVD_TPU_GUARD_CADENCE the
                  cross-rank digest exchange detects the mismatch, the
                  redundant-recompute vote attributes RANK 1 (not rank
                  0), rank 1 reports the integrity failure and
                  quarantines (its HOST leaves the driver's spawn
                  pool), and the survivor rolls back to the last
                  VERIFIED checkpoint — discarding the poisoned-window
                  checkpoints — then re-runs to the exact final count
                  with bounded recovery_seconds{phase="rollback"}.
  serve-recover   crash-surviving SERVING requests (docs/SERVING.md
                  fault tolerance): a 3-replica fleet router under a
                  templated request load loses one replica mid-burst
                  (chaos raise at serve.replica_step with
                  HVD_TPU_FLEET_REPLICA_ERRORS=1).  The router dumps a
                  replica_loss flight bundle, re-disperses the
                  victim's in-flight work — warm KV migration where
                  verified blocks exist, cold re-prefill otherwise —
                  and every request must complete with output
                  BIT-IDENTICAL to an unkilled control run: zero lost
                  requests, zero duplicated emissions, zero
                  post-warmup compiles on the survivors.
  replay          the same HVD_TPU_CHAOS_SEED must reproduce the same
                  injection trace, event for event.
  overhead        chaos OFF must cost one module-bool per injection point
                  (measured and printed; no flaky wall-clock assert).

Local-host note: on machines whose jax cannot run multi-process XLA
collectives on CPU (jax < 0.5), the workers run with
``HVD_TPU_SOAK_LOCAL_SYNC=1`` — the control plane under test
(rendezvous, native negotiation frames + MACs, heartbeats, chaos,
exec-restart, checkpoint auto-resume) is identical; only the cross-worker
state broadcast is skipped.  On a TPU fleet run without it.

Usage: python tools/chaos_soak.py [--batches N] [--seed S]
       [--serve-requests N]
       [--scenario all|kill-resume|corrupt-recover|autoscale|preempt
                  |sdc|serve-recover|replay|overhead]
Exit code 0 = every scenario passed.  Marked `slow` in the test suite
(tests/test_chaos.py wraps it); a full run is a few minutes of real
process churn.
"""

import argparse
import json
import os
import stat
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "integration", "chaos_worker.py")
if REPO not in sys.path:  # `python tools/chaos_soak.py` from anywhere
    sys.path.insert(0, REPO)


def _env(extra=None):
    env = os.environ.copy()
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    env["HVD_TPU_ELASTIC_TIMEOUT"] = "120"
    env["HVD_TPU_SOAK_LOCAL_SYNC"] = "1"
    env.update(extra or {})
    return env


def _discovery(tmp, slots, hosts_lines=None):
    hosts = os.path.join(tmp, "hosts.txt")
    with open(hosts, "w") as f:
        if hosts_lines is not None:
            f.write("".join(line + "\n" for line in hosts_lines))
        else:
            f.write(f"localhost:{slots}\n")
    script = os.path.join(tmp, "discover.sh")
    with open(script, "w") as f:
        f.write(f"#!/bin/sh\ncat {hosts}\n")
    os.chmod(script, os.stat(script).st_mode | stat.S_IEXEC)
    return script


def _read_events(logdir):
    events = []
    for name in sorted(os.listdir(logdir)):
        if not (name.startswith("worker_") and name.endswith(".log")):
            continue  # per-rank trace dumps share the directory
        with open(os.path.join(logdir, name)) as f:
            for line in f:
                ev = json.loads(line)
                ev["worker"] = name
                events.append(ev)
    return events


def _run_job(tmp, *, np_, min_np, max_np, slots, batches, chaos, seed,
             timeout=420, extra_env=None, hosts_lines=None):
    logdir = os.path.join(tmp, "logs")
    ckpt = os.path.join(tmp, "ckpt")
    os.makedirs(logdir)
    os.makedirs(ckpt)
    script = _discovery(tmp, slots, hosts_lines)
    cmd = [sys.executable, "-m", "horovod_tpu.runner",
           "--host-discovery-script", script,
           "--min-np", str(min_np), "-np", str(np_)]
    if max_np is not None:
        cmd += ["--max-np", str(max_np)]
    cmd += ["--", sys.executable, WORKER, logdir, str(batches), ckpt]
    env = _env({"HVD_TPU_CHAOS": chaos, "HVD_TPU_CHAOS_SEED": str(seed),
                **(extra_env or {})})
    proc = subprocess.run(cmd, env=env, cwd=REPO, timeout=timeout,
                          capture_output=True, text=True)
    return proc, _read_events(logdir)


def _read_bundles(bdir, reason):
    """Flight-recorder bundles of one trigger reason (may import the
    package: the soak driver already does for other scenarios)."""
    from horovod_tpu.trace.flight import read_bundle

    if not os.path.isdir(bdir):
        return []
    return [read_bundle(os.path.join(bdir, n))
            for n in sorted(os.listdir(bdir))
            if n.startswith(f"bundle-{reason}-")]


def _bundle_sites(bundle):
    return [(e["name"], (e.get("args") or {}).get("site"))
            for e in bundle["trace"]["traceEvents"]
            if e.get("ph") in ("X", "i")]


def scenario_kill_resume(batches, seed):
    """Worker killed at commit #K; the fresh replacement must resume from
    the checkpoint, not step 0, and finish exactly.  The dying worker's
    flight recorder must leave a crash bundle carrying its final spans —
    including the injected chaos event (the ISSUE-15 black-box drill)."""
    kill_at = max(3, batches // 3)
    with tempfile.TemporaryDirectory(prefix="chaos_soak_") as tmp:
        fuse = os.path.join(tmp, "kill.fuse")
        bdir = os.path.join(tmp, "bundles")
        proc, events = _run_job(
            tmp, np_=1, min_np=1, max_np=1, slots=2, batches=batches,
            chaos=f"elastic.commit:kill,at={kill_at},rank=0,fuse={fuse}",
            seed=seed,
            extra_env={"HVD_TPU_TRACE_BUNDLE_DIR": bdir},
        )
        assert proc.returncode == 0, (
            f"job failed rc={proc.returncode}\n{proc.stderr[-4000:]}")
        dones = [e for e in events if e["event"] == "done"]
        assert len(dones) == 1 and abs(dones[0]["weight"] - batches) < 1e-6, \
            f"wrong final count: {dones}"
        assert os.path.exists(fuse), "chaos kill never fired"
        workers = {e["worker"] for e in events if e["event"] == "init"}
        assert len(workers) == 2, f"no replacement spawned: {workers}"
        # the replacement had NO exec-restart snapshot: a boot at step > 0
        # can only come from checkpoint auto-resume
        done_worker = dones[0]["worker"]
        boots = [e for e in events
                 if e["event"] == "boot" and e["worker"] == done_worker]
        assert any(b["step"] >= kill_at - 1 and b["step"] > 0
                   for b in boots), \
            f"replacement did not auto-resume from checkpoint: {boots}"
        # flight recorder: the killed worker dumped its black box BEFORE
        # os._exit — final train.step spans + the chaos kill event at
        # the elastic.commit site, attributed to the dying rank
        bundles = _read_bundles(bdir, "chaos_kill")
        assert bundles, f"no chaos_kill crash bundle in {bdir}"
        b = bundles[0]
        assert b["rank"] == 0 and b["extra"]["site"] == "elastic.commit", b
        sites = _bundle_sites(b)
        assert ("chaos.inject", "elastic.commit") in sites, sites
        assert any(name == "train.step" for name, _ in sites), \
            f"bundle carries no final train.step spans: {sites}"
        return {"kill_at": kill_at, "boots": boots,
                "recovered_steps": dones[0]["step"],
                "bundle_events": len(b["trace"]["traceEvents"])}


def scenario_corrupt_recover(batches, seed):
    """One corrupted negotiation frame must fail the control plane
    cleanly on every rank, trigger exec-restart recovery, and still end
    with exact per-worker counts."""
    # enough runway that the failure push reaches every member while it
    # is still committing (recovery propagation is ~0.5 s; see
    # docs/FAULT_TOLERANCE.md on the end-of-job window under jax < 0.5)
    batches = max(batches, 40)
    with tempfile.TemporaryDirectory(prefix="chaos_soak_") as tmp:
        fuse = os.path.join(tmp, "corrupt.fuse")
        proc, events = _run_job(
            tmp, np_=2, min_np=2, max_np=2, slots=2, batches=batches,
            chaos=("transport.frame.send:corrupt,after=150,rank=1,"
                   f"times=1,fuse={fuse}"),
            seed=seed,
        )
        assert proc.returncode == 0, (
            f"job failed rc={proc.returncode}\n{proc.stderr[-4000:]}")
        dones = [e for e in events if e["event"] == "done"]
        assert len(dones) == 2, f"expected 2 finishers: {dones}"
        for d in dones:
            assert abs(d["weight"] - batches) < 1e-6, f"wrong count: {d}"
        assert os.path.exists(fuse), "frame corruption never fired"
        # both workers went through a reset epoch (exec-restart recovery)
        resets = [e for e in events if e["event"] == "reset"]
        assert resets, f"no reset epoch after the corrupted frame: {events}"
        assert "bad MAC" in proc.stderr or "chaos injecting" in \
            proc.stderr, "native chaos left no trace in stderr"
        # cross-rank trace merge (ISSUE-15): both finishers dumped their
        # span rings; the collector must align their train.step clocks
        # and produce one perfetto-loadable timeline with 2 rank lanes
        logdir = os.path.join(tmp, "logs")
        dumps = sorted(os.path.join(logdir, n) for n in os.listdir(logdir)
                       if n.startswith("trace_") and n.endswith(".json"))
        assert len(dumps) == 2, f"expected 2 per-rank trace dumps: {dumps}"
        merged_path = os.path.join(tmp, "merged_trace.json")
        subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "trace_collect.py")]
            + dumps + ["-o", merged_path],
            env=_env(), cwd=REPO, check=True, timeout=120,
            capture_output=True)
        with open(merged_path) as f:
            merged = json.load(f)
        pids = {e["pid"] for e in merged["traceEvents"]}
        assert pids == {0, 1}, f"merged trace missing a rank lane: {pids}"
        for ev in merged["traceEvents"]:
            assert "name" in ev and "ph" in ev, ev
        # step alignment: for steps BOTH ranks recorded, the shifted
        # start deltas must be centred (median ~0 by construction) and
        # bounded — the clocks really were put on one axis
        per_rank = {}
        for ev in merged["traceEvents"]:
            if ev.get("name") == "train.step" and ev.get("ph") == "X":
                step = (ev.get("args") or {}).get("step")
                per_rank.setdefault(ev["pid"], {}).setdefault(
                    step, ev["ts"])
        common = set(per_rank.get(0, {})) & set(per_rank.get(1, {}))
        assert common, "no common train.step anchors across ranks"
        deltas = sorted(abs(per_rank[0][s] - per_rank[1][s])
                        for s in common)
        median_delta_us = deltas[len(deltas) // 2]
        assert median_delta_us < 1e6, (
            f"ranks' steps not aligned after merge: median "
            f"|delta|={median_delta_us}us over {len(common)} steps")
        return {"resets": len(resets), "merged_events":
                len(merged["traceEvents"]),
                "aligned_steps": len(common),
                "median_step_delta_ms": round(median_delta_us / 1e3, 2)}


def scenario_autoscale(batches, seed, peak=4):
    """The PR-13 closed-loop scale drill (docs/FLEET.md): the driver's
    fleet autoscaler runs a timed plan 2 -> peak -> 2 through
    ``request_world_size`` while chaos SIGKILLs one member mid-run
    (blacklist + replacement).  Every resize lands as a planned reset
    epoch at a commit boundary; the final world's members must finish
    with EXACT counts (scale-up members auto-resume from the fleet
    checkpoint, never from step 0) and every exec-restart must stay
    bounded."""
    # the plan spans scale-up at 6 s and scale-down at 18 s of driver
    # time; the workers must still be training after both (plus the
    # injected kill's recovery), so the step count keys off the plan
    batches = max(batches, 560)  # ~28 s of 0.05 s steps
    with tempfile.TemporaryDirectory(prefix="chaos_soak_") as tmp:
        fuse = os.path.join(tmp, "kill.fuse")
        proc, events = _run_job(
            tmp, np_=2, min_np=2, max_np=peak, slots=peak + 1,
            batches=batches,
            chaos=f"elastic.commit:kill,after=60,rank=1,times=1,fuse={fuse}",
            seed=seed, timeout=560,
            extra_env={"HVD_TPU_FLEET_PLAN": f"0:2,6:{peak},18:2"},
        )
        assert proc.returncode == 0, (
            f"job failed rc={proc.returncode}\n{proc.stderr[-4000:]}")
        dones = [e for e in events if e["event"] == "done"]
        assert len(dones) == 2, f"expected the scaled-down world of 2 " \
            f"finishers: {dones}"
        for d in dones:
            assert abs(d["weight"] - batches) < 1e-6, f"wrong count: {d}"
            assert d["world"] == 2, f"final world not 2: {d}"
        peak_seen = max(e["world"] for e in events if e["event"] == "batch")
        assert peak_seen == peak, \
            f"world never reached the plan's peak {peak}: {peak_seen}"
        assert os.path.exists(fuse), "chaos kill never fired"
        # scale-up members had no snapshot: step > 0 at boot is the
        # checkpoint auto-resume (exact counts depend on it)
        boots = [e for e in events if e["event"] == "boot"]
        restarts = [e["restart_total_s"] for e in boots
                    if e.get("restart_total_s")]
        assert all(r < 120.0 for r in restarts), \
            f"unbounded exec-restart: {restarts}"
        return {"peak_world": peak_seen, "finishers": len(dones),
                "exec_restarts": len(restarts),
                "max_restart_s": round(max(restarts), 2) if restarts
                else None}


def scenario_preempt(batches, seed):
    """The preemption path (ISSUE 13 satellite): a chaos ``kill`` rule
    with a NEGATIVE code at the new ``fleet.preempt`` site delivers
    SIGTERM to rank 1 mid-training; the fleet guard takes a bounded
    planned snapshot (HVD_TPU_ELASTIC_PLANNED_SNAPSHOT_SECONDS),
    checkpoints it, reports 'leaving', and exits 0.  The driver books
    a scale-down (slot held, planned reset epoch — NOT a failure, NOT
    job completion), and the survivor converges to the exact count."""
    batches = max(batches, 160)  # ~8 s: the notice lands ~2.5 s in
    with tempfile.TemporaryDirectory(prefix="chaos_soak_") as tmp:
        fuse = os.path.join(tmp, "preempt.fuse")
        proc, events = _run_job(
            tmp, np_=2, min_np=1, max_np=2, slots=2, batches=batches,
            chaos=f"fleet.preempt:kill,code=-15,at=4,rank=1,fuse={fuse}",
            seed=seed,
        )
        assert proc.returncode == 0, (
            f"job failed rc={proc.returncode}\n{proc.stderr[-4000:]}")
        assert os.path.exists(fuse), "chaos preemption never fired"
        leaves = [e for e in events if e["event"] == "leave"]
        assert len(leaves) == 1, f"expected exactly one leave: {leaves}"
        leave = leaves[0]
        # bounded planned recovery: notice -> snapshot -> exit within
        # the snapshot budget (30 s default) + margin — the
        # hvd_tpu_recovery_seconds{phase="planned"} bound
        assert 0 <= leave["planned_s"] < 35.0, leave
        assert leave["snapshot"] in ("live", "commit"), leave
        assert leave["step"] > 0, f"preempted before any progress: {leave}"
        dones = [e for e in events if e["event"] == "done"]
        assert len(dones) == 1, f"expected 1 finisher: {dones}"
        assert abs(dones[0]["weight"] - batches) < 1e-6, dones
        assert dones[0]["world"] == 1, f"survivor world not 1: {dones}"
        # before the notice the world really was 2 (the leave shrank it)
        assert any(e["event"] == "batch" and e["world"] == 2
                   for e in events), "never trained at world 2"
        return {"leave_step": leave["step"],
                "planned_s": round(leave["planned_s"], 2),
                "snapshot": leave["snapshot"]}


def scenario_sdc(batches, seed, cadence=4):
    """The guard.py closed loop (docs/FAULT_TOLERANCE.md, silent
    corruption): detect -> attribute -> quarantine -> roll back ->
    converge, end to end on a real 2-worker elastic job.  The two
    workers sit on DISTINCT host names (localhost / 127.0.0.1 — both
    spawn locally) so the integrity quarantine blacklists only the
    lying rank's host."""
    flip_step = 3 * cadence - 2   # mid-window: detection must wait for
    # the NEXT cadence check, pinning the <= 1 cadence detection bound
    batches = max(batches, flip_step + 4 * cadence)
    with tempfile.TemporaryDirectory(prefix="chaos_soak_") as tmp:
        fuse = os.path.join(tmp, "sdc.fuse")
        board = os.path.join(tmp, "board")
        bdir = os.path.join(tmp, "bundles")
        proc, events = _run_job(
            tmp, np_=2, min_np=1, max_np=2, slots=2, batches=batches,
            hosts_lines=["localhost:1", "127.0.0.1:1"],
            # eval N of guard.grad is the step that becomes N+1
            chaos=(f"guard.grad:flipbit,at={flip_step - 1},rank=1,"
                   f"fuse={fuse}"),
            seed=seed,
            extra_env={"HVD_TPU_GUARD": "1",
                       "HVD_TPU_GUARD_CADENCE": str(cadence),
                       "HVD_TPU_GUARD_BOARD": board,
                       "HVD_TPU_TRACE_BUNDLE_DIR": bdir},
        )
        assert proc.returncode == 0, (
            f"job failed rc={proc.returncode}\n{proc.stderr[-4000:]}")
        assert os.path.exists(fuse), "chaos flipbit never fired"
        # detection: the first bad verdict, within one cadence of the flip
        bad = [e for e in events if e["event"] == "guard" and not e["ok"]]
        assert bad, f"corruption never detected: {events}"
        detect_step = min(e["step"] for e in bad)
        assert flip_step <= detect_step < flip_step + cadence, (
            f"detected at {detect_step}, flipped at {flip_step}, "
            f"cadence {cadence}")
        # attribution: rank 1 (not rank 0), on BOTH ranks' verdicts
        for e in bad:
            assert e["kind"] == "mismatch" and e["attributed"] == [1], e
            assert e["divergent_step"] == flip_step, e
            assert e["self_attributed"] == (e["rank"] == 1), e
        assert {e["rank"] for e in bad} == {0, 1}, bad
        # quarantine: the driver blacklisted rank 1's HOST, and no
        # replacement was ever spawned into it (2 workers total)
        assert "QUARANTINED" in proc.stderr, proc.stderr[-2000:]
        inits = [e for e in events if e["event"] == "init"]
        assert len({e["worker"] for e in inits}) == 2, inits
        # rollback: the survivor restarted WITHOUT its live state and
        # auto-resumed from the last VERIFIED checkpoint (the poisoned
        # window's checkpoints were discarded)
        verified = max(e["verified"] for e in bad if e["rank"] == 0)
        assert verified == ((flip_step - 1) // cadence) * cadence, bad
        done_rollbacks = [e for e in events
                          if e["event"] == "rollback_done"]
        assert done_rollbacks, f"no rollback accounting: {events}"
        assert all(0 <= e["rollback_s"] < 60 for e in done_rollbacks), \
            done_rollbacks
        boots = [e for e in events if e["event"] == "boot"
                 and 0 < e["step"] <= verified]
        assert boots, f"survivor did not resume from the verified " \
            f"checkpoint: {events}"
        # convergence: exactly the surviving world of 1, EXACT count
        dones = [e for e in events if e["event"] == "done"]
        assert len(dones) == 1, f"expected 1 finisher: {dones}"
        assert abs(dones[0]["weight"] - batches) < 1e-6, dones
        assert dones[0]["world"] == 1, dones
        # flight recorder: the QUARANTINED rank (1) dumped its black box
        # before exit 86 — final spans incl. the injected guard.grad
        # flipbit event and the guard exchange that convicted it
        bundles = _read_bundles(bdir, "quarantine")
        assert bundles, f"no quarantine crash bundle in {bdir}"
        qb = [b for b in bundles if b["rank"] == 1]
        assert qb, f"quarantine bundle not from rank 1: " \
            f"{[b['rank'] for b in bundles]}"
        sites = _bundle_sites(qb[0])
        assert ("chaos.inject", "guard.grad") in sites, sites
        assert any(name == "guard.exchange" for name, _ in sites), sites
        assert qb[0]["extra"]["step"] == detect_step, qb[0]["extra"]
        return {"flip_step": flip_step, "detect_step": detect_step,
                "verified_step": verified,
                "rollback_s": round(max(e["rollback_s"]
                                        for e in done_rollbacks), 2),
                "quarantine_bundle_events":
                len(qb[0]["trace"]["traceEvents"])}


def scenario_serve_recover(n_requests, seed):
    """The ISSUE-18 serving drill: kill a serving replica mid-burst and
    prove no request is lost, duplicated, or altered.  Two runs of
    tests/integration/serve_fleet_worker.py on the SAME seeded load:
    a fault-free control, then a chaotic run where the K-th
    serve.replica_step raises (one strike ejects).  The chaotic run's
    streams must be bit-identical to the control's, with >= 1 recorded
    migration, a replica_loss flight bundle, and compile-free
    survivors (recovery re-registers KV pages / re-prefills — it never
    compiles a new program)."""
    worker = os.path.join(REPO, "tests", "integration",
                          "serve_fleet_worker.py")
    # mid-burst: the victim has served ~kill_at steps of a load that is
    # still mostly in flight, so it holds running requests (warm
    # migrations) AND queued ones (cold re-dispatch)
    kill_at = max(24, n_requests // 8)
    with tempfile.TemporaryDirectory(prefix="chaos_soak_") as tmp:
        bdir = os.path.join(tmp, "bundles")
        fuse = os.path.join(tmp, "serve.fuse")
        ctl_path = os.path.join(tmp, "control.json")
        cha_path = os.path.join(tmp, "chaotic.json")
        subprocess.run(
            [sys.executable, worker, ctl_path, str(n_requests), str(seed)],
            env=_env(), cwd=REPO, check=True, timeout=900,
            capture_output=True)
        proc = subprocess.run(
            [sys.executable, worker, cha_path, str(n_requests), str(seed)],
            env=_env({
                "HVD_TPU_CHAOS":
                    f"serve.replica_step:raise,at={kill_at},fuse={fuse}",
                "HVD_TPU_CHAOS_SEED": str(seed),
                "HVD_TPU_FLEET_REPLICA_ERRORS": "1",
                "HVD_TPU_SERVE_SNAPSHOT_STEPS": "8",
                "HVD_TPU_SERVE_HEDGE": "1",
                "HVD_TPU_TRACE_BUNDLE_DIR": bdir,
            }), cwd=REPO, timeout=900, capture_output=True, text=True)
        assert proc.returncode == 0, (
            f"chaotic serve run failed rc={proc.returncode}\n"
            f"{proc.stderr[-4000:]}")
        assert os.path.exists(fuse), "chaos replica loss never fired"
        with open(ctl_path) as f:
            ctl = json.load(f)
        with open(cha_path) as f:
            cha = json.load(f)
        assert ctl["lost"] == [], f"control run lost requests: {ctl['lost']}"
        assert cha["lost"] == [], f"requests lost in recovery: {cha['lost']}"
        assert set(cha["results"]) == set(ctl["results"]), \
            "chaotic run's request ids diverged from control"
        mismatch = [g for g in ctl["results"]
                    if ctl["results"][g] != cha["results"][g]]
        assert not mismatch, (
            f"{len(mismatch)} of {n_requests} streams not bit-identical "
            f"after recovery: {mismatch[:5]}")
        assert cha["replicas_retired"] >= 1, "no replica was ejected"
        assert cha["recovery"], "ejection recorded no migrations"
        assert cha["migration_ms"] > 0, cha["migration_ms"]
        assert ctl["compile_free"] and cha["compile_free"], \
            "recovery compiled a new program post-warmup"
        # the black box: _eject dumps BEFORE touching any state
        bundles = _read_bundles(bdir, "replica_loss")
        assert bundles, f"no replica_loss flight bundle in {bdir}"
        warm = sum(1 for x in cha["recovery"] if x["path"] == "warm")
        return {"requests": n_requests, "kill_at": kill_at,
                "migrations": len(cha["recovery"]), "warm": warm,
                "cold": len(cha["recovery"]) - warm,
                "migration_ms": round(cha["migration_ms"], 2),
                "hedge_rate": round(cha["hedge_rate"], 4),
                "bundle_events":
                len(bundles[0]["trace"]["traceEvents"])}


def _replay_trace(tmp, tag, seed):
    trace = os.path.join(tmp, f"trace_{tag}.jsonl")
    code = (
        "from horovod_tpu import chaos\n"
        "chaos.install_from_env(rank=0)\n"
        "for _ in range(300):\n"
        "    chaos.point('elastic.commit')\n"
    )
    env = _env({
        "HVD_TPU_CHAOS": "elastic.commit:delay,delay=0,prob=0.1",
        "HVD_TPU_CHAOS_SEED": str(seed),
        "HVD_TPU_CHAOS_LOG": trace,
    })
    subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                   check=True, timeout=120, capture_output=True)
    with open(trace) as f:
        return [json.loads(line) for line in f]


def scenario_replay(seed):
    """Same seed => byte-identical injection trace; different seed =>
    different trace (the draws really are seed-driven)."""
    with tempfile.TemporaryDirectory(prefix="chaos_soak_") as tmp:
        a = _replay_trace(tmp, "a", seed)
        b = _replay_trace(tmp, "b", seed)
        c = _replay_trace(tmp, "c", seed + 1)
        assert a and a == b, "same seed did not replay the same trace"
        assert [e["eval"] for e in a] != [e["eval"] for e in c], \
            "different seeds produced identical traces (seed unused?)"
        return {"fires": len(a)}


def scenario_overhead():
    """Chaos off: point() must be a module-bool check.  Prints the
    measured per-call cost; asserts only the structural property."""
    from horovod_tpu import chaos

    chaos.clear()
    assert not chaos.active
    n = 1_000_000
    t0 = time.perf_counter()
    for _ in range(n):
        if chaos.active:
            chaos.point("training.step")
    per_call_ns = (time.perf_counter() - t0) / n * 1e9
    return {"inactive_point_ns": round(per_call_ns, 1)}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--batches", type=int, default=24)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--scenario", default="all",
                    choices=["all", "kill-resume", "corrupt-recover",
                             "autoscale", "preempt", "sdc",
                             "serve-recover", "replay", "overhead"])
    ap.add_argument("--peak", type=int, default=4,
                    help="autoscale scenario's peak world (CI smoke: 3)")
    ap.add_argument("--serve-requests", type=int, default=512,
                    help="serve-recover scenario's request count "
                         "(CI smoke: 96)")
    args = ap.parse_args(argv)

    runs = {
        "kill-resume": lambda: scenario_kill_resume(args.batches, args.seed),
        "corrupt-recover": lambda: scenario_corrupt_recover(
            args.batches, args.seed),
        "autoscale": lambda: scenario_autoscale(args.batches, args.seed,
                                                peak=args.peak),
        "preempt": lambda: scenario_preempt(args.batches, args.seed),
        "sdc": lambda: scenario_sdc(args.batches, args.seed),
        "serve-recover": lambda: scenario_serve_recover(
            args.serve_requests, args.seed),
        "replay": lambda: scenario_replay(args.seed),
        "overhead": scenario_overhead,
    }
    selected = list(runs) if args.scenario == "all" else [args.scenario]
    failed = False
    for name in selected:
        t0 = time.time()
        try:
            detail = runs[name]()
            print(f"[chaos_soak] PASS {name} ({time.time() - t0:.1f}s) "
                  f"{json.dumps(detail)}")
        except (AssertionError, subprocess.TimeoutExpired,
                subprocess.CalledProcessError) as e:
            failed = True
            print(f"[chaos_soak] FAIL {name} ({time.time() - t0:.1f}s): {e}",
                  file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
