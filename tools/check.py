#!/usr/bin/env python3
"""Standalone launcher for the horovod_tpu contract checker.

Loads ``horovod_tpu.analysis`` WITHOUT executing the package's
``__init__`` (which imports jax) by pre-registering a stub parent
package — so this runs on a bare CI box with nothing installed, in
seconds (the jax-gated ``programs`` pass reports empty here; run it
via tools/verify_programs.py)::

    python tools/check.py              # all eight passes (7 live bare-box)
    python tools/check.py env chaos    # a subset
    python tools/check.py --list-c-symbols   # for rebuild_native.sh

Exit status: 0 clean, 1 findings, 2 usage error.
See docs/ANALYSIS.md for what the passes check and how to suppress a
finding.
"""

import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_analysis():
    if "horovod_tpu" not in sys.modules:
        stub = types.ModuleType("horovod_tpu")
        stub.__path__ = [os.path.join(REPO, "horovod_tpu")]
        sys.modules["horovod_tpu"] = stub
    import importlib

    return importlib.import_module("horovod_tpu.analysis")


if __name__ == "__main__":
    analysis = _load_analysis()
    argv = sys.argv[1:]
    if not any(a.startswith("--root") for a in argv):
        argv = ["--root", REPO] + argv
    sys.exit(analysis.main(argv))
