#!/usr/bin/env python
"""Program-contract verifier launcher (the ``programs`` analysis pass).

Lowers the canonical program menu — serving decode/mixed/speculative
tiers at shard counts 1 and 2, guarded + overlapped + ZeRO train
steps, the hierarchical allreduce — and machine-checks the invariants
docs promise in prose (see ``horovod_tpu/analysis/programs.py``):

* guard/trace no-op paths lower BYTE-identical; guard on adds 0
  collectives (plain AND ZeRO steps)
* no serving-step collective's replica group spans >1 slice (the
  DCN-exclusion contract of docs/SERVING.md)
* ``ops/comm_model`` modeled bytes == the lowered inventory, per tier
  program and for the hierarchical allreduce
* every program key dispatched under a randomized request load is in
  the warmup menu (the zero-recompile lint)

This needs jax (CPU is fine — it reads StableHLO, not wall clocks), so
it is a SEPARATE front door from ``tools/check.py``: the bare-box lint
stays <10s while this runs as its own CI job on 8 virtual devices.

Usage:
  tools/verify_programs.py                  # full run (CI program-verify)
  tools/verify_programs.py --requests 64    # faster local iteration
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_WORLD = 8
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={_WORLD}"
    ).strip()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--shards", default="1,2",
                    help="comma list of serving shard counts (default 1,2)")
    ap.add_argument("--requests", type=int, default=512,
                    help="randomized load size for the zero-recompile "
                    "lint (default 512)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    from horovod_tpu.analysis import programs
    from horovod_tpu.analysis._common import Suppressions

    t0 = time.perf_counter()
    shards = tuple(int(s) for s in args.shards.split(",") if s)
    findings = programs.verify(shards=shards, requests=args.requests,
                               seed=args.seed)
    findings = Suppressions(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ).filter(findings)
    for f in findings:
        print(f.render())
    dt = time.perf_counter() - t0
    verdict = (f"{len(findings)} finding(s)" if findings
               else "all program contracts hold")
    print(f"verify_programs: {verdict} ({dt:.1f} s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
