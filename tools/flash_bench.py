#!/usr/bin/env python
"""Flash-attention kernel benchmark: pallas vs the XLA dense attention
(materialized S x S logits), plus GQA-ratio and window-sweep legs.

Every leg emits ONE bench-style JSON line on stdout (human summary on
stderr) so the numbers are regression-trackable round over round.  The
GQA legs carry a MODELED attention-bytes column — the HBM traffic the
kernel's BlockSpecs imply (K/V fetched once per KV head, Q/out once per
query head) — so the ``num_heads/num_kv_heads`` K/V-read reduction is
pinned even on a CPU box where wall-clock runs in interpret mode; chip
legs re-run when a TPU tunnel is attached.  The window legs carry the
modeled-FLOPs column from the same block-skip bounds the kernels use
(``kb_bounds`` mirrors ``ops.flash_attention._kb_range`` and is
property-tested against it in tests/test_gqa_flash.py).

Timing uses chained iterations with a scalar fetch as the sync (axon
contract, see PERF.md).  ``HVD_TPU_BENCH_ITERS`` / ``HVD_TPU_BENCH_WARMUP``
override the iteration counts (docs/running.md).

Usage:
  flash_bench.py                 # chip kernel legs (dense vs flash)
  flash_bench.py --gqa           # GQA ratio sweep (1/2/4/8)
  flash_bench.py --window        # window sweep at fixed S
  flash_bench.py --smoke         # tiny interpret-mode pass of all legs
                                 #  (CI: runs on the CPU workflow)
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from horovod_tpu.common.retry import env_int  # noqa: E402
from horovod_tpu.models.transformer import causal_dot_attention  # noqa: E402
from horovod_tpu.ops.flash_attention import (  # noqa: E402
    _clamp_blocks, flash_attention,
)


# -- traffic / FLOP models ---------------------------------------------------
#
# _clamp_blocks is the KERNEL's clamp (imported, not mirrored), so the
# modeled columns track exactly the tiling the kernels execute.


def _pad(s, m):
    return s + (-s) % m


def kb_bounds(q_off, block_q, block_k, padded_kb, causal, window, kv_off=0):
    """Pure-python mirror of ``ops.flash_attention._kb_range``: [lo, hi)
    K-block loop bounds for one Q block (the windowed/causal block skip).
    Property-tested against the kernel's version, so the modeled columns
    below track exactly what the kernels execute."""
    hi = padded_kb
    if causal:
        hi = min(hi, (q_off + block_q - 1 - kv_off) // block_k + 1)
    elif window is not None:
        hi = min(
            hi, (q_off + block_q - 1 + window - 1 - kv_off) // block_k + 1)
    if window is None:
        lo = 0
    else:
        lo = max(0, (q_off - (window - 1) - kv_off) // block_k)
    return lo, max(hi, 0)


def _kv_tiles(s, causal, window, block_q, block_k):
    """Total (Q block, K block) tile pairs the forward kernel visits."""
    bq, bk = _clamp_blocks(s, block_q, block_k)
    sq, sk = _pad(s, bq), _pad(s, bk)
    tiles = 0
    for qi in range(sq // bq):
        lo, hi = kb_bounds(qi * bq, bq, bk, sk // bk, causal, window)
        tiles += max(0, hi - lo)
    return tiles


def modeled_attention_bytes(b, s, h, h_kv, d,
                            block_q=256, block_k=256, dtype_bytes=2):
    """Modeled HBM bytes of ONE flash forward: Q and out stream once per
    query head, K/V once per KV head (the GQA BlockSpec sharing), lse is
    one f32 per row.  Returns a dict with the K/V component split out —
    that component is what shrinks by num_heads/num_kv_heads.
    Deliberately window-independent: the kernel streams the whole K/V
    extent per program (the window's block-skip saves COMPUTE, not
    bytes — see modeled_attention_flops)."""
    bq, bk = _clamp_blocks(s, block_q, block_k)
    sq, sk = _pad(s, bq), _pad(s, bk)
    q_bytes = b * h * sq * d * dtype_bytes
    kv_bytes = 2 * b * h_kv * sk * d * dtype_bytes
    out_bytes = b * h * sq * d * dtype_bytes + b * h * sq * 4
    return {
        "q_bytes": q_bytes,
        "kv_bytes": kv_bytes,
        "out_bytes": out_bytes,
        "total_bytes": q_bytes + kv_bytes + out_bytes,
    }


def modeled_repeat_baseline_bytes(b, s, h, h_kv, d,
                                  block_q=256, block_k=256, dtype_bytes=2):
    """The pre-GQA-native baseline: repeat K/V to full heads (read H_kv
    heads, write H heads), then run the MHA kernel (which reads the
    repeated H heads)."""
    m = modeled_attention_bytes(b, s, h, h, d, block_q, block_k,
                                dtype_bytes)
    bq, bk = _clamp_blocks(s, block_q, block_k)
    sk = _pad(s, bk)
    # repeat(1) is a no-op — the MHA "baseline" pays no extra IO
    repeat_io = (0 if h == h_kv
                 else 2 * b * (h_kv + h) * sk * d * dtype_bytes)
    return {**m, "repeat_io_bytes": repeat_io,
            "total_bytes": m["total_bytes"] + repeat_io}


def modeled_attention_flops(b, s, h, d, causal=True, window=None,
                            block_q=256, block_k=256):
    """MXU FLOPs of one flash forward from the block-skip bounds: two
    (bq x d) @ (d x bk) matmuls per visited tile."""
    bq, bk = _clamp_blocks(s, block_q, block_k)
    tiles = _kv_tiles(s, causal, window, block_q, block_k)
    return 4 * b * h * bq * bk * d * tiles


# -- timing ------------------------------------------------------------------


def bench(fn, q, k, v, iters, warmup):
    out = None
    for _ in range(warmup):
        out = fn(q, k, v)
        q = out  # chain so iterations cannot overlap/elide
    float(jnp.sum(out[0, 0, 0]))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(q, k, v)
        q = out
    float(jnp.sum(out[0, 0, 0]))
    return (time.perf_counter() - t0) / iters * 1e3


def _qkv(b, s, h, h_kv, d, dtype=jnp.bfloat16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    mk = lambda kk, heads: jax.random.normal(
        kk, (b, s, heads, d), jnp.float32).astype(dtype)
    return mk(ks[0], h), mk(ks[1], h_kv), mk(ks[2], h_kv)


def _emit(rec, human):
    rec["backend"] = jax.default_backend()
    print(json.dumps(rec))
    print(human, file=sys.stderr)


# -- legs --------------------------------------------------------------------


def leg_kernel(shapes, iters, warmup, interpret):
    """Dense (materialized logits) vs flash at MHA shapes."""
    dense = jax.jit(causal_dot_attention)
    for (b, s, h, d) in shapes:
        q, k, v = _qkv(b, s, h, h, d)
        t_dense = bench(dense, q, k, v, iters, warmup)
        t_flash = bench(
            lambda a, b_, c: flash_attention(a, b_, c, interpret=interpret),
            q, k, v, iters, warmup,
        )
        flops = 2 * b * h * s * s * d  # two matmuls, halved by causality
        _emit(
            {"bench": "flash_kernel", "b": b, "s": s, "h": h, "d": d,
             "dense_ms": round(t_dense, 3), "flash_ms": round(t_flash, 3),
             "speedup": round(t_dense / t_flash, 3),
             "flash_tflops": round(flops / (t_flash / 1e3) / 1e12, 2)},
            f"B{b} S{s} H{h} D{d}: dense {t_dense:7.2f} ms  "
            f"flash {t_flash:7.2f} ms  speedup {t_dense / t_flash:4.2f}x",
        )


def leg_gqa(b, s, h, d, ratios, iters, warmup, interpret):
    """GQA ratio sweep: kernel-native grouped K/V vs the repeat baseline
    (materialize K/V at full heads, then the MHA kernel)."""
    for ratio in ratios:
        if h % ratio:
            continue
        h_kv = h // ratio
        q, k, v = _qkv(b, s, h, h_kv, d)

        def native(q_, k_, v_):
            return flash_attention(q_, k_, v_, interpret=interpret)

        @jax.jit
        def repeat_baseline(q_, k_, v_):
            k_ = jnp.repeat(k_, ratio, axis=2)
            v_ = jnp.repeat(v_, ratio, axis=2)
            return flash_attention(q_, k_, v_, interpret=interpret)

        t_native = bench(native, q, k, v, iters, warmup)
        t_repeat = bench(repeat_baseline, q, k, v, iters, warmup)
        m = modeled_attention_bytes(b, s, h, h_kv, d)
        m_rep = modeled_repeat_baseline_bytes(b, s, h, h_kv, d)
        _emit(
            {"bench": "flash_gqa", "b": b, "s": s, "h": h, "h_kv": h_kv,
             "d": d, "ratio": ratio,
             "native_ms": round(t_native, 3),
             "repeat_ms": round(t_repeat, 3),
             "kv_bytes": m["kv_bytes"],
             "kv_bytes_repeat": m_rep["kv_bytes"] + m_rep["repeat_io_bytes"],
             "attn_bytes": m["total_bytes"],
             "attn_bytes_repeat": m_rep["total_bytes"],
             "bytes_ratio": round(m_rep["total_bytes"] / m["total_bytes"],
                                  3)},
            f"GQA {h}/{h_kv} (x{ratio}): native {t_native:7.2f} ms  "
            f"repeat {t_repeat:7.2f} ms  "
            f"modeled bytes {m['total_bytes']:.3g} vs "
            f"{m_rep['total_bytes']:.3g}",
        )


def leg_window(b, s, h, d, windows, iters, warmup, interpret,
               block_q=256, block_k=256):
    """Window sweep at fixed S: block-skip compute scaling."""
    full_flops = modeled_attention_flops(b, s, h, d, causal=True,
                                         window=None, block_q=block_q,
                                         block_k=block_k)
    for w in windows:
        q, k, v = _qkv(b, s, h, h, d)
        t = bench(
            lambda a, b_, c: flash_attention(a, b_, c, window=w,
                                             block_q=block_q,
                                             block_k=block_k,
                                             interpret=interpret),
            q, k, v, iters, warmup,
        )
        flops = modeled_attention_flops(b, s, h, d, causal=True, window=w,
                                        block_q=block_q, block_k=block_k)
        _emit(
            {"bench": "flash_window", "b": b, "s": s, "h": h, "d": d,
             "window": w, "ms": round(t, 3), "modeled_flops": flops,
             "flops_frac": round(flops / full_flops, 4)},
            f"window {str(w):>6}: {t:7.2f} ms  "
            f"modeled flops {flops / full_flops:5.1%} of full",
        )


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--gqa", action="store_true")
    ap.add_argument("--window", action="store_true")
    ap.add_argument("--kernel", action="store_true")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny interpret-mode pass of every leg (CI)")
    args = ap.parse_args(argv)

    iters = env_int("HVD_TPU_BENCH_ITERS", 20)
    warmup = env_int("HVD_TPU_BENCH_WARMUP", 3)
    print("backend:", jax.default_backend(), file=sys.stderr)

    if args.smoke:
        # interpret mode, tiny shapes: proves the legs + JSON schema on
        # any box; chip numbers come from the un-smoked legs on TPU
        leg_kernel([(1, 256, 2, 64)], 2, 1, True)
        leg_gqa(1, 256, 4, 64, (1, 2, 4), 2, 1, True)
        leg_window(1, 384, 2, 64, (None, 128), 2, 1, True,
                   block_q=128, block_k=128)
        return 0

    run_all = not (args.gqa or args.window or args.kernel)
    if args.kernel or run_all:
        leg_kernel([(4, 1024, 8, 128), (4, 2048, 8, 128),
                    (2, 4096, 8, 128)], iters, warmup, None)
    if args.gqa or run_all:
        leg_gqa(4, 2048, 8, 128, (1, 2, 4, 8), iters, warmup, None)
    if args.window or run_all:
        leg_window(2, 4096, 8, 128,
                   (None, 2048, 1024, 512, 256), iters, warmup, None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
