#!/usr/bin/env python
"""Flash-attention kernel benchmark on the real chip: pallas vs the XLA
dense attention (materialized S x S logits).  Chained iterations with a
scalar fetch as the sync (axon contract, see PERF.md)."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from horovod_tpu.models.transformer import causal_dot_attention  # noqa: E402
from horovod_tpu.ops.flash_attention import flash_attention  # noqa: E402


def bench(fn, q, k, v, iters=20, warmup=3):
    out = None
    for _ in range(warmup):
        out = fn(q, k, v)
        q = out  # chain so iterations cannot overlap/elide
    float(jnp.sum(out[0, 0, 0]))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(q, k, v)
        q = out
    float(jnp.sum(out[0, 0, 0]))
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    print("backend:", jax.default_backend(), file=sys.stderr)
    dense = jax.jit(causal_dot_attention)
    for (b, s, h, d) in [(4, 1024, 8, 128), (4, 2048, 8, 128),
                         (2, 4096, 8, 128)]:
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (
            jax.random.normal(kk, (b, s, h, d), jnp.float32)
            .astype(jnp.bfloat16) for kk in ks
        )
        t_dense = bench(dense, q, k, v)
        t_flash = bench(
            lambda a, b_, c: flash_attention(a, b_, c, block_q=256,
                                             block_k=256),
            q, k, v,
        )
        # causal attention FLOPs: ~0.5 * 2 * 2 * B*H*S^2*D (QK^T + PV)
        flops = 2 * b * h * s * s * d  # two matmuls, halved by causality
        print(
            f"B{b} S{s} H{h} D{d}: dense {t_dense:7.2f} ms  "
            f"flash {t_flash:7.2f} ms  speedup {t_dense / t_flash:4.2f}x  "
            f"flash {flops / (t_flash / 1e3) / 1e12:.1f} TFLOP/s"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
