#!/usr/bin/env python
"""Torch-adapter latency probe: the host-bridge cost, as a recorded number.

The torch adapter round-trips tensor -> numpy -> engine -> numpy -> tensor
on the main thread (VERDICT round 3: "far from the reference's async
device-tensor semantics").  This probe measures what that costs, per op
and per optimizer step, against the JAX-surface numpy path on the same
world — so the bridge overhead is a number in PERF.md, not a guess.

Run single-process (loopback negotiation) or under the launcher:

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/torch_latency.py
    tpurun -np 2 python tools/torch_latency.py

Prints per-path mean/p50/p99 microseconds and the derived bridge overhead.
"""

import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def timed(fn, iters=200, warmup=20):
    for _ in range(warmup):
        fn()
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        samples.append((time.perf_counter() - t0) * 1e6)
    return {
        "mean_us": statistics.fmean(samples),
        "p50_us": statistics.median(samples),
        "p99_us": sorted(samples)[int(len(samples) * 0.99) - 1],
    }


def main():
    import torch

    import horovod_tpu as hvd
    import horovod_tpu.torch as hvd_torch

    hvd.init()
    rank = hvd.rank()

    results = {}
    for numel in (1024, 1 << 20):
        t_np = np.ones(numel, np.float32)
        t_torch = torch.ones(numel, dtype=torch.float32)
        results[f"np_allreduce_{numel}"] = timed(
            lambda: hvd.allreduce(t_np, name=f"probe_np_{numel}"))
        results[f"torch_allreduce_{numel}"] = timed(
            lambda: hvd_torch.allreduce(t_torch, name=f"probe_t_{numel}"))

    # optimizer-step overhead: DistributedOptimizer on a small MLP vs the
    # identical local step (world-of-1: allreduce is identity, so the
    # delta IS the bridge + negotiation cost)
    model = torch.nn.Sequential(
        torch.nn.Linear(64, 256), torch.nn.ReLU(), torch.nn.Linear(256, 10))
    x = torch.randn(32, 64)
    y = torch.randint(0, 10, (32,))
    loss_fn = torch.nn.CrossEntropyLoss()

    def make_step(opt):
        def step():
            opt.zero_grad()
            loss_fn(model(x), y).backward()
            opt.step()
        return step

    local_opt = torch.optim.SGD(model.parameters(), lr=0.0)
    results["torch_local_step"] = timed(make_step(local_opt), iters=100)
    dist_opt = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.0),
        named_parameters=model.named_parameters())
    results["torch_distributed_step"] = timed(make_step(dist_opt), iters=100)

    if rank == 0:
        for name, r in results.items():
            print(f"{name:28s} mean={r['mean_us']:9.1f}us "
                  f"p50={r['p50_us']:9.1f}us p99={r['p99_us']:9.1f}us")
        for numel in (1024, 1 << 20):
            bridge = (results[f"torch_allreduce_{numel}"]["p50_us"]
                      - results[f"np_allreduce_{numel}"]["p50_us"])
            print(f"bridge overhead @ {numel} elems: {bridge:+.1f}us p50")
        step_oh = (results["torch_distributed_step"]["p50_us"]
                   - results["torch_local_step"]["p50_us"])
        print(f"DistributedOptimizer step overhead: {step_oh:+.1f}us p50")


if __name__ == "__main__":
    main()
