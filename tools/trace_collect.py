#!/usr/bin/env python
"""Cross-rank trace collector: merge per-rank Chrome-trace dumps.

The driver-side half of ``horovod_tpu.trace`` (docs/TRACING.md): each
rank exports its span rings — ``GET /trace`` on its metrics endpoint,
``trace.export.write_dump()``, or a flight-recorder bundle's ``trace``
member — and this tool merges them onto ONE timeline with step-boundary
clock alignment (every rank's ``train.step`` spans carry a global
``step`` arg; the median per-step start delta against the first dump is
that rank's clock offset).  The merged file loads in ui.perfetto.dev
with one process lane per rank.

Usage::

    python tools/trace_collect.py rank0.json rank1.json -o merged.json
    python tools/trace_collect.py --bundles /path/to/bundles -o merged.json

Exit 0 on success; the merged JSON also prints a one-line summary to
stderr (ranks, events, offsets).
"""

import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _load(path: str) -> dict:
    """One per-rank dump: a raw Chrome-trace JSON, or a flight bundle
    (checksum-wrapped; its ``trace`` member is the dump)."""
    from horovod_tpu.trace.flight import read_bundle

    try:
        with open(path) as f:
            doc = json.load(f)
    except (UnicodeDecodeError, ValueError):
        doc = read_bundle(path)
    if "traceEvents" in doc:
        return doc
    if "trace" in doc:  # a flight bundle
        inner = doc["trace"]
        inner.setdefault("metadata", {}).setdefault("rank", doc.get("rank", 0))
        return inner
    raise ValueError(f"{path}: neither a trace dump nor a flight bundle")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dumps", nargs="*", help="per-rank trace dumps")
    ap.add_argument("--bundles", default=None,
                    help="directory of flight bundles to merge instead")
    ap.add_argument("-o", "--out", default="merged_trace.json")
    args = ap.parse_args(argv)

    paths = list(args.dumps)
    if args.bundles:
        paths += sorted(glob.glob(os.path.join(args.bundles, "bundle-*.json")))
    if not paths:
        print("nothing to merge (pass dumps or --bundles)", file=sys.stderr)
        return 2

    from horovod_tpu.trace.export import merge_ranks

    merged = merge_ranks([_load(p) for p in paths])
    with open(args.out, "w") as f:
        json.dump(merged, f)
    md = merged["metadata"]
    print(f"[trace_collect] {len(paths)} dump(s) -> {args.out}: "
          f"ranks={md['ranks']} events={len(merged['traceEvents'])} "
          f"offsets_us={md['clock_offsets_us']}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
