#!/usr/bin/env python
"""Transformer training-step benchmark: optimizer sharding (ZeRO vs
replicated) and activation-remat policy legs.

Every leg emits ONE bench-style JSON line on stdout (human summary on
stderr) so the numbers are regression-trackable round over round —
the flash_bench contract.  Two leg families:

  * ``transformer_optim`` — the full data-parallel training step over a
    ``world``-chip mesh with either the replicated optimizer
    (``training.data_parallel_train_step`` + plain AdamW state on every
    rank) or the ZeRO-sharded one (``training.zero_train_setup``:
    reduce-scatter → shard update → allgather).  The
    ``opt_state_bytes_per_rank`` column is MEASURED from the live state
    arrays (sharded leaves divided by world), so the 1/world_size ZeRO
    saving is pinned even on a CPU box where wall-clock is
    interpret-grade; chip wall-clock legs re-run when a TPU tunnel
    returns.
  * ``transformer_remat`` — single-device step time per activation-remat
    policy, with the ``modeled_activation_bytes`` column from
    ``models.transformer.modeled_activation_bytes`` (the capacity
    arithmetic PERF.md round 6 calls "remat territory"; pinned by
    tests/test_remat_policies.py).

``HVD_TPU_BENCH_ITERS`` / ``HVD_TPU_BENCH_WARMUP`` override iteration
counts; ``HVD_TPU_BENCH_WORLD`` sets the mesh width for the optim legs
(CPU boxes get that many virtual host devices; docs/running.md).

Usage:
  transformer_bench.py                  # chip legs: optim pair + remat sweep
  transformer_bench.py --optim zero     # one optimizer leg
  transformer_bench.py --remat none,full,dots,dots_no_batch
  transformer_bench.py --smoke          # tiny CPU-safe pass of all legs (CI)
"""

import argparse
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# the optim legs shard the batch over a mesh: on CPU-only boxes expose
# HVD_TPU_BENCH_WORLD virtual host devices (raw parse: this must run
# BEFORE jax — and therefore the package — can be imported)
try:
    _WORLD = max(1, int(os.environ.get("HVD_TPU_BENCH_WORLD", "") or 8))
except ValueError:
    _WORLD = 8
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={_WORLD}"
    ).strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from horovod_tpu import training  # noqa: E402
from horovod_tpu.common.retry import env_int  # noqa: E402
from horovod_tpu.common.topology import WORLD_AXIS  # noqa: E402
from horovod_tpu.models.transformer import (  # noqa: E402
    Transformer, TransformerConfig, modeled_activation_bytes,
)
from horovod_tpu.optim import (  # noqa: E402
    sharded_state_bytes_per_rank, state_bytes,
)

ITERS = env_int("HVD_TPU_BENCH_ITERS", 20)
WARMUP = env_int("HVD_TPU_BENCH_WARMUP", 3)


def emit(rec, human=""):
    print(json.dumps(rec))
    if human:
        print(human, file=sys.stderr)


def _config(args):
    if args.smoke:
        return dict(vocab_size=256, num_layers=2, num_heads=2, head_dim=16,
                    max_seq_len=64, dtype=jnp.float32), 8, 64
    return dict(vocab_size=32000, num_layers=12, num_heads=12, head_dim=64,
                max_seq_len=args.seq, dtype=jnp.bfloat16), args.batch, args.seq


def _data(batch, seq, vocab):
    rs = np.random.RandomState(0)
    tok = jnp.asarray(rs.randint(0, vocab, (batch, seq)))
    tgt = jnp.asarray(rs.randint(0, vocab, (batch, seq)))
    return tok, tgt


def _timed(step_once, iters, warmup):
    """Chained iterations with a scalar fetch as the sync (axon
    contract, PERF.md)."""
    loss = None
    for _ in range(warmup):
        loss = step_once()
    if loss is not None:  # warmup=0: nothing to sync yet
        float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step_once()
    final = float(loss)
    dt = (time.perf_counter() - t0) / iters
    assert np.isfinite(final), f"non-finite loss {final}"
    return dt


def run_optim_leg(kind, args, remat="none"):
    cfg_kw, batch, seq = _config(args)
    world = min(_WORLD, jax.device_count())
    batch = max(batch, world)
    batch -= batch % world  # P(axis) batch sharding needs divisibility
    cfg = TransformerConfig(remat_policy=remat, **cfg_kw)
    model = Transformer(cfg)
    mesh = Mesh(np.array(jax.devices()[:world]), (WORLD_AXIS,))
    tok, tgt = _data(batch, seq, cfg.vocab_size)
    inner = optax.adamw(1e-3)
    rng = jax.random.PRNGKey(0)

    if kind == "zero":
        state, step, ospecs = training.zero_train_setup(
            model, inner, rng, tok[:1], mesh=mesh)
        opt_bytes = sharded_state_bytes_per_rank(
            state.opt_state, ospecs, world)
    else:
        state = training.create_train_state(model, inner, rng, tok[:1])
        step = training.data_parallel_train_step(model, inner, mesh=mesh)
        opt_bytes = state_bytes(state.opt_state)

    box = {"state": state}

    def once():
        box["state"], loss = step(box["state"], tok, tgt)
        return loss

    dt = _timed(once, ITERS, WARMUP)
    n_params = sum(
        x.size for x in jax.tree_util.tree_leaves(box["state"].params))
    rec = {
        "bench": "transformer_optim",
        "optim": kind,
        "world": world,
        "batch": batch,
        "seq": seq,
        "remat": remat,
        "step_ms": round(dt * 1e3, 2),
        "tokens_per_s": round(batch * seq / dt, 1),
        "params": int(n_params),
        "opt_state_bytes_per_rank": int(opt_bytes),
        # per-rank, like the opt-state column: the step shards the
        # global batch over the world axis
        "modeled_activation_bytes": int(
            modeled_activation_bytes(cfg, batch // world)["total_bytes"]),
        "backend": jax.default_backend(),
    }
    emit(rec, f"[optim] {kind:10s} world {world}: step {dt*1e3:8.1f} ms  "
              f"opt state/rank {opt_bytes/1e6:.2f} MB")
    return rec


def run_remat_leg(policy, args):
    cfg_kw, batch, seq = _config(args)
    cfg = TransformerConfig(remat_policy=policy, **cfg_kw)
    model = Transformer(cfg)
    tok, tgt = _data(batch, seq, cfg.vocab_size)
    variables = model.init(jax.random.PRNGKey(0), tok[:1])
    opt = optax.adamw(1e-3)

    @jax.jit
    def step(params, opt_state, tok, tgt):
        def loss_fn(p):
            logits = model.apply({"params": p}, tok)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt).mean()
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    box = {"p": variables["params"], "o": opt.init(variables["params"])}

    def once():
        box["p"], box["o"], loss = step(box["p"], box["o"], tok, tgt)
        return loss

    dt = _timed(once, ITERS, WARMUP)
    modeled = modeled_activation_bytes(cfg, batch)
    none_cfg = TransformerConfig(remat_policy="none", **cfg_kw)
    rec = {
        "bench": "transformer_remat",
        "policy": policy,
        "batch": batch,
        "seq": seq,
        "step_ms": round(dt * 1e3, 2),
        "tokens_per_s": round(batch * seq / dt, 1),
        "modeled_activation_bytes": int(modeled["total_bytes"]),
        "modeled_activation_bytes_none": int(
            modeled_activation_bytes(none_cfg, batch)["total_bytes"]),
        "backend": jax.default_backend(),
    }
    emit(rec, f"[remat] {policy:14s}: step {dt*1e3:8.1f} ms  "
              f"modeled act {modeled['total_bytes']/1e6:.1f} MB")
    return rec


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--optim", choices=["zero", "replicated", "both"],
                   default=None, help="optimizer-sharding legs")
    p.add_argument("--remat", default=None,
                   help="comma list of remat policies to sweep")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--smoke", action="store_true",
                   help="tiny CPU-safe pass of all legs (CI)")
    args = p.parse_args()
    print("backend:", jax.default_backend(), file=sys.stderr)

    failed = False
    def leg(fn, *leg_args):
        # one OOM/compile-failure leg must not kill the sweep — the
        # remaining legs (e.g. the remat policy that DOES fit) still
        # emit their regression-tracked JSON lines
        nonlocal failed
        label = f"{fn.__name__}:{leg_args[0]}"
        try:
            fn(*leg_args)
        except Exception as e:
            if "Ran out of memory" in str(e) or "RESOURCE_EXHAUSTED" in str(e):
                print(f"[{label}] OOM (hbm exceeded)", file=sys.stderr)
            else:
                traceback.print_exc()
                print(f"[{label}] FAILED ({type(e).__name__})",
                      file=sys.stderr)
                failed = True
    if args.optim or args.smoke or (args.remat is None):
        kinds = (["zero", "replicated"]
                 if args.optim in (None, "both") else [args.optim])
        for kind in kinds:
            leg(run_optim_leg, kind, args)
    if args.remat or args.smoke or (args.optim is None):
        policies = (args.remat.split(",") if args.remat
                    else ["none", "dots", "dots_no_batch", "full"])
        if args.smoke and not args.remat:
            policies = ["none", "dots_no_batch"]
        for pol in policies:
            leg(run_remat_leg, pol, args)
    if failed:
        sys.exit(1)


if __name__ == "__main__":
    main()
