#!/usr/bin/env python
"""Transformer training-step benchmark on the real chip: flash vs dense
attention end-to-end (GPT-style 138M decoder, bf16, AdamW, S=2048).
MFU uses the standard 6*N*D decoder train-FLOPs convention."""
import sys, time
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np, optax
from horovod_tpu.models.transformer import Transformer, TransformerConfig
from bench import peak_flops_for_current_gen

def run(attention_impl, batch=8, seq=2048, remat=False):
    cfg = TransformerConfig(
        vocab_size=32000, num_layers=12, num_heads=12, head_dim=64,
        max_seq_len=seq, dtype=jnp.bfloat16, attention_impl=attention_impl,
        remat=remat,
    )
    model = Transformer(cfg)
    rs = np.random.RandomState(0)
    tok = jnp.asarray(rs.randint(0, 32000, (batch, seq)))
    tgt = jnp.asarray(rs.randint(0, 32000, (batch, seq)))
    variables = model.init(jax.random.PRNGKey(0), tok[:1])
    opt = optax.adamw(1e-3)
    opt_state = opt.init(variables["params"])
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(variables["params"]))

    @jax.jit
    def step(params, opt_state, tok, tgt):
        def loss_fn(p):
            logits = model.apply({"params": p}, tok)
            return optax.softmax_cross_entropy_with_integer_labels(logits, tgt).mean()
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    params = variables["params"]
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tok, tgt)
    float(loss)
    t0 = time.perf_counter(); n = 10
    for _ in range(n):
        params, opt_state, loss = step(params, opt_state, tok, tgt)
    float(loss)
    dt = (time.perf_counter() - t0) / n
    toks = batch * seq
    flops = 6 * n_params * toks  # standard decoder train FLOPs
    peak = peak_flops_for_current_gen()
    mfu = f"{flops / dt / peak:.3f}" if peak else "n/a (unknown TPU gen)"
    tag = attention_impl + ("+remat" if remat else "")
    print(f"{tag:12s} b{batch:<3d}: step {dt*1e3:7.1f} ms  "
          f"{toks/dt:9.0f} tok/s  MFU(6ND) {mfu}  params {n_params/1e6:.0f}M")

print("backend:", jax.default_backend(), file=sys.stderr)
import traceback
configs = [("dot", 4, False), ("flash", 4, False), ("dot", 8, False),
           ("flash", 8, False), ("flash", 16, False),
           ("flash", 16, True), ("flash", 32, True)]
for impl, batch, remat in configs:
    try:
        run(impl, batch=batch, remat=remat)
    except Exception as e:
        if "Ran out of memory" in str(e):
            print(f"{impl:6s} batch {batch}: OOM (hbm exceeded)")
        else:
            traceback.print_exc()
            print(f"{impl:6s} batch {batch}: FAILED ({type(e).__name__})")
