#!/usr/bin/env python
"""Capture a jax.profiler (XPlane) trace with framework spans in it.

Runs a short burst of negotiated collectives inside a profiler capture
so the resulting trace shows ``hvd_tpu::<name>::ENQUEUE`` /
``hvd_tpu::<op>::XLA_COMM`` spans (utils/profiler.py bridge) next to
XLA's own op activity — the reference's NVTX-next-to-kernels view,
TPU edition (SURVEY.md §5.1).

Usage (single process; works on the virtual CPU mesh or a TPU)::

    python tools/profile_capture.py /tmp/hvd-trace
    tensorboard --logdir /tmp/hvd-trace           # Profile plugin
    # or load plugins/profile/<ts>/<host>.trace.json.gz in
    # ui.perfetto.dev

docs/example_trace.json.gz in the repo is one committed capture from
the 8-device virtual CPU mesh (see PERF.md round 4).
"""

import os
import sys


def main() -> int:
    logdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/hvd-trace"
    if os.environ.get("JAX_PLATFORMS", "") == "":
        # default to the virtual CPU mesh so the tool runs anywhere
        os.environ.setdefault("XLA_FLAGS",
                              "--xla_force_host_platform_device_count=8")
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd

    hvd.init()
    # timeline active => XLA_COMM spans end at data-ready (controller
    # resolve() blocks), giving the capture true collective extents
    hvd.start_timeline(os.path.join("/tmp", "hvd-chrome-timeline.json"))

    x = jnp.arange(1 << 16, dtype=jnp.float32)
    hvd.allreduce(x, name="warmup")  # compile outside the capture

    from horovod_tpu import trace
    from horovod_tpu.trace import export as trace_export

    since = trace.now()
    jax.profiler.start_trace(logdir)
    for i in range(8):
        y = hvd.allreduce(x, name=f"grad_{i % 4}")
    jax.block_until_ready(y)
    # a grouped submission so a fused XLA_COMM span appears too
    hvd.grouped_allreduce([x, x * 2, x * 3], name="bucket")
    jax.profiler.stop_trace()
    hvd.stop_timeline()
    # ONE instrumentation point, two views (docs/TRACING.md): the same
    # collective.enqueue/exec spans that just landed in the XPlane
    # capture also export as standalone Chrome trace-event JSON
    chrome = os.path.join(logdir, "hvd_framework_spans.json")
    trace_export.write_dump(chrome, since=since)
    print(f"trace written under {logdir}/plugins/profile/")
    print(f"framework spans (Chrome trace-event JSON): {chrome}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
