#!/usr/bin/env python
"""Collective-routing benchmark: flat vs hierarchical vs hierarchical +
DCN wire compression, with modeled AND measured per-tier bytes.

Every leg emits ONE bench-style JSON line on stdout (human summary on
stderr) — the flash_bench/transformer_bench contract.  Per leg:

  * ``modeled``  — ``ops.comm_model.modeled_collective_bytes`` (the pure
    ring model docs/COLLECTIVES.md derives);
  * ``measured`` — ``ops.comm_model.measured_tier_bytes`` over the
    lowered StableHLO of the EXACT compiled program: the real collective
    instruction inventory (shapes, wire dtypes, replica groups), each
    group attributed to ICI or DCN by the slice map.  The lowered module
    is read rather than backend-optimized HLO because XLA:CPU legalizes
    16-bit collectives to f32 (TPU executes them natively);
  * ``max_rel_err`` / ``bit_exact`` — the allreduce oracle: leg output
    vs a float64 numpy reduction of the same contributions;
  * ``time_ms`` — wall clock per step (interpret-grade on a CPU box;
    chip numbers re-run when a TPU tunnel returns).

The default configuration IS the MULTICHIP ground-truth topology: an
8-virt-device world split 2 slices x 4 chips (``HVD_TPU_SLICE_SIZE=4``
over virtual CPU devices), the acceptance harness of ISSUE 7 /
ROADMAP item 3.

``HVD_TPU_BENCH_ITERS`` / ``HVD_TPU_BENCH_WARMUP`` override iteration
counts (docs/running.md).

Usage:
  collective_bench.py                      # full sweep, 4 MiB payload
  collective_bench.py --numel 1048576      # payload size (elements)
  collective_bench.py --legs flat,hier_bf16
  collective_bench.py --smoke              # tiny CPU-safe pass (CI)
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# expose the virtual multislice world BEFORE jax can be imported: raw
# parse, same bootstrap as transformer_bench
try:  # contract-ok: env -- bootstrap runs before the package's env_int is importable
    _WORLD = max(2, int(os.environ.get("HVD_TPU_BENCH_WORLD", "") or 8))
except ValueError:
    _WORLD = 8
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={_WORLD}"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.common.retry import env_int  # noqa: E402
from horovod_tpu.common.topology import (  # noqa: E402
    DCN_AXIS, ICI_AXIS, WORLD_AXIS,
)
from horovod_tpu.compression import DcnCompression  # noqa: E402
from horovod_tpu.ops import spmd_ops  # noqa: E402
from horovod_tpu.ops.comm_model import (  # noqa: E402
    measured_tier_bytes, mesh_slice_ids, modeled_collective_bytes,
)

ITERS = env_int("HVD_TPU_BENCH_ITERS", 20)
WARMUP = env_int("HVD_TPU_BENCH_WARMUP", 3)

#: leg -> (hierarchical?, wire dtype or None)
LEGS = {
    "flat": (False, None),
    "hier": (True, None),
    "hier_bf16": (True, "bfloat16"),
    "hier_fp16": (True, "float16"),
}


def emit(rec, human=""):
    print(json.dumps(rec))
    if human:
        print(human, file=sys.stderr)


def _timed(fn, *args):
    out = jax.block_until_ready(fn(*args))
    for _ in range(max(WARMUP - 1, 0)):
        out = jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    iters = max(ITERS, 1)
    for _ in range(iters):
        out = jax.block_until_ready(fn(*args))
    return out, (time.perf_counter() - t0) / iters


def run_leg(leg, x, hmesh, wmesh, slice_ids, n_ici):
    hierarchical, wire = LEGS[leg]
    world = x.shape[0]
    comp = DcnCompression(wire) if wire else None
    if hierarchical:
        fn = jax.jit(jax.shard_map(
            lambda t: spmd_ops.hierarchical_allreduce(
                t, op=hvd.Sum, dcn_compression=comp
            ),
            mesh=hmesh, in_specs=P((DCN_AXIS, ICI_AXIS)),
            out_specs=P((DCN_AXIS, ICI_AXIS)), check_vma=False,
        ))
    else:
        fn = jax.jit(jax.shard_map(
            lambda t: spmd_ops.allreduce(t, op=hvd.Sum),
            mesh=wmesh, in_specs=P(WORLD_AXIS), out_specs=P(WORLD_AXIS),
            check_vma=False,
        ))
    out, step_s = _timed(fn, x)
    ref = np.asarray(x, np.float64).sum(axis=0)
    got = np.asarray(out, np.float64)
    err = np.abs(got - ref[None]).max()
    scale = max(np.abs(ref).max(), 1e-30)
    # hierarchical programs: replica groups use the hmesh's row-major
    # LOGICAL ids (mesh_slice_ids); the flat program runs over the 1-D
    # world mesh where logical order == world order
    measured = measured_tier_bytes(
        fn.lower(x).as_text(),
        mesh_slice_ids(hmesh) if hierarchical else slice_ids,
    )
    if hierarchical:
        n_ici_model = n_ici
    else:
        # flat routing over a slice-spanning world: every ring step's
        # bytes cross a slice-boundary link (n_ici=1 attribution —
        # comm_model's bottleneck-link view, matching measured_tier_bytes'
        # classification of the world-spanning replica group)
        n_ici_model = 1 if len(set(slice_ids)) > 1 else world
    modeled = modeled_collective_bytes(
        x.shape[1:], world, n_ici_model,
        wire_dtype=wire, dtype=str(x.dtype),
    )
    return {
        "bench": "collective",
        "leg": leg,
        "world": world,
        "n_ici": n_ici if hierarchical else world,
        "n_dcn": (world // n_ici) if hierarchical else 1,
        "numel": int(np.prod(x.shape[1:])),
        "dtype": str(x.dtype),
        "wire_dtype": wire,
        "comm_bytes": {
            "ici": modeled["ici_bytes"],
            "dcn": modeled["dcn_bytes"],
            "wire_dtype": modeled["wire_dtype"],
        },
        "measured_bytes": {
            "ici": measured["ici_bytes"],
            "dcn": measured["dcn_bytes"],
        },
        "collective_ops": [
            (o["op"], o["tier"], o["stream_bytes"]) for o in measured["ops"]
        ],
        "time_ms": round(step_s * 1e3, 3),
        "max_rel_err": float(err / scale),
        "bit_exact": bool(err == 0.0),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--legs", default=",".join(LEGS),
                    help=f"comma list of {'/'.join(LEGS)}")
    ap.add_argument("--numel", type=int, default=1 << 20,
                    help="payload elements per contribution")
    ap.add_argument("--slice-size", type=int, default=0,
                    help="chips per slice (default world/2)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU-safe pass of every leg (CI)")
    args = ap.parse_args(argv)

    numel = 4096 if args.smoke else args.numel
    hvd.init()
    world = hvd.size()
    n_ici = args.slice_size or max(world // 2, 1)
    if world % n_ici:
        ap.error(f"--slice-size {n_ici} does not divide world {world}")
    os.environ["HVD_TPU_SLICE_SIZE"] = str(n_ici)
    from horovod_tpu.common import basics
    topo = basics._require_init().topology
    slice_ids = topo.slice_ids()
    hmesh = topo.hierarchical_mesh()
    wmesh = hvd.world_mesh()

    # dyadic-friendly contributions: distinct per chip, exactly
    # representable so the fp32 Sum oracle can be bit-checked
    rs = np.random.RandomState(0)
    x = jnp.asarray(
        np.round(rs.randn(world, numel) * 8) / 8
    ).astype(jnp.float32)

    failed = False
    for leg in args.legs.split(","):
        leg = leg.strip()
        if leg not in LEGS:
            ap.error(f"unknown leg {leg!r}")
        try:
            rec = run_leg(leg, x, hmesh, wmesh, slice_ids, n_ici)
        except Exception as e:  # noqa: BLE001 - isolate legs, report at exit
            print(f"[collective_bench] leg {leg} FAILED: {e}",
                  file=sys.stderr)
            failed = True
            continue
        emit(rec, (
            f"[collective_bench] {leg:>10}: modeled dcn "
            f"{rec['comm_bytes']['dcn']}B measured dcn "
            f"{rec['measured_bytes']['dcn']}B ici "
            f"{rec['measured_bytes']['ici']}B "
            f"{rec['time_ms']}ms rel_err {rec['max_rel_err']:.2e}"
        ))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
