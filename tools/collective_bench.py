#!/usr/bin/env python
"""Collective-routing benchmark: flat vs hierarchical vs hierarchical +
DCN wire compression, with modeled AND measured per-tier bytes.

Every leg emits ONE bench-style JSON line on stdout (human summary on
stderr) — the flash_bench/transformer_bench contract.  Per leg:

  * ``modeled``  — ``ops.comm_model.modeled_collective_bytes`` (the pure
    ring model docs/COLLECTIVES.md derives);
  * ``measured`` — ``ops.comm_model.measured_tier_bytes`` over the
    lowered StableHLO of the EXACT compiled program: the real collective
    instruction inventory (shapes, wire dtypes, replica groups), each
    group attributed to ICI or DCN by the slice map.  The lowered module
    is read rather than backend-optimized HLO because XLA:CPU legalizes
    16-bit collectives to f32 (TPU executes them natively);
  * ``max_rel_err`` / ``bit_exact`` — the allreduce oracle: leg output
    vs a float64 numpy reduction of the same contributions;
  * ``time_ms`` — wall clock per step (interpret-grade on a CPU box;
    chip numbers re-run when a TPU tunnel returns).

The default configuration IS the MULTICHIP ground-truth topology: an
8-virt-device world split 2 slices x 4 chips (``HVD_TPU_SLICE_SIZE=4``
over virtual CPU devices), the acceptance harness of ISSUE 7 /
ROADMAP item 3.

``HVD_TPU_BENCH_ITERS`` / ``HVD_TPU_BENCH_WARMUP`` override iteration
counts (docs/running.md).

Usage:
  collective_bench.py                      # full sweep, 4 MiB payload
  collective_bench.py --numel 1048576      # payload size (elements)
  collective_bench.py --legs flat,hier_bf16
  collective_bench.py --smoke              # tiny CPU-safe pass (CI)
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# expose the virtual multislice world BEFORE jax can be imported: raw
# parse, same bootstrap as transformer_bench
try:  # contract-ok: env -- bootstrap runs before the package's env_int is importable
    _WORLD = max(2, int(os.environ.get("HVD_TPU_BENCH_WORLD", "") or 8))
except ValueError:
    _WORLD = 8
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={_WORLD}"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.common.retry import env_int  # noqa: E402
from horovod_tpu.common.topology import (  # noqa: E402
    DCN_AXIS, ICI_AXIS, WORLD_AXIS,
)
from horovod_tpu.compression import DcnCompression  # noqa: E402
from horovod_tpu.ops import spmd_ops  # noqa: E402
from horovod_tpu.ops.comm_model import (  # noqa: E402
    measured_tier_bytes, mesh_slice_ids, modeled_collective_bytes,
)

ITERS = env_int("HVD_TPU_BENCH_ITERS", 20)
WARMUP = env_int("HVD_TPU_BENCH_WARMUP", 3)

#: leg -> (hierarchical?, wire dtype or None)
LEGS = {
    "flat": (False, None),
    "hier": (True, None),
    "hier_bf16": (True, "bfloat16"),
    "hier_fp16": (True, "float16"),
}

#: overlap legs (ops/overlap.py): handled by run_overlap_legs /
#: run_overlap_autotune_leg rather than the allreduce sweep above
OVERLAP_LEGS = ("overlap", "overlap_autotune")


_leg_t0 = time.time()


def begin_leg():
    """Stamp the wall-clock start of the next leg (emit() pairs it with
    t_end so bench rows correlate with trace dumps from the same run)."""
    global _leg_t0
    _leg_t0 = time.time()


def emit(rec, human=""):
    rec.setdefault("t_start", round(_leg_t0, 3))
    rec.setdefault("t_end", round(time.time(), 3))
    print(json.dumps(rec))
    if human:
        print(human, file=sys.stderr)


def _timed(fn, *args):
    out = jax.block_until_ready(fn(*args))
    for _ in range(max(WARMUP - 1, 0)):
        out = jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    iters = max(ITERS, 1)
    for _ in range(iters):
        out = jax.block_until_ready(fn(*args))
    return out, (time.perf_counter() - t0) / iters


def run_leg(leg, x, hmesh, wmesh, slice_ids, n_ici):
    hierarchical, wire = LEGS[leg]
    world = x.shape[0]
    comp = DcnCompression(wire) if wire else None
    if hierarchical:
        fn = jax.jit(jax.shard_map(
            lambda t: spmd_ops.hierarchical_allreduce(
                t, op=hvd.Sum, dcn_compression=comp
            ),
            mesh=hmesh, in_specs=P((DCN_AXIS, ICI_AXIS)),
            out_specs=P((DCN_AXIS, ICI_AXIS)), check_vma=False,
        ))
    else:
        fn = jax.jit(jax.shard_map(
            lambda t: spmd_ops.allreduce(t, op=hvd.Sum),
            mesh=wmesh, in_specs=P(WORLD_AXIS), out_specs=P(WORLD_AXIS),
            check_vma=False,
        ))
    out, step_s = _timed(fn, x)
    ref = np.asarray(x, np.float64).sum(axis=0)
    got = np.asarray(out, np.float64)
    err = np.abs(got - ref[None]).max()
    scale = max(np.abs(ref).max(), 1e-30)
    # hierarchical programs: replica groups use the hmesh's row-major
    # LOGICAL ids (mesh_slice_ids); the flat program runs over the 1-D
    # world mesh where logical order == world order
    measured = measured_tier_bytes(
        fn.lower(x).as_text(),
        mesh_slice_ids(hmesh) if hierarchical else slice_ids,
    )
    if hierarchical:
        n_ici_model = n_ici
    else:
        # flat routing over a slice-spanning world: every ring step's
        # bytes cross a slice-boundary link (n_ici=1 attribution —
        # comm_model's bottleneck-link view, matching measured_tier_bytes'
        # classification of the world-spanning replica group)
        n_ici_model = 1 if len(set(slice_ids)) > 1 else world
    modeled = modeled_collective_bytes(
        x.shape[1:], world, n_ici_model,
        wire_dtype=wire, dtype=str(x.dtype),
    )
    return {
        "bench": "collective",
        "leg": leg,
        "world": world,
        "n_ici": n_ici if hierarchical else world,
        "n_dcn": (world // n_ici) if hierarchical else 1,
        "numel": int(np.prod(x.shape[1:])),
        "dtype": str(x.dtype),
        "wire_dtype": wire,
        "comm_bytes": {
            "ici": modeled["ici_bytes"],
            "dcn": modeled["dcn_bytes"],
            "wire_dtype": modeled["wire_dtype"],
        },
        "measured_bytes": {
            "ici": measured["ici_bytes"],
            "dcn": measured["dcn_bytes"],
        },
        "collective_ops": [
            (o["op"], o["tier"], o["stream_bytes"]) for o in measured["ops"]
        ],
        "time_ms": round(step_s * 1e3, 3),
        "max_rel_err": float(err / scale),
        "bit_exact": bool(err == 0.0),
    }


def _overlap_chain(world, n_seg, d, batch):
    """A segment-chain training program (relu MLP) sized so the
    BucketSchedule splits it into several buckets — the overlap leg's
    workload.  Returns (segments, params, x, schedule bucket bytes)."""
    from horovod_tpu.ops.overlap import Segment

    rs = np.random.RandomState(1)
    params = {
        f"w{k}": jnp.asarray(
            np.round(rs.randn(d, d) * 8) / 8, jnp.float32
        )
        for k in range(n_seg)
    }

    def make(k):
        def seg(p, x):
            return jax.nn.relu(x @ p[f"w{k}"])

        return Segment(seg, keys=(f"w{k}",))

    def head(p, x):
        return jnp.mean((x @ p[f"w{n_seg - 1}"]) ** 2)

    segments = [make(k) for k in range(n_seg - 1)] + [
        Segment(head, keys=(f"w{n_seg - 1}",))
    ]
    x = jnp.asarray(
        np.round(rs.randn(batch, d) * 8) / 8, jnp.float32
    )
    return segments, params, x


def _overlap_step_fn(segments, wmesh, world, bucket_bytes, overlap):
    from horovod_tpu.ops.overlap import overlapped_value_and_grad

    def f(p, x):
        loss, grads, _ = overlapped_value_and_grad(
            segments, p, x,
            bucket_reduce=lambda b: jax.lax.psum(b, WORLD_AXIS)
            / jnp.asarray(world, b.dtype),
            bucket_bytes=bucket_bytes, overlap=overlap,
        )
        return loss, grads

    return jax.jit(jax.shard_map(
        f, mesh=wmesh, in_specs=(P(), P(WORLD_AXIS)),
        out_specs=(P(), P()), check_vma=False,
    ))


def run_overlap_legs(wmesh, world, smoke):
    """The backward/collective overlap leg: overlapped vs unoverlapped
    step time, static (program-inventory) exposed-comm fraction on both,
    bucket count/size columns, grads-bit-equal oracle — plus the r4
    scaling-model row (modeled exposed fraction + efficiency at the
    PERF.md round-4 measured point, cross-checked against
    tools/scaling_model.py's inline twin)."""
    from horovod_tpu.ops.fusion import BucketSchedule
    from horovod_tpu.ops.overlap import record_overlap_metrics
    from horovod_tpu.ops.comm_model import (
        modeled_overlap_exposed, overlap_inventory,
    )

    n_seg, d = (4, 32) if smoke else (8, 256)
    batch = world * (2 if smoke else 8)
    segments, params, x = _overlap_chain(world, n_seg, d, batch)
    leaf_bytes = d * d * 4
    bucket_bytes = 2 * leaf_bytes  # 2 layers per bucket -> n_seg/2 buckets
    f_ov = _overlap_step_fn(segments, wmesh, world, bucket_bytes, True)
    f_un = _overlap_step_fn(segments, wmesh, world, bucket_bytes, False)
    (l1, g1), t_ov = _timed(f_ov, params, x)
    (l2, g2), t_un = _timed(f_un, params, x)
    bit_equal = bool(np.asarray(l1) == np.asarray(l2)) and all(
        (np.asarray(a) == np.asarray(b)).all()
        for a, b in zip(
            jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)
        )
    )
    inv_ov = record_overlap_metrics(f_ov.lower(params, x).as_text())
    inv_un = overlap_inventory(f_un.lower(params, x).as_text())
    sched = BucketSchedule(
        jax.tree_util.tree_leaves(params), bucket_bytes
    )
    # r4 scaling-model point (tools/scaling_model.py constants): the
    # acceptance bar is a >=2x modeled exposed-comm drop there
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "scaling_model",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "scaling_model.py"),
    )
    sm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sm)
    n_chips = 256
    n_buckets_r4 = -(-int(sm.WIRE_BYTES) // sm.BUCKET_BYTES)
    r4 = modeled_overlap_exposed(
        [sm.BUCKET_BYTES] * (n_buckets_r4 - 1)
        + [int(sm.WIRE_BYTES) - sm.BUCKET_BYTES * (n_buckets_r4 - 1)],
        sm.T_STEP_S, sm.B_ICI, n_chips,
    )
    exp_sm, frac_sm, eff_sm = sm.overlap_model(n_chips)
    if abs(frac_sm - r4["exposed_fraction"]) > 1e-9:
        raise AssertionError(
            "scaling_model.overlap_model drifted from "
            f"comm_model.modeled_overlap_exposed: {frac_sm} vs "
            f"{r4['exposed_fraction']}"
        )
    recs = [
        {
            "bench": "collective",
            "leg": "overlap",
            "world": world,
            "segments": n_seg,
            "n_buckets": sched.num_buckets,
            "bucket_bytes": bucket_bytes,
            "bucket_nbytes": list(sched.bucket_nbytes),
            "time_ms": round(t_ov * 1e3, 3),
            "time_ms_unoverlapped": round(t_un * 1e3, 3),
            "exposed_fraction_static": round(
                inv_ov["exposed_fraction"], 4),
            "exposed_fraction_static_unoverlapped": round(
                inv_un["exposed_fraction"], 4),
            "interleaved": inv_ov["interleaved"],
            "interleaved_unoverlapped": inv_un["interleaved"],
            "collectives": len(inv_ov["collectives"]),
            "bit_exact": bit_equal,
        },
        {
            "bench": "collective",
            "leg": "overlap_r4_model",
            "chips": n_chips,
            "bucket_bytes": int(sm.BUCKET_BYTES),
            "n_buckets": r4["n_buckets"],
            "t_comm_ms": round(r4["t_comm_s"] * 1e3, 4),
            "t_exposed_ms": round(r4["t_exposed_s"] * 1e3, 4),
            "exposed_fraction": round(r4["exposed_fraction"], 4),
            "exposed_fraction_unoverlapped": 1.0,
            "exposed_drop_x": round(
                1.0 / max(r4["exposed_fraction"], 1e-9), 2),
            "efficiency_bucketed_overlap": round(eff_sm, 4),
        },
    ]
    return recs


def run_overlap_autotune_leg(wmesh, world, smoke):
    """BucketAutotuner leg: sweep bucket sizes over the overlap chain,
    pin the winner, report per-candidate step times — the bench
    acceptance is structural (the default is trial 0 and the pin is the
    argmin, so the pinned plan can never regress against it)."""
    import time as _time

    from horovod_tpu.ops.overlap import BucketAutotuner, Candidate

    n_seg, d = (4, 32) if smoke else (8, 256)
    batch = world * (2 if smoke else 8)
    segments, params, x = _overlap_chain(world, n_seg, d, batch)
    leaf_bytes = d * d * 4
    default = Candidate(2 * leaf_bytes)
    candidates = [Candidate(leaf_bytes), Candidate(4 * leaf_bytes)]
    tuner = BucketAutotuner(
        candidates=candidates, default=default,
        trial_budget=len(candidates) + 1,
        steps_per_trial=2 if smoke else max(3, WARMUP + 1),
    )

    def build(cand):
        step = _overlap_step_fn(
            segments, wmesh, world, cand.bucket_bytes, True
        )
        return lambda: step(params, x)

    def timed(thunk):
        t0 = _time.perf_counter()
        jax.block_until_ready(thunk())
        return _time.perf_counter() - t0

    pinned = tuner.run(build, timed)
    scores = {c.bucket_bytes: t for c, t in tuner.scores}
    return {
        "bench": "collective",
        "leg": "overlap_autotune",
        "world": world,
        "candidates": sorted(scores),
        "step_ms_by_bucket": {
            str(k): round(v * 1e3, 3) for k, v in sorted(scores.items())
        },
        "pinned_bucket_bytes": pinned.bucket_bytes,
        "trials": len(tuner.scores),
        "trial_budget": tuner.trial_budget,
        "pinned_step_ms": round(scores[pinned.bucket_bytes] * 1e3, 3),
        "default_step_ms": round(scores[default.bucket_bytes] * 1e3, 3),
        "regressed_vs_default": bool(
            scores[pinned.bucket_bytes] > scores[default.bucket_bytes]
        ),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    all_legs = tuple(LEGS) + OVERLAP_LEGS
    ap.add_argument("--legs", default=",".join(all_legs),
                    help=f"comma list of {'/'.join(all_legs)}")
    ap.add_argument("--numel", type=int, default=1 << 20,
                    help="payload elements per contribution")
    ap.add_argument("--slice-size", type=int, default=0,
                    help="chips per slice (default world/2)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU-safe pass of every leg (CI)")
    args = ap.parse_args(argv)

    numel = 4096 if args.smoke else args.numel
    hvd.init()
    world = hvd.size()
    n_ici = args.slice_size or max(world // 2, 1)
    if world % n_ici:
        ap.error(f"--slice-size {n_ici} does not divide world {world}")
    os.environ["HVD_TPU_SLICE_SIZE"] = str(n_ici)
    from horovod_tpu.common import basics
    topo = basics._require_init().topology
    slice_ids = topo.slice_ids()
    hmesh = topo.hierarchical_mesh()
    wmesh = hvd.world_mesh()

    # dyadic-friendly contributions: distinct per chip, exactly
    # representable so the fp32 Sum oracle can be bit-checked
    rs = np.random.RandomState(0)
    x = jnp.asarray(
        np.round(rs.randn(world, numel) * 8) / 8
    ).astype(jnp.float32)

    failed = False
    for leg in args.legs.split(","):
        leg = leg.strip()
        if leg not in LEGS and leg not in OVERLAP_LEGS:
            ap.error(f"unknown leg {leg!r}")
        begin_leg()
        try:
            if leg == "overlap":
                for rec in run_overlap_legs(wmesh, world, args.smoke):
                    if rec["leg"] == "overlap":
                        emit(rec, (
                            f"[collective_bench]    overlap: "
                            f"{rec['n_buckets']} buckets, static exposed "
                            f"{rec['exposed_fraction_static']} (unoverlapped "
                            f"{rec['exposed_fraction_static_unoverlapped']}), "
                            f"bit_exact {rec['bit_exact']}, "
                            f"{rec['time_ms']}ms vs "
                            f"{rec['time_ms_unoverlapped']}ms"
                        ))
                    else:
                        emit(rec, (
                            f"[collective_bench] overlap_r4: modeled exposed "
                            f"{rec['exposed_fraction']} at {rec['chips']} "
                            f"chips ({rec['exposed_drop_x']}x drop)"
                        ))
                continue
            if leg == "overlap_autotune":
                rec = run_overlap_autotune_leg(wmesh, world, args.smoke)
                emit(rec, (
                    f"[collective_bench]   autotune: pinned "
                    f"{rec['pinned_bucket_bytes']}B after {rec['trials']} "
                    f"trials, {rec['pinned_step_ms']}ms (default "
                    f"{rec['default_step_ms']}ms)"
                ))
                continue
            rec = run_leg(leg, x, hmesh, wmesh, slice_ids, n_ici)
        except Exception as e:  # noqa: BLE001 - isolate legs, report at exit
            print(f"[collective_bench] leg {leg} FAILED: {e}",
                  file=sys.stderr)
            failed = True
            continue
        emit(rec, (
            f"[collective_bench] {leg:>10}: modeled dcn "
            f"{rec['comm_bytes']['dcn']}B measured dcn "
            f"{rec['measured_bytes']['dcn']}B ici "
            f"{rec['measured_bytes']['ici']}B "
            f"{rec['time_ms']}ms rel_err {rec['max_rel_err']:.2e}"
        ))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
