"""Model zoo smoke tests + ring attention exactness.

Reference analog for models: the reference's examples are its model zoo
(BASELINE.md tracked configs).  Ring attention has no reference analog
(SURVEY.md §5.7) — correctness is checked against dense causal attention.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.models.resnet import ResNetTiny
from horovod_tpu.models.simple import MLP, LeNet
from horovod_tpu.models.transformer import (
    Transformer, causal_dot_attention, gpt_tiny,
)
from horovod_tpu.parallel.ring_attention import ring_attention

N = 8


def test_mlp_forward():
    model = MLP()
    x = jnp.ones((4, 28, 28, 1))
    params = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(params, x)
    assert out.shape == (4, 10)


def test_lenet_forward():
    model = LeNet()
    x = jnp.ones((2, 28, 28, 1))
    params = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(params, x)
    assert out.shape == (2, 10)


def test_resnet_tiny_train_step():
    model = ResNetTiny(dtype=jnp.float32)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x)
    out, updates = model.apply(
        variables, x, mutable=["batch_stats"]
    )
    assert out.shape == (2, 10)
    assert "batch_stats" in updates


def test_space_to_depth_stem_equivalence():
    """The space-to-depth stem computes EXACTLY the classic 7x7/s2 stem's
    linear map when the 4x4x12 kernel carries the mapped 7x7x3 weights
    (models/resnet.py space_to_depth_stem docstring); this is the proof
    the bench's fast stem is the same model."""
    import numpy as np
    from horovod_tpu.models import resnet as rn

    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (2, 32, 32, 3), jnp.float32)

    classic = rn.ResNet(
        stage_sizes=[1], block_cls=rn.ResNetBlock, num_filters=8,
        num_classes=4, dtype=jnp.float32, stem="conv",
    )
    s2d = rn.ResNet(
        stage_sizes=[1], block_cls=rn.ResNetBlock, num_filters=8,
        num_classes=4, dtype=jnp.float32, stem="space_to_depth",
    )
    v_classic = classic.init(rng, x)
    v_s2d = s2d.init(jax.random.PRNGKey(1), x)

    # map the classic 7x7x3xF stem kernel into the 4x4x12xF layout
    w7 = np.asarray(v_classic["params"]["conv_init"]["kernel"])
    w4 = np.zeros((4, 4, 12, w7.shape[-1]), np.float32)
    for kp in range(4):
        for a in range(2):
            di = 2 * kp + a - 1
            if not 0 <= di < 7:
                continue
            for kq in range(4):
                for b in range(2):
                    dj = 2 * kq + b - 1
                    if not 0 <= dj < 7:
                        continue
                    w4[kp, kq, a * 6 + b * 3:a * 6 + b * 3 + 3] = (
                        w7[di, dj]
                    )
    params = jax.tree_util.tree_map(lambda t: t, v_classic["params"])
    params = dict(params)
    params["conv_init"] = {"kernel": jnp.asarray(w4)}
    variables = {
        "params": params,
        "batch_stats": v_classic["batch_stats"],
    }
    out_classic = classic.apply(v_classic, x, train=False)
    out_s2d = s2d.apply(variables, x, train=False)
    np.testing.assert_allclose(
        np.asarray(out_classic), np.asarray(out_s2d), rtol=1e-4, atol=1e-4
    )
    # shapes of the fresh s2d init agree with the mapped layout
    assert v_s2d["params"]["conv_init"]["kernel"].shape == (4, 4, 12, 8)


def test_transformer_forward():
    cfg = gpt_tiny(dtype=jnp.float32)
    model = Transformer(cfg)
    tokens = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)


def test_causal_attention_is_causal():
    b, s, h, d = 1, 8, 2, 4
    key = jax.random.PRNGKey(1)
    q, k, v = (
        jax.random.normal(jax.random.fold_in(key, i), (b, s, h, d))
        for i in range(3)
    )
    out1 = causal_dot_attention(q, k, v)
    # changing future K/V must not change past outputs
    k2 = k.at[:, -1].set(100.0)
    v2 = v.at[:, -1].set(-100.0)
    out2 = causal_dot_attention(q, k2, v2)
    np.testing.assert_allclose(
        np.asarray(out1[:, :-1]), np.asarray(out2[:, :-1]), rtol=1e-5
    )


def test_ring_attention_matches_dense():
    b, s_global, h, d = 1, 32, 2, 8
    s_local = s_global // N
    key = jax.random.PRNGKey(2)
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, s_global, h, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, s_global, h, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, s_global, h, d))

    dense = causal_dot_attention(q, k, v)

    def per_rank(r):
        sl = lambda t: jax.lax.dynamic_slice_in_dim(
            t, r * s_local, s_local, axis=1
        )
        out = ring_attention(sl(q), sl(k), sl(v))
        return jnp.swapaxes(out, 0, 1)  # leading axis = local seq

    out = hvd.run_per_rank(per_rank)  # (N, s_local, b, h, d)
    ring = jnp.moveaxis(out.reshape((s_global,) + out.shape[2:]), 0, 1)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=1e-4, atol=1e-5
    )


def test_ring_attention_single_axis_fallback():
    # n == 1 falls back to dense attention
    ps = hvd.add_process_set([5])
    try:
        b, s, h, d = 1, 8, 1, 4
        q = jax.random.normal(jax.random.PRNGKey(3), (b, s, h, d))

        out = hvd.run_per_rank(
            lambda r: jnp.swapaxes(ring_attention(q, q, q), 0, 1),
            process_set=ps,
        )
        dense = causal_dot_attention(q, q, q)
        np.testing.assert_allclose(
            np.asarray(jnp.moveaxis(out[0], 0, 1)), np.asarray(dense),
            rtol=1e-4, atol=1e-5,
        )
    finally:
        hvd.remove_process_set(ps)


def test_transformer_ring_attention_training_parity():
    """A tiny LM loss under 8-way sequence parallelism must match the
    dense single-worker computation — the long-context flagship path."""
    cfg_dense = gpt_tiny(dtype=jnp.float32)
    cfg_ring = gpt_tiny(
        dtype=jnp.float32, attention_impl="ring", seq_axis_name="hvd"
    )
    s_global = 32
    s_local = s_global // N
    tokens = jax.random.randint(
        jax.random.PRNGKey(4), (1, s_global), 0, cfg_dense.vocab_size
    )
    model_d = Transformer(cfg_dense)
    params = model_d.init(jax.random.PRNGKey(5), tokens)
    dense_logits = model_d.apply(params, tokens)

    model_r = Transformer(cfg_ring)

    def per_rank(r):
        local = jax.lax.dynamic_slice_in_dim(
            tokens, r * s_local, s_local, axis=1
        )
        pos = (r * s_local + jnp.arange(s_local))[None, :]
        logits = model_r.apply(params, local, positions=pos)
        return jnp.swapaxes(logits, 0, 1)

    out = hvd.run_per_rank(per_rank)  # (N, s_local, b, vocab)
    ring_logits = jnp.moveaxis(
        out.reshape((s_global,) + out.shape[2:]), 0, 1
    )
    np.testing.assert_allclose(
        np.asarray(ring_logits), np.asarray(dense_logits),
        rtol=2e-3, atol=2e-3,
    )


def test_ring_default_positions_are_global():
    """positions=None under ring attention must derive global offsets from
    the axis index (regression: shard-local RoPE positions)."""
    cfg_dense = gpt_tiny(dtype=jnp.float32)
    cfg_ring = gpt_tiny(
        dtype=jnp.float32, attention_impl="ring", seq_axis_name="hvd"
    )
    s_global = 32
    s_local = s_global // N
    tokens = jax.random.randint(
        jax.random.PRNGKey(7), (1, s_global), 0, cfg_dense.vocab_size
    )
    model_d = Transformer(cfg_dense)
    params = model_d.init(jax.random.PRNGKey(8), tokens)
    dense_logits = model_d.apply(params, tokens)
    model_r = Transformer(cfg_ring)

    def per_rank(r):
        local = jax.lax.dynamic_slice_in_dim(
            tokens, r * s_local, s_local, axis=1
        )
        logits = model_r.apply(params, local)  # no positions passed
        return jnp.swapaxes(logits, 0, 1)

    out = hvd.run_per_rank(per_rank)
    ring_logits = jnp.moveaxis(
        out.reshape((s_global,) + out.shape[2:]), 0, 1
    )
    np.testing.assert_allclose(
        np.asarray(ring_logits), np.asarray(dense_logits),
        rtol=2e-3, atol=2e-3,
    )


def test_ring_flash_attention_matches_dense():
    """Flash-block ring parity: same values as the dense oracle, sharded
    over the 8-chip mesh, with the pallas kernels in interpret mode."""
    b, s_global, h, d = 1, 32, 2, 8
    s_local = s_global // N
    key = jax.random.PRNGKey(7)
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, s_global, h, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, s_global, h, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, s_global, h, d))

    dense = causal_dot_attention(q, k, v)

    def per_rank(r):
        sl = lambda t: jax.lax.dynamic_slice_in_dim(
            t, r * s_local, s_local, axis=1
        )
        out = ring_attention(sl(q), sl(k), sl(v), impl="flash")
        return jnp.swapaxes(out, 0, 1)

    out = hvd.run_per_rank(per_rank)
    ring = jnp.moveaxis(out.reshape((s_global,) + out.shape[2:]), 0, 1)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=1e-4, atol=1e-5
    )


def test_ring_flash_attention_gradients_match_dense():
    """Flash-block ring backward (traveling dk/dv accumulators) parity
    against autodiff through the dense oracle."""
    b, s_global, h, d = 1, 16, 1, 8
    s_local = s_global // N
    key = jax.random.PRNGKey(9)
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, s_global, h, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, s_global, h, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, s_global, h, d))
    w = jax.random.normal(jax.random.fold_in(key, 3), (b, s_global, h, d))

    def dense_loss(q_, k_, v_):
        return jnp.sum(causal_dot_attention(q_, k_, v_) * w)

    want = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)

    def per_rank(r):
        sl = lambda t: jax.lax.dynamic_slice_in_dim(
            t, r * s_local, s_local, axis=1
        )

        def loss(q_, k_, v_):
            out = ring_attention(q_, k_, v_, impl="flash")
            return jnp.sum(out * sl(w))

        # psum: each shard's loss contributes to the same global scalar
        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(sl(q), sl(k), sl(v))
        return jnp.swapaxes(jnp.stack([gq, gk, gv]), 1, 2)

    out = hvd.run_per_rank(per_rank)  # (N, 3, s_local, b, h, d)
    got = jnp.moveaxis(
        out.transpose(1, 0, 2, 3, 4, 5).reshape(
            (3, s_global) + out.shape[3:]
        ), 1, 2,
    )
    for g_got, g_want in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g_got), np.asarray(g_want), rtol=1e-3, atol=1e-4
        )


@pytest.mark.parametrize("impl", ["dense", "flash"])
def test_ring_attention_bidirectional_matches_dense(impl):
    """Encoder-mode ring attention (causal=False): every shard attends
    every other, matching the full bidirectional dot oracle."""
    b, s_global, h, d = 1, 32, 2, 8
    s_local = s_global // N
    key = jax.random.PRNGKey(11)
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, s_global, h, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, s_global, h, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, s_global, h, d))

    dense = causal_dot_attention(q, k, v, causal=False)

    def per_rank(r):
        sl = lambda t: jax.lax.dynamic_slice_in_dim(
            t, r * s_local, s_local, axis=1
        )
        out = ring_attention(sl(q), sl(k), sl(v), impl=impl, causal=False)
        return jnp.swapaxes(out, 0, 1)

    out = hvd.run_per_rank(per_rank)
    ring = jnp.moveaxis(out.reshape((s_global,) + out.shape[2:]), 0, 1)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=1e-4, atol=1e-5
    )


def test_ring_flash_bidirectional_gradients_match_dense():
    """Encoder-mode flash-block ring backward parity against autodiff
    through the bidirectional dot oracle."""
    b, s_global, h, d = 1, 16, 1, 8
    s_local = s_global // N
    key = jax.random.PRNGKey(13)
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, s_global, h, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, s_global, h, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, s_global, h, d))
    w = jax.random.normal(jax.random.fold_in(key, 3), (b, s_global, h, d))

    def dense_loss(q_, k_, v_):
        return jnp.sum(causal_dot_attention(q_, k_, v_, causal=False) * w)

    want = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)

    def per_rank(r):
        sl = lambda t: jax.lax.dynamic_slice_in_dim(
            t, r * s_local, s_local, axis=1
        )

        def loss(q_, k_, v_):
            out = ring_attention(q_, k_, v_, impl="flash", causal=False)
            return jnp.sum(out * sl(w))

        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(sl(q), sl(k), sl(v))
        return jnp.swapaxes(jnp.stack([gq, gk, gv]), 1, 2)

    out = hvd.run_per_rank(per_rank)  # (N, 3, s_local, b, h, d)
    got = jnp.moveaxis(
        out.transpose(1, 0, 2, 3, 4, 5).reshape(
            (3, s_global) + out.shape[3:]
        ), 1, 2,
    )
    for g_got, g_want in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g_got), np.asarray(g_want), rtol=1e-3, atol=1e-4
        )


def test_sliding_window_attention():
    """Mistral-style window: position p attends exactly its last
    `window` predecessors — keys beyond the window cannot influence the
    output; keys inside it must."""
    rng = np.random.RandomState(21)
    b, s, h, d = 1, 12, 2, 8
    q = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    W = 4
    out = causal_dot_attention(q, k, v, window=W)

    # perturb key/value at position 2: position 9 (distance 7 >= W) must
    # be unchanged, position 5 (distance 3 < W) must change
    k2 = k.at[:, 2].add(1.0)
    v2 = v.at[:, 2].add(1.0)
    out2 = causal_dot_attention(q, k2, v2, window=W)
    np.testing.assert_allclose(np.asarray(out[:, 9]),
                               np.asarray(out2[:, 9]), rtol=1e-6)
    assert not np.allclose(np.asarray(out[:, 5]), np.asarray(out2[:, 5]))

    # bidirectional window is symmetric: position 9 sees neither side
    # beyond |delta| < W
    out_b = causal_dot_attention(q, k, v, causal=False, window=W)
    out_b2 = causal_dot_attention(q, k2, v2, causal=False, window=W)
    np.testing.assert_allclose(np.asarray(out_b[:, 9]),
                               np.asarray(out_b2[:, 9]), rtol=1e-6)
    assert not np.allclose(np.asarray(out_b[:, 4]), np.asarray(out_b2[:, 4]))


def test_ring_attention_windowed_matches_dense():
    """Sliding window over GLOBAL positions through the sharded dense
    ring — the window must be exact across shard boundaries."""
    b, s_global, h, d = 1, 32, 2, 8
    s_local = s_global // N
    key = jax.random.PRNGKey(23)
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, s_global, h, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, s_global, h, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, s_global, h, d))
    W = 6  # crosses the 4-wide shard boundaries

    dense = causal_dot_attention(q, k, v, window=W)

    def per_rank(r):
        sl = lambda t: jax.lax.dynamic_slice_in_dim(
            t, r * s_local, s_local, axis=1
        )
        out = ring_attention(sl(q), sl(k), sl(v), window=W)
        return jnp.swapaxes(out, 0, 1)

    out = hvd.run_per_rank(per_rank)
    ring = jnp.moveaxis(out.reshape((s_global,) + out.shape[2:]), 0, 1)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=1e-4, atol=1e-5
    )


def test_window_config_plumbing():
    """TransformerConfig.window reaches the mask (windowed logits differ
    from unwindowed), the flash impl agrees with dot under a window, and
    ring_flash+window — rejected before the windowed merge landed — now
    constructs."""
    from horovod_tpu.models.transformer import TransformerConfig

    tokens = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6]])

    def logits(**kw):
        cfg = TransformerConfig(
            vocab_size=32, num_layers=1, num_heads=2, head_dim=8,
            max_seq_len=8, dtype=jnp.float32, **kw)
        model = Transformer(cfg)
        v = model.init(jax.random.PRNGKey(0), tokens)
        return np.asarray(model.apply(v, tokens))

    assert not np.allclose(logits(window=2), logits())
    np.testing.assert_allclose(
        logits(window=2, attention_impl="flash"), logits(window=2),
        rtol=1e-4, atol=1e-5)

    # the windowed ring-flash merge composes at config time now; the
    # numerics are pinned by test_transformer_ring_flash_windowed_parity
    cfg = TransformerConfig(
        vocab_size=32, num_layers=1, num_heads=2, head_dim=8,
        max_seq_len=8, window=2, attention_impl="ring_flash",
        seq_axis_name="hvd")
    assert cfg.window == 2


def test_gqa_attention():
    """Grouped-query attention (num_kv_heads < num_heads, the
    Llama-2-70B/Llama-3 layout): flash matches dot under GQA, the K/V
    projections actually shrink, gradients flow, and a non-divisible
    head split is rejected."""
    import optax
    from horovod_tpu.models.transformer import TransformerConfig

    tokens = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6]])

    def build(attention_impl):
        cfg = TransformerConfig(
            vocab_size=32, num_layers=1, num_heads=4, num_kv_heads=2,
            head_dim=8, max_seq_len=8, dtype=jnp.float32,
            attention_impl=attention_impl,
        )
        model = Transformer(cfg)
        return model, model.init(jax.random.PRNGKey(0), tokens)

    model_d, v_d = build("dot")
    model_f, v_f = build("flash")
    # identical params (same init seed/structure) — impls must agree
    np.testing.assert_allclose(
        np.asarray(model_d.apply(v_d, tokens)),
        np.asarray(model_f.apply(v_f, tokens)), rtol=1e-4, atol=1e-5)

    # K/V projections carry kv_heads (2), Q carries num_heads (4)
    attn = v_d["params"]["layer_0"]["attn"]
    assert attn["q"]["kernel"].shape[-2] == 4
    assert attn["k"]["kernel"].shape[-2] == 2
    assert attn["v"]["kernel"].shape[-2] == 2

    def loss(p):
        logits = model_d.apply({"params": p}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tokens).mean()

    g = jax.grad(loss)(v_d["params"])
    gnorm = sum(float(jnp.sum(x ** 2))
                for x in jax.tree_util.tree_leaves(g))
    assert np.isfinite(gnorm) and gnorm > 0

    for bad in (3, 0):
        with pytest.raises(ValueError, match="num_kv_heads"):
            cfg = TransformerConfig(
                vocab_size=32, num_layers=1, num_heads=4, num_kv_heads=bad,
                head_dim=8, max_seq_len=8, dtype=jnp.float32)
            Transformer(cfg).init(jax.random.PRNGKey(0), tokens)


def test_gqa_under_ring_attention():
    """Every impl consumes GQA K/V natively (grouped einsums — only the
    kv heads rotate the ring, no repeat) — pin it for ring:
    sharded-ring logits match the single-device dot model."""
    from horovod_tpu.models.transformer import TransformerConfig

    s_global = 16
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 32, size=(1, s_global)))

    def cfg_of(**kw):
        return TransformerConfig(
            vocab_size=32, num_layers=1, num_heads=4, num_kv_heads=2,
            head_dim=8, max_seq_len=s_global, dtype=jnp.float32, **kw)

    model_d = Transformer(cfg_of())
    v = model_d.init(jax.random.PRNGKey(0), tokens)
    dense_logits = np.asarray(model_d.apply(v, tokens))

    cfg_r = cfg_of(attention_impl="ring", seq_axis_name="hvd")
    model_r = Transformer(cfg_r)
    s_local = s_global // N

    def per_rank(r):
        sl = jax.lax.dynamic_slice_in_dim(tokens, r * s_local, s_local, 1)
        return jnp.swapaxes(model_r.apply(v, sl), 0, 1)

    out = hvd.run_per_rank(per_rank)  # (N, s_local, b, vocab)
    ring_logits = jnp.moveaxis(
        out.reshape((s_global,) + out.shape[2:]), 0, 1)
    np.testing.assert_allclose(np.asarray(ring_logits), dense_logits,
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("W", [3, 6])
def test_ring_flash_windowed_matches_dense(W):
    """Windowed flash-block ring (per-step kv_offset into the kernels +
    truncated rotation) vs the single-device windowed dot oracle; both
    windows cross the 4-wide shard boundaries (W=6) or sit inside one
    (W=3, where the rotation truncates to 2 of 8 steps)."""
    b, s_global, h, d = 1, 32, 2, 8
    s_local = s_global // N
    key = jax.random.PRNGKey(29)
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, s_global, h, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, s_global, h, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, s_global, h, d))

    dense = causal_dot_attention(q, k, v, window=W)

    def per_rank(r):
        sl = lambda t: jax.lax.dynamic_slice_in_dim(
            t, r * s_local, s_local, axis=1
        )
        out = ring_attention(sl(q), sl(k), sl(v), impl="flash", window=W)
        return jnp.swapaxes(out, 0, 1)

    out = hvd.run_per_rank(per_rank)
    ring = jnp.moveaxis(out.reshape((s_global,) + out.shape[2:]), 0, 1)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=1e-4, atol=1e-5
    )


def test_ring_flash_windowed_bidirectional_matches_dense():
    """Bidirectional window through the flash-block ring: symmetric
    global-position reach, no rotation truncation (shards must transit
    the full ring), per-chip kernel masking only."""
    b, s_global, h, d = 1, 32, 2, 8
    s_local = s_global // N
    key = jax.random.PRNGKey(31)
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, s_global, h, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, s_global, h, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, s_global, h, d))
    W = 6

    dense = causal_dot_attention(q, k, v, causal=False, window=W)

    def per_rank(r):
        sl = lambda t: jax.lax.dynamic_slice_in_dim(
            t, r * s_local, s_local, axis=1
        )
        out = ring_attention(sl(q), sl(k), sl(v), impl="flash",
                             causal=False, window=W)
        return jnp.swapaxes(out, 0, 1)

    out = hvd.run_per_rank(per_rank)
    ring = jnp.moveaxis(out.reshape((s_global,) + out.shape[2:]), 0, 1)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=1e-4, atol=1e-5
    )


def test_ring_flash_windowed_gradients_match_dense():
    """Windowed flash-block ring backward: per-step kv_offset in both
    backward kernels, truncated rotation, and the home-shift ppermute
    returning the traveling dk/dv accumulators (steps < n exercises the
    non-trivial shift)."""
    b, s_global, h, d = 1, 16, 1, 8
    s_local = s_global // N
    W = 6  # steps = min(8, (6-2)//2 + 2) = 4 < 8: truncation active
    key = jax.random.PRNGKey(33)
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, s_global, h, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, s_global, h, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, s_global, h, d))
    w = jax.random.normal(jax.random.fold_in(key, 3), (b, s_global, h, d))

    def dense_loss(q_, k_, v_):
        return jnp.sum(causal_dot_attention(q_, k_, v_, window=W) * w)

    want = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)

    def per_rank(r):
        sl = lambda t: jax.lax.dynamic_slice_in_dim(
            t, r * s_local, s_local, axis=1
        )

        def loss(q_, k_, v_):
            out = ring_attention(q_, k_, v_, impl="flash", window=W)
            return jnp.sum(out * sl(w))

        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(sl(q), sl(k), sl(v))
        return jnp.swapaxes(jnp.stack([gq, gk, gv]), 1, 2)

    out = hvd.run_per_rank(per_rank)  # (N, 3, s_local, b, h, d)
    got = jnp.moveaxis(
        out.transpose(1, 0, 2, 3, 4, 5).reshape(
            (3, s_global) + out.shape[3:]
        ), 1, 2,
    )
    for g_got, g_want in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g_got), np.asarray(g_want), rtol=1e-3, atol=1e-4
        )


def test_ring_flash_gqa_windowed_matches_oracle():
    """Maximum composition: GQA (kv heads only rotate) x causal sliding
    window x flash-block ring vs the repeat-expanded single-device dot
    oracle."""
    b, s_global, h, h_kv, d = 1, 32, 4, 2, 8
    s_local = s_global // N
    W = 6
    key = jax.random.PRNGKey(37)
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, s_global, h, d))
    k = jax.random.normal(jax.random.fold_in(key, 1),
                          (b, s_global, h_kv, d))
    v = jax.random.normal(jax.random.fold_in(key, 2),
                          (b, s_global, h_kv, d))

    dense = causal_dot_attention(
        q, jnp.repeat(k, h // h_kv, axis=2),
        jnp.repeat(v, h // h_kv, axis=2), window=W)

    def per_rank(r):
        sl = lambda t: jax.lax.dynamic_slice_in_dim(
            t, r * s_local, s_local, axis=1
        )
        out = ring_attention(sl(q), sl(k), sl(v), impl="flash", window=W)
        return jnp.swapaxes(out, 0, 1)

    out = hvd.run_per_rank(per_rank)
    ring = jnp.moveaxis(out.reshape((s_global,) + out.shape[2:]), 0, 1)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=1e-4, atol=1e-5
    )


def test_ring_window_steps_truncation():
    """The causal-window ring schedule skips whole out-of-window steps:
    (a) ring_window_steps matches a brute force over which steps hold
    any in-window (q, k) pair; (b) the step count is ASSERTED in the
    traced program — the ring's rotation loop is the jaxpr's single
    scan, whose static length is steps-1."""
    import re

    from jax.sharding import Mesh, PartitionSpec as P
    from horovod_tpu.parallel.ring_attention import (
        ring_flash_attention, ring_window_steps,
    )

    def brute(n, s_local, window):
        steps = 1  # the resident/diagonal step always runs
        for t in range(1, n):
            if (t - 1) * s_local + 1 <= window - 1:
                steps = t + 1
        return min(steps, n)

    for n in (2, 4, 8):
        for s_local in (1, 2, 4, 8):
            assert ring_window_steps(n, s_local, True, None) == n
            assert ring_window_steps(n, s_local, False, 3) == n
            for window in range(1, 3 * n * s_local):
                assert ring_window_steps(n, s_local, True, window) == \
                    brute(n, s_local, window), \
                    f"n={n} s_local={s_local} window={window}"

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("x",))
    s_local = 4

    def scan_length(window):
        def f(q):
            return jax.shard_map(
                lambda a: ring_flash_attention(
                    a, a, a, axis_name="x", window=window,
                    block_q=128, block_k=128),
                mesh=mesh, in_specs=P(None, "x"), out_specs=P(None, "x"),
                check_vma=False,
            )(q)
        q = jnp.zeros((1, 8 * s_local, 2, 8), jnp.float32)
        lengths = re.findall(r"length=(\d+)", str(jax.make_jaxpr(f)(q)))
        assert len(lengths) == 1  # the rotation loop is the only scan
        return int(lengths[0])

    assert scan_length(None) == 7  # full rotation: n-1 hops
    assert scan_length(1) == 0  # W=1 attends self only: no hops at all
    assert scan_length(6) == ring_window_steps(8, s_local, True, 6) - 1
    assert scan_length(2 * 8 * s_local) == 7  # window >= S: full again


def test_transformer_ring_flash_windowed_parity():
    """ISSUE 5 acceptance: TransformerConfig(attention_impl='ring_flash',
    window=W) constructs and TRAINS — sharded logits match the dense
    single-device windowed model and a grad step is finite."""
    import optax
    from horovod_tpu.models.transformer import TransformerConfig

    s_global = 32
    s_local = s_global // N
    tokens = jax.random.randint(
        jax.random.PRNGKey(41), (1, s_global), 0, 32)

    def cfg_of(**kw):
        return TransformerConfig(
            vocab_size=32, num_layers=1, num_heads=4, num_kv_heads=2,
            head_dim=8, max_seq_len=s_global, dtype=jnp.float32,
            window=6, **kw)

    model_d = Transformer(cfg_of())
    params = model_d.init(jax.random.PRNGKey(42), tokens)
    dense_logits = np.asarray(model_d.apply(params, tokens))

    model_r = Transformer(
        cfg_of(attention_impl="ring_flash", seq_axis_name="hvd"))

    def per_rank(r):
        local = jax.lax.dynamic_slice_in_dim(
            tokens, r * s_local, s_local, axis=1
        )

        def loss_fn(p):
            logits = model_r.apply(p, local)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, local).mean()

        logits = model_r.apply(params, local)
        loss, g = jax.value_and_grad(loss_fn)(params)
        gnorm = sum(jnp.sum(x.astype(jnp.float32) ** 2)
                    for x in jax.tree_util.tree_leaves(g))
        return jnp.swapaxes(logits, 0, 1), loss, gnorm

    logits, loss, gnorm = hvd.run_per_rank(per_rank)
    ring_logits = jnp.moveaxis(
        logits.reshape((s_global,) + logits.shape[2:]), 0, 1)
    np.testing.assert_allclose(np.asarray(ring_logits), dense_logits,
                               rtol=2e-3, atol=2e-3)
    assert np.all(np.isfinite(np.asarray(loss)))
    assert np.all(np.isfinite(np.asarray(gnorm)))
    assert float(jnp.max(gnorm)) > 0


def test_transformer_remat_matches_no_remat():
    """cfg.remat trades FLOPs for memory; numerics must be identical."""
    import optax
    from horovod_tpu.models.transformer import gpt_tiny

    tok = jnp.asarray(
        np.random.RandomState(0).randint(0, 256, size=(2, 32))
    )
    grads = {}
    for remat in (False, True):
        cfg = gpt_tiny(dtype=jnp.float32, remat=remat)
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(0), tok)

        def loss_fn(p):
            logits = model.apply(p, tok)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], tok[:, 1:]
            ).mean()

        loss, g = jax.value_and_grad(loss_fn)(params)
        grads[remat] = (loss, g)
    np.testing.assert_allclose(
        float(grads[False][0]), float(grads[True][0]), rtol=1e-6
    )
    flat_a = jax.tree_util.tree_leaves(grads[False][1])
    flat_b = jax.tree_util.tree_leaves(grads[True][1])
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_resnet_remat_numerics_identical():
    """remat=True must change only the backward's memory/FLOP schedule,
    never the numbers: identical loss and gradients vs remat=False."""
    import numpy as np
    from horovod_tpu.models import resnet as rn

    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    y = jnp.asarray([1, 3])

    def loss_grads(remat):
        model = rn.ResNetTiny(dtype=jnp.float32, remat=remat)
        variables = model.init(jax.random.PRNGKey(0), x)

        def loss_fn(params):
            out, _ = model.apply(
                {"params": params,
                 "batch_stats": variables["batch_stats"]},
                x, mutable=["batch_stats"],
            )
            import optax

            return optax.softmax_cross_entropy_with_integer_labels(
                out, y
            ).mean()

        return jax.value_and_grad(loss_fn)(variables["params"])

    loss0, g0 = loss_grads(False)
    loss1, g1 = loss_grads(True)
    np.testing.assert_allclose(float(loss0), float(loss1),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_encoder_attention_is_bidirectional():
    """causal=False (BERT-family encoder mode): position 0's output
    depends on later tokens; causal=True must not."""
    import numpy as np
    from horovod_tpu.models.transformer import Transformer, \
        TransformerConfig

    def out_at_zero(causal, tokens):
        cfg = TransformerConfig(
            vocab_size=32, num_layers=1, num_heads=2, head_dim=8,
            max_seq_len=8, dtype=jnp.float32, causal=causal,
        )
        model = Transformer(cfg)
        v = model.init(jax.random.PRNGKey(0), tokens)
        return np.asarray(model.apply(v, tokens))[:, 0]

    t1 = jnp.asarray([[1, 2, 3, 4]])
    t2 = jnp.asarray([[1, 2, 3, 9]])  # perturb only the LAST token
    assert not np.allclose(out_at_zero(False, t1), out_at_zero(False, t2))
    np.testing.assert_allclose(out_at_zero(True, t1),
                               out_at_zero(True, t2), rtol=1e-6)


def test_encoder_flash_matches_dot():
    """Encoder mode (causal=False) through the pallas flash kernel gives
    the same logits as the dot oracle — long-context BERT-family support
    is not dot-only."""
    import numpy as np
    from horovod_tpu.models.transformer import Transformer, \
        TransformerConfig

    tokens = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6]])

    def logits(attention_impl):
        cfg = TransformerConfig(
            vocab_size=32, num_layers=1, num_heads=2, head_dim=8,
            max_seq_len=8, dtype=jnp.float32, causal=False,
            attention_impl=attention_impl,
        )
        model = Transformer(cfg)
        v = model.init(jax.random.PRNGKey(0), tokens)
        return np.asarray(model.apply(v, tokens))

    np.testing.assert_allclose(logits("flash"), logits("dot"),
                               rtol=1e-4, atol=1e-5)
