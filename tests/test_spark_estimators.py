"""Estimator + Store contract tests.

Reference analog: test/integration/test_spark_keras.py /
test_spark_torch.py (SURVEY.md §4) — fit a DataFrame, get a Transformer
back, checkpoint lands in the Store.  pyspark is absent, so the
launcher-subprocess backend runs the workers (the `local-cluster`
technique: real multi-process on one box).
"""

import os

import numpy as np
import pytest

from envguards import requires_multiprocess_collectives

from horovod_tpu.spark import LocalStore, Store
from horovod_tpu.spark.keras import FlaxEstimator, KerasEstimator
from horovod_tpu.spark.torch import TorchEstimator
from tests.estimator_models import TinyMLP, TinyTorchNet


def _blob_data(n=96, seed=0):
    """Linearly separable 3-class blobs: learnable by a tiny MLP fast."""
    rng = np.random.RandomState(seed)
    centers = np.asarray(
        [[2, 2, 0, 0], [-2, 2, 0, 0], [0, -2, 2, 0]], np.float32
    )
    labels = rng.randint(0, 3, size=n)
    feats = centers[labels] + 0.3 * rng.randn(n, 4).astype(np.float32)
    return {"features": feats, "label": labels.astype(np.int32)}


def test_store_create_dispatch(tmp_path):
    s = Store.create(str(tmp_path))
    assert isinstance(s, LocalStore)
    s.write_bytes(str(tmp_path / "a" / "b.bin"), b"xyz")
    assert s.read_bytes(str(tmp_path / "a" / "b.bin")) == b"xyz"
    assert s.exists(str(tmp_path / "a" / "b.bin"))
    assert s.list_files(str(tmp_path / "a")) == ["b.bin"]
    # URL schemes dispatch through fsspec; s3 needs s3fs (absent here)
    with pytest.raises(ImportError):
        Store.create("s3://bucket/prefix")


def test_fsspec_store_roundtrip():
    """Remote-store contract against fsspec's in-process fake filesystem
    (reference: HDFSStore/S3Store — VERDICT r3 item 4's 'local fake
    filesystem test')."""
    from horovod_tpu.spark import FsspecStore

    s = Store.create("memory://hvd-store-test")
    assert isinstance(s, FsspecStore)
    path = "memory://hvd-store-test/x/y.bin"
    assert not s.exists(path)
    s.write_bytes(path, b"payload")
    assert s.read_bytes(path) == b"payload"
    assert s.exists(path)
    assert s.list_files("memory://hvd-store-test/x") == ["y.bin"]
    assert s.list_files("memory://hvd-store-test/absent") == []
    # worker-side reconstruction travels (class name, prefix)
    spec = s.worker_spec()
    assert spec == {"store_cls": "FsspecStore",
                    "store_prefix": "memory://hvd-store-test"}


def test_sharded_materialization_accounting():
    """Streamed dealing: balanced per-rank rows, bounded shard files,
    equalized usable_rows, validation split — all recorded in the
    manifest (reference: Petastorm row-group assignment)."""
    from horovod_tpu.spark import sharding

    store = Store.create("memory://hvd-shard-test")
    rng = np.random.RandomState(0)

    def chunks():
        for i in range(7):
            n = 37 + i  # ragged chunk sizes on purpose
            yield {
                "features": rng.randn(n, 4).astype(np.float32),
                "label": rng.randint(0, 3, n).astype(np.int32),
            }

    m = sharding.materialize_streaming(
        store, "run1", chunks(), num_proc=3, batch_size=16,
        validation=0.1, seed=0, shard_rows=40,
    )
    total = sum(37 + i for i in range(7))
    assert sum(m["rows_per_rank"]) + m["val_rows"] == total
    assert max(m["rows_per_rank"]) - min(m["rows_per_rank"]) <= 1
    assert m["usable_rows"] == (min(m["rows_per_rank"]) // 16) * 16
    # every shard file exists and respects the row bound
    for rank in range(3):
        for i in range(m["shards_per_rank"][rank]):
            name = f"part_{rank}_{i:05d}.npz"
            p = store.get_train_data_path("run1") + "/" + name
            assert store.exists(p), name


def test_shard_reader_memory_contract():
    """The epoch reader holds at most one shard + a sub-batch carry in
    memory and yields exactly usable_rows//batch_size whole batches —
    the per-shard memory high-water VERDICT r3 item 4 requires."""
    from horovod_tpu.spark import sharding

    store = Store.create("memory://hvd-reader-test")
    rng = np.random.RandomState(0)
    n, shard_rows, bs = 500, 64, 32
    data = {
        "features": rng.randn(n, 2).astype(np.float32),
        "label": np.arange(n, dtype=np.int64),  # unique → coverage check
    }
    m = sharding.materialize_streaming(
        store, "r", iter([data]), num_proc=1, batch_size=bs,
        shuffle=True, seed=1, shard_rows=shard_rows,
    )
    reader = sharding.ShardReader(
        store, store.get_train_data_path("r"), 0, m["shards_per_rank"][0]
    )
    seen = []
    nb = 0
    for batch in reader.iter_batches(
        np.random.RandomState(2), bs, m["usable_rows"]
    ):
        assert len(batch["label"]) == bs
        seen.extend(batch["label"].tolist())
        nb += 1
    assert nb == m["usable_rows"] // bs
    assert len(set(seen)) == len(seen)  # no row repeated within an epoch
    assert reader.max_resident_rows <= shard_rows + bs
    # different epoch rng → different order (shuffling actually happens)
    other = [
        b["label"].tolist()
        for b in reader.iter_batches(
            np.random.RandomState(3), bs, m["usable_rows"]
        )
    ]
    assert [x for b in other for x in b] != seen


@pytest.mark.integration
@requires_multiprocess_collectives  # estimator workers allreduce across processes
def test_flax_estimator_fit_transform(tmp_path, monkeypatch):
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    data = _blob_data()

    # feed fit() a CHUNK ITERATOR (the fully streaming input path) with
    # shard_rows small enough to force multiple shards per rank — the
    # subprocess workers then exercise the multi-shard epoch reader
    def chunk_stream():
        for start in range(0, 96, 24):
            yield {k: v[start:start + 24] for k, v in data.items()}

    est = FlaxEstimator(
        model=TinyMLP(features=3),
        optimizer=("sgd", {"learning_rate": 0.2}),
        loss="softmax_cross_entropy",
        store=LocalStore(str(tmp_path)),
        batch_size=16,
        epochs=8,
        num_proc=2,
        validation=0.1,
        shard_rows=20,
    )
    model = est.fit(chunk_stream())
    from horovod_tpu.spark import sharding

    manifest = sharding.read_manifest(
        est.store, est.store.get_run_path(est.run_id)
    )
    assert all(s >= 2 for s in manifest["shards_per_rank"]), manifest
    # checkpoint landed in the store under the run id
    assert est.run_id is not None
    ckpt = os.path.join(
        est.store.get_checkpoint_path(est.run_id), "model.bin"
    )
    assert est.store.exists(ckpt)
    # transformer appends predictions; separable blobs must be learned
    out = model.transform(data)
    preds = np.argmax(out["label__output"], axis=-1)
    acc = float((preds == data["label"]).mean())
    assert out["label__output"].shape == (96, 3)
    assert acc >= 0.8, f"accuracy {acc}"


@pytest.mark.integration
@requires_multiprocess_collectives  # estimator workers allreduce across processes
def test_torch_estimator_fit_transform(tmp_path, monkeypatch):
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    pd = pytest.importorskip("pandas")
    rng = np.random.RandomState(0)
    feats = rng.randn(64, 4).astype(np.float32)
    w = np.asarray([1.0, -2.0, 0.5, 3.0], np.float32)
    labels = feats @ w
    # pandas with an object column of per-row vectors — the reference's
    # vector-features input shape (stacked dense by _to_columns)
    df = pd.DataFrame({"features": list(feats), "label": labels})
    est = TorchEstimator(
        model=TinyTorchNet(),
        optimizer=("sgd", {"lr": 0.05}),
        loss="mse",
        store=LocalStore(str(tmp_path)),
        batch_size=16,
        epochs=20,
        num_proc=2,
        validation=0.1,
    )
    model = est.fit(df)
    out = model.transform({"features": feats, "label": labels})
    mse = float(((out["label__output"] - labels) ** 2).mean())
    base = float((labels ** 2).mean())
    assert mse < 0.1 * base, f"mse {mse} vs baseline {base}"
    # per-epoch history recorded, including the validation series
    assert model.history and len(model.history["loss"]) == 20
    assert len(model.history["val_loss"]) == 20


@pytest.mark.integration
@requires_multiprocess_collectives  # estimator workers allreduce across processes
def test_keras_estimator_fit_transform(tmp_path, monkeypatch):
    """Real-Keras estimator: a Keras 3 model trains across the worker
    fleet via the Keras adapter's DistributedOptimizer (reference:
    spark/keras KerasEstimator)."""
    keras = pytest.importorskip("keras")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("TF_CPP_MIN_LOG_LEVEL", "3")
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    rng = np.random.RandomState(0)
    x = rng.randn(96, 4).astype(np.float32)
    w_true = rng.randn(4, 1).astype(np.float32)
    data = {"features": x, "label": (x @ w_true).ravel()}

    keras.utils.set_random_seed(3)
    model = keras.Sequential([
        keras.Input(shape=(4,)), keras.layers.Dense(1)
    ])
    est = KerasEstimator(
        model=model,
        optimizer=keras.optimizers.SGD(0.1),
        loss="mse",
        store=LocalStore(str(tmp_path)),
        batch_size=16,
        epochs=6,
        num_proc=2,
        validation=0.1,
    )
    trained = est.fit(data)
    assert trained.history is not None
    losses = trained.history["loss"]
    assert losses[-1] < losses[0] * 0.2, losses
    assert len(trained.history["val_loss"]) == 6  # per-epoch contract
    out = trained.transform(data)
    pred = out["label__output"].ravel()
    mse = float(np.mean((pred - data["label"]) ** 2))
    assert mse < 0.1, mse


@pytest.mark.integration
@requires_multiprocess_collectives  # estimator workers allreduce across processes
def test_keras_estimator_deferred_build_model(tmp_path, monkeypatch):
    """A driver model with no Input spec ships no weights; workers must
    build against the data and broadcast rank 0's init instead of
    training from divergent per-process random initializations."""
    keras = pytest.importorskip("keras")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("TF_CPP_MIN_LOG_LEVEL", "3")
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    rng = np.random.RandomState(1)
    x = rng.randn(64, 3).astype(np.float32)
    data = {"features": x, "label": (x.sum(axis=1)).astype(np.float32)}

    model = keras.Sequential([keras.layers.Dense(1)])  # deferred build
    assert model.get_weights() == []
    est = KerasEstimator(
        model=model, optimizer="sgd", loss="mse",
        store=LocalStore(str(tmp_path)), batch_size=16, epochs=3,
        num_proc=2,
    )
    trained = est.fit(data)
    losses = trained.history["loss"]
    assert losses[-1] < losses[0], losses


def test_validation_credit_accumulates_across_small_chunks():
    """validation=0.1 with 4-row chunks must still yield ~10% val rows
    (fractional credit carries across chunks instead of rounding to
    zero per chunk)."""
    from horovod_tpu.spark import sharding

    store = Store.create("memory://hvd-valcredit-test")
    rng = np.random.RandomState(0)

    def chunks():
        for _ in range(50):  # 200 rows total, 4 at a time
            yield {"x": rng.randn(4, 2).astype(np.float32),
                   "label": np.zeros(4, np.int32)}

    m = sharding.materialize_streaming(
        store, "r", chunks(), num_proc=2, batch_size=8,
        validation=0.1, seed=0, shard_rows=64,
    )
    assert m["val_rows"] == 20, m  # exactly 10% of 200


def test_materialize_missing_column_fails_before_writing():
    """A typo'd feature column raises on the FIRST chunk — before the
    stream is consumed and shards land in the store."""
    from horovod_tpu.spark import sharding

    store = Store.create("memory://hvd-failfast-test")
    consumed = []

    def chunks():
        for i in range(100):
            consumed.append(i)
            yield {"x": np.zeros((8, 2), np.float32),
                   "label": np.zeros(8, np.int32)}

    with pytest.raises(ValueError, match="featurez"):
        sharding.materialize_streaming(
            store, "r", chunks(), num_proc=1, batch_size=4,
            required_columns=["featurez", "label"],
        )
    assert len(consumed) == 1  # only the first chunk was pulled
