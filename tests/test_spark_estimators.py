"""Estimator + Store contract tests.

Reference analog: test/integration/test_spark_keras.py /
test_spark_torch.py (SURVEY.md §4) — fit a DataFrame, get a Transformer
back, checkpoint lands in the Store.  pyspark is absent, so the
launcher-subprocess backend runs the workers (the `local-cluster`
technique: real multi-process on one box).
"""

import os

import numpy as np
import pytest

from horovod_tpu.spark import LocalStore, Store
from horovod_tpu.spark.keras import FlaxEstimator, KerasEstimator
from horovod_tpu.spark.torch import TorchEstimator
from tests.estimator_models import TinyMLP, TinyTorchNet


def _blob_data(n=96, seed=0):
    """Linearly separable 3-class blobs: learnable by a tiny MLP fast."""
    rng = np.random.RandomState(seed)
    centers = np.asarray(
        [[2, 2, 0, 0], [-2, 2, 0, 0], [0, -2, 2, 0]], np.float32
    )
    labels = rng.randint(0, 3, size=n)
    feats = centers[labels] + 0.3 * rng.randn(n, 4).astype(np.float32)
    return {"features": feats, "label": labels.astype(np.int32)}


def test_store_create_dispatch(tmp_path):
    s = Store.create(str(tmp_path))
    assert isinstance(s, LocalStore)
    s.write_bytes(str(tmp_path / "a" / "b.bin"), b"xyz")
    assert s.read_bytes(str(tmp_path / "a" / "b.bin")) == b"xyz"
    assert s.exists(str(tmp_path / "a" / "b.bin"))
    with pytest.raises(ImportError):
        Store.create("s3://bucket/prefix")  # fsspec absent in this image


@pytest.mark.integration
def test_flax_estimator_fit_transform(tmp_path, monkeypatch):
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    data = _blob_data()
    est = FlaxEstimator(
        model=TinyMLP(features=3),
        optimizer=("sgd", {"learning_rate": 0.2}),
        loss="softmax_cross_entropy",
        store=LocalStore(str(tmp_path)),
        batch_size=16,
        epochs=8,
        num_proc=2,
        validation=0.1,
    )
    model = est.fit(data)
    # checkpoint landed in the store under the run id
    assert est.run_id is not None
    ckpt = os.path.join(
        est.store.get_checkpoint_path(est.run_id), "model.bin"
    )
    assert est.store.exists(ckpt)
    # transformer appends predictions; separable blobs must be learned
    out = model.transform(data)
    preds = np.argmax(out["label__output"], axis=-1)
    acc = float((preds == data["label"]).mean())
    assert out["label__output"].shape == (96, 3)
    assert acc >= 0.8, f"accuracy {acc}"


@pytest.mark.integration
def test_torch_estimator_fit_transform(tmp_path, monkeypatch):
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    pd = pytest.importorskip("pandas")
    rng = np.random.RandomState(0)
    feats = rng.randn(64, 4).astype(np.float32)
    w = np.asarray([1.0, -2.0, 0.5, 3.0], np.float32)
    labels = feats @ w
    # pandas with an object column of per-row vectors — the reference's
    # vector-features input shape (stacked dense by _to_columns)
    df = pd.DataFrame({"features": list(feats), "label": labels})
    est = TorchEstimator(
        model=TinyTorchNet(),
        optimizer=("sgd", {"lr": 0.05}),
        loss="mse",
        store=LocalStore(str(tmp_path)),
        batch_size=16,
        epochs=20,
        num_proc=2,
        validation=0.1,
    )
    model = est.fit(df)
    out = model.transform({"features": feats, "label": labels})
    mse = float(((out["label__output"] - labels) ** 2).mean())
    base = float((labels ** 2).mean())
    assert mse < 0.1 * base, f"mse {mse} vs baseline {base}"
    # per-epoch history recorded, including the validation series
    assert model.history and len(model.history["loss"]) == 20
    assert len(model.history["val_loss"]) == 20


@pytest.mark.integration
def test_keras_estimator_fit_transform(tmp_path, monkeypatch):
    """Real-Keras estimator: a Keras 3 model trains across the worker
    fleet via the Keras adapter's DistributedOptimizer (reference:
    spark/keras KerasEstimator)."""
    keras = pytest.importorskip("keras")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("TF_CPP_MIN_LOG_LEVEL", "3")
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    rng = np.random.RandomState(0)
    x = rng.randn(96, 4).astype(np.float32)
    w_true = rng.randn(4, 1).astype(np.float32)
    data = {"features": x, "label": (x @ w_true).ravel()}

    keras.utils.set_random_seed(3)
    model = keras.Sequential([
        keras.Input(shape=(4,)), keras.layers.Dense(1)
    ])
    est = KerasEstimator(
        model=model,
        optimizer=keras.optimizers.SGD(0.1),
        loss="mse",
        store=LocalStore(str(tmp_path)),
        batch_size=16,
        epochs=6,
        num_proc=2,
        validation=0.1,
    )
    trained = est.fit(data)
    assert trained.history is not None
    losses = trained.history["loss"]
    assert losses[-1] < losses[0] * 0.2, losses
    assert len(trained.history["val_loss"]) == 6  # per-epoch contract
    out = trained.transform(data)
    pred = out["label__output"].ravel()
    mse = float(np.mean((pred - data["label"]) ** 2))
    assert mse < 0.1, mse


@pytest.mark.integration
def test_keras_estimator_deferred_build_model(tmp_path, monkeypatch):
    """A driver model with no Input spec ships no weights; workers must
    build against the data and broadcast rank 0's init instead of
    training from divergent per-process random initializations."""
    keras = pytest.importorskip("keras")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("TF_CPP_MIN_LOG_LEVEL", "3")
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    rng = np.random.RandomState(1)
    x = rng.randn(64, 3).astype(np.float32)
    data = {"features": x, "label": (x.sum(axis=1)).astype(np.float32)}

    model = keras.Sequential([keras.layers.Dense(1)])  # deferred build
    assert model.get_weights() == []
    est = KerasEstimator(
        model=model, optimizer="sgd", loss="mse",
        store=LocalStore(str(tmp_path)), batch_size=16, epochs=3,
        num_proc=2,
    )
    trained = est.fit(data)
    losses = trained.history["loss"]
    assert losses[-1] < losses[0], losses
