"""Control-plane authentication + ssh fan-out tests.

Reference analog: the HMAC-signed driver/task RPC of
horovod/runner/common/util/{secret,network}.py and the mocked-ssh
launcher tests of test/single/test_run.py (SURVEY.md §2.4, §4).  Covers:

  * wire_auth sign/verify round-trip and tamper rejection;
  * the elastic driver dropping unsigned/forged control messages;
  * the native TCP star rejecting a secret-less rogue peer while the
    authenticated fleet still forms and completes;
  * ``_launch_ssh`` driven end-to-end through a PATH-shimmed ``ssh``
    that execs locally: arg construction, env plumbing (incl. the job
    secret), rank-0 host addressing, and exit-code lockstep reaping.
"""

import json
import os
import socket
import stat
import struct
import subprocess
import sys
import time

import pytest

import horovod_tpu.runner.launch as launch
from horovod_tpu.common import wire_auth
from envguards import (native_child_env, native_lib_path,
                       requires_multiprocess_collectives)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "integration", "launcher_worker.py")
NATIVE_LIB = native_lib_path(REPO)


# -- wire_auth unit ----------------------------------------------------------


def test_sign_verify_roundtrip():
    secret = wire_auth.make_secret()
    msg = {"type": "rendezvous", "worker_id": 3}
    signed = wire_auth.sign_message(msg, secret)
    assert "hmac" in signed
    out = wire_auth.verify_message(signed, secret)
    assert out == msg


def test_verify_rejects_tamper_and_missing():
    secret = wire_auth.make_secret()
    signed = wire_auth.sign_message({"type": "assignment", "rank": 0},
                                    secret)
    tampered = dict(signed)
    tampered["rank"] = 1
    assert wire_auth.verify_message(tampered, secret) is None
    assert wire_auth.verify_message({"type": "assignment"}, secret) is None
    wrong = wire_auth.sign_message({"type": "assignment", "rank": 0},
                                   wire_auth.make_secret())
    assert wire_auth.verify_message(wrong, secret) is None


def test_no_secret_passthrough():
    msg = {"type": "register"}
    assert wire_auth.sign_message(msg, None) == msg
    assert wire_auth.verify_message(msg, None) == msg


# -- elastic driver rejects forged messages ---------------------------------


def test_elastic_driver_drops_unsigned_register(monkeypatch):
    from horovod_tpu.runner.elastic_driver import ElasticDriver

    monkeypatch.setenv(wire_auth.SECRET_ENV, wire_auth.make_secret())
    driver = ElasticDriver(command=["true"], discovery=None, min_np=1)
    host, port = driver._start_server()
    try:
        # unsigned register: the driver must close the socket unacted
        s = socket.create_connection(("127.0.0.1", port), timeout=10)
        s.sendall((json.dumps(
            {"type": "register", "worker_id": 0}) + "\n").encode())
        s.settimeout(10)
        assert s.recv(1) == b""  # server closed on us
        s.close()
        assert driver._notify_socks == {}

        # signed register: accepted and retained as the notify channel
        s2 = socket.create_connection(("127.0.0.1", port), timeout=10)
        s2.sendall((json.dumps(wire_auth.sign_message(
            {"type": "register", "worker_id": 0},
            wire_auth.job_secret())) + "\n").encode())
        deadline = time.time() + 10
        while time.time() < deadline and 0 not in driver._notify_socks:
            time.sleep(0.05)
        assert 0 in driver._notify_socks
        s2.close()
    finally:
        driver._shutdown = True
        driver._server.close()


# -- auth-mode mismatch fails fast ------------------------------------------


def test_auth_mode_mismatch_fails_fast():
    """A secret-carrying worker dialing a secret-less coordinator must
    reject the hello IMMEDIATELY with a clear error (the auth-mode flag
    byte), not hang until the rendezvous timeout.  Drives the native
    TcpTransport directly over ctypes against a fake coordinator socket —
    no jax, no fleet."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    lib_path = NATIVE_LIB
    if not os.path.exists(lib_path):
        pytest.skip("native core not built")
    code = f"""
import ctypes, sys, time
lib = ctypes.CDLL({lib_path!r})
lib.hvdtpu_init.restype = ctypes.c_int
lib.hvdtpu_init.argtypes = [
    ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
    ctypes.c_double, ctypes.c_longlong, ctypes.c_int, ctypes.c_char_p,
    ctypes.c_double, ctypes.c_double, ctypes.c_int, ctypes.c_char_p,
]
t0 = time.time()
rc = lib.hvdtpu_init(1, 2, b"127.0.0.1", {port}, 1.0, 1 << 20, 16, b"",
                     0.0, 0.0, 0, b"")
elapsed = time.time() - t0
print("RC", rc, "ELAPSED", elapsed, flush=True)
sys.exit(0 if rc != 0 and elapsed < 30 else 1)
"""
    env = native_child_env()
    env["HVD_TPU_SECRET"] = wire_auth.make_secret()
    proc = subprocess.Popen(
        [sys.executable, "-c", code], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        srv.settimeout(30)
        conn, _ = srv.accept()
        conn.settimeout(30)
        hello = b""
        while len(hello) < 5:  # rank(4) + auth flag(1)
            chunk = conn.recv(5 - len(hello))
            if not chunk:
                break
            hello += chunk
        assert struct.unpack("<i", hello[:4])[0] == 1
        assert hello[4:5] == b"\x01"  # worker advertises auth
        conn.sendall(b"\x00")         # coordinator: no secret
        out, err = proc.communicate(timeout=60)
        assert proc.returncode == 0, (out, err)
        assert "auth-mode mismatch" in err
        conn.close()
    finally:
        srv.close()
        if proc.poll() is None:
            proc.kill()
            proc.wait()


# -- steady-state frame MAC: tamper rejection --------------------------------


def _hmac(key: bytes, msg: bytes) -> bytes:
    import hashlib
    import hmac as _hmac_mod

    return _hmac_mod.new(key, msg, hashlib.sha256).digest()


def _frame_mac(key: bytes, direction: bytes, seq: int,
               payload: bytes) -> bytes:
    return _hmac(key, direction + struct.pack("<Q", seq) + payload)


def _recv_exact(conn, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError(f"EOF after {len(buf)}/{n} bytes")
        buf += chunk
    return buf


def test_steady_state_frame_tamper_rejected():
    """Round-5 ADVICE closure: frames AFTER the authenticated hello are
    MAC'd under a per-connection key derived from the challenge exchange.
    A fake coordinator that passes the full handshake (it knows the
    secret) but then corrupts one steady-state frame's MAC must kill the
    worker's transport — while a correctly MAC'd frame keeps it alive
    (proving the rejection is the tamper check, not protocol drift).
    Drives the native TcpTransport over ctypes; no jax, no fleet."""
    secret = wire_auth.make_secret()
    skey = secret.encode()
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    lib_path = NATIVE_LIB
    if not os.path.exists(lib_path):
        pytest.skip("native core not built")
    code = f"""
import ctypes, sys, time
lib = ctypes.CDLL({lib_path!r})
lib.hvdtpu_init.restype = ctypes.c_int
lib.hvdtpu_init.argtypes = [
    ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
    ctypes.c_double, ctypes.c_longlong, ctypes.c_int, ctypes.c_char_p,
    ctypes.c_double, ctypes.c_double, ctypes.c_int, ctypes.c_char_p,
]
rc = lib.hvdtpu_init(1, 2, b"127.0.0.1", {port}, 5.0, 1 << 20, 16, b"",
                     0.0, 0.0, 0, b"")
print("INIT", rc, flush=True)
if rc != 0:
    sys.exit(2)
deadline = time.time() + 60
while time.time() < deadline:
    if lib.hvdtpu_loop_dead():
        print("LOOP_DEAD", flush=True)
        lib.hvdtpu_shutdown()  # join the (dead) background loop cleanly
        sys.exit(0)
    time.sleep(0.05)
print("LOOP_STILL_ALIVE", flush=True)
sys.exit(3)
"""
    env = native_child_env()
    env["HVD_TPU_SECRET"] = secret
    proc = subprocess.Popen(
        [sys.executable, "-c", code], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        srv.settimeout(30)
        conn, _ = srv.accept()
        conn.settimeout(30)
        # ---- hello + mutual challenge-response (coordinator role) ----
        hello = _recv_exact(conn, 5)
        assert struct.unpack("<i", hello[:4])[0] == 1
        assert hello[4:5] == b"\x01"
        conn.sendall(b"\x01")  # we hold the secret too
        cw = _recv_exact(conn, 16)
        cr = os.urandom(16)
        conn.sendall(cr + _hmac(skey, b"coord" + cw))
        proof = _recv_exact(conn, 32)
        assert proof == _hmac(
            skey, b"rank" + struct.pack("<i", 1) + cr
        ), "worker's hello proof diverged from the documented wire"
        frame_key = _hmac(skey, b"frame" + cw + cr)

        # ---- steady state: worker sends one MAC'd request per cycle ----
        def read_worker_frame(expect_seq):
            (length,) = struct.unpack("<I", _recv_exact(conn, 4))
            payload = _recv_exact(conn, length)
            mac = _recv_exact(conn, 32)
            assert mac == _frame_mac(
                frame_key, b"W", expect_seq, payload
            ), "worker frame MAC diverged from the documented construction"
            return payload

        read_worker_frame(0)
        # control: a correctly MAC'd (empty) response keeps the loop alive
        conn.sendall(struct.pack("<I", 0)
                     + _frame_mac(frame_key, b"C", 0, b""))
        read_worker_frame(1)  # next cycle arrives => transport survived
        assert proc.poll() is None

        # tamper: same frame, one MAC bit flipped => transport must die
        bad = bytearray(_frame_mac(frame_key, b"C", 1, b""))
        bad[0] ^= 0x01
        conn.sendall(struct.pack("<I", 0) + bytes(bad))

        out, err = proc.communicate(timeout=60)
        assert proc.returncode == 0, (out, err)
        assert "LOOP_DEAD" in out
        assert "bad MAC" in err
        conn.close()
    finally:
        srv.close()
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_replayed_frame_rejected():
    """A validly MAC'd frame captured and re-sent must fail: the MAC is
    bound to the per-direction sequence number."""
    secret = wire_auth.make_secret()
    skey = secret.encode()
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    lib_path = NATIVE_LIB
    if not os.path.exists(lib_path):
        pytest.skip("native core not built")
    code = f"""
import ctypes, sys, time
lib = ctypes.CDLL({lib_path!r})
lib.hvdtpu_init.restype = ctypes.c_int
lib.hvdtpu_init.argtypes = [
    ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
    ctypes.c_double, ctypes.c_longlong, ctypes.c_int, ctypes.c_char_p,
    ctypes.c_double, ctypes.c_double, ctypes.c_int, ctypes.c_char_p,
]
rc = lib.hvdtpu_init(1, 2, b"127.0.0.1", {port}, 5.0, 1 << 20, 16, b"",
                     0.0, 0.0, 0, b"")
if rc != 0:
    sys.exit(2)
deadline = time.time() + 60
while time.time() < deadline:
    if lib.hvdtpu_loop_dead():
        lib.hvdtpu_shutdown()  # join the (dead) background loop cleanly
        sys.exit(0)
    time.sleep(0.05)
sys.exit(3)
"""
    env = native_child_env()
    env["HVD_TPU_SECRET"] = secret
    proc = subprocess.Popen(
        [sys.executable, "-c", code], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        srv.settimeout(30)
        conn, _ = srv.accept()
        conn.settimeout(30)
        _recv_exact(conn, 5)
        conn.sendall(b"\x01")
        cw = _recv_exact(conn, 16)
        cr = os.urandom(16)
        conn.sendall(cr + _hmac(skey, b"coord" + cw))
        _recv_exact(conn, 32)
        frame_key = _hmac(skey, b"frame" + cw + cr)

        def skip_worker_frame():
            (length,) = struct.unpack("<I", _recv_exact(conn, 4))
            _recv_exact(conn, length + 32)

        skip_worker_frame()
        first = struct.pack("<I", 0) + _frame_mac(frame_key, b"C", 0, b"")
        conn.sendall(first)          # valid at seq 0
        skip_worker_frame()
        conn.sendall(first)          # replay at seq 1: stale MAC
        out, err = proc.communicate(timeout=60)
        assert proc.returncode == 0, (out, err)
        assert "bad MAC" in err
        conn.close()
    finally:
        srv.close()
        if proc.poll() is None:
            proc.kill()
            proc.wait()


# -- native star rejects rogue peers ----------------------------------------


@pytest.mark.integration
@requires_multiprocess_collectives  # the 2-proc job runs real collectives
def test_native_star_rejects_secretless_peer():
    """A peer without the job secret must be rejected by rank 0's accept
    loop WITHOUT consuming the rank slot: the rogue sees EOF after its
    bad proof, and the authenticated 2-proc job still completes."""
    env = os.environ.copy()
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    secret = wire_auth.make_secret()

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    coord_port, native_port = free_port(), free_port()
    procs = []
    try:
        for rank in range(2):
            wenv = dict(env)
            wenv.update({
                "HVD_TPU_COORDINATOR": f"127.0.0.1:{coord_port}",
                "HVD_TPU_NATIVE_PORT": str(native_port),
                "HVD_TPU_NUM_PROCESSES": "2",
                "HVD_TPU_PROCESS_ID": str(rank),
                "HVD_TPU_LOCAL_RANK": str(rank),
                "HVD_TPU_LOCAL_SIZE": "2",
                "HVD_TPU_SECRET": secret,
            })
            procs.append(subprocess.Popen(
                [sys.executable, WORKER, "2"], env=wenv, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            ))

        # rogue: connect to the negotiation port as "rank 1" with a
        # garbage proof; must observe rejection (EOF), not admission
        rejected = False
        deadline = time.time() + 120
        while not rejected and time.time() < deadline:
            try:
                s = socket.create_connection(
                    ("127.0.0.1", native_port), timeout=1)
            except OSError:
                time.sleep(0.1)
                continue
            try:
                s.settimeout(10)
                s.sendall(struct.pack("<i", 1))       # claim rank 1
                s.sendall(b"\x01")                    # auth-mode flag: yes
                s.sendall(b"\x00" * 16)               # challenge Cw
                hdr = b""
                while len(hdr) < 49:                  # flag + Cr + proof
                    chunk = s.recv(49 - len(hdr))
                    if not chunk:
                        break
                    hdr += chunk
                if len(hdr) == 49:
                    assert hdr[0:1] == b"\x01"        # coord is secured
                    s.sendall(b"\x00" * 32)           # forged proof
                    if s.recv(1) == b"":
                        rejected = True
            except OSError:
                pass  # server tore the socket down mid-handshake: also
                # a rejection, but retry for the clean EOF observation
            finally:
                s.close()
            time.sleep(0.1)
        assert rejected, "rogue peer was never cleanly rejected"

        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, (out, err)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()


# -- fake-ssh launch path ----------------------------------------------------


_FAKE_SSH = """#!/bin/bash
# PATH-shimmed ssh (reference technique: mocked ssh in test/single/
# test_run.py): consume ssh flags, log host+command, exec locally.
args=()
while [[ $# -gt 0 ]]; do
  case "$1" in
    -o) shift 2;;
    -p) shift 2;;
    *) args+=("$1"); shift;;
  esac
done
host="${args[0]}"
cmd="${args[1]}"
printf '%s\\t%s\\n' "$host" "$cmd" >> "$FAKE_SSH_LOG"
exec bash -c "$cmd"
"""


@pytest.fixture
def fake_ssh(tmp_path, monkeypatch):
    bindir = tmp_path / "bin"
    bindir.mkdir()
    shim = bindir / "ssh"
    shim.write_text(_FAKE_SSH)
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    log = tmp_path / "ssh.log"
    log.write_text("")
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    monkeypatch.setenv("FAKE_SSH_LOG", str(log))
    return log


@pytest.mark.integration
@requires_multiprocess_collectives  # the 2-proc job runs real collectives
def test_launch_ssh_end_to_end(fake_ssh, monkeypatch):
    """_launch_ssh over two non-local 'hosts' (loopback aliases), driven
    through the shim: collectives must pass on both ranks, the secret and
    coordination env must travel in the remote command line, and rank 0
    must be addressed at the first host."""
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    knob_env = {
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "TF_CPP_MIN_LOG_LEVEL": "3",
        "LAUNCHER_WORKER_MULTIHOST": "1",
    }
    hosts = [("127.0.1.1", 1), ("127.0.2.1", 1)]
    rc = launch._launch_ssh(
        [sys.executable, WORKER, "2"], hosts, 2, knob_env,
        ssh_port=None, verbose=True, disable_native=False,
    )
    assert rc == 0
    lines = [ln for ln in fake_ssh.read_text().splitlines() if ln]
    assert len(lines) == 2
    assert [ln.split("\t")[0] for ln in lines] == ["127.0.1.1", "127.0.2.1"]
    for ln in lines:
        cmd = ln.split("\t", 1)[1]
        # env plumbing: coordinator on the FIRST host and the full
        # coordination set exported into the remote command — but the
        # secret must NOT be on the argv (world-readable cmdline); it
        # arrives via ssh stdin through the read/export preamble
        assert "HVD_TPU_COORDINATOR=127.0.1.1:" in cmd
        assert "HVD_TPU_SECRET=" not in cmd
        assert "IFS= read -r HVD_TPU_SECRET" in cmd
        assert "HVD_TPU_NUM_PROCESSES=2" in cmd
        assert f"cd {os.getcwd()}" in cmd
    ranks = sorted(
        int(ln.split("HVD_TPU_PROCESS_ID=", 1)[1].split()[0])
        for ln in lines
    )
    assert ranks == [0, 1]


@pytest.mark.integration
def test_launch_ssh_lockstep_reap(fake_ssh):
    """First nonzero exit must reap the remaining remote workers
    (monitor_lockstep on the ssh path): rank 1 exits 7 immediately while
    rank 0 would sleep for a minute — the launch must return 7 fast."""
    prog = ("import os,sys,time; "
            "sys.exit(7) if os.environ['HVD_TPU_PROCESS_ID']=='1' "
            "else time.sleep(60)")
    t0 = time.time()
    rc = launch._launch_ssh(
        [sys.executable, "-c", prog],
        [("127.0.1.1", 1), ("127.0.2.1", 1)], 2, {},
        ssh_port=None, verbose=False, disable_native=False,
    )
    assert rc == 7
    assert time.time() - t0 < 30
