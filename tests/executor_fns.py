"""Module-level functions for executor tests (must be plain-picklable —
the reference's Ray tests use module-level train fns the same way)."""


def rank_report(arg):
    import jax.numpy as jnp

    import horovod_tpu as hvd

    out = hvd.allreduce(jnp.ones(()), op=hvd.Sum)
    return {
        "rank": hvd.cross_rank(),
        "world": hvd.cross_size(),
        "allreduce_sum": float(out),
        "arg": arg,
    }
