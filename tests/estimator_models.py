"""Module-level models for estimator tests (estimator specs are pickled
into worker subprocesses, so models must be importable — the same
constraint the reference's cloudpickled Spark estimators have on
lambda-free models)."""

import flax.linen as nn
import torch


class TinyMLP(nn.Module):
    features: int = 3

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(16)(x)
        x = nn.relu(x)
        return nn.Dense(self.features)(x)


class TinyTorchNet(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.fc = torch.nn.Linear(4, 1)

    def forward(self, x):
        return self.fc(x).squeeze(-1)
