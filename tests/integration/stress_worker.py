"""Randomized negotiation stress worker.

Reference analog: the controller's job is to make progress when ranks
submit the same set of collectives in DIFFERENT orders with skewed
timing (gradients become ready in autograd order, which differs across
ranks) — SURVEY.md §3.2 and §5.2 (the StallInspector's "distributed
race" is exactly cross-rank submission divergence).  This worker builds
one shared schedule of mixed collectives from a fixed seed, then each
rank submits it asynchronously in its OWN shuffled order with random
delays,
synchronizes in yet another order, and checks every result against a
locally computed expectation.  Two rounds reuse the same tensor names so
round 2 runs entirely on the ResponseCache bit-vector bypass.

NATIVE PATH ONLY: out-of-order submission tolerance is exactly what the
C++ negotiation controller provides.  Under ``--disable-native`` eager
collectives execute in SPMD program order and this schedule would (by
design) deadlock — see docs/running.md.
"""

import os
import random
import sys
import time

import jax.numpy as jnp
import numpy as np

import horovod_tpu as hvd

# schedule length / seed are env-tunable so CI can run a short leg on
# every change and a longer seeded soak (HVD_TPU_STRESS_OPS=200+) in the
# slow lane without editing the worker
N_OPS = int(os.environ.get("HVD_TPU_STRESS_OPS", "40"))
SEED = int(os.environ.get("HVD_TPU_STRESS_SEED", "1234"))


# comparison tolerance per wire dtype (low-precision sums accumulate
# rounding; min/max/broadcast/gather values are chosen exactly
# representable, but the engine may accumulate in the wire dtype)
TOL = {"float32": 1e-5, "int32": 0.0, "bfloat16": 0.05, "float16": 0.02}


def payload(i, r, shape, dtype, rnd):
    base = (np.arange(int(np.prod(shape))).reshape(shape) + 1.0) * (r + 1)
    base = base + i + 1000.0 * rnd
    if dtype in ("bfloat16", "float16"):
        # round to the wire dtype's grid, represented in f32, so local
        # expectations start from the exact values the wire carries
        return np.asarray(jnp.asarray(base, dtype).astype(jnp.float32))
    return base.astype(dtype)


def build_schedule(world):
    rng = random.Random(SEED)
    sched = []
    for i in range(N_OPS):
        kind = rng.choice(
            ["allreduce", "allreduce", "allreduce", "grouped",
             "broadcast", "allgather", "reducescatter", "ps_allreduce",
             "alltoall"]
        )
        shape = tuple(rng.choice([1, 2, 3, 5]) for _ in range(rng.randint(1, 2)))
        dtype = rng.choice(
            ["float32", "int32", "float32", "bfloat16", "float16"])
        op = rng.choice(["sum", "avg", "min", "max"])
        if dtype == "int32" and op == "avg":
            op = "sum"
        root = rng.randrange(world)
        k = rng.randint(2, 3)
        m = rng.randint(1, 2)
        sched.append(dict(i=i, kind=kind, shape=shape, dtype=dtype,
                          op=op, root=root, k=k, m=m))
    return sched


def reduce_expected(arrs, op):
    stack = np.stack(arrs)
    if op == "sum":
        return stack.sum(axis=0)
    if op == "avg":
        return stack.mean(axis=0)
    if op == "min":
        return stack.min(axis=0)
    return stack.max(axis=0)


def hvd_op(op):
    return {"sum": hvd.Sum, "avg": hvd.Average,
            "min": hvd.Min, "max": hvd.Max}[op]


def submit(entry, rank, world, members, ps, rnd):
    """Submit one schedule entry asynchronously; returns
    (handle, expected, kind, tol) or None if this rank doesn't
    participate.  Low-precision entries travel as bf16/fp16 on the wire;
    expectations are computed from the rounded values."""
    i, kind, shape, dtype = (entry["i"], entry["kind"], entry["shape"],
                             entry["dtype"])
    tol = TOL[dtype]
    name = f"stress.{i}"

    def wire(arr):
        return jnp.asarray(arr).astype(dtype)

    if kind == "allreduce":
        h = hvd.allreduce_async(wire(payload(i, rank, shape, dtype, rnd)),
                                op=hvd_op(entry["op"]), name=name)
        exp = reduce_expected(
            [payload(i, r, shape, dtype, rnd) for r in range(world)],
            entry["op"])
        return h, exp, kind, tol
    if kind == "grouped":
        xs = [wire(payload(i, rank, shape, dtype, rnd) + j)
              for j in range(entry["k"])]
        h = hvd.grouped_allreduce_async(xs, op=hvd_op(entry["op"]),
                                        name=name)
        exp = [reduce_expected(
            [payload(i, r, shape, dtype, rnd) + j for r in range(world)],
            entry["op"]) for j in range(entry["k"])]
        return h, exp, kind, tol
    if kind == "broadcast":
        h = hvd.broadcast_async(wire(payload(i, rank, shape, dtype, rnd)),
                                root_rank=entry["root"], name=name)
        exp = payload(i, entry["root"], shape, dtype, rnd)
        return h, exp, kind, 0.0  # broadcast is bit-exact in any dtype
    if kind == "allgather":
        rows = 1 + (i + rank) % 3  # uneven dim0 across ranks
        x = wire(np.full((rows, 2), float(i + rank + rnd), np.float32)
                 if dtype != "int32"
                 else np.full((rows, 2), i + rank + rnd, np.int32))
        h = hvd.allgather_async(x, name=name)
        exp = np.concatenate([
            np.full((1 + (i + r) % 3, 2), i + r + rnd, np.float64)
            for r in range(world)])  # small ints: exact in every dtype
        return h, exp, kind, 0.0
    if kind == "reducescatter":
        shape2 = (world * entry["m"], 3)
        h = hvd.reducescatter_async(
            wire(payload(i, rank, shape2, dtype, rnd)), op=hvd.Sum,
            name=name)
        total = reduce_expected(
            [payload(i, r, shape2, dtype, rnd) for r in range(world)],
            "sum")
        exp = total[rank * entry["m"]:(rank + 1) * entry["m"]]
        return h, exp, kind, tol
    if kind == "alltoall":
        # per-rank uneven splits: the coordinator negotiates the full
        # send matrix, so skewed submission stresses that exchange too.
        # Low-precision dtypes ride it too: alltoall is pure data
        # movement, so wire-rounded values come back bit-exact.
        def rounded(val):
            return float(np.asarray(
                jnp.asarray(float(val), dtype).astype(jnp.float32)))

        if dtype == "int32":
            rounded = float  # noqa: F811 — ints are exact
        splits = [1 + (i + rank + d) % 2 for d in range(world)]
        rows = []
        for d, s in enumerate(splits):
            rows += [[rounded(i + rank + 3 * d + rnd)] * 2] * s
        x = wire(np.asarray(rows, dtype="float32"))
        h = hvd.alltoall_async(x, splits=splits, name=name)
        exp_rows = []
        for src in range(world):
            s_src = 1 + (i + src + rank) % 2
            exp_rows += [[rounded(i + src + 3 * rank + rnd)] * 2] * s_src
        exp = np.asarray(exp_rows, dtype="float64")
        return h, exp, kind, 0.0
    # ps_allreduce: only the subset's members participate
    if rank not in members:
        return None
    x = jnp.asarray(payload(i, rank, shape, "float32", rnd))
    h = hvd.allreduce_async(x, op=hvd.Sum, name=name, process_set=ps)
    exp = reduce_expected(
        [payload(i, r, shape, "float32", rnd) for r in members], "sum")
    return h, exp, kind, TOL["float32"]


def main():
    hvd.init()
    world = hvd.cross_size()
    rank = hvd.rank()
    assert world == int(sys.argv[1]), (world, sys.argv)
    assert hvd.size() == world, "stress worker expects 1 device/process"

    members = sorted({0, world - 1})
    # a subset equal to the world is the global set (np=1 smoke runs)
    ps = (hvd.add_process_set(members) if len(members) < world
          else hvd.global_process_set)
    sched = build_schedule(world)

    for rnd in range(2):  # round 2 = steady-state ResponseCache bypass
        order = list(sched)
        random.Random(SEED * 31 + rank * 7 + rnd).shuffle(order)
        jitter = random.Random(SEED * 101 + rank * 13 + rnd)
        pending = []
        for entry in order:
            got = submit(entry, rank, world, members, ps, rnd)
            if got is not None:
                pending.append((entry["i"], got))
            if jitter.random() < 0.3:
                time.sleep(jitter.random() * 0.003)
        # synchronize in yet another per-rank order
        random.Random(SEED * 977 + rank * 3 + rnd).shuffle(pending)
        for i, (h, exp, kind, tol) in pending:
            out = hvd.synchronize(h)
            if kind == "alltoall" and isinstance(out, tuple):
                out = out[0]  # (received, recv_splits)

            def check(o, e):
                np.testing.assert_allclose(
                    np.asarray(o, dtype=np.float64), np.asarray(e, np.float64),
                    rtol=max(tol, 1e-6), atol=tol, err_msg=f"op {i}")

            if kind == "grouped":
                for o, e in zip(out, exp):
                    check(o, e)
            else:
                check(out, exp)

    if ps is not hvd.global_process_set:
        hvd.remove_process_set(ps)

    if world > 1:
        # process-set churn under traffic: add/use/remove sets repeatedly
        # while world-set ops are in flight — registration is symmetric
        # but interleaves arbitrarily with negotiation cycles
        churn_jitter = random.Random(SEED * 7 + rank)
        for c in range(6):
            pending = [
                hvd.allreduce_async(
                    jnp.full((3,), float(rank + c)), op=hvd.Sum,
                    name=f"churn.bg.{c}")
            ]
            churn_members = sorted({c % world, world - 1})
            if len(churn_members) == world:  # dup of the global set
                churn_members = [world - 1]
            sub = hvd.add_process_set(churn_members)
            if churn_jitter.random() < 0.5:
                time.sleep(churn_jitter.random() * 0.002)
            if sub.included(rank):
                got = hvd.allreduce(
                    jnp.full((2,), float(rank + 1)), op=hvd.Sum,
                    name=f"churn.ps.{c}", process_set=sub)
                exp = sum(r + 1 for r in churn_members)
                np.testing.assert_allclose(np.asarray(got),
                                           np.full(2, float(exp)))
            for h in pending:
                out = hvd.synchronize(h)
                exp_bg = sum(r + c for r in range(world))
                np.testing.assert_allclose(np.asarray(out),
                                           np.full(3, float(exp_bg)))
            # remove-after-quiesce contract (docs/process_sets.md): a set
            # may only be removed once no member still has ops in flight
            # on it — removal mid-negotiation reverts membership to the
            # world and the op would wait on non-members forever
            hvd.barrier()
            hvd.remove_process_set(sub)

        # negative leg: a grouped call whose MEMBERSHIP disagrees across
        # ranks (2 members on rank 0, 3 elsewhere) must raise cleanly on
        # every rank — including the orphan member only some ranks hold —
        # instead of deadlocking the completeness filter
        k = 2 if rank == 0 else 3
        xs = [jnp.ones((2,)) for _ in range(k)]
        try:
            hvd.grouped_allreduce(xs, name="bad_group")
        except hvd.HorovodInternalError:
            pass
        else:
            raise AssertionError("mismatched grouped call did not raise")
        # ...and an IMMEDIATE retry of the corrected group under the SAME
        # name must succeed: the per-call nonce in the group key means the
        # old error cannot poison it (no sleep needed)
        outs = hvd.grouped_allreduce(
            [jnp.ones((2,)) * (rank + 1), jnp.ones((2,)) * 10.0],
            op=hvd.Sum, name="bad_group")
        np.testing.assert_allclose(
            np.asarray(outs[0]), np.full(2, world * (world + 1) / 2))
        np.testing.assert_allclose(
            np.asarray(outs[1]), np.full(2, 10.0 * world))

    print(f"STRESS_OK rank={rank}", flush=True)


if __name__ == "__main__":
    main()
