"""Per-rank torch-adapter worker for launcher integration tests.

Reference analog: test/parallel/test_torch.py under ``horovodrun -np 2``
(SURVEY.md §4) — cross-process collectives on torch tensors, grouped
ops, SyncBatchNorm global statistics, and gradient flow through the
differentiable stats allreduce.
"""

import sys

import numpy as np
import torch

import horovod_tpu.torch as hvd


def main():
    hvd.init()
    nproc = hvd.cross_size()
    assert nproc == int(sys.argv[1]), (nproc, sys.argv)
    me = hvd.cross_rank()

    # average + grouped ops across ranks
    out = hvd.allreduce(torch.tensor([float(me)]))
    np.testing.assert_allclose(out.numpy(), [np.mean(np.arange(nproc))])
    outs = hvd.grouped_allreduce(
        [torch.ones(2) * (me + 1), torch.full((3,), float(me))],
        op=hvd.Sum, name="torch_grouped",
    )
    np.testing.assert_allclose(
        outs[0].numpy(), np.full(2, nproc * (nproc + 1) / 2)
    )
    np.testing.assert_allclose(
        outs[1].numpy(), np.full(3, sum(range(nproc)))
    )

    # alltoall with uneven splits: rank r sends c+1 rows tagged 10r+c
    send = torch.cat([
        torch.full((c + 1,), 10.0 * me + c) for c in range(nproc)
    ])
    recv, rsplits = hvd.alltoall(
        send, splits=torch.tensor([c + 1 for c in range(nproc)]),
        name="torch_a2a",
    )
    assert rsplits.tolist() == [me + 1] * nproc
    np.testing.assert_allclose(
        recv.numpy(),
        np.concatenate([np.full(me + 1, 10.0 * p + me)
                        for p in range(nproc)]),
    )

    # SyncBatchNorm: global stats over per-rank constant batches.
    # Rank r feeds (r+1); global mean = mean(1..n), var likewise.
    bn = hvd.SyncBatchNorm(1, eps=0.0, affine=False, momentum=1.0)
    bn.train()
    x = torch.full((2, 1, 3), float(me + 1), requires_grad=True)
    out = bn(x)
    vals = np.arange(1, nproc + 1)
    g_mean = vals.mean()
    g_var = ((vals - g_mean) ** 2).mean()
    expected = (x.detach().numpy() - g_mean) / np.sqrt(g_var) \
        if nproc > 1 else np.zeros_like(x.detach().numpy())
    np.testing.assert_allclose(out.detach().numpy(), expected,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(bn.running_mean.numpy(), [g_mean],
                               rtol=1e-5)
    # gradient flows through the differentiable stats allreduce
    out.sum().backward()
    assert x.grad is not None and np.isfinite(x.grad.numpy()).all()

    hvd.barrier()
    print(f"TORCH_WORKER_OK rank={hvd.rank()} nproc={nproc}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
