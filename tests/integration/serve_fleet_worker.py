#!/usr/bin/env python
"""Standalone serving-fleet worker for the chaos soak's serve-recover
scenario (tools/chaos_soak.py).

Builds a 3-replica ``FleetRouter`` over a tiny CPU transformer, submits
a templated request load (the shared-prompt production shape), drives
the fleet to drain, and writes a JSON report: every request's token
stream plus the router's recovery/hedge bookkeeping.

The CONTROL run gets no chaos env and must complete every request.
The CHAOTIC run gets ``HVD_TPU_CHAOS=serve.replica_step:raise,at=K``
(+ ``HVD_TPU_FLEET_REPLICA_ERRORS=1``): the K-th replica step dies
mid-burst, the router ejects that replica and re-disperses its work —
warm from the live KV export where blocks are verified, cold
re-prefill otherwise.  The soak driver asserts the two runs'
token streams are BIT-IDENTICAL and no request was lost.

Usage: serve_fleet_worker.py OUT.json N_REQUESTS SEED
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from horovod_tpu import chaos  # noqa: E402
from horovod_tpu.fleet.router import FleetRouter  # noqa: E402
from horovod_tpu.models.transformer import (  # noqa: E402
    Transformer, TransformerConfig,
)
from horovod_tpu.serving import ServeConfig, ServingEngine  # noqa: E402


def main():
    out_path, n_requests, seed = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
    chaos.install_from_env(rank=0)

    cfg = TransformerConfig(
        vocab_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=96, dtype=jnp.float32,
        attention_impl="dot", causal=True)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32), train=False)["params"]
    serve_kw = dict(block_size=16, num_blocks=0, token_budget=256,
                    watermark=2, prefill_tiers=(64,), decode_tiers=(1, 2, 4),
                    prefill_chunk=16)

    def build():
        return ServingEngine(cfg, params, serve=ServeConfig(**serve_kw))

    router = FleetRouter(build, replicas=3, mode="affinity")

    # templated load: N requests over 4 shared 40-token templates with
    # short random suffixes — identical across control/chaotic runs
    # (same seed), so streams must match byte for byte
    rs = np.random.RandomState(seed)
    temps = [rs.randint(1, 120, size=40).astype(np.int32) for _ in range(4)]
    load = []
    for _ in range(n_requests):
        t = temps[int(rs.randint(len(temps)))]
        sfx = rs.randint(1, 120,
                         size=int(rs.randint(2, 9))).astype(np.int32)
        load.append((np.concatenate([t, sfx]), int(rs.randint(2, 7))))

    gids = [router.submit(p, g, arrival=float(i))
            for i, (p, g) in enumerate(load)]
    router.run_until_drained()

    out = {
        "requests": n_requests,
        "results": {str(g): np.asarray(router.results[g]).tolist()
                    for g in gids if g in router.results},
        "lost": [int(g) for g in gids if g not in router.results],
        "recovery": [{"path": x["path"], "ms": x["ms"]}
                     for x in router.recovery],
        "migration_ms": router.migration_ms(),
        "hedge_rate": router.hedge_rate(),
        "compile_free": bool(router.all_compile_free()),
        "replicas_retired": len(router.retired),
    }
    with open(out_path, "w") as f:
        json.dump(out, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
