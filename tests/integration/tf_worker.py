"""Per-rank TensorFlow-adapter worker for launcher integration tests.

Reference analog: test/parallel/test_tensorflow.py run under
``horovodrun -np 2`` (SURVEY.md §4) — the same script executes on every
rank; collective results are asserted against locally computed
expectations.  Exercises the tf.Tensor bridge over the REAL multi-process
negotiated engine, plus DistributedGradientTape gradient averaging and
Keras optimizer weight consistency across ranks.
"""

import os
import sys

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import numpy as np  # noqa: E402
import tensorflow as tf  # noqa: E402

import horovod_tpu.tensorflow as hvd  # noqa: E402


def main():
    hvd.init()
    nproc = hvd.cross_size()
    assert nproc == int(sys.argv[1]), (nproc, sys.argv)
    me = hvd.cross_rank()

    # average of per-process values
    out = hvd.allreduce(tf.constant([float(me)]))
    np.testing.assert_allclose(out.numpy(), [np.mean(np.arange(nproc))],
                               rtol=1e-6)

    # sum with prescale, int dtype
    out = hvd.allreduce(tf.constant([1, 2], tf.int64), op=hvd.Sum,
                        name="tf_int_sum")
    np.testing.assert_array_equal(out.numpy(), [nproc, 2 * nproc])

    # allreduce inside tf.function (py_function bridge under tracing)
    @tf.function
    def compiled(x):
        return hvd.allreduce(x, op=hvd.Sum, name="tf_graph_sum")

    out = compiled(tf.constant([float(me + 1)]))
    np.testing.assert_allclose(out.numpy(), [nproc * (nproc + 1) / 2])

    # uneven allgather: rank r contributes r+1 rows
    rows = tf.fill((me + 1, 2), float(me))
    out = hvd.allgather(rows, name="tf_uneven_ag")
    expected = np.concatenate(
        [np.full((r + 1, 2), float(r)) for r in range(nproc)]
    )
    np.testing.assert_allclose(out.numpy(), expected)

    # broadcast_variables: non-root starts different, ends with root's
    v = tf.Variable([float(me + 1), -float(me)])
    hvd.broadcast_variables([v], root_rank=0)
    np.testing.assert_allclose(v.numpy(), [1.0, 0.0])

    # DistributedGradientTape: per-rank losses, averaged gradients
    w = tf.Variable([2.0])
    with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = w[0] * float(me + 1)  # d/dw = me+1
    g = tape.gradient(loss, [w])[0]
    np.testing.assert_allclose(
        g.numpy(), [np.mean(np.arange(1, nproc + 1))], rtol=1e-6
    )

    # Keras DistributedOptimizer: ranks start identical, see different
    # grads, and must stay in lockstep after the averaged update
    import keras

    keras.utils.set_random_seed(7)  # identical init on every rank
    model = keras.Sequential([keras.Input(shape=(3,)),
                              keras.layers.Dense(2)])
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(0.1))
    x = np.full((4, 3), float(me + 1), np.float32)
    y = np.zeros((4, 2), np.float32)
    with tf.GradientTape() as tape:
        pred = model(x, training=True)
        loss = tf.reduce_mean((pred - y) ** 2)
    grads = tape.gradient(loss, model.trainable_variables)
    opt.apply(grads, model.trainable_variables)
    digest = hvd.allgather_object(
        [np.asarray(w).sum() for w in model.get_weights()]
    )
    for other in digest[1:]:
        np.testing.assert_allclose(digest[0], other, rtol=1e-5)

    # SyncBatchNorm: stats over the GLOBAL batch.  Rank r feeds constant
    # (r+1); global mean over ranks' equal-sized batches = mean(1..nproc)
    sbn = hvd.SyncBatchNormalization(axis=-1, epsilon=0.0, center=False,
                                     scale=False, momentum=0.0)
    xb = np.full((2, 3, 1), float(me + 1), np.float32)
    out = sbn(xb, training=True)
    g_mean = np.mean(np.arange(1, nproc + 1))
    g_var = np.mean((np.arange(1, nproc + 1) - g_mean) ** 2)
    np.testing.assert_allclose(
        np.asarray(out),
        (xb - g_mean) / np.sqrt(g_var) if nproc > 1 else np.zeros_like(xb),
        rtol=1e-4, atol=1e-4,
    )
    np.testing.assert_allclose(
        np.asarray(sbn.moving_mean), [g_mean], rtol=1e-5
    )

    # metric averaging
    from horovod_tpu.keras.callbacks import MetricAverageCallback

    logs = {"loss": float(me)}
    MetricAverageCallback().on_epoch_end(0, logs)
    np.testing.assert_allclose(logs["loss"], np.mean(np.arange(nproc)))

    # jit_compile=True: collectives lower through the XLA custom-call
    # bridge (reference: xla_mpi_ops.cc), negotiating with peers from
    # INSIDE a compiled program
    from horovod_tpu.tensorflow import xla_ops

    if xla_ops.available():
        @tf.function(jit_compile=True)
        def jit_step(x):
            s = hvd.allreduce(x, op=hvd.Sum, name="tf_jit_sum")
            b = hvd.broadcast(x, root_rank=0, name="tf_jit_bcast")
            return s, b

        s, b = jit_step(tf.constant([float(me + 1), 1.0]))
        np.testing.assert_allclose(
            s.numpy(), [nproc * (nproc + 1) / 2, nproc], rtol=1e-6)
        np.testing.assert_allclose(b.numpy(), [1.0, 1.0], rtol=1e-6)

        # jit-compiled train step with DistributedGradientTape: the exact
        # scenario the reference built XLA ops for
        wj = tf.Variable([2.0, -1.0])

        @tf.function(jit_compile=True)
        def jit_train_step(scale):
            with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
                loss = tf.reduce_sum(wj * scale)
            return tape.gradient(loss, [wj])[0]

        gj = jit_train_step(tf.constant(float(me + 1)))
        np.testing.assert_allclose(
            gj.numpy(), np.full(2, np.mean(np.arange(1, nproc + 1))),
            rtol=1e-6)
        print(f"TF_WORKER_XLA_OK rank={hvd.rank()}")
    else:
        print("TF_WORKER_XLA_SKIPPED (bridge unavailable)")

    hvd.barrier()
    print(f"TF_WORKER_OK rank={hvd.rank()} nproc={nproc}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
