"""Chaos-soak worker: commit-driven elastic training under fault injection.

Reference analog: the training scripts of elastic_common.py (SURVEY.md
§4), extended with the round-7 fault-tolerance machinery: per-batch
``state.commit()`` (the chaos ``elastic.commit`` injection point and the
controller-liveness poll), per-batch rank-0 state checkpoints, and
``enable_auto_resume`` so a REPLACEMENT worker — spawned fresh after
chaos kills a member, with no exec-restart snapshot to inherit — resumes
from the fleet's newest checkpoint instead of step 0.

Usage: chaos_worker.py <logdir> <batches> <ckpt_dir>

Env:
  HVD_TPU_SOAK_LOCAL_SYNC=1   use a per-worker state (sync() = save only).
      Needed on hosts whose jax cannot run multi-process XLA collectives
      (CPU backend < jax 0.5): the control plane (rendezvous, native
      negotiation, heartbeats, exec-restart recovery) is fully exercised,
      only the cross-worker state broadcast is skipped.  On real TPU
      fleets leave it unset.

Every batch "trains" by incrementing ``weight`` by exactly 1, so after
any fault/recovery dance the final weight must equal the batch count —
lost or duplicated work is arithmetically visible.
"""

import json
import os
import sys
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import checkpoint as hvd_checkpoint


def log(logdir, **kv):
    wid = os.environ.get("HVD_TPU_ELASTIC_WORKER_ID", "na")
    with open(os.path.join(logdir, f"worker_{wid}.log"), "a") as f:
        f.write(json.dumps(kv) + "\n")


class LocalSyncState(hvd.elastic.TpuState):
    """Per-worker state: every worker is its own authority (no rank-0
    broadcast).  For workloads/hosts where cross-worker sync is either
    unwanted or unavailable; recovery still flows through commits,
    snapshots and checkpoints."""

    def sync(self):
        self.save()


def main():
    logdir, batches, ckpt_dir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    hvd.init()

    cls = (LocalSyncState
           if os.environ.get("HVD_TPU_SOAK_LOCAL_SYNC") == "1"
           else hvd.elastic.TpuState)
    state = cls(step=0, weight=np.zeros(()))
    state.enable_auto_resume(ckpt_dir, step_attr="step")

    # preemption guard (docs/FLEET.md): SIGTERM (or a fleet.preempt
    # chaos drill) -> planned snapshot -> clean leave; the logged
    # "leave" event carries the planned_s the soak bounds
    from horovod_tpu.fleet.preemption import PreemptionGuard

    PreemptionGuard(
        state,
        on_leave=lambda info: log(logdir, event="leave",
                                  rank=hvd.cross_rank(), **info),
    ).install()

    log(logdir, event="init", rank=hvd.cross_rank(), world=hvd.cross_size(),
        pid=os.getpid())

    def on_reset():
        log(logdir, event="reset", world=hvd.cross_size(),
            step=int(state.step))

    state.register_reset_callbacks([on_reset])

    @hvd.elastic.run
    def train(state):
        # first visible step after boot/reset: >0 here on a FRESH worker
        # proves checkpoint auto-resume kicked in (it had no snapshot)
        from horovod_tpu.elastic import worker as _ew

        stats = _ew.last_restart_stats
        log(logdir, event="boot", step=int(state.step),
            rank=hvd.cross_rank(), world=hvd.cross_size(),
            restart_total_s=(stats["total_s"] if stats else None))
        while state.step < batches:
            state.weight = np.asarray(state.weight) + 1.0
            state.step = int(state.step) + 1
            state.commit()
            if hvd.cross_rank() == 0:
                hvd_checkpoint.save_state_checkpoint(
                    ckpt_dir, state, state.step)
            log(logdir, event="batch", step=state.step,
                weight=float(state.weight), rank=hvd.cross_rank(),
                world=hvd.cross_size())
            time.sleep(0.05)
        return float(state.weight)

    final = train(state)
    assert abs(final - batches) < 1e-6, (final, batches)
    log(logdir, event="done", weight=final, step=int(state.step),
        world=hvd.cross_size(), rank=hvd.cross_rank())


if __name__ == "__main__":
    main()
