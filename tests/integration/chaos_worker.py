"""Chaos-soak worker: commit-driven elastic training under fault injection.

Reference analog: the training scripts of elastic_common.py (SURVEY.md
§4), extended with the round-7 fault-tolerance machinery: per-batch
``state.commit()`` (the chaos ``elastic.commit`` injection point and the
controller-liveness poll), per-batch rank-0 state checkpoints, and
``enable_auto_resume`` so a REPLACEMENT worker — spawned fresh after
chaos kills a member, with no exec-restart snapshot to inherit — resumes
from the fleet's newest checkpoint instead of step 0.

Usage: chaos_worker.py <logdir> <batches> <ckpt_dir>

Env:
  HVD_TPU_SOAK_LOCAL_SYNC=1   use a per-worker state (sync() = save only).
      Needed on hosts whose jax cannot run multi-process XLA collectives
      (CPU backend < jax 0.5): the control plane (rendezvous, native
      negotiation, heartbeats, exec-restart recovery) is fully exercised,
      only the cross-worker state broadcast is skipped.  On real TPU
      fleets leave it unset.
  HVD_TPU_GUARD=1             arm the silent-corruption guard (guard.py):
      every step's increment rides guard.tap_grads (the guard.grad chaos
      site — a flipbit rule here IS the SDC drill) and its digest joins
      the agreement window; at HVD_TPU_GUARD_CADENCE the ranks exchange
      windows over the HVD_TPU_GUARD_BOARD directory, attribute any
      mismatch (recompute vote: the increment is deterministic), and the
      attributed rank quarantines while survivors roll back to the last
      verified checkpoint.  The 'guard' / 'rollback_done' log events
      carry what the sdc soak scenario asserts.

Every batch "trains" by incrementing ``weight`` by exactly 1, so after
any fault/recovery dance the final weight must equal the batch count —
lost or duplicated work is arithmetically visible.
"""

import json
import os
import sys
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import checkpoint as hvd_checkpoint
from horovod_tpu import trace as hvd_trace
from horovod_tpu.trace import export as trace_export


def log(logdir, **kv):
    wid = os.environ.get("HVD_TPU_ELASTIC_WORKER_ID", "na")
    with open(os.path.join(logdir, f"worker_{wid}.log"), "a") as f:
        f.write(json.dumps(kv) + "\n")


class LocalSyncState(hvd.elastic.TpuState):
    """Per-worker state: every worker is its own authority (no rank-0
    broadcast).  For workloads/hosts where cross-worker sync is either
    unwanted or unavailable; recovery still flows through commits,
    snapshots and checkpoints."""

    def sync(self):
        self.save()


def main():
    logdir, batches, ckpt_dir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    hvd.init()

    cls = (LocalSyncState
           if os.environ.get("HVD_TPU_SOAK_LOCAL_SYNC") == "1"
           else hvd.elastic.TpuState)
    state = cls(step=0, weight=np.zeros(()))
    state.enable_auto_resume(ckpt_dir, step_attr="step")

    # silent-corruption guard (docs/FAULT_TOLERANCE.md): armed by
    # HVD_TPU_GUARD=1 — constructed AFTER init so world/rank are live,
    # and before training so a rollback restart books its wall time
    from horovod_tpu import guard as hvd_guard

    iguard = hvd_guard.IntegrityGuard.from_env(
        world=hvd.cross_size(), rank=hvd.cross_rank(), ckpt_dir=ckpt_dir)
    if iguard.last_rollback_s is not None:
        log(logdir, event="rollback_done",
            rollback_s=iguard.last_rollback_s, rank=hvd.cross_rank())

    # preemption guard (docs/FLEET.md): SIGTERM (or a fleet.preempt
    # chaos drill) -> planned snapshot -> clean leave; the logged
    # "leave" event carries the planned_s the soak bounds
    from horovod_tpu.fleet.preemption import PreemptionGuard

    PreemptionGuard(
        state,
        on_leave=lambda info: log(logdir, event="leave",
                                  rank=hvd.cross_rank(), **info),
    ).install()

    log(logdir, event="init", rank=hvd.cross_rank(), world=hvd.cross_size(),
        pid=os.getpid())

    def on_reset():
        log(logdir, event="reset", world=hvd.cross_size(),
            step=int(state.step))

    state.register_reset_callbacks([on_reset])

    @hvd.elastic.run
    def train(state):
        # first visible step after boot/reset: >0 here on a FRESH worker
        # proves checkpoint auto-resume kicked in (it had no snapshot)
        from horovod_tpu.elastic import worker as _ew

        stats = _ew.last_restart_stats
        log(logdir, event="boot", step=int(state.step),
            rank=hvd.cross_rank(), world=hvd.cross_size(),
            restart_total_s=(stats["total_s"] if stats else None))
        clean_inc = np.ones((), np.float32)
        while state.step < batches:
            # train.step spans with GLOBAL step args: the anchors the
            # cross-rank trace merge aligns clocks on (docs/TRACING.md)
            with hvd_trace.span("train.step", step=int(state.step) + 1):
                inc = clean_inc
                if iguard.enabled:
                    # the guard.grad chaos site: a flipbit rule here IS
                    # the silent-corruption drill — the (possibly lying)
                    # value is what this "chip" hands the training step
                    inc = iguard.tap_grads(clean_inc)
                state.weight = np.asarray(state.weight) + inc
                state.step = int(state.step) + 1
                state.commit()
            if hvd.cross_rank() == 0:
                # with the guard armed the ring must outlive a full
                # agreement window: a rollback discards every
                # checkpoint newer than the last VERIFIED step, and a
                # ring shallower than the cadence would then be empty
                # (guard.py rollback docstring)
                keep = max(3, 2 * iguard.cadence) if iguard.enabled else 3
                hvd_checkpoint.save_state_checkpoint(
                    ckpt_dir, state, state.step, keep=keep)
            log(logdir, event="batch", step=state.step,
                weight=float(state.weight), rank=hvd.cross_rank(),
                world=hvd.cross_size())
            if iguard.enabled:
                iguard.observe_grads(
                    state.step, hvd_guard.host_digest([inc]))
                if iguard.due(state.step):
                    verdict = iguard.check(
                        state.step, loss=float(state.weight),
                        param_digest=hvd_guard.host_digest(
                            [iguard.tap_params(np.asarray(state.weight))]),
                        # the "sampled microbatch" recompute: the step's
                        # gradient is deterministic, so any window step
                        # re-derives exactly — the redundant-recompute
                        # attribution vote
                        recompute=lambda s: hvd_guard.host_digest(
                            [clean_inc]))
                    log(logdir, event="guard", step=state.step,
                        kind=verdict.kind, ok=verdict.ok,
                        attributed=verdict.attributed,
                        self_attributed=verdict.self_attributed,
                        divergent_step=verdict.divergent_step,
                        spike=verdict.spike, rank=hvd.cross_rank(),
                        verified=iguard.last_verified_step)
                    iguard.respond(verdict, state=state)
            time.sleep(0.05)
        return float(state.weight)

    final = train(state)
    assert abs(final - batches) < 1e-6, (final, batches)
    # per-rank Chrome-trace dump: the soak's cross-rank merge input
    # (tools/trace_collect.py; step-aligned in the corrupt-recover
    # scenario's assertion)
    wid = os.environ.get("HVD_TPU_ELASTIC_WORKER_ID", "na")
    try:
        trace_export.write_dump(
            os.path.join(logdir, f"trace_{wid}.json"))
    except OSError:
        pass
    log(logdir, event="done", weight=final, step=int(state.step),
        world=hvd.cross_size(), rank=hvd.cross_rank())


if __name__ == "__main__":
    main()
