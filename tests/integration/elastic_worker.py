"""Elastic integration worker.

Reference analog: the training scripts run by
test/integration/test_elastic_torch.py via elastic_common.py (SURVEY.md §4)
— a small training loop under ``hvd.elastic.run`` that commits every batch
and logs progress so the test can assert recovery/rescale bookkeeping.

Usage: elastic_worker.py <logdir> <num_epochs> <batches_per_epoch>
                         [ballast_bytes]
Each batch "trains" by allreducing a per-worker gradient of 1.0 (average),
so after any membership dance the final weight must equal the number of
completed batches exactly — lost/duplicated batches would show up as a
wrong weight.  ``ballast_bytes`` adds a numpy array of that size to the
state so restart cost vs state size is measurable (the reset callback
logs a ``restart_stats`` event with the persist/reboot/restore split).
"""

import json
import os
import sys
import time

import jax.numpy as jnp
import numpy as np

import horovod_tpu as hvd


def log(logdir, **kv):
    wid = os.environ.get("HVD_TPU_ELASTIC_WORKER_ID", "na")
    with open(os.path.join(logdir, f"worker_{wid}.log"), "a") as f:
        f.write(json.dumps(kv) + "\n")


def main():
    logdir, num_epochs, batches = sys.argv[1], int(sys.argv[2]), int(
        sys.argv[3])
    ballast_bytes = int(sys.argv[4]) if len(sys.argv) > 4 else 0
    hvd.init()
    log(logdir, event="init", rank=hvd.cross_rank(), world=hvd.cross_size(),
        pid=os.getpid())

    kwargs = {}
    if ballast_bytes:
        kwargs["ballast"] = np.ones(ballast_bytes // 8, np.float64)
    state = hvd.elastic.TpuState(
        weight=np.zeros(()), epoch=0, batch=0, resets=0, **kwargs)

    def on_reset():
        from horovod_tpu.elastic import worker as elastic_worker

        log(logdir, event="reset", world=hvd.cross_size())
        if elastic_worker.last_restart_stats:
            log(logdir, event="restart_stats",
                **elastic_worker.last_restart_stats)

    state.register_reset_callbacks([on_reset])

    @hvd.elastic.run
    def train(state):
        while state.epoch < num_epochs:
            while state.batch < batches:
                grad = hvd.allreduce(jnp.ones(()), op=hvd.Average)
                state.weight = np.asarray(state.weight + np.asarray(grad))
                state.batch += 1
                state.commit()
                log(logdir, event="batch", epoch=state.epoch,
                    batch=state.batch, world=hvd.cross_size(),
                    rank=hvd.cross_rank(), weight=float(state.weight))
                time.sleep(0.15)
            state.batch = 0
            state.epoch += 1
            state.commit()
        return float(state.weight)

    final = train(state)
    expected = float(num_epochs * batches)
    assert abs(final - expected) < 1e-6, (final, expected)
    log(logdir, event="done", weight=final, world=hvd.cross_size(),
        rank=hvd.cross_rank())


if __name__ == "__main__":
    main()
