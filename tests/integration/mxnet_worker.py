"""Per-rank MXNet-adapter worker for launcher integration tests.

Reference analog: test/parallel/test_mxnet.py under ``horovodrun -np 2``
(SURVEY.md §4).  Real mxnet is not installable in this image, so the
faked mxnet (tests/_fake_modules) provides NDArray storage while every
collective below crosses real process boundaries through the native
controller — the same split the single-process contract tests use.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir, "_fake_modules"))

import numpy as np  # noqa: E402

import mxnet as mx  # noqa: E402  (the fake)

import horovod_tpu.mxnet as hvd  # noqa: E402


def main():
    hvd.init()
    nproc = hvd.cross_size()
    assert nproc == int(sys.argv[1]), (nproc, sys.argv)
    me = hvd.cross_rank()

    # average across ranks
    out = hvd.allreduce(mx.nd.array(np.array([float(me)], dtype=np.float32)))
    np.testing.assert_allclose(out.asnumpy(), [np.mean(np.arange(nproc))])

    # in-place sum
    t = mx.nd.array(np.ones(3, dtype=np.float32) * (me + 1))
    hvd.allreduce_(t, op=hvd.Sum, name="mx_sum")
    np.testing.assert_allclose(
        t.asnumpy(), np.full(3, nproc * (nproc + 1) / 2))

    # grouped in-place average
    a = mx.nd.array(np.full(2, float(me), dtype=np.float32))
    b = mx.nd.array(np.full(4, float(2 * me), dtype=np.float32))
    hvd.grouped_allreduce_([a, b], name="mx_grouped")
    np.testing.assert_allclose(a.asnumpy(),
                               np.full(2, np.mean(np.arange(nproc))))
    np.testing.assert_allclose(b.asnumpy(),
                               np.full(4, 2 * np.mean(np.arange(nproc))))

    # broadcast: non-root values overwritten in place
    w = mx.nd.array(np.full(3, float(me + 7), dtype=np.float32))
    hvd.broadcast_(w, root_rank=0, name="mx_bcast")
    np.testing.assert_allclose(w.asnumpy(), np.full(3, 7.0))

    # reducescatter with the adapter's default op=None (must normalize
    # to Sum on the native path — int(op) crash regression)
    full = mx.nd.array(np.arange(nproc * 2, dtype=np.float32))
    chunk = hvd.reducescatter(full, name="mx_rs")
    np.testing.assert_allclose(
        chunk.asnumpy(), nproc * np.arange(me * 2, me * 2 + 2))

    # broadcast_parameters over a gluon collection with divergent values
    p = mx.gluon.Parameter("w0", shape=(2,))
    p.data()[:] = np.full(2, float(me + 1))
    hvd.broadcast_parameters({"w0": p}, root_rank=0)
    np.testing.assert_allclose(p.data().asnumpy(), np.full(2, 1.0))

    # DistributedTrainer: divergent grads -> averaged update
    p.grad()[:] = np.full(2, float(me))  # avg grad = mean(0..n-1)
    trainer = hvd.DistributedTrainer({"w0": p}, "sgd",
                                     {"learning_rate": 1.0})
    trainer.step(batch_size=1)
    expect = 1.0 - np.mean(np.arange(nproc))
    np.testing.assert_allclose(p.data().asnumpy(), np.full(2, expect),
                               rtol=1e-6)

    # DistributedOptimizer: same math through the update() hook
    sgd = mx.optimizer.SGD(learning_rate=1.0)
    opt = hvd.DistributedOptimizer(sgd)
    w2 = mx.nd.array(np.zeros(2, dtype=np.float32))
    g2 = mx.nd.array(np.full(2, float(me), dtype=np.float32))
    opt.update(0, w2, g2, None)
    np.testing.assert_allclose(w2.asnumpy(),
                               np.full(2, -np.mean(np.arange(nproc))),
                               rtol=1e-6)

    print(f"MXNET_WORKER_OK rank={me} nproc={nproc} "
          f"native={hvd.native_built()}", flush=True)


if __name__ == "__main__":
    main()
