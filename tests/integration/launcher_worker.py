"""Per-rank worker script for launcher integration tests.

Reference analog: the body of a test/parallel/test_*.py file — the same
script runs on every rank under the launcher and asserts collective
results against locally computed expectations (SURVEY.md §4).  Exercises
the REAL multi-process path: jax.distributed rendezvous + the eager engine
over a cross-process device mesh.
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd


def main():
    hvd.init()
    size = hvd.size()
    rank = hvd.rank()
    nproc = hvd.cross_size()
    assert nproc == int(sys.argv[1]), (nproc, sys.argv)
    assert size >= nproc
    assert 0 <= rank < size

    # allreduce: average of per-process values
    out = hvd.allreduce(jnp.asarray([float(hvd.cross_rank())]))
    expected = np.mean(np.arange(nproc))
    np.testing.assert_allclose(np.asarray(out), [expected], rtol=1e-6)

    # sum + scaling factors
    out = hvd.allreduce(jnp.ones((4,)), op=hvd.Sum, prescale_factor=2.0)
    np.testing.assert_allclose(np.asarray(out), np.full(4, 2.0 * nproc))

    # pytree fusion across a dict
    tree = {"a": jnp.full((3,), float(hvd.cross_rank())),
            "b": jnp.ones((2, 2))}
    out = hvd.allreduce(tree, op=hvd.Sum)
    np.testing.assert_allclose(
        np.asarray(out["a"]), np.full(3, float(sum(range(nproc))))
    )

    # allgather: concat along dim 0 in rank order
    mine = jnp.full((2, 2), float(hvd.cross_rank()))
    gathered = hvd.allgather(mine)
    assert gathered.shape == (2 * nproc, 2)
    for p in range(nproc):
        np.testing.assert_allclose(
            np.asarray(gathered[2 * p:2 * p + 2]), np.full((2, 2), float(p))
        )

    # broadcast from the last process's lead chip
    root = size - hvd.local_size()  # lead device rank of last process
    out = hvd.broadcast(jnp.full((3,), float(hvd.cross_rank())), root)
    np.testing.assert_allclose(np.asarray(out), np.full(3, float(nproc - 1)))

    # alltoall with even splits
    send = jnp.arange(nproc * 2, dtype=jnp.float32) + 100 * hvd.cross_rank()
    received, splits = hvd.alltoall(send)
    assert received.shape == (nproc * 2,)
    for p in range(nproc):
        np.testing.assert_allclose(
            np.asarray(received[2 * p:2 * p + 2]),
            100.0 * p + 2 * hvd.cross_rank() + np.arange(2),
        )

    # reducescatter: my chunk of the sum
    full = jnp.arange(nproc * 3, dtype=jnp.float32)
    chunk = hvd.reducescatter(full, op=hvd.Sum)
    me = hvd.cross_rank()
    np.testing.assert_allclose(
        np.asarray(chunk), nproc * np.arange(me * 3, me * 3 + 3)
    )

    # object plumbing
    objs = hvd.allgather_object({"rank": hvd.cross_rank()})
    assert [o["rank"] for o in objs] == list(range(nproc))
    obj = hvd.broadcast_object({"x": 42} if rank == 0 else None, 0)
    assert obj == {"x": 42}

    # broadcast_parameters + eager DistributedOptimizer step parity
    params = {"w": jnp.full((4,), float(hvd.cross_rank()))}
    params = hvd.broadcast_parameters(params, root_rank=0)
    np.testing.assert_allclose(np.asarray(params["w"]), np.zeros(4))
    opt = hvd.DistributedOptimizer(optax.sgd(1.0))
    state = opt.init(params)
    grads = {"w": jnp.full((4,), float(hvd.cross_rank()))}
    updates, _ = opt.update(grads, state, params)
    new_params = optax.apply_updates(params, updates)
    np.testing.assert_allclose(
        np.asarray(new_params["w"]), -np.full(4, np.mean(np.arange(nproc)))
    )

    hvd.barrier()
    print(f"WORKER_OK rank={rank} nproc={nproc} native={hvd.native_built()}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
