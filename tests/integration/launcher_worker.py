"""Per-rank worker script for launcher integration tests.

Reference analog: the body of a test/parallel/test_*.py file — the same
script runs on every rank under the launcher and asserts collective
results against locally computed expectations (SURVEY.md §4).  Exercises
the REAL multi-process path: jax.distributed rendezvous + the eager engine
over a cross-process device mesh.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd


def main():
    hvd.init()
    size = hvd.size()
    rank = hvd.rank()
    nproc = hvd.cross_size()
    assert nproc == int(sys.argv[1]), (nproc, sys.argv)
    assert size >= nproc
    assert 0 <= rank < size

    # allreduce: average of per-process values
    out = hvd.allreduce(jnp.asarray([float(hvd.cross_rank())]))
    expected = np.mean(np.arange(nproc))
    np.testing.assert_allclose(np.asarray(out), [expected], rtol=1e-6)

    # sum + scaling factors
    out = hvd.allreduce(jnp.ones((4,)), op=hvd.Sum, prescale_factor=2.0)
    np.testing.assert_allclose(np.asarray(out), np.full(4, 2.0 * nproc))

    # integer min/max exercise the masked pmin/pmax fill values; bool
    # takes the row-stack path (no psum/fill semantics)
    me0 = hvd.cross_rank()
    out = hvd.allreduce(
        jnp.asarray([me0 + 1, 10 - me0], jnp.int32), op=hvd.Min,
        name="int_min",
    )
    np.testing.assert_array_equal(
        np.asarray(out), [1, 10 - (nproc - 1)]
    )
    out = hvd.allreduce(
        jnp.asarray([me0 + 1], jnp.int32), op=hvd.Max, name="int_max"
    )
    np.testing.assert_array_equal(np.asarray(out), [nproc])
    out = hvd.allreduce(
        jnp.asarray([me0 == 0, True]), op=hvd.Min, name="bool_min"
    )
    np.testing.assert_array_equal(np.asarray(out), [nproc == 1, True])

    # pytree fusion across a dict
    tree = {"a": jnp.full((3,), float(hvd.cross_rank())),
            "b": jnp.ones((2, 2))}
    out = hvd.allreduce(tree, op=hvd.Sum)
    np.testing.assert_allclose(
        np.asarray(out["a"]), np.full(3, float(sum(range(nproc))))
    )

    # allgather: concat along dim 0 in rank order
    mine = jnp.full((2, 2), float(hvd.cross_rank()))
    gathered = hvd.allgather(mine)
    assert gathered.shape == (2 * nproc, 2)
    for p in range(nproc):
        np.testing.assert_allclose(
            np.asarray(gathered[2 * p:2 * p + 2]), np.full((2, 2), float(p))
        )

    # broadcast from the last process's lead chip
    root = size - hvd.local_size()  # lead device rank of last process
    out = hvd.broadcast(jnp.full((3,), float(hvd.cross_rank())), root)
    np.testing.assert_allclose(np.asarray(out), np.full(3, float(nproc - 1)))

    # alltoall with even splits
    send = jnp.arange(nproc * 2, dtype=jnp.float32) + 100 * hvd.cross_rank()
    received, splits = hvd.alltoall(send)
    assert received.shape == (nproc * 2,)
    for p in range(nproc):
        np.testing.assert_allclose(
            np.asarray(received[2 * p:2 * p + 2]),
            100.0 * p + 2 * hvd.cross_rank() + np.arange(2),
        )

    # uneven allgather: rank r contributes r+1 rows (reference:
    # MPIAllgather recvcounts negotiation)
    mine = jnp.full((hvd.cross_rank() + 1, 2), float(hvd.cross_rank()))
    gathered = hvd.allgather(mine, name="uneven_ag")
    assert gathered.shape == (sum(p + 1 for p in range(nproc)), 2), (
        gathered.shape
    )
    off = 0
    for p in range(nproc):
        np.testing.assert_allclose(
            np.asarray(gathered[off:off + p + 1]),
            np.full((p + 1, 2), float(p)),
        )
        off += p + 1

    # fused grouped allgather: mixed dtypes + uneven dim0s in ONE dim0
    # exchange + one uneven allgather per dtype bucket
    me_f = float(hvd.cross_rank())
    group = [
        jnp.full((hvd.cross_rank() + 1, 2), me_f),          # uneven f32
        jnp.asarray([hvd.cross_rank()], jnp.int32),          # even i32
        jnp.full((3,), 10.0 + me_f),                         # even f32
    ]
    g0, g1, g2 = hvd.grouped_allgather(group, name="grp_ag")
    np.testing.assert_allclose(
        np.asarray(g0),
        np.concatenate([np.full((p + 1, 2), float(p)) for p in range(nproc)]),
    )
    np.testing.assert_array_equal(np.asarray(g1), np.arange(nproc))
    np.testing.assert_allclose(
        np.asarray(g2),
        np.concatenate([np.full(3, 10.0 + p) for p in range(nproc)]),
    )

    # alltoall with explicit uneven splits: rank r sends c+1 rows tagged
    # 100*r + c to peer c (reference: MPIAlltoall splits negotiation)
    me = hvd.cross_rank()
    send = jnp.concatenate(
        [jnp.full((c + 1,), 100.0 * me + c) for c in range(nproc)]
    )
    recv, rsplits = hvd.alltoall(
        send, splits=[c + 1 for c in range(nproc)], name="uneven_a2a"
    )
    assert list(np.asarray(rsplits)) == [me + 1] * nproc, rsplits
    np.testing.assert_allclose(
        np.asarray(recv),
        np.concatenate([np.full(me + 1, 100.0 * p + me) for p in range(nproc)]),
    )

    # cross-rank shape mismatch must raise cleanly, not execute garbage
    # (reference: the parallel-test error cases of SURVEY.md §4)
    if hvd.native_built() and nproc > 1:
        try:
            hvd.allreduce(jnp.ones((2 + me,)), name="mismatch_probe")
        except hvd.HorovodInternalError:
            pass
        else:
            raise AssertionError("mismatched shapes did not raise")

    # reducescatter: my chunk of the sum
    full = jnp.arange(nproc * 3, dtype=jnp.float32)
    chunk = hvd.reducescatter(full, op=hvd.Sum)
    me = hvd.cross_rank()
    np.testing.assert_allclose(
        np.asarray(chunk), nproc * np.arange(me * 3, me * 3 + 3)
    )
    # op=None is what the torch/tf/mxnet adapters pass by default; it
    # must normalize to Sum on the native path (int(op) crash regression)
    chunk_none = hvd.reducescatter(full, op=None, name="rs_none_op")
    np.testing.assert_allclose(np.asarray(chunk_none), np.asarray(chunk))

    # grouped reducescatter: atomic group release, per-entry chunks
    ra, rb = hvd.grouped_reducescatter(
        [full, full * 2.0], op=hvd.Sum, name="grp_rs"
    )
    np.testing.assert_allclose(
        np.asarray(ra), nproc * np.arange(me * 3, me * 3 + 3))
    np.testing.assert_allclose(
        np.asarray(rb), 2.0 * nproc * np.arange(me * 3, me * 3 + 3))

    # object plumbing
    objs = hvd.allgather_object({"rank": hvd.cross_rank()})
    assert [o["rank"] for o in objs] == list(range(nproc))

    # local_rank (reference: horovod_local_rank per-host slots).  Under
    # the fake-ssh multi-host test each "host" runs its own slot 0, so
    # the single-host {0..nproc-1} expectation only holds without
    # LAUNCHER_WORKER_MULTIHOST.
    locals_ = hvd.allgather_object(hvd.local_rank())
    if os.environ.get("LAUNCHER_WORKER_MULTIHOST"):
        sizes = hvd.allgather_object(hvd.local_process_count())
        assert all(lr < ls for lr, ls in zip(locals_, sizes)), (
            locals_, sizes)
    else:
        assert sorted(locals_) == list(range(nproc)), locals_
        assert hvd.local_process_count() == nproc
    obj = hvd.broadcast_object({"x": 42} if rank == 0 else None, 0)
    assert obj == {"x": 42}

    # broadcast_parameters + eager DistributedOptimizer step parity
    params = {"w": jnp.full((4,), float(hvd.cross_rank()))}
    params = hvd.broadcast_parameters(params, root_rank=0)
    np.testing.assert_allclose(np.asarray(params["w"]), np.zeros(4))
    opt = hvd.DistributedOptimizer(optax.sgd(1.0))
    state = opt.init(params)
    grads = {"w": jnp.full((4,), float(hvd.cross_rank()))}
    updates, _ = opt.update(grads, state, params)
    new_params = optax.apply_updates(params, updates)
    np.testing.assert_allclose(
        np.asarray(new_params["w"]), -np.full(4, np.mean(np.arange(nproc)))
    )

    # eager cross-process Adasum (reference: adasum_mpi_operations.cc):
    # must match the shared fold+hypercube oracle (tests/adasum_oracle.py)
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    )
    from tests.adasum_oracle import host_adasum

    vs = [np.arange(1.0, 5.0, dtype=np.float32) + p * p for p in range(nproc)]
    out = hvd.allreduce(
        jnp.asarray(vs[hvd.cross_rank()]), op=hvd.Adasum, name="adasum_probe"
    )
    np.testing.assert_allclose(
        np.asarray(out), host_adasum(vs), rtol=1e-5
    )

    # join(): ragged per-rank batch counts (reference: JoinOp).  Rank r
    # runs r+1 allreduce steps; finished ranks join and keep contributing
    # zeros, so step i sums 1 from every rank still running (nproc - i).
    if hvd.native_built() and nproc > 1:
        got = []
        for i in range(me + 1):
            out = hvd.allreduce(
                jnp.asarray(1.0), name=f"ragged_{i}", op=hvd.Sum
            )
            got.append(float(out))
        last = hvd.join()
        assert got == [float(nproc - i) for i in range(me + 1)], got
        assert last == nproc - 1, f"last joining rank {last}"

    # eager cross-process process-set collectives: a sub-world of the
    # first two processes (reference: process_set= scoped collectives)
    if nproc >= 3:
        ps = hvd.add_process_set([0, 1])
        if me in (0, 1):
            out = hvd.allreduce(
                jnp.asarray([float(me + 1)]), op=hvd.Sum,
                name="subset_ar", process_set=ps,
            )
            np.testing.assert_allclose(np.asarray(out), [3.0])
            sub = hvd.allgather(
                jnp.asarray([[float(me)]]), name="subset_ag",
                process_set=ps,
            )
            np.testing.assert_allclose(np.asarray(sub), [[0.0], [1.0]])
        hvd.remove_process_set(ps)

    # ResponseCache bit-vector steady state across processes: repeats of
    # the same signature negotiate as cache positions (payload shrinks to
    # O(positions)) and still reduce correctly on every rank
    if hvd.native_built() and nproc > 1:
        ctrl = hvd.common.basics._require_init().controller
        hvd.allreduce(jnp.asarray([1.0]), name="steady", op=hvd.Sum)
        full_bytes = ctrl.last_request_bytes()
        for i in range(3):
            out = hvd.allreduce(
                jnp.asarray([float(hvd.cross_rank() + i)]),
                name="steady", op=hvd.Sum,
            )
            np.testing.assert_allclose(
                np.asarray(out), [sum(range(nproc)) + i * nproc]
            )
            assert ctrl.last_request_bytes() < full_bytes, (
                ctrl.last_request_bytes(), full_bytes,
            )

    hvd.barrier()
    print(f"WORKER_OK rank={rank} nproc={nproc} native={hvd.native_built()}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
