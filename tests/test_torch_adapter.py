"""Torch adapter tests.

Reference analog: test/parallel/test_torch.py (SURVEY.md §4) — collectives
on torch tensors, DistributedOptimizer gradient averaging, parameter /
optimizer-state broadcast, compression, SyncBatchNorm.  Single-process
world here (the per-rank semantics are covered by the launcher integration
tests); these verify the adapter's bridging, hooks and state machinery.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import horovod_tpu.torch as hvd  # noqa: E402


def test_allreduce_roundtrip():
    t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    out = hvd.allreduce(t)
    assert isinstance(out, torch.Tensor)
    assert out.dtype == t.dtype
    torch.testing.assert_close(out, t)  # world of one process: identity


def test_allreduce_inplace_and_handles():
    t = torch.ones(4)
    h = hvd.allreduce_async_(t, op=hvd.Sum)
    assert hvd.poll(h) in (True, False)
    out = hvd.synchronize(h)
    assert out is t
    torch.testing.assert_close(t, torch.ones(4))


def test_allreduce_prescale():
    t = torch.ones(3)
    out = hvd.allreduce(t, op=hvd.Sum, prescale_factor=2.0)
    torch.testing.assert_close(out, torch.full((3,), 2.0))


def test_grouped_allreduce():
    ts = [torch.ones(2), torch.full((3,), 2.0)]
    outs = hvd.grouped_allreduce(ts)
    assert len(outs) == 2
    torch.testing.assert_close(outs[1], torch.full((3,), 2.0))


def test_allgather_broadcast_alltoall():
    t = torch.arange(4, dtype=torch.float32)
    torch.testing.assert_close(hvd.allgather(t), t)
    torch.testing.assert_close(hvd.broadcast(t, root_rank=0), t)
    received, splits = hvd.alltoall(t)
    torch.testing.assert_close(received, t)
    assert splits.sum().item() == 4


def test_int_dtypes_preserved():
    t = torch.arange(5, dtype=torch.int64)
    out = hvd.allreduce(t, op=hvd.Sum)
    assert out.dtype == torch.int64
    torch.testing.assert_close(out, t)


def test_compression_fp16_roundtrip():
    t = torch.randn(8)
    c, ctx = hvd.Compression.fp16.compress(t)
    assert c.dtype == torch.float16
    d = hvd.Compression.fp16.decompress(c, ctx)
    assert d.dtype == torch.float32
    torch.testing.assert_close(d, t, rtol=1e-3, atol=1e-3)


def _train_step(model, opt, x, y):
    opt.zero_grad()
    loss = torch.nn.functional.mse_loss(model(x), y)
    loss.backward()
    opt.step()
    return float(loss)


def test_distributed_optimizer_trains():
    torch.manual_seed(0)
    model = torch.nn.Sequential(
        torch.nn.Linear(4, 8), torch.nn.ReLU(), torch.nn.Linear(8, 1)
    )
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters()
    )
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    x = torch.randn(16, 4)
    y = x.sum(dim=1, keepdim=True)
    losses = [_train_step(model, opt, x, y) for _ in range(20)]
    assert losses[-1] < losses[0] * 0.5  # actually learning


def test_distributed_optimizer_matches_local_sgd():
    """With one worker the wrapped optimizer must match plain SGD exactly
    (the reference's correctness invariant for np=1)."""
    def build():
        torch.manual_seed(7)
        m = torch.nn.Linear(3, 1)
        return m

    m1, m2 = build(), build()
    o1 = torch.optim.SGD(m1.parameters(), lr=0.05)
    o2 = hvd.DistributedOptimizer(
        torch.optim.SGD(m2.parameters(), lr=0.05),
        named_parameters=m2.named_parameters(),
    )
    x = torch.randn(8, 3)
    y = torch.randn(8, 1)
    for _ in range(5):
        _train_step(m1, o1, x, y)
        _train_step(m2, o2, x, y)
    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        torch.testing.assert_close(p1, p2)


def test_backward_passes_per_step_accumulates():
    model = torch.nn.Linear(2, 1, bias=False)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=1.0),
        named_parameters=model.named_parameters(),
        backward_passes_per_step=2,
    )
    x = torch.ones(1, 2)
    opt.zero_grad()
    (model(x).sum()).backward()
    assert not opt._handles  # first pass: only locally accumulated
    (model(x).sum()).backward()
    # submission is async (the hook posts to the submit worker); drain it
    # before peeking at the handle table
    for f in list(opt._pending_submits):
        f.result()
    assert opt._handles  # second pass submitted the allreduce
    opt.step()


def test_skip_synchronize_pattern():
    model = torch.nn.Linear(2, 1)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
    )
    opt.zero_grad()
    model(torch.ones(1, 2)).sum().backward()
    opt.synchronize()
    torch.nn.utils.clip_grad_norm_(model.parameters(), 1.0)
    with opt.skip_synchronize():
        opt.step()


def test_broadcast_optimizer_state_roundtrip():
    model = torch.nn.Linear(3, 2)
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    model(torch.randn(4, 3)).sum().backward()
    opt.step()
    hvd.broadcast_optimizer_state(opt, root_rank=0)
    sd = opt.state_dict()
    assert sd["state"]  # momentum buffers survived the roundtrip
    for st in sd["state"].values():
        assert isinstance(st["exp_avg"], torch.Tensor)


def test_sync_batch_norm_single_worker_matches_bn():
    torch.manual_seed(0)
    x = torch.randn(8, 4, 5, 5)
    bn = torch.nn.BatchNorm2d(4)
    sbn = hvd.SyncBatchNorm(4)
    sbn.load_state_dict(bn.state_dict())
    bn.train(), sbn.train()
    # single process: SyncBatchNorm takes the plain-BN fast path
    torch.testing.assert_close(bn(x), sbn(x))


def test_sync_batch_norm_stats_math():
    """Force the collective path and check it equals plain BN in a world
    of one (the reduction is then an identity)."""
    import horovod_tpu.torch.sync_batch_norm as sbn_mod

    torch.manual_seed(1)
    x = torch.randn(6, 3, 4, requires_grad=True)
    x2 = x.detach().clone().requires_grad_(True)
    bn = torch.nn.BatchNorm1d(3)
    sbn = hvd.SyncBatchNorm(3)
    sbn.load_state_dict(bn.state_dict())
    bn.train(), sbn.train()

    orig = sbn_mod.basics.cross_size
    sbn_mod.basics.cross_size = lambda: 2  # pretend multi-worker
    try:
        out = sbn(x)
    finally:
        sbn_mod.basics.cross_size = orig
    ref = bn(x2)
    torch.testing.assert_close(out, ref, rtol=1e-4, atol=1e-4)
    out.sum().backward()
    ref.sum().backward()
    torch.testing.assert_close(x.grad, x2.grad, rtol=1e-4, atol=1e-4)
    torch.testing.assert_close(sbn.running_mean, bn.running_mean,
                               rtol=1e-4, atol=1e-4)


def test_torch_elastic_state_roundtrip():
    model = torch.nn.Linear(2, 2)
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    state = hvd.elastic.TorchState(model=model, optimizer=opt, epoch=0)
    w0 = model.weight.detach().clone()
    state.commit()
    with torch.no_grad():
        model.weight.add_(1.0)
    state.epoch = 5
    state.restore()
    torch.testing.assert_close(model.weight.detach(), w0)
    assert state.epoch == 0
    assert state.model is model  # restored in place via load_state_dict


def test_torch_grouped_allgather_and_reducescatter_single():
    """np=1 degenerate semantics of the new grouped torch wrappers."""
    import horovod_tpu.torch as hvd_t

    a = torch.arange(6, dtype=torch.float32)
    b = torch.ones(4) * 2.0
    ga, gb = hvd_t.grouped_allgather([a, b])
    assert torch.equal(ga, a) and torch.equal(gb, b)
    ra, rb = hvd_t.grouped_reducescatter([a, b], op=hvd_t.Sum)
    assert torch.equal(ra, a) and torch.equal(rb, b)


def test_torch_allgather_object_single():
    import horovod_tpu.torch as hvd_t

    objs = hvd_t.allgather_object({"rank": hvd_t.cross_rank()})
    assert objs == [{"rank": 0}]


def test_distributed_optimizer_close_shuts_submit_pool_down():
    """close() (and __del__) must remove the grad hooks and stop the
    submission worker thread — before the fix every DistributedOptimizer
    leaked one live thread for the rest of the process (ADVICE r5)."""
    model = torch.nn.Linear(4, 2)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
    )
    pool = opt._submit_pool
    # one step so the worker thread actually spawns and hooks fire
    loss = model(torch.randn(8, 4)).sum()
    loss.backward()
    opt.step()
    # THIS optimizer's worker threads only — other tests' un-closed
    # optimizers legitimately keep theirs alive in the same process
    worker_threads = list(pool._threads)
    assert worker_threads, "submission worker never started"

    opt.close()
    assert opt._submit_pool is None
    assert opt._hook_handles == []
    for t in worker_threads:
        t.join(timeout=10)
        assert not t.is_alive(), "submission worker leaked after close()"
    # post-close: the wrapper still works as a plain local optimizer
    opt.zero_grad()
    loss = model(torch.randn(8, 4)).sum()
    loss.backward()
    opt.step()
    # and close() is idempotent / __del__-safe
    opt.close()
    assert pool._shutdown
