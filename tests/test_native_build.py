"""Checked-in native binary vs source: the stale-.so guard.

The repo commits ``libhvd_tpu_core.so`` (documented fallback when no
compiler is present) next to its sources.  Nothing previously failed
when someone edited ``c_api.cc`` and forgot ``tools/rebuild_native.sh``
— the Python side would crash at runtime with a missing-symbol
AttributeError on whatever box loaded the stale binary first.  These
tests pin the contract at test time: every ``hvdtpu_*`` function
declared in ``c_api.cc`` must resolve in the committed binary.
"""

import ctypes
import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "horovod_tpu", "native", "src", "c_api.cc")
LIB = os.path.join(REPO, "horovod_tpu", "native", "libhvd_tpu_core.so")

# extern "C" definitions in c_api.cc: return type at column 0, then the
# symbol.  Kept in sync with tools/rebuild_native.sh's nm-based check.
_DECL_RE = re.compile(
    r"^(?:int|void|long long|double|const char\*)\s+(hvdtpu_[a-z0-9_]+)\s*\(",
    re.MULTILINE,
)


def declared_symbols():
    with open(SRC) as f:
        syms = sorted(set(_DECL_RE.findall(f.read())))
    assert len(syms) >= 20, f"c_api.cc parse broke? found only {syms}"
    return syms


@pytest.fixture(scope="module")
def lib():
    if not os.path.exists(LIB):
        pytest.skip("native core not built")
    return ctypes.CDLL(LIB)


def test_committed_binary_exports_declared_c_api(lib):
    missing = [s for s in declared_symbols() if not hasattr(lib, s)]
    assert not missing, (
        f"libhvd_tpu_core.so is stale: missing {missing} — run "
        "tools/rebuild_native.sh and commit the rebuilt binary"
    )


def test_known_surface_is_declared():
    """The parse itself must see the symbols the Python controller binds
    (guards the regex against a c_api.cc style change going unnoticed)."""
    syms = set(declared_symbols())
    for required in ("hvdtpu_init", "hvdtpu_shutdown", "hvdtpu_enqueue",
                     "hvdtpu_enqueue_n", "hvdtpu_loop_dead",
                     "hvdtpu_pack", "hvdtpu_set_exec_callback"):
        assert required in syms


def test_binary_not_older_than_sources(lib):
    """Soft staleness tripwire: the committed .so must export everything;
    beyond symbols, a source newer than the binary is suspicious on a dev
    tree but legitimate right after checkout — so only symbol coverage is
    enforced, and this test documents the rebuild entry point."""
    assert os.path.exists(
        os.path.join(REPO, "tools", "rebuild_native.sh"))


def test_declared_symbols_match_analysis_parser():
    """This file's regex and the contract checker's C-API parser must
    agree on the declared surface — rebuild_native.sh trusts the
    parser, these tests trust the regex; divergence would let a symbol
    slip one of the nets."""
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check.py"),
         "--list-c-symbols"],
        capture_output=True, text=True, check=True,
    )
    assert sorted(out.stdout.split()) == declared_symbols()


# -- sanitizer builds (slow tier; docs/ANALYSIS.md) ---------------------------
#
# TSan has never covered the 78 std::thread/std::mutex sites in
# native/src; these jobs build the instrumented twins and drive the
# EXISTING ctypes fault/auth tests against them.  Skip cleanly when the
# container toolchain lacks the sanitizer runtimes.

import shutil
import subprocess
import sys
import tempfile

SRC_DIR = os.path.join(REPO, "horovod_tpu", "native", "src")
NATIVE_DIR = os.path.join(REPO, "horovod_tpu", "native")


def _sanitizer_runtime(name: str) -> str:
    """Full path of lib<name>.so via the compiler, or skip."""
    cxx = os.environ.get("CXX", "g++")
    if shutil.which(cxx) is None:
        pytest.skip(f"no C++ compiler ({cxx})")
    out = subprocess.run([cxx, f"-print-file-name=lib{name}.so"],
                         capture_output=True, text=True)
    path = out.stdout.strip()
    if not os.path.isabs(path) or not os.path.exists(path):
        pytest.skip(f"toolchain lacks lib{name} (got {path!r})")
    return path


def _probe_sanitizer_link(flag: str) -> None:
    """Skip unless a trivial -fsanitize=<flag> shared lib links."""
    cxx = os.environ.get("CXX", "g++")
    with tempfile.TemporaryDirectory() as td:
        src = os.path.join(td, "probe.cc")
        with open(src, "w") as f:
            f.write("int probe() { return 0; }\n")
        rc = subprocess.run(
            [cxx, f"-fsanitize={flag}", "-fPIC", "-shared", "-o",
             os.path.join(td, "probe.so"), src],
            capture_output=True, text=True,
        )
        if rc.returncode != 0:
            pytest.skip(f"-fsanitize={flag} does not link here: "
                        f"{rc.stderr.strip()[:200]}")


def _make_sanitized(mode: str) -> str:
    """`make SANITIZE=<mode>` and return the built library path."""
    rc = subprocess.run(["make", "-C", SRC_DIR, f"SANITIZE={mode}"],
                        capture_output=True, text=True)
    assert rc.returncode == 0, rc.stderr
    suffix = {"thread": ".tsan", "address": ".asan"}[mode]
    so = os.path.join(NATIVE_DIR, f"libhvd_tpu_core{suffix}.so")
    assert os.path.exists(so)
    return so


@pytest.mark.slow
def test_asan_build_and_ctypes_roundtrip(tmp_path):
    """`make SANITIZE=address` produces a working .so: a loopback
    init → initialized → stats → shutdown round-trip over ctypes runs
    clean under ASan+UBSan (the runtime halts on any report because the
    build sets -fno-sanitize-recover=undefined and we make ASan errors
    fatal)."""
    _probe_sanitizer_link("address")
    runtime = _sanitizer_runtime("asan")
    so = _make_sanitized("address")
    log_base = str(tmp_path / "asan")
    code = f"""
import ctypes
lib = ctypes.CDLL({so!r})
lib.hvdtpu_init.restype = ctypes.c_int
lib.hvdtpu_init.argtypes = [
    ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
    ctypes.c_double, ctypes.c_longlong, ctypes.c_int, ctypes.c_char_p,
    ctypes.c_double, ctypes.c_double, ctypes.c_int, ctypes.c_char_p,
]
assert lib.hvdtpu_init(0, 1, b"", 0, 1.0, 1 << 20, 16, b"",
                       0.0, 0.0, 0, b"") == 0
assert lib.hvdtpu_initialized() == 1
lib.hvdtpu_cache_hits.restype = ctypes.c_longlong
lib.hvdtpu_cache_hits.argtypes = []
assert lib.hvdtpu_cache_hits() == 0
lib.hvdtpu_shutdown()
print("ROUNDTRIP_OK", flush=True)
"""
    env = os.environ.copy()
    env["LD_PRELOAD"] = runtime
    # python leaks by design; the report files catch real ASan errors
    env["ASAN_OPTIONS"] = (f"detect_leaks=0:log_path={log_base}:"
                           "abort_on_error=1")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300)
    reports = list(tmp_path.glob("asan.*"))
    details = "\n".join(p.read_text() for p in reports)
    assert proc.returncode == 0 and "ROUNDTRIP_OK" in proc.stdout, (
        proc.stdout, proc.stderr, details)
    assert not reports, f"ASan reported errors:\n{details}"


@pytest.mark.slow
def test_tsan_fault_and_auth_tests_race_free(tmp_path):
    """The existing ctypes fault/auth tests rerun against the TSan build
    (heartbeat thread, background loop, stall inspector, chaos engine
    all exercised across threads); any ThreadSanitizer report fails.
    TSan writes reports to log_path with exitcode=0 so the inner tests
    still judge behavior — the race audit is the file check here."""
    _probe_sanitizer_link("thread")
    runtime = _sanitizer_runtime("tsan")
    so = _make_sanitized("thread")
    log_base = str(tmp_path / "tsan")
    env = os.environ.copy()
    env["HVD_TPU_TEST_NATIVE_LIB"] = so
    env["HVD_TPU_TEST_CHILD_PRELOAD"] = runtime
    env["TSAN_OPTIONS"] = (f"log_path={log_base}:report_bugs=1:"
                           "halt_on_error=0:exitcode=0")
    inner = [
        "tests/test_fault_native.py",
        "tests/test_control_auth.py::test_auth_mode_mismatch_fails_fast",
        "tests/test_control_auth.py::test_steady_state_frame_tamper_rejected",
        "tests/test_control_auth.py::test_replayed_frame_rejected",
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
         *inner],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, (proc.stdout[-4000:], proc.stderr[-2000:])
    reports = list(tmp_path.glob("tsan.*"))
    racy = [p for p in reports
            if "WARNING: ThreadSanitizer" in p.read_text()]
    details = "\n\n".join(p.read_text()[:4000] for p in racy)
    assert not racy, (
        f"ThreadSanitizer reported {len(racy)} issue(s) in the native "
        f"core:\n{details}")