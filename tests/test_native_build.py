"""Checked-in native binary vs source: the stale-.so guard.

The repo commits ``libhvd_tpu_core.so`` (documented fallback when no
compiler is present) next to its sources.  Nothing previously failed
when someone edited ``c_api.cc`` and forgot ``tools/rebuild_native.sh``
— the Python side would crash at runtime with a missing-symbol
AttributeError on whatever box loaded the stale binary first.  These
tests pin the contract at test time: every ``hvdtpu_*`` function
declared in ``c_api.cc`` must resolve in the committed binary.
"""

import ctypes
import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "horovod_tpu", "native", "src", "c_api.cc")
LIB = os.path.join(REPO, "horovod_tpu", "native", "libhvd_tpu_core.so")

# extern "C" definitions in c_api.cc: return type at column 0, then the
# symbol.  Kept in sync with tools/rebuild_native.sh's nm-based check.
_DECL_RE = re.compile(
    r"^(?:int|void|long long|double|const char\*)\s+(hvdtpu_[a-z0-9_]+)\s*\(",
    re.MULTILINE,
)


def declared_symbols():
    with open(SRC) as f:
        syms = sorted(set(_DECL_RE.findall(f.read())))
    assert len(syms) >= 20, f"c_api.cc parse broke? found only {syms}"
    return syms


@pytest.fixture(scope="module")
def lib():
    if not os.path.exists(LIB):
        pytest.skip("native core not built")
    return ctypes.CDLL(LIB)


def test_committed_binary_exports_declared_c_api(lib):
    missing = [s for s in declared_symbols() if not hasattr(lib, s)]
    assert not missing, (
        f"libhvd_tpu_core.so is stale: missing {missing} — run "
        "tools/rebuild_native.sh and commit the rebuilt binary"
    )


def test_known_surface_is_declared():
    """The parse itself must see the symbols the Python controller binds
    (guards the regex against a c_api.cc style change going unnoticed)."""
    syms = set(declared_symbols())
    for required in ("hvdtpu_init", "hvdtpu_shutdown", "hvdtpu_enqueue",
                     "hvdtpu_enqueue_n", "hvdtpu_loop_dead",
                     "hvdtpu_pack", "hvdtpu_set_exec_callback"):
        assert required in syms


def test_binary_not_older_than_sources(lib):
    """Soft staleness tripwire: the committed .so must export everything;
    beyond symbols, a source newer than the binary is suspicious on a dev
    tree but legitimate right after checkout — so only symbol coverage is
    enforced, and this test documents the rebuild entry point."""
    assert os.path.exists(
        os.path.join(REPO, "tools", "rebuild_native.sh"))