"""Kernel-native GQA + windowed flash oracle tests.

The flash kernels consume (B, S, H_kv, D) K/V directly (query head h
reads kv head h // group); the oracle here is the PRE-GQA-native
semantics — ``jnp.repeat`` K/V to full heads, then the unchanged MHA
dense path — so any grouping bug in the kernels or the grouped dense
einsums shows up as a numeric diff.  Gradients through the repeat
oracle sum each kv head's group automatically (autodiff of repeat is
the grouped sum), which pins the kernels' in-VMEM dK/dV accumulation.

Also here: the `_kb_range` block-skip property test (the bounds the
windowed kernels AND the bench's modeled columns both rely on) and the
modeled-attention-bytes pin for the ~num_heads/num_kv_heads K/V
traffic reduction (ISSUE 5 acceptance).
"""

import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models.transformer import causal_dot_attention
from horovod_tpu.ops.flash_attention import (
    _kb_range, flash_attention,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_flash_bench():
    spec = importlib.util.spec_from_file_location(
        "flash_bench", os.path.join(_REPO, "tools", "flash_bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _qkv(b, s, h, h_kv, d, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    mk = lambda kk, heads: jax.random.normal(
        kk, (b, s, heads, d), jnp.float32).astype(dtype)
    return mk(ks[0], h), mk(ks[1], h_kv), mk(ks[2], h_kv)


def repeat_oracle(q, k, v, causal=True, window=None):
    """Pre-GQA-native semantics: expand K/V to full heads, MHA dense."""
    g = q.shape[2] // k.shape[2]
    return causal_dot_attention(
        q, jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2),
        causal=causal, window=window,
    )


def test_dense_gqa_matches_repeat_oracle():
    """The grouped dense einsum (no materialized repeat) is numerically
    the repeat+MHA computation."""
    q, k, v = _qkv(2, 48, 4, 2, 16, seed=11)
    for causal, window in ((True, None), (True, 7), (False, None),
                           (False, 7)):
        out = causal_dot_attention(q, k, v, causal=causal, window=window)
        ref = repeat_oracle(q, k, v, causal=causal, window=window)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5,
            err_msg=f"causal={causal} window={window}")


def test_dense_rejects_bad_head_split():
    q, k, v = _qkv(1, 8, 4, 3, 8)
    with pytest.raises(ValueError, match="multiple"):
        causal_dot_attention(q, k, v)
    with pytest.raises(ValueError, match="multiple"):
        flash_attention(q, k, v)


@pytest.mark.parametrize("ratio", [2, 4])
@pytest.mark.parametrize("causal,window", [(True, None), (True, 120),
                                           (False, 120)])
def test_flash_gqa_matches_oracle(ratio, causal, window):
    """Grouped flash forward vs the repeat-dense reference across the
    causal x window x ratio grid (S=320 crosses 128-block boundaries,
    W=120 crosses them within a window)."""
    q, k, v = _qkv(1, 320, 4, 4 // ratio, 32, seed=ratio)
    out = flash_attention(q, k, v, causal=causal, window=window,
                          block_q=128, block_k=128)
    ref = repeat_oracle(q, k, v, causal=causal, window=window)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("window", [None, 150])
def test_flash_gqa_gradients_match_oracle(window):
    """GQA backward: dq per query head, dk/dv per KV head (the in-VMEM
    group accumulation) vs autodiff through the repeat oracle — whose
    repeat-transpose IS the grouped sum."""
    q, k, v = _qkv(1, 320, 4, 2, 32, seed=5)

    gf = jax.grad(
        lambda a, b, c: (flash_attention(
            a, b, c, window=window, block_q=128, block_k=128) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    gd = jax.grad(
        lambda a, b, c: (repeat_oracle(a, b, c, window=window) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    assert gf[1].shape == k.shape and gf[2].shape == v.shape
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3)


def test_flash_gqa_bf16():
    q, k, v = _qkv(1, 256, 4, 1, 32, dtype=jnp.bfloat16, seed=7)
    out = flash_attention(q, k, v, block_q=128, block_k=128)
    ref = repeat_oracle(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2)


@pytest.mark.slow
def test_flash_gqa_exhaustive_grid():
    """Full causal x window x ratio x dtype sweep (slow tier; the fast
    tier covers the representative corners above)."""
    for ratio in (1, 2, 4):
        for causal in (True, False):
            for window in (None, 1, 33, 120):
                for dtype, tol in ((jnp.float32, 2e-5),
                                   (jnp.bfloat16, 2e-2)):
                    q, k, v = _qkv(1, 272, 4, 4 // ratio, 16,
                                   dtype=dtype, seed=ratio)
                    out = flash_attention(q, k, v, causal=causal,
                                          window=window, block_q=128,
                                          block_k=128)
                    ref = repeat_oracle(q, k, v, causal=causal,
                                        window=window)
                    np.testing.assert_allclose(
                        np.asarray(out, np.float32),
                        np.asarray(ref, np.float32), rtol=tol, atol=tol,
                        err_msg=f"ratio={ratio} causal={causal} "
                                f"window={window} dtype={dtype}")


# -- _kb_range block-skip bounds --------------------------------------------


def _brute_blocks(q_off, block_q, block_k, padded_kb, causal, window,
                  kv_off):
    """Brute-force: K blocks holding >= 1 (q, k) pair unmasked by the
    causal/window terms (padding excluded — _kb_range doesn't see it)."""
    blocks = set()
    for kb in range(padded_kb):
        hit = False
        for qp in range(q_off, q_off + block_q):
            for kp in range(kb * block_k, (kb + 1) * block_k):
                rel = qp - kp - kv_off
                if causal and rel < 0:
                    continue
                if window is not None:
                    if rel >= window or (not causal and rel <= -window):
                        continue
                hit = True
                break
            if hit:
                break
        if hit:
            blocks.add(kb)
    return blocks


def _bounds_int(fn, *args):
    lo, hi = fn(*args)
    return int(lo), int(hi)


def test_kb_range_bounds_property():
    """kv_off=0 (self/diagonal attention): [lo, hi) covers EXACTLY the
    causal/window-unmasked K blocks — no block skipped that has work,
    no empty block visited at either edge.  The bench's pure-python
    mirror (tools/flash_bench.kb_bounds) must agree bit-for-bit."""
    fb = _load_flash_bench()
    for block_q, block_k in ((64, 64), (128, 64), (64, 128)):
        for padded_kb in (2, 3):
            s_k = padded_kb * block_k
            for q_off in range(0, s_k, block_q):
                for causal in (True, False):
                    for window in (None, 1, 17, 100, 1000):
                        want = _brute_blocks(q_off, block_q, block_k,
                                             padded_kb, causal, window, 0)
                        lo, hi = _bounds_int(_kb_range, q_off, block_q,
                                             block_k, padded_kb, causal,
                                             window, 0)
                        got = set(range(lo, hi))
                        assert got == want, (
                            f"bq={block_q} bk={block_k} kb={padded_kb} "
                            f"q_off={q_off} causal={causal} "
                            f"window={window}: {sorted(got)} != "
                            f"{sorted(want)}")
                        assert (lo, hi) == fb.kb_bounds(
                            q_off, block_q, block_k, padded_kb, causal,
                            window, 0)


def test_kb_range_bounds_with_offset():
    """kv_off != 0 (ring off-diagonal blocks): the bounds must CONTAIN
    every unmasked block (correctness — a skipped block with work would
    silently drop attention mass), and the bench mirror agrees."""
    fb = _load_flash_bench()
    rng = np.random.RandomState(0)
    for _ in range(200):
        block_q = int(rng.choice([32, 64]))
        block_k = int(rng.choice([32, 64]))
        padded_kb = int(rng.randint(1, 4))
        q_off = int(rng.randint(0, 3)) * block_q
        causal = bool(rng.randint(2))
        window = [None, 1, 9, 50][rng.randint(4)]
        kv_off = int(rng.randint(-3, 4)) * 32
        want = _brute_blocks(q_off, block_q, block_k, padded_kb, causal,
                             window, kv_off)
        lo, hi = _bounds_int(_kb_range, q_off, block_q, block_k,
                             padded_kb, causal, window, kv_off)
        assert want <= set(range(lo, hi)), (
            f"bq={block_q} bk={block_k} kb={padded_kb} q_off={q_off} "
            f"causal={causal} window={window} kv_off={kv_off}: "
            f"{sorted(want)} not within [{lo}, {hi})")
        assert (lo, hi) == fb.kb_bounds(q_off, block_q, block_k,
                                        padded_kb, causal, window, kv_off)


# -- modeled K/V traffic (ISSUE 5 acceptance pin) ---------------------------


def test_modeled_kv_bytes_shrink_by_group():
    """The bench's modeled-bytes column: flash GQA K/V HBM reads are
    exactly num_heads/num_kv_heads smaller than MHA, and the total
    (incl. the repeat materialization the old path paid) shrinks
    accordingly."""
    fb = _load_flash_bench()
    b, s, h, d = 4, 2048, 8, 128
    mha = fb.modeled_attention_bytes(b, s, h, h, d)
    for h_kv in (4, 2, 1):
        gqa = fb.modeled_attention_bytes(b, s, h, h_kv, d)
        assert gqa["kv_bytes"] * (h // h_kv) == mha["kv_bytes"]
        baseline = fb.modeled_repeat_baseline_bytes(b, s, h, h_kv, d)
        # old path: repeat materialization + MHA-sized kernel reads
        assert baseline["kv_bytes"] == mha["kv_bytes"]
        assert baseline["repeat_io_bytes"] > 0
        assert baseline["total_bytes"] > mha["total_bytes"]
        assert gqa["total_bytes"] < mha["total_bytes"]
    # MHA "baseline" pays no repeat traffic (repeat(1) is a no-op)
    assert fb.modeled_repeat_baseline_bytes(
        b, s, h, h, d)["repeat_io_bytes"] == 0


def test_modeled_flops_drop_with_window():
    fb = _load_flash_bench()
    full = fb.modeled_attention_flops(1, 4096, 8, 128, causal=True,
                                      window=None)
    prev = full
    for w in (2048, 1024, 512, 256):
        f = fb.modeled_attention_flops(1, 4096, 8, 128, causal=True,
                                       window=w)
        assert f <= prev
        prev = f
    # O(S·W): at W=256 with 256-blocks, each Q block visits <= 3 K blocks
    assert prev <= 4 * 1 * 8 * 256 * 256 * 128 * (4096 // 256) * 3


# -- q_len=1 decode entry (the paged-KV serving path, ISSUE 8) ---------------


from horovod_tpu.ops.flash_attention import flash_decode_attention  # noqa: E402


def decode_oracle(q, k, v, kv_lens, window=None, kv_start=None):
    """Dense per-sequence reference for single-token decode: query at
    global position kv_lens-1 attends keys at global positions
    kv_start..kv_start+S_kv-1 masked by length and window."""
    b, _, h, d = q.shape
    s_k = k.shape[1]
    g = h // k.shape[2]
    kf = np.repeat(np.asarray(k, np.float32), g, axis=2)
    vf = np.repeat(np.asarray(v, np.float32), g, axis=2)
    starts = (np.zeros(b, np.int64) if kv_start is None
              else np.asarray(kv_start, np.int64))
    outs = np.zeros((b, 1, h, d), np.float32)
    for i in range(b):
        qpos = int(kv_lens[i]) - 1
        kg = starts[i] + np.arange(s_k)
        mask = kg <= qpos
        if window is not None:
            mask &= (qpos - kg) < window
        if not mask.any():
            continue  # fully masked row: the kernel's -inf lse sentinel
        s = np.einsum("hd,shd->hs",
                      np.asarray(q[i, 0], np.float32) / np.sqrt(d), kf[i])
        s[:, ~mask] = -np.inf
        p = np.exp(s - s.max(axis=1, keepdims=True))
        p /= p.sum(axis=1, keepdims=True)
        outs[i, 0] = np.einsum("hs,shd->hd", p, vf[i])
    return outs


def _decode_qkv(b, s_k, h, h_kv, d, kv_lens, seed=0, kv_start=None):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, 1, h, d), jnp.float32)
    k = np.array(jax.random.normal(ks[1], (b, s_k, h_kv, d)))
    v = np.array(jax.random.normal(ks[2], (b, s_k, h_kv, d)))
    # poison every position the mask must exclude: a wrong/missing mask
    # turns into a huge numeric diff, not a subtle one
    starts = np.zeros(b, np.int64) if kv_start is None else np.asarray(kv_start)
    for i in range(b):
        k[i, max(0, kv_lens[i] - starts[i]):] = 1e4
        v[i, max(0, kv_lens[i] - starts[i]):] = 1e4
    return q, jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("ratio", [1, 2, 4])
@pytest.mark.parametrize("window", [None, 24])
def test_flash_decode_matches_oracle(ratio, window):
    """Single query row vs dense reference across GQA ratio x window;
    per-sequence kv_lens land mid-block and at block boundaries, with
    poisoned K/V beyond every length."""
    kv_lens = np.array([1, 37, 128, 160], np.int32)  # edges + mid-block
    q, k, v = _decode_qkv(4, 160, 4, 4 // ratio, 16, kv_lens, seed=ratio)
    out = flash_decode_attention(q, k, v, kv_lens, window=window)
    ref = decode_oracle(q, k, v, kv_lens, window=window)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_flash_decode_kv_start_offsets():
    """The windowed-gather contract: k[:, 0] sits at a per-sequence
    global position (page-aligned or not); masks must stay global.
    Covers kv_offset at non-zero block-size boundaries (128 = one
    block_k) and unaligned starts."""
    starts = np.array([0, 128, 37], np.int64)
    kv_lens = np.array([60, 170, 95], np.int32)
    q, k, v = _decode_qkv(3, 64, 4, 2, 16, kv_lens, seed=9,
                          kv_start=starts)
    for window in (None, 16):
        out = flash_decode_attention(q, k, v, kv_lens, window=window,
                                     kv_start=starts)
        ref = decode_oracle(q, k, v, kv_lens, window=window,
                            kv_start=starts)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5,
                                   atol=2e-5, err_msg=f"window={window}")


def test_flash_decode_fully_masked_rows_are_zero():
    """kv_lens<=0 pad slots (and window pushed fully past the gather)
    ride the -inf lse sentinel: all-zero output, no NaN."""
    kv_lens = np.array([0, 48, 0], np.int32)
    q, k, v = _decode_qkv(3, 64, 4, 2, 16, kv_lens, seed=3)
    out = np.asarray(flash_decode_attention(q, k, v, kv_lens))
    assert np.isfinite(out).all()
    assert np.all(out[0] == 0) and np.all(out[2] == 0)
    ref = decode_oracle(q, k, v, kv_lens)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_flash_decode_validates():
    q = jnp.zeros((2, 3, 4, 16))
    kv = jnp.zeros((2, 64, 2, 16))
    with pytest.raises(ValueError, match="q_len=1"):
        flash_decode_attention(q, kv, kv, np.array([1, 1]))
    with pytest.raises(ValueError, match="window"):
        flash_decode_attention(jnp.zeros((2, 1, 4, 16)), kv, kv,
                               np.array([1, 1]), window=0)
