"""Keras 3 adapter tests.

Reference analog: test/parallel/test_tensorflow2_keras.py (SURVEY.md §4) —
DistributedOptimizer under model.fit, the four callbacks, elastic
KerasState.  Single-process world (per-rank semantics are covered by the
launcher integration tests).
"""

import numpy as np
import pytest

keras = pytest.importorskip("keras")

import horovod_tpu.keras as hvd  # noqa: E402


def _tiny_model():
    model = keras.Sequential([
        keras.Input(shape=(4,)),
        keras.layers.Dense(8, activation="relu"),
        keras.layers.Dense(1),
    ])
    return model


def _data(n=64):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 4).astype(np.float32)
    y = (x @ rng.randn(4, 1)).astype(np.float32)
    return x, y


def test_distributed_optimizer_fit_reduces_loss():
    model = _tiny_model()
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(0.05))
    model.compile(optimizer=opt, loss="mse")
    x, y = _data()
    hist = model.fit(x, y, batch_size=16, epochs=5, verbose=0)
    losses = hist.history["loss"]
    assert losses[-1] < losses[0] * 0.7, losses


def test_distributed_optimizer_matches_plain_sgd():
    x, y = _data(32)
    w_init = None
    results = []
    for distributed in (False, True):
        keras.utils.set_random_seed(0)
        model = _tiny_model()
        if w_init is None:
            w_init = model.get_weights()
        else:
            model.set_weights(w_init)
        opt = keras.optimizers.SGD(0.1)
        if distributed:
            opt = hvd.DistributedOptimizer(opt)
        model.compile(optimizer=opt, loss="mse")
        model.fit(x, y, batch_size=32, epochs=3, shuffle=False, verbose=0)
        results.append(model.get_weights())
    for a, b in zip(*results):
        # world of one process: allreduce is identity, so training must
        # match plain SGD bit-for-bit up to float noise
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_distributed_optimizer_rejects_double_wrap():
    # ADVICE round 3: wrapping twice used to recurse infinitely inside
    # super(self.__class__, self).apply — must be a clear error instead.
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(0.1))
    with pytest.raises(ValueError, match="already"):
        hvd.DistributedOptimizer(opt)


def test_warmup_default_initial_lr_uses_process_count(monkeypatch):
    # ADVICE round 3: gradient averaging divides by the PROCESS count
    # (cross_size), so the warmup default must start from
    # target/processes, not target/chips.
    from horovod_tpu.common import basics
    from horovod_tpu.keras.callbacks import LearningRateWarmupCallback

    monkeypatch.setattr(basics, "is_initialized", lambda: True)
    monkeypatch.setattr(basics, "cross_size", lambda: 2)
    monkeypatch.setattr(basics, "size", lambda: 16)  # 8 chips/process
    cb = LearningRateWarmupCallback(target_lr=0.8)
    assert cb._initial() == pytest.approx(0.4)


def test_backward_passes_per_step_aggregates():
    model = _tiny_model()
    opt = hvd.DistributedOptimizer(
        keras.optimizers.SGD(0.05), backward_passes_per_step=2
    )
    model.compile(optimizer=opt, loss="mse", run_eagerly=True)
    x, y = _data()
    w_before = [w.copy() for w in model.get_weights()]
    hist = model.fit(x, y, batch_size=16, epochs=3, verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0]
    assert any(
        not np.allclose(a, b)
        for a, b in zip(w_before, model.get_weights())
    )


def test_broadcast_callback_single_process():
    model = _tiny_model()
    model.compile(optimizer=hvd.DistributedOptimizer(
        keras.optimizers.SGD(0.01)), loss="mse")
    x, y = _data(32)
    w0 = [w.copy() for w in model.get_weights()]
    cb = hvd.callbacks.BroadcastGlobalVariablesCallback(0)
    model.fit(x, y, batch_size=32, epochs=1, verbose=0, callbacks=[cb])
    assert cb._done  # broadcast executed (identity at world 1)
    assert len(w0) == len(model.get_weights())


def test_metric_average_callback_single_process():
    cb = hvd.callbacks.MetricAverageCallback()
    logs = {"loss": 1.5, "acc": 0.5}
    cb.on_epoch_end(0, logs)
    assert logs == {"loss": 1.5, "acc": 0.5}  # world of 1: unchanged


def test_lr_warmup_callback_ramps():
    model = _tiny_model()
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(0.0))
    model.compile(optimizer=opt, loss="mse")
    cb = hvd.callbacks.LearningRateWarmupCallback(
        target_lr=0.8, warmup_epochs=2, steps_per_epoch=2, initial_lr=0.0
    )
    x, y = _data(64)
    model.fit(x, y, batch_size=32, epochs=3, verbose=0, callbacks=[cb])
    # warmup finished: LR pinned at target
    assert abs(float(np.array(model.optimizer.learning_rate)) - 0.8) < 1e-6


def test_lr_schedule_callback_staircase():
    model = _tiny_model()
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(1.0))
    model.compile(optimizer=opt, loss="mse")
    cb = hvd.callbacks.LearningRateScheduleCallback(
        initial_lr=1.0, multiplier=lambda e: 0.1 ** e, start_epoch=0
    )
    x, y = _data(32)
    model.fit(x, y, batch_size=32, epochs=3, verbose=0, callbacks=[cb])
    # last epoch (2) set lr = 1.0 * 0.1^2
    assert abs(float(np.array(model.optimizer.learning_rate)) - 0.01) < 1e-8


def test_keras_state_commit_restore_with_optimizer():
    model = _tiny_model()
    model.compile(optimizer=keras.optimizers.SGD(0.05), loss="mse")
    x, y = _data(32)
    model.fit(x, y, batch_size=32, epochs=1, verbose=0)  # builds optimizer
    state = hvd.elastic.KerasState(model, epoch=1)
    state.commit()
    w_committed = [w.copy() for w in model.get_weights()]
    model.fit(x, y, batch_size=32, epochs=1, verbose=0)
    state.epoch = 2
    state.restore()
    for got, want in zip(model.get_weights(), w_committed):
        np.testing.assert_allclose(got, want)
    assert state.epoch == 1


def test_commit_state_callback_commits_every_n():
    class DummyState:
        def __init__(self):
            self.commits = 0

        def commit(self):
            self.commits += 1

    st = DummyState()
    cb = hvd.elastic.CommitStateCallback(st, batches_per_commit=2)
    for b in range(6):
        cb.on_train_batch_end(b)
    assert st.commits == 3


@pytest.mark.parametrize("backend", ["jax", "torch"])
def test_alt_backend_distributed_optimizer_subprocess(backend):
    """KERAS_BACKEND=jax reaches the eager engine via jax.pure_callback
    from inside keras's jitted train step; KERAS_BACKEND=torch bridges
    grads through numpy and returns torch tensors.  A subprocess per
    backend is required because the keras backend is fixed at import."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = (
        "import numpy as np, keras\n"
        "import horovod_tpu.keras as hvd\n"
        "hvd.init()\n"
        f"assert keras.backend.backend() == '{backend}'\n"
        "model = keras.Sequential([keras.Input(shape=(4,)),"
        " keras.layers.Dense(1)])\n"
        "opt = hvd.DistributedOptimizer(keras.optimizers.SGD(0.05))\n"
        "model.compile(optimizer=opt, loss='mse')\n"
        "rng = np.random.RandomState(0)\n"
        "x = rng.randn(64, 4).astype(np.float32)\n"
        "y = (x @ rng.randn(4, 1)).astype(np.float32)\n"
        "h = model.fit(x, y, batch_size=16, epochs=4, verbose=0)\n"
        "assert h.history['loss'][-1] < h.history['loss'][0] * 0.7\n"
        "print('ALT-BACKEND-OK')\n"
    )
    env = os.environ.copy()
    env.update({"KERAS_BACKEND": backend, "PALLAS_AXON_POOL_IPS": "",
                "JAX_PLATFORMS": "cpu", "TF_CPP_MIN_LOG_LEVEL": "3",
                "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", "")})
    env.pop("XLA_FLAGS", None)
    res = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=300,
                         cwd=repo)
    assert res.returncode == 0, res.stderr[-3000:]
    assert "ALT-BACKEND-OK" in res.stdout


def test_load_model_round_trips_distributed_optimizer(tmp_path):
    """Reference: hvd.keras.load_model — the REAL scenario: a model
    saved mid-training with a DistributedOptimizer-wrapped optimizer
    (whose dynamic subclass rides the saved config) must load and come
    back wrapped."""
    import keras
    import numpy as np

    import horovod_tpu.keras as hvd_keras

    model = keras.Sequential([keras.Input(shape=(4,)),
                              keras.layers.Dense(2)])
    model.compile(
        optimizer=hvd_keras.DistributedOptimizer(
            keras.optimizers.SGD(0.05)),
        loss="mse",
    )
    x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    model.fit(x, np.zeros((8, 2), np.float32), epochs=1, verbose=0)
    path = str(tmp_path / "m.keras")
    model.save(path)
    loaded = hvd_keras.load_model(path)
    assert hasattr(loaded.optimizer, "_hvd_passes_per_step") or \
        "Distributed" in type(loaded.optimizer).__name__
    # the restored model still trains
    loaded.fit(x, np.zeros((8, 2), np.float32), epochs=1, verbose=0)


def test_broadcast_global_variables_contract():
    """Keras-3 mapping of broadcast_global_variables: explicit models
    broadcast deterministically; the bare TF1-style call raises with
    migration guidance instead of guessing at live models."""
    import keras
    import numpy as np
    import pytest

    import horovod_tpu.keras as hvd_keras

    model = keras.Sequential([keras.Input(shape=(3,)),
                              keras.layers.Dense(2)])
    before = [np.asarray(w) for w in model.get_weights()]
    hvd_keras.broadcast_global_variables(0, models=model)
    for a, b in zip(before, model.get_weights()):
        np.testing.assert_allclose(a, np.asarray(b))
    with pytest.raises(ValueError, match="BroadcastGlobalVariables"):
        hvd_keras.broadcast_global_variables(0)
