"""compression.py unit tests — the module's first (ISSUE 7 satellite).

Covers the whole-tensor :class:`Compression` contract (reference:
horovod/torch/compression.py) and the new DCN-hop
:class:`DcnCompression` shard contract: pytree roundtrips, mixed
float/int leaves, fp64 leaves, the fp16 finite-range clamp, and the
error-feedback residual algebra.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.compression import (
    Compression,
    DcnCompression,
    dcn_compression_from_name,
)


def _tree():
    return {
        "w": jnp.asarray(np.linspace(-2.0, 2.0, 12, dtype=np.float32)),
        "b": (jnp.asarray([1.5, -0.25], jnp.float32),
              jnp.asarray([3, -7], jnp.int32)),
        "step": jnp.asarray(11, jnp.int32),
    }


class TestCompression:
    @pytest.mark.parametrize("comp,wire", [
        (Compression.fp16, jnp.float16),
        (Compression.bf16, jnp.bfloat16),
    ])
    def test_pytree_roundtrip_casts_only_wide_floats(self, comp, wire):
        tree = _tree()
        wired, ctx = comp.compress(tree)
        assert wired["w"].dtype == wire
        assert wired["b"][0].dtype == wire
        # non-float leaves ride through untouched
        assert wired["b"][1].dtype == jnp.int32
        assert wired["step"].dtype == jnp.int32
        out = comp.decompress(wired, ctx)
        assert jax.tree_util.tree_structure(out) == \
            jax.tree_util.tree_structure(tree)
        assert out["w"].dtype == jnp.float32
        np.testing.assert_allclose(
            np.asarray(out["w"]), np.asarray(tree["w"]), rtol=1e-2)
        np.testing.assert_array_equal(
            np.asarray(out["b"][1]), np.asarray(tree["b"][1]))

    def test_none_compressor_is_identity(self):
        tree = _tree()
        wired, ctx = Compression.none.compress(tree)
        assert wired is tree and ctx is None
        assert Compression.none.decompress(wired, ctx) is tree

    def test_fp64_leaves_compress_and_restore(self):
        with jax.experimental.enable_x64():
            x = {"p": jnp.asarray([1.0, -2.5], jnp.float64)}
            assert x["p"].dtype == jnp.float64
            wired, ctx = Compression.bf16.compress(x)
            assert wired["p"].dtype == jnp.bfloat16
            out = Compression.bf16.decompress(wired, ctx)
            assert out["p"].dtype == jnp.float64

    def test_fp16_overflow_clamps_to_finite(self):
        # fp16 max finite is 65504: a large fp32 gradient must saturate,
        # not become inf and poison the whole reduction (ISSUE 7)
        big = jnp.asarray([1e6, -1e6, 3.0], jnp.float32)
        wired, ctx = Compression.fp16.compress(big)
        w = np.asarray(wired, np.float32)
        assert np.isfinite(w).all(), w
        lim = float(np.finfo(np.float16).max)
        np.testing.assert_allclose(w[:2], [lim, -lim])
        out = np.asarray(Compression.fp16.decompress(wired, ctx))
        assert np.isfinite(out).all()

    def test_bf16_keeps_fp32_range(self):
        # bf16 shares fp32's exponent: the same magnitudes stay exact in
        # range — the documented recommendation over fp16
        big = jnp.asarray([1e6, -3e38], jnp.float32)
        wired, _ = Compression.bf16.compress(big)
        assert np.isfinite(np.asarray(wired, np.float32)).all()


class TestDcnCompression:
    def test_shard_roundtrip(self):
        comp = DcnCompression("bfloat16")
        shard = jnp.asarray(np.linspace(-4, 4, 64, dtype=np.float32))
        wire, residual = comp.compress_shard(shard)
        assert wire.dtype == jnp.bfloat16
        assert residual is None  # error feedback off
        back = comp.decompress_shard(wire, shard.dtype)
        assert back.dtype == jnp.float32
        np.testing.assert_allclose(
            np.asarray(back), np.asarray(shard), rtol=1e-2)

    def test_narrow_and_int_shards_pass_through(self):
        comp = DcnCompression("bfloat16")
        for shard in (jnp.asarray([1, 2], jnp.int32),
                      jnp.asarray([1.0, 2.0], jnp.bfloat16),
                      jnp.asarray([1.0], jnp.float16)):
            wire, _ = comp.compress_shard(shard)
            assert wire.dtype == shard.dtype

    def test_fp16_wire_clamps(self):
        comp = DcnCompression("float16")
        wire, _ = comp.compress_shard(jnp.asarray([1e9, -1e9], jnp.float32))
        assert np.isfinite(np.asarray(wire, np.float32)).all()

    def test_error_feedback_residual_algebra(self):
        comp = DcnCompression("bfloat16", error_feedback=True)
        shard = jnp.asarray(
            np.random.RandomState(0).randn(128).astype(np.float32))
        wire, res = comp.compress_shard(shard, None)
        # residual IS the quantization error of this step
        np.testing.assert_allclose(
            np.asarray(res),
            np.asarray(shard) - np.asarray(wire, np.float32),
            rtol=0, atol=0,
        )
        # next step: the carried residual is added back before the cast,
        # so the two-step wire sum tracks the two-step true sum to within
        # ONE quantization error, not two (the EF-SGD invariant:
        # sum(wire_i) == sum(shard_i) - res_final)
        wire2, res2 = comp.compress_shard(shard, res)
        total_wire = np.asarray(wire, np.float64) + np.asarray(
            wire2, np.float64)
        total_true = 2.0 * np.asarray(shard, np.float64)
        np.testing.assert_allclose(
            total_wire + np.asarray(res2, np.float64), total_true,
            rtol=1e-6,
        )

    def test_rejects_non_float_wire(self):
        with pytest.raises(ValueError):
            DcnCompression("int8")

    def test_from_name(self):
        assert dcn_compression_from_name(None) is None
        assert dcn_compression_from_name("") is None
        assert dcn_compression_from_name("none") is None
        assert dcn_compression_from_name("off") is None
        c = dcn_compression_from_name("bf16")
        assert c is not None and c.wire_dtype == jnp.bfloat16
        assert not c.error_feedback  # routed path is stateless
        assert dcn_compression_from_name("fp16").wire_dtype == jnp.float16
        assert dcn_compression_from_name("float16").wire_dtype == jnp.float16

    def test_from_name_garbled_warns_and_disables(self):
        # env convention (env_float): a typo'd knob falls back instead
        # of killing the first routed collective of a long job
        from horovod_tpu import compression as C

        assert dcn_compression_from_name("bf61") is None  # typo of bf16
        assert dcn_compression_from_name("int8") is None  # non-float
        # wider-or-equal wires are silent no-ops, not compression
        assert dcn_compression_from_name("float32") is None
        assert dcn_compression_from_name("float64") is None
        # warned once per spelling, not per collective (the resolver
        # runs on every routed call)
        assert {"bf61", "int8", "float32"} <= C._warned_wire_dtypes
