"""Eager collective API tests (single-process degenerate semantics +
handle/async machinery + fusion).

Reference analog: the np=1 cases of test/parallel/test_torch.py plus the
handle tests (allreduce_async/synchronize/poll).  Multi-process eager paths
get exercised by the tpurun integration tests.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.ops.fusion import FusionPlan, fuse, unfuse


def test_allreduce_identity_single():
    x = jnp.arange(6, dtype=jnp.float32).reshape(2, 3)
    np.testing.assert_allclose(np.asarray(hvd.allreduce(x)), np.asarray(x))
    np.testing.assert_allclose(
        np.asarray(hvd.allreduce(x, op=hvd.Sum)), np.asarray(x)
    )


def test_allreduce_scaling():
    x = jnp.ones((4,), jnp.float32)
    out = hvd.allreduce(x, op=hvd.Sum, prescale_factor=0.5,
                        postscale_factor=4.0)
    np.testing.assert_allclose(np.asarray(out), 2.0 * np.ones(4))


def test_allreduce_pytree():
    tree = {"a": jnp.ones((3,)), "b": {"c": jnp.zeros((2, 2))}}
    out = hvd.allreduce(tree)
    assert set(out) == {"a", "b"}
    np.testing.assert_allclose(np.asarray(out["b"]["c"]), np.zeros((2, 2)))


def test_async_handle():
    x = jnp.ones((8,), jnp.float32)
    h = hvd.allreduce_async(x)
    assert isinstance(h, hvd.Handle)
    out = hvd.synchronize(h)
    np.testing.assert_allclose(np.asarray(out), np.ones(8))
    assert hvd.poll(h)


def test_grouped_allreduce():
    ts = [jnp.ones((2,)), jnp.full((3,), 2.0)]
    outs = hvd.grouped_allreduce(ts, op=hvd.Sum)
    assert len(outs) == 2
    np.testing.assert_allclose(np.asarray(outs[1]), [2.0, 2.0, 2.0])


def test_allgather_single():
    x = jnp.arange(4).reshape(2, 2)
    np.testing.assert_array_equal(np.asarray(hvd.allgather(x)), np.asarray(x))


def test_broadcast_single():
    x = jnp.arange(3.0)
    np.testing.assert_allclose(np.asarray(hvd.broadcast(x, 0)), np.asarray(x))
    with pytest.raises(ValueError):
        hvd.broadcast(x, root_rank=99)


def test_alltoall_single():
    x = jnp.arange(8.0)
    out, splits = hvd.alltoall(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))
    assert int(np.asarray(splits)[0]) == 8


def test_reducescatter_single():
    x = jnp.arange(8.0)
    np.testing.assert_allclose(np.asarray(hvd.reducescatter(x)),
                               np.asarray(x))


def test_barrier_and_join_single():
    hvd.barrier()
    assert hvd.join() == hvd.rank()


def test_broadcast_parameters_and_object():
    params = {"w": jnp.ones((3, 3)), "b": jnp.zeros((3,))}
    out = hvd.broadcast_parameters(params, root_rank=0)
    np.testing.assert_allclose(np.asarray(out["w"]), np.ones((3, 3)))
    obj = {"epoch": 3, "name": "x"}
    assert hvd.broadcast_object(obj, 0) == obj
    assert hvd.allgather_object(obj) == [obj]


def test_op_average_conflict():
    with pytest.raises(ValueError):
        hvd.allreduce(jnp.ones(2), average=True, op=hvd.Sum)


def test_fusion_roundtrip():
    leaves = [
        jnp.arange(5, dtype=jnp.float32),
        jnp.ones((2, 3), jnp.float32),
        jnp.arange(4, dtype=jnp.int32),
        jnp.zeros((1,), jnp.float32),
    ]
    plan = FusionPlan(leaves, threshold_bytes=1 << 20)
    fused = fuse(leaves, plan)
    # one f32 bucket + one i32 bucket
    assert len(fused) == 2
    out = unfuse(fused, plan)
    for a, b in zip(leaves, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.dtype == b.dtype


def test_fusion_threshold_splits_buckets():
    leaves = [jnp.ones((1024,), jnp.float32) for _ in range(4)]
    plan = FusionPlan(leaves, threshold_bytes=4096)  # one tensor per bucket
    assert len(plan.buckets) == 4
    fused = fuse(leaves, plan)
    out = unfuse(fused, plan)
    assert len(out) == 4


def test_fusion_deterministic_signature():
    leaves = [jnp.ones((3,)), jnp.ones((4,), jnp.int32)]
    p1 = FusionPlan(leaves, 64)
    p2 = FusionPlan(leaves, 64)
    assert p1.signature() == p2.signature()
    assert [b[1] for b in p1.buckets] == [b[1] for b in p2.buckets]


def test_prescale_rejected_for_min():
    with pytest.raises(ValueError):
        hvd.allreduce(jnp.ones(2), op=hvd.Min, prescale_factor=2.0)


def test_fusion_threshold_zero_disables_fusion():
    leaves = [jnp.ones((4,), jnp.float32), jnp.ones((4,), jnp.float32)]
    plan = FusionPlan(leaves, threshold_bytes=0)
    assert len(plan.buckets) == 2  # one bucket per tensor
    out = unfuse(fuse(leaves, plan), plan)
    np.testing.assert_array_equal(np.asarray(out[0]), np.ones(4))


def test_scalar_allreduce_preserves_zero_d_shape():
    """0-d inputs round-trip as 0-d through the native fused path
    (regression: ascontiguousarray promotes 0-d to 1-d; the unpack
    reshape must use the original shape)."""
    out = hvd.allreduce(jnp.asarray(3.0), name="scalar_rt", op=hvd.Sum)
    assert np.asarray(out).shape == ()
    assert float(out) == 3.0


def test_profiler_bridge_spans_in_xplane_capture(tmp_path):
    """The jax.profiler bridge (utils/profiler.py) puts ENQUEUE/XLA_COMM
    spans into an XPlane capture with the same names the Chrome timeline
    uses — SURVEY.md §5.1's 'framework spans next to XLA ops' view."""
    import glob
    import gzip
    import json

    logdir = str(tmp_path / "trace")
    x = jnp.arange(1024, dtype=jnp.float32)
    hvd.allreduce(x, name="bridge_warm")  # compile outside the capture
    jax.profiler.start_trace(logdir)
    try:
        out = hvd.allreduce(x, name="bridge_probe", op=hvd.Sum)
        jax.block_until_ready(out)
    finally:
        jax.profiler.stop_trace()
    traces = glob.glob(
        os.path.join(logdir, "plugins", "profile", "*", "*.trace.json.gz")
    )
    assert traces, "no trace file produced"
    with gzip.open(traces[0]) as f:
        events = json.load(f)["traceEvents"]
    names = {str(e.get("name", "")) for e in events}
    assert any("hvd_tpu::bridge_probe" in n and "ENQUEUE" in n
               for n in names), sorted(n for n in names if "hvd" in n)
    assert any("hvd_tpu::bridge_probe" in n and "XLA_COMM" in n
               for n in names), sorted(n for n in names if "hvd" in n)


def test_grouped_reducescatter_single():
    """np=1 degenerate: each entry's full reduction is its own chunk
    (reference: torch grouped_reducescatter surface)."""
    a, b = jnp.arange(6.0), jnp.ones((4,)) * 3.0
    ra, rb = hvd.grouped_reducescatter([a, b], op=hvd.Sum, name="grs1")
    np.testing.assert_allclose(np.asarray(ra), np.asarray(a))
    np.testing.assert_allclose(np.asarray(rb), np.asarray(b))


def test_build_capability_flags():
    """Reference: horovod/common/basics.py capability probes — scripts
    branch on these; every backend the reference can report is answered
    honestly (XLA yes, everything else no)."""
    assert hvd.xla_built()
    for probe in (hvd.nccl_built, hvd.mpi_built, hvd.mpi_enabled,
                  hvd.mpi_threads_supported, hvd.gloo_built,
                  hvd.gloo_enabled, hvd.ccl_built, hvd.cuda_built,
                  hvd.rocm_built, hvd.ddl_built):
        assert probe() is False, probe
