"""Fused BN(+residual)+ReLU kernel numerics (ops/fused_norm.py).

The pallas kernels run in interpreter mode on the CPU mesh and must
match the XLA reference implementation bit-for-bit in structure:
forward outputs, batch stats, and all gradients (x, gamma, beta,
residual), including the lane-folded C < 128 path.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.ops.fused_norm import fused_batch_norm_act


@pytest.mark.parametrize(
    "shape,relu,with_res",
    [
        ((4, 8, 8, 256), True, False),
        ((4, 8, 8, 256), True, True),
        ((4, 8, 8, 256), False, False),
        ((8, 4, 4, 64), True, True),  # lane-folded channels
    ],
)
def test_fused_bn_act_matches_reference(shape, relu, with_res):
    rng = np.random.RandomState(0)
    c = shape[-1]
    x = jnp.asarray(rng.randn(*shape), jnp.float32)
    gamma = jnp.asarray(rng.rand(c) + 0.5, jnp.float32)
    beta = jnp.asarray(rng.randn(c), jnp.float32)
    res = jnp.asarray(rng.randn(*shape), jnp.float32) if with_res else None
    dy = jnp.asarray(rng.randn(*shape), jnp.float32)

    def run(impl):
        def f(x, gamma, beta, res):
            y, mean, var = fused_batch_norm_act(
                x, gamma, beta, res, relu=relu, impl=impl)
            return (y * dy).sum(), (y, mean, var)

        argnums = (0, 1, 2) + ((3,) if with_res else ())
        (_, aux), grads = jax.value_and_grad(
            f, argnums=argnums, has_aux=True)(x, gamma, beta, res)
        return aux, grads

    (y0, m0, v0), g0 = run("reference")
    (y1, m1, v1), g1 = run("interpret")
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1), atol=2e-5)
    np.testing.assert_allclose(np.asarray(m0), np.asarray(m1), atol=1e-5)
    np.testing.assert_allclose(np.asarray(v0), np.asarray(v1), atol=1e-5)
    for a, b in zip(g0, g1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-4, rtol=2e-5)


def test_fused_bn_running_stats_contract():
    """The (mean, var) outputs are the biased batch stats a BN wrapper
    folds into running averages (reference: torch BN semantics)."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 4, 4, 128), jnp.float32)
    gamma = jnp.ones((128,))
    beta = jnp.zeros((128,))
    _, mean, var = fused_batch_norm_act(x, gamma, beta, impl="reference")
    xf = np.asarray(x).reshape(-1, 128)
    np.testing.assert_allclose(np.asarray(mean), xf.mean(0), atol=1e-5)
    np.testing.assert_allclose(np.asarray(var), xf.var(0), atol=1e-5)
