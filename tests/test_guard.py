"""Silent-corruption guard (horovod_tpu.guard): digests, detectors,
cross-rank agreement + attribution, rollback, and the training-step
threading contracts (docs/FAULT_TOLERANCE.md, silent corruption).

The two standing oracles this file pins:

* the guarded step is BIT-identical to the unguarded step when no
  fault fires (state and loss; the diagnostics are pure extra outputs);
* the guard adds ZERO collectives to the compiled step — enabled or
  not (the digest exchange rides the host control plane at cadence),
  so ``HVD_TPU_GUARD=0`` trivially lowers to the baseline program.

The end-to-end closed loop (detect -> attribute -> quarantine -> roll
back -> exact convergence) is proved by ``tools/chaos_soak.py``'s
``sdc`` scenario over real elastic worker processes.
"""

import os
import re
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from horovod_tpu import checkpoint as hvd_checkpoint
from horovod_tpu import guard, training
from horovod_tpu.elastic import ObjectState
from horovod_tpu.models.simple import MLP


# -- digests -----------------------------------------------------------------


@pytest.mark.parametrize("value", [
    np.arange(12, dtype=np.float32).reshape(3, 4),
    np.arange(5, dtype=np.int32),
    np.ones((3,), np.float16),
    np.array([True, False, True]),
    np.arange(4, dtype=np.float64),  # downcast like jnp.asarray (no x64)
], ids=["f32", "i32", "f16", "bool", "f64"])
def test_host_digest_equals_device_digest(value):
    np.testing.assert_array_equal(
        guard.host_digest([value]), np.asarray(guard.device_digest([value])))


def test_digest_bf16_and_nested_tree():
    import ml_dtypes

    np.testing.assert_array_equal(
        guard.host_digest(np.ones((7,), ml_dtypes.bfloat16)),
        np.asarray(guard.device_digest(jnp.ones((7,), jnp.bfloat16))))
    tree = {"a": np.ones((4, 4), np.float32),
            "b": {"c": np.arange(3, dtype=np.int32)}}
    np.testing.assert_array_equal(
        guard.host_digest(tree), np.asarray(guard.device_digest(tree)))


def test_digest_catches_any_single_bit_flip():
    """Lane 0's odd multipliers make a single flipped bit PROVABLY
    visible — sweep a few positions across words and bit indices."""
    base = np.ones((64,), np.float32)
    d0 = guard.host_digest([base])
    for word, bit in [(0, 0), (17, 3), (31, 22), (63, 31), (40, 15)]:
        mutant = base.copy()
        mutant.view(np.uint32)[word] ^= np.uint32(1 << bit)
        assert (guard.host_digest([mutant]) != d0).any(), (word, bit)


def test_digest_is_content_deterministic_and_order_sensitive():
    a = np.arange(8, dtype=np.float32)
    np.testing.assert_array_equal(guard.host_digest([a]),
                                  guard.host_digest([a.copy()]))
    # leaf order participates (the fold salts by leaf index)
    assert (guard.host_digest([a, a * 2]) !=
            guard.host_digest([a * 2, a])).any()


def test_allfinite_sentinel():
    assert bool(guard.device_allfinite(
        {"a": np.ones(3), "b": np.arange(3)}))
    assert not bool(guard.device_allfinite({"a": np.array([1.0, np.nan])}))
    assert not bool(guard.device_allfinite([np.array([np.inf])]))
    # int-only trees are vacuously finite
    assert bool(guard.device_allfinite([np.arange(4)]))


# -- exchange + agreement ----------------------------------------------------


def _run_ranks(board, world, fn):
    """Drive one guard per rank on threads (the soak does it with real
    processes); returns {rank: fn's result}."""
    results = {}

    def _one(rank):
        ex = guard.FileBoardExchange(str(board), timeout=20)
        g = guard.IntegrityGuard(
            cadence=4, world=world, rank=rank, exchange=ex,
            exit_fn=lambda code: results.setdefault(("exit", rank), code))
        results[rank] = fn(g, rank)

    ts = [threading.Thread(target=_one, args=(r,)) for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return results


CLEAN = guard.host_digest([np.ones((), np.float32)])


@pytest.fixture(autouse=True)
def _clean_guard_env():
    """Rollback arms cross-execv env markers; tests must not leak them
    into each other (the fuse counter would accumulate)."""
    markers = (guard.ENV_ROLLBACK_T0, guard.ENV_GEN,
               guard.ENV_ROLLBACK_COUNT, guard.ENV_ROLLBACK_STEP,
               guard.ENV_VERIFIED)
    for k in markers:
        os.environ.pop(k, None)
    yield
    for k in markers:
        os.environ.pop(k, None)


def _bad_digest():
    x = np.ones((), np.float32)
    x.reshape(-1).view(np.uint32)[0] ^= np.uint32(1 << 22)
    return guard.host_digest([x])


def test_agreement_verified_advances_watermark(tmp_path):
    def fn(g, rank):
        g.observe_grads(3, CLEAN)
        g.observe_grads(4, CLEAN)
        v = g.check(4, loss=1.0)
        return v, g.last_verified_step

    out = _run_ranks(tmp_path, 2, fn)
    for rank in (0, 1):
        v, watermark = out[rank]
        assert v.ok and v.kind == "verified"
        assert watermark == 4


def test_pairwise_mismatch_recompute_vote_attributes_the_liar(tmp_path):
    """Two ranks disagree — no majority.  The redundant-recompute vote:
    the corrupt rank's own recompute contradicts what it published, so
    it attributes ITSELF, and the verdict round tells the survivor."""
    def fn(g, rank):
        for s in (1, 2, 3, 4):
            g.observe_grads(
                s, _bad_digest() if (rank == 1 and s == 2) else CLEAN)
        return g.check(4, loss=4.0, recompute=lambda s: CLEAN)

    out = _run_ranks(tmp_path, 2, fn)
    for rank in (0, 1):
        v = out[rank]
        assert not v.ok and v.kind == "mismatch"
        assert v.attributed == [1]
        assert v.divergent_step == 2
        assert v.self_attributed == (rank == 1)


def test_majority_vote_attributes_without_recompute(tmp_path):
    def fn(g, rank):
        for s in (1, 2, 3, 4):
            g.observe_grads(
                s, _bad_digest() if (rank == 2 and s == 3) else CLEAN)
        return g.check(4, loss=4.0)  # no recompute hook at all

    out = _run_ranks(tmp_path, 3, fn)
    for rank in range(3):
        assert out[rank].attributed == [2], out[rank]
        assert out[rank].self_attributed == (rank == 2)


def test_param_only_divergence_without_recompute_is_unattributed(tmp_path):
    """Identical windows but diverged param fingerprints (the drift
    predates the window) and no majority: nobody is named — the
    response degrades to rollback-for-everyone."""
    def fn(g, rank):
        g.observe_grads(4, CLEAN)
        pd = _bad_digest() if rank == 1 else CLEAN
        return g.check(4, loss=4.0, param_digest=pd)

    out = _run_ranks(tmp_path, 2, fn)
    for rank in (0, 1):
        v = out[rank]
        assert not v.ok and v.kind == "mismatch"
        assert v.attributed == [] and not v.self_attributed
        assert v.divergent_step is None


def test_board_generation_hides_stale_entries(tmp_path):
    """Entries from before a rollback must read as ABSENT, not fresh:
    a gen-0 file for the same key is ignored by a gen-1 gather (and the
    poll then times out on the missing peer)."""
    ex0 = guard.FileBoardExchange(str(tmp_path), timeout=5, generation=0)
    ex0.gather("chk-4", b"stale", world=1, rank=0)  # publishes rank0 file
    ex1 = guard.FileBoardExchange(str(tmp_path), timeout=0.3, generation=1)
    out = ex1.gather("chk-4", b"fresh", world=2, rank=1)
    assert out[1] == b"fresh"
    assert out[0] is None  # gen-0 entry treated as not-yet-posted
    # same generation DOES read
    ex1b = guard.FileBoardExchange(str(tmp_path), timeout=5, generation=1)
    out = ex1b.gather("chk-4", b"peer", world=2, rank=0)
    assert out[1] == b"fresh"


def test_missing_peer_times_out_to_partial_not_failure(tmp_path):
    ex = guard.FileBoardExchange(str(tmp_path), timeout=0.3)
    g = guard.IntegrityGuard(cadence=4, world=2, rank=0, exchange=ex)
    g.observe_grads(4, CLEAN)
    v = g.check(4, loss=1.0)
    assert v.ok and v.kind == "partial"
    # an unverified window must NOT advance the rollback watermark
    assert g.last_verified_step == 0


# -- local detectors ---------------------------------------------------------


def test_nan_verdict_and_respond_raises_integrity_error(tmp_path):
    g = guard.IntegrityGuard(cadence=1, world=1,
                             ckpt_dir=str(tmp_path / "ck"))
    v = g.check(1, loss=float("nan"))
    assert not v.ok and v.kind == "nan"
    with pytest.raises(guard.IntegrityError):
        g.respond(v)


def test_finite_flag_false_trips_without_loss(tmp_path):
    g = guard.IntegrityGuard(cadence=1, world=1)
    v = g.check(1, finite=False)
    assert not v.ok and v.kind == "nan"


def test_loss_spike_is_advisory():
    g = guard.IntegrityGuard(cadence=1, world=1, spike=5.0)
    for i in range(1, 5):
        v = g.check(i, loss=1.0)
        assert v.ok and not v.spike
    v = g.check(5, loss=100.0)
    assert v.ok and v.spike  # flagged, never failing by itself
    # spike=0 disables the detector
    g2 = guard.IntegrityGuard(cadence=1, world=1, spike=0.0)
    for i in range(1, 6):
        assert not g2.check(i, loss=10.0 ** i).spike


# -- rollback ----------------------------------------------------------------


def test_rollback_discards_poisoned_window_and_raises(tmp_path):
    ckpt = str(tmp_path / "ring")
    state = ObjectState(step=0, weight=np.zeros(()))
    for step in range(1, 7):
        state.step = step
        hvd_checkpoint.save_state_checkpoint(ckpt, state, step, keep=10)
    g = guard.IntegrityGuard(cadence=4, world=1, ckpt_dir=ckpt)
    g.last_verified_step = 4
    with pytest.raises(guard.IntegrityError):
        g.rollback(reason="test", step=6)
    step, _snap = hvd_checkpoint.peek_state_checkpoint(ckpt)
    assert step == 4  # 5 and 6 were inside the poisoned window
    # the restart markers were armed for the (not-taken) exec path
    assert os.environ.pop(guard.ENV_GEN) == "1"
    t0 = os.environ.pop(guard.ENV_ROLLBACK_T0)
    assert float(t0) > 0
    # a fresh guard books the rollback wall time from the marker
    os.environ[guard.ENV_ROLLBACK_T0] = t0
    g2 = guard.IntegrityGuard(cadence=4, world=1)
    assert g2.last_rollback_s is not None and g2.last_rollback_s >= 0
    assert guard.ENV_ROLLBACK_T0 not in os.environ


def test_rollback_loop_fuse_refuses_deterministic_reproduction():
    """The same step tripping repeatedly (a deterministic divergence,
    not transient SDC) must NOT restart forever: past
    HVD_TPU_GUARD_MAX_ROLLBACKS the guard refuses with a clear error —
    and a verified check PAST the tripping step disarms the fuse."""
    g = guard.IntegrityGuard(cadence=4, world=1)
    g.max_rollbacks = 2
    for _ in range(2):
        with pytest.raises(guard.IntegrityError, match="rolled the"):
            g.rollback(reason="nan", step=8)  # the normal rollback
    with pytest.raises(guard.IntegrityError,
                       match="refusing another restart"):
        g.rollback(reason="nan", step=8)  # fuse blown
    # the env markers survive an execv: a fresh guard inherits the fuse
    g2 = guard.IntegrityGuard(cadence=4, world=1)
    assert g2._rollback_count == 2 and g2._rollback_barrier == 8
    # a verified check at a step BEYOND the barrier disarms it
    g2.check(12, loss=1.0)
    assert g2._rollback_count == 0 and g2._rollback_barrier == -1
    assert guard.ENV_ROLLBACK_COUNT not in os.environ
    # ...and rolling back again afterwards starts a fresh count
    with pytest.raises(guard.IntegrityError, match="rolled the"):
        g2.rollback(reason="nan", step=16)


def test_respond_quarantines_self_attributed(tmp_path):
    codes = []
    g = guard.IntegrityGuard(cadence=1, world=1,
                             exit_fn=lambda c: codes.append(c))
    v = guard.Verdict(step=4, ok=False, kind="mismatch", attributed=[0],
                      self_attributed=True)
    g.respond(v)
    assert codes == [guard.QUARANTINE_EXIT]


def test_discard_newer_than_is_concurrency_tolerant(tmp_path):
    ckpt = str(tmp_path)
    state = ObjectState(step=0)
    for step in (1, 2, 3):
        hvd_checkpoint.save_state_checkpoint(ckpt, state, step, keep=10)
    removed = hvd_checkpoint.discard_newer_than(ckpt, 1)
    assert sorted(os.path.basename(p) for p in removed) == \
        ["ckpt-2", "ckpt-3"]
    assert hvd_checkpoint.discard_newer_than(ckpt, 1) == []


# -- training-step threading -------------------------------------------------


@pytest.fixture(scope="module")
def mlp_setup():
    model = MLP(features=(16, 10))
    opt = optax.adam(1e-3)
    rng = jax.random.PRNGKey(0)
    x = np.random.default_rng(0).random((16, 8)).astype(np.float32)
    y = np.random.default_rng(1).integers(0, 10, (16,))
    state = training.replicate_state(
        training.create_train_state(model, opt, rng, x[:2]))
    return model, opt, state, x, y


def _copy(state):
    return jax.tree_util.tree_map(jnp.copy, state)


def test_guarded_step_bit_identical_and_diag_shape(mlp_setup):
    model, opt, state, x, y = mlp_setup
    plain = training.data_parallel_train_step(model, opt, guard=False)
    guarded = training.data_parallel_train_step(model, opt, guard=True)
    sa, la = plain(_copy(state), x, y)
    sb, lb, diag = guarded(_copy(state), x, y)
    assert float(la) == float(lb)
    for a, b in zip(jax.tree_util.tree_leaves(sa),
                    jax.tree_util.tree_leaves(sb)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert bool(np.asarray(diag["finite"]))
    assert np.asarray(diag["digest"]).shape == (2,)
    # deterministic: same inputs, same digest; advanced state differs
    _, _, diag2 = guarded(_copy(state), x, y)
    np.testing.assert_array_equal(np.asarray(diag["digest"]),
                                  np.asarray(diag2["digest"]))
    _, _, diag3 = guarded(sb, x, y)
    assert (np.asarray(diag3["digest"]) !=
            np.asarray(diag["digest"])).any()


def test_guard_adds_zero_collectives(mlp_setup):
    """The zero-guard-collectives contract: the guarded program's
    collective inventory equals the baseline's, so HVD_TPU_GUARD=0
    trivially lowers to a program with zero guard collectives."""
    model, opt, state, x, y = mlp_setup
    colls = re.compile(
        r"stablehlo\.(all_reduce|all_gather|reduce_scatter|"
        r"collective_permute|all_to_all)")

    def inventory(step):
        return len(colls.findall(step.lower(_copy(state), x, y).as_text()))

    plain = training.data_parallel_train_step(model, opt, guard=False)
    guarded = training.data_parallel_train_step(model, opt, guard=True)
    assert inventory(plain) == inventory(guarded) > 0


def test_guard_env_default(mlp_setup, monkeypatch):
    model, opt, state, x, y = mlp_setup
    monkeypatch.setenv("HVD_TPU_GUARD", "1")
    step = training.data_parallel_train_step(model, opt)  # guard=None
    out = step(_copy(state), x, y)
    assert len(out) == 3
    monkeypatch.setenv("HVD_TPU_GUARD", "0")
    step = training.data_parallel_train_step(model, opt)
    assert len(step(_copy(state), x, y)) == 2


def test_zero_guard_bit_identical_with_shard_tap(mlp_setup):
    model, opt, _state, x, y = mlp_setup
    rng = jax.random.PRNGKey(0)
    st_g, step_g, _ = training.zero_train_setup(
        model, optax.sgd(1e-2), rng, x[:2], guard=True)
    st_p, step_p, _ = training.zero_train_setup(
        model, optax.sgd(1e-2), rng, x[:2], guard=False)
    sa, la, diag = step_g(st_g, x, y)
    sb, lb = step_p(st_p, x, y)
    assert float(la) == float(lb)
    for a, b in zip(jax.tree_util.tree_leaves(sa.params),
                    jax.tree_util.tree_leaves(sb.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert bool(np.asarray(diag["finite"]))
    assert np.asarray(diag["digest"]).shape == (2,)


def test_fit_epoch_drives_the_guard(mlp_setup):
    """fit_epoch feeds per-step diagnostics and runs the cadence check
    (world=1: the local detectors + watermark advance)."""
    model, opt, state, x, y = mlp_setup
    step = training.data_parallel_train_step(model, opt, guard=True)
    g = guard.IntegrityGuard(cadence=2, world=1)
    loader = [(x, y)] * 4
    out_state, loss = training.fit_epoch(step, _copy(state), loader,
                                         guard=g)
    assert loss is not None and np.isfinite(loss)
    assert g.last_verified_step == 4  # checks ran at steps 2 and 4
    assert int(out_state.step) == 4


def test_step_diag_composes_manually():
    loss = jnp.asarray(1.5)
    grads = {"w": jnp.ones((4,), jnp.float32)}
    diag = jax.jit(guard.step_diag)(loss, grads)
    assert bool(diag["finite"])
    np.testing.assert_array_equal(
        np.asarray(diag["digest"]), guard.host_digest(grads))


def test_nan_rank_still_joins_the_exchange(tmp_path):
    """A NaN-tripped rank must NOT bail before the exchange: its peers
    are already entering this step's gather and would block on a
    collective (or a board timeout) that never completes.  The nan flag
    rides the payload instead — both ranks verdict 'nan' in the same
    number of rounds, nobody hangs (review finding)."""
    def fn(g, rank):
        g.observe_grads(4, CLEAN)
        return g.check(4, loss=float("nan") if rank == 1 else 1.0)

    out = _run_ranks(tmp_path, 2, fn)
    for rank in (0, 1):
        v = out[rank]
        assert not v.ok and v.kind == "nan", (rank, v)
        assert not v.self_attributed  # nan names a value, not a rank
    # the clean rank's verdict carries the origin for the logs
    assert "rank(s) [1]" in out[0].detail


def test_verified_watermark_survives_the_rollback_restart():
    """last_verified_step rides the env across the exec-restart: a
    SECOND trip after a rollback must discard only past the inherited
    watermark — a fresh guard resetting to 0 would hand
    discard_newer_than(0) the entire ring (review finding)."""
    g = guard.IntegrityGuard(cadence=4, world=1)
    g.check(32, loss=1.0)  # verified: watermark 32, env armed
    assert os.environ[guard.ENV_VERIFIED] == "32"
    # the post-execv guard inherits it instead of restarting at 0
    g2 = guard.IntegrityGuard(cadence=4, world=1)
    assert g2.last_verified_step == 32


def test_majority_vote_never_attributes_an_absent_vote(tmp_path):
    """A rank whose window lacks the divergent step (restarted
    mid-window) casts NO vote — it must not be quarantined by absence
    (review finding)."""
    def fn(g, rank):
        for s in (1, 2, 3, 4):
            if rank == 3 and s <= 2:
                continue  # rank 3 joined mid-window: no entry at s=2
            g.observe_grads(
                s, _bad_digest() if (rank == 2 and s == 2) else CLEAN)
        return g.check(4, loss=4.0)

    out = _run_ranks(tmp_path, 4, fn)
    for rank in range(4):
        assert out[rank].attributed == [2], (rank, out[rank])
        assert out[rank].self_attributed == (rank == 2)


def test_absent_param_fingerprint_is_abstention_not_mismatch(tmp_path):
    """param_digest is optional per rank: one rank fingerprinting and
    the other not must VERIFY when the windows agree — absence read as
    disagreement falsely tripped every cadence check until the
    rollback fuse killed the job (review finding)."""
    def fn(g, rank):
        g.observe_grads(4, CLEAN)
        pd = CLEAN if rank == 0 else None
        v = g.check(4, loss=1.0, param_digest=pd)
        return v, g.last_verified_step

    out = _run_ranks(tmp_path, 2, fn)
    for rank in (0, 1):
        v, watermark = out[rank]
        assert v.ok and v.kind == "verified", (rank, v)
        assert watermark == 4
