"""`horovod` compatibility alias tests (BASELINE.md north star:
reference scripts running UNMODIFIED).

Reference analog: the reference's own public import surface
(horovod/__init__.py + framework submodules, SURVEY.md §2.3) and its
`horovodrun` CLI (§2.4).  The alias package must hand back the SAME
module objects as horovod_tpu (no duplicated singleton state), and a
verbatim reference-style training script must train under a
``horovodrun -np 2`` console script with zero edits.
"""

import os
import stat
import subprocess
import sys

from envguards import requires_multiprocess_collectives

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "examples", "compat",
                      "pytorch_mnist_unmodified.py")


def test_alias_shares_module_objects():
    import horovod
    import horovod.torch as hvd_alias

    import horovod_tpu
    import horovod_tpu.torch as hvd_real

    assert hvd_alias is hvd_real
    # deep submodules too — a separate module instance would duplicate
    # handle tables and controller singletons
    import horovod.torch.elastic as a_el
    import horovod_tpu.torch.elastic as r_el

    assert a_el is r_el
    assert horovod.__version__ == horovod_tpu.__version__
    # the reference's flat top-level surface rides along
    assert callable(horovod.init) and callable(horovod.allreduce)


def test_alias_run_module():
    import horovod.run as hrun

    from horovod_tpu import runner

    assert hrun is runner
    # the reference's programmatic launcher lives at horovod.runner.run
    from horovod.runner import run, run_commandline

    assert callable(run) and callable(run_commandline)


def test_alias_missing_backend_parity():
    # horovod.mxnet must fail exactly like horovod_tpu.mxnet does in an
    # image without mxnet — the alias adds no masking layer
    with pytest.raises(ImportError):
        import horovod.mxnet  # noqa: F401


@pytest.mark.integration
@requires_multiprocess_collectives  # spawns an N-proc world running collectives
def test_unmodified_reference_script_under_horovodrun(tmp_path):
    """The whole north-star sentence, literally: a console script named
    ``horovodrun`` (same entry point the wheel installs) launches the
    unchanged-reference-imports example at -np 2 and it trains."""
    bindir = tmp_path / "bin"
    bindir.mkdir()
    shim = bindir / "horovodrun"
    # exactly what pyproject's [project.scripts] horovodrun resolves to
    shim.write_text(
        "#!" + sys.executable + "\n"
        "import sys\n"
        "from horovod_tpu.runner.launch import run_commandline\n"
        "sys.exit(run_commandline())\n"
    )
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)

    env = os.environ.copy()
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["TF_CPP_MIN_LOG_LEVEL"] = "3"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PATH"] = f"{bindir}:{env['PATH']}"
    env.pop("XLA_FLAGS", None)

    out = subprocess.run(
        ["horovodrun", "-np", "2", "--", sys.executable, SCRIPT,
         "--epochs", "2"],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "UNMODIFIED_OK" in out.stdout
