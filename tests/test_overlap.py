"""Backward/collective overlap scheduler (ISSUE 11, docs/tensor-fusion.md).

Covers, against the 8-virt-device session mesh:

* ``BucketSchedule`` determinism (permuted-but-equal leaf lists build the
  identical layout), reverse-production launch order, and the
  threshold-sensitive ``signature()`` (executable-cache collision guard);
* strict env validation of ``HVD_TPU_FUSION_THRESHOLD`` and the overlap/
  autotune knobs;
* the overlap oracle — gradients and optimizer updates bit-equal between
  overlapped and unoverlapped steps at fp32, ZeRO on and off, replicated
  and multi-axis (tp-sharded) alike;
* the StableHLO interleave check: each bucket's collective pinned between
  segment computations (``overlap_inventory``), with the unoverlapped
  program as the trailing negative control;
* the PR-7 ``measured_tier_bytes`` inventory idiom on the hierarchical
  (2-slice) overlapped program: modeled == measured per tier;
* ``BucketAutotuner`` convergence, default-never-regresses, budget
  exhaustion, and metric side effects;
* the torch bridge's deterministic bucket-ordered submission.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import training
from horovod_tpu.common.topology import DCN_AXIS, ICI_AXIS, WORLD_AXIS
from horovod_tpu.metrics import instruments as _metrics
from horovod_tpu.models.transformer import (
    Transformer, gpt_tiny, overlap_segments,
)
from horovod_tpu.ops.comm_model import (
    measured_tier_bytes, mesh_slice_ids, modeled_collective_bytes,
    modeled_overlap_exposed, overlap_inventory,
)
from horovod_tpu.ops.fusion import BucketSchedule, FusionPlan
from horovod_tpu.ops.overlap import (
    BucketAutotuner, Candidate, Segment, overlapped_value_and_grad,
    record_overlap_metrics, used_leaf_mask,
)


def _leaves(specs):
    return [jnp.zeros(s, d) for s, d in specs]


def _tree_max_diff(a, b):
    return max(
        jax.tree_util.tree_leaves(jax.tree_util.tree_map(
            lambda x, y: float(np.abs(np.asarray(x) - np.asarray(y)).max()),
            a, b,
        ))
    )


def _tree_bit_equal(a, b):
    return all(
        (np.asarray(x) == np.asarray(y)).all()
        for x, y in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
        )
    )


# -- BucketSchedule ----------------------------------------------------------


class TestBucketSchedule:
    SPECS = [
        ((64, 64), jnp.float32),   # 16 KiB
        ((32,), jnp.float32),
        ((64, 64), jnp.bfloat16),  # 8 KiB
        ((128, 64), jnp.float32),  # 32 KiB
        ((16, 16), jnp.float32),
    ]

    def test_permuted_but_equal_lists_build_identical_layout(self):
        leaves = _leaves(self.SPECS)
        order = list(range(len(leaves)))[::-1]  # explicit production order
        a = BucketSchedule(leaves, 20 * 1024, production_order=order)
        perm = [3, 0, 4, 1, 2]
        b = BucketSchedule(
            [leaves[i] for i in perm], 20 * 1024,
            production_order=[order[i] for i in perm],
        )
        assert a.layout() == b.layout()
        assert a.ready_at == b.ready_at
        assert a.bucket_nbytes == b.bucket_nbytes

    def test_reverse_production_launch_order(self):
        # default production order: reversed list order -> the LAST leaf
        # completes first and its bucket launches first
        leaves = _leaves([((8, 8), jnp.float32)] * 4)
        sched = BucketSchedule(leaves, 8 * 8 * 4)  # one leaf per bucket
        launch_leaves = [idxs[0] for _, idxs in sched.buckets]
        assert launch_leaves == [3, 2, 1, 0]
        assert sched.ready_at == [0, 1, 2, 3]

    def test_buckets_pack_consecutive_production_under_threshold(self):
        leaves = _leaves([((8, 8), jnp.float32)] * 6)  # 256 B each
        sched = BucketSchedule(leaves, 512)
        assert sched.num_buckets == 3
        assert all(n == 512 for n in sched.bucket_nbytes)
        # members of one bucket are consecutively produced
        for _, idxs in sched.buckets:
            prods = sorted(sched.production_order[i] for i in idxs)
            assert prods == list(range(prods[0], prods[0] + len(prods)))

    def test_zero_threshold_one_bucket_per_leaf(self):
        leaves = _leaves(self.SPECS)
        sched = BucketSchedule(leaves, 0)
        assert sched.num_buckets == len(leaves)

    def test_signature_distinguishes_thresholds(self):
        leaves = _leaves(self.SPECS)
        # the executable-cache collision guard: same leaves, different
        # HVD_TPU_FUSION_THRESHOLD -> different signature, for the plan
        # AND the schedule
        assert FusionPlan(leaves, 1 << 20).signature() != \
            FusionPlan(leaves, 1 << 10).signature()
        assert BucketSchedule(leaves, 1 << 20).signature() != \
            BucketSchedule(leaves, 1 << 10).signature()
        # and stays deterministic for equal inputs
        assert FusionPlan(leaves, 64).signature() == \
            FusionPlan(leaves, 64).signature()
        assert BucketSchedule(leaves, 64).signature() == \
            BucketSchedule(leaves, 64).signature()

    def test_from_specs_matches_array_build(self):
        leaves = _leaves(self.SPECS)
        a = BucketSchedule(leaves, 20 * 1024)
        b = BucketSchedule.from_specs(
            [(s, str(jnp.dtype(d))) for s, d in self.SPECS], 20 * 1024
        )
        assert a.layout() == b.layout()


# -- env validation ----------------------------------------------------------


class TestEnvValidation:
    def _from_env(self, monkeypatch, name, value):
        from horovod_tpu.utils.env_parser import Config

        monkeypatch.setenv(name, value)
        return Config.from_env()

    def test_garbage_fusion_threshold_rejected(self, monkeypatch):
        with pytest.raises(ValueError, match="FUSION_THRESHOLD"):
            self._from_env(monkeypatch, "HVD_TPU_FUSION_THRESHOLD", "64MB")

    def test_negative_fusion_threshold_rejected(self, monkeypatch):
        with pytest.raises(ValueError, match="FUSION_THRESHOLD"):
            self._from_env(monkeypatch, "HVD_TPU_FUSION_THRESHOLD", "-1")

    def test_zero_threshold_still_disables_fusion(self, monkeypatch):
        cfg = self._from_env(monkeypatch, "HVD_TPU_FUSION_THRESHOLD", "0")
        assert cfg.fusion_threshold_bytes == 0

    def test_overlap_bucket_bytes_validated(self, monkeypatch):
        with pytest.raises(ValueError, match="OVERLAP_BUCKET_BYTES"):
            self._from_env(
                monkeypatch, "HVD_TPU_OVERLAP_BUCKET_BYTES", "4MiB")
        cfg = self._from_env(
            monkeypatch, "HVD_TPU_OVERLAP_BUCKET_BYTES", "1048576")
        assert cfg.overlap_bucket_bytes == 1 << 20

    def test_autotune_trials_must_be_positive(self, monkeypatch):
        with pytest.raises(ValueError, match="OVERLAP_AUTOTUNE_TRIALS"):
            self._from_env(
                monkeypatch, "HVD_TPU_OVERLAP_AUTOTUNE_TRIALS", "0")


# -- the overlapped chain ----------------------------------------------------


def _mlp_chain(n_seg=4, d=16):
    rs = np.random.RandomState(0)
    params = {
        f"w{k}": jnp.asarray(np.round(rs.randn(d, d) * 8) / 8, jnp.float32)
        for k in range(n_seg)
    }

    def make(k):
        def seg(p, x):
            return jax.nn.relu(x @ p[f"w{k}"])

        return Segment(seg, keys=(f"w{k}",))

    def head(p, x):
        return jnp.mean((x @ p[f"w{n_seg - 1}"]) ** 2)

    segments = [make(k) for k in range(n_seg - 1)] + [
        Segment(head, keys=(f"w{n_seg - 1}",))
    ]
    x = jnp.asarray(
        np.round(rs.randn(hvd.size() * 2, d) * 8) / 8, jnp.float32
    )
    return segments, params, x


def _chain_fn(segments, world, bucket_bytes, overlap):
    def f(p, x):
        loss, grads, _ = overlapped_value_and_grad(
            segments, p, x,
            bucket_reduce=lambda b: jax.lax.psum(b, WORLD_AXIS)
            / jnp.asarray(world, b.dtype),
            bucket_bytes=bucket_bytes, overlap=overlap,
        )
        return loss, grads

    return jax.jit(jax.shard_map(
        f, mesh=hvd.world_mesh(), in_specs=(P(), P(WORLD_AXIS)),
        out_specs=(P(), P()), check_vma=False,
    ))


class TestOverlappedChain:
    def test_used_leaf_mask_detects_reads(self):
        params = {"a": jnp.ones((3,)), "b": jnp.ones((3,))}
        mask = used_leaf_mask(lambda p, x: p["a"] * x, params,
                              jnp.ones((3,)))
        # leaves flatten alphabetically: a, b
        assert mask == [True, False]

    def test_bare_callables_auto_detect(self):
        # segments WITHOUT declared keys take the jaxpr-analysis path
        segments, params, x = _mlp_chain()
        bare = [Segment(s.fn) for s in segments]
        world = hvd.size()
        f_decl = _chain_fn(segments, world, 1 << 10, True)
        f_auto = _chain_fn(bare, world, 1 << 10, True)
        l1, g1 = f_decl(params, x)
        l2, g2 = f_auto(params, x)
        assert float(l1) == float(l2)
        assert _tree_bit_equal(g1, g2)

    def test_grads_bit_equal_and_match_plain_grad(self):
        segments, params, x = _mlp_chain()
        world = hvd.size()
        f_ov = _chain_fn(segments, world, 1 << 10, True)
        f_un = _chain_fn(segments, world, 1 << 10, False)
        l1, g1 = f_ov(params, x)
        l2, g2 = f_un(params, x)
        assert float(l1) == float(l2)
        assert _tree_bit_equal(g1, g2)

        def plain(p, xx):
            def loss_fn(pp):
                h = xx
                for k in range(3):
                    h = jax.nn.relu(h @ pp[f"w{k}"])
                return jnp.mean((h @ pp["w3"]) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(p)
            return loss, jax.tree_util.tree_map(
                lambda t: jax.lax.psum(t, WORLD_AXIS)
                / jnp.asarray(world, t.dtype),
                grads,
            )

        f_plain = jax.jit(jax.shard_map(
            plain, mesh=hvd.world_mesh(), in_specs=(P(), P(WORLD_AXIS)),
            out_specs=(P(), P()), check_vma=False,
        ))
        l3, g3 = f_plain(params, x)
        assert float(l1) == float(l3)
        assert _tree_bit_equal(g1, g3)

    def test_stablehlo_interleave_and_negative_control(self):
        segments, params, x = _mlp_chain()
        world = hvd.size()
        f_ov = _chain_fn(segments, world, 1 << 10, True)
        f_un = _chain_fn(segments, world, 1 << 10, False)
        inv_ov = overlap_inventory(f_ov.lower(params, x).as_text())
        inv_un = overlap_inventory(f_un.lower(params, x).as_text())
        # every non-final bucket's collective has compute after it...
        assert inv_ov["interleaved"]
        assert all(
            op["compute_after"] > 0 for op in inv_ov["collectives"][:-1]
        )
        assert inv_ov["exposed_fraction"] < 1.0
        # ...while the unoverlapped control trails everything
        assert not inv_un["interleaved"]
        assert inv_un["exposed_fraction"] == 1.0
        assert all(
            op["compute_after"] == 0 for op in inv_un["collectives"]
        )

    def test_record_overlap_metrics_sets_gauge(self):
        segments, params, x = _mlp_chain()
        f_ov = _chain_fn(segments, hvd.size(), 1 << 10, True)
        inv = record_overlap_metrics(f_ov.lower(params, x).as_text())
        assert _metrics.OVERLAP_EXPOSED_FRACTION.get() == pytest.approx(
            inv["exposed_fraction"]
        )

    def test_scalar_loss_enforced(self):
        segments, params, x = _mlp_chain()
        bad = segments[:-1]  # chain now ends with a (B, d) activation
        with pytest.raises(ValueError, match="scalar loss"):
            overlapped_value_and_grad(
                bad, params, x, bucket_reduce=lambda b: b,
                bucket_bytes=1 << 10,
            )


class TestHierarchicalOverlapInventory:
    """The PR-7 measured_tier_bytes idiom on the OVERLAPPED program:
    each bucket's two-level reduction, launched at its bucket boundary,
    must show up in the lowered module with modeled == measured bytes
    per fabric tier."""

    def test_modeled_equals_measured_per_tier(self, monkeypatch):
        from horovod_tpu.ops import spmd_ops

        monkeypatch.setenv("HVD_TPU_SLICE_SIZE", "4")
        topo = hvd.common.basics._require_init().topology
        hmesh = topo.hierarchical_mesh()
        n_dcn, n_ici = hmesh.devices.shape
        world = n_dcn * n_ici
        segments, params, x = _mlp_chain(n_seg=4, d=16)

        def bucket_reduce(buf):
            red, _ = spmd_ops._two_level_sum_leaf(
                buf, ICI_AXIS, DCN_AXIS, None, None
            )
            return red / jnp.asarray(world, buf.dtype)

        def f(p, xx):
            loss, grads, _ = overlapped_value_and_grad(
                segments, p, xx, bucket_reduce=bucket_reduce,
                bucket_bytes=2 * 16 * 16 * 4,
            )
            return loss, grads

        fj = jax.jit(jax.shard_map(
            f, mesh=hmesh,
            in_specs=(P(), P((DCN_AXIS, ICI_AXIS))),
            out_specs=(P(), P()), check_vma=False,
        ))
        measured = measured_tier_bytes(
            fj.lower(params, x).as_text(), mesh_slice_ids(hmesh)
        )
        sched = BucketSchedule(
            jax.tree_util.tree_leaves(params), 2 * 16 * 16 * 4
        )
        want_ici = want_dcn = 0
        for nbytes in sched.bucket_nbytes:
            m = modeled_collective_bytes(
                (nbytes // 4,), world, n_ici, dtype="float32"
            )
            want_ici += m["ici_bytes"]
            want_dcn += m["dcn_bytes"]
        assert measured["ici_bytes"] == want_ici
        assert measured["dcn_bytes"] == want_dcn
        # and the interleave holds on the hierarchical program too
        inv = overlap_inventory(fj.lower(params, x).as_text())
        assert inv["interleaved"]


# -- train-step oracles ------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_lm():
    cfg = gpt_tiny(dtype=jnp.float32)
    model = Transformer(cfg)
    rng = jax.random.PRNGKey(0)
    tokens = jax.random.randint(rng, (8, 16), 0, cfg.vocab_size)
    targets = jax.random.randint(
        jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size
    )
    return model, rng, tokens, targets


class TestTrainStepOracles:
    def test_replicated_overlap_bit_equal_adamw(self, tiny_lm):
        model, rng, tokens, targets = tiny_lm
        opt = optax.adamw(1e-2)
        st_a = training.replicate_state(
            training.create_train_state(model, opt, rng, tokens[:1])
        )
        st_b = jax.tree_util.tree_map(jnp.copy, st_a)
        step_a = training.data_parallel_train_step(model, opt)
        step_b = training.data_parallel_train_step(
            model, opt, overlap=True, bucket_bytes=1 << 15
        )
        for _ in range(2):
            st_a, la = step_a(st_a, tokens, targets)
            st_b, lb = step_b(st_b, tokens, targets)
            assert float(la) == float(lb)
            assert _tree_max_diff(st_a.params, st_b.params) == 0.0

    def test_zero_overlap_bit_equal_sgd(self, tiny_lm):
        # the ISSUE-11 oracle: updates bit-equal, ZeRO ON, overlapped vs
        # unoverlapped (elementwise-exact inner; see the adamw test for
        # the FMA caveat)
        model, rng, tokens, targets = tiny_lm
        opt = optax.sgd(0.1)
        st_a, step_a, _ = training.zero_train_setup(
            model, opt, rng, tokens[:1]
        )
        st_b, step_b, _ = training.zero_train_setup(
            model, opt, rng, tokens[:1], overlap=True,
            bucket_bytes=1 << 15,
        )
        for _ in range(2):
            st_a, la = step_a(st_a, tokens, targets)
            st_b, lb = step_b(st_b, tokens, targets)
            assert float(la) == float(lb)
            assert _tree_max_diff(st_a.params, st_b.params) == 0.0

    def test_zero_overlap_adamw_fma_bound(self, tiny_lm):
        # XLA:CPU contracts adamw's nu update (g*g fma) differently
        # across globally-different programs: gradients stay bit-equal
        # (pinned below) but nu — and through it the params — may drift
        # by 1-2 ulp.  Pin the bound tightly so a real numerics
        # regression (not contraction noise) still fails loudly.
        model, rng, tokens, targets = tiny_lm
        opt = optax.adamw(1e-2)
        st_a, step_a, _ = training.zero_train_setup(
            model, opt, rng, tokens[:1]
        )
        st_b, step_b, _ = training.zero_train_setup(
            model, opt, rng, tokens[:1], overlap=True,
            bucket_bytes=1 << 15,
        )
        for _ in range(2):
            st_a, la = step_a(st_a, tokens, targets)
            st_b, lb = step_b(st_b, tokens, targets)
        assert float(la) == float(lb)
        assert _tree_max_diff(st_a.params, st_b.params) <= 4e-7

    def test_zero_overlap_grads_bit_equal(self, tiny_lm):
        # gradients (as opposed to fma-contracted updates) are bit-equal
        # under the ZeRO bucket exchange too: run one sgd step (update =
        # params - lr*grad, exact) and an identity-lr probe
        model, rng, tokens, targets = tiny_lm
        opt = optax.sgd(1.0)
        st_a, step_a, _ = training.zero_train_setup(
            model, opt, rng, tokens[:1]
        )
        st_b, step_b, _ = training.zero_train_setup(
            model, opt, rng, tokens[:1], overlap=True,
            bucket_bytes=1 << 15,
        )
        st_a, _ = step_a(st_a, tokens, targets)
        st_b, _ = step_b(st_b, tokens, targets)
        assert _tree_max_diff(st_a.params, st_b.params) == 0.0

    def test_zero_hierarchical_overlap_parity(self, monkeypatch, tiny_lm):
        # the two-level (2 slices x 4 chips) ZeRO exchange on the bucket
        # schedule: sgd updates bit-equal overlapped vs unoverlapped,
        # and with STATELESS bf16 wire compression the overlap
        # composition must not add quantization the unoverlapped path
        # doesn't have (the gradient gather runs full-precision — only
        # the RS hop and the update allgather carry the wire dtype)
        from horovod_tpu.compression import DcnCompression

        monkeypatch.setenv("HVD_TPU_SLICE_SIZE", "4")
        topo = hvd.common.basics._require_init().topology
        hmesh = topo.hierarchical_mesh()
        model, rng, tokens, targets = tiny_lm
        for comp in (None, DcnCompression("bfloat16")):
            opt = optax.sgd(0.1)
            st_a, step_a, _ = training.zero_train_setup(
                model, opt, rng, tokens[:1], hierarchical=True,
                mesh=hmesh, dcn_compression=comp,
            )
            st_b, step_b, _ = training.zero_train_setup(
                model, opt, rng, tokens[:1], hierarchical=True,
                mesh=hmesh, dcn_compression=comp, overlap=True,
                bucket_bytes=1 << 15,
            )
            for _ in range(2):
                st_a, la = step_a(st_a, tokens, targets)
                st_b, lb = step_b(st_b, tokens, targets)
            assert float(la) == float(lb)
            # the wire cast is elementwise, so even the compressed legs
            # agree bit-for-bit under an elementwise-exact inner
            assert _tree_max_diff(st_a.params, st_b.params) == 0.0

    def test_zero_overlap_rejects_error_feedback(self, tiny_lm):
        from horovod_tpu.compression import DcnCompression

        model, rng, tokens, _ = tiny_lm
        with pytest.raises(ValueError, match="error_feedback"):
            training.zero_train_setup(
                model, optax.sgd(0.1), rng, tokens[:1],
                hierarchical=True,
                dcn_compression=DcnCompression(
                    "bfloat16", error_feedback=True),
                overlap=True,
            )

    def test_overlap_rejects_batch_stats_models(self, tiny_lm):
        model, rng, tokens, targets = tiny_lm
        opt = optax.sgd(0.1)
        st = training.replicate_state(
            training.create_train_state(model, opt, rng, tokens[:1])
        )
        st = st.replace(batch_stats={"mean": jnp.zeros((2,))})
        step = training.data_parallel_train_step(
            model, opt, overlap=True
        )
        with pytest.raises(Exception, match="batch_stats"):
            step(st, tokens, targets)

    def test_overlap_requires_segmenter_for_unknown_models(self):
        import flax.linen as nn

        class Mlp(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(4)(x)

        with pytest.raises(ValueError, match="segment chain"):
            training.data_parallel_train_step(
                Mlp(), optax.sgd(0.1), overlap=True
            )


class TestMultiAxisOverlap:
    def test_sharded_step_bit_equal(self):
        from horovod_tpu.parallel import sharded as sh

        mesh = sh.multi_axis_mesh(dp=2, sp=1, tp=2,
                                  devices=jax.devices()[:4])
        model = sh.MultiAxisTransformer(
            vocab=64, d_model=32, num_heads=4, num_layers=2,
            seq_len=16, dtype=jnp.float32,
        )
        rng = jax.random.PRNGKey(0)
        variables, pspecs = sh.init_sharded(model, mesh, rng)
        opt = optax.adamw(1e-2)
        opt_state, ospecs = sh.init_opt_sharded(
            opt, variables, mesh, pspecs
        )
        tok = jax.random.randint(rng, (4, 16), 0, 64)
        tgt = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, 64)
        step_a = sh.make_sharded_train_step(
            model, opt, mesh, pspecs, ospecs
        )
        step_b = sh.make_sharded_train_step(
            model, opt, mesh, pspecs, ospecs, overlap=True,
            bucket_bytes=1 << 13,
        )
        cp = lambda t: jax.tree_util.tree_map(jnp.copy, t)  # noqa: E731
        pa, oa, tokc, tgtc = cp(variables), cp(opt_state), tok, tgt
        pb, ob = cp(variables), cp(opt_state)
        for _ in range(2):
            pa, oa, la = step_a(pa, oa, tokc, tgtc)
            pb, ob, lb = step_b(pb, ob, tokc, tgtc)
        assert float(la) == float(lb)
        assert _tree_max_diff(pa, pb) == 0.0

    def test_sharded_overlap_interleaves(self):
        from horovod_tpu.parallel import sharded as sh

        mesh = sh.multi_axis_mesh(dp=2, sp=1, tp=2,
                                  devices=jax.devices()[:4])
        model = sh.MultiAxisTransformer(
            vocab=64, d_model=32, num_heads=4, num_layers=2,
            seq_len=16, dtype=jnp.float32,
        )
        rng = jax.random.PRNGKey(0)
        variables, pspecs = sh.init_sharded(model, mesh, rng)
        opt = optax.sgd(0.1)
        opt_state, ospecs = sh.init_opt_sharded(
            opt, variables, mesh, pspecs
        )
        tok = jax.random.randint(rng, (4, 16), 0, 64)
        tgt = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, 64)
        step = sh.make_sharded_train_step(
            model, opt, mesh, pspecs, ospecs, overlap=True,
            bucket_bytes=1 << 13,
        )
        txt = step.lower(
            variables, opt_state, tok, tgt
        ).as_text()
        # scalar loss pmean filtered out: buckets are >= 1 KiB here
        inv = overlap_inventory(txt, min_payload_bytes=1024)
        assert len(inv["collectives"]) >= 2
        assert inv["interleaved"]
        assert inv["exposed_fraction"] < 1.0


# -- autotuner ---------------------------------------------------------------


class TestBucketAutotuner:
    CANDS = [Candidate(1 << 20), Candidate(4 << 20), Candidate(16 << 20)]

    def _drive(self, tuner, time_of):
        while not tuner.converged:
            cand = tuner.propose()
            tuner.observe(time_of(cand))
        return tuner

    def test_converges_to_argmin_within_budget(self):
        tuner = BucketAutotuner(
            candidates=self.CANDS, default=Candidate(8 << 20),
            trial_budget=8, steps_per_trial=3,
        )
        times = {1 << 20: 0.9, 4 << 20: 0.3, 8 << 20: 0.5, 16 << 20: 0.7}
        self._drive(tuner, lambda c: times[c.bucket_bytes])
        assert tuner.converged
        assert tuner.pinned.bucket_bytes == 4 << 20
        assert len(tuner.scores) <= 8
        # once pinned, propose() is stable and observe() is a no-op
        assert tuner.propose() == tuner.pinned
        tuner.observe(0.0001)
        assert tuner.pinned.bucket_bytes == 4 << 20

    def test_never_regresses_vs_default(self):
        # the default is the global best -> it must win (it is trial 0)
        tuner = BucketAutotuner(
            candidates=self.CANDS, default=Candidate(8 << 20),
            trial_budget=8, steps_per_trial=2,
        )
        times = {1 << 20: 0.9, 4 << 20: 0.8, 8 << 20: 0.1, 16 << 20: 0.7}
        self._drive(tuner, lambda c: times[c.bucket_bytes])
        assert tuner.pinned.bucket_bytes == 8 << 20

    def test_budget_exhaustion_pins_best_so_far(self):
        tuner = BucketAutotuner(
            candidates=self.CANDS, default=Candidate(8 << 20),
            trial_budget=2, steps_per_trial=1,
        )
        times = {1 << 20: 0.2, 4 << 20: 0.05, 8 << 20: 0.5, 16 << 20: 0.7}
        self._drive(tuner, lambda c: times[c.bucket_bytes])
        # only default + first candidate scored; best of those pinned
        assert len(tuner.scores) == 2
        assert tuner.pinned.bucket_bytes == 1 << 20

    def test_trial_counter_increments(self):
        before = _metrics.OVERLAP_AUTOTUNE_TRIALS.get()
        tuner = BucketAutotuner(
            candidates=self.CANDS[:1], default=Candidate(8 << 20),
            trial_budget=4, steps_per_trial=1,
        )
        self._drive(tuner, lambda c: 0.1)
        assert _metrics.OVERLAP_AUTOTUNE_TRIALS.get() == before + 2
        assert _metrics.OVERLAP_AUTOTUNE_PINNED_BYTES.get() == \
            tuner.pinned.bucket_bytes

    def test_first_step_of_trial_discarded(self):
        # the first observation pays the recompile; the median must
        # ignore it
        tuner = BucketAutotuner(
            candidates=[], default=Candidate(8 << 20),
            trial_budget=1, steps_per_trial=3,
        )
        for t in (9.0, 0.1, 0.1):  # compile spike first
            tuner.observe(t)
        assert tuner.converged
        assert tuner.scores[0][1] == pytest.approx(0.1)


# -- torch bridge ------------------------------------------------------------


class TestTorchBucketedSubmission:
    def test_bucket_ordered_drain_matches_local_sgd(self):
        torch = pytest.importorskip("torch")
        from horovod_tpu.common import basics
        from horovod_tpu.torch.optimizer import DistributedOptimizer

        cfg = basics._require_init().config
        old = cfg.overlap_bucket_bytes
        cfg.overlap_bucket_bytes = 64  # force several tiny buckets
        try:
            torch.manual_seed(0)
            model = torch.nn.Sequential(
                torch.nn.Linear(8, 16), torch.nn.ReLU(),
                torch.nn.Linear(16, 8), torch.nn.Linear(8, 4),
            )
            ref = torch.nn.Sequential(
                torch.nn.Linear(8, 16), torch.nn.ReLU(),
                torch.nn.Linear(16, 8), torch.nn.Linear(8, 4),
            )
            ref.load_state_dict(model.state_dict())
            opt = DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.1),
                named_parameters=model.named_parameters(),
            )
            ref_opt = torch.optim.SGD(ref.parameters(), lr=0.1)
            xb = torch.randn(4, 8)
            try:
                for _ in range(2):
                    opt.zero_grad()
                    model(xb).pow(2).mean().backward()
                    opt.step()
                    ref_opt.zero_grad()
                    ref(xb).pow(2).mean().backward()
                    ref_opt.step()
                # single-process world: distributed average == local grad,
                # so the bucketed submission must reproduce plain SGD
                for p, q in zip(model.parameters(), ref.parameters()):
                    assert torch.equal(p, q)
                # the deterministic schedule split the params into
                # several buckets
                assert len(set(opt._bucket_of.values())) >= 2
            finally:
                opt.close()
        finally:
            cfg.overlap_bucket_bytes = old


# -- modeled exposure --------------------------------------------------------


class TestModeledOverlap:
    def test_r4_point_drops_2x(self):
        # PERF.md round-4 measured inputs (tools/scaling_model.py):
        # ResNet-50, 47.6 ms step, 51.2 MB bf16 wire, ~200 GB/s ICI
        wire = int(25.6e6 * 2)
        bucket = 4 << 20
        n = -(-wire // bucket)
        sizes = [bucket] * (n - 1) + [wire - bucket * (n - 1)]
        m = modeled_overlap_exposed(sizes, 0.0476, 200e9, 256)
        assert m["exposed_fraction"] * 2 <= 1.0  # the >=2x bar
        assert m["t_step_s"] < 0.0476 + m["t_comm_s"]

    def test_unbucketed_exposes_nothing_hidden(self):
        # one bucket produced at the very end == the unoverlapped step
        m = modeled_overlap_exposed([1 << 20], 0.01, 1e9, 8)
        assert m["exposed_fraction"] == pytest.approx(1.0)

    def test_world_one_is_free(self):
        m = modeled_overlap_exposed([1 << 20], 0.01, 1e9, 1)
        assert m["t_comm_s"] == 0.0 and m["exposed_fraction"] == 0.0
