"""Launcher + multi-process integration tests.

Reference analog: test/integration/test_static_run.py (end-to-end
horovodrun on localhost) and the multi-node-without-a-cluster technique of
SURVEY.md §4: N real processes on one box, rendezvous over loopback — here
the JAX coordination service instead of the Gloo HTTP store.
"""

import os
import subprocess
import sys

import pytest

from envguards import requires_multiprocess_collectives

import horovod_tpu.runner.launch as launch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "integration", "launcher_worker.py")


def _run_tpurun(np_, extra=None, timeout=180, target=None,
                target_args=None):
    """Launch ``tpurun -np N`` on a per-rank script with the suite's
    standard child environment (CPU backend, repo on PYTHONPATH, one
    device per process).  Defaults to the collective-asserting WORKER."""
    env = os.environ.copy()
    env["PALLAS_AXON_POOL_IPS"] = ""  # force CPU in children
    env["JAX_PLATFORMS"] = "cpu"
    env["TF_CPP_MIN_LOG_LEVEL"] = "3"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)  # one CPU device per process
    if target is None:
        assert target_args is None, "target_args requires an explicit target"
        target, target_args = WORKER, [str(np_)]
    cmd = [
        sys.executable, "-m", "horovod_tpu.runner",
        "-np", str(np_), *(extra or []), "--",
        sys.executable, target, *(target_args or []),
    ]
    return subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=timeout,
        cwd=REPO,
    )


def test_host_parsing():
    assert launch.parse_host_spec("h1:4,h2:2") == [("h1", 4), ("h2", 2)]
    assert launch.parse_host_spec("h1") == [("h1", 1)]


def test_hostfile_parsing(tmp_path):
    f = tmp_path / "hosts"
    f.write_text("# comment\nnode1 slots=8\nnode2 slots=4\n")
    assert launch.parse_hostfile(str(f)) == [("node1", 8), ("node2", 4)]


def test_check_build():
    out = launch.check_build()
    assert "XLA" in out and "horovod_tpu" in out


def test_config_file_to_env(tmp_path):
    import yaml

    from horovod_tpu.runner.config_parser import (
        config_to_env, load_config_file,
    )

    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(yaml.safe_dump({
        "fusion_threshold": 1234, "autotune": True, "log_level": "debug",
    }))
    args = launch.build_parser().parse_args(
        ["--cycle-time-ms", "2.5", "--", "true"]
    )
    env = config_to_env(args, load_config_file(str(cfg)))
    assert env["HVD_TPU_FUSION_THRESHOLD"] == "1234"
    assert env["HVD_TPU_AUTOTUNE"] == "1"
    assert env["HVD_TPU_CYCLE_TIME"] == "2.5"  # CLI wins layering intact
    assert env["HVD_TPU_LOG_LEVEL"] == "debug"


def test_np_exceeding_slots_rejected(capsys):
    rc = launch.run_commandline(["-np", "4", "-H", "localhost:2", "--",
                                 "true"])
    assert rc == 2


@pytest.mark.parametrize("np_", [2])
@requires_multiprocess_collectives  # spawns an N-proc world running collectives
def test_tpurun_multiprocess_collectives(np_):
    """The big one: np real processes, jax.distributed rendezvous, every
    eager collective checked cross-process (python fallback controller)."""
    res = _run_tpurun(np_, extra=["--disable-native"])
    assert res.returncode == 0, f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    assert res.stdout.count("WORKER_OK") == np_


def test_tpurun_failure_propagates():
    env = os.environ.copy()
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, "-m", "horovod_tpu.runner", "-np", "2", "--",
           sys.executable, "-c", "import sys; sys.exit(3)"]
    res = subprocess.run(cmd, env=env, capture_output=True, text=True,
                         timeout=120, cwd=REPO)
    assert res.returncode == 3


@pytest.mark.parametrize("np_", [2, 3])
@requires_multiprocess_collectives  # spawns an N-proc world running collectives
def test_tpurun_multiprocess_native_controller(np_):
    """Same per-rank assertions with the C++ controller negotiating over
    its TCP star (reference analog: the gloo-controller path of
    test_static_run).  np=3 additionally exercises eager cross-process
    process-set collectives and ragged join fills."""
    res = _run_tpurun(np_)
    assert res.returncode == 0, f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    assert res.stdout.count("WORKER_OK") == np_
    assert "native=True" in res.stdout


@pytest.mark.integration
@requires_multiprocess_collectives  # spawns an N-proc world running collectives
def test_tpurun_tensorflow_adapter():
    """TF/Keras adapter under 2 real processes: tf.Tensor bridge, graph
    mode, DistributedGradientTape averaging, Keras optimizer lockstep
    (reference analog: test/parallel/test_tensorflow.py under
    horovodrun -np 2)."""
    tf_worker = os.path.join(REPO, "tests", "integration", "tf_worker.py")
    res = _run_tpurun(2, timeout=420, target=tf_worker, target_args=["2"])
    assert res.returncode == 0, f"stdout:\n{res.stdout}\nstderr:\n{res.stderr[-4000:]}"
    assert res.stdout.count("TF_WORKER_OK") == 2
    # the jit_compile=True leg must have RUN (bridge builds under g++,
    # which this image has) — a silent skip would mask a regression
    assert res.stdout.count("TF_WORKER_XLA_OK") == 2, res.stdout


@pytest.mark.integration
@requires_multiprocess_collectives  # spawns an N-proc world running collectives
def test_tpurun_keras_mnist_example():
    """The Keras example trains to high accuracy under 2 real processes —
    pins the full model.fit + DistributedOptimizer + callbacks path
    (reference analog: test/integration style end-to-end runs)."""
    example = os.path.join(REPO, "examples", "tensorflow2",
                           "tensorflow2_keras_mnist.py")
    res = _run_tpurun(2, timeout=420, target=example,
                      target_args=["--epochs", "1"])
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout[-2000:]}\nstderr:\n{res.stderr[-3000:]}"
    # rank-0 accuracy assertion ran inside the child
    assert "final accuracy" in res.stdout, res.stdout[-2000:]


@pytest.mark.integration
@requires_multiprocess_collectives  # spawns an N-proc world running collectives
def test_tpurun_keras_elastic_example():
    """The elastic Keras example (reference:
    tensorflow2_keras_mnist_elastic.py) trains under 2 real processes:
    KerasState sync, commit/epoch callbacks inside model.fit, resume via
    initial_epoch; the script asserts final accuracy."""
    example = os.path.join(REPO, "examples", "tensorflow2",
                           "tensorflow2_keras_mnist_elastic.py")
    res = _run_tpurun(2, timeout=420, target=example,
                      target_args=["--epochs", "2"])
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout[-2000:]}\nstderr:\n{res.stderr[-3000:]}"
    assert "KERAS_ELASTIC_OK" in res.stdout, res.stdout[-2000:]


@pytest.mark.integration
@requires_multiprocess_collectives  # spawns an N-proc world running collectives
def test_tpurun_negotiation_stress():
    """Randomized mixed-collective schedule, submitted async in a
    DIFFERENT order on every rank with timing jitter (the cross-rank
    readiness skew of SURVEY §3.2/§5.2).  Caught a real deadlock: the
    coordinator's group-atomicity check keyed on per-process group ids,
    which diverge under out-of-order submission (see group_table.h)."""
    worker = os.path.join(REPO, "tests", "integration", "stress_worker.py")
    res = _run_tpurun(3, timeout=300, target=worker, target_args=["3"])
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout[-2000:]}\nstderr:\n{res.stderr[-3000:]}"
    assert res.stdout.count("STRESS_OK") == 3


@pytest.mark.integration
@requires_multiprocess_collectives  # spawns an N-proc world running collectives
def test_tpurun_negotiation_stress_np8_soak():
    """np=8 + a longer seeded schedule (120 ops, different seed): more
    ranks means more cross-rank submission-order divergence and more
    partial-readiness cycles at the coordinator — the regime where the
    round-4 grouped deadlock and the round-5 wire-name mismatch both
    lived.  The batched-enqueue + CV-wake paths get their widest
    exercise here."""
    worker = os.path.join(REPO, "tests", "integration", "stress_worker.py")
    os.environ["HVD_TPU_STRESS_OPS"] = "120"
    os.environ["HVD_TPU_STRESS_SEED"] = "77"
    try:
        res = _run_tpurun(8, timeout=600, target=worker, target_args=["8"])
    finally:
        os.environ.pop("HVD_TPU_STRESS_OPS", None)
        os.environ.pop("HVD_TPU_STRESS_SEED", None)
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout[-2000:]}\nstderr:\n{res.stderr[-3000:]}"
    assert res.stdout.count("STRESS_OK") == 8


@pytest.mark.integration
@requires_multiprocess_collectives  # spawns an N-proc world running collectives
def test_tpurun_elastic_pretrain_example():
    """The elastic LM-pretrain example (BASELINE's elastic-Llama-pretrain
    analog at toy scale) trains under 2 real processes: elastic
    commit/restore wrapper + ElasticSampler + DistributedOptimizer grad
    averaging on the negotiated path; the script asserts the loss fell."""
    example = os.path.join(REPO, "examples", "jax",
                           "jax_elastic_pretrain.py")
    res = _run_tpurun(2, timeout=420, target=example,
                      target_args=["--epochs", "2", "--docs", "128"])
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout[-2000:]}\nstderr:\n{res.stderr[-3000:]}"
    assert "ELASTIC_PRETRAIN_OK" in res.stdout, res.stdout[-2000:]


@pytest.mark.integration
@requires_multiprocess_collectives  # spawns an N-proc world running collectives
def test_tpurun_pytorch_synthetic_example():
    """The torch synthetic benchmark example runs under 2 real processes
    (grad-hook DistributedOptimizer + state broadcasts end to end)."""
    example = os.path.join(REPO, "examples", "pytorch",
                           "pytorch_synthetic_benchmark.py")
    res = _run_tpurun(2, timeout=420, target=example,
                      target_args=["--num-iters", "3", "--num-warmup", "1"])
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout[-2000:]}\nstderr:\n{res.stderr[-3000:]}"
    assert "Total img/sec on 2 worker(s)" in res.stdout, res.stdout[-2000:]


@pytest.mark.integration
def test_jax_pipeline_example():
    """The GPipe example trains (8 virtual devices, loss halves — the
    script asserts it) with grad-outside-shard_map over the pp axis."""
    example = os.path.join(REPO, "examples", "jax", "jax_pipeline_mlp.py")
    env = os.environ.copy()
    env.update({
        "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    res = subprocess.run(
        [sys.executable, example, "--steps", "20"],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout[-2000:]}\nstderr:\n{res.stderr[-3000:]}"
    assert "pp=8 stages" in res.stdout


@pytest.mark.integration
@requires_multiprocess_collectives  # spawns an N-proc world running collectives
def test_tpurun_mxnet_adapter():
    """MXNet adapter under 2 real processes (faked-mxnet NDArray storage,
    real cross-process collectives): in-place/grouped ops, default-op
    reducescatter, broadcast_parameters, DistributedTrainer/Optimizer
    averaging (reference analog: test/parallel/test_mxnet.py)."""
    worker = os.path.join(REPO, "tests", "integration", "mxnet_worker.py")
    res = _run_tpurun(2, timeout=420, target=worker, target_args=["2"])
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout[-2000:]}\nstderr:\n{res.stderr[-3000:]}"
    assert res.stdout.count("MXNET_WORKER_OK") == 2


@pytest.mark.integration
@requires_multiprocess_collectives  # spawns an N-proc world running collectives
def test_tpurun_torch_adapter():
    """Torch adapter under 2 real processes: grouped ops, uneven
    alltoall, SyncBatchNorm global stats + gradient flow (reference
    analog: test/parallel/test_torch.py under horovodrun -np 2)."""
    worker = os.path.join(REPO, "tests", "integration", "torch_worker.py")
    res = _run_tpurun(2, timeout=420, target=worker, target_args=["2"])
    assert res.returncode == 0, \
        f"stdout:\n{res.stdout[-2000:]}\nstderr:\n{res.stderr[-3000:]}"
    assert res.stdout.count("TORCH_WORKER_OK") == 2
