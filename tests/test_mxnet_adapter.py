"""Contract tests for the MXNet adapter with a faked mxnet module.

Reference analog: test/parallel/test_mxnet.py (SURVEY.md §4).  Real
mxnet is not installable in this image (archived upstream), so — like
the pyspark/ray launch paths (VERDICT r3 item 5 technique) — these
tests inject a minimal fake `mxnet` (tests/_fake_modules/mxnet) and run
the REAL adapter bodies: the NDArray→numpy bridge, in-place writeback,
DistributedOptimizer's update hook, DistributedTrainer's
_allreduce_grads override, and broadcast_parameters.  Only NDArray
storage is faked; every collective goes through the shared eager
engine (single-process world: identity).
"""

import os
import sys

import numpy as np
import pytest

FAKES = os.path.join(os.path.dirname(__file__), "_fake_modules")


def _purge():
    for name in list(sys.modules):
        if name == "mxnet" or name.startswith("mxnet.") \
                or name == "horovod_tpu.mxnet" \
                or name.startswith("horovod_tpu.mxnet."):
            del sys.modules[name]


@pytest.fixture
def hvd_mx(monkeypatch):
    monkeypatch.syspath_prepend(FAKES)
    _purge()
    import mxnet as mx
    import horovod_tpu.mxnet as hvd

    yield mx, hvd
    _purge()


def test_allreduce_roundtrip_and_dtype(hvd_mx):
    mx, hvd = hvd_mx
    t = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    out = hvd.allreduce(t)
    assert isinstance(out, mx.nd.NDArray)
    assert out.dtype == np.float32 and out.shape == (2, 3)
    np.testing.assert_allclose(out.asnumpy(), t.asnumpy())


def test_allreduce_inplace_writes_back(hvd_mx):
    mx, hvd = hvd_mx
    t = mx.nd.array(np.ones(4, dtype=np.float32))
    ret = hvd.allreduce_(t, op=hvd.Sum)
    assert ret is t
    np.testing.assert_allclose(t.asnumpy(), np.ones(4))


def test_allreduce_prescale(hvd_mx):
    mx, hvd = hvd_mx
    t = mx.nd.array(np.ones(3, dtype=np.float32))
    out = hvd.allreduce(t, op=hvd.Sum, prescale_factor=2.0)
    np.testing.assert_allclose(out.asnumpy(), np.full(3, 2.0))


def test_grouped_allreduce_inplace(hvd_mx):
    mx, hvd = hvd_mx
    ts = [mx.nd.array(np.ones(2, dtype=np.float32)),
          mx.nd.array(np.full(3, 2.0, dtype=np.float32))]
    outs = hvd.grouped_allreduce_(ts)
    assert outs[0] is ts[0] and outs[1] is ts[1]
    np.testing.assert_allclose(ts[1].asnumpy(), np.full(3, 2.0))


def test_allgather_broadcast_alltoall_reducescatter(hvd_mx):
    mx, hvd = hvd_mx
    t = mx.nd.array(np.arange(4, dtype=np.float32))
    np.testing.assert_allclose(hvd.allgather(t).asnumpy(), t.asnumpy())
    np.testing.assert_allclose(
        hvd.broadcast(t, root_rank=0).asnumpy(), t.asnumpy())
    received, splits = hvd.alltoall(t)
    np.testing.assert_allclose(received.asnumpy(), t.asnumpy())
    assert int(splits.asnumpy().sum()) == 4
    np.testing.assert_allclose(
        hvd.reducescatter(t, op=hvd.Sum).asnumpy(), t.asnumpy())


def test_non_ndarray_rejected(hvd_mx):
    mx, hvd = hvd_mx
    with pytest.raises(ValueError, match="NDArray"):
        hvd.allreduce(np.ones(3))


def test_broadcast_parameters_dict_and_gluon(hvd_mx):
    mx, hvd = hvd_mx
    # plain dict of NDArrays (module get_params shape)
    params = {"w": mx.nd.array(np.ones(3, dtype=np.float32)),
              "b": mx.nd.array(np.zeros(2, dtype=np.float32))}
    hvd.broadcast_parameters(params, root_rank=0)
    np.testing.assert_allclose(params["w"].asnumpy(), np.ones(3))
    # gluon parameter collection (name -> Parameter with list_data)
    p = mx.gluon.Parameter("dense0_weight", shape=(2, 2))
    p.data()[:] = np.full((2, 2), 3.0)
    hvd.broadcast_parameters({"dense0_weight": p}, root_rank=0)
    np.testing.assert_allclose(p.data().asnumpy(), np.full((2, 2), 3.0))
    with pytest.raises(ValueError, match="dict"):
        hvd.broadcast_parameters([1, 2, 3])


def test_broadcast_parameters_deferred_init_hooks_init_impl(hvd_mx,
                                                            monkeypatch):
    """Deferred-shape gluon params broadcast right after their deferred
    init runs (reference: _append_broadcast_init wrapping _init_impl)."""
    mx, hvd = hvd_mx
    p = mx.gluon.Parameter("dense0_weight", shape=None)  # shape unknown
    names = []
    real = hvd.mpi_ops.broadcast_

    def recording(tensor, root_rank, **kw):
        names.append(kw.get("name"))
        return real(tensor, root_rank, **kw)

    monkeypatch.setattr("horovod_tpu.mxnet.functions.mpi_ops.broadcast_",
                        recording)
    hvd.broadcast_parameters({"dense0_weight": p}, root_rank=0)
    assert names == []  # nothing broadcast yet — shape still unknown
    p._init_impl(np.full((3, 2), 5.0))  # first forward resolves the shape
    assert len(names) == 1 and "dense0_weight" in names[0]
    np.testing.assert_allclose(p.data().asnumpy(), np.full((3, 2), 5.0))


def test_distributed_trainer_num_groups_batches_allreduces(hvd_mx,
                                                           monkeypatch):
    mx, hvd = hvd_mx
    params = {}
    for k in range(5):
        p = mx.gluon.Parameter(f"w{k}", shape=(2,))
        p.grad()[:] = np.ones(2)
        params[f"w{k}"] = p
    trainer = hvd.DistributedTrainer(params, "sgd", {"learning_rate": 0.1},
                                     num_groups=2)
    groups = []
    real = hvd.mpi_ops.grouped_allreduce_

    def recording(tensors, **kw):
        groups.append(len(tensors))
        return real(tensors, **kw)

    monkeypatch.setattr("horovod_tpu.mxnet.mpi_ops.grouped_allreduce_",
                        recording)
    monkeypatch.setattr(hvd.mpi_ops, "grouped_allreduce_", recording)
    trainer._allreduce_grads()
    assert sorted(groups) == [2, 3]  # 5 grads split across 2 groups


def test_distributed_optimizer_update_averages_then_applies(hvd_mx):
    mx, hvd = hvd_mx
    sgd = mx.optimizer.SGD(learning_rate=0.5)
    opt = hvd.DistributedOptimizer(sgd)
    w = mx.nd.array(np.full(3, 10.0, dtype=np.float32))
    g = mx.nd.array(np.full(3, 2.0, dtype=np.float32))
    opt.update(0, w, g, opt.create_state(0, w))
    # single-process world: averaged grad == grad; w -= lr * grad
    np.testing.assert_allclose(w.asnumpy(), np.full(3, 9.0))
    # delegation to the wrapped optimizer's attributes
    assert opt.learning_rate == 0.5
    with pytest.raises(ValueError, match="already"):
        hvd.DistributedOptimizer(opt)


def test_distributed_optimizer_wire_contract(hvd_mx, monkeypatch):
    """Pin the wire semantics: AVERAGE op with prescale 1/f on the
    collective, rescale_grad absorbing f (the ADVICE-r3 topology-safe
    recipe) — recorded by faking the engine-level call."""
    mx, hvd = hvd_mx
    from horovod_tpu.ops import collective_ops as _ops

    calls = []

    def fake_allreduce(tensor, average=None, name=None, op=None,
                       prescale_factor=1.0, postscale_factor=1.0,
                       process_set=None):
        calls.append(dict(average=average, op=op, name=name,
                          prescale=prescale_factor))
        return tensor

    monkeypatch.setattr(hvd.mpi_ops._ops, "allreduce", fake_allreduce)
    sgd = mx.optimizer.SGD(learning_rate=1.0)
    opt = hvd.DistributedOptimizer(sgd, gradient_predivide_factor=4.0)
    assert sgd.rescale_grad == pytest.approx(4.0)
    w = mx.nd.array(np.ones(2, dtype=np.float32))
    g = mx.nd.array(np.ones(2, dtype=np.float32))
    opt.update(7, w, g, None)
    assert len(calls) == 1
    assert calls[0]["average"] is True
    assert calls[0]["prescale"] == pytest.approx(0.25)
    assert "7" in calls[0]["name"]


def test_distributed_trainer_step(hvd_mx):
    mx, hvd = hvd_mx
    p = mx.gluon.Parameter("w", shape=(2,))
    p.data()[:] = np.full(2, 4.0)
    p.grad()[:] = np.full(2, 1.0)
    trainer = hvd.DistributedTrainer(
        {"w": p}, "sgd", {"learning_rate": 1.0})
    trainer.step(batch_size=1)
    # single-process: avg grad = 1.0; w -= lr * scale * grad, scale = 1
    np.testing.assert_allclose(p.data().asnumpy(), np.full(2, 3.0))
    with pytest.raises(ValueError, match="bare optimizer"):
        hvd.DistributedTrainer(
            {"w": p}, hvd.DistributedOptimizer(mx.optimizer.SGD()))


def test_distributed_trainer_skips_null_grads(hvd_mx, monkeypatch):
    mx, hvd = hvd_mx
    frozen = mx.gluon.Parameter("frozen", shape=(2,), grad_req="null")
    live = mx.gluon.Parameter("live", shape=(2,))
    live.grad()[:] = np.ones(2)
    trainer = hvd.DistributedTrainer(
        {"frozen": frozen, "live": live}, "sgd", {"learning_rate": 0.1})
    names = []
    real = hvd.mpi_ops.allreduce_

    def recording(tensor, **kw):
        names.append(kw.get("name"))
        return real(tensor, **kw)

    monkeypatch.setattr(hvd.mpi_ops, "allreduce_", recording)
    monkeypatch.setattr(hvd, "allreduce_", recording)
    trainer._allreduce_grads()
    assert len(names) == 1  # only the live param reduced
