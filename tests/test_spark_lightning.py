"""Lightning-estimator contract tests with a faked pytorch_lightning.

Reference analog: test/integration/test_spark_lightning.py (SURVEY.md
§2.4 lightning estimator row).  lightning is not installable in this
image, so — like the pyspark/ray/mxnet surfaces — a minimal fake
(tests/_fake_modules/pytorch_lightning) provides the LightningModule
base class; the estimator, worker loop (configure_optimizers →
DistributedOptimizer, training_step, validation_step,
on_train_epoch_end) and Store plumbing all run for real across 2
subprocess workers.
"""

import os
import sys

import numpy as np
import pytest

from envguards import requires_multiprocess_collectives

FAKES = os.path.join(os.path.dirname(__file__), "_fake_modules")


@pytest.fixture
def lightning_env(monkeypatch):
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    # workers must import the fake pytorch_lightning to unpickle the model
    monkeypatch.setenv(
        "PYTHONPATH",
        FAKES + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    monkeypatch.syspath_prepend(FAKES)
    yield
    for name in list(sys.modules):
        if name.startswith("pytorch_lightning"):
            del sys.modules[name]


def _regression_df(n=64, seed=0):
    rng = np.random.RandomState(seed)
    feats = rng.randn(n, 4).astype(np.float32)
    w = np.asarray([1.0, -2.0, 0.5, 3.0], np.float32)
    return {"features": feats, "label": feats @ w}


def test_resolve_configure_optimizers_shapes(lightning_env):
    import torch

    from horovod_tpu.spark._estimator_worker import (
        _resolve_lightning_optimizer,
    )
    from tests.estimator_models_lightning import LitRegression

    m = LitRegression()
    opt = torch.optim.SGD(m.parameters(), lr=0.1)
    sched = torch.optim.lr_scheduler.StepLR(opt, step_size=1)
    assert _resolve_lightning_optimizer(opt) == (opt, None)
    assert _resolve_lightning_optimizer({"optimizer": opt}) == (opt, None)
    assert _resolve_lightning_optimizer(
        {"optimizer": opt, "lr_scheduler": {"scheduler": sched}}
    ) == (opt, sched)
    assert _resolve_lightning_optimizer(([opt], [sched])) == (opt, sched)
    assert _resolve_lightning_optimizer(([opt], [])) == (opt, None)
    # lightning's list-of-dicts shape
    assert _resolve_lightning_optimizer([{"optimizer": opt}]) == (opt, None)
    assert _resolve_lightning_optimizer(
        [{"optimizer": opt, "lr_scheduler": sched}]
    ) == (opt, sched)


@pytest.mark.integration
@requires_multiprocess_collectives  # estimator workers allreduce across processes
def test_lightning_estimator_fit_transform(tmp_path, lightning_env):
    from horovod_tpu.spark import LocalStore
    from horovod_tpu.spark.lightning import (
        LightningEstimator, TorchEstimator,
    )
    from tests.estimator_models_lightning import LitRegression

    assert LightningEstimator is TorchEstimator  # both reference names
    data = _regression_df()
    est = TorchEstimator(
        model=LitRegression(),
        store=LocalStore(str(tmp_path)),
        batch_size=16,
        epochs=20,
        num_proc=2,
        validation=0.1,
    )
    model = est.fit(data)
    out = model.transform(data)
    labels = data["label"]
    mse = float(((out["label__output"] - labels) ** 2).mean())
    base = float((labels ** 2).mean())
    assert mse < 0.1 * base, f"mse {mse} vs baseline {base}"
    # per-epoch history incl. the validation_step series
    assert model.history and len(model.history["loss"]) == 20
    assert len(model.history["val_loss"]) == 20


@pytest.mark.integration
def test_lightning_dict_configure_optimizers(tmp_path, lightning_env):
    from horovod_tpu.spark import LocalStore
    from horovod_tpu.spark.lightning import TorchEstimator
    from tests.estimator_models_lightning import LitDictOptimizer

    data = _regression_df(n=48, seed=1)
    est = TorchEstimator(
        model=LitDictOptimizer(),
        store=LocalStore(str(tmp_path)),
        batch_size=16,
        epochs=4,
        num_proc=1,
    )
    model = est.fit(data)
    assert len(model.history["loss"]) == 4
    # loss decreased over training
    assert model.history["loss"][-1] < model.history["loss"][0]
