"""Per-rank collective parity tests over the virtual 8-chip mesh.

This file reproduces the reference's test/parallel/test_torch.py matrix
(every collective x dtype x shape, SURVEY.md §4) using ``hvd.run_per_rank``
— the shard_map harness standing in for `horovodrun -np 8 pytest`.
Assertions compare against locally computed references built from the
deterministic per-rank tensors, the reference's no-golden-files technique.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd

DTYPES = [jnp.float32, jnp.bfloat16, jnp.int32]
SHAPES = [(4,), (2, 3), (2, 2, 2)]
N = 8


def per_rank_tensor(r, shape, dtype):
    """Deterministic per-rank content, distinct across ranks."""
    base = jnp.arange(int(np.prod(shape)), dtype=jnp.float32).reshape(shape)
    return ((base + 1.0) * (r + 1)).astype(dtype)


def host_stack(shape, dtype):
    return np.stack(
        [np.asarray(per_rank_tensor(jnp.asarray(i), shape, dtype),
                    dtype=np.float32) for i in range(N)]
    )


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("shape", SHAPES)
def test_allreduce_sum(shape, dtype):
    out = hvd.run_per_rank(
        lambda r: hvd.spmd.allreduce(
            per_rank_tensor(r, shape, dtype), op=hvd.Sum
        )
    )
    expected = host_stack(shape, dtype).sum(0)
    for r in range(N):
        np.testing.assert_allclose(
            np.asarray(out[r], dtype=np.float32), expected, rtol=2e-2
        )


@pytest.mark.parametrize("shape", SHAPES)
def test_allreduce_average(shape):
    out = hvd.run_per_rank(
        lambda r: hvd.spmd.allreduce(per_rank_tensor(r, shape, jnp.float32))
    )
    expected = host_stack(shape, jnp.float32).mean(0)
    for r in range(N):
        np.testing.assert_allclose(np.asarray(out[r]), expected, rtol=1e-5)


def test_allreduce_min_max_product():
    shape = (3, 2)
    for op, red in [(hvd.Min, np.min), (hvd.Max, np.max),
                    (hvd.Product, np.prod)]:
        out = hvd.run_per_rank(
            lambda r: hvd.spmd.allreduce(
                per_rank_tensor(r, shape, jnp.float32), op=op
            )
        )
        expected = red(host_stack(shape, jnp.float32), axis=0)
        np.testing.assert_allclose(np.asarray(out[0]), expected, rtol=1e-4)


def test_allreduce_prescale_postscale():
    shape = (4,)
    out = hvd.run_per_rank(
        lambda r: hvd.spmd.allreduce(
            per_rank_tensor(r, shape, jnp.float32),
            op=hvd.Sum, prescale_factor=0.5, postscale_factor=2.0,
        )
    )
    expected = host_stack(shape, jnp.float32).sum(0)  # 0.5 * 2 cancels
    np.testing.assert_allclose(np.asarray(out[0]), expected, rtol=1e-5)


def test_allreduce_pytree_fused():
    def fn(r):
        tree = {
            "w": per_rank_tensor(r, (3,), jnp.float32),
            "b": per_rank_tensor(r, (2, 2), jnp.float32),
        }
        return hvd.spmd.allreduce(tree, op=hvd.Sum)

    out = hvd.run_per_rank(fn)
    np.testing.assert_allclose(
        np.asarray(out["w"][0]), host_stack((3,), jnp.float32).sum(0)
    )
    np.testing.assert_allclose(
        np.asarray(out["b"][0]), host_stack((2, 2), jnp.float32).sum(0)
    )


@pytest.mark.parametrize("dtype", DTYPES)
def test_allgather(dtype):
    shape = (2, 3)
    out = hvd.run_per_rank(
        lambda r: hvd.spmd.allgather(per_rank_tensor(r, shape, dtype))
    )
    # horovod semantics: concat along dim0 -> (N*2, 3) on every rank
    stacked = host_stack(shape, dtype).reshape(N * 2, 3)
    for r in range(N):
        np.testing.assert_allclose(
            np.asarray(out[r], dtype=np.float32), stacked, rtol=1e-2
        )


@pytest.mark.parametrize("root", [0, 3, 7])
def test_broadcast(root):
    shape = (2, 2)
    out = hvd.run_per_rank(
        lambda r: hvd.spmd.broadcast(
            per_rank_tensor(r, shape, jnp.float32), root_rank=root
        )
    )
    expected = host_stack(shape, jnp.float32)[root]
    for r in range(N):
        np.testing.assert_allclose(np.asarray(out[r]), expected)


def test_broadcast_bool():
    out = hvd.run_per_rank(
        lambda r: hvd.spmd.broadcast(r % 2 == 0, root_rank=3)
    )
    assert not bool(np.asarray(out[0]))  # rank 3: 3 % 2 != 0


def test_alltoall():
    # rank r sends value r*10+dst to each dst; after alltoall rank d holds
    # [src*10+d for src in ranks]
    def fn(r):
        send = r * 10 + jnp.arange(N, dtype=jnp.int32)
        return hvd.spmd.alltoall(send)

    out = hvd.run_per_rank(fn)
    for d in range(N):
        expected = np.arange(N) * 10 + d
        np.testing.assert_array_equal(np.asarray(out[d]), expected)


def test_alltoall_multi_chunk():
    # two rows per destination
    def fn(r):
        send = jnp.stack([
            jnp.full((2,), r * 100 + d, dtype=jnp.int32)
            for d in range(N) for _ in (0,)
        ]).reshape(N, 2) if False else (
            (r * 100 + jnp.repeat(jnp.arange(N, dtype=jnp.int32), 2))[:, None]
            * jnp.ones((1, 3), jnp.int32)
        )
        return hvd.spmd.alltoall(send)

    out = hvd.run_per_rank(fn)
    for d in range(N):
        col = np.asarray(out[d])[:, 0]
        expected = np.repeat(np.arange(N) * 100 + d, 2)
        np.testing.assert_array_equal(col, expected)


def test_reducescatter():
    shape = (N * 2, 3)

    def fn(r):
        return hvd.spmd.reducescatter(
            per_rank_tensor(r, shape, jnp.float32), op=hvd.Sum
        )

    out = hvd.run_per_rank(fn)
    total = host_stack(shape, jnp.float32).sum(0)  # (16, 3)
    for r in range(N):
        np.testing.assert_allclose(
            np.asarray(out[r]), total[r * 2:(r + 1) * 2], rtol=1e-5
        )


def test_reducescatter_average():
    shape = (N, 2)

    def fn(r):
        return hvd.spmd.reducescatter(
            per_rank_tensor(r, shape, jnp.float32), op=hvd.Average
        )

    out = hvd.run_per_rank(fn)
    mean = host_stack(shape, jnp.float32).mean(0)
    for r in range(N):
        np.testing.assert_allclose(
            np.asarray(out[r]), mean[r:r + 1], rtol=1e-5
        )


def test_rank_and_size():
    out = hvd.run_per_rank(
        lambda r: (hvd.spmd.rank(), jnp.asarray(hvd.spmd.size()))
    )
    np.testing.assert_array_equal(np.asarray(out[0]), np.arange(N))
    assert int(np.asarray(out[1])[0]) == N


def test_adasum_two_rank_identity():
    # With two orthogonal gradients adasum == sum; with identical gradients
    # adasum == the gradient itself (scale invariance). Check on a 2-rank
    # process set... the world is 8 ranks, so check the identical case:
    # all ranks send the same vector -> result equals that vector.
    def fn(r):
        v = jnp.asarray([1.0, 2.0, 3.0, 4.0])
        return hvd.spmd.allreduce(v, op=hvd.Adasum)

    out = hvd.run_per_rank(fn)
    np.testing.assert_allclose(
        np.asarray(out[0]), [1.0, 2.0, 3.0, 4.0], rtol=1e-5
    )


def test_adasum_orthogonal_sums():
    # rank r contributes a one-hot basis vector e_r: all contributions are
    # mutually orthogonal, so adasum degenerates to a plain sum.
    def fn(r):
        return hvd.spmd.allreduce(
            jax.nn.one_hot(r, N, dtype=jnp.float32), op=hvd.Adasum
        )

    out = hvd.run_per_rank(fn)
    np.testing.assert_allclose(np.asarray(out[0]), np.ones(N), rtol=1e-5)


from tests.adasum_oracle import host_adasum  # noqa: E402


@pytest.mark.parametrize("set_size", [6, 5])
def test_adasum_non_power_of_two_axis(set_size):
    """Non-pow2 axes fold the excess ranks first (reference:
    adasum_mpi.cc odd-rank handling) — every rank must hold the same
    combination, matching the host emulation."""
    ps = hvd.add_process_set(list(range(set_size)))
    try:
        out = hvd.run_per_rank(
            lambda r: hvd.spmd.allreduce(
                per_rank_tensor(r, (4,), jnp.float32), op=hvd.Adasum
            ),
            process_set=ps,
        )
        vs = [
            np.asarray(per_rank_tensor(jnp.asarray(i), (4,), jnp.float32),
                       dtype=np.float32).ravel()
            for i in range(set_size)
        ]
        expected = host_adasum(vs).reshape(4)
        for i in range(set_size):
            np.testing.assert_allclose(
                np.asarray(out[i]), expected, rtol=1e-5,
                err_msg=f"rank {i}",
            )
    finally:
        hvd.remove_process_set(ps)


def test_barrier_traces():
    out = hvd.run_per_rank(
        lambda r: (hvd.spmd.barrier(), jnp.asarray(1))[1]
    )
    assert np.asarray(out).sum() == N


def test_process_set_submesh_collective():
    ps = hvd.add_process_set([0, 1, 2, 3])
    try:
        out = hvd.run_per_rank(
            lambda r: hvd.spmd.allreduce(
                jnp.asarray([1.0]), op=hvd.Sum
            ),
            process_set=ps,
        )
        assert out.shape[0] == 4
        np.testing.assert_allclose(np.asarray(out[0]), [4.0])
    finally:
        hvd.remove_process_set(ps)


@pytest.mark.parametrize("shape", [(4,), (5,), (3, 5), ()])
@pytest.mark.parametrize("op", [hvd.Sum, hvd.Average])
def test_hierarchical_allreduce_matches_flat_psum(shape, op):
    """ICI reduce-scatter -> DCN allreduce -> ICI allgather over the 2x4
    hierarchical mesh must equal the flat psum over both axes (reference:
    NCCLHierarchicalAllreduce vs NCCLAllreduce parity).  Odd shapes
    exercise the pad/unpad path (5 elements over 4 ICI chips)."""
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.common.topology import DCN_AXIS, ICI_AXIS

    mesh = hvd.hierarchical_mesh(num_groups=2)  # (2, 4) over the 8 chips

    def body(r):
        x = per_rank_tensor(r[0][0], shape, jnp.float32)
        h = hvd.spmd.hierarchical_allreduce(x, op=op)
        flat = jax.lax.psum(x, (DCN_AXIS, ICI_AXIS))
        if op == hvd.Average:
            flat = flat / 8.0
        return h[None, None], flat[None, None]

    h, flat = jax.jit(
        jax.shard_map(
            body, mesh=mesh,
            in_specs=P(DCN_AXIS, ICI_AXIS),
            out_specs=(P(DCN_AXIS, ICI_AXIS), P(DCN_AXIS, ICI_AXIS)),
            check_vma=False,
        )
    )(jnp.arange(8, dtype=jnp.int32).reshape(2, 4))
    np.testing.assert_allclose(
        np.asarray(h), np.asarray(flat), rtol=1e-6
    )
    expected = host_stack(shape, jnp.float32).sum(axis=0)
    if op == hvd.Average:
        expected = expected / 8.0
    np.testing.assert_allclose(
        np.asarray(h[0, 0]), expected, rtol=1e-5
    )


def test_hierarchical_allreduce_from_distributed_optimizer():
    """hierarchical=True routes DistributedOptimizer's gradient reduce
    through the two-level op when stepping inside a hierarchical mesh."""
    import optax
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.common.topology import DCN_AXIS, ICI_AXIS

    mesh = hvd.hierarchical_mesh(num_groups=2)
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), hierarchical=True)
    params = {"w": jnp.zeros((6,))}

    def step(r):
        grads = {"w": per_rank_tensor(r[0][0], (6,), jnp.float32)}
        state = opt.init(params)
        updates, _ = opt.update(grads, state, params)
        new = optax.apply_updates(params, updates)
        return jax.tree_util.tree_map(lambda t: t[None, None], new)

    out = jax.jit(
        jax.shard_map(
            step, mesh=mesh,
            in_specs=P(DCN_AXIS, ICI_AXIS),
            out_specs=P(DCN_AXIS, ICI_AXIS),
            check_vma=False,
        )
    )(jnp.arange(8, dtype=jnp.int32).reshape(2, 4))
    expected = -host_stack((6,), jnp.float32).mean(axis=0)
    for i in range(2):
        for j in range(4):
            np.testing.assert_allclose(
                np.asarray(out["w"][i, j]), expected, rtol=1e-5
            )


def test_spmd_prescale_rejected_for_min():
    with pytest.raises(ValueError):
        hvd.run_per_rank(
            lambda r: hvd.spmd.allreduce(
                jnp.ones(2), op=hvd.Min, prescale_factor=2.0
            )
        )
