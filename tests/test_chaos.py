"""Chaos subsystem, retry policy, crash-atomic checkpoints, auto-resume.

The end-to-end recovery proof lives in tools/chaos_soak.py (wrapped here
as a `slow` test); these are the deterministic unit layers under it.
"""

import os
import pickle
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from horovod_tpu import chaos
from horovod_tpu import checkpoint as hvd_checkpoint
from horovod_tpu.chaos.spec import ChaosSpecError, parse_spec
from horovod_tpu.common.retry import retry_call
from horovod_tpu.elastic import ObjectState

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.clear()
    yield
    chaos.clear()


# -- spec grammar ------------------------------------------------------------

def test_parse_spec_full_grammar():
    rules = parse_spec(
        "elastic.commit:kill,at=8,rank=1;"
        "transport.frame.send:corrupt,prob=0.25,fuse=/tmp/f;"
        "data.batch:delay,delay=0.5,after=10,times=3"
    )
    assert [r.site for r in rules] == [
        "elastic.commit", "transport.frame.send", "data.batch"]
    kill, corrupt, delay = rules
    assert kill.action == "kill" and kill.at == 8 and kill.rank == 1
    assert kill.times == 1  # at= implies a single fire
    assert corrupt.prob == 0.25 and corrupt.fuse == "/tmp/f"
    assert delay.delay == 0.5 and delay.after == 10 and delay.times == 3


@pytest.mark.parametrize("bad", [
    "noseparator", "site:explode", "site:kill,prob=2.0",
    "site:kill,unknown=1", "site:delay,delay=abc", ":kill",
    "site:scale,factor=abc",
])
def test_parse_spec_rejects(bad):
    with pytest.raises(ChaosSpecError):
        parse_spec(bad)


def test_parse_spec_flipbit_and_scale_grammar():
    rules = parse_spec(
        "guard.grad:flipbit,at=9,rank=1,fuse=/tmp/f;"
        "guard.grad:scale,factor=64,prob=0.5,after=3")
    flip, scale = rules
    assert flip.action == "flipbit" and flip.at == 9 and flip.times == 1
    assert flip.rank == 1 and flip.fuse == "/tmp/f"
    assert scale.action == "scale" and scale.factor == 64.0
    assert scale.prob == 0.5 and scale.after == 3
    assert parse_spec("s:scale")[0].factor == 1024.0  # the default


# -- evaluation semantics ----------------------------------------------------

def test_point_inactive_is_passthrough():
    assert not chaos.active
    payload = b"bytes"
    assert chaos.point("anything", payload) is payload


def test_rank_filter_installs_only_matching_rules():
    chaos.configure("a:raise,rank=3", seed=0, rank=0)
    assert not chaos.active  # rule is for another rank
    chaos.configure("a:raise,rank=3", seed=0, rank=3)
    assert chaos.active
    with pytest.raises(chaos.ChaosInjected):
        chaos.point("a")


def test_at_fires_exactly_once_then_disarms():
    chaos.configure("s:raise,at=1", seed=0, rank=0)
    chaos.point("s")  # eval 0: no fire
    with pytest.raises(chaos.ChaosInjected):
        chaos.point("s")  # eval 1
    for _ in range(5):
        chaos.point("s")  # spent


def test_corrupt_flips_one_bit_of_bytes():
    chaos.configure("c:corrupt", seed=0, rank=0)
    out = chaos.point("c", b"\x00\x00\x00")
    assert out != b"\x00\x00\x00"
    assert len(out) == 3
    assert sum(bin(b).count("1") for b in out) == 1  # exactly one bit


def test_drop_returns_sentinel_and_delay_sleeps():
    chaos.configure("d:drop;t:delay,delay=0.05", seed=0, rank=0)
    assert chaos.point("d", "payload") is chaos.DROP
    t0 = time.perf_counter()
    assert chaos.point("t", "payload") == "payload"
    assert time.perf_counter() - t0 >= 0.04


def test_flipbit_ndarray_flips_exactly_one_material_bit():
    chaos.configure("f:flipbit", seed=0, rank=0)
    a = np.ones((9,), np.float32)
    out = chaos.point("f", a)
    assert out.shape == a.shape and out.dtype == a.dtype
    assert out is not a and (a == 1.0).all()  # input untouched (copy)
    diff = out.view(np.uint32) ^ a.view(np.uint32)
    changed = diff[diff != 0]
    assert changed.size == 1
    assert bin(int(changed[0])).count("1") == 1
    # the flip is MATERIAL (an exponent-region bit) yet stays finite —
    # the value only a digest, not the NaN/Inf sentinel, can see
    assert np.isfinite(out).all()
    assert (out != a).sum() == 1 and not np.allclose(out, a)


def test_flipbit_scalars_and_bytes():
    chaos.configure("f:flipbit,times=10", seed=0, rank=0)
    out = chaos.point("f", b"\x00\x00\x00")
    assert sum(bin(b).count("1") for b in out) == 1
    assert chaos.point("f", 7) != 7
    assert chaos.point("f", 1.0) not in (1.0, float("inf"))
    with pytest.raises(chaos.ChaosInjected):
        chaos.point("f")  # no payload: injected as failure, not a no-op


def test_scale_multiplies_and_preserves_dtype():
    chaos.configure("s:scale,factor=100,times=10", seed=0, rank=0)
    out = chaos.point("s", np.full((3,), 2.0, np.float32))
    np.testing.assert_allclose(out, 200.0)
    assert out.dtype == np.float32
    assert chaos.point("s", 3.0) == 300.0
    with pytest.raises(chaos.ChaosInjected):
        chaos.point("s", "not numeric")


def test_flipbit_composes_with_at_and_fuse(tmp_path):
    fuse = str(tmp_path / "flip.fuse")
    chaos.configure(f"g:flipbit,at=2,fuse={fuse}", seed=0, rank=0)
    a = np.ones((4,), np.float32)
    assert chaos.point("g", a) is a        # eval 0
    assert chaos.point("g", a) is a        # eval 1
    out = chaos.point("g", a)              # eval 2: fires + burns fuse
    assert (out != a).any() and os.path.exists(fuse)
    assert chaos.point("g", a) is a        # at= implies times=1
    # a fresh install (the post-restart process) finds the fuse burnt
    chaos.configure(f"g:flipbit,at=2,fuse={fuse}", seed=0, rank=0)
    for _ in range(5):
        assert chaos.point("g", a) is a


def test_flipbit_prob_replays_exactly_under_fixed_seed():
    def trace(seed):
        chaos.configure("p:flipbit,prob=0.3", seed=seed, rank=0)
        a = np.ones((4,), np.float32)
        for _ in range(100):
            chaos.point("p", a)
        return [e["eval"] for e in chaos.injection_trace()]

    a, b, c = trace(7), trace(7), trace(8)
    assert a and a == b
    assert a != c


def test_same_seed_same_trace_different_seed_differs():
    def trace(seed):
        chaos.configure("p:delay,delay=0,prob=0.3", seed=seed, rank=0)
        for _ in range(100):
            chaos.point("p")
        return [e["eval"] for e in chaos.injection_trace()]

    a, b, c = trace(11), trace(11), trace(12)
    assert a and a == b
    assert a != c


def test_fuse_fires_once_across_installs(tmp_path):
    fuse = str(tmp_path / "once.fuse")
    chaos.configure(f"f:raise,fuse={fuse}", seed=0, rank=0)
    with pytest.raises(chaos.ChaosInjected):
        chaos.point("f")
    # a fresh install (simulating the post-restart process) finds the
    # fuse burnt and never fires again
    chaos.configure(f"f:raise,fuse={fuse}", seed=0, rank=0)
    for _ in range(3):
        chaos.point("f")


# -- retry policy ------------------------------------------------------------

def test_retry_call_retries_then_succeeds():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    out = retry_call(flaky, site="t.flaky", attempts=5, base_delay=0.001)
    assert out == "ok" and len(calls) == 3


def test_retry_call_exhausts_and_reraises_last_error():
    def always():
        raise OSError("nope")

    with pytest.raises(OSError, match="nope"):
        retry_call(always, site="t.always", attempts=3, base_delay=0.001)


def test_retry_call_honors_deadline():
    t0 = time.monotonic()
    with pytest.raises(OSError):
        retry_call(lambda: (_ for _ in ()).throw(OSError("x")),
                   site="t.deadline", timeout=0.2, base_delay=0.05)
    assert time.monotonic() - t0 < 2.0


def test_retry_call_does_not_catch_unlisted_errors():
    def boom():
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        retry_call(boom, site="t.unlisted", attempts=5, base_delay=0.001)


def test_retry_call_single_attempt_by_default():
    calls = []

    def once():
        calls.append(1)
        raise OSError("x")

    with pytest.raises(OSError):
        retry_call(once, site="t.once")
    assert len(calls) == 1


def test_retry_deadline_shorter_than_first_backoff_reraises_promptly():
    """A deadline tighter than even the first backoff cap must clip
    the sleep to the remaining budget and re-raise at expiry — not
    serve the full backoff first."""
    calls = []

    def always():
        calls.append(time.monotonic())
        raise OSError("x")

    t0 = time.monotonic()
    with pytest.raises(OSError):
        retry_call(always, site="t.tight", timeout=0.1,
                   base_delay=30.0, max_delay=30.0)
    elapsed = time.monotonic() - t0
    assert elapsed < 1.0, f"slept a full backoff past the deadline: " \
        f"{elapsed:.2f}s"
    assert len(calls) >= 1


def test_retry_deadline_expiring_mid_sleep_returns_promptly():
    """The jittered sleep is clipped to the deadline: with base_delay
    far beyond the budget, total wall time tracks the TIMEOUT, not the
    backoff schedule."""
    t0 = time.monotonic()
    with pytest.raises(OSError):
        retry_call(lambda: (_ for _ in ()).throw(OSError("x")),
                   site="t.midsleep", timeout=0.3, base_delay=10.0,
                   max_delay=10.0)
    elapsed = time.monotonic() - t0
    assert 0.0 <= elapsed < 1.5, elapsed


def test_retry_attempts_and_deadline_compose():
    """attempts exhausts first when the deadline is generous."""
    calls = []

    def always():
        calls.append(1)
        raise OSError("x")

    with pytest.raises(OSError):
        retry_call(always, site="t.compose", attempts=2, timeout=30.0,
                   base_delay=0.001)
    assert len(calls) == 2


# -- crash-atomic checkpoints ------------------------------------------------

def test_save_checkpoint_is_atomic_and_prunes(tmp_path):
    state = {"w": np.arange(4, dtype=np.float32)}
    for step in range(5):
        hvd_checkpoint.save_checkpoint(str(tmp_path), state, step, keep=2)
    names = sorted(os.listdir(tmp_path))
    assert names == ["ckpt-3", "ckpt-4"]
    latest = hvd_checkpoint.latest_checkpoint(str(tmp_path))
    assert latest.endswith("ckpt-4")
    restored = hvd_checkpoint.restore_checkpoint(str(tmp_path), state,
                                                 broadcast=False)
    np.testing.assert_array_equal(restored["w"], state["w"])


def test_kill_mid_save_never_publishes_truncated_checkpoint(tmp_path):
    """Regression for the exact fault chaos injects: a writer SIGKILLed
    mid-save must leave at most temp debris — latest_checkpoint() must
    keep resuming from the previous complete checkpoint, and the next
    save must sweep the debris."""
    directory = str(tmp_path)
    state = {"w": np.zeros(1 << 18, dtype=np.float64)}  # 2 MB payload
    hvd_checkpoint.save_checkpoint(directory, state, 1)

    code = f"""
import numpy as np, os, sys
sys.path.insert(0, {REPO!r})
import horovod_tpu.checkpoint as cp

# slow writer: fsync made synchronous page-out likely mid-write
big = {{"w": np.random.default_rng(0).random(1 << 21)}}  # ~16 MB
print("WRITING", flush=True)
cp.save_checkpoint({directory!r}, big, 2)
print("DONE", flush=True)
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, "-c", code], env=env,
                            stdout=subprocess.PIPE, text=True)
    assert proc.stdout.readline().strip() == "WRITING"
    # kill while the 16 MB serialize/write/fsync is in flight
    time.sleep(0.05)
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()
    assert proc.returncode == -signal.SIGKILL

    latest = hvd_checkpoint.latest_checkpoint(directory)
    if latest.endswith("ckpt-2"):
        # the child won the race: its publish is then COMPLETE by
        # construction (os.replace after fsync) — verify readability
        with open(latest, "rb") as f:
            assert len(f.read()) > (1 << 24) - (1 << 20)
    else:
        assert latest.endswith("ckpt-1")
        restored = hvd_checkpoint.restore_checkpoint(
            directory, state, broadcast=False)
        np.testing.assert_array_equal(restored["w"], state["w"])
    # FRESH debris survives the next save's sweep (it could belong to a
    # concurrent saver still writing); once stale it is collected
    hvd_checkpoint.save_checkpoint(directory, state, 3)
    debris = [n for n in os.listdir(directory) if ".tmp." in n]
    for n in debris:  # backdate past the liveness window
        path = os.path.join(directory, n)
        os.utime(path, (time.time() - 600, time.time() - 600))
    hvd_checkpoint.save_checkpoint(directory, state, 4)
    assert not [n for n in os.listdir(directory) if ".tmp." in n]


def test_state_checkpoint_roundtrip_and_peek(tmp_path):
    state = ObjectState(step=7, weight=np.ones((2,)))
    path = hvd_checkpoint.save_state_checkpoint(str(tmp_path), state, 7)
    assert path.endswith("ckpt-7")
    step, snap = hvd_checkpoint.peek_state_checkpoint(str(tmp_path))
    assert step == 7
    other = ObjectState(step=0, weight=np.zeros((2,)))
    restored_step = hvd_checkpoint.restore_state_checkpoint(
        str(tmp_path), other)
    assert restored_step == 7 and other.step == 7
    np.testing.assert_array_equal(other.weight, [1.0, 1.0])


def test_peek_tolerates_garbage_checkpoint(tmp_path):
    with open(tmp_path / "ckpt-5", "wb") as f:
        f.write(b"HVDTPU-STATE1\n\x80garbage")
    assert hvd_checkpoint.peek_state_checkpoint(str(tmp_path)) is None


# -- checkpoint content checksums (silent-corruption defense) ----------------


def _flip_file_bit(path, offset=None):
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2 if offset is None else offset] ^= 0x10
    open(path, "wb").write(bytes(blob))


def test_corrupt_latest_state_checkpoint_falls_back_to_ring(tmp_path):
    """The corrupt-latest-checkpoint drill (ISSUE 14 acceptance): a
    bit-flipped newest snapshot is SKIPPED with a loud log and resume
    succeeds from the previous ring entry instead of raising (or
    silently restoring garbage that happens to unpickle)."""
    import logging

    from horovod_tpu.utils.logging import get_logger

    state = ObjectState(step=0, weight=np.zeros((2,)))
    for step in (1, 2):
        state.step = step
        state.weight = np.full((2,), float(step))
        hvd_checkpoint.save_state_checkpoint(str(tmp_path), state, step)
    _flip_file_bit(tmp_path / "ckpt-2")
    records = []

    class _Grab(logging.Handler):
        def emit(self, record):
            records.append(record)

    handler = _Grab(level=logging.ERROR)
    get_logger().addHandler(handler)
    try:
        found = hvd_checkpoint.peek_state_checkpoint(str(tmp_path))
    finally:
        get_logger().removeHandler(handler)
    assert found is not None and found[0] == 1
    assert any("FAILED its content checksum" in r.getMessage()
               for r in records)
    other = ObjectState(step=0, weight=np.zeros((2,)))
    assert hvd_checkpoint.restore_state_checkpoint(str(tmp_path),
                                                   other) == 1
    np.testing.assert_array_equal(other.weight, [1.0, 1.0])


def test_corrupt_latest_pytree_checkpoint_falls_back(tmp_path):
    tree = {"w": np.arange(4, dtype=np.float32)}
    hvd_checkpoint.save_checkpoint(str(tmp_path), tree, 1)
    hvd_checkpoint.save_checkpoint(
        str(tmp_path), {"w": np.arange(4, dtype=np.float32) * 3}, 2)
    _flip_file_bit(tmp_path / "ckpt-2")
    restored = hvd_checkpoint.restore_checkpoint(
        str(tmp_path), tree, broadcast=False)
    np.testing.assert_array_equal(restored["w"], np.arange(4))


def test_every_ring_entry_corrupt_degrades_to_none(tmp_path):
    state = ObjectState(step=0)
    for step in (1, 2):
        hvd_checkpoint.save_state_checkpoint(str(tmp_path), state, step)
    _flip_file_bit(tmp_path / "ckpt-1")
    _flip_file_bit(tmp_path / "ckpt-2")
    assert hvd_checkpoint.peek_state_checkpoint(str(tmp_path)) is None


def test_pre_checksum_checkpoints_still_load(tmp_path):
    """Files without the CRC header (written before this PR) load
    unverified — no flag day for existing checkpoint directories."""
    import pickle

    payload = (b"HVDTPU-STATE1\n" + pickle.dumps(
        {"step": 9, "snapshot": {"step": ("__value__", 9)}}))
    with open(tmp_path / "ckpt-9", "wb") as f:
        f.write(payload)
    found = hvd_checkpoint.peek_state_checkpoint(str(tmp_path))
    assert found is not None and found[0] == 9


def test_chaos_checkpoint_payload_drill(tmp_path):
    """checkpoint.payload chaos site: a flipbit on the bytes about to
    publish writes a checksum-failing file — the exact corrupt-on-write
    fault the readers' ring fallback recovers from."""
    state = ObjectState(step=0)
    hvd_checkpoint.save_state_checkpoint(str(tmp_path), state, 1)
    chaos.configure("checkpoint.payload:flipbit,at=0", seed=0, rank=0)
    try:
        hvd_checkpoint.save_state_checkpoint(str(tmp_path), state, 2)
    finally:
        chaos.clear()
    found = hvd_checkpoint.peek_state_checkpoint(str(tmp_path))
    assert found is not None and found[0] == 1
    # a DROP rule silently loses the write (the lost-checkpoint fault)
    chaos.configure("checkpoint.payload:drop,at=0", seed=0, rank=0)
    try:
        hvd_checkpoint.save_state_checkpoint(str(tmp_path), state, 3)
    finally:
        chaos.clear()
    assert not os.path.exists(tmp_path / "ckpt-3")


# -- elastic auto-resume -----------------------------------------------------

def test_auto_resume_lifts_stale_state_only(tmp_path):
    fleet = ObjectState(step=20, weight=np.full((2,), 20.0))
    hvd_checkpoint.save_state_checkpoint(str(tmp_path), fleet, 20)

    fresh = ObjectState(step=0, weight=np.zeros((2,)))
    fresh.enable_auto_resume(str(tmp_path))
    assert fresh.maybe_auto_resume() == 20
    assert fresh.step == 20

    ahead = ObjectState(step=25, weight=np.full((2,), 25.0))
    ahead.enable_auto_resume(str(tmp_path))
    assert ahead.maybe_auto_resume() is None  # live state wins
    assert ahead.step == 25


def test_auto_resume_noop_without_enable_or_checkpoint(tmp_path):
    state = ObjectState(step=3)
    assert state.maybe_auto_resume() is None  # never enabled
    state.enable_auto_resume(str(tmp_path))
    assert state.maybe_auto_resume() is None  # empty directory
    assert state.step == 3


# -- the end-to-end soak (slow) ----------------------------------------------

@pytest.mark.slow
@pytest.mark.integration
def test_chaos_soak_end_to_end():
    """Full recovery proof: kill + checkpoint auto-resume, native frame
    corruption + exec-restart recovery, the fleet autoscale 2->4->2
    plan under an injected kill, the fleet.preempt SIGTERM-grace leave,
    the serve-recover replica-loss bit-identity drill (reduced load —
    the 512-request default is the off-CI soak), seeded replay, idle
    overhead.  See tools/chaos_soak.py."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_soak.py"),
         "--serve-requests", "96"],
        cwd=REPO, timeout=1500, capture_output=True, text=True,
    )
    assert proc.returncode == 0, (
        f"chaos soak failed:\n{proc.stdout[-3000:]}\n{proc.stderr[-3000:]}")


def test_header_corruption_still_falls_back_through_the_ring(tmp_path):
    """Corruption in the checksum HEADER itself (the magic bytes) makes
    the file unverifiable rather than checksum-failed — the ring walk
    must keep going to the next-oldest entry, not abort (review
    finding: `return None` there silently restarted from step 0)."""
    state = ObjectState(step=0, weight=np.zeros((2,)))
    for step in (1, 2):
        state.step = step
        hvd_checkpoint.save_state_checkpoint(str(tmp_path), state, step)
    _flip_file_bit(tmp_path / "ckpt-2", offset=2)  # inside the magic
    found = hvd_checkpoint.peek_state_checkpoint(str(tmp_path))
    assert found is not None and found[0] == 1
