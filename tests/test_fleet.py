"""horovod_tpu.fleet: policy engine, resize API, router, preemption.

The closed loop's properties, each pinned where it is cheapest to pin:

* policy math (target tracking, deadband, hysteresis, cooldown,
  clamps, schedule plans, env/HTTP-settable targets) — pure unit
  tests, injected clocks;
* ``ElasticDriver.request_world_size`` — both directions through the
  real ``_reconcile`` path with stubbed processes (no fork): grow
  spawns into free slots, shrink marks the highest slots leaving
  (epoch-boundary semantics), blacklist + preemption holds respected,
  min/max clamped, ``None`` returns to capacity tracking;
* router placement — affinity routes to the replica whose published
  block-hash index holds the prompt's prefix, least-queue fallback on
  unseen templates, the max_skew balance escape, drain-before-retire,
  scale via warm spares — over REAL engines (tiny config; the oracle
  keeps holding);
* preemption guard — a real SIGTERM in a subprocess: planned snapshot,
  ``recovery_seconds{phase="planned"}``, exit 0 (the full
  multi-process drill lives in tools/chaos_soak.py preempt/autoscale);
* chaos negative-code kill — delivers a signal instead of exiting
  (the fleet.preempt drill mechanism).

The end-to-end closed loop (2→4→2 under faults, exact counts) is the
slow-marked soak in tools/chaos_soak.py.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time
import urllib.request

import numpy as np
import pytest

from horovod_tpu.fleet.policy import (
    SchedulePolicy, Target, TargetTrackingPolicy, histogram_quantile,
    snapshot_signals,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- policy ------------------------------------------------------------------


def test_target_ratio_orientation():
    assert Target("p99_ttft", 0.5).ratio(1.0) == 2.0
    assert Target("throughput", 100.0, invert=True).ratio(50.0) == 2.0
    assert Target("throughput", 100.0, invert=True).ratio(0.0) == float(
        "inf")
    assert Target("x", 0.0).ratio(1.0) is None


def test_policy_scales_out_on_breach_and_clamps():
    p = TargetTrackingPolicy([Target("p99_ttft", 0.5)], min_size=1,
                             max_size=4, cooldown_s=10.0)
    d = p.evaluate({"p99_ttft": 1.5}, 2, now=0.0)
    assert d.direction == "out" and d.desired == 4  # ceil(2 * 3.0) -> max
    d = p.evaluate({"p99_ttft": 0.7}, 2, now=0.0)
    assert d.direction == "out" and d.desired == 3
    # at max already: hold, not a phantom resize
    d = p.evaluate({"p99_ttft": 9.9}, 4, now=0.0)
    assert d.direction == "hold"


def test_policy_deadband_holds():
    p = TargetTrackingPolicy([Target("queue_depth", 4.0)], deadband=0.25)
    assert p.evaluate({"queue_depth": 4.9}, 2, now=0.0).direction == "hold"
    assert p.evaluate({"queue_depth": 5.1}, 2, now=0.0).direction == "out"


def test_policy_scale_in_needs_hysteresis_and_cooldown():
    p = TargetTrackingPolicy([Target("queue_depth", 4.0)], min_size=1,
                             max_size=4, hysteresis=3, cooldown_s=10.0,
                             scale_in_at=0.5)
    lo = {"queue_depth": 0.5}
    assert p.evaluate(lo, 3, now=0.0).direction == "hold"
    assert p.evaluate(lo, 3, now=1.0).direction == "hold"
    d = p.evaluate(lo, 3, now=2.0)
    assert d.direction == "in" and d.desired == 2  # one step at a time
    p.note_applied(now=2.0)
    # cooling: the streak is satisfied but the window blocks action
    assert p.evaluate(lo, 2, now=5.0).direction == "hold"
    assert p.evaluate(lo, 2, now=13.0).direction == "in"
    # a single hot sample resets the streak (chaos noise can't flap it)
    p2 = TargetTrackingPolicy([Target("queue_depth", 4.0)], hysteresis=2,
                              cooldown_s=0.0)
    p2.evaluate(lo, 3, now=0.0)
    p2.evaluate({"queue_depth": 8.0}, 3, now=1.0)  # breach resets
    assert p2.evaluate(lo, 3, now=2.0).direction == "hold"


def test_policy_min_size_floor_and_missing_signals():
    p = TargetTrackingPolicy([Target("queue_depth", 4.0)], min_size=2,
                             hysteresis=1, cooldown_s=0.0)
    assert p.evaluate({"queue_depth": 0.1}, 2, now=0.0).direction == "hold"
    assert p.evaluate({}, 2, now=0.0).reason == "no watched signals"


def test_policy_set_target_and_env(monkeypatch):
    p = TargetTrackingPolicy([Target("p99_ttft", 0.5)])
    p.set_target("p99_ttft", 0.25)
    assert p.targets()["p99_ttft"].value == 0.25
    p.set_target("throughput", 10.0, invert=True)
    assert p.targets()["throughput"].invert
    with pytest.raises(ValueError):
        p.set_target("p99_ttft", -1)
    monkeypatch.setenv("HVD_TPU_FLEET_TTFT_SLO", "0.4")
    monkeypatch.setenv("HVD_TPU_FLEET_THROUGHPUT_FLOOR", "50")
    monkeypatch.setenv("HVD_TPU_FLEET_MAX", "6")
    pe = TargetTrackingPolicy.from_env()
    assert set(pe.targets()) == {"p99_ttft", "throughput"}
    assert pe.max_size == 6 and pe.targets()["throughput"].invert


def test_schedule_policy_parse_and_evaluate():
    sp = SchedulePolicy.parse("0:2, 4:4, 8:2")
    assert sp.evaluate({}, 2, now=100.0).direction == "hold"  # t0 pinned
    d = sp.evaluate({}, 2, now=104.5)
    assert d.direction == "out" and d.desired == 4
    d = sp.evaluate({}, 4, now=109.0)
    assert d.direction == "in" and d.desired == 2
    with pytest.raises(ValueError):
        SchedulePolicy.parse("4:4,2:2")  # offsets must ascend
    with pytest.raises(ValueError):
        SchedulePolicy.parse("nope")
    with pytest.raises(ValueError):
        SchedulePolicy([])


def test_histogram_quantile_interpolates_and_clamps():
    bounds = [0.1, 0.5, 1.0]
    assert histogram_quantile(bounds, [0, 10, 0, 0], 0.5) == \
        pytest.approx(0.3)
    assert histogram_quantile(bounds, [10, 0, 0, 0], 0.99) == \
        pytest.approx(0.099)
    # overflow bucket clamps to the last bound (bounded-histogram truth)
    assert histogram_quantile(bounds, [0, 0, 0, 10], 0.99) == 1.0
    assert histogram_quantile(bounds, [0, 0, 0, 0], 0.5) == 0.0
    with pytest.raises(ValueError):
        histogram_quantile(bounds, [1, 2], 0.5)


def test_snapshot_signals_extraction():
    buckets = [0.1, 0.5, 1.0]
    snap = {"metrics": {
        "hvd_tpu_serve_queue_depth": {
            "kind": "gauge", "labelnames": ["rank"],
            "series": [[["0"], 3.0], [["1"], 5.0]]},
        "hvd_tpu_serve_token_latency_seconds": {
            "kind": "histogram", "labelnames": ["kind"],
            "buckets": buckets,
            "series": [[["first"],
                        {"buckets": [0, 10, 0, 0], "sum": 3.0,
                         "count": 10}]]},
        "hvd_tpu_serve_steps_total": {
            "kind": "counter", "labelnames": [],
            "series": [[[], 120.0]]},
    }}
    prev = {"metrics": {"hvd_tpu_serve_steps_total": {
        "kind": "counter", "labelnames": [], "series": [[[], 20.0]]}}}
    sig = snapshot_signals(snap, prev, dt=10.0)
    assert sig["queue_depth"] == 8.0
    assert sig["p99_ttft"] == pytest.approx(0.496)
    assert sig["throughput"] == pytest.approx(10.0)
    assert "step_time" not in sig  # absent metric -> absent signal


# -- the autoscaler loop -----------------------------------------------------


def test_autoscaler_tick_applies_and_respects_rejection():
    from horovod_tpu.fleet.autoscaler import Autoscaler

    applied = []
    accept = [True]
    policy = TargetTrackingPolicy([Target("queue_depth", 2.0)],
                                  max_size=8, cooldown_s=100.0)
    scaler = Autoscaler(policy, lambda n: accept[0] and applied.append(n)
                        is None, current_fn=lambda: 2,
                        signals_fn=lambda: {"queue_depth": 8.0},
                        interval_s=999, kind="train")
    d = scaler.tick(now=0.0)
    assert d.direction == "out" and applied == [8]
    # cooldown armed by the applied action: the next breach holds
    assert scaler.tick(now=1.0).direction == "hold"
    # a REJECTED apply must not burn the cooldown: retry next tick
    accept[0] = False
    scaler2 = Autoscaler(
        TargetTrackingPolicy([Target("queue_depth", 2.0)], max_size=8,
                             cooldown_s=100.0),
        lambda n: False, current_fn=lambda: 2,
        signals_fn=lambda: {"queue_depth": 8.0}, interval_s=999)
    assert scaler2.tick(now=0.0).direction == "out"
    assert scaler2.tick(now=1.0).direction == "out"  # not cooling


def test_autoscaler_does_not_respam_unconverged_target():
    """A plan target already handed to the applier is sticky there
    (request_world_size persists); while the world converges — or when
    capacity is short — the autoscaler must not re-apply and re-count
    the same decision every tick (SchedulePolicy has no cooldown, so
    the tick-level guard is the only damper)."""
    from horovod_tpu.fleet.autoscaler import Autoscaler

    applied = []
    scaler = Autoscaler(SchedulePolicy([(0.0, 4)]),
                        lambda n: applied.append(n) is None,
                        current_fn=lambda: 2, interval_s=999)
    for t in (0.0, 1.0, 2.0):  # world stuck at 2 (slots short)
        scaler.tick(now=t)
    assert applied == [4], f"re-applied an unconverged target: {applied}"


def test_maybe_training_autoscaler_from_env(monkeypatch):
    from horovod_tpu.fleet.autoscaler import maybe_training_autoscaler

    monkeypatch.delenv("HVD_TPU_FLEET_PLAN", raising=False)
    assert maybe_training_autoscaler(lambda n: n, lambda: 2, min_size=1,
                                     max_size=None) is None
    monkeypatch.setenv("HVD_TPU_FLEET_PLAN", "0:2,5:4")
    sc = maybe_training_autoscaler(lambda n: n, lambda: 2, min_size=1,
                                   max_size=4)
    assert sc is not None and isinstance(sc.policy, SchedulePolicy)
    # SLO mode without a scrape source refuses to start blind
    monkeypatch.delenv("HVD_TPU_FLEET_PLAN")
    monkeypatch.setenv("HVD_TPU_FLEET_TTFT_SLO", "0.5")
    monkeypatch.delenv("HVD_TPU_FLEET_SCRAPE", raising=False)
    assert maybe_training_autoscaler(lambda n: n, lambda: 2, min_size=1,
                                     max_size=4) is None


def test_endpoint_signal_source_and_http_targets():
    """The scrape loop + HTTP-settable targets, against a REAL PR-1
    exposition server: gauges/histograms in, policy signals out, and a
    GET /control/fleet/targets?set=... retunes the live policy."""
    from horovod_tpu.fleet.autoscaler import (
        EndpointSignalSource, register_targets_endpoint,
    )
    from horovod_tpu.metrics import exposition as expo
    from horovod_tpu.metrics import instruments as instr

    instr.SERVE_QUEUE_DEPTH.set(7.0)
    instr.SERVE_TOKEN_LATENCY.labels("first").observe(0.3)
    instr.SERVE_STEPS.labels("decode").inc(5)
    srv = expo.MetricsHTTPServer(0, addr="127.0.0.1")
    try:
        url = f"http://127.0.0.1:{srv.port}"
        src = EndpointSignalSource([url], clock=iter(
            [0.0, 10.0]).__next__)
        s1 = src()
        assert s1["queue_depth"] == 7.0
        assert 0.25 <= s1["p99_ttft"] <= 0.5  # bucket-interpolated
        instr.SERVE_STEPS.labels("decode").inc(20)
        s2 = src()
        assert s2["throughput"] == pytest.approx(2.0)  # 20 steps / 10 s
        # -- HTTP-settable targets ----------------------------------
        policy = TargetTrackingPolicy([Target("p99_ttft", 0.5)])
        register_targets_endpoint(policy)
        with urllib.request.urlopen(
                url + "/control/fleet/targets?set=p99_ttft:0.125") as r:
            body = json.load(r)
        assert body["targets"]["p99_ttft"]["value"] == 0.125
        assert policy.targets()["p99_ttft"].value == 0.125
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                url + "/control/fleet/targets?set=garbage")
        assert ei.value.code == 400
    finally:
        expo.unregister_control_handler("fleet/targets")
        srv.close()
        instr.SERVE_QUEUE_DEPTH.set(0)


# -- driver resize API -------------------------------------------------------


class _FakeProc:
    def __init__(self):
        self.code = None

    def poll(self):
        return self.code


def _stub_driver(slots=4, min_np=1, max_np=None):
    from horovod_tpu.runner import elastic_driver as ed

    drv = ed.ElasticDriver(
        command=["true"], discovery=None, min_np=min_np, max_np=max_np)

    def fake_spawn(host, slot, addr):
        w = ed._Worker(drv._next_worker_id, host, slot, _FakeProc())
        drv._next_worker_id += 1
        drv._workers[w.worker_id] = w
        return w

    drv._spawn = fake_spawn
    return drv, [("localhost", slots)]


def test_request_world_size_grows_and_shrinks():
    drv, hosts = _stub_driver(slots=4, min_np=1, max_np=None)
    assert drv.request_world_size(2) == 2
    assert drv._reconcile(hosts, "addr")
    assert drv.current_world() == 2
    assert {(w.host, w.slot) for w in drv._workers.values()} == \
        {("localhost", 0), ("localhost", 1)}
    # grow: spawns into the freed slots (epoch follows via the caller)
    drv.request_world_size(4)
    assert drv._reconcile(hosts, "addr")
    assert drv.current_world() == 4
    # shrink: the HIGHEST slots get leaving marks, nobody is killed —
    # they exit through the next rendezvous's shutdown reply
    drv.request_world_size(2)
    assert drv._reconcile(hosts, "addr")
    leaving = {(w.host, w.slot) for w in drv._workers.values()
               if w.alive and w.leaving}
    assert leaving == {("localhost", 2), ("localhost", 3)}
    assert drv.current_world() == 2
    assert all(w.alive for w in drv._workers.values()), \
        "resize must not kill processes directly"
    # steady state: an already-leaving worker is not re-marked (no
    # membership-epoch spin while it walks to its shutdown reply)
    assert not drv._reconcile(hosts, "addr")


def test_request_world_size_clamps_and_resets():
    drv, hosts = _stub_driver(slots=4, min_np=2, max_np=3)
    assert drv.request_world_size(1) == 2   # min_np floor
    assert drv.request_world_size(99) == 3  # max_np ceiling
    assert drv._reconcile(hosts, "addr")
    assert drv.current_world() == 3
    # None returns to capacity tracking (all slots, still max_np-capped)
    assert drv.request_world_size(None) == -1
    assert not drv._reconcile(hosts, "addr")  # max_np 3 == current
    drv.max_np = None
    assert drv._reconcile(hosts, "addr")
    assert drv.current_world() == 4


def test_resize_respects_blacklist_and_holds():
    drv, hosts = _stub_driver(slots=4)
    drv._blacklist.add(("localhost", 0))
    drv._slot_hold[("localhost", 1)] = time.monotonic() + 60  # hold
    drv.request_world_size(4)
    drv._reconcile(hosts, "addr")
    used = {(w.host, w.slot) for w in drv._workers.values() if w.alive}
    assert used == {("localhost", 2), ("localhost", 3)}, \
        "blacklisted/held slots must never be re-filled"
    # an EXPIRED hold releases the slot back to discovery's authority
    drv._slot_hold[("localhost", 1)] = time.monotonic() - 1
    drv._reconcile(hosts, "addr")
    used = {(w.host, w.slot) for w in drv._workers.values() if w.alive}
    assert ("localhost", 1) in used


def test_leaving_exit_books_scale_down_not_completion():
    drv, hosts = _stub_driver(slots=2)
    drv.request_world_size(2)
    drv._reconcile(hosts, "addr")
    w = next(iter(drv._workers.values()))
    w.leaving = True
    w.proc.code = 0
    with drv._cv:
        any_exit, any_failure = drv._observe_exits()
    assert any_exit and not any_failure
    assert not getattr(drv, "_completing", False), \
        "a planned leave must not read as job completion"
    assert drv._leaver_exited, "survivors need a planned reset epoch"


# -- router + replicas (real engines, tiny config) ---------------------------


@pytest.fixture(scope="module")
def fleet_pieces():
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from horovod_tpu.serving import ServeConfig, ServingEngine

    cfg = TransformerConfig(
        vocab_size=97, num_layers=1, num_heads=2, num_kv_heads=2,
        head_dim=8, max_seq_len=48, dtype=jnp.float32,
        attention_impl="dot", causal=True)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32), train=False)["params"]
    serve = ServeConfig(block_size=8, num_blocks=0, token_budget=128,
                        watermark=2, prefill_tiers=(32,),
                        decode_tiers=(1, 2), prefill_chunk=8)

    def build():
        return ServingEngine(cfg, params, serve=serve)

    return cfg, params, build


def test_router_affinity_routes_to_cached_replica(fleet_pieces):
    from horovod_tpu.fleet.router import FleetRouter

    _cfg, _params, build = fleet_pieces
    router = FleetRouter(build, replicas=2, mode="affinity")
    rs = np.random.RandomState(0)
    template = rs.randint(1, 90, size=24).astype(np.int32)
    # first sight of the template: least-queue fallback places it
    g0 = router.submit(np.concatenate([template, [3, 4]]), 2,
                       arrival=time.perf_counter())
    first = router._placed[g0].replica
    router.run_until_drained()
    assert router.route_counts["least_queue"] == 1
    assert first.cached_prefix_blocks(template) > 0, \
        "served template not published"
    # the OTHER replica never saw it
    other = next(r for r in router.replicas if r is not first)
    assert other.cached_prefix_blocks(template) == 0
    # second request with the same template must stick to `first`
    g1 = router.submit(np.concatenate([template, [9]]), 2,
                       arrival=time.perf_counter())
    assert router._placed[g1].replica is first
    assert router.route_counts["affinity"] == 1
    router.run_until_drained()
    assert router.all_compile_free()


def test_router_max_skew_balance_escape(fleet_pieces):
    from horovod_tpu.fleet.router import FleetRouter

    _cfg, _params, build = fleet_pieces
    router = FleetRouter(build, replicas=2, mode="affinity", max_skew=2)
    rs = np.random.RandomState(1)
    template = rs.randint(1, 90, size=24).astype(np.int32)
    router.submit(np.concatenate([template, [1]]), 1,
                  arrival=time.perf_counter())
    router.run_until_drained()
    hot = max(router.replicas,
              key=lambda r: r.cached_prefix_blocks(template))
    # pile queued work onto the cache-hot replica past the skew bound
    for i in range(4):
        g = router.submit(np.concatenate([template, [i + 2]]), 1,
                          arrival=time.perf_counter())
    # the 4th submit saw hot.queue >= 3 > min queue 0 + skew 2: escape
    assert router.route_counts["least_queue"] >= 2
    cold = next(r for r in router.replicas if r is not hot)
    assert cold.engine.scheduler.queue_depth() \
        + len(cold.engine.scheduler.running) > 0, \
        "skew escape never spread the hot template"
    router.run_until_drained()


def test_router_drain_semantics_and_scale(fleet_pieces):
    from horovod_tpu.fleet.policy import Target, TargetTrackingPolicy
    from horovod_tpu.fleet.router import FleetRouter

    _cfg, _params, build = fleet_pieces
    policy = TargetTrackingPolicy([Target("queue_depth", 2.0)],
                                  min_size=1, max_size=2, hysteresis=3,
                                  cooldown_s=0.0, scale_in_at=0.5)
    router = FleetRouter(build, replicas=1, mode="affinity",
                         policy=policy, spares=1)
    assert router.size == 1 and len(router.replicas) == 2  # 1 + spare
    rs = np.random.RandomState(2)
    # flood: queue past target -> the policy unparks the warm spare
    gids = [router.submit(rs.randint(1, 90, size=10).astype(np.int32), 2,
                          arrival=time.perf_counter())
            for _ in range(8)]
    deadline = time.time() + 30
    while router.size < 2 and time.time() < deadline:
        router.step()
    assert router.size == 2, "scale-out never unparked the spare"
    assert ("out", 2) in router.scale_events
    # drain the tail: empty queues scale back in; the drained replica
    # finishes its in-flight work and retires, its results intact
    deadline = time.time() + 30
    while (router.size > 1 or any(r.state == "draining"
                                  for r in router.replicas)) \
            and time.time() < deadline:
        router.step()
    router.run_until_drained()
    assert router.size == 1
    assert len(router.retired) == 1
    assert router.retired[0].state == "retired"
    assert all(g in router.results for g in gids), \
        "a drained replica dropped in-flight work"
    assert router.all_compile_free()
    # a retired replica's surface stays safe (stats survive the engine)
    hits, lookups = router.prefix_stats()
    assert lookups >= 0 and router.all_ttfts()


def test_replica_lifecycle_guards(fleet_pieces):
    from horovod_tpu.fleet.replica import ServingReplica

    _cfg, _params, build = fleet_pieces
    r = ServingReplica("t", build)
    with pytest.raises(AttributeError):
        r.queue_depth()  # not spawned: no engine
    r.spawn(park=True)
    assert r.state == "parked" and not r.accepting
    with pytest.raises(RuntimeError, match="not accepting"):
        r.submit(np.ones((4,), np.int32), 1)
    r.unpark()
    rid = r.submit(np.arange(1, 6, dtype=np.int32), 2,
                   arrival=time.perf_counter())
    with pytest.raises(RuntimeError, match="drain before retire"):
        r.drain() or r.retire()
    while r.has_work:
        r.step()
    assert r.drained and r.healthy()
    r.retire()
    assert r.state == "retired" and r.engine is None
    assert rid in [s for s, _ in r.ttft_samples()] or r.ttft_samples()
    r.retire()  # idempotent


# -- preemption: signal-kill + the guard ------------------------------------


def test_chaos_negative_code_kill_delivers_signal():
    """kill with code=-N sends signal N to self and RETURNS — the
    drill mechanism behind fleet.preempt (spec grammar, PR 13)."""
    from horovod_tpu import chaos

    got = []
    old = signal.signal(signal.SIGUSR1,
                        lambda *_: got.append(True))
    try:
        chaos.configure(
            f"training.step:kill,at=0,code=-{signal.SIGUSR1.value}",
            seed=1)
        assert chaos.point("training.step", "payload") == "payload"
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got, "signal never delivered"
        assert chaos.injection_trace()[-1]["action"] == "kill"
    finally:
        chaos.clear()
        signal.signal(signal.SIGUSR1, old)


_GUARD_SCRIPT = textwrap.dedent("""
    import json, os, signal, sys, time
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    from horovod_tpu.elastic.state import ObjectState
    from horovod_tpu.fleet.preemption import PreemptionGuard
    from horovod_tpu.metrics import instruments as instr

    out = sys.argv[1]
    state = ObjectState(step=0, weight=np.zeros(()))
    state.enable_auto_resume(sys.argv[2], step_attr="step")

    def on_leave(info):
        info["recovery_planned_s"] = instr.RECOVERY_SECONDS.labels(
            "planned").get()
        with open(out, "w") as f:
            json.dump(info, f)

    PreemptionGuard(state, on_leave=on_leave, poll_s=10.0).install()
    for i in range(1000):
        state.weight = np.asarray(state.weight) + 1.0
        state.step = int(state.step) + 1
        state.commit()
        if state.step == 5:
            os.kill(os.getpid(), signal.SIGTERM)  # the notice
        time.sleep(0.02)
    sys.exit(3)  # the guard must have exited us long before
""")


def test_preemption_guard_sigterm_snapshot_leave(tmp_path):
    """A real SIGTERM: bounded planned snapshot, checkpoint published
    (any rank), recovery_seconds{planned} set, exit 0."""
    out = tmp_path / "leave.json"
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, "-c", _GUARD_SCRIPT, str(out), str(ckpt)],
        env=env, timeout=120, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-2000:]
    info = json.loads(out.read_text())
    assert info["snapshot"] in ("live", "commit")
    assert info["step"] >= 5
    assert 0 <= info["planned_s"] < 35.0
    assert info["recovery_planned_s"] == pytest.approx(
        info["planned_s"], abs=1.0)
    # the leave published a state checkpoint a replacement can resume
    from horovod_tpu import checkpoint as ckpt_mod

    peeked = ckpt_mod.peek_state_checkpoint(str(ckpt))
    assert peeked is not None and peeked[0] >= 5


# -- replica resilience: suspect ejection + re-route (ISSUE 14) --------------


def test_router_ejects_raising_replica_and_reroutes(fleet_pieces,
                                                    monkeypatch):
    """A replica whose submit() raises is marked SUSPECT after
    HVD_TPU_FLEET_REPLICA_ERRORS consecutive errors and ejected from
    placement; its in-flight requests re-route ONCE to the least-queue
    survivor and every request still completes — a raising replica can
    no longer keep winning affinity for its cached templates."""
    from horovod_tpu.fleet.router import FleetRouter

    monkeypatch.setenv("HVD_TPU_FLEET_REPLICA_ERRORS", "2")
    _cfg, _params, build = fleet_pieces
    router = FleetRouter(build, replicas=2, mode="round_robin")
    rs = np.random.RandomState(3)
    gids = [router.submit(rs.randint(1, 90, 10).astype(np.int32), 4)
            for _ in range(4)]
    victim = router.replicas[0]
    placed_on_victim = [g for g, p in router._placed.items()
                        if p.replica is victim]
    assert placed_on_victim, "round robin should have placed on both"

    def boom(*a, **k):
        raise RuntimeError("chip on fire")

    victim.engine.submit = boom
    gids += [router.submit(rs.randint(1, 90, 10).astype(np.int32), 4)
             for _ in range(4)]
    assert victim.suspect and not victim.accepting
    # the victim's in-flight requests were re-routed exactly once
    for g in placed_on_victim:
        assert router._placed[g].rerouted
        assert router._placed[g].replica is not victim
    res = router.run_until_drained()
    assert len(res) == 8 and all(res[g].size == 4 for g in gids)
    # the suspect drained empty and retired; the survivor serves alone
    assert victim.state == "retired"
    assert router.size == 1
    assert router.all_compile_free()


def test_router_step_errors_count_toward_suspect(fleet_pieces,
                                                 monkeypatch):
    from horovod_tpu.fleet.router import FleetRouter

    monkeypatch.setenv("HVD_TPU_FLEET_REPLICA_ERRORS", "2")
    _cfg, _params, build = fleet_pieces
    router = FleetRouter(build, replicas=2, mode="round_robin")
    rs = np.random.RandomState(4)
    gids = [router.submit(rs.randint(1, 90, 10).astype(np.int32), 3)
            for _ in range(2)]
    victim = next(p.replica for p in router._placed.values())

    def boom():
        raise RuntimeError("wedged step")

    victim.engine.step = boom
    res = router.run_until_drained()
    assert victim.suspect and victim.state == "retired"
    assert len(res) == 2 and all(res[g].size == 3 for g in gids)


def test_replica_stall_trip_feeds_the_error_counter(fleet_pieces,
                                                    monkeypatch):
    """The healthz stall source (has-work-but-no-progress) drives the
    same consecutive-error counter as raises do."""
    from horovod_tpu.fleet.replica import ServingReplica

    monkeypatch.setenv("HVD_TPU_FLEET_REPLICA_STALL_SECONDS", "0.5")
    _cfg, _params, build = fleet_pieces
    t = [0.0]
    r = ServingReplica("stall", build, clock=lambda: t[0])
    r.spawn()
    r.submit(np.arange(1, 9, dtype=np.int32), 2)
    assert r.healthy()
    t[0] = 10.0  # work pending, no progress for 10s > 0.5s stall bound
    assert not r.healthy()
    assert not r.note_error() and not r.note_error()
    assert r.note_error()  # default threshold 3 -> suspect transition
    assert r.suspect
    r.engine.scheduler.pending.clear()
    r.drain()
    r.retire()


def test_note_ok_resets_consecutive_errors(fleet_pieces):
    from horovod_tpu.fleet.replica import ServingReplica

    _cfg, _params, build = fleet_pieces
    r = ServingReplica("flappy", build)
    r.spawn()
    assert not r.note_error() and not r.note_error()
    r.note_ok()  # a success breaks the run
    assert not r.note_error() and not r.note_error()
    assert not r.suspect
    assert r.note_error()
    r.engine.scheduler.pending.clear()
    r.drain()
    r.retire()


def test_router_deadline_aware_placement_skips_slow_replica(fleet_pieces):
    """A replica whose estimated queue delay exceeds the request's
    remaining deadline budget is skipped — placement onto it could
    only produce a shed."""
    from horovod_tpu.fleet.router import FleetRouter

    _cfg, _params, build = fleet_pieces
    router = FleetRouter(build, replicas=2, mode="affinity")
    slow, fast = router.replicas
    slow.avg_step_s = 10.0
    for _ in range(3):  # queue depth makes slow's estimate ~30s
        slow.engine.submit(np.arange(1, 9, dtype=np.int32), 2)
    g = router.submit(np.arange(1, 9, dtype=np.int32), 2,
                      deadline_s=1.0)
    assert router._placed[g].replica is fast
    # without a deadline the same queue state is NOT skipped on a
    # cache hit: run the template through `slow` first
    router.run_until_drained()


def test_quarantine_host_blacklists_and_kills_siblings():
    """Integrity attribution quarantines the WHOLE host: its slots
    leave the spawn pool AND its sibling workers are hard-killed —
    leaving them computing would keep re-tripping the guard until the
    survivors' rollback fuse kills the job (review finding)."""
    drv, hosts = _stub_driver(slots=2)
    hosts = [("hostA", 2), ("hostB", 1)]
    with drv._cv:
        for h, n in hosts:
            for s in range(n):
                drv._spawn(h, s, "addr")
        killed = []
        for w in drv._workers.values():
            w.proc.kill = (lambda wid=w.worker_id:
                           killed.append(wid))
        liar = next(w for w in drv._workers.values()
                    if (w.host, w.slot) == ("hostA", 0))
        drv._quarantine_host(liar.worker_id)
        assert "hostA" in drv._host_blacklist
        sibling = next(w for w in drv._workers.values()
                       if (w.host, w.slot) == ("hostA", 1))
        assert killed == [sibling.worker_id]  # hostB untouched, liar
        # exits itself
        # quarantined slots never refill; hostB's survive
        assert set(drv._desired_slots(hosts)) == {("hostB", 0)}
        # idempotent: a re-report doesn't double-kill
        drv._quarantine_host(liar.worker_id)
        assert killed == [sibling.worker_id]


def test_validation_errors_never_suspect_replicas(fleet_pieces,
                                                  monkeypatch):
    """Client-input errors (over-long prompt) re-raise to the caller
    instead of booking replica health — a few bad requests must not
    eject the whole fleet (review finding)."""
    from horovod_tpu.fleet.router import FleetRouter

    monkeypatch.setenv("HVD_TPU_FLEET_REPLICA_ERRORS", "2")
    _cfg, _params, build = fleet_pieces
    router = FleetRouter(build, replicas=2, mode="round_robin")
    too_long = np.arange(1, 200, dtype=np.int32)  # > max_seq_len 48
    for _ in range(4):
        with pytest.raises(ValueError):
            router.submit(too_long, 4)
    assert not any(r.suspect for r in router.replicas)
    assert router.size == 2
    g = router.submit(np.arange(1, 9, dtype=np.int32), 3)
    assert router.run_until_drained()[g].size == 3


def test_stalled_draining_replica_still_ejects(fleet_pieces,
                                               monkeypatch):
    """A replica already DRAINING voluntarily (scale-down) that then
    wedges must STILL get the full ejection — the old state-based
    guard made the stall response a no-op and run_until_drained spun
    forever (review finding)."""
    from horovod_tpu.fleet.router import FleetRouter

    monkeypatch.setenv("HVD_TPU_FLEET_REPLICA_ERRORS", "2")
    _cfg, _params, build = fleet_pieces
    router = FleetRouter(build, replicas=2, mode="round_robin")
    rs = np.random.RandomState(5)
    gids = [router.submit(rs.randint(1, 90, 10).astype(np.int32), 3)
            for _ in range(2)]
    victim = next(p.replica for p in router._placed.values())
    victim.drain()  # voluntary scale-down with work still in flight
    assert victim.state == "draining"

    def wedged():
        raise RuntimeError("wedged mid-drain")

    victim.engine.step = wedged
    res = router.run_until_drained()
    assert victim.suspect and victim.ejected
    assert victim.state == "retired"
    assert len(res) == 2 and all(res[g].size == 3 for g in gids)


# -- crash-surviving requests: migration, hedging, chaos (ISSUE 18) ----------


def _decode_until(router, victim, n, timeout_s=60):
    """Step the fleet until every running request on ``victim`` has
    generated >= n tokens (the mid-decode interruption point)."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        router.step()
        seqs = list(victim.engine.scheduler.running)
        if seqs and all(len(s.generated) >= n for s in seqs):
            return
    raise AssertionError("victim never reached the interruption point")


def test_router_recovery_token_identical_and_warm(fleet_pieces,
                                                  monkeypatch, tmp_path):
    """The tentpole oracle: kill a replica mid-decode and every one of
    its requests completes on a survivor with output bit-identical to
    an unkilled control run — the already-generated prefix emitted
    exactly once, the KV snapshot re-registered (warm path), zero
    post-warmup compiles on the recovery path, and a replica_loss
    flight bundle on disk."""
    from horovod_tpu.fleet.router import FleetRouter
    from horovod_tpu.trace import flight as _flight

    monkeypatch.setenv("HVD_TPU_FLEET_REPLICA_ERRORS", "1")
    monkeypatch.setenv("HVD_TPU_TRACE_BUNDLE_DIR", str(tmp_path))
    _flight._last_dump.clear()  # another test's dump must not gate ours
    _cfg, _params, build = fleet_pieces
    rs = np.random.RandomState(18)
    # 2 per replica: both of the victim's land IN DECODE (a 3rd would
    # sit queued — no KV yet — and correctly migrate cold)
    prompts = [rs.randint(1, 90, size=10).astype(np.int32)
               for _ in range(4)]
    ctrl = FleetRouter(build, replicas=2, mode="round_robin")
    cgids = [ctrl.submit(p, 12) for p in prompts]
    cres = ctrl.run_until_drained()
    router = FleetRouter(build, replicas=2, mode="round_robin")
    gids = [router.submit(p, 12) for p in prompts]
    victim = router.replicas[0]
    on_victim = [g for g, p in router._placed.items()
                 if p.replica is victim]
    assert on_victim, "round robin should have placed on both"
    _decode_until(router, victim, 7)  # >= 1 full block generated

    def boom():
        raise RuntimeError("chip on fire")

    victim.engine.step = boom
    res = router.run_until_drained()
    assert len(res) == 4
    for g, cg in zip(gids, cgids):
        np.testing.assert_array_equal(
            res[g], cres[cg],
            err_msg=f"gid {g} diverged from the unkilled control")
    assert router.recovery, "ejection must book recovery records"
    assert {x["path"] for x in router.recovery} == {"warm"}, \
        "mid-decode requests with full blocks must migrate warm"
    assert all(x["ms"] >= 0 for x in router.recovery)
    assert router.migration_ms() > 0
    assert victim.state == "retired"
    assert router.all_compile_free(), \
        "the recovery path must not compile on the survivor"
    bundles = [p for p in os.listdir(tmp_path)
               if p.startswith("bundle-replica_loss-")]
    assert bundles, "replica loss must dump a flight-recorder bundle"


def test_serve_migrate_corrupt_degrades_to_cold(fleet_pieces,
                                                monkeypatch):
    """Corrupt injection on the serve.migrate wire: the chain-hash
    verification rejects the snapshot and recovery DEGRADES to the
    cold path (re-prefill from tokens) — outputs stay exact, never
    wrong tokens."""
    from horovod_tpu import chaos
    from horovod_tpu.fleet.router import FleetRouter

    monkeypatch.setenv("HVD_TPU_FLEET_REPLICA_ERRORS", "1")
    _cfg, _params, build = fleet_pieces
    ref_eng = build()
    rs = np.random.RandomState(19)
    prompts = [rs.randint(1, 90, size=10).astype(np.int32)
               for _ in range(4)]
    rids = [ref_eng.submit(p, 12) for p in prompts]
    ref = ref_eng.run()
    chaos.configure("serve.migrate:corrupt,prob=1", seed=7)
    try:
        router = FleetRouter(build, replicas=2, mode="round_robin")
        gids = [router.submit(p, 12) for p in prompts]
        victim = router.replicas[0]
        _decode_until(router, victim, 7)

        def boom():
            raise RuntimeError("chip on fire")

        victim.engine.step = boom
        res = router.run_until_drained()
        fired = [t["site"] for t in chaos.injection_trace()]
    finally:
        chaos.clear()
    assert "serve.migrate" in fired
    assert router.recovery
    assert {x["path"] for x in router.recovery} == {"cold"}, \
        "a corrupt snapshot must fall back to cold re-prefill"
    for g, rid in zip(gids, rids):
        np.testing.assert_array_equal(res[g], ref[rid])


def test_ejection_preserves_arrival_order(fleet_pieces, monkeypatch):
    """Fairness satellite: requests migrated off a dead replica rejoin
    the survivor's admission queue in ORIGINAL arrival order, not at
    the tail behind later arrivals."""
    from horovod_tpu.fleet.router import FleetRouter

    monkeypatch.setenv("HVD_TPU_FLEET_REPLICA_ERRORS", "1")
    _cfg, _params, build = fleet_pieces
    router = FleetRouter(build, replicas=2, mode="round_robin")
    gids = [router.submit(np.arange(1, 9, dtype=np.int32), 2,
                          arrival=float(i)) for i in range(6)]
    victim, survivor = router.replicas

    def boom(*a, **k):
        raise RuntimeError("chip on fire")

    victim.engine.submit = boom
    gids.append(router.submit(np.arange(1, 9, dtype=np.int32), 2,
                              arrival=6.0))  # trips the ejection
    assert victim.ejected
    arrivals = [s.req.arrival for s in
                survivor.engine.scheduler.pending]
    assert arrivals == sorted(arrivals), \
        f"migrated requests broke arrival order: {arrivals}"
    assert set(arrivals) == {float(i) for i in range(7)}
    res = router.run_until_drained()
    assert len(res) == 7 and all(res[g].size == 2 for g in gids)


def test_hedged_dispatch_first_wins_and_budget(fleet_pieces,
                                               monkeypatch):
    """HVD_TPU_SERVE_HEDGE: a prefill-phase request past the sliding
    p99 TTFT gets one second dispatch; first completion wins, the
    loser cancels (blocks freed, result never raced into collection);
    HVD_TPU_SERVE_HEDGE_BUDGET=0 suppresses instead."""
    from horovod_tpu.fleet.router import FleetRouter

    monkeypatch.setenv("HVD_TPU_SERVE_HEDGE", "1")
    # the default 0.1 budget would suppress the very first hedge
    # (1 > 0.1 x 1 submitted) — that conservatism is the point of the
    # budget, but here we want a hedge to actually fly
    monkeypatch.setenv("HVD_TPU_SERVE_HEDGE_BUDGET", "1")
    _cfg, _params, build = fleet_pieces
    ref_eng = build()
    prompt = np.arange(1, 9, dtype=np.int32)
    rid = ref_eng.submit(prompt, 3)
    ref = ref_eng.run()[rid]
    t = [0.0]
    router = FleetRouter(build, replicas=2, mode="round_robin",
                         clock=lambda: t[0])
    router._ttfts.extend([0.001] * 16)  # stable p99 estimate
    g = router.submit(prompt, 3)
    primary = router._placed[g].replica
    t[0] = 1.0  # way past p99, still no first token: hedgeable
    router._maybe_hedge()
    p = router._placed[g]
    assert p.hedged and p.hedge is not None
    hedge_replica = p.hedge[0]
    assert hedge_replica is not primary
    assert router.hedge_rate() == pytest.approx(1.0)
    res = router.run_until_drained()
    np.testing.assert_array_equal(res[g], ref)
    assert router.hedges["won"] + router.hedges["lost"] == 1
    # the loser was cancelled: neither engine still holds the request
    for r in router.replicas:
        assert not r.engine.scheduler.running
        assert not r.engine.scheduler.pending
    assert router.all_compile_free()
    # budget 0: the hedge decision books as suppressed, no dispatch
    monkeypatch.setenv("HVD_TPU_SERVE_HEDGE_BUDGET", "0")
    r2 = FleetRouter(build, replicas=2, mode="round_robin",
                     clock=lambda: t[0])
    r2._ttfts.extend([0.001] * 16)
    t[0] = 2.0
    g2 = r2.submit(prompt, 3)
    t[0] = 3.0
    r2._maybe_hedge()
    assert r2._placed[g2].hedge is None and r2._placed[g2].hedged
    assert r2.hedges == {"won": 0, "lost": 0, "suppressed": 1}
    assert r2.hedge_rate() == 0.0
    np.testing.assert_array_equal(r2.run_until_drained()[g2], ref)


def test_periodic_snapshot_cadence_and_chaos_skip(fleet_pieces,
                                                  monkeypatch):
    """HVD_TPU_SERVE_SNAPSHOT_STEPS: the replica snapshots its
    in-flight KV every N steps (the warm source when a dead engine
    can't export); a chaos raise on serve.snapshot skips that beat
    without failing the step."""
    from horovod_tpu import chaos
    from horovod_tpu.fleet.replica import ServingReplica

    monkeypatch.setenv("HVD_TPU_SERVE_SNAPSHOT_STEPS", "2")
    _cfg, _params, build = fleet_pieces
    r = ServingReplica("snap", build)
    r.spawn()
    r.submit(np.arange(1, 9, dtype=np.int32), 8,
             arrival=time.perf_counter())
    r.step()
    assert not r.kv_snapshots, "cadence 2 must not snapshot on step 1"
    r.step()
    assert r.kv_snapshots, "cadence 2 must snapshot on step 2"
    rid, (tokens, _snap, _arr) = next(iter(r.kv_snapshots.items()))
    assert tokens.size >= 8
    # chaos raise on the snapshot site: the beat skips, the step lives
    chaos.configure("serve.snapshot:raise,prob=1", seed=3)
    try:
        r.kv_snapshots = {}
        r.step()
        r.step()
        assert not r.kv_snapshots, "chaos raise must skip the beat"
    finally:
        chaos.clear()
    while r.has_work:
        r.step()
    r.drain()
    r.retire()
