"""horovod_tpu.data: sources, sharding, worker pool, device prefetch.

The input-pipeline contract (docs/DATA.md): deterministic per-rank
sharding over the live topology, ordered worker-pool decode, bounded
double-buffered device staging, and the starvation instrumentation the
bench rides (input_wait / prefetch depth).
"""

import os
import threading
import time

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import data
from horovod_tpu.data import prefetch as prefetch_mod
from horovod_tpu.data import workers as workers_mod
from horovod_tpu.metrics import instruments as instr


def _array_source(n=32, size=4):
    """inputs[i] encodes i so order/identity assertions are trivial."""
    inputs = np.arange(n, dtype=np.float32)[:, None, None, None] * np.ones(
        (n, size, size, 3), np.float32)
    labels = np.arange(n, dtype=np.int32)
    return data.ArraySource(inputs, labels)


# -- sources -----------------------------------------------------------------


def test_synthetic_source_deterministic_per_index():
    s = data.SyntheticSource(64, image_size=6, seed=7)
    a, la = s.batch([3, 11, 3])
    b, lb = s.batch([11, 3, 5])
    assert np.array_equal(a[0], b[1]) and la[0] == lb[1]
    assert np.array_equal(a[1], b[0]) and la[1] == lb[0]
    assert np.array_equal(a[0], a[2])
    # single-sample path agrees with the batch path
    one, lbl = s.sample(11)
    assert np.array_equal(one, a[1]) and lbl == la[1]
    assert 0 <= lbl < s.num_classes


def test_npy_shard_source_round_trip(tmp_path):
    n = 23
    inputs = np.random.RandomState(0).randint(
        0, 256, size=(n, 5, 5, 3), dtype=np.uint8)
    labels = np.arange(n, dtype=np.int64)
    stems = data.write_npy_shards(str(tmp_path), inputs, labels,
                                  num_shards=4)
    assert len(stems) == 4
    src = data.NpyShardSource(str(tmp_path))
    assert len(src) == n
    # cross-shard gather, arbitrary order, duplicates allowed
    idx = [22, 0, 7, 13, 7, 19]
    bx, by = src.batch(idx)
    assert np.array_equal(by, labels[idx])
    assert np.array_equal(bx, inputs[idx])
    sx, sy = src.sample(13)
    assert np.array_equal(sx, inputs[13]) and sy == 13


def test_npy_shard_source_rejects_empty_and_mismatch(tmp_path):
    with pytest.raises(FileNotFoundError):
        data.NpyShardSource(str(tmp_path))
    np.save(tmp_path / "shard-00000-inputs.npy", np.zeros((3, 2)))
    np.save(tmp_path / "shard-00000-labels.npy", np.zeros((2,)))
    with pytest.raises(ValueError, match="disagree"):
        data.NpyShardSource(str(tmp_path))


def test_image_folder_source(tmp_path):
    from PIL import Image

    for cls, color in [("cats", (255, 0, 0)), ("dogs", (0, 255, 0))]:
        d = tmp_path / cls
        d.mkdir()
        for i in range(3):
            Image.new("RGB", (10 + i, 12), color).save(d / f"img{i}.png")
    src = data.ImageFolderSource(str(tmp_path), image_size=8)
    assert len(src) == 6
    assert src.classes == ["cats", "dogs"]
    img, label = src.sample(0)
    assert img.shape == (8, 8, 3) and img.dtype == np.uint8
    assert label == 0 and np.all(img[:, :, 0] == 255)
    img, label = src.sample(5)
    assert label == 1 and np.all(img[:, :, 1] == 255)
    bx, by = src.batch([0, 5])
    assert bx.shape == (2, 8, 8, 3) and list(by) == [0, 1]


def test_open_source_dispatch(tmp_path):
    assert isinstance(data.open_source("synthetic", num_samples=4),
                      data.SyntheticSource)
    with pytest.raises(ValueError, match="requires a dataset path"):
        data.open_source("npy")
    with pytest.raises(ValueError, match="unknown data source"):
        data.open_source("parquet", "/nope")


# -- sharding ----------------------------------------------------------------


def test_shards_partition_the_epoch():
    n, world = 37, 4
    seen = []
    lengths = set()
    for r in range(world):
        s = data.ShardedIndexSampler(
            n, shard=data.ShardSpec(r, world), shuffle=True, seed=3)
        idx = s.shard_indices()
        lengths.add(len(idx))
        seen.extend(idx.tolist())
    assert lengths == {n // world}  # equal-length truncation
    assert len(seen) == len(set(seen))  # disjoint


def test_shard_reshuffles_per_epoch_deterministically():
    s = data.ShardedIndexSampler(32, shard=data.ShardSpec(0, 2), seed=1)
    e0 = s.shard_indices()
    s.set_epoch(1)
    e1 = s.shard_indices()
    assert not np.array_equal(e0, e1)
    s.set_epoch(0)
    assert np.array_equal(s.shard_indices(), e0)


def test_world_resize_reshards_same_epoch_order():
    """Elastic contract: the epoch permutation is world-independent, so a
    resize re-slices the SAME ordering — shards stay disjoint and jointly
    exhaustive before and after."""
    n = 24
    full = data.ShardedIndexSampler(
        n, shard=data.ShardSpec(0, 1), seed=5).shard_indices()
    for world in (2, 3):
        got = np.empty(n, dtype=np.int64)
        for r in range(world):
            sl = data.ShardedIndexSampler(
                n, shard=data.ShardSpec(r, world), seed=5).shard_indices()
            got[r::world] = sl  # strided slicing of the same order
        assert np.array_equal(got, full)


def test_current_shard_follows_topology():
    spec = data.current_shard()
    assert spec.num_shards == hvd.cross_size()
    assert spec.shard == hvd.cross_rank()


def test_batches_drop_remainder_static_shapes():
    s = data.ShardedIndexSampler(30, shard=data.ShardSpec(0, 1),
                                 shuffle=False)
    batches = list(s.batches(8))
    assert [len(b) for b in batches] == [8, 8, 8]
    assert s.num_batches(8) == 3
    s2 = data.ShardedIndexSampler(30, shard=data.ShardSpec(0, 1),
                                  shuffle=False, drop_remainder=False)
    assert [len(b) for b in s2.batches(8)] == [8, 8, 8, 6]


# -- worker pool -------------------------------------------------------------


def test_map_ordered_preserves_order_under_jitter():
    def slow_square(i):
        time.sleep(0.002 * ((i * 7) % 5))
        return i * i

    out = list(workers_mod.map_ordered(slow_square, range(20),
                                       num_workers=4, window=6))
    assert out == [i * i for i in range(20)]


def test_map_ordered_inline_when_zero_workers():
    main = threading.get_ident()
    tids = []

    def probe(i):
        tids.append(threading.get_ident())
        return i

    assert list(workers_mod.map_ordered(probe, range(3),
                                        num_workers=0)) == [0, 1, 2]
    assert set(tids) == {main}


def test_map_ordered_propagates_errors_in_order():
    def maybe_fail(i):
        if i == 3:
            raise RuntimeError("boom")
        return i

    it = workers_mod.map_ordered(maybe_fail, range(6), num_workers=2,
                                 window=4)
    assert [next(it) for _ in range(3)] == [0, 1, 2]
    with pytest.raises(RuntimeError, match="boom"):
        next(it)


def test_default_num_workers_env(monkeypatch):
    monkeypatch.setenv(workers_mod.WORKERS_ENV, "7")
    assert workers_mod.default_num_workers() == 7
    monkeypatch.setenv(workers_mod.WORKERS_ENV, "-1")
    with pytest.raises(ValueError):
        workers_mod.default_num_workers()
    monkeypatch.delenv(workers_mod.WORKERS_ENV)
    assert workers_mod.default_num_workers() >= 1


# -- device prefetcher -------------------------------------------------------


def test_prefetcher_yields_all_batches_in_order():
    batches = [(np.full((2, 3), i, np.float32), np.array([i, i])) for i in
               range(7)]
    pf = data.DevicePrefetcher(iter(batches), depth=2, device_put=False)
    got = [int(b[0][0, 0]) for b in pf]
    assert got == list(range(7))
    # exhaustion is sticky
    with pytest.raises(StopIteration):
        next(pf)
    stats = pf.stats()
    assert stats["batches"] == 7 and stats["prefetch_depth"] == 2


def test_prefetcher_bounded_runahead():
    """The producer must stall once depth batches are staged — bounded
    host/HBM memory is the whole point of the staging queue."""
    produced = []

    def gen():
        for i in range(10):
            produced.append(i)
            yield (np.zeros(1),)

    pf = data.DevicePrefetcher(gen(), depth=2, device_put=False)
    time.sleep(0.3)  # give the producer every chance to run ahead
    # at most depth staged + 1 in the producer's hand
    assert len(produced) <= 3
    list(pf)
    assert len(produced) == 10


def test_prefetcher_depth_zero_is_synchronous():
    pf = data.DevicePrefetcher(iter([(np.ones(2),)] * 3), depth=0,
                               device_put=False)
    assert pf._thread is None
    assert len(list(pf)) == 3


def test_prefetcher_propagates_producer_error():
    def gen():
        yield (np.zeros(1),)
        raise ValueError("decode failed")

    pf = data.DevicePrefetcher(gen(), depth=2, device_put=False)
    next(pf)
    with pytest.raises(ValueError, match="decode failed"):
        next(pf)
    with pytest.raises(ValueError, match="decode failed"):
        next(pf)  # error is sticky too


def test_prefetcher_poll_and_exhausted_marker():
    """The staging-queue consume (serving): poll() never raises
    StopIteration — items, then the sticky EXHAUSTED marker."""
    pf = data.DevicePrefetcher(
        iter([(np.full(2, i),) for i in range(3)]), depth=2,
        device_put=False, source_kind="serving")
    got = []
    while True:
        item = pf.poll(block=True)
        if item is pf.EXHAUSTED:
            break
        got.append(int(item[0][0]))
    assert got == [0, 1, 2]
    assert pf.exhausted
    assert pf.poll() is pf.EXHAUSTED, "exhaustion is sticky for poll too"
    with pytest.raises(StopIteration):
        next(pf)


def test_prefetcher_poll_depth_zero_synchronous():
    pf = data.DevicePrefetcher(iter([(np.ones(1),)] * 2), depth=0,
                               device_put=False)
    assert pf.poll() is not None and pf.poll(block=True) is not None
    assert pf.poll() is pf.EXHAUSTED
    assert pf.exhausted


def test_prefetcher_poll_after_close_returns_exhausted():
    """close() drains the queue (sentinel included): a blocking poll
    afterwards must return EXHAUSTED, not hang on the empty queue."""
    pf = data.DevicePrefetcher(iter([(np.ones(1),)] * 5), depth=2,
                               device_put=False)
    pf.close()
    assert pf.poll(block=True) is pf.EXHAUSTED
    assert pf.poll() is pf.EXHAUSTED


def test_prefetcher_restart_contract():
    """The long-lived reuse contract: restart() re-arms an exhausted
    prefetcher on a fresh iterable, stats keep summing, and restarting
    an ACTIVE stream is refused (its producer would race the new one)."""
    pf = data.DevicePrefetcher(iter([(np.zeros(1),)] * 2), depth=2,
                               device_put=False)
    with pytest.raises(RuntimeError, match="active"):
        pf.restart(iter([]))
    assert len(list(pf)) == 2 and pf.exhausted
    pf.restart(iter([(np.ones(1),)] * 3))
    assert not pf.exhausted
    assert len(list(pf)) == 3
    assert pf.stats()["batches"] == 5, "stats sum across streams"
    # restart also revives a close()d prefetcher
    pf.close()
    assert pf.closed
    pf.restart(iter([(np.ones(1),)]))
    assert not pf.closed and len(list(pf)) == 1
    pf.close()


def test_prefetcher_restart_does_not_leak_old_stream():
    """A producer parked on a full queue at close() must never deliver
    its stale items into the restarted stream's queue."""
    def slow_then_poisoned():
        for i in range(50):
            yield (np.full(1, -1.0),)  # stale marker

    pf = data.DevicePrefetcher(slow_then_poisoned(), depth=1,
                               device_put=False)
    time.sleep(0.1)  # let the producer fill the queue and block
    pf.close()
    pf.restart(iter([(np.full(1, float(i)),) for i in range(4)]))
    got = [float(b[0][0]) for b in pf]
    assert got == [0.0, 1.0, 2.0, 3.0], got
    pf.close()


def test_prefetcher_bf16_cast_floats_only():
    import jax.numpy as jnp

    pf = data.DevicePrefetcher(
        iter([(np.ones((2, 2), np.float32), np.array([1, 2], np.int32))]),
        depth=1, cast="bfloat16", device_put=True)
    x, y = next(pf)
    assert x.dtype == jnp.bfloat16
    assert y.dtype == np.int32  # labels untouched


def test_prefetch_depth_env(monkeypatch):
    monkeypatch.setenv(prefetch_mod.PREFETCH_ENV, "5")
    assert prefetch_mod.default_prefetch_depth() == 5
    monkeypatch.setenv(prefetch_mod.PREFETCH_ENV, "-2")
    with pytest.raises(ValueError):
        prefetch_mod.default_prefetch_depth()
    monkeypatch.delenv(prefetch_mod.PREFETCH_ENV)
    assert prefetch_mod.default_prefetch_depth() == 2


# -- loader end-to-end -------------------------------------------------------


def test_loader_device_batches_and_len():
    import jax

    src = _array_source(n=32)
    loader = data.DataLoader(src, batch_size=4, shuffle=False,
                             shard=data.ShardSpec(0, 1),
                             num_workers=2, prefetch_depth=2)
    assert len(loader) == 8
    batches = list(loader)
    assert len(batches) == 8
    assert isinstance(batches[0][0], jax.Array)
    # shuffle=False + identity labels: batches enumerate the dataset
    flat = np.concatenate([np.asarray(b[1]) for b in batches])
    assert np.array_equal(flat, np.arange(32))
    assert loader.stats()["batches"] == 8


def test_loader_shards_cover_world_disjointly():
    src = _array_source(n=32)
    seen = []
    for r in range(4):
        loader = data.DataLoader(src, batch_size=2, seed=9,
                                 shard=data.ShardSpec(r, 4),
                                 device_put=False, num_workers=0,
                                 prefetch_depth=0)
        for _, labels in loader:
            seen.extend(np.asarray(labels).tolist())
    assert sorted(seen) == list(range(32))


def test_loader_transform_runs_on_worker_pool():
    src = _array_source(n=8)

    def transform(x, y):
        return x * 2.0, y + 100

    loader = data.DataLoader(src, batch_size=4, shuffle=False,
                             shard=data.ShardSpec(0, 1),
                             transform=transform, device_put=False,
                             num_workers=2, prefetch_depth=1)
    x, y = next(iter(loader))
    assert np.asarray(y)[0] == 100
    assert float(np.asarray(x)[1, 0, 0, 0]) == 2.0


def test_reiterating_loader_closes_abandoned_prefetcher():
    """`break`-ing an epoch (or `next(iter(loader))`) must not leak the
    old prefetcher's producer thread or its staged device batches — the
    next __iter__ closes it."""
    src = _array_source(n=32)
    loader = data.DataLoader(src, batch_size=4, shuffle=False,
                             shard=data.ShardSpec(0, 1),
                             num_workers=1, prefetch_depth=2)
    first = iter(loader)
    next(first)  # abandon mid-epoch with batches still staged
    second = iter(loader)
    assert first._closed
    if first._thread is not None:
        first._thread.join(timeout=5)
        assert not first._thread.is_alive()
    assert len(list(second)) == 8  # fresh epoch unaffected
    loader._last.close()


def test_loader_epoch_reshuffle():
    src = _array_source(n=16)
    loader = data.DataLoader(src, batch_size=16, seed=2,
                             shard=data.ShardSpec(0, 1),
                             device_put=False, num_workers=0,
                             prefetch_depth=0)
    loader.set_epoch(0)
    _, y0 = next(iter(loader))
    loader.set_epoch(1)
    _, y1 = next(iter(loader))
    loader.set_epoch(0)
    _, y0b = next(iter(loader))
    assert not np.array_equal(y0, y1)
    assert np.array_equal(y0, y0b)


def test_make_loader_npy_normalizes_uint8(tmp_path):
    inputs = np.full((8, 4, 4, 3), 255, np.uint8)
    labels = np.zeros(8, np.int32)
    data.write_npy_shards(str(tmp_path), inputs, labels)
    loader = data.make_loader("npy", str(tmp_path), batch_size=4,
                              shard=data.ShardSpec(0, 1),
                              device_put=False, prefetch_depth=0,
                              num_workers=0)
    x, _ = next(iter(loader))
    assert x.dtype == np.float32 and float(x.max()) == 1.0


def test_loader_feeds_compiled_train_step():
    """The headline integration: loader batches drive training.py's
    compiled SPMD step (global batch sharded over the 8-device mesh)."""
    import jax.numpy as jnp
    import optax
    from horovod_tpu import training
    from horovod_tpu.models import MLP

    n, batch = 64, 16  # divisible by the 8-device world axis
    rng = np.random.RandomState(0)
    src = data.ArraySource(rng.randn(n, 12).astype(np.float32),
                           rng.randint(0, 4, size=(n,)).astype(np.int32))
    loader = data.DataLoader(src, batch_size=batch,
                             shard=data.ShardSpec(0, 1),
                             num_workers=2, prefetch_depth=2, seed=0)
    model = MLP(features=(16, 4))
    optimizer = optax.sgd(0.05)
    sample = jnp.zeros((2, 12), jnp.float32)
    state = training.create_train_state(
        model, optimizer, __import__("jax").random.PRNGKey(0), sample)
    state = training.replicate_state(state)
    step = training.data_parallel_train_step(model, optimizer)
    state, loss = training.fit_epoch(step, state, loader, epoch=0)
    assert loss is not None and np.isfinite(loss)
    assert int(state.step) == len(loader)


# -- instrumentation ---------------------------------------------------------


def test_pipeline_metrics_reach_registry():
    before_wait = instr.DATA_HOST_WAIT.get()["count"]
    src = _array_source(n=16)
    loader = data.DataLoader(src, batch_size=4, shuffle=False,
                             shard=data.ShardSpec(0, 1),
                             num_workers=1, prefetch_depth=2)
    list(loader)
    assert instr.DATA_HOST_WAIT.get()["count"] >= before_wait + 4
    assert instr.DATA_BATCHES.labels(source="array").get() >= 4
    assert instr.DATA_BATCH_PRODUCE.get()["count"] >= 4
    assert instr.DATA_PREFETCH_DEPTH.get() >= 0
    stats = loader.stats()
    for key in ("input_wait_ms_total", "host_produce_ms_mean",
                "device_put_ms_mean", "starved_batches"):
        assert key in stats


def test_pipeline_metrics_in_prometheus_exposition():
    """Acceptance criterion: the pipeline metrics appear in /metrics."""
    from horovod_tpu.metrics import exposition

    src = _array_source(n=8)
    list(data.DataLoader(src, batch_size=4, shuffle=False,
                         shard=data.ShardSpec(0, 1),
                         num_workers=1, prefetch_depth=1))
    text = exposition.render()
    assert "hvd_tpu_data_prefetch_depth" in text
    assert "hvd_tpu_data_host_wait_seconds_bucket" in text
    assert 'hvd_tpu_data_batches_total{source="array"}' in text
