"""Native C++ controller tests.

Reference analog: the background-thread path every parallel test in the
reference implicitly exercises (SURVEY.md §3.2) plus targeted unit checks
for the aux components (response cache stats, group atomicity, timeline
output, autotune knobs).  Skipped when the native core failed to build
(feature-gated skips, reference test technique §4).
"""

import json
import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd

pytestmark = pytest.mark.skipif(
    not (lambda: (hvd.init() or hvd.native_built()))(),
    reason="native core not built",
)


def test_native_loaded():
    assert hvd.native_built()


def test_native_allreduce_roundtrip():
    x = jnp.arange(16, dtype=jnp.float32)
    out = hvd.allreduce(x, op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_native_async_future_handle():
    h = hvd.allreduce_async({"a": jnp.ones((4,)), "b": jnp.zeros((2, 2))})
    out = h.wait()
    np.testing.assert_allclose(np.asarray(out["a"]), np.ones(4))
    assert h.done()


def test_native_fusion_across_entries():
    # many small tensors in one async batch: the controller fuses them into
    # one collective (observable via cache stats moving and results correct)
    tensors = [jnp.full((8,), float(i)) for i in range(20)]
    outs = hvd.grouped_allreduce(tensors, op=hvd.Sum)
    for i, o in enumerate(outs):
        np.testing.assert_allclose(np.asarray(o), np.full(8, float(i)))


def test_native_response_cache_hits():
    ctrl = hvd.common.basics._require_init().controller
    before_h, before_m = ctrl.cache_hits(), ctrl.cache_misses()
    for _ in range(3):
        hvd.allreduce(jnp.ones((5,)), name="cache_probe")
    after_h, after_m = ctrl.cache_hits(), ctrl.cache_misses()
    # same name+signature resubmitted -> at least one hit, exactly one miss
    assert after_m - before_m == 1
    assert after_h - before_h >= 2


def test_native_cache_bitvector_bypass():
    """Steady state, the negotiation payload is O(cache positions), not a
    full request list (reference: ResponseCache bit-vector sync,
    horovod/common/response_cache.cc).  First submission of a signature
    travels fully encoded; repeats travel as one i64 position."""
    ctrl = hvd.common.basics._require_init().controller
    hvd.allreduce(jnp.ones((64,)), name="bitvec_probe")
    first = ctrl.last_request_bytes()
    hits_before = ctrl.cache_hits()
    steady_sizes = []
    for _ in range(3):
        hvd.allreduce(jnp.ones((64,)), name="bitvec_probe")
        steady_sizes.append(ctrl.last_request_bytes())
    assert ctrl.cache_hits() - hits_before >= 3
    # steady-state cycles carry [version][npos][pos][empty entry list]:
    # constant-size and far smaller than the full encoding
    assert all(s == steady_sizes[0] for s in steady_sizes)
    assert steady_sizes[0] < first
    assert steady_sizes[0] <= 32
    # a changed signature (new shape) must fall back to full encoding
    hvd.allreduce(jnp.ones((128,)), name="bitvec_probe")
    assert ctrl.last_request_bytes() > steady_sizes[0]


def test_native_all_ops_roundtrip():
    x = jnp.arange(8.0)
    np.testing.assert_allclose(np.asarray(hvd.allgather(x)), np.asarray(x))
    np.testing.assert_allclose(
        np.asarray(hvd.broadcast(x, 0)), np.asarray(x)
    )
    out, splits = hvd.alltoall(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))
    np.testing.assert_allclose(np.asarray(hvd.reducescatter(x)),
                               np.asarray(x))
    hvd.barrier()


def test_native_duplicate_name_rejected():
    """Names stay claimed from enqueue until the response executes, so a
    resubmission inside the negotiation window must be rejected
    (reference: tensor-table duplicate check).

    Since the CV-wake loop (round 5) a world-of-1 entry executes within
    microseconds of enqueue, so a slow cycle no longer holds the window
    open.  Instead an INCOMPLETE grouped call pins the claim
    deterministically: the coordinator cannot release a group until all
    ``group_size`` members arrive, so the first member's name stays
    claimed until the second member is submitted."""
    from horovod_tpu.common import basics
    from horovod_tpu.native.controller import OP_ALLREDUCE

    ctrl = basics._require_init().controller
    if ctrl is None or not ctrl.is_native:
        pytest.skip("native controller not active")
    f1 = ctrl.enqueue(jnp.ones((8,)), OP_ALLREDUCE, name="dup",
                      group_key="dupg#0", group_size=2)
    # the group is incomplete: "dup" is claimed and pending
    with pytest.raises(ValueError):
        ctrl.enqueue(jnp.ones((8,)), OP_ALLREDUCE, name="dup",
                     group_key="dupg#0", group_size=2)
    # ... and the batched entry point enforces the same check
    if ctrl.supports_batch:
        with pytest.raises(ValueError):
            ctrl.enqueue_batch([jnp.ones((8,))], ["dup"], OP_ALLREDUCE,
                               group_key="dupg#0", group_size=2)
    # completing the group releases both members and frees the name
    f2 = ctrl.enqueue(jnp.ones((8,)), OP_ALLREDUCE, name="dup2",
                      group_key="dupg#0", group_size=2)
    f1.result()
    f2.result()
    hvd.allreduce(jnp.ones((4,)), name="dup")  # name reusable again


def test_native_timeline_comm_span_covers_execution(tmp_path):
    """XLA_COMM must end when the result data is READY, not when the
    async dispatch returns (round-2 verdict item 6: dispatch-time spans
    showed near-zero COMM).  A large reduction's COMM span must cover a
    meaningful fraction of its measured wall time."""
    path = str(tmp_path / "timeline_comm.json")
    hvd.shutdown()
    os.environ["HVD_TPU_TIMELINE"] = path
    try:
        hvd.init()
        big = jnp.ones((4 << 20,), jnp.float32)  # 16 MB: >> dispatch time
        t0 = time.perf_counter()
        out = hvd.allreduce(big, name="comm_span_probe", op=hvd.Sum)
        import jax

        jax.block_until_ready(out)
        wall = time.perf_counter() - t0
        hvd.shutdown()
    finally:
        os.environ.pop("HVD_TPU_TIMELINE", None)
        hvd.init()
    with open(path) as f:
        events = json.load(f)
    spans = {}
    for e in events:
        if (
            e.get("name") == "XLA_COMM"
            and str(e.get("args", {}).get("tensor", "")).startswith(
                "comm_span_probe"
            )
        ):
            spans.setdefault(e["ph"], e["ts"])
    assert "B" in spans and "E" in spans, spans
    comm_s = (spans["E"] - spans["B"]) / 1e6  # chrome trace ts is in us
    # the span includes compile on first use, so it can exceed wall-start
    # measurement; the regression being pinned is span ~= dispatch-only
    # (tens of microseconds) — demand a real fraction of the wall time
    assert comm_s >= 0.05 * wall, (comm_s, wall)


def test_native_autotune_knobs_readable():
    ctrl = hvd.common.basics._require_init().controller
    assert ctrl.fusion_threshold() > 0
    assert ctrl.cycle_time_ms() > 0
    assert ctrl.pending_count() >= 0


def test_native_autotune_converges_on_synthetic_surface(tmp_path):
    """The tuner must CLIMB the score surface, not walk it blindly
    (round-2 verdict weak item 8): on a unimodal synthetic surface it has
    to converge to the optimum and hold there, logging its samples."""
    import math

    log = str(tmp_path / "autotune.csv")
    hvd.shutdown()
    os.environ["HVD_TPU_AUTOTUNE"] = "1"
    os.environ["HVD_TPU_AUTOTUNE_LOG"] = log
    # start at the TOP of the threshold grid: a tuner that never tries the
    # reverse direction from a grid edge would hold at 128MB immediately
    os.environ["HVD_TPU_FUSION_THRESHOLD"] = str(128 << 20)
    try:
        hvd.init()
        ctrl = hvd.common.basics._require_init().controller
        assert ctrl.autotune_active()

        opt_threshold, opt_cycle = 16 << 20, 2.5

        def score():
            t = ctrl.fusion_threshold()
            c = ctrl.cycle_time_ms()
            return (
                1000.0
                - (math.log2(t) - math.log2(opt_threshold)) ** 2 * 10
                - (math.log2(c) - math.log2(opt_cycle)) ** 2 * 10
            )

        for _ in range(64):
            if not ctrl.autotune_active():
                break
            ctrl.autotune_inject(score())
        assert not ctrl.autotune_active(), "tuner never converged/held"
        assert ctrl.fusion_threshold() == opt_threshold
        assert ctrl.cycle_time_ms() == opt_cycle
        hvd.shutdown()
    finally:
        os.environ.pop("HVD_TPU_AUTOTUNE", None)
        os.environ.pop("HVD_TPU_AUTOTUNE_LOG", None)
        os.environ.pop("HVD_TPU_FUSION_THRESHOLD", None)
        hvd.init()
    with open(log) as f:
        lines = f.read().strip().splitlines()
    assert lines[0].startswith("sample,") and len(lines) >= 4


def test_native_timeline_writes_chrome_trace(tmp_path):
    """Restart the framework with a timeline file and check the output is
    loadable chrome-trace JSON with our phases (reference: §5.1 format)."""
    path = str(tmp_path / "timeline.json")
    hvd.shutdown()
    os.environ["HVD_TPU_TIMELINE"] = path
    try:
        hvd.init()
        hvd.allreduce(jnp.ones((64,)), name="traced_tensor")
        hvd.shutdown()
    finally:
        os.environ.pop("HVD_TPU_TIMELINE", None)
        hvd.init()  # restore for subsequent tests
    with open(path) as f:
        events = json.load(f)
    names = {e.get("name") for e in events}
    assert "QUEUE" in names and "XLA_COMM" in names
    tensors = {
        e.get("args", {}).get("tensor")
        for e in events if e.get("ph") in ("B", "E")
    }
    assert any(t and t.startswith("traced_tensor") for t in tensors)


def test_runtime_start_stop_timeline(tmp_path):
    """hvd.start_timeline/stop_timeline at RUNTIME — no env, no restart
    (reference: horovod_start_timeline/horovod_stop_timeline)."""
    path = str(tmp_path / "runtime_timeline.json")
    hvd.allreduce(jnp.ones((8,)), name="before_timeline")  # not traced
    hvd.start_timeline(path)
    try:
        with pytest.raises(ValueError):
            hvd.start_timeline(str(tmp_path / "other.json"))  # already on
        hvd.allreduce(jnp.ones((32,)), name="runtime_traced")
    finally:
        hvd.stop_timeline()
    hvd.allreduce(jnp.ones((8,)), name="after_timeline")  # not traced
    with open(path) as f:
        events = json.load(f)
    tensors = {
        e.get("args", {}).get("tensor")
        for e in events if e.get("ph") in ("B", "E")
    }
    assert any(t and t.startswith("runtime_traced") for t in tensors)
    assert not any(t and t.startswith("after_timeline") for t in tensors)
    # a second start/stop round works (fresh file, fresh writer thread)
    path2 = str(tmp_path / "runtime_timeline2.json")
    hvd.start_timeline(path2)
    hvd.allreduce(jnp.ones((16,)), name="second_round")
    hvd.stop_timeline()
    with open(path2) as f:
        events = json.load(f)
    assert any(
        e.get("args", {}).get("tensor", "").startswith("second_round")
        for e in events if e.get("ph") in ("B", "E")
    )


def test_runtime_timeline_python_fallback(tmp_path, monkeypatch):
    """start_timeline on the python-fallback controller records the eager
    engine's spans through utils.timeline (the native core otherwise owns
    the file)."""
    import horovod_tpu.common.basics as basics

    path = str(tmp_path / "fallback_timeline.json")
    ctrl = basics._state.controller
    monkeypatch.setattr(type(ctrl), "is_native", False)
    hvd.start_timeline(path)
    try:
        hvd.allreduce(jnp.ones((8,)), name="fallback_traced")
    finally:
        hvd.stop_timeline()
        monkeypatch.undo()
    with open(path) as f:
        events = json.load(f)
    tensors = {
        e.get("args", {}).get("tensor")
        for e in events if e.get("ph") in ("B", "E")
    }
    assert "fallback_traced" in tensors


def test_setup_py_build_ext_compiles_core(tmp_path):
    """Packaging contract (VERDICT r3 weak #5): ``pip install .`` must
    BUILD the native core, not silently ship the checked-in binary.
    Exercises the same BuildNativeCore command pip's wheel build runs."""
    import shutil
    import subprocess
    import sys

    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    build_lib = tmp_path / "lib"
    subprocess.run(
        [sys.executable, "setup.py", "-q", "build_ext",
         "--build-lib", str(build_lib), "--build-temp", str(tmp_path / "t")],
        cwd=repo, check=True, capture_output=True, timeout=240,
    )
    so = build_lib / "horovod_tpu" / "native" / "libhvd_tpu_core.so"
    assert so.exists() and so.stat().st_size > 10000
