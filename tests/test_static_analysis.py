"""Self-tests for the cross-layer contract checker (horovod_tpu.analysis).

Two layers of proof:

* the REAL repo passes every contract (so the suite gates tier-1), and
  the whole run finishes far inside its 10-second budget;
* on synthetic mini-trees, deliberately introducing one drift of each
  class — a ctypes arity mismatch, an undocumented env var, an
  uncatalogued metric name, an undocumented chaos site — is caught with
  a finding naming the offending file, and the suppression machinery
  (inline markers, allowlist file) behaves exactly as documented.

The analysis package is stdlib-only, so these tests are cheap tier-1
citizens (marker: ``analysis``).
"""

import os
import subprocess
import sys
import time

import pytest

from horovod_tpu import analysis
from horovod_tpu.analysis import _common, c_api

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(root, rel, text):
    path = os.path.join(str(root), rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(text)
    return path


# -- the real repo ------------------------------------------------------------


def test_repo_holds_every_contract_fast():
    t0 = time.perf_counter()
    findings = analysis.run_all(REPO)
    elapsed = time.perf_counter() - t0
    assert not findings, "\n".join(f.render() for f in findings)
    assert elapsed < 10.0, f"analysis took {elapsed:.1f}s (budget 10s)"


def test_check_py_standalone_runs_clean():
    """tools/check.py must work without importing jax (bare-box CI lint
    job): the bootstrap stubs the parent package."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check.py")],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "all contracts hold" in proc.stderr


# -- c-api pass on synthetic trees -------------------------------------------

_SYN_C_API = """\
extern "C" {

int hvdtpu_foo(int a, long long b) { return 0; }

long long hvdtpu_counter() { return 0; }

}  // extern "C"
"""


def _syn_controller(argtypes, restype="ctypes.c_int",
                    counter_args="\nlib.hvdtpu_counter.argtypes = []"):
    return (
        "import ctypes\n"
        f"lib.hvdtpu_foo.restype = {restype}\n"
        f"lib.hvdtpu_foo.argtypes = {argtypes}\n"
        "lib.hvdtpu_counter.restype = ctypes.c_longlong"
        f"{counter_args}\n"
    )


def test_c_api_clean_tree_passes(tmp_path):
    _write(tmp_path, _common.C_API_CC, _SYN_C_API)
    _write(tmp_path, _common.CONTROLLER_PY,
           _syn_controller("[ctypes.c_int, ctypes.c_longlong]"))
    assert analysis.run_all(str(tmp_path), ["c-api"]) == []


def test_c_api_arity_drift_caught(tmp_path):
    _write(tmp_path, _common.C_API_CC, _SYN_C_API)
    _write(tmp_path, _common.CONTROLLER_PY,
           _syn_controller("[ctypes.c_int]"))  # one arg short
    findings = analysis.run_all(str(tmp_path), ["c-api"])
    assert len(findings) == 1
    f = findings[0]
    assert f.file == _common.CONTROLLER_PY and f.key == "hvdtpu_foo"
    assert "1 entries" in f.message and "2 parameters" in f.message


def test_c_api_type_and_restype_drift_caught(tmp_path):
    _write(tmp_path, _common.C_API_CC, _SYN_C_API)
    _write(tmp_path, _common.CONTROLLER_PY, _syn_controller(
        "[ctypes.c_int, ctypes.c_int]",      # c_int where longlong due
        restype="ctypes.c_double"))           # int return misdeclared
    keys = {(f.key, "argtypes[1]" in f.message or "restype" in f.message)
            for f in analysis.run_all(str(tmp_path), ["c-api"])}
    assert keys == {("hvdtpu_foo", True)}


def test_c_api_missing_argtypes_and_unknown_symbol_caught(tmp_path):
    _write(tmp_path, _common.C_API_CC, _SYN_C_API)
    _write(tmp_path, _common.CONTROLLER_PY, (
        "import ctypes\n"
        "lib.hvdtpu_foo.restype = ctypes.c_int\n"   # argtypes missing
        "lib.hvdtpu_counter.restype = ctypes.c_longlong\n"
        "lib.hvdtpu_counter.argtypes = []\n"
        "lib.hvdtpu_ghost.restype = ctypes.c_int\n"  # not declared in C
        "lib.hvdtpu_ghost.argtypes = []\n"
    ))
    found = {f.key: f.message
             for f in analysis.run_all(str(tmp_path), ["c-api"])}
    assert "only 0 argtypes" in found["hvdtpu_foo"]
    assert "no such function" in found["hvdtpu_ghost"]


def test_c_api_harness_checked_too(tmp_path):
    """Drift inside an embedded ``python -c`` string literal in a test
    harness is caught — the scan is textual by design."""
    _write(tmp_path, _common.C_API_CC, _SYN_C_API)
    _write(tmp_path, _common.CONTROLLER_PY,
           _syn_controller("[ctypes.c_int, ctypes.c_longlong]"))
    harness = _common.CTYPES_HARNESSES[0]
    _write(tmp_path, harness, (
        'code = f"""\n'
        "lib.hvdtpu_foo.restype = ctypes.c_int\n"
        "lib.hvdtpu_foo.argtypes = [ctypes.c_int, ctypes.c_int,\n"
        "                           ctypes.c_int]\n"
        '"""\n'
    ))
    findings = analysis.run_all(str(tmp_path), ["c-api"])
    assert [f.file for f in findings] == [harness]
    assert "3 entries" in findings[0].message


def test_c_api_duplicate_declarations_all_checked(tmp_path):
    """The harnesses declare the same symbol once per embedded blob; a
    drifted EARLY declaration must be caught even when a later one is
    correct (last-occurrence-wins would mask it)."""
    _write(tmp_path, _common.C_API_CC, _SYN_C_API)
    _write(tmp_path, _common.CONTROLLER_PY,
           _syn_controller("[ctypes.c_int, ctypes.c_longlong]"))
    harness = _common.CTYPES_HARNESSES[0]
    _write(tmp_path, harness, (
        "blob_a = '''\n"
        "lib.hvdtpu_foo.restype = ctypes.c_int\n"
        "lib.hvdtpu_foo.argtypes = [ctypes.c_int]\n"   # drifted
        "'''\n"
        "blob_b = '''\n"
        "lib.hvdtpu_foo.restype = ctypes.c_int\n"
        "lib.hvdtpu_foo.argtypes = [ctypes.c_int, ctypes.c_longlong]\n"
        "'''\n"
    ))
    findings = analysis.run_all(str(tmp_path), ["c-api"])
    assert [(f.file, f.key) for f in findings] == [(harness, "hvdtpu_foo")]
    assert "1 entries" in findings[0].message


def test_c_api_missing_restype_on_nonint_return_caught(tmp_path):
    """argtypes without restype on a non-int-returning export: ctypes
    silently defaults to c_int and truncates the long long."""
    _write(tmp_path, _common.C_API_CC, _SYN_C_API)
    _write(tmp_path, _common.CONTROLLER_PY, (
        "import ctypes\n"
        "lib.hvdtpu_foo.restype = ctypes.c_int\n"
        "lib.hvdtpu_foo.argtypes = [ctypes.c_int, ctypes.c_longlong]\n"
        "lib.hvdtpu_counter.argtypes = []\n"   # restype missing
    ))
    findings = analysis.run_all(str(tmp_path), ["c-api"])
    assert [f.key for f in findings] == ["hvdtpu_counter"]
    assert "default to c_int" in findings[0].message


def test_real_c_api_parser_sees_full_surface():
    """The parser must see every symbol the production binding binds —
    anchored on a few that exercise tricky parses (function pointer,
    multi-line params)."""
    syms = c_api.declared_symbols(REPO)
    for required in ("hvdtpu_init", "hvdtpu_set_exec_callback",
                     "hvdtpu_enqueue_n", "hvdtpu_pack",
                     "hvdtpu_chaos_set"):
        assert required in syms
    funcs = c_api.parse_c_api(
        _common.read_text(os.path.join(REPO, _common.C_API_CC)))
    assert funcs["hvdtpu_set_exec_callback"].args == ("funcptr", "void*")
    assert len(funcs["hvdtpu_init"].args) == 12


# -- env pass on synthetic trees ---------------------------------------------

_SYN_RUNNING = """\
| Variable | Meaning |
|---|---|
| `HVD_TPU_KNOWN` | documented knob |
"""


def test_env_clean_tree_passes(tmp_path):
    _write(tmp_path, _common.RUNNING_MD, _SYN_RUNNING)
    _write(tmp_path, "horovod_tpu/mod.py",
           'import os\nv = os.environ.get("HVD_TPU_KNOWN")\n')
    assert analysis.run_all(str(tmp_path), ["env"]) == []


def test_env_undocumented_read_caught(tmp_path):
    _write(tmp_path, _common.RUNNING_MD, _SYN_RUNNING)
    _write(tmp_path, "horovod_tpu/mod.py", (
        "import os\n"
        'k = os.environ.get("HVD_TPU_KNOWN")\n'
        'v = os.environ.get("HVD_TPU_SURPRISE")\n'
    ))
    findings = analysis.run_all(str(tmp_path), ["env"])
    assert len(findings) == 1
    assert findings[0].file == "horovod_tpu/mod.py"
    assert findings[0].key == "HVD_TPU_SURPRISE"


def test_env_tools_reads_scoped(tmp_path):
    """tools/ scripts legitimize doc rows but never raise hygiene
    findings: an undocumented tools-only read is ignored, and a row
    backed only by a tools read is not stale."""
    _write(tmp_path, _common.RUNNING_MD,
           _SYN_RUNNING + "| `HVD_TPU_TOOL_DOCED` | bench knob |\n")
    _write(tmp_path, "horovod_tpu/mod.py",
           'import os\nv = os.environ.get("HVD_TPU_KNOWN")\n')
    _write(tmp_path, "tools/bench.py", (
        "import os\n"
        'a = os.environ.get("HVD_TPU_TOOL_DOCED")\n'
        'b = os.environ.get("HVD_TPU_TOOL_SURPRISE")\n'
    ))
    assert analysis.run_all(str(tmp_path), ["env"]) == []


def test_env_stale_doc_row_caught(tmp_path):
    _write(tmp_path, _common.RUNNING_MD,
           _SYN_RUNNING + "| `HVD_TPU_GONE` | removed knob |\n")
    _write(tmp_path, "horovod_tpu/mod.py",
           'import os\nv = os.environ.get("HVD_TPU_KNOWN")\n')
    findings = analysis.run_all(str(tmp_path), ["env"])
    assert [f.key for f in findings] == ["HVD_TPU_GONE"]
    assert findings[0].file == _common.RUNNING_MD


def test_env_raw_parse_caught_and_wildcard_docs(tmp_path):
    _write(tmp_path, _common.RUNNING_MD,
           _SYN_RUNNING + "and the `HVD_TPU_FAM_*` family\n")
    _write(tmp_path, "horovod_tpu/mod.py", (
        "import os\n"
        'n = int(os.environ.get("HVD_TPU_KNOWN", "1"))\n'
        'f = os.environ.get("HVD_TPU_FAM_A")\n'  # wildcard-covered
    ))
    findings = analysis.run_all(str(tmp_path), ["env"])
    assert len(findings) == 1
    assert "raw numeric parse" in findings[0].message
    assert findings[0].key == "HVD_TPU_KNOWN"


def test_env_native_reads_scanned(tmp_path):
    _write(tmp_path, _common.RUNNING_MD, _SYN_RUNNING)
    _write(tmp_path, "horovod_tpu/mod.py",
           'import os\nk = os.environ.get("HVD_TPU_KNOWN")\n')
    _write(tmp_path, "horovod_tpu/native/src/x.h",
           '#include <cstdlib>\nauto v = std::getenv("HVD_TPU_NATIVE_ONLY");\n')
    findings = analysis.run_all(str(tmp_path), ["env"])
    assert [f.key for f in findings] == ["HVD_TPU_NATIVE_ONLY"]
    assert findings[0].file.endswith("x.h")


# -- metrics pass on synthetic trees -----------------------------------------


def _metrics_tree(tmp_path, instruments, docs, module=""):
    _write(tmp_path, _common.INSTRUMENTS_PY, instruments)
    _write(tmp_path, _common.METRICS_MD, docs)
    if module:
        _write(tmp_path, "horovod_tpu/mod.py", module)


def test_metrics_clean_tree_passes(tmp_path):
    _metrics_tree(
        tmp_path,
        'A = counter("hvd_tpu_a_total", "doc")\n',
        "catalogue: `hvd_tpu_a_total`\n",
    )
    assert analysis.run_all(str(tmp_path), ["metrics"]) == []


def test_metrics_uncatalogued_name_caught(tmp_path):
    _metrics_tree(
        tmp_path,
        'A = counter("hvd_tpu_a_total", "doc")\n',
        "catalogue: `hvd_tpu_a_total`\n",
        module='r = counter("hvd_tpu_rogue_total", "undeclared")\n',
    )
    findings = analysis.run_all(str(tmp_path), ["metrics"])
    assert [f.file for f in findings] == ["horovod_tpu/mod.py"]
    assert findings[0].key == "hvd_tpu_rogue_total"


def test_metrics_undocumented_and_stale_doc_caught(tmp_path):
    _metrics_tree(
        tmp_path,
        'A = counter("hvd_tpu_a_total", "doc")\n'
        'B = gauge("hvd_tpu_b", "doc")\n',
        "catalogue: `hvd_tpu_a_total` and `hvd_tpu_vanished`\n",
    )
    found = {f.key: f.file
             for f in analysis.run_all(str(tmp_path), ["metrics"])}
    assert found == {
        "hvd_tpu_b": _common.INSTRUMENTS_PY,       # not documented
        "hvd_tpu_vanished": _common.METRICS_MD,    # documented, gone
    }


def test_metrics_brace_expansion_understood(tmp_path):
    _metrics_tree(
        tmp_path,
        'H = gauge("hvd_tpu_cache_hits", "d")\n'
        'M = gauge("hvd_tpu_cache_misses", "d")\n'
        'S = gauge("hvd_tpu_t_seconds", "d", ["phase"])\n',
        "`hvd_tpu_cache_{hits,misses}` and `hvd_tpu_t_seconds{phase}`\n",
    )
    assert analysis.run_all(str(tmp_path), ["metrics"]) == []


# -- chaos pass on synthetic trees -------------------------------------------

_SYN_CHAOS_INIT = """\
SITES = (
    "transport.frame.send",
    "module.step",
)
"""

_SYN_FAULT_MD = """\
| site | layer |
|---|---|
| `transport.frame.send` | native |
| `module.step` | python |
"""


def _chaos_tree(tmp_path, init=_SYN_CHAOS_INIT, doc=_SYN_FAULT_MD,
                module='def f():\n    point("module.step")\n',
                native='Decide("transport.frame.send");\n'):
    _write(tmp_path, _common.CHAOS_INIT_PY, init)
    _write(tmp_path, _common.FAULT_MD, doc)
    _write(tmp_path, "horovod_tpu/mod.py", module)
    _write(tmp_path, "horovod_tpu/native/src/t.h", native)


def test_chaos_clean_tree_passes(tmp_path):
    _chaos_tree(tmp_path)
    assert analysis.run_all(str(tmp_path), ["chaos"]) == []


def test_chaos_undocumented_site_caught(tmp_path):
    _chaos_tree(tmp_path, doc="| site | layer |\n|---|---|\n"
                              "| `transport.frame.send` | native |\n")
    findings = analysis.run_all(str(tmp_path), ["chaos"])
    assert [(f.key, f.file) for f in findings] == [
        ("module.step", _common.CHAOS_INIT_PY)]
    assert "site table" in findings[0].message


def test_chaos_uncatalogued_point_and_dead_entry_caught(tmp_path):
    _chaos_tree(tmp_path,
                module='def f():\n    raise_point("rogue.site")\n')
    found = {f.key: f for f in analysis.run_all(str(tmp_path), ["chaos"])}
    assert found["rogue.site"].file == "horovod_tpu/mod.py"
    # module.step lost its only call site -> dead catalogue entry
    assert "dead catalogue entry" in found["module.step"].message


def test_chaos_native_divergence_caught(tmp_path):
    _chaos_tree(tmp_path,
                native='Decide("transport.frame.send");\n'
                       'Decide("transport.frame.recv");\n')
    findings = analysis.run_all(str(tmp_path), ["chaos"])
    assert [f.key for f in findings] == ["transport.frame.recv"]
    assert findings[0].file.endswith("t.h")


# -- locks pass on synthetic trees -------------------------------------------

_SYN_LOCKS_HEAD = "import threading\n\n\n"


def test_locks_clean_tree_passes(tmp_path):
    _write(tmp_path, "horovod_tpu/pool.py", _SYN_LOCKS_HEAD + (
        "class Pool:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._items = []\n"
        "        threading.Thread(target=self._worker).start()\n\n"
        "    def _worker(self):\n"
        "        with self._lock:\n"
        "            self._items = []\n\n"
        "    def add(self, x):\n"
        "        with self._lock:\n"
        "            self._items = [x]\n"
    ))
    assert analysis.run_all(str(tmp_path), ["locks"]) == []


def test_locks_order_inversion_caught(tmp_path):
    _write(tmp_path, "horovod_tpu/pair.py", _SYN_LOCKS_HEAD + (
        "class Pair:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n\n"
        "    def one(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n\n"
        "    def two(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"
    ))
    findings = analysis.run_all(str(tmp_path), ["locks"])
    assert [f.file for f in findings] == ["horovod_tpu/pair.py"]
    assert findings[0].key == "Pair._a->Pair._b->Pair._a"
    assert "inversion" in findings[0].message


def test_locks_interprocedural_inversion_caught(tmp_path):
    """One level of same-class calls: a method holding A calls a method
    that takes B (and vice versa) — the same deadlock, split across
    method bodies."""
    _write(tmp_path, "horovod_tpu/indirect.py", _SYN_LOCKS_HEAD + (
        "class Indirect:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n\n"
        "    def take_b(self):\n"
        "        with self._b:\n"
        "            pass\n\n"
        "    def take_a(self):\n"
        "        with self._a:\n"
        "            pass\n\n"
        "    def one(self):\n"
        "        with self._a:\n"
        "            self.take_b()\n\n"
        "    def two(self):\n"
        "        with self._b:\n"
        "            self.take_a()\n"
    ))
    findings = analysis.run_all(str(tmp_path), ["locks"])
    assert [f.key for f in findings] == ["Indirect._a->Indirect._b->"
                                         "Indirect._a"]


def test_locks_mixed_guarded_unguarded_write_caught(tmp_path):
    _write(tmp_path, "horovod_tpu/counter.py", _SYN_LOCKS_HEAD + (
        "class Counter:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0\n"
        "        threading.Thread(target=self._run).start()\n\n"
        "    def _run(self):\n"
        "        with self._lock:\n"
        "            self.n = 1\n\n"
        "    def reset(self):\n"
        "        self.n = 0\n"
    ))
    findings = analysis.run_all(str(tmp_path), ["locks"])
    assert [(f.key, f.file) for f in findings] == [
        ("Counter.n", "horovod_tpu/counter.py")]
    assert "races every guarded reader" in findings[0].message


def test_locks_thread_target_write_race_caught(tmp_path):
    _write(tmp_path, "horovod_tpu/racer.py", _SYN_LOCKS_HEAD + (
        "class Racer:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.state = 0\n"
        "        threading.Thread(target=self._run).start()\n\n"
        "    def _run(self):\n"
        "        self.state = 1\n\n"
        "    def poke(self):\n"
        "        self.state = 2\n"
    ))
    findings = analysis.run_all(str(tmp_path), ["locks"])
    assert [f.key for f in findings] == ["Racer.state"]
    assert "write/write race" in findings[0].message


def test_locks_unthreaded_class_not_flagged(tmp_path):
    """A class that never spawns threads may write freely — the
    shared-state rules only engage once concurrency exists."""
    _write(tmp_path, "horovod_tpu/single.py", _SYN_LOCKS_HEAD + (
        "class Single:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0\n\n"
        "    def guarded(self):\n"
        "        with self._lock:\n"
        "            self.n = 1\n\n"
        "    def bare(self):\n"
        "        self.n = 2\n"
    ))
    assert analysis.run_all(str(tmp_path), ["locks"]) == []


def test_locks_inline_marker_suppresses(tmp_path):
    _write(tmp_path, "horovod_tpu/counter.py", _SYN_LOCKS_HEAD + (
        "class Counter:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0\n"
        "        threading.Thread(target=self._run).start()\n\n"
        "    def _run(self):\n"
        "        with self._lock:\n"
        "            self.n = 1\n\n"
        "    def reset(self):\n"
        "        # contract-ok: locks -- reset only runs pre-start\n"
        "        self.n = 0\n"
    ))
    assert analysis.run_all(str(tmp_path), ["locks"]) == []


# -- collectives pass on synthetic trees --------------------------------------


def test_collectives_clean_tree_passes(tmp_path):
    # raw lax inside ops/ is the public layer's own right; world-size
    # branches agree on every rank; broadcast_to is a false friend
    _write(tmp_path, "horovod_tpu/ops/spmd.py",
           "import jax\ndef f(x):\n    return jax.lax.psum(x, 'w')\n")
    _write(tmp_path, "horovod_tpu/mod.py", (
        "import jax.numpy as jnp\n"
        "def g(x, size):\n"
        "    if size > 1:\n"
        "        x = allreduce(x)\n"
        "    return jnp.broadcast_to(x, (2,) + x.shape)\n"
    ))
    assert analysis.run_all(str(tmp_path), ["collectives"]) == []


def test_collectives_rank_gated_allreduce_caught(tmp_path):
    _write(tmp_path, "horovod_tpu/mod.py", (
        "def g(x, rank):\n"
        "    if rank == 0:\n"
        "        x = allreduce(x)\n"
        "    return x\n"
    ))
    findings = analysis.run_all(str(tmp_path), ["collectives"])
    assert [(f.key, f.file, f.line) for f in findings] == [
        ("allreduce", "horovod_tpu/mod.py", 3)]
    assert "rendezvous" in findings[0].message


def test_collectives_rank_gated_else_arm_caught(tmp_path):
    """The else of a rank branch is exactly as rank-conditional as the
    body — a collective there diverges the same way."""
    _write(tmp_path, "horovod_tpu/mod.py", (
        "def g(x):\n"
        "    if process_index() == 0:\n"
        "        pass\n"
        "    else:\n"
        "        x = barrier(x)\n"
        "    return x\n"
    ))
    findings = analysis.run_all(str(tmp_path), ["collectives"])
    assert [f.key for f in findings] == ["barrier"]


def test_collectives_raw_lax_outside_ops_caught(tmp_path):
    _write(tmp_path, "horovod_tpu/train2.py", (
        "import jax\n"
        "def step(g):\n"
        "    return jax.lax.psum(g, 'world')\n"
    ))
    findings = analysis.run_all(str(tmp_path), ["collectives"])
    assert [(f.key, f.line) for f in findings] == [("lax.psum", 3)]
    assert "bypasses the public collective API" in findings[0].message


def test_collectives_inline_marker_suppresses(tmp_path):
    _write(tmp_path, "horovod_tpu/train2.py", (
        "import jax\n"
        "def step(g, axes):\n"
        "    # contract-ok: collectives -- tuple-axis psum the public "
        "API cannot spell\n"
        "    return jax.lax.psum(g, axes)\n"
    ))
    assert analysis.run_all(str(tmp_path), ["collectives"]) == []


# -- programs pass: gate + pure check helpers ---------------------------------

_SYN_HLO_LOCAL = (
    '%1 = "stablehlo.all_reduce"(%0) {replica_groups = '
    "dense<[[0, 1]]> : tensor<1x2xi64>} : "
    "(tensor<128xf32>) -> tensor<128xf32>\n"
)
_SYN_HLO_SPANNING = (
    '%1 = "stablehlo.all_reduce"(%0) {replica_groups = '
    "dense<[[0, 4]]> : tensor<1x2xi64>} : "
    "(tensor<128xf32>) -> tensor<128xf32>\n"
)
_TWO_SLICES = [0, 0, 0, 0, 1, 1, 1, 1]


def test_programs_pass_is_gated_off_bare(monkeypatch):
    from horovod_tpu.analysis import programs

    monkeypatch.delenv(programs.ENV_GATE, raising=False)
    assert programs.run(REPO) == []
    assert set(analysis.PASSES) == {
        "c-api", "env", "metrics", "chaos", "trace", "locks",
        "collectives", "programs"}


def test_programs_dcn_exclusion_helper():
    from horovod_tpu.analysis import programs

    assert programs.check_dcn_exclusion(
        "decode:b1", _SYN_HLO_LOCAL, _TWO_SLICES) == []
    findings = programs.check_dcn_exclusion(
        "decode:b1", _SYN_HLO_SPANNING, _TWO_SLICES)
    assert [f.key for f in findings] == ["serve-dcn:decode:b1:all_reduce"]
    assert "spans >1 slice" in findings[0].message


def test_programs_byte_identity_and_collective_budget_helpers():
    from horovod_tpu.analysis import programs

    assert programs.check_byte_identical("guard", _SYN_HLO_LOCAL,
                                         _SYN_HLO_LOCAL) == []
    drift = programs.check_byte_identical(
        "guard", _SYN_HLO_LOCAL, _SYN_HLO_LOCAL + _SYN_HLO_LOCAL)
    assert [f.key for f in drift] == ["byte-identical:guard"]
    assert "+1 collective" in drift[0].message
    assert programs.check_added_collectives(
        "guard", _SYN_HLO_LOCAL, _SYN_HLO_LOCAL) == []
    grew = programs.check_added_collectives(
        "guard", _SYN_HLO_LOCAL, _SYN_HLO_LOCAL + _SYN_HLO_SPANNING)
    assert [f.key for f in grew] == ["added-collectives:guard"]


def test_programs_menu_and_model_helpers():
    from horovod_tpu.analysis import programs

    warmed = {("decode", 1, 8), ("mixed", 1, 8, None)}
    assert programs.check_menu_keys("e", warmed, set(warmed)) == []
    off = programs.check_menu_keys(
        "e", warmed, warmed | {("decode", 16, 8)})
    assert [f.key for f in off] == ["off-menu:e:decode-16-8"]
    assert "never warmed" in off[0].message
    assert programs.check_modeled_measured(
        "h", {"ici": 10, "dcn": 2}, {"ici": 10, "dcn": 2}) == []
    bad = programs.check_modeled_measured(
        "h", {"ici": 10, "dcn": 2}, {"ici": 10, "dcn": 0})
    assert [f.key for f in bad] == ["model-mismatch:h:dcn"]


# -- suppression machinery ----------------------------------------------------


def test_inline_marker_suppresses_with_justification(tmp_path):
    _write(tmp_path, _common.RUNNING_MD, _SYN_RUNNING)
    _write(tmp_path, "horovod_tpu/mod.py", (
        "import os\n"
        "# contract-ok: env -- launcher-set, garbage must crash\n"
        'n = int(os.environ.get("HVD_TPU_KNOWN", "1"))\n'
    ))
    assert analysis.run_all(str(tmp_path), ["env"]) == []


def test_inline_marker_without_justification_is_reported(tmp_path):
    _write(tmp_path, _common.RUNNING_MD, _SYN_RUNNING)
    _write(tmp_path, "horovod_tpu/mod.py", (
        "import os\n"
        "# contract-ok: env\n"
        'n = int(os.environ.get("HVD_TPU_KNOWN", "1"))\n'
    ))
    findings = analysis.run_all(str(tmp_path), ["env"])
    assert [f.check for f in findings] == ["allowlist"]
    assert "no justification" in findings[0].message


def test_allowlist_file_suppresses_and_audits(tmp_path):
    _write(tmp_path, "pyproject.toml", (
        "[tool.horovod_tpu.analysis]\n"
        'allowlist = "allow.txt"\n'
    ))
    _write(tmp_path, "allow.txt", (
        "# comment\n"
        "env:HVD_TPU_SURPRISE -- vendor reads it, row lands next PR\n"
        "env:HVD_TPU_NEVER_MATCHES -- stale entry\n"
        "malformed line without separator\n"
    ))
    _write(tmp_path, _common.RUNNING_MD, _SYN_RUNNING)
    _write(tmp_path, "horovod_tpu/mod.py", (
        "import os\n"
        'k = os.environ.get("HVD_TPU_KNOWN")\n'
        'v = os.environ.get("HVD_TPU_SURPRISE")\n'
    ))
    findings = analysis.run_all(str(tmp_path), ["env"])
    # the real finding is suppressed; the malformed line is reported
    # (stale-entry audit runs only on full runs, not pass subsets)
    assert [f.check for f in findings] == ["allowlist"]
    assert "malformed" in findings[0].message
    full = analysis.run_all(str(tmp_path))
    stale = [f for f in full if "stale allowlist" in f.message]
    assert [f.key for f in stale] == ["env:HVD_TPU_NEVER_MATCHES"]


# -- entrypoint ---------------------------------------------------------------


def test_main_exit_codes_and_rendering(tmp_path, capsys):
    _write(tmp_path, _common.RUNNING_MD, "| Variable | Meaning |\n")
    _write(tmp_path, "horovod_tpu/mod.py",
           'import os\nv = os.environ.get("HVD_TPU_SURPRISE")\n')
    rc = analysis.main(["--root", str(tmp_path), "env"])
    out = capsys.readouterr()
    assert rc == 1
    assert "horovod_tpu/mod.py:2: [env]" in out.out
    _write(tmp_path, _common.RUNNING_MD,
           "| Variable | Meaning |\n"
           "| `HVD_TPU_SURPRISE` | now documented |\n")
    assert analysis.main(["--root", str(tmp_path), "env"]) == 0


def test_list_c_symbols_matches_parser(capsys):
    rc = analysis.main(["--root", REPO, "--list-c-symbols"])
    assert rc == 0
    out = capsys.readouterr().out.split()
    assert out == c_api.declared_symbols(REPO)
