"""ZeRO-style sharded optimizer tests (optim.ZeroDistributedOptimizer /
ZeroSpmdOptimizer — ISSUE 6, ROADMAP item 1).

The load-bearing guarantee is BIT-EQUALITY: the sharded update must equal
the replicated update exactly (fp32), because the inner transformation is
elementwise over the flat partition and reduce-scatter hands each rank
the same reduced values an allreduce would.  The parity tests therefore
use exact-dyadic gradients (every partial sum representable, so the
reduction order cannot round) and assert with assert_array_equal, never
allclose — any drift is a real contract break, not noise.

Reduce-scatter oracle style mirrors test_spmd_collectives: per-rank
tensors over the 8-device virtual mesh, reference computed as
allreduce-then-slice.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import training
from horovod_tpu.optim import (
    ZeroPlan,
    ZeroState,
    sharded_state_bytes_per_rank,
    state_bytes,
    zero_opt_state_specs,
)

W = 8


def _dyadic_params():
    """Parameter pytree with exact-dyadic fp32 values whose total size
    (3*2 + 7 = 13) does NOT divide the 8-rank world — the
    padding/unflatten bookkeeping is always live."""
    rng = np.random.RandomState(0)
    return {
        "w": jnp.asarray(rng.randint(-4, 5, (3, 2)).astype(np.float32) / 8),
        "b": jnp.zeros((7,), jnp.float32),
    }


def _dyadic_batch(n):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randint(-8, 9, (n, 3)).astype(np.float32) / 16)
    y = jnp.asarray(rng.randint(-8, 9, (n, 2)).astype(np.float32) / 16)
    return x, y


def _loss(p, xs, ys):
    pred = xs @ p["w"] + p["b"][:2]
    return jnp.mean((pred - ys) ** 2)


def _train(opt, params, x, y, steps):
    """Run `steps` updates under shard_map with the batch sharded over
    the world axis; params stay replicated (ZeRO allgathers its updates,
    the replicated wrapper allreduces its grads)."""

    @functools.partial(
        jax.shard_map, mesh=hvd.world_mesh(),
        in_specs=(P(), P("hvd"), P("hvd")), out_specs=P(),
        check_vma=False,
    )
    def run(p, xs, ys):
        st = opt.init(p)
        for _ in range(steps):
            g = jax.grad(_loss)(p, xs, ys)
            u, st = opt.update(g, st, p)
            p = optax.apply_updates(p, u)
        return p

    return run(params, x, y)


def test_zero_spmd_parity_bit_equal_fp32():
    """ROADMAP item 1 acceptance: sharded-vs-replicated parameter
    updates bit-equal per step (3 steps of adamw, non-divisible flat
    size)."""
    params = _dyadic_params()
    x, y = _dyadic_batch(W * 4)
    inner = optax.adamw(1e-2)
    pz = _train(hvd.ZeroSpmdOptimizer(inner), params, x, y, steps=3)
    pr = _train(hvd.DistributedOptimizer(inner), params, x, y, steps=3)
    for k in params:
        np.testing.assert_array_equal(np.asarray(pz[k]), np.asarray(pr[k]))


def test_zero_spmd_parity_with_gradient_accumulation():
    """The ISSUE-named composition: backward_passes_per_step accumulates
    the FULL local gradient (optax.MultiSteps) and the sharded exchange
    runs on the k-th microbatch — parity must hold bit-exactly."""
    params = _dyadic_params()
    x, y = _dyadic_batch(W * 4)
    inner = optax.adamw(1e-2)
    zopt = optax.MultiSteps(
        hvd.ZeroSpmdOptimizer(inner), every_k_schedule=2
    )
    ropt = hvd.DistributedOptimizer(inner, backward_passes_per_step=2)
    pz = _train(zopt, params, x, y, steps=4)  # 4 microbatches, 2 updates
    pr = _train(ropt, params, x, y, steps=4)
    for k in params:
        np.testing.assert_array_equal(np.asarray(pz[k]), np.asarray(pr[k]))


def test_zero_spmd_sum_op_parity():
    params = _dyadic_params()
    x, y = _dyadic_batch(W * 4)
    inner = optax.sgd(0.25)
    pz = _train(hvd.ZeroSpmdOptimizer(inner, op=hvd.Sum),
                params, x, y, steps=2)
    pr = _train(hvd.DistributedOptimizer(inner, op=hvd.Sum),
                params, x, y, steps=2)
    for k in params:
        np.testing.assert_array_equal(np.asarray(pz[k]), np.asarray(pr[k]))


def test_zero_eager_single_process_equals_inner():
    """np=1 eager degenerate (reference np=1 semantics): the flat
    partition must be arithmetically invisible — bit-equal to the plain
    inner optimizer on the structured tree."""
    params = _dyadic_params()
    x, y = _dyadic_batch(8)
    inner = optax.adamw(1e-2)
    zopt = hvd.ZeroDistributedOptimizer(inner)
    grads = jax.grad(_loss)(params, x, y)
    zs = zopt.init(params)
    uz, _ = zopt.update(grads, zs, params)
    ui, _ = inner.update(grads, inner.init(params), params)
    for k in params:
        np.testing.assert_array_equal(
            np.asarray(optax.apply_updates(params, uz)[k]),
            np.asarray(optax.apply_updates(params, ui)[k]),
        )


def test_zero_eager_requires_params():
    zopt = hvd.ZeroDistributedOptimizer(optax.adam(1e-3))
    with pytest.raises(ValueError, match="params"):
        zopt.init(None)


def test_zero_rejects_non_sum_ops():
    with pytest.raises(ValueError):
        hvd.ZeroDistributedOptimizer(optax.adam(1e-3), op=hvd.Min)
    with pytest.raises(ValueError):
        hvd.ZeroSpmdOptimizer(optax.adam(1e-3), op=hvd.Adasum)


def test_zero_eager_min_total_bytes_fallback_matches(monkeypatch):
    """Below the sharding threshold the wrapper keeps replicated state +
    one allreduce; the numbers must be identical either way."""
    params = _dyadic_params()
    x, y = _dyadic_batch(8)
    grads = jax.grad(_loss)(params, x, y)
    inner = optax.adam(1e-2)
    outs = []
    for min_bytes in (0, 10 ** 9):
        zopt = hvd.ZeroDistributedOptimizer(
            inner, min_total_bytes=min_bytes)
        u, _ = zopt.update(grads, zopt.init(params), params)
        outs.append(u)
    for k in params:
        np.testing.assert_array_equal(
            np.asarray(outs[0][k]), np.asarray(outs[1][k]))
    # the env default parses through env_int
    monkeypatch.setenv("HVD_TPU_ZERO_MIN_BYTES", "4096")
    zopt = hvd.ZeroDistributedOptimizer(inner)
    u, _ = zopt.update(grads, zopt.init(params), params)
    for k in params:
        np.testing.assert_array_equal(
            np.asarray(u[k]), np.asarray(outs[0][k]))


def test_zero_eager_with_gradient_accumulation_single_process():
    """backward_passes_per_step composes on the eager wrapper exactly as
    on DistributedOptimizer (MultiSteps traces the inner update through
    lax.cond — the collective path must stay traceable at np=1)."""
    params = _dyadic_params()
    x, y = _dyadic_batch(8)
    grads = jax.grad(_loss)(params, x, y)
    zopt = hvd.ZeroDistributedOptimizer(
        optax.sgd(1.0), backward_passes_per_step=2)
    st = zopt.init(params)
    u1, st = zopt.update(grads, st, params)
    for leaf in jax.tree_util.tree_leaves(u1):
        np.testing.assert_array_equal(np.asarray(leaf),
                                      np.zeros_like(np.asarray(leaf)))
    u2, st = zopt.update(grads, st, params)
    np.testing.assert_array_equal(
        np.asarray(u2["w"]), np.asarray(-grads["w"]))


# -- ZeroPlan bookkeeping ----------------------------------------------------


def test_zero_plan_roundtrip_mixed_dtypes_non_divisible():
    leaves = [
        jnp.arange(5, dtype=jnp.float32),
        jnp.ones((3, 3), jnp.bfloat16),
        jnp.arange(7, dtype=jnp.float32).reshape(7, 1),
        jnp.zeros((2,), jnp.bfloat16),
    ]
    plan = ZeroPlan(leaves, W)
    assert len(plan.buckets) == 2  # one per dtype
    for padded in plan.padded_sizes:
        assert padded % W == 0
    bufs = plan.flatten(leaves)
    for buf, padded in zip(bufs, plan.padded_sizes):
        assert buf.shape == (padded,)
    out = plan.unflatten(bufs)
    for a, b in zip(leaves, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.dtype == b.dtype


def test_zero_plan_is_deterministic():
    leaves = [jnp.zeros((11,)), jnp.zeros((4, 2), jnp.bfloat16)]
    p1, p2 = ZeroPlan(leaves, W), ZeroPlan(leaves, W)
    assert p1.buckets == p2.buckets
    assert p1.shard_sizes == p2.shard_sizes
    assert p1.total_bytes == 11 * 4 + 8 * 2
    assert p1.shard_bytes * W == p1.padded_bytes


def test_zero_opt_state_specs_layout():
    """Adam m/v over the shard buffers are axis-sharded; the step count
    stays replicated."""
    params = _dyadic_params()
    specs = zero_opt_state_specs(optax.adam(1e-3), params, W)
    assert isinstance(specs, ZeroState)
    adam_state = specs.inner[0]
    assert adam_state.count == P()
    for leaf in jax.tree_util.tree_leaves(adam_state.mu):
        assert leaf == P("hvd")
    for leaf in jax.tree_util.tree_leaves(adam_state.nu):
        assert leaf == P("hvd")


def test_sharded_state_bytes_per_rank_accounting():
    params = _dyadic_params()
    inner = optax.adam(1e-3)
    specs = zero_opt_state_specs(inner, params, W)
    plan = ZeroPlan(jax.tree_util.tree_leaves(params), W)
    # global sharded state: count () + mu/nu over (W*shard,) buffers
    global_state = ZeroState(
        inner=inner.init([jnp.zeros((plan.padded_sizes[0],))]))
    per_rank = sharded_state_bytes_per_rank(global_state, specs, W)
    expected = 4 + 2 * plan.shard_bytes  # int32 count + mu + nu
    assert per_rank == expected


# -- reduce-scatter oracle (allreduce-then-slice reference) ------------------


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("size", [64, 17, 5])
def test_reducescatter_oracle_spmd(dtype, size):
    """SPMD reduce-scatter over the padded ZeRO layout == allreduce then
    slice, bit-exact, for divisible and non-divisible sizes in fp32 and
    bf16 (values chosen so every partial sum is representable — the
    reduction order cannot round)."""
    pad = (-size) % W
    s = (size + pad) // W

    def per_rank(r):
        base = jnp.arange(size, dtype=jnp.float32) % 4
        return (base + r.astype(jnp.float32) * 0.5).astype(dtype)

    def rs(r):
        t = per_rank(r)
        buf = (jnp.concatenate([t, jnp.zeros((pad,), t.dtype)])
               if pad else t)
        return hvd.spmd.reducescatter(buf, op=hvd.Sum)

    out = np.asarray(jax.device_get(hvd.run_per_rank(rs)))  # (W, s)

    # reference: allreduce (sum over ranks) then slice rank chunks
    vals = np.stack([
        np.asarray((np.arange(size) % 4 + r * 0.5), np.float64)
        for r in range(W)
    ])
    full = np.zeros(size + pad)
    full[:size] = vals.sum(axis=0)
    ref = full.astype(np.asarray(jnp.zeros(0, dtype)).dtype)
    for r in range(W):
        np.testing.assert_array_equal(out[r], ref[r * s:(r + 1) * s])


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("size", [16, 7])
def test_reducescatter_oracle_eager(dtype, size):
    """Eager/native-path oracle: reducescatter == allreduce-then-slice
    through the public API (native controller when built — bf16 rides
    the wire enum and the multi-leaf pytree exercises per-leaf naming).
    Written against member_info so it holds at any world size; at np=1
    both sides degenerate identically (reference np=1 semantics)."""
    eng = hvd.common.basics._require_init().engine
    n, me = eng.member_info()
    pad = (-size) % n
    x = (jnp.arange(size, dtype=jnp.float32) % 4 / 2).astype(dtype)
    buf = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)]) if pad else x
    tree = {"a": buf, "b": buf * 2}
    rs = hvd.reducescatter(tree, op=hvd.Sum, name="rs_oracle")
    ar = hvd.allreduce(tree, op=hvd.Sum, name="rs_oracle_ref")
    s = buf.shape[0] // n
    for k in tree:
        np.testing.assert_array_equal(
            np.asarray(rs[k]),
            np.asarray(ar[k][me * s:(me + 1) * s]),
        )


def test_engine_reducescatter_multi_fallbacks():
    """The one-compiled-program multi path must decline (None) exactly
    where the per-tensor path's error/bool handling is authoritative."""
    eng = hvd.common.basics._require_init().engine
    xs = [jnp.ones((8,)), jnp.ones((16,))]
    # Sum/Average accepted: identity at one contributor
    out = eng.reducescatter_multi(xs, hvd.Sum)
    assert out is not None
    for a, b in zip(out, xs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert eng.reducescatter_multi(xs, hvd.Min) is None
    assert eng.reducescatter_multi(
        [jnp.array([True, False])], hvd.Sum) is None
    assert eng.reducescatter_multi([jnp.asarray(1.0)], hvd.Sum) is None


def test_eager_reducescatter_pytree_multi_path():
    """A multi-leaf pytree rides the reducescatter_multi branch of
    collective_ops and still returns the per-leaf results."""
    tree = [jnp.arange(8.0), jnp.arange(16.0) * 2]
    out = hvd.reducescatter(tree, op=hvd.Sum)
    for a, b in zip(out, tree):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- end-to-end trainer ------------------------------------------------------


def test_zero_train_setup_descends_and_shards_state():
    from horovod_tpu.models.transformer import Transformer, gpt_tiny

    cfg = gpt_tiny(dtype=jnp.float32)
    model = Transformer(cfg)
    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, 256, (8, 32)))
    tgt = jnp.asarray(rng.randint(0, 256, (8, 32)))
    inner = optax.adamw(1e-3)
    state, step, ospecs = training.zero_train_setup(
        model, inner, jax.random.PRNGKey(0), tok[:1])
    losses = []
    for _ in range(4):
        state, loss = step(state, tok, tgt)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]

    # the acceptance column: per-rank optimizer state ~ 1/world of the
    # replicated baseline (exact up to padding + the replicated count)
    zb = sharded_state_bytes_per_rank(state.opt_state, ospecs, W)
    rstate = training.create_train_state(
        model, inner, jax.random.PRNGKey(0), tok[:1])
    rb = state_bytes(rstate.opt_state)
    assert zb < rb / (W - 1)
    assert zb > rb / (W + 1)
