"""Module-level LightningModule for the lightning-estimator contract
tests (pickled into worker subprocesses, so it must be importable there
— workers get tests/_fake_modules on PYTHONPATH from the test).

Kept separate from estimator_models.py: importing this module requires
`pytorch_lightning` (the fake) on sys.path.
"""

import pytorch_lightning as pl
import torch


class LitRegression(pl.LightningModule):
    """y = w·x regression; loss and optimizer live inside the module,
    per the lightning contract."""

    def __init__(self):
        super().__init__()
        self.fc = torch.nn.Linear(4, 1)
        self.epoch_end_calls = 0

    def forward(self, x):
        return self.fc(x).squeeze(-1)

    def training_step(self, batch, batch_idx):
        x, y = batch
        loss = torch.nn.functional.mse_loss(self(x), y)
        self.log("train_loss", loss)
        return loss

    def validation_step(self, batch, batch_idx):
        x, y = batch
        return {"val_loss": torch.nn.functional.mse_loss(self(x), y)}

    def on_train_epoch_end(self):
        self.epoch_end_calls += 1

    def configure_optimizers(self):
        return torch.optim.SGD(self.parameters(), lr=0.05)


class LitDictOptimizer(LitRegression):
    """configure_optimizers returning the dict shape."""

    def configure_optimizers(self):
        return {"optimizer": torch.optim.SGD(self.parameters(), lr=0.05)}
