"""Tests for the horovod_tpu.metrics telemetry subsystem.

Covers the ISSUE-1 acceptance surface: registry concurrency (many
threads bumping labeled counters), golden Prometheus text rendering,
the /metrics + /healthz endpoint round-trip on an ephemeral port (the
endpoint binds NOTHING unless a test opts in — tier-1 runs with
HVD_TPU_METRICS_PORT unset), allgather-backed cluster aggregation on
the CPU backend, and the hot-path instrumentation populating the
per-collective latency histograms from a training-shaped workload.
"""

import json
import threading
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import metrics
from horovod_tpu.metrics import aggregate, exposition
from horovod_tpu.metrics.registry import (
    Counter, Gauge, Histogram, MetricsRegistry,
)


# -- registry ----------------------------------------------------------------


def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = metrics.counter("t_ops", "ops", ["op"], registry=reg)
    c.labels(op="allreduce").inc()
    c.labels("allreduce").inc(2)
    assert c.labels("allreduce").get() == 3
    with pytest.raises(ValueError):
        c.labels("allreduce").inc(-1)  # counters only go up

    g = metrics.gauge("t_g", "g", registry=reg)
    g.set(5)
    g.dec(1.5)
    assert g.get() == 3.5
    g.set_function(lambda: 42)
    assert g.get() == 42

    h = metrics.histogram("t_h", "h", buckets=(1, 10), registry=reg)
    for v in (0.5, 5, 500):
        h.observe(v)
    state = h.get()
    assert state["count"] == 3
    assert state["sum"] == 505.5
    assert state["buckets"] == [1, 1, 1]  # <=1, <=10, +Inf


def test_factories_are_idempotent_and_type_checked():
    reg = MetricsRegistry()
    a = metrics.counter("t_same", "d", ["x"], registry=reg)
    b = metrics.counter("t_same", "d", ["x"], registry=reg)
    assert a is b
    with pytest.raises(ValueError):
        metrics.gauge("t_same", "d", registry=reg)  # kind mismatch
    with pytest.raises(ValueError):
        metrics.counter("t_same", "d", ["y"], registry=reg)  # labels


def test_invalid_names_rejected():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        metrics.counter("bad name", "d", registry=reg)
    with pytest.raises(ValueError):
        metrics.counter("1leading", "d", registry=reg)
    with pytest.raises(ValueError):
        metrics.histogram("t_le", "d", ["le"], registry=reg)  # reserved


def test_registry_concurrency_exact_totals():
    """Many threads bumping labeled counters and histograms must lose no
    increments (per-child locks; the registry lock only guards child
    creation)."""
    reg = MetricsRegistry()
    c = metrics.counter("t_conc", "d", ["worker"], registry=reg)
    h = metrics.histogram("t_conc_h", "d", buckets=(0.5,), registry=reg)
    n_threads, n_iter = 16, 2000
    barrier = threading.Barrier(n_threads)

    def bump(i):
        child = c.labels(str(i % 4))  # contended: 4 children, 16 threads
        barrier.wait()
        for _ in range(n_iter):
            child.inc()
            h.observe(0.25)

    threads = [
        threading.Thread(target=bump, args=(i,)) for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = sum(child.get() for _, child in
                ((k, c.labels(*k)) for k, _ in c.samples()))
    assert total == n_threads * n_iter
    assert h.get()["count"] == n_threads * n_iter


# -- exposition --------------------------------------------------------------


GOLDEN = (
    '# HELP t_gauge a gauge\n'
    '# TYPE t_gauge gauge\n'
    't_gauge 2.5\n'
    '# HELP t_hist a histogram\n'
    '# TYPE t_hist histogram\n'
    't_hist_bucket{op="ar",le="0.001"} 0\n'
    't_hist_bucket{op="ar",le="0.1"} 1\n'
    't_hist_bucket{op="ar",le="+Inf"} 2\n'
    't_hist_sum{op="ar"} 5.005\n'
    't_hist_count{op="ar"} 2\n'
    '# HELP t_ops_total ops "quoted" and\\nnewlined\n'
    '# TYPE t_ops_total counter\n'
    't_ops_total{op="all\\"reduce"} 3\n'
)


def test_prometheus_text_rendering_golden():
    reg = MetricsRegistry()
    c = metrics.counter("t_ops_total", 'ops "quoted" and\nnewlined',
                        ["op"], registry=reg)
    c.labels('all"reduce').inc(3)
    g = metrics.gauge("t_gauge", "a gauge", registry=reg)
    g.set(2.5)
    h = metrics.histogram("t_hist", "a histogram", ["op"],
                          buckets=(0.001, 0.1), registry=reg)
    h.labels("ar").observe(0.005)
    h.labels("ar").observe(5.0)
    assert exposition.render(reg) == GOLDEN


def test_render_escapes_and_infinities():
    reg = MetricsRegistry()
    g = metrics.gauge("t_inf", "d", ["k"], registry=reg)
    g.labels('a\\b"c\nd').set(float("inf"))
    text = exposition.render(reg)
    assert r'{k="a\\b\"c\nd"}' in text
    assert "+Inf" in text


def test_registry_poll_runs_at_collection():
    reg = MetricsRegistry()
    g = metrics.gauge("t_polled", "d", registry=reg)
    calls = []
    reg.register_poll(lambda: (calls.append(1), g.set(len(calls)))[0])
    exposition.render(reg)
    exposition.render(reg)
    assert g.get() == len(calls) == 2
    reg.unregister_poll(reg._polls[0])
    assert reg._polls == []


def test_health_sources_aggregate():
    exposition.register_health_source("t_ok", lambda: (True, {"a": 1}))
    exposition.register_health_source(
        "t_bad", lambda: (False, {"why": "testing"}))
    try:
        healthy, details = exposition.health_snapshot()
        assert not healthy
        assert details["t_ok"]["healthy"] and details["t_ok"]["a"] == 1
        assert not details["t_bad"]["healthy"]
    finally:
        exposition.unregister_health_source("t_ok")
        exposition.unregister_health_source("t_bad")


def test_http_endpoint_roundtrip():
    """/metrics + /healthz on an ephemeral port (explicit opt-in: tier-1
    leaves HVD_TPU_METRICS_PORT unset so no port is ever bound by the
    suite outside this test)."""
    reg = MetricsRegistry()
    metrics.counter("t_endpoint_hits_total", "d", registry=reg).inc(7)
    srv = exposition.MetricsHTTPServer(0, registry=reg)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        body = urllib.request.urlopen(f"{base}/metrics", timeout=10)
        assert body.status == 200
        assert "version=0.0.4" in body.headers["Content-Type"]
        text = body.read().decode()
        assert "t_endpoint_hits_total 7" in text

        h = urllib.request.urlopen(f"{base}/healthz", timeout=10)
        payload = json.loads(h.read().decode())
        assert h.status == 200
        assert payload["status"] == "ok"

        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/nope", timeout=10)
        assert exc.value.code == 404
    finally:
        srv.close()


def test_healthz_returns_503_when_unhealthy():
    exposition.register_health_source(
        "t_down", lambda: (False, {"reason": "synthetic"}))
    srv = exposition.MetricsHTTPServer(0, registry=MetricsRegistry())
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=10)
        assert exc.value.code == 503
        payload = json.loads(exc.value.read().decode())
        assert payload["status"] == "unhealthy"
        assert payload["sources"]["t_down"]["reason"] == "synthetic"
    finally:
        srv.close()
        exposition.unregister_health_source("t_down")


def test_maybe_start_from_env_disabled_by_default(monkeypatch):
    monkeypatch.delenv(exposition.ENV_METRICS_PORT, raising=False)
    assert exposition.maybe_start_from_env() is None
    monkeypatch.setenv(exposition.ENV_METRICS_PORT, "-1")
    assert exposition.maybe_start_from_env() is None
    monkeypatch.setenv(exposition.ENV_METRICS_PORT, "junk")
    assert exposition.maybe_start_from_env() is None


# -- instrumentation + aggregation (needs the initialized framework) ---------


def test_training_collectives_populate_latency_histograms():
    """A training-shaped burst on the CPU backend must land in the
    per-collective latency histograms and submission counters (the
    acceptance criterion's 'measurement substrate')."""
    lat = metrics.REGISTRY.get("hvd_tpu_collective_latency_seconds")
    subs = metrics.REGISTRY.get("hvd_tpu_collectives_total")
    before = dict(lat.samples()) if lat else {}

    grads = {"w": jnp.ones((8, 4)), "b": jnp.ones((4,))}
    hvd.allreduce(grads, name="metrics_test_grads")
    hvd.allgather(jnp.ones((2, 3)), name="metrics_test_gather")

    lat = metrics.REGISTRY.get("hvd_tpu_collective_latency_seconds")
    assert lat is not None
    after = dict(lat.samples())
    ar_count = after[("allreduce",)]["count"] - (
        before.get(("allreduce",), {"count": 0})["count"]
    )
    assert ar_count >= 1
    assert after[("allgather",)]["count"] >= 1
    text = metrics.render()
    assert 'hvd_tpu_collective_latency_seconds_bucket{op="allreduce"' \
        in text
    assert subs is None or dict(subs.samples())  # counters present too


def test_enqueue_depth_and_native_stats_exposed():
    """With the native controller loaded (single-process loopback) the
    pull gauges must refresh at render time and /healthz must carry the
    stall-inspector + loop-liveness details."""
    st = hvd._basics._require_init()
    if not getattr(st.controller, "is_native", False):
        pytest.skip("python fallback controller (no native lib)")
    text = metrics.render()
    assert "hvd_tpu_native_pending_collectives" in text
    assert "hvd_tpu_enqueue_depth 0" in text
    healthy, details = exposition.health_snapshot()
    assert healthy
    nc = details["native_controller"]
    assert nc["loop_dead"] is False
    assert nc["pending_collectives"] == 0


def test_cluster_snapshot_allgather_roundtrip():
    """Rank-0-style job-wide view: every rank's registry snapshot rides
    the framework's own allgather and merges (counters sum, gauges keep
    a per-rank label)."""
    metrics.counter("t_agg_steps_total", "d").inc(5)
    metrics.gauge("t_agg_loss", "d").set(0.25)
    merged = metrics.cluster_snapshot(name="metrics_test_snapshot")
    n = merged["ranks"]
    assert n >= 1
    m = merged["metrics"]["t_agg_steps_total"]
    # every rank contributed 5 (single-process CPU run: n == 1)
    [(labels, total)] = m["series"]
    assert total == 5 * n
    g = merged["metrics"]["t_agg_loss"]
    assert g["labelnames"][0] == "rank"
    assert len(g["series"]) == n
    assert merged["per_rank"][0]["version"] == aggregate.SNAPSHOT_VERSION


def test_merge_snapshots_histogram_and_mismatch():
    reg = MetricsRegistry()
    h = metrics.histogram("t_m_h", "d", buckets=(1, 2), registry=reg)
    h.observe(0.5)
    s1 = aggregate.snapshot(reg)
    s2 = json.loads(json.dumps(s1))  # wire round-trip
    merged = aggregate.merge_snapshots([s1, s2])
    [(_, state)] = merged["metrics"]["t_m_h"]["series"]
    assert state["count"] == 2 and state["buckets"][0] == 2
    # mismatched bucket layouts keep sum/count only
    s3 = json.loads(json.dumps(s1))
    for _, st3 in s3["metrics"]["t_m_h"]["series"]:
        st3["buckets"] = [1]
    merged = aggregate.merge_snapshots([s1, s3])
    [(_, state)] = merged["metrics"]["t_m_h"]["series"]
    assert state["buckets"] == [] and state["count"] == 2


def test_step_time_instrumentation_via_train_loop():
    loop = hvd.callbacks.TrainLoop.__new__(hvd.callbacks.TrainLoop)
    loop.callbacks = []
    hist = metrics.REGISTRY.get("hvd_tpu_step_duration_seconds")
    before = dict(hist.samples()).get(("jax",), {"count": 0})["count"] \
        if hist else 0
    loop.batch = 0
    loop.on_batch_begin(0)
    loop.on_batch_end(0, {"loss": 1.0})
    hist = metrics.REGISTRY.get("hvd_tpu_step_duration_seconds")
    after = dict(hist.samples())[("jax",)]["count"]
    assert after == before + 1
