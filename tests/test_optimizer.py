"""DistributedOptimizer tests: SPMD gradient averaging end-to-end.

Reference analog: test/parallel/test_torch.py's DistributedOptimizer cases
(gradient averaging across ranks, local aggregation) — exercised over the
virtual mesh: a data-parallel train step under shard_map must produce
identical params on every rank and match the single-worker full-batch step.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd

N = 8


def _loss(params, x, y):
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def _data():
    rng = np.random.RandomState(0)
    x = rng.randn(N * 4, 3).astype(np.float32)
    y = rng.randn(N * 4, 1).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


def _init_params():
    return {"w": jnp.zeros((3, 1)), "b": jnp.zeros((1,))}


def test_spmd_distributed_step_matches_global_batch():
    mesh = hvd.world_mesh()
    opt = hvd.DistributedOptimizer(optax.sgd(0.1))
    params = _init_params()
    x, y = _data()
    opt_state = opt.init(params)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P(), P("hvd"), P("hvd")),
        out_specs=P(),
        check_vma=False,
    )
    def step(params, opt_state, xs, ys):
        grads = jax.grad(_loss)(params, xs, ys)
        updates, new_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_state

    new_params, _ = step(params, opt_state, x, y)

    # single-worker reference on the full batch
    ref_grads = jax.grad(_loss)(params, x, y)
    ref_opt = optax.sgd(0.1)
    updates, _ = ref_opt.update(ref_grads, ref_opt.init(params), params)
    ref_params = optax.apply_updates(params, updates)

    np.testing.assert_allclose(
        np.asarray(new_params["w"]), np.asarray(ref_params["w"]), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(new_params["b"]), np.asarray(ref_params["b"]), rtol=1e-5
    )


def test_eager_distributed_optimizer_single_process():
    opt = hvd.DistributedOptimizer(optax.sgd(0.1))
    params = _init_params()
    x, y = _data()
    grads = jax.grad(_loss)(params, x, y)
    updates, _ = opt.update(grads, opt.init(params), params)
    new_params = optax.apply_updates(params, updates)
    # single process: allreduce(avg) is identity -> plain sgd
    ref = optax.apply_updates(
        params, optax.sgd(0.1).update(grads, optax.sgd(0.1).init(params))[0]
    )
    np.testing.assert_allclose(
        np.asarray(new_params["w"]), np.asarray(ref["w"]), rtol=1e-6
    )


def test_allreduce_gradients_dispatches_by_context():
    grads = {"g": jnp.ones((4,))}
    # eager: identity (single process)
    out = hvd.allreduce_gradients(grads)
    np.testing.assert_allclose(np.asarray(out["g"]), np.ones(4))
    # spmd: true mean across ranks
    res = hvd.run_per_rank(
        lambda r: hvd.allreduce_gradients(
            {"g": jnp.full((2,), r.astype(jnp.float32))}
        )["g"]
    )
    np.testing.assert_allclose(np.asarray(res[0]), np.full(2, 3.5))


def test_gradient_accumulation():
    opt = hvd.DistributedOptimizer(
        optax.sgd(1.0), backward_passes_per_step=2
    )
    params = {"w": jnp.zeros((2,))}
    state = opt.init(params)
    g1 = {"w": jnp.ones((2,))}
    g2 = {"w": jnp.full((2,), 3.0)}
    up1, state = opt.update(g1, state, params)
    # first microbatch: no update applied yet
    np.testing.assert_allclose(np.asarray(up1["w"]), np.zeros(2))
    up2, state = opt.update(g2, state, params)
    # second: mean grad (1+3)/2 = 2 with lr 1.0 -> -2
    np.testing.assert_allclose(np.asarray(up2["w"]), -2.0 * np.ones(2))
