"""Contract tests for the pyspark / ray launch paths with faked modules.

Reference analog: the reference CI runs horovod.spark/ray against real
installations; this image ships neither (VERDICT r3 item 5), so these
tests inject minimal fakes (tests/_fake_modules) that pin the exact API
calls `horovod_tpu.spark.run` and `RayExecutor.run` make and the env
each worker receives.  The real function bodies execute — only the
framework init (which would rendezvous) and the external cluster API
are faked.
"""

import os
import sys

import pytest

FAKES = os.path.join(os.path.dirname(__file__), "_fake_modules")


@pytest.fixture
def fake_cluster_modules(monkeypatch):
    """Put the fake pyspark/ray first on sys.path, purge real/previous
    imports, and restore os.environ afterwards (the task bodies under
    test mutate it)."""
    saved_env = dict(os.environ)
    monkeypatch.syspath_prepend(FAKES)
    for name in list(sys.modules):
        if name == "pyspark" or name.startswith("pyspark.") \
                or name == "ray" or name.startswith("ray."):
            monkeypatch.delitem(sys.modules, name)
    yield
    for name in list(sys.modules):
        if name == "pyspark" or name.startswith("pyspark.") \
                or name == "ray" or name.startswith("ray."):
            del sys.modules[name]
    os.environ.clear()
    os.environ.update(saved_env)


@pytest.fixture
def recorded_init(monkeypatch):
    """Replace horovod_tpu.init with a recorder that snapshots the
    coordination env the worker body set up before calling it."""
    import horovod_tpu

    snapshots = []

    def fake_init(*args, **kwargs):
        snapshots.append({
            k: v for k, v in os.environ.items()
            if k.startswith("HVD_TPU_")
        })

    monkeypatch.setattr(horovod_tpu, "init", fake_init)
    return snapshots


def _worker_fn(tag):
    # runs inside the (fake) cluster task, after hvd.init()
    return (tag, os.environ["HVD_TPU_PROCESS_ID"])


def test_spark_run_contract(fake_cluster_modules, recorded_init):
    """spark.run executes fn in num_proc barrier tasks: parallelize →
    barrier → mapPartitions → collect, BarrierTaskContext.barrier after
    fn, coordination env per rank (SURVEY.md §2.4 horovod.spark.run)."""
    import pyspark

    pyspark._reset()
    import horovod_tpu.spark as spark

    results = spark.run(_worker_fn, args=("job",), num_proc=3)

    # per-rank results in rank order
    assert results == [("job", "0"), ("job", "1"), ("job", "2")]
    # every rank initialized with the same coordinator, its own rank id
    assert len(recorded_init) == 3
    coords = {s["HVD_TPU_COORDINATOR"] for s in recorded_init}
    assert len(coords) == 1 and ":" in coords.pop()
    for rank, snap in enumerate(recorded_init):
        assert snap["HVD_TPU_PROCESS_ID"] == str(rank)
        assert snap["HVD_TPU_NUM_PROCESSES"] == "3"
    # the pyspark call sequence: session → parallelize(n, n) → barrier
    # rdd → mapPartitions → collect → per-task barrier()
    events = [e for e, _ in pyspark.CALLS]
    assert events[:5] == [
        "getOrCreate", "parallelize", "barrier_rdd", "mapPartitions",
        "collect",
    ]
    assert pyspark.CALLS[1][1] == (3, 3)  # n items over n partitions
    assert [p for e, p in pyspark.CALLS if e == "barrier"] == [0, 1, 2]


def test_spark_run_without_pyspark_raises():
    """Without pyspark the contract is an ImportError pointing at the
    alternatives — not a silent local fallback."""
    import horovod_tpu.spark as spark

    if any(n == "pyspark" for n in sys.modules):
        pytest.skip("real pyspark present")
    with pytest.raises(ImportError, match="RayExecutor|tpurun"):
        spark.run(_worker_fn, num_proc=2)


def test_ray_executor_contract(fake_cluster_modules, recorded_init):
    """RayExecutor on the (fake) ray backend: ray.init at start(), one
    remote task per worker, results via ray.get in rank order, each
    worker env-wired to the same coordinator (reference:
    horovod/ray/runner.py RayExecutor.run → run_remote + get)."""
    import ray

    ray._reset()
    # import AFTER the fake is on sys.path so _ray_available() sees it
    import importlib

    import horovod_tpu.ray as hvd_ray

    importlib.reload(hvd_ray)
    ex = hvd_ray.RayExecutor(num_workers=4)
    assert ex._backend == "ray"
    with pytest.raises(RuntimeError, match="start"):
        ex.run(_worker_fn)
    ex.start()
    assert ray.is_initialized()
    results = ex.run(_worker_fn, args=["rayjob"])
    ex.shutdown()

    assert results == [("rayjob", str(r)) for r in range(4)]
    assert len(recorded_init) == 4
    for rank, snap in enumerate(recorded_init):
        assert snap["HVD_TPU_PROCESS_ID"] == str(rank)
        assert snap["HVD_TPU_NUM_PROCESSES"] == "4"
        assert ":" in snap["HVD_TPU_COORDINATOR"]
    events = [e for e, _ in ray.CALLS]
    assert events.count("init") == 1
    assert events.count("task_submit") == 4
    # all four tasks submitted before any get (fan-out, then gather)
    assert events.index("get") > max(
        i for i, e in enumerate(events) if e == "task_submit"
    )
    # ranks submitted in order
    assert [a[0] for e, a in ray.CALLS if e == "task_submit"] == [
        0, 1, 2, 3,
    ]
