"""Environment capability guards for integration tests.

jax < 0.5 cannot execute MULTI-PROCESS XLA computations on the CPU
backend ("Multiprocess computations aren't implemented on the CPU
backend"), and every guarded test pins ``JAX_PLATFORMS=cpu`` in its
worker environment — so with jax < 0.5 these tests fail on ANY image
(TPU hosts included), slowly, through elastic restart loops and
rendezvous timeouts.  Guarding on the jax version alone is therefore
exact; on jax >= 0.5 the guard is inert.
``HVD_TPU_TEST_FORCE_MULTIPROC=1`` forces the tests to run anyway
(e.g. to re-probe a new jax).

Note the boundary: multi-process *control-plane* tests (rendezvous,
native negotiation/auth frames, heartbeats, exec-restart recovery,
chaos soak) do NOT need this guard — only cross-process data-plane
collectives are unsupported.
"""

import os

import pytest


def cpu_multiprocess_collectives_supported() -> bool:
    if os.environ.get("HVD_TPU_TEST_FORCE_MULTIPROC") == "1":
        return True
    import jax

    try:
        major, minor = (int(x) for x in jax.__version__.split(".")[:2])
    except ValueError:
        return True  # unparseable dev version: let the test decide
    return (major, minor) >= (0, 5)


requires_multiprocess_collectives = pytest.mark.skipif(
    not cpu_multiprocess_collectives_supported(),
    reason="jax < 0.5 cannot run multi-process XLA collectives on the "
           "CPU backend (set HVD_TPU_TEST_FORCE_MULTIPROC=1 to force)",
)
