"""Environment capability guards for integration tests.

jax < 0.5 cannot execute MULTI-PROCESS XLA computations on the CPU
backend ("Multiprocess computations aren't implemented on the CPU
backend"), and every guarded test pins ``JAX_PLATFORMS=cpu`` in its
worker environment — so with jax < 0.5 these tests fail on ANY image
(TPU hosts included), slowly, through elastic restart loops and
rendezvous timeouts.  Guarding on the jax version alone is therefore
exact; on jax >= 0.5 the guard is inert.
``HVD_TPU_TEST_FORCE_MULTIPROC=1`` forces the tests to run anyway
(e.g. to re-probe a new jax).

Note the boundary: multi-process *control-plane* tests (rendezvous,
native negotiation/auth frames, heartbeats, exec-restart recovery,
chaos soak) do NOT need this guard — only cross-process data-plane
collectives are unsupported.
"""

import os

import pytest


def cpu_multiprocess_collectives_supported() -> bool:
    if os.environ.get("HVD_TPU_TEST_FORCE_MULTIPROC") == "1":
        return True
    import jax

    try:
        major, minor = (int(x) for x in jax.__version__.split(".")[:2])
    except ValueError:
        return True  # unparseable dev version: let the test decide
    return (major, minor) >= (0, 5)


requires_multiprocess_collectives = pytest.mark.skipif(
    not cpu_multiprocess_collectives_supported(),
    reason="jax < 0.5 cannot run multi-process XLA collectives on the "
           "CPU backend (set HVD_TPU_TEST_FORCE_MULTIPROC=1 to force)",
)


# -- native-library selection (sanitizer reruns) ------------------------------
#
# The ctypes fault/auth tests drive whichever core library these two
# variables select, so the same tests re-run unchanged against the
# TSan/ASan builds (tools/rebuild_native.sh --sanitize=...; see
# docs/ANALYSIS.md).  The sanitizer runtimes must be the FIRST loaded
# DSO, hence the child-side LD_PRELOAD hook.

NATIVE_LIB_ENV = "HVD_TPU_TEST_NATIVE_LIB"
CHILD_PRELOAD_ENV = "HVD_TPU_TEST_CHILD_PRELOAD"


def native_lib_path(repo: str) -> str:
    """Path of the core library under test: the committed/production
    build unless HVD_TPU_TEST_NATIVE_LIB points at an instrumented one."""
    return os.environ.get(NATIVE_LIB_ENV) or os.path.join(
        repo, "horovod_tpu", "native", "libhvd_tpu_core.so")


def native_child_env() -> dict:
    """os.environ copy for a ctypes child process, with the sanitizer
    runtime LD_PRELOADed when a rerun requests it (dlopen'ing a
    TSan/ASan-instrumented .so requires its runtime to be loaded first
    — static-TLS/shadow setup fails otherwise)."""
    env = os.environ.copy()
    preload = env.get(CHILD_PRELOAD_ENV)
    if preload:
        # prepend: the sanitizer runtime must come first, but any
        # preload already in force (jemalloc, profiler shims) stays
        existing = env.get("LD_PRELOAD")
        env["LD_PRELOAD"] = (f"{preload}:{existing}" if existing
                             else preload)
    return env
