"""Continuous-batching serving: the batched-decode oracle + bounded
compiled-program set (ISSUE 8 acceptance), extended with the prefix
cache, copy-on-write blocks and chunked prefill (ISSUE 10).

The oracle (the serving exactness contract, docs/SERVING.md): greedy
decode is deterministic, so continuous batching over the paged KV
cache — whatever admission order, padding tier, eviction, block-table
reuse, PREFIX-CACHE hit or CHUNKED-prefill schedule the scheduler
lands on — must emit token-for-token what one-at-a-time full-context
decode emits, and bit-identical streams with the prefix cache on vs
off.  Any paging bug (wrong block, stale page, bad tail-block offset,
a padded slot leaking into a real row, a shared block written through)
breaks exactness immediately, which is why the oracle is the test
rather than a statistical check.

Program bounding: the padding-tier menu caps the compiled-program set
by |decode_tiers| x (|chunk_tiers| + |page_tiers| + spec·|page_tiers|)
regardless of the request distribution; the 512-request randomized
load (now with 4 shared prompt templates) pins it via the PR-1
executable-cache counters (warmup compiles the menu, traffic must be
all hits) — spec off AND spec on (ISSUE 17: per-request draft lengths
vary every step, the program keys never do).

Speculative decoding (ISSUE 17) rides the same oracle: greedy
accept/reject emits only verifier argmaxes, so the speculative stream
is bit-identical to the plain one — with rollback (truncate_tail) in
the loop, at shard factors 1 and 2.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.metrics import instruments as _instr
from horovod_tpu.models.transformer import Transformer, TransformerConfig
from horovod_tpu.serving import (
    BlockAllocator, ModelDrafter, PromptLookupDrafter, Request,
    ServeConfig, ServingEngine, accept_greedy, blocks_for, make_drafter,
    modeled_decode_read_bytes,
)
from horovod_tpu.serving.kv_cache import PREFIX_HASH_ROOT


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(
        vocab_size=97, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=8, max_seq_len=64, dtype=jnp.float32,
        attention_impl="dot", causal=True)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32), train=False)["params"]
    return cfg, model, params


def ref_decode(model, params, prompt, n, eos_id=None):
    """One-at-a-time full-context greedy decode (no cache at all)."""
    toks = list(np.asarray(prompt))
    out = []
    for _ in range(n):
        x = jnp.asarray(np.asarray(toks, np.int32))[None]
        logits = model.apply({"params": params}, x, train=False)
        t = int(jnp.argmax(logits[0, -1].astype(jnp.float32)))
        toks.append(t)
        out.append(t)
        if eos_id is not None and t == eos_id:
            break
    return np.asarray(out, np.int32)


def _prompts(rs, n, lo=3, hi=20):
    return [rs.randint(1, 97, size=rs.randint(lo, hi)).astype(np.int32)
            for _ in range(n)]


# -- the batched-decode oracle ----------------------------------------------


def test_continuous_batched_decode_matches_one_at_a_time(model_and_params):
    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=128, watermark=2,
        decode_tiers=(1, 2, 4)))
    rs = np.random.RandomState(0)
    prompts = _prompts(rs, 6)
    gens = [10, 3, 7, 10, 1, 5]
    ids = [eng.submit(p, max_new_tokens=g) for p, g in zip(prompts, gens)]
    out = eng.run()
    for i, rid in enumerate(ids):
        ref = ref_decode(model, params, prompts[i], gens[i])
        np.testing.assert_array_equal(out[rid], ref, err_msg=f"req {i}")


def test_oracle_pinned_across_evictions_and_block_reuse(model_and_params):
    """A pool too small for the batch forces LIFO recompute evictions;
    freed blocks are immediately reallocated to other sequences (table
    reuse), and the evicted sequence re-prefills prompt+generated.
    Token streams must be pinned through all of it."""
    cfg, model, params = model_and_params
    # 16 allocatable blocks of 4 = 64 cache slots for 3 sequences that
    # each want prompt+18 tokens (~7 blocks): admission overcommits,
    # growth evicts
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=4, num_blocks=17, token_budget=64, watermark=0,
        decode_tiers=(1, 2, 4)))
    rs = np.random.RandomState(1)
    prompts = _prompts(rs, 3, lo=10, hi=14)
    ids = [eng.submit(p, max_new_tokens=18) for p in prompts]
    out = eng.run()
    assert eng.scheduler.evictions > 0, "pool was sized to force evictions"
    for i, rid in enumerate(ids):
        ref = ref_decode(model, params, prompts[i], 18)
        np.testing.assert_array_equal(out[rid], ref, err_msg=f"req {i}")


def test_eos_stops_generation(model_and_params):
    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=128, watermark=1,
        decode_tiers=(1, 2)))
    rs = np.random.RandomState(2)
    prompt = _prompts(rs, 1)[0]
    ref = ref_decode(model, params, prompt, 16)
    eos = int(ref[4])  # stop at the 5th token the model will emit
    rid = eng.submit(prompt, max_new_tokens=16, eos_id=eos)
    out = eng.run()
    np.testing.assert_array_equal(
        out[rid], ref_decode(model, params, prompt, 16, eos_id=eos))
    assert out[rid][-1] == eos and len(out[rid]) <= 16


def test_staged_source_path_matches_submit_path(model_and_params):
    """attach_source (DevicePrefetcher staging) and direct submit are
    the same requests — same tokens out."""
    cfg, model, params = model_and_params
    rs = np.random.RandomState(3)
    prompts = _prompts(rs, 5)
    reqs = [Request(id=i, prompt=p, max_new_tokens=6)
            for i, p in enumerate(prompts)]
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=128, watermark=2,
        decode_tiers=(1, 2, 4)))
    eng.attach_source(iter(reqs))
    out = eng.run()
    for i, p in enumerate(prompts):
        np.testing.assert_array_equal(
            out[i], ref_decode(model, params, p, 6), err_msg=f"req {i}")


def test_submit_validates(model_and_params):
    cfg, _, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, decode_tiers=(1, 2)))
    with pytest.raises(ValueError, match="empty"):
        eng.submit(np.zeros((0,), np.int32), max_new_tokens=4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(np.ones((4,), np.int32), max_new_tokens=0)
    with pytest.raises(ValueError, match="max_seq_len"):
        eng.submit(np.ones((60,), np.int32), max_new_tokens=10)
    with pytest.raises(ValueError, match="causal"):
        ServingEngine(
            TransformerConfig(causal=False, dtype=jnp.float32), params)


def test_oversize_prefill_tier_dropped(model_and_params):
    """A tier > max_seq_len would index block-table columns past
    max_blocks and corrupt real KV through the clamped gather — the
    engine must drop it (warning) rather than compile it."""
    cfg, _, params = model_and_params  # max_seq_len = 64
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, prefill_tiers=(32, 100),
        decode_tiers=(1, 2)))
    assert max(eng.prefill_tiers) <= cfg.max_seq_len
    assert eng.prefill_tiers == (32, 64)


def test_sourced_id_collision_rejected(model_and_params):
    """A sourced request reusing an id already handed out by submit()
    must be rejected, not silently clobber that request's results."""
    cfg, _, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, decode_tiers=(1, 2)))
    rid = eng.submit(np.ones((4,), np.int32), max_new_tokens=2)
    eng.attach_source(iter(
        [Request(id=rid, prompt=np.ones((4,), np.int32),
                 max_new_tokens=2)]))
    with pytest.raises(ValueError, match="already in use"):
        eng.run()


# -- bounded compiled-program set under randomized load ----------------------


def _templated_load(rs, n, templates, lo=3, hi=41):
    """Randomized load where ~half the prompts start with one of the
    shared templates — the dominant production shape (shared system
    prompts / few-shot headers) the prefix cache exists for."""
    load = []
    for _ in range(n):
        suffix = rs.randint(1, 97, size=rs.randint(lo, hi)).astype(np.int32)
        if rs.random_sample() < 0.5:
            t = templates[rs.randint(len(templates))]
            prompt = np.concatenate([t, suffix])[:57]  # < max_seq_len-gen
        else:
            prompt = suffix
        load.append((prompt, int(rs.randint(1, 7))))
    return load


def test_program_count_bounded_under_randomized_load(model_and_params):
    """512 randomized requests over 4 shared prompt templates; the tier
    menu bounds the compiled set and the PR-1 executable-cache counters
    prove steady state is all hits: warmup compiles the menu, traffic
    (prefix hits, CoW tails, chunked prefills and all) adds ZERO
    misses."""
    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=256, watermark=2,
        decode_tiers=(1, 2, 4, 8), prefill_chunk=16))
    menu = len(eng.decode_tiers) * (
        len(eng.chunk_tiers) + len(eng.page_tiers))
    warmed = eng.warmup()
    assert warmed == menu == eng.program_count
    hits0 = _instr.EXEC_CACHE.labels("hit").get()
    miss0 = _instr.EXEC_CACHE.labels("miss").get()
    rs = np.random.RandomState(4)
    templates = [rs.randint(1, 97, size=24).astype(np.int32)
                 for _ in range(4)]
    load = _templated_load(rs, 512, templates)
    for prompt, gen in load:
        eng.submit(prompt, max_new_tokens=gen)
    out = eng.run()
    assert len(out) == 512 and all(len(v) >= 1 for v in out.values())
    assert eng.program_count == menu, (
        f"{eng.program_count} programs compiled; menu bounds it to {menu}")
    assert _instr.EXEC_CACHE.labels("miss").get() == miss0
    assert _instr.EXEC_CACHE.labels("hit").get() > hits0
    # the templated load must actually exercise the prefix cache
    assert eng.scheduler.prefix_hit_blocks > 0
    # spot-check the oracle still holds at this scale
    for rid in (0, 99, 511):
        prompt, gen = load[rid]
        np.testing.assert_array_equal(
            out[rid], ref_decode(model, params, prompt, gen))


# -- allocator / kv-model units ---------------------------------------------


def test_block_allocator_contract():
    a = BlockAllocator(8, block_size=4)
    assert a.capacity == 7 and a.free_blocks == 7
    got = a.alloc(3)
    assert len(got) == 3 and 0 not in got, "block 0 is the trash block"
    assert a.alloc(5) is None, "all-or-nothing"
    assert a.free_blocks == 4
    assert a.occupancy() == pytest.approx(3 / 7)
    assert a.peak_occupancy == pytest.approx(3 / 7)
    a.free(got)
    assert a.free_blocks == 7 and a.occupancy() == 0.0
    assert a.peak_occupancy == pytest.approx(3 / 7), "peak is sticky"
    with pytest.raises(ValueError, match="double free"):
        a.free([a.alloc(1)[0]] * 2)
    with pytest.raises(ValueError, match="out of range"):
        a.free([0])
    with pytest.raises(ValueError, match=">= 2"):
        BlockAllocator(1)
    assert blocks_for(9, 4) == 3 and blocks_for(8, 4) == 2


def test_modeled_decode_read_bytes_reductions():
    """The serve_bench kv_model column: paging (vs max-seq reservation),
    GQA (vs MHA) and windowing each cut modeled decode reads."""
    kw = dict(block_size=16, num_heads=8, num_kv_heads=2, head_dim=64,
              num_layers=4, dtype_bytes=2, max_seq_len=2048)
    m = modeled_decode_read_bytes(256, **kw)
    # 256 of 2048 tokens resident, GQA 4x: >= 16x kernel-read reduction
    assert m["full_bytes"] >= 16 * m["paged_bytes"]
    assert m["pages_read"] == 16
    # the window=None gather copy is max_blocks wide (static shapes):
    # only the GQA factor survives in the gather term
    assert m["pages_gathered"] == 2048 // 16
    assert m["full_bytes"] == 4 * m["gathered_bytes"]
    w = modeled_decode_read_bytes(1024, window=128, **kw)
    nw = modeled_decode_read_bytes(1024, **kw)
    assert w["paged_bytes"] < nw["paged_bytes"] / 4, "window caps reads"
    assert w["pages_read"] <= 128 // 16 + 2
    assert w["pages_gathered"] <= 128 // 16 + 2, "window truncates gather"
    # tier-bounded gather: the live-context page tier caps the copy
    # where the pre-tier model charged the full max_blocks width
    t = modeled_decode_read_bytes(256, gather_pages=32, **kw)
    assert t["pages_gathered"] == 32 < m["pages_gathered"] == 2048 // 16
    assert t["gathered_bytes"] == 2 * t["paged_bytes"]  # 32 vs 16 pages
    # the tier can never model FEWER pages than the kernel reads
    u = modeled_decode_read_bytes(1024, gather_pages=2, **kw)
    assert u["pages_gathered"] >= u["pages_read"]


def test_decode_gather_bounded_by_live_context_tier(model_and_params):
    """The unwindowed decode gather copy is keyed by the batch's live
    max-context PAGE TIER: short contexts run the small-tier program
    and growth walks up the menu — never a max_blocks-wide copy for a
    two-page batch."""
    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=128, watermark=2,
        decode_tiers=(1, 2)))
    assert eng.page_tiers == (1, 2, 4, 8)  # 64-token max_seq, 8/block
    rid = eng.submit(np.ones((4,), np.int32), max_new_tokens=8)
    eng.run()
    decode_keys = [k for k in eng._progs if k[0] == "decode"]
    # 4+8 tokens = 12 -> at most the 2-page tier was ever gathered
    assert decode_keys and all(k[2] <= 2 for k in decode_keys), decode_keys
    np.testing.assert_array_equal(
        eng.results[rid], ref_decode(model, params, np.ones((4,)), 8))


# -- prefix cache: refcount lifecycle, CoW, collisions ------------------------


def test_allocator_refcount_lifecycle():
    """Shared blocks: match bumps refs, each holder frees once, the
    block parks on the LRU only at refcount 0; double-free (over-free
    of a shared block included) is loud; eviction never reclaims a
    block with live refs."""
    a = BlockAllocator(8, block_size=4)
    owner = a.alloc(2)
    h0 = a.register(owner[0], PREFIX_HASH_ROOT, [1, 2, 3, 4])
    m, hs = a.match_prefix([1, 2, 3, 4, 9], max_blocks=1)
    assert m == [owner[0]] and hs == [h0]
    assert a.ref(owner[0]) == 2, "matched block is SHARED"
    a.free(owner)  # first holder releases
    assert a.ref(owner[0]) == 1
    assert a.cached_blocks == 1
    # eviction never reclaims a block with refs: draining the whole
    # pool must leave the shared block alone
    rest = a.alloc(a.free_blocks)
    assert owner[0] not in rest
    assert a.ref(owner[0]) == 1, "still owned by the matcher"
    a.free(rest)
    a.free(m)  # last holder -> parks on the LRU, still cached
    assert a.ref(owner[0]) == 0 and a.cached_blocks == 1
    with pytest.raises(ValueError, match="double free"):
        a.free(m)  # over-free of the shared block
    # parked block is still matchable...
    m2, _ = a.match_prefix([1, 2, 3, 4, 9], max_blocks=1)
    assert m2 == [owner[0]]
    a.free(m2)
    # ...until a full-pool allocation reclaims it LRU-last
    every = a.alloc(7)
    assert a.cached_blocks == 0, "reclaim drops the cache entry"
    a.free(every)


def test_register_guards():
    a = BlockAllocator(8, block_size=4)
    got = a.alloc(1)
    with pytest.raises(ValueError, match="full block"):
        a.register(got[0], PREFIX_HASH_ROOT, [1, 2])  # partial tail
    a.free(got)
    with pytest.raises(ValueError, match="unreferenced"):
        a.register(got[0], PREFIX_HASH_ROOT, [1, 2, 3, 4])
    off = BlockAllocator(8, block_size=4, prefix_cache=False)
    b = off.alloc(1)
    assert off.register(b[0], PREFIX_HASH_ROOT, [1, 2, 3, 4]) is None
    assert off.match_prefix([1, 2, 3, 4, 5]) == ([], [])
    off.free(b)
    assert off.free_blocks == 7 and off.cached_blocks == 0


def test_hash_collision_safe_via_full_compare():
    """A degenerate hash function collides EVERY block; the full
    token-id + parent compare must still reject false hits."""
    a = BlockAllocator(8, block_size=4)
    a.hash_fn = lambda parent, tokens: 42  # all chains collide
    got = a.alloc(1)
    a.register(got[0], PREFIX_HASH_ROOT, [1, 2, 3, 4])
    m, _ = a.match_prefix([5, 6, 7, 8, 0], max_blocks=1)
    assert m == [], "collision must NOT match different tokens"
    m, _ = a.match_prefix([1, 2, 3, 4, 0], max_blocks=1)
    assert m == [got[0]], "identical content still matches"
    a.free(m)
    a.free(got)


def test_partial_tail_block_never_matched():
    """CoW by construction: only FULL blocks register, and the match is
    capped one block short of the prompt, so the block a new sequence
    will write into is always private (refcount 1)."""
    a = BlockAllocator(16, block_size=4)
    owner = a.alloc(3)  # 12 tokens, say 10 real: blocks 0,1 full, 2 partial
    h0 = a.register(owner[0], PREFIX_HASH_ROOT, [1, 2, 3, 4])
    a.register(owner[1], h0, [5, 6, 7, 8])
    # identical 10-token prompt: both full blocks hit, tail is private
    m, _ = a.match_prefix([1, 2, 3, 4, 5, 6, 7, 8, 9, 9],
                          max_blocks=(10 - 1) // 4)
    assert m == owner[:2]
    # a prompt EQUAL to the cached full span still computes >= 1 token:
    # the (ctx-1)//bs cap leaves the last full block unmatched
    m2, _ = a.match_prefix([1, 2, 3, 4, 5, 6, 7, 8],
                           max_blocks=(8 - 1) // 4)
    assert m2 == owner[:1]
    a.free(m)
    a.free(m2)
    a.free(owner)


# -- prefix cache + chunked prefill: engine-level oracles ---------------------


def _template_prompts(rs, n, t_len=19, s_lo=2, s_hi=6):
    template = rs.randint(1, 97, size=t_len).astype(np.int32)
    return [np.concatenate([
        template, rs.randint(1, 97, size=rs.randint(s_lo, s_hi))
        .astype(np.int32)]) for _ in range(n)]


def test_prefix_cache_hits_are_token_exact(model_and_params):
    """Requests sharing a prompt template, admitted in waves so later
    waves hit the cache: hits must be > 0 and every stream must match
    the no-cache one-at-a-time reference — cached K/V is REUSED, so any
    staleness or misindexed block surfaces here."""
    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=128, watermark=2,
        decode_tiers=(1, 2), prefill_chunk=8))
    rs = np.random.RandomState(7)
    prompts = _template_prompts(rs, 6)
    ids = [eng.submit(p, max_new_tokens=6) for p in prompts]
    out = eng.run()
    assert eng.scheduler.prefix_hit_blocks > 0, "templates must hit"
    for i, rid in enumerate(ids):
        np.testing.assert_array_equal(
            out[rid], ref_decode(model, params, prompts[i], 6),
            err_msg=f"req {i}")


def test_prefix_cache_on_off_bit_identical(model_and_params):
    """The acceptance bar: the same request stream with the prefix
    cache disabled vs enabled produces bit-identical token streams,
    while the enabled engine computes measurably fewer prefill
    tokens."""
    cfg, model, params = model_and_params
    rs = np.random.RandomState(8)
    prompts = _template_prompts(rs, 6)
    outs, computed = [], []
    for enabled in (True, False):
        eng = ServingEngine(cfg, params, serve=ServeConfig(
            block_size=8, num_blocks=0, token_budget=128, watermark=2,
            decode_tiers=(1, 2), prefill_chunk=8, prefix_cache=enabled))
        ids = [eng.submit(p, max_new_tokens=6) for p in prompts]
        out = eng.run()
        outs.append([out[r] for r in ids])
        computed.append(eng.prefill_tokens_computed)
    for a, b in zip(outs[0], outs[1]):
        np.testing.assert_array_equal(a, b)
    assert computed[0] < computed[1], (
        "prefix hits must shrink prefill_tokens_computed")


def test_chunked_prefill_interleaves_with_decode(model_and_params):
    """A long prompt arriving while short requests decode: with
    prefill_chunk set the prompt streams in across MIXED steps (chunk
    rows packed beside decode rows) and every stream stays
    token-exact."""
    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=64, watermark=2,
        decode_tiers=(1, 2, 4), prefill_chunk=8))
    rs = np.random.RandomState(9)
    short = _prompts(rs, 2, lo=3, hi=6)
    long_p = rs.randint(1, 97, size=40).astype(np.int32)
    ids = [eng.submit(p, max_new_tokens=10) for p in short]
    ids.append(eng.submit(long_p, max_new_tokens=6))
    out = eng.run()
    # the 40-token tail at chunk 8 takes >= 5 mixed steps; decode rows
    # rode along (mixed steps outnumber the long prompt's chunks alone)
    assert eng.prefill_tokens_computed >= 40 + sum(len(p) for p in short)
    for i, (p, g) in enumerate(zip(short + [long_p], [10, 10, 6])):
        np.testing.assert_array_equal(
            out[ids[i]], ref_decode(model, params, p, g),
            err_msg=f"req {i}")


def test_eviction_readmits_through_prefix_match(model_and_params):
    """LIFO recompute eviction + prefix cache: a preempted sequence's
    published full blocks park on the LRU, and — given any pool slack —
    its re-admission goes through the same prefix match as a fresh
    request, re-mapping the surviving blocks instead of re-prefilling
    from token 0 (hits recorded AFTER the eviction), with only the
    uncached tail re-booked against the token budget.  Streams stay
    pinned through all of it.  (The zero-slack case, where reclaim eats
    the parked blocks before re-admission, is the honest fallback and is
    covered by test_oracle_pinned_across_evictions.)"""
    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=4, num_blocks=33, token_budget=64, watermark=0,
        decode_tiers=(1, 2)))
    rs = np.random.RandomState(10)
    prompts = _prompts(rs, 2, lo=12, hi=14)
    ids = [eng.submit(p, max_new_tokens=12) for p in prompts]
    for _ in range(6):  # prefill both + a few decode steps -> published
        eng.step()
    hits_before = eng.scheduler.prefix_hit_blocks
    assert eng.scheduler._evict_one(), "LIFO preemption of the newest seq"
    out = eng.run()
    assert eng.scheduler.evictions == 1
    assert eng.scheduler.prefix_hit_blocks > hits_before, (
        "re-admission must reuse the victim's surviving cached blocks")
    for i, rid in enumerate(ids):
        np.testing.assert_array_equal(
            out[rid], ref_decode(model, params, prompts[i], 12),
            err_msg=f"req {i}")


# -- tensor-sharded serving (ISSUE 12) ----------------------------------------


def _shard_mesh(n):
    from horovod_tpu.parallel import tensor_shard_mesh

    return tensor_shard_mesh("tp", n)


def test_modeled_decode_read_bytes_shards_pin():
    """The shards= satellite: per-chip modeled reads at shard factors
    1/2/4 equal the kernel term exactly — pages x one page's K+V bytes
    at THIS CHIP's kv-head slice x layers — and drop by the factor."""
    kw = dict(block_size=16, num_heads=8, num_kv_heads=4, head_dim=64,
              num_layers=4, dtype_bytes=2, max_seq_len=2048)
    base = modeled_decode_read_bytes(256, **kw)
    for s in (1, 2, 4):
        m = modeled_decode_read_bytes(256, shards=s, **kw)
        kernel_term = (kw["num_layers"] * m["pages_read"] * 2
                       * kw["block_size"] * (kw["num_kv_heads"] // s)
                       * kw["head_dim"] * kw["dtype_bytes"])
        assert m["paged_bytes"] == kernel_term == base["paged_bytes"] // s
        assert m["gathered_bytes"] == base["gathered_bytes"] // s
        assert m["pages_read"] == base["pages_read"], "geometry replicates"
        assert m["full_bytes"] == base["full_bytes"], "baseline unsharded"
    with pytest.raises(ValueError, match="divide"):
        modeled_decode_read_bytes(256, shards=3, **kw)


def test_env_tiers_reject_malformed(monkeypatch):
    """ServeConfig.from_env tier knobs fail at PARSE time with a clear
    ValueError — not as a confusing menu/program-key miss at warmup."""
    for bad, msg in (("1,banana", "int list"),
                     ("3,5", "powers of two"),
                     ("8,4", "ascending"),
                     ("4,4", "ascending"),
                     ("0,2", "powers of two"),
                     ("-2,4", "powers of two")):
        monkeypatch.setenv("HVD_TPU_SERVE_DECODE_TIERS", bad)
        with pytest.raises(ValueError, match=msg):
            ServeConfig.from_env()
    monkeypatch.setenv("HVD_TPU_SERVE_DECODE_TIERS", "2,8,32")
    monkeypatch.setenv("HVD_TPU_SERVE_PREFILL_TIERS", "16,64")
    got = ServeConfig.from_env()
    assert got.decode_tiers == (2, 8, 32)
    assert got.prefill_tiers == (16, 64)


def test_sharded_engine_validates(model_and_params):
    cfg, _, params = model_and_params  # num_kv_heads=2
    with pytest.raises(ValueError, match="divide"):
        ServingEngine(cfg, params, serve=ServeConfig(
            block_size=8, num_blocks=0, decode_tiers=(1, 2), shards=4),
            mesh=_shard_mesh(4))
    from horovod_tpu.parallel import tensor_shard_mesh
    with pytest.raises(ValueError, match="devices"):
        tensor_shard_mesh("tp", 99)


def test_sharded_decode_token_identical_with_evictions(model_and_params):
    """The standing oracle, sharded: prefix hits, CoW tails, chunked
    schedules AND forced LIFO evictions on a 2-shard engine emit
    token-for-token what the single-device engine emits."""
    cfg, model, params = model_and_params
    serve = dict(block_size=4, num_blocks=25, token_budget=64,
                 watermark=0, decode_tiers=(1, 2, 4), prefill_chunk=8)
    rs = np.random.RandomState(11)
    prompts = _template_prompts(rs, 4, t_len=11, s_lo=2, s_hi=5)
    outs = []
    for mesh in (None, _shard_mesh(2)):
        eng = ServingEngine(cfg, params, serve=ServeConfig(**serve),
                            mesh=mesh)
        ids = [eng.submit(p, max_new_tokens=14) for p in prompts]
        out = eng.run()
        outs.append([out[r] for r in ids])
        assert eng.scheduler.evictions > 0, "pool sized to force evictions"
        assert eng.scheduler.prefix_hit_blocks > 0, "templates must hit"
    for i, (a, b) in enumerate(zip(*outs)):
        np.testing.assert_array_equal(a, b, err_msg=f"req {i}")
        np.testing.assert_array_equal(
            a, ref_decode(model, params, prompts[i], 14),
            err_msg=f"req {i} vs no-cache reference")


def test_sharded_menu_compile_free_under_load(model_and_params):
    """Zero post-warmup compiles on the SHARDED program menu: warmup
    compiles |decode|x(|chunk|+|page|) shard_map programs, a randomized
    templated load adds no executable-cache misses, and the sharded
    psum byte counter grows per the comm model."""
    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=128, watermark=2,
        decode_tiers=(1, 2, 4), prefill_chunk=16, shards=2))
    assert eng.shards == 2
    menu = len(eng.decode_tiers) * (
        len(eng.chunk_tiers) + len(eng.page_tiers))
    warmed = eng.warmup()
    assert warmed == menu == eng.program_count
    miss0 = _instr.EXEC_CACHE.labels("miss").get()
    psum0 = _instr.SERVE_SHARD_PSUM_BYTES.get()
    rs = np.random.RandomState(12)
    templates = [rs.randint(1, 97, size=16).astype(np.int32)
                 for _ in range(2)]
    load = _templated_load(rs, 24, templates, lo=3, hi=20)
    ids = [eng.submit(p, max_new_tokens=g) for p, g in load]
    out = eng.run()
    assert eng.program_count == menu
    assert _instr.EXEC_CACHE.labels("miss").get() == miss0
    assert eng.shard_psum_bytes > 0
    assert _instr.SERVE_SHARD_PSUM_BYTES.get() - psum0 == \
        eng.shard_psum_bytes
    for i in (0, 13, 23):  # spot-check the oracle at this scale
        prompt, gen = load[i]
        np.testing.assert_array_equal(
            out[ids[i]], ref_decode(model, params, prompt, gen))


def test_sharded_models_match_lowering(model_and_params):
    """Modeled == measured per the PR-7 idiom, on the decode program
    the engine actually dispatches: the StableHLO all_reduce inventory
    equals the psum model, the rank-5 page-gather inventory equals the
    per-chip gathered-bytes model x batch tier, and BOTH drop by the
    shard factor vs the single-device lowering."""
    from horovod_tpu.ops.comm_model import (
        measured_tier_bytes, modeled_serve_psum_bytes,
        serve_gather_read_bytes,
    )

    cfg, _, params = model_and_params  # 2 kv heads, f32
    bt, pt = 2, 2
    gathered = {}
    for s in (1, 2):
        eng = ServingEngine(cfg, params, serve=ServeConfig(
            block_size=8, num_blocks=0, decode_tiers=(1, bt), shards=s))
        txt = eng.lowered_decode_text(batch_tier=bt, pages=pt)
        measured = measured_tier_bytes(txt, [0] * s)
        modeled = modeled_serve_psum_bytes(
            bt, 1, cfg.d_model, cfg.num_layers, s, "float32")
        assert measured["ici_bytes"] == modeled["stream_bytes"]
        n_psums = sum(1 for op in measured["ops"]
                      if op["op"] == "all_reduce")
        assert n_psums == modeled["psum_count"]
        m = modeled_decode_read_bytes(
            pt * 8, block_size=8, num_heads=cfg.num_heads,
            num_kv_heads=cfg.num_kv_heads, head_dim=cfg.head_dim,
            num_layers=cfg.num_layers, dtype_bytes=4,
            max_seq_len=cfg.max_seq_len, gather_pages=pt, shards=s)
        g = serve_gather_read_bytes(txt)
        assert g["gather_bytes"] == bt * m["gathered_bytes"]
        gathered[s] = g["gather_bytes"]
    assert gathered[2] == gathered[1] // 2, "per-chip reads halve"


def test_pool_watermark_defers_admission(model_and_params):
    """With a deep queue and a watermark, admission stops before the
    pool drains: running sequences keep headroom to grow."""
    cfg, _, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=17, token_budget=256, watermark=6,
        decode_tiers=(1, 2, 4, 8)))
    for _ in range(8):
        eng.submit(np.ones((8,), np.int32), max_new_tokens=2)
    admitted = eng.scheduler.admit()
    # each sequence needs 2 blocks (8+1 tokens @ block 8); 16 free,
    # watermark 6 -> at most 5 admitted (16 - 5*2 = 6)
    assert 0 < len(admitted) <= 5
    assert eng.allocator.free_blocks >= 6


# -- PR 13: queue-depth honesty, the published prefix index, drain ----------


def test_queue_depth_gauge_counts_staged_rows(model_and_params):
    """The ISSUE-13 satellite pin: ``hvd_tpu_serve_queue_depth`` must
    count device-STAGED rows (attach_source's prefetcher queue), not
    just scheduler-pending ones — the fleet router's least-queue
    fallback reads the same sum (scheduler.queue_depth()), so an
    undercount would route new load onto a replica that is already
    backed up behind its staging queue."""
    import time as _time

    cfg, _, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=128, watermark=2,
        decode_tiers=(1, 2)))
    reqs = [Request(id=i, prompt=np.ones((8,), np.int32),
                    max_new_tokens=2) for i in range(6)]
    eng.attach_source(iter(reqs), depth=8)
    # the staging producer runs on its own thread: wait until it has
    # staged every row (meta appended at yield time, before device put)
    deadline = _time.time() + 10
    while len(eng._staging_meta) < 6 and _time.time() < deadline:
        _time.sleep(0.01)
    assert len(eng._staging_meta) == 6, "staging never filled"
    # nothing drained yet: pending==0, staged==6 — the sum is 6, on
    # both the router's read and (after a booking pass) the gauge
    assert eng.scheduler.queue_depth() == 6
    eng.scheduler._book()
    assert _instr.SERVE_QUEUE_DEPTH.get() == 6
    # draining moves rows staged -> pending -> admitted; the gauge
    # tracks the honest waiting count at every step of the way
    eng._drain_staging(block=True)
    assert eng.scheduler.queue_depth() == len(eng.scheduler.pending) \
        + len(eng._staging_meta)
    assert _instr.SERVE_QUEUE_DEPTH.get() == eng.scheduler.queue_depth()
    eng.run()
    assert _instr.SERVE_QUEUE_DEPTH.get() == 0


def test_peek_prefix_matches_match_prefix_without_side_effects():
    """peek_prefix (the router's placement probe) agrees with
    match_prefix on the match length but moves NO state: refcounts,
    LRU order and peak occupancy are untouched."""
    alloc = BlockAllocator(num_blocks=12, block_size=4)
    stream = np.arange(1, 13, dtype=np.int32)  # 3 full blocks
    blocks = alloc.alloc(3)
    parent = PREFIX_HASH_ROOT
    for i, b in enumerate(blocks):
        parent = alloc.register(b, parent, stream[i * 4:(i + 1) * 4])
    alloc.free(blocks)  # ref 0 -> parked on the LRU, still matchable
    refs_before = list(alloc._ref)
    lru_before = list(alloc._lru)
    peak_before = alloc.peak_occupancy
    assert alloc.peek_prefix(stream) == 3
    assert alloc.peek_prefix(stream, max_blocks=2) == 2
    assert alloc.peek_prefix(stream[:7]) == 1  # one full block only
    assert alloc.peek_prefix(np.flip(stream)) == 0
    assert list(alloc._ref) == refs_before, "peek bumped a refcount"
    assert list(alloc._lru) == lru_before, "peek un-parked a block"
    assert alloc.peak_occupancy == peak_before
    # the real match still works afterwards and DOES take references
    matched, _ = alloc.match_prefix(stream)
    assert len(matched) == 3 and all(alloc.ref(b) == 1 for b in matched)
    # collision safety: peek confirms content like match_prefix does
    alloc2 = BlockAllocator(num_blocks=6, block_size=4)
    alloc2.hash_fn = lambda parent, toks: 7  # every block collides
    b2 = alloc2.alloc(1)
    alloc2.register(b2[0], PREFIX_HASH_ROOT, stream[:4])
    assert alloc2.peek_prefix(stream[:4]) == 1
    assert alloc2.peek_prefix(np.flip(stream[:4]).copy()) == 0


def test_engine_drain_gate_rejects_new_intake(model_and_params):
    """accepting=False (the fleet drain hook): new submits and sources
    are rejected, in-flight work steps to completion untouched."""
    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=128, watermark=2,
        decode_tiers=(1, 2)))
    prompt = np.arange(1, 9, dtype=np.int32)
    rid = eng.submit(prompt, max_new_tokens=4)
    eng.accepting = False
    with pytest.raises(RuntimeError, match="draining"):
        eng.submit(prompt, max_new_tokens=4)
    with pytest.raises(RuntimeError, match="draining"):
        eng.attach_source(iter(()))
    out = eng.run()
    np.testing.assert_array_equal(out[rid],
                                  ref_decode(model, params, prompt, 4))


# -- request deadlines (ISSUE 14 satellite) ----------------------------------


def _deadline_engine(cfg, params, clock, **kw):
    serve = ServeConfig(block_size=8, num_blocks=0, token_budget=128,
                        watermark=2, decode_tiers=(1, 2, 4), **kw)
    return ServingEngine(cfg, params, serve=serve, clock=clock)


def test_deadline_sheds_before_admission(model_and_params):
    """A request whose budget is spent while queued is shed by admit():
    its prefill would compute tokens nobody is waiting for.  The result
    entry publishes (empty) so callers never wait forever."""
    cfg, model, params = model_and_params
    t = [0.0]
    eng = _deadline_engine(cfg, params, lambda: t[0])
    before = _instr.SERVE_DEADLINE_EXCEEDED.get()
    rid = eng.submit(np.arange(1, 6), max_new_tokens=5, deadline_s=0.5)
    t[0] = 1.0
    eng.step()
    assert rid in eng.results and eng.results[rid].size == 0
    assert _instr.SERVE_DEADLINE_EXCEEDED.get() == before + 1


def test_deadline_cancels_in_flight_and_frees_blocks(model_and_params):
    """step() cancels an expired running sequence; its blocks release
    through the normal refcount path and the partial output publishes."""
    cfg, model, params = model_and_params
    t = [0.0]
    eng = _deadline_engine(cfg, params, lambda: t[0])
    free0 = eng.allocator.free_blocks
    rid = eng.submit(np.arange(1, 6), max_new_tokens=50, deadline_s=5.0)
    for _ in range(4):
        t[0] += 0.1
        eng.step()
    assert rid not in eng.results  # still generating inside budget
    t[0] = 10.0
    eng.step()
    assert rid in eng.results
    partial = eng.results[rid]
    assert 0 < partial.size < 50
    # the cancelled tokens match the reference stream prefix (greedy
    # decode: a cancellation truncates, never corrupts)
    ref = ref_decode(model, params, np.arange(1, 6), partial.size)
    np.testing.assert_array_equal(partial, ref)
    assert eng.allocator.free_blocks == free0


def test_engine_default_deadline_from_config(model_and_params):
    cfg, model, params = model_and_params
    t = [0.0]
    eng = _deadline_engine(cfg, params, lambda: t[0], deadline_s=0.25)
    rid = eng.submit(np.arange(1, 6), max_new_tokens=5)  # inherits 0.25
    t[0] = 1.0
    eng.step()
    assert rid in eng.results and eng.results[rid].size == 0
    # per-request override beats the engine default
    rid2 = eng.submit(np.arange(1, 6), max_new_tokens=5,
                      deadline_s=100.0, arrival=t[0])
    out = eng.run()
    assert out[rid2].size == 5


def test_no_deadline_requests_never_scan(model_and_params):
    """Without any deadline in play the expiry machinery stays off the
    hot path entirely (and outputs are oracle-exact, as ever)."""
    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=128, watermark=2,
        decode_tiers=(1, 2, 4)))
    assert not eng._any_deadline
    prompt = np.arange(1, 9, dtype=np.int32)
    rid = eng.submit(prompt, max_new_tokens=6)
    out = eng.run()
    assert not eng._any_deadline
    np.testing.assert_array_equal(out[rid],
                                  ref_decode(model, params, prompt, 6))


def test_deadline_expiry_mixed_with_live_requests(model_and_params):
    """Expired and live requests interleave: sheds must not disturb
    the survivors' token streams (the standing exactness oracle)."""
    cfg, model, params = model_and_params
    t = [0.0]
    eng = _deadline_engine(cfg, params, lambda: t[0])
    rs = np.random.RandomState(7)
    live_p = rs.randint(1, 97, size=9).astype(np.int32)
    dead_p = rs.randint(1, 97, size=9).astype(np.int32)
    rid_live = eng.submit(live_p, max_new_tokens=8, deadline_s=1e9)
    rid_dead = eng.submit(dead_p, max_new_tokens=8, deadline_s=0.2)
    t[0] = 0.5  # the second request expires before admission completes
    out = eng.run()
    assert out[rid_dead].size < 8
    np.testing.assert_array_equal(
        out[rid_live], ref_decode(model, params, live_p, 8))


def test_cancel_all_publishes_every_partial(model_and_params):
    """cancel_all (the fleet ejection hook) aborts running, pending AND
    device-staged requests, freeing blocks through the refcount path
    and publishing partials so no poller waits forever."""
    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=64, watermark=2,
        decode_tiers=(1, 2)))
    free0 = eng.allocator.free_blocks
    rid_run = eng.submit(np.arange(1, 9), max_new_tokens=20)
    for _ in range(3):
        eng.step()  # rid_run is mid-decode
    rid_pend = eng.submit(np.arange(2, 10), max_new_tokens=5)
    # an attached SOURCE request the router never placed: staged rows
    # must complete (empty), not hang their poller (review finding)
    eng.attach_source(iter([Request(id=500, prompt=np.arange(3, 11),
                                    max_new_tokens=4)]))
    eng._drain_staging(block=True)
    eng.cancel_all()
    assert 0 < eng.results[rid_run].size < 20
    assert rid_pend in eng.results
    assert 500 in eng.results
    assert eng.allocator.free_blocks == free0
    assert not eng.scheduler.running and not eng.scheduler.pending
    assert not eng.step()  # drained: nothing left to do


def test_sourced_requests_inherit_engine_default_deadline(model_and_params):
    """attach_source'd requests get ServeConfig.deadline_s exactly like
    submit()'s do — the open-loop intake is the path overload shedding
    exists for — and an UNSET arrival starts its clock when the request
    surfaces (a 0.0 default against a perf_counter clock would read as
    hours past budget and shed 100% of sourced traffic)."""
    cfg, model, params = model_and_params
    t = [100.0]  # a perf_counter-style clock: far from the 0.0 default
    eng = _deadline_engine(cfg, params, lambda: t[0], deadline_s=0.25)
    eng.attach_source(iter([Request(id=0, prompt=np.arange(1, 9),
                                    max_new_tokens=30)]))
    eng.step()  # drains + admits: arrival stamped 100.0, NOT shed
    assert eng._any_deadline
    assert 0 not in eng.results or eng.results[0].size > 0
    t[0] = 101.0  # now the inherited 0.25s budget is spent
    out = eng.run()
    assert out[0].size < 30  # cancelled mid-flight by the default


def test_cancel_all_stops_a_live_staging_producer(model_and_params):
    """cancel_all must CLOSE the staging prefetcher before publishing:
    a still-running producer would append more staged requests after
    the snapshot — ids that then never resolve (review finding)."""
    import itertools

    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=64, watermark=2,
        decode_tiers=(1, 2)))
    n = 12
    reqs = [Request(id=i, prompt=np.arange(1, 9), max_new_tokens=3)
            for i in range(n)]
    eng.attach_source(iter(reqs), depth=2)
    eng.step()  # let the producer spin up and stage a few
    eng.cancel_all()
    assert eng._staging.closed
    # EVERY id the staging pipeline ever surfaced has a results entry,
    # and nothing new arrives afterwards
    surfaced = set(eng.results)
    assert not eng.step()
    assert set(eng.results) == surfaced
    assert not eng._staging_meta


# -- speculative decoding (ISSUE 17) -----------------------------------------


def test_truncate_tail_contract():
    """The rollback primitive: releases exactly the blocks past what
    keep_tokens occupies, no-ops when nothing extends past it, and the
    trash block 0 is as untouchable here as through free()."""
    a = BlockAllocator(10, block_size=4)
    table = a.alloc(3)  # covers up to 12 tokens
    assert a.truncate_tail(table, 5) == table[:2]  # 5 tokens -> 2 blocks
    assert a.free_blocks == 7
    assert a.truncate_tail(table[:2], 8) == table[:2], "exact fit no-ops"
    assert a.truncate_tail(table[:2], 9) == table[:2], \
        "keep past the table never allocates"
    assert a.truncate_tail(table[:2], 0) == []
    assert a.free_blocks == 9
    assert a.truncate_tail([], 0) == []
    with pytest.raises(ValueError, match="out of range"):
        a.truncate_tail([0], 0)  # the trash block guard


def test_truncate_tail_shared_tail_never_double_frees():
    """The CoW edge the rollback rides on: a speculative tail that
    lands in a PREFIX-REGISTERED shared block must drop this table's
    reference only — the block stays live under the other holder, and
    nothing ever reaches the free list while a ref survives."""
    a = BlockAllocator(10, block_size=4)
    owner = a.alloc(2)
    h = a.register(owner[0], PREFIX_HASH_ROOT, [1, 2, 3, 4])
    m, hs = a.match_prefix([1, 2, 3, 4, 9], max_blocks=1)
    assert m == [owner[0]] and hs == [h]
    sharer = m + a.alloc(1)  # shared prefix block + an owned tail
    free0 = a.free_blocks
    # rollback past the owned tail INTO the shared block's extent:
    # keep 4 tokens = the shared block only
    sharer = a.truncate_tail(sharer, 4)
    assert sharer == [owner[0]]
    assert a.free_blocks == free0 + 1, "only the owned tail released"
    assert a.ref(owner[0]) == 2, "shared block untouched"
    # roll the shared block off this table too: ref drops, block lives
    assert a.truncate_tail(sharer, 0) == []
    assert a.ref(owner[0]) == 1, "owner's ref survives the rollback"
    assert a.cached_blocks == 1, "still indexed for future prefix hits"
    a.free(owner)  # the real owner's release still works (no double free)
    assert a.ref(owner[0]) == 0 and a.cached_blocks == 1
    # only now, at refcount 0, may a full-pool allocation reclaim it
    every = a.alloc(a.capacity)
    assert every is not None and a.cached_blocks == 0
    a.free(every)


def test_prompt_lookup_drafter():
    """N-gram lookup over the sequence's own history: longest trailing
    n-gram wins, the most recent FULL-k-continuation occurrence wins
    (most recent of any as fallback), drafts cap at k, and no match
    (or a degenerate stream) drafts nothing."""
    d = PromptLookupDrafter(max_ngram=3, min_ngram=1)
    # trailing [1,2,3] recurs at the start; what followed it is drafted
    assert d.draft([1, 2, 3, 9, 8, 1, 2, 3], 2) == [9, 8]
    assert d.draft([1, 2, 3, 9, 8, 1, 2, 3], 5) == [9, 8, 1, 2, 3]
    # recency: trailing [1,2] matches at i=0 (-> 5) and i=3 (-> 7);
    # both have k of headroom, the most recent occurrence wins
    assert d.draft([1, 2, 5, 1, 2, 7, 1, 2], 1) == [7]
    # headroom beats recency: the recent match (-> [9,1,2]) can't fill
    # k=4, so the older full-length continuation is the draft
    assert d.draft([1, 2, 8, 8, 8, 1, 2, 9, 1, 2], 2) == [9, 1]
    assert d.draft([1, 2, 8, 8, 8, 1, 2, 9, 1, 2], 4) == [8, 8, 8, 1]
    # all-distinct stream: nothing to look up
    assert d.draft([1, 2, 3, 4, 5], 4) == []
    assert d.draft([7], 4) == [], "degenerate stream"
    # unigram fallback: the only earlier [3] match leaves one
    # continuation token, which is still worth drafting
    assert d.draft([3, 3, 3, 3], 2) == [3]


def test_model_drafter_and_registry():
    d = ModelDrafter(lambda toks, k: [11, 12, 13, 14, 15])
    assert d.draft([1, 2, 3], 3) == [11, 12, 13], "hook capped at k"
    assert isinstance(make_drafter("prompt_lookup"), PromptLookupDrafter)
    with pytest.raises(ValueError, match="prompt_lookup"):
        make_drafter("no_such_drafter")


def test_accept_greedy_edges():
    """The acceptance rule IS the exactness proof: every emitted token
    is the verifier's argmax, so full/partial/zero acceptance all emit
    exactly what plain greedy decode would have."""
    emitted, m = accept_greedy([1, 2, 3], [1, 2, 3, 7])
    assert emitted == [1, 2, 3, 7] and m == 3, "full accept + bonus"
    emitted, m = accept_greedy([1, 9, 3], [1, 2, 3, 7])
    assert emitted == [1, 2] and m == 1, "correction token at the split"
    emitted, m = accept_greedy([9], [5, 6])
    assert emitted == [5] and m == 0, "zero accept still emits one"
    emitted, m = accept_greedy([], [4])
    assert emitted == [4] and m == 0, "draft-free row decodes plain"


def test_spec_engine_validates(model_and_params):
    cfg, _, params = model_and_params
    with pytest.raises(ValueError, match="spec_k must be >= 1"):
        ServingEngine(cfg, params, serve=ServeConfig(
            block_size=8, num_blocks=0, spec=True, spec_k=0))
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, spec=True, spec_k=4))
    assert eng.spec_w == 8, "next pow2 >= k+1"
    with pytest.raises(ValueError, match="spec_k must be >= 0"):
        eng.submit(np.arange(1, 5), max_new_tokens=2, spec_k=-1)


@pytest.mark.parametrize("shard", [1, 2])
def test_speculative_oracle_with_rollback(model_and_params, shard):
    """THE acceptance oracle: speculative decode over templated prompts
    with forced evictions, prefix hits and CoW tails — with both
    acceptance AND rollback exercised — emits bit-identical streams to
    the no-cache reference, at shard factors 1 and 2."""
    cfg, model, params = model_and_params
    mesh = None if shard == 1 else _shard_mesh(2)
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=4, num_blocks=25, token_budget=64, watermark=0,
        decode_tiers=(1, 2, 4), prefill_chunk=8, spec=True, spec_k=4),
        mesh=mesh)
    rs = np.random.RandomState(11)
    prompts = _template_prompts(rs, 4, t_len=11, s_lo=2, s_hi=5)
    ids = [eng.submit(p, max_new_tokens=14) for p in prompts]
    out = eng.run()
    assert eng.scheduler.evictions > 0, "pool sized to force evictions"
    assert eng.scheduler.prefix_hit_blocks > 0, "templates must hit"
    assert eng.spec_accepted_tokens > 0, "drafts must land"
    assert eng.spec_rolled_back_tokens > 0, "rollback must be in the loop"
    for i, rid in enumerate(ids):
        np.testing.assert_array_equal(
            out[rid], ref_decode(model, params, prompts[i], 14),
            err_msg=f"req {i} (shard factor {shard})")


def test_spec_menu_compile_free_under_randomized_load(model_and_params):
    """k as a STATIC menu axis: spec on adds exactly |decode_tiers| x
    |page_tiers| verify-width programs to the warmup menu, and a
    512-request randomized templated load adds ZERO executable-cache
    misses — per-request draft lengths vary every step, the program
    keys never do.  (Two decode tiers keep the warmup bill small; the
    menu arithmetic below is tier-count-generic.)"""
    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=256, watermark=2,
        decode_tiers=(2, 8), prefill_chunk=16, spec=True,
        spec_k=4))
    menu = len(eng.decode_tiers) * (
        len(eng.chunk_tiers) + 2 * len(eng.page_tiers))
    warmed = eng.warmup()
    assert warmed == menu == eng.program_count
    miss0 = _instr.EXEC_CACHE.labels("miss").get()
    rs = np.random.RandomState(4)
    templates = [rs.randint(1, 97, size=24).astype(np.int32)
                 for _ in range(4)]
    load = _templated_load(rs, 512, templates)
    for prompt, gen in load:
        eng.submit(prompt, max_new_tokens=gen)
    out = eng.run()
    assert len(out) == 512 and all(len(v) >= 1 for v in out.values())
    assert eng.program_count == menu
    assert _instr.EXEC_CACHE.labels("miss").get() == miss0
    assert eng.spec_steps > 0 and eng.spec_drafted_tokens > 0
    assert eng.spec_rolled_back_tokens > 0
    for rid in (0, 99, 511):  # spot-check the oracle at this scale
        prompt, gen = load[rid]
        np.testing.assert_array_equal(
            out[rid], ref_decode(model, params, prompt, gen))


def test_spec_cache_state_lags_one_and_republishes(model_and_params):
    """The tokens_in_cache invariant generalizes to k-token steps: the
    last emitted token is ALWAYS the verifier's bonus/correction token
    whose K/V the step never fed, so cache state lags the stream by
    exactly one in decode whatever k landed — and the block table never
    retains a speculative tail past a settle.  Prefix publication
    (which trusts tokens_in_cache) therefore re-admits a repeat prompt
    through the cache with a bit-identical stream."""
    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=4, num_blocks=0, token_budget=64, watermark=2,
        decode_tiers=(1, 2), spec=True, spec_k=4))
    prompt = np.asarray([5, 6, 7, 5, 6, 7, 5, 6], np.int32)  # draftable
    rid = eng.submit(prompt, max_new_tokens=12)
    while eng.step():
        for s in eng.scheduler.running:
            if s.in_decode:
                assert s.tokens_in_cache == s.length - 1
                assert blocks_for(s.length, 4) <= len(s.blocks) \
                    <= blocks_for(s.length + 1, 4), \
                    "stale speculative tail in the block table"
    out1 = eng.results[rid]
    assert eng.spec_drafted_tokens > 0, "the load must actually draft"
    hits0 = eng.scheduler.prefix_hit_blocks
    rid2 = eng.submit(prompt, max_new_tokens=12)
    eng.run()
    assert eng.scheduler.prefix_hit_blocks > hits0, \
        "post-spec published blocks must re-admit"
    np.testing.assert_array_equal(eng.results[rid2], out1)
    np.testing.assert_array_equal(
        out1, ref_decode(model, params, prompt, 12))


def test_spec_k_per_request_opt_out(model_and_params):
    """submit(spec_k=0) turns speculation off for ONE request without
    touching the engine default — same stream either way."""
    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=64, watermark=2,
        decode_tiers=(1,), prefill_tiers=(16,), spec=True, spec_k=4))
    prompt = np.asarray([3, 4, 3, 4, 3, 4, 3, 4], np.int32)
    rid = eng.submit(prompt, max_new_tokens=10, spec_k=0)
    eng.run()
    assert eng.spec_drafted_tokens == 0 and eng.spec_steps == 0
    np.testing.assert_array_equal(
        eng.results[rid], ref_decode(model, params, prompt, 10))
    rid2 = eng.submit(prompt, max_new_tokens=10)  # engine default k
    eng.run()
    assert eng.spec_drafted_tokens > 0
    np.testing.assert_array_equal(eng.results[rid2], eng.results[rid])


def test_router_threads_spec_k(model_and_params):
    """The fleet path carries the per-request knob end to end: router
    -> replica -> engine, including on a spec-enabled replica."""
    from horovod_tpu.fleet.router import FleetRouter

    cfg, model, params = model_and_params

    def build():
        return ServingEngine(cfg, params, serve=ServeConfig(
            block_size=8, num_blocks=0, token_budget=64, watermark=2,
            decode_tiers=(1,), prefill_tiers=(16,), spec=True,
            spec_k=4))

    router = FleetRouter(build, replicas=1, mode="round_robin")
    eng = router.replicas[0].engine
    prompt = np.asarray([3, 4, 3, 4, 3, 4, 3, 4], np.int32)
    g0 = router.submit(prompt, 10, spec_k=0)
    while router.step() or router._placed:
        pass
    assert eng.spec_drafted_tokens == 0, "opt-out must reach the engine"
    g1 = router.submit(prompt, 10)
    while router.step() or router._placed:
        pass
    assert eng.spec_drafted_tokens > 0, "default k must reach the engine"
    np.testing.assert_array_equal(router.results[g0], router.results[g1])
    np.testing.assert_array_equal(
        router.results[g0], ref_decode(model, params, prompt, 10))


# -- KV snapshot / migration (ISSUE 18) ---------------------------------------


@pytest.mark.parametrize("shard", [1, 2])
@pytest.mark.parametrize("spec", [False, True])
def test_kv_migration_resumes_token_identical(model_and_params, shard,
                                              spec):
    """THE recovery oracle (ISSUE 18): a request interrupted mid-decode,
    exported (verified stream + KV block snapshot) and re-registered in
    a FRESH engine resumes bit-identical to uninterrupted decode — the
    warm path serves the re-prefill from the imported cache with zero
    post-warmup compiles — at shard factors 1 and 2, spec on and off."""
    cfg, model, params = model_and_params
    mesh = None if shard == 1 else _shard_mesh(2)

    def build():
        return ServingEngine(cfg, params, serve=ServeConfig(
            block_size=4, num_blocks=25, token_budget=64, watermark=0,
            decode_tiers=(1, 2), prefill_chunk=8, spec=spec, spec_k=4),
            mesh=mesh)

    src = build()
    rs = np.random.RandomState(18)
    prompt = rs.randint(1, 97, size=13).astype(np.int32)
    total = 18
    rid = src.submit(prompt, max_new_tokens=total)
    while True:  # interrupt with >= 2 full blocks of verified stream
        seq = next((s for s in src.scheduler.running
                    if s.req.id == rid), None)
        if seq is not None and len(seq.generated) >= 8:
            break
        assert src.step(), "request finished before the interruption"
    tokens, snap, _arr = src.export_requests()[rid]
    gen = np.asarray(tokens[len(prompt):], np.int32)
    assert gen.size >= 8
    assert snap is not None and len(snap["hashes"]) >= 2
    tgt = build()
    tgt.warmup()
    miss0 = _instr.EXEC_CACHE.labels("miss").get()
    assert tgt.import_kv(snap) == len(snap["hashes"])
    rid2 = tgt.submit(np.concatenate([prompt, gen]),
                      max_new_tokens=total - gen.size)
    out = tgt.run()
    assert tgt.scheduler.prefix_hit_blocks >= len(snap["hashes"]) - 1, \
        "the imported chain must serve the re-prefill (warm path)"
    assert _instr.EXEC_CACHE.labels("miss").get() == miss0, \
        "the recovery path must not compile"
    np.testing.assert_array_equal(
        np.concatenate([gen, out[rid2]]),
        ref_decode(model, params, prompt, total),
        err_msg=f"shard={shard} spec={spec}")


def test_import_blocks_verifies_chain_and_rolls_back():
    """The serve.migrate corrupt-detection contract: one flipped token
    anywhere in the snapshot fails the chain-hash recomputation BEFORE
    any allocator state changes; a pool too small mid-chain rolls back
    every reference and registration taken so far."""
    a = BlockAllocator(12, block_size=4)
    owner = a.alloc(2)
    h0 = a.register(owner[0], PREFIX_HASH_ROOT, [1, 2, 3, 4])
    a.register(owner[1], h0, [5, 6, 7, 8])
    snap = a.export_blocks(owner, [1, 2, 3, 4, 5, 6, 7, 8])
    with pytest.raises(ValueError, match="need exactly"):
        a.export_blocks(owner, [1, 2, 3])
    b = BlockAllocator(12, block_size=4)
    bad = dict(snap)
    bad["tokens"] = [1, 2, 3, 4, 5, 6, 7, 9]  # one corrupted token
    free0, cached0 = b.free_blocks, b.cached_blocks
    with pytest.raises(ValueError, match="chain-hash mismatch"):
        b.import_blocks(bad)
    assert (b.free_blocks, b.cached_blocks) == (free0, cached0)
    with pytest.raises(ValueError, match="format"):
        b.import_blocks({**snap, "format": "nope"})
    with pytest.raises(ValueError, match="block_size"):
        b.import_blocks({**snap, "block_size": 8})
    # the good snapshot imports as two FRESH registered blocks...
    blocks, fresh = b.import_blocks(snap)
    assert len(blocks) == 2 and [i for i, _ in fresh] == [0, 1]
    b.free(blocks)  # park: matchable like any cached prefix
    m, _ = b.match_prefix([1, 2, 3, 4, 5, 6, 7, 8, 9], max_blocks=2)
    assert m == blocks
    b.free(m)
    # ...and a re-import is all index hits (nothing fresh to fill)
    blocks2, fresh2 = b.import_blocks(snap)
    assert blocks2 == blocks and fresh2 == []
    b.free(blocks2)
    # pool exhausted mid-chain: all-or-nothing rollback
    c = BlockAllocator(2, block_size=4)  # 1 usable block (0 is trash)
    free0, cached0 = c.free_blocks, c.cached_blocks
    with pytest.raises(ValueError, match="pool exhausted"):
        c.import_blocks(snap)
    assert (c.free_blocks, c.cached_blocks) == (free0, cached0)
    # prefix cache off: the chain could never be matched — refuse
    off = BlockAllocator(12, block_size=4, prefix_cache=False)
    with pytest.raises(ValueError, match="prefix cache"):
        off.import_blocks(snap)
    a.free(owner)


def test_truncate_tail_registered_tail_parks_matchable():
    """Satellite audit (ISSUE 18): a REGISTERED block released by
    truncate_tail must PARK on the LRU — still indexed, still matching
    exactly its registered tokens — never reach the free list while
    cached; an UNREGISTERED tail block returns to the free list and is
    never matchable."""
    a = BlockAllocator(10, block_size=4)
    table = a.alloc(3)
    h0 = a.register(table[0], PREFIX_HASH_ROOT, [1, 2, 3, 4])
    a.register(table[1], h0, [5, 6, 7, 8])  # registered mid-block
    free0 = a.free_blocks
    kept = a.truncate_tail(table, 4)  # drop registered + unregistered
    assert kept == table[:1]
    # both tails count reclaimable, but the registered one PARKS (LRU,
    # still indexed) while the unregistered one hits the plain free list
    assert a.free_blocks == free0 + 2
    assert a.ref(table[1]) == 0 and a.cached_blocks == 2
    # the parked block re-matches with exactly its registered tokens
    m, _ = a.match_prefix([1, 2, 3, 4, 5, 6, 7, 8, 9], max_blocks=2)
    assert m == table[:2]
    # ...and never with different content behind the same chain
    m2, _ = a.match_prefix([1, 2, 3, 4, 9, 9, 9, 9, 9], max_blocks=2)
    assert m2 == table[:1]
    a.free(m2)
    # while matched (ref > 0) a full-pool drain must not hand it out
    rest = a.alloc(a.free_blocks)
    assert table[1] not in rest
    a.free(rest)
    a.free(m)
    a.free(table[:1])
