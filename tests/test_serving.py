"""Continuous-batching serving: the batched-decode oracle + bounded
compiled-program set (ISSUE 8 acceptance).

The oracle (the serving exactness contract, docs/SERVING.md): greedy
decode is deterministic, so continuous batching over the paged KV
cache — whatever admission order, padding tier, eviction or block-table
reuse the scheduler lands on — must emit token-for-token what
one-at-a-time full-context decode emits.  Any paging bug (wrong block,
stale page, bad tail-block offset, a padded slot leaking into a real
row) breaks exactness immediately, which is why the oracle is the test
rather than a statistical check.

Program bounding: the padding-tier menu caps the compiled-program set
by |decode_tiers| x (|prefill_tiers| + 1) regardless of the request
distribution; the 512-request randomized load pins it via the PR-1
executable-cache counters (warmup compiles the menu, traffic must be
all hits).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.metrics import instruments as _instr
from horovod_tpu.models.transformer import Transformer, TransformerConfig
from horovod_tpu.serving import (
    BlockAllocator, Request, ServeConfig, ServingEngine, blocks_for,
    modeled_decode_read_bytes,
)


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(
        vocab_size=97, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=8, max_seq_len=64, dtype=jnp.float32,
        attention_impl="dot", causal=True)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32), train=False)["params"]
    return cfg, model, params


def ref_decode(model, params, prompt, n, eos_id=None):
    """One-at-a-time full-context greedy decode (no cache at all)."""
    toks = list(np.asarray(prompt))
    out = []
    for _ in range(n):
        x = jnp.asarray(np.asarray(toks, np.int32))[None]
        logits = model.apply({"params": params}, x, train=False)
        t = int(jnp.argmax(logits[0, -1].astype(jnp.float32)))
        toks.append(t)
        out.append(t)
        if eos_id is not None and t == eos_id:
            break
    return np.asarray(out, np.int32)


def _prompts(rs, n, lo=3, hi=20):
    return [rs.randint(1, 97, size=rs.randint(lo, hi)).astype(np.int32)
            for _ in range(n)]


# -- the batched-decode oracle ----------------------------------------------


def test_continuous_batched_decode_matches_one_at_a_time(model_and_params):
    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=128, watermark=2,
        decode_tiers=(1, 2, 4)))
    rs = np.random.RandomState(0)
    prompts = _prompts(rs, 6)
    gens = [10, 3, 7, 10, 1, 5]
    ids = [eng.submit(p, max_new_tokens=g) for p, g in zip(prompts, gens)]
    out = eng.run()
    for i, rid in enumerate(ids):
        ref = ref_decode(model, params, prompts[i], gens[i])
        np.testing.assert_array_equal(out[rid], ref, err_msg=f"req {i}")


def test_oracle_pinned_across_evictions_and_block_reuse(model_and_params):
    """A pool too small for the batch forces LIFO recompute evictions;
    freed blocks are immediately reallocated to other sequences (table
    reuse), and the evicted sequence re-prefills prompt+generated.
    Token streams must be pinned through all of it."""
    cfg, model, params = model_and_params
    # 16 allocatable blocks of 4 = 64 cache slots for 3 sequences that
    # each want prompt+18 tokens (~7 blocks): admission overcommits,
    # growth evicts
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=4, num_blocks=17, token_budget=64, watermark=0,
        decode_tiers=(1, 2, 4)))
    rs = np.random.RandomState(1)
    prompts = _prompts(rs, 3, lo=10, hi=14)
    ids = [eng.submit(p, max_new_tokens=18) for p in prompts]
    out = eng.run()
    assert eng.scheduler.evictions > 0, "pool was sized to force evictions"
    for i, rid in enumerate(ids):
        ref = ref_decode(model, params, prompts[i], 18)
        np.testing.assert_array_equal(out[rid], ref, err_msg=f"req {i}")


def test_eos_stops_generation(model_and_params):
    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=128, watermark=1,
        decode_tiers=(1, 2)))
    rs = np.random.RandomState(2)
    prompt = _prompts(rs, 1)[0]
    ref = ref_decode(model, params, prompt, 16)
    eos = int(ref[4])  # stop at the 5th token the model will emit
    rid = eng.submit(prompt, max_new_tokens=16, eos_id=eos)
    out = eng.run()
    np.testing.assert_array_equal(
        out[rid], ref_decode(model, params, prompt, 16, eos_id=eos))
    assert out[rid][-1] == eos and len(out[rid]) <= 16


def test_staged_source_path_matches_submit_path(model_and_params):
    """attach_source (DevicePrefetcher staging) and direct submit are
    the same requests — same tokens out."""
    cfg, model, params = model_and_params
    rs = np.random.RandomState(3)
    prompts = _prompts(rs, 5)
    reqs = [Request(id=i, prompt=p, max_new_tokens=6)
            for i, p in enumerate(prompts)]
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=128, watermark=2,
        decode_tiers=(1, 2, 4)))
    eng.attach_source(iter(reqs))
    out = eng.run()
    for i, p in enumerate(prompts):
        np.testing.assert_array_equal(
            out[i], ref_decode(model, params, p, 6), err_msg=f"req {i}")


def test_submit_validates(model_and_params):
    cfg, _, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, decode_tiers=(1, 2)))
    with pytest.raises(ValueError, match="empty"):
        eng.submit(np.zeros((0,), np.int32), max_new_tokens=4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(np.ones((4,), np.int32), max_new_tokens=0)
    with pytest.raises(ValueError, match="max_seq_len"):
        eng.submit(np.ones((60,), np.int32), max_new_tokens=10)
    with pytest.raises(ValueError, match="causal"):
        ServingEngine(
            TransformerConfig(causal=False, dtype=jnp.float32), params)


def test_oversize_prefill_tier_dropped(model_and_params):
    """A tier > max_seq_len would index block-table columns past
    max_blocks and corrupt real KV through the clamped gather — the
    engine must drop it (warning) rather than compile it."""
    cfg, _, params = model_and_params  # max_seq_len = 64
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, prefill_tiers=(32, 100),
        decode_tiers=(1, 2)))
    assert max(eng.prefill_tiers) <= cfg.max_seq_len
    assert eng.prefill_tiers == (32, 64)


def test_sourced_id_collision_rejected(model_and_params):
    """A sourced request reusing an id already handed out by submit()
    must be rejected, not silently clobber that request's results."""
    cfg, _, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, decode_tiers=(1, 2)))
    rid = eng.submit(np.ones((4,), np.int32), max_new_tokens=2)
    eng.attach_source(iter(
        [Request(id=rid, prompt=np.ones((4,), np.int32),
                 max_new_tokens=2)]))
    with pytest.raises(ValueError, match="already in use"):
        eng.run()


# -- bounded compiled-program set under randomized load ----------------------


def test_program_count_bounded_under_randomized_load(model_and_params):
    """512 randomized requests; the tier menu bounds the compiled set
    and the PR-1 executable-cache counters prove steady state is all
    hits: warmup compiles the menu, traffic adds ZERO misses."""
    cfg, model, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=0, token_budget=256, watermark=2,
        decode_tiers=(1, 2, 4, 8)))
    menu = (len(eng.prefill_tiers) + 1) * len(eng.decode_tiers)
    warmed = eng.warmup()
    assert warmed == menu == eng.program_count
    hits0 = _instr.EXEC_CACHE.labels("hit").get()
    miss0 = _instr.EXEC_CACHE.labels("miss").get()
    rs = np.random.RandomState(4)
    for p in _prompts(rs, 512, lo=3, hi=41):
        eng.submit(p, max_new_tokens=int(rs.randint(1, 7)))
    out = eng.run()
    assert len(out) == 512 and all(len(v) >= 1 for v in out.values())
    assert eng.program_count == menu, (
        f"{eng.program_count} programs compiled; menu bounds it to {menu}")
    assert _instr.EXEC_CACHE.labels("miss").get() == miss0
    assert _instr.EXEC_CACHE.labels("hit").get() > hits0
    # spot-check the oracle still holds at this scale
    for rid in (0, 99, 511):
        prompt = None
        rs2 = np.random.RandomState(4)
        for i, p in enumerate(_prompts(rs2, 512, lo=3, hi=41)):
            n = int(rs2.randint(1, 7))
            if i == rid:
                prompt, gen = p, n
        np.testing.assert_array_equal(
            out[rid], ref_decode(model, params, prompt, gen))


# -- allocator / kv-model units ---------------------------------------------


def test_block_allocator_contract():
    a = BlockAllocator(8, block_size=4)
    assert a.capacity == 7 and a.free_blocks == 7
    got = a.alloc(3)
    assert len(got) == 3 and 0 not in got, "block 0 is the trash block"
    assert a.alloc(5) is None, "all-or-nothing"
    assert a.free_blocks == 4
    assert a.occupancy() == pytest.approx(3 / 7)
    assert a.peak_occupancy == pytest.approx(3 / 7)
    a.free(got)
    assert a.free_blocks == 7 and a.occupancy() == 0.0
    assert a.peak_occupancy == pytest.approx(3 / 7), "peak is sticky"
    with pytest.raises(ValueError, match="double free"):
        a.free([a.alloc(1)[0]] * 2)
    with pytest.raises(ValueError, match="out of range"):
        a.free([0])
    with pytest.raises(ValueError, match=">= 2"):
        BlockAllocator(1)
    assert blocks_for(9, 4) == 3 and blocks_for(8, 4) == 2


def test_modeled_decode_read_bytes_reductions():
    """The serve_bench kv_model column: paging (vs max-seq reservation),
    GQA (vs MHA) and windowing each cut modeled decode reads."""
    kw = dict(block_size=16, num_heads=8, num_kv_heads=2, head_dim=64,
              num_layers=4, dtype_bytes=2, max_seq_len=2048)
    m = modeled_decode_read_bytes(256, **kw)
    # 256 of 2048 tokens resident, GQA 4x: >= 16x kernel-read reduction
    assert m["full_bytes"] >= 16 * m["paged_bytes"]
    assert m["pages_read"] == 16
    # the window=None gather copy is max_blocks wide (static shapes):
    # only the GQA factor survives in the gather term
    assert m["pages_gathered"] == 2048 // 16
    assert m["full_bytes"] == 4 * m["gathered_bytes"]
    w = modeled_decode_read_bytes(1024, window=128, **kw)
    nw = modeled_decode_read_bytes(1024, **kw)
    assert w["paged_bytes"] < nw["paged_bytes"] / 4, "window caps reads"
    assert w["pages_read"] <= 128 // 16 + 2
    assert w["pages_gathered"] <= 128 // 16 + 2, "window truncates gather"


def test_pool_watermark_defers_admission(model_and_params):
    """With a deep queue and a watermark, admission stops before the
    pool drains: running sequences keep headroom to grow."""
    cfg, _, params = model_and_params
    eng = ServingEngine(cfg, params, serve=ServeConfig(
        block_size=8, num_blocks=17, token_budget=256, watermark=6,
        decode_tiers=(1, 2, 4, 8)))
    for _ in range(8):
        eng.submit(np.ones((8,), np.int32), max_new_tokens=2)
    admitted = eng.scheduler.admit()
    # each sequence needs 2 blocks (8+1 tokens @ block 8); 16 free,
    # watermark 6 -> at most 5 admitted (16 - 5*2 = 6)
    assert 0 < len(admitted) <= 5
    assert eng.allocator.free_blocks >= 6
