"""RayExecutor / spark-run contract tests.

Reference analog: test/single/test_ray.py (SURVEY.md §4) — executor
start/run/shutdown semantics with per-rank results.  Ray itself is not
in this image, so the local backend (same contract) is what runs; the
spark module's no-pyspark guidance is asserted too.
"""

import os
import sys

import pytest

from envguards import requires_multiprocess_collectives

import horovod_tpu.ray as hvd_ray
import horovod_tpu.spark as hvd_spark
from tests.executor_fns import rank_report


@pytest.mark.integration
@requires_multiprocess_collectives  # spawns an N-proc world running collectives
def test_ray_executor_local_backend(monkeypatch):
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    executor = hvd_ray.RayExecutor(num_workers=2)
    assert executor._backend == "local"  # ray absent in this image
    executor.start()
    results = executor.run(rank_report, args=[7])
    executor.shutdown()
    assert len(results) == 2
    # rank order preserved; collective result agrees everywhere
    assert [r["rank"] for r in results] == [0, 1]
    assert all(r["world"] == 2 for r in results)
    assert all(abs(r["allreduce_sum"] - 2.0) < 1e-6 for r in results)
    assert all(r["arg"] == 7 for r in results)


@pytest.mark.integration
@requires_multiprocess_collectives  # spawns an N-proc world running collectives
def test_elastic_ray_executor_local_backend(monkeypatch):
    """ElasticRayExecutor contract on the subprocess backend: callable
    discovery feeds the same ElasticDriver as tpurun --host-discovery-
    script; per-rank results of the final world come back in rank order
    (reference: horovod/ray/elastic.py ElasticRayExecutor)."""
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    executor = hvd_ray.ElasticRayExecutor(
        min_workers=2, max_workers=2,
        discovery=lambda: [("localhost", 2)],
    )
    executor.start()
    results = executor.run(rank_report, args=[3])
    executor.shutdown()
    assert len(results) == 2
    assert [r["rank"] for r in results] == [0, 1]
    assert all(r["world"] == 2 for r in results)
    assert all(abs(r["allreduce_sum"] - 2.0) < 1e-6 for r in results)


def test_ray_executor_requires_start():
    executor = hvd_ray.RayExecutor(num_workers=1)
    with pytest.raises(RuntimeError):
        executor.run(rank_report, args=[0])


def test_spark_run_without_pyspark_raises_helpfully():
    with pytest.raises(ImportError) as e:
        hvd_spark.run(rank_report, args=(0,), num_proc=2)
    assert "RayExecutor" in str(e.value)
