"""Direct unit tests for utils/jax_compat.py (the cross-version shim).

Previously only covered indirectly through test_spmd_collectives; these
pin the shim's own contract: install on jax<0.5 (this image), no-op when
jax already has the modern spellings, and faithful delegation of
``shard_map``'s renamed keyword and ``jax.lax.axis_size``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd  # noqa: F401 — init fixture + shim install
from horovod_tpu.common import basics
from horovod_tpu.utils import jax_compat


def _world_mesh():
    return basics.topology().mesh()


def test_shim_installed_at_package_import():
    """horovod_tpu/__init__ runs install(); both spellings must exist
    regardless of the underlying jax version."""
    assert hasattr(jax, "shard_map")
    assert hasattr(jax.lax, "axis_size")


def test_install_is_idempotent_and_never_overwrites(monkeypatch):
    """On a jax that already has the attributes (>= 0.5 or an earlier
    install), install() must be a no-op — nothing overwritten."""
    sentinel_sm = object()
    sentinel_as = object()
    monkeypatch.setattr(jax, "shard_map", sentinel_sm, raising=False)
    monkeypatch.setattr(jax.lax, "axis_size", sentinel_as, raising=False)
    jax_compat.install()
    assert jax.shard_map is sentinel_sm
    assert jax.lax.axis_size is sentinel_as


def test_install_publishes_wrapper_when_missing(monkeypatch):
    """Simulate the jax<0.5 state: no jax.shard_map attribute.  install()
    must publish a working adapter (on this image that IS the live path;
    on modern jax the monkeypatched deletion simulates it)."""
    monkeypatch.delattr(jax, "shard_map")
    assert not hasattr(jax, "shard_map")
    jax_compat.install()
    assert hasattr(jax, "shard_map")
    # and the published callable actually runs a sharded computation
    mesh = _world_mesh()
    n = len(mesh.devices.ravel())
    x = jnp.arange(4 * n, dtype=jnp.float32)

    def body(x):
        return jax.lax.psum(x, "hvd")

    f = jax.shard_map(body, mesh=mesh, in_specs=(P("hvd"),),
                      out_specs=P("hvd"), check_vma=False)
    out = f(x)
    expect = np.tile(x.reshape(n, -1).sum(axis=0), n)
    np.testing.assert_allclose(np.asarray(out), expect)


def test_shard_map_accepts_check_vma_keyword():
    """The modern ``check_vma`` keyword must be honored whichever
    underlying implementation serves it (renamed to check_rep on
    legacy jax) — passing it must not raise."""
    mesh = _world_mesh()

    def body(x):
        return x * 2.0

    n = len(mesh.devices.ravel())
    x = jnp.ones((n, 2), jnp.float32)
    for check_vma in (False, None):
        f = jax.shard_map(body, mesh=mesh, in_specs=(P("hvd"),),
                          out_specs=P("hvd"), check_vma=check_vma)
        np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x) * 2.0)


def test_shard_map_delegates_semantics():
    """Per-shard semantics must match the legacy implementation exactly:
    each shard sees only its slice."""
    mesh = _world_mesh()
    n = len(mesh.devices.ravel())

    def body(x):
        # shard-local shape: the world axis is split away
        assert x.shape[0] == 1
        return x + jax.lax.axis_index("hvd").astype(jnp.float32)

    x = jnp.zeros((n, 3), jnp.float32)
    out = np.asarray(jax.shard_map(
        body, mesh=mesh, in_specs=(P("hvd"),), out_specs=P("hvd"),
        check_vma=False)(x))
    np.testing.assert_allclose(out, np.arange(n)[:, None] * np.ones(3))


def test_axis_size_resolves_inside_shard_map():
    mesh = _world_mesh()
    n = len(mesh.devices.ravel())

    def body(x):
        return x + jnp.float32(jax.lax.axis_size("hvd"))

    out = np.asarray(jax.shard_map(
        body, mesh=mesh, in_specs=(P("hvd"),), out_specs=P("hvd"),
        check_vma=False)(jnp.zeros((n,), jnp.float32)))
    np.testing.assert_allclose(out, np.full(n, n, np.float32))


def test_axis_size_installer_noop_when_present(monkeypatch):
    sentinel = object()
    monkeypatch.setattr(jax.lax, "axis_size", sentinel, raising=False)
    jax_compat._install_axis_size()
    assert jax.lax.axis_size is sentinel


def test_shard_map_installer_handles_absent_legacy_module(monkeypatch):
    """On a hypothetical jax with NEITHER spelling, install() must leave
    jax untouched instead of publishing a broken attribute."""
    import builtins

    monkeypatch.delattr(jax, "shard_map")
    real_import = builtins.__import__

    def no_legacy(name, *a, **k):
        if name.startswith("jax.experimental.shard_map"):
            raise ImportError("simulated: no legacy shard_map")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_legacy)
    jax_compat._install_shard_map()
    assert not hasattr(jax, "shard_map")
    monkeypatch.setattr(builtins, "__import__", real_import)
    jax_compat.install()  # restore for the rest of the suite
    assert hasattr(jax, "shard_map")