"""Pallas flash-attention parity tests (interpret mode on the CPU mesh;
the identical kernel compiles for real on TPU — tools/flash_bench.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models.transformer import causal_dot_attention
from horovod_tpu.ops.flash_attention import flash_attention


def _qkv(b, s, h, d, dtype, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, h, d)
    return tuple(
        jax.random.normal(kk, shape, jnp.float32).astype(dtype) for kk in ks
    )


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("s", [256, 384])
def test_flash_matches_dense_causal(dtype, s):
    q, k, v = _qkv(2, s, 2, 64, dtype)
    ref = causal_dot_attention(q, k, v)
    out = flash_attention(q, k, v, block_q=128, block_k=128)
    tol = 2e-2 if dtype == jnp.bfloat16 else 2e-5
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=tol, atol=tol,
    )


def test_flash_unpadded_sequence():
    # S=200 pads to 256; pad keys must be masked and pad rows dropped
    q, k, v = _qkv(1, 200, 2, 64, jnp.float32, seed=1)
    ref = causal_dot_attention(q, k, v)
    out = flash_attention(q, k, v, block_q=128, block_k=128)
    assert out.shape == (1, 200, 2, 64)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_transformer_flash_impl_matches_dot():
    """attention_impl='flash' must produce the same transformer forward
    as the dense default (same params, same logits)."""
    from horovod_tpu.models.transformer import Transformer, TransformerConfig

    cfg = dict(vocab_size=64, num_heads=2, head_dim=16,
               num_layers=2, dtype=jnp.float32)
    m_dot = Transformer(TransformerConfig(**cfg, attention_impl="dot"))
    m_flash = Transformer(TransformerConfig(**cfg, attention_impl="flash"))
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 96), 0, 64)
    variables = m_dot.init(jax.random.PRNGKey(1), tokens)
    out_dot = m_dot.apply(variables, tokens)
    out_flash = m_flash.apply(variables, tokens)
    np.testing.assert_allclose(
        np.asarray(out_flash), np.asarray(out_dot), rtol=2e-4, atol=2e-4
    )


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 1e-3),
                                       (jnp.bfloat16, 1e-1)])
def test_flash_gradients_match_dense(dtype, tol):
    """Training through the kernel: custom_vjp gradients must match the
    dense path's (backward recomputes with the kernel's upcast numerics;
    bf16 compares loosely against the model's dense reference)."""
    q, k, v = _qkv(1, 256, 2, 32, dtype, seed=3)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v).astype(jnp.float32) ** 2).sum()

    def loss_dense(q, k, v):
        return (
            causal_dot_attention(q, k, v).astype(jnp.float32) ** 2
        ).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=tol, atol=tol,
        )


def test_flash_gradients_unpadded_sequence():
    """Backward over a padded sequence: pad rows/keys must contribute
    zero gradient (S=200 pads to 256 inside the kernels)."""
    q, k, v = _qkv(1, 200, 2, 32, jnp.float32, seed=5)

    gf = jax.grad(
        lambda a, b, c: (flash_attention(a, b, c) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    gd = jax.grad(
        lambda a, b, c: (causal_dot_attention(a, b, c) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3
        )


def test_flash_non_causal():
    q, k, v = _qkv(1, 256, 2, 64, jnp.float32, seed=2)
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(float(d))
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    ref = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v)
    out = flash_attention(q, k, v, causal=False)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("causal", [True, False])
def test_flash_sliding_window_matches_dot(causal):
    """Windowed block-skip parity: same values as the windowed dot
    oracle, with the window crossing block boundaries (S=384, 256-blocks,
    W=200) so the skip ranges and tile masks both matter."""
    q, k, v = _qkv(1, 384, 2, 32, jnp.float32, seed=5)
    out = flash_attention(q, k, v, causal=causal, window=200)
    ref = causal_dot_attention(q, k, v, causal=causal, window=200)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_sliding_window_gradients():
    """Windowed backward (skip ranges in both bwd kernels) matches
    autodiff through the windowed dot oracle."""
    q, k, v = _qkv(1, 320, 2, 32, jnp.float32, seed=6)
    gf = jax.grad(
        lambda a, b, c: (
            flash_attention(a, b, c, window=150) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    gd = jax.grad(
        lambda a, b, c: (
            causal_dot_attention(a, b, c, window=150) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3
        )


def test_flash_window_small_blocks():
    """Window much smaller than a block plus a window smaller than the
    sequence tail: every skip-bound edge case in one sweep."""
    for s, w in ((256, 17), (300, 64), (128, 1)):
        q, k, v = _qkv(1, s, 1, 32, jnp.float32, seed=s)
        out = flash_attention(q, k, v, window=w, block_q=128, block_k=128)
        ref = causal_dot_attention(q, k, v, window=w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5, err_msg=f"s={s} w={w}")


def test_flash_non_causal_gradients():
    """Encoder-mode backward through the pallas kernels matches autodiff
    through the dot oracle."""
    q, k, v = _qkv(1, 192, 2, 32, jnp.float32, seed=3)
    gf = jax.grad(
        lambda a, b, c: (flash_attention(a, b, c, causal=False) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    gd = jax.grad(
        lambda a, b, c: (
            causal_dot_attention(a, b, c, causal=False) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3
        )
