"""The jax-requiring half of the ``programs`` analysis pass.

tests/test_static_analysis.py proves the pure check helpers catch
injected drift (slice-spanning collective, off-menu key, byte drift);
this file runs the REAL verification legs against the framework's own
lowered programs — the same code path ``tools/verify_programs.py``
(the program-verify CI job) runs at full scale, here scaled down so
tier-1 stays fast:

* training leg — guard/trace byte-identity, zero added collectives
  (plain + ZeRO), overlap interleave;
* hierarchical leg — modeled == measured per-tier bytes of the
  two-level allreduce over the 8-device virtual world;
* serving leg — DCN-exclusion + modeled == measured psum stream per
  tier program + the zero-recompile lint, on a small randomized load.

Marker: ``analysis`` (these ARE the contract checker, jax flavor).
"""

import pytest

from horovod_tpu.analysis import programs

pytestmark = pytest.mark.analysis


def _render(findings):
    return "\n".join(f.render() for f in findings)


def test_training_program_contracts():
    findings = programs._verify_training()
    assert not findings, _render(findings)


def test_hierarchical_allreduce_modeled_equals_measured():
    findings = programs._verify_hierarchical()
    assert not findings, _render(findings)


@pytest.mark.slow
def test_serving_program_contracts_small_load():
    # shards 1 AND 2 plus the speculative engine; the load is small —
    # the 512-request sweep is the program-verify CI job's
    # (tools/verify_programs.py defaults)
    findings = programs._verify_serving((1, 2), requests=24, seed=0)
    assert not findings, _render(findings)
