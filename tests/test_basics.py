"""Lifecycle + topology API tests.

Reference analog: the rank/size assertions at the top of every
test/parallel/test_torch.py case plus test/single/ launcher-free checks
(SURVEY.md §4).
"""

import jax
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.common.topology import WORLD_AXIS


def test_initialized():
    assert hvd.is_initialized()
    assert hvd.size() == 8
    assert hvd.local_size() == 8
    assert hvd.rank() == 0
    assert hvd.cross_size() == 1
    assert hvd.cross_rank() == 0
    assert hvd.local_rank() == 0
    assert hvd.is_homogeneous()


def test_init_idempotent():
    hvd.init()
    assert hvd.size() == 8


def test_build_probes():
    assert hvd.xla_built()
    assert not hvd.nccl_built()
    assert not hvd.mpi_enabled()
    assert not hvd.gloo_built()


def test_world_mesh():
    mesh = hvd.world_mesh()
    assert mesh.axis_names == (WORLD_AXIS,)
    assert mesh.devices.size == 8


def test_hierarchical_mesh():
    mesh = hvd.hierarchical_mesh(num_groups=2)
    assert mesh.axis_names == (hvd.DCN_AXIS, hvd.ICI_AXIS)
    assert mesh.devices.shape == (2, 4)
    with pytest.raises(ValueError):
        hvd.hierarchical_mesh(num_groups=3)


def test_process_sets():
    ps = hvd.add_process_set([0, 1, 2, 3])
    try:
        assert ps.process_set_id is not None and ps.process_set_id > 0
        assert ps.size() == 4
        assert ps.mesh.devices.size == 4
        assert ps.included(2)
        assert not ps.included(7)
        assert ps.rank_in_set(3) == 3
        assert ps.process_set_id in hvd.process_set_ids()
        # duplicate ranks rejected
        with pytest.raises(hvd.HorovodTpuError):
            hvd.add_process_set([0, 1, 2, 3])
    finally:
        hvd.remove_process_set(ps)
    assert ps.process_set_id is None
    # cannot remove the world set
    with pytest.raises(hvd.HorovodTpuError):
        hvd.remove_process_set(hvd.global_process_set)


def test_world_duplicate_process_set_rejected():
    from horovod_tpu.common.process_sets import ProcessSet

    with pytest.raises(hvd.HorovodTpuError):
        hvd.add_process_set(ProcessSet())  # ranks=None == world == set 0


def test_owns_rank():
    topo = hvd.common.basics.topology()
    assert topo.owns_rank(0) and topo.owns_rank(7)
    with pytest.raises(ValueError):
        topo.owns_rank(8)
