"""Disaggregated prefill/decode fleet (ROADMAP item 2, docs/FLEET.md).

The two-tier pipeline's properties, each pinned where it is cheapest:

* engine ``role="prefill"``: warmup compiles the mixed chunk menu ONLY
  (the structural proof the tier can never run a decode step) and
  every request leaves at the handoff boundary with its first token +
  ``kvsnap/1`` chain parked for the router;
* the tentpole oracle: decode on MIGRATED blocks is bit-identical to
  decode on locally-prefilled blocks, at shards 1 and 2, with zero
  post-warmup compiles on both tiers and warm handoffs observed;
* kvsnap ``source`` tag: import rejections name the exporting replica
  (and untagged snapshots stay importable — backward compatible);
* the two-hop deadline filter: remaining-budget checks charge prefill
  queue + handoff + decode-tier delay, not one replica's queue alone;
* edge cases: decode replica dies mid-decode post-handoff (PR-18
  replica-loss recovery, watermark semantics), prefill
  retire-while-draining holds the engine until its handoffs are
  collected, hedged dispatch resolves first-handoff-wins within the
  prefill tier;
* chaos ``serve.handoff``: a corrupted wire degrades every handoff to
  the cold path — outputs stay token-identical, never wrong — and the
  handoff span reaches the flight-recorder bundle on the chaos path;
* modeled == measured: ``modeled_kvsnap_bytes`` reproduces the warm
  handoffs' measured wire bytes exactly (comm_model idiom);
* per-tier scaling: TTFT breaches grow the prefill tier, a
  decode-tokens/s floor breach grows the decode tier, independently.
"""

import time

import numpy as np
import pytest

from horovod_tpu.metrics import instruments as _instr


@pytest.fixture(scope="module")
def disagg_pieces():
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from horovod_tpu.serving import ServeConfig, ServingEngine

    cfg = TransformerConfig(
        vocab_size=97, num_layers=1, num_heads=2, num_kv_heads=2,
        head_dim=8, max_seq_len=48, dtype=jnp.float32,
        attention_impl="dot", causal=True)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32), train=False)["params"]
    serve = ServeConfig(block_size=8, num_blocks=0, token_budget=128,
                        watermark=2, prefill_tiers=(32,),
                        decode_tiers=(1, 2), prefill_chunk=8)

    def build(role="both"):
        return ServingEngine(cfg, params, serve=serve, role=role)

    return cfg, params, serve, build


def _prompts(seed, n, lo=9, hi=14):
    """>= 9 tokens each: at least one FULL block at block_size=8, so
    prefill-complete exports always have a warm-path chain."""
    rs = np.random.RandomState(seed)
    return [rs.randint(1, 90, size=rs.randint(lo, hi)).astype(np.int32)
            for _ in range(n)]


# -- engine: the prefill role ------------------------------------------------


def test_prefill_role_menu_and_handoff_boundary(disagg_pieces):
    cfg, params, serve, build = disagg_pieces
    from horovod_tpu.serving import ServingEngine

    with pytest.raises(ValueError, match="role"):
        ServingEngine(cfg, params, serve=serve, role="decode")
    eng = build(role="prefill")
    menu = len(eng.decode_tiers) * len(eng.chunk_tiers)
    assert eng.warmup() == menu == eng.program_count
    assert all(k[0] == "mixed" for k in eng._progs), \
        "prefill role must never compile a decode/spec program"
    full = build()
    assert full.warmup() > menu, "the full menu is a strict superset"

    prompt = np.arange(1, 12, dtype=np.int32)
    rid = eng.submit(prompt, max_new_tokens=6)
    out = eng.run()
    # the request LEFT at the boundary: no result, one parked handoff
    assert rid not in out and set(eng.handoffs) == {rid}
    stream, snap, _arr = eng.handoffs[rid]
    # stream = prompt + exactly the boundary (first) token
    assert stream.size == prompt.size + 1
    np.testing.assert_array_equal(stream[:prompt.size], prompt)
    assert snap is not None and len(snap["hashes"]) == 1  # 11 // 8
    assert not eng.scheduler.running and not eng.scheduler.pending
    assert eng.program_count == menu, "handoff must not compile"
    # the freed chain PARKED matchable: a repeat template still hits
    assert eng.allocator.peek_prefix(prompt, max_blocks=1) == 1


def test_prefill_role_finishes_short_requests_locally(disagg_pieces):
    """max_new_tokens=1 completes AT the boundary — no handoff, the
    result publishes on the prefill engine like any finished request."""
    _cfg, _params, _serve, build = disagg_pieces
    eng = build(role="prefill")
    eng.warmup()
    rid = eng.submit(np.arange(1, 11, dtype=np.int32), max_new_tokens=1)
    out = eng.run()
    assert rid in out and out[rid].size == 1 and not eng.handoffs


# -- the tentpole oracle -----------------------------------------------------


def test_disagg_token_identity_and_pure_roles(disagg_pieces):
    """Decode on migrated blocks == decode on local blocks, bit for
    bit, across a 1-prefill + 2-decode fleet under a templated load —
    with warm handoffs observed, both tiers compile-free, and the
    prefill tier's menu strictly smaller than the decode tier's."""
    from horovod_tpu.fleet.router import FleetRouter

    _cfg, _params, _serve, build = disagg_pieces
    prompts = _prompts(20, 10)
    ref = build()
    ref.warmup()
    rids = [ref.submit(p, max_new_tokens=12) for p in prompts]
    want = ref.run()

    router = FleetRouter(build, replicas=2, prefill_replicas=1)
    assert router.disagg
    pre = [r for r in router.replicas if r.tier == "prefill"]
    dec = [r for r in router.replicas if r.tier == "decode"]
    assert len(pre) == 1 and len(dec) == 2
    assert pre[0].engine.role == "prefill"
    assert all(k[0] == "mixed" for k in pre[0].engine._progs)
    assert pre[0].warmed_programs < dec[0].warmed_programs
    gids = [router.submit(p, 12) for p in prompts]
    got = router.run_until_drained()
    for i, (r, g) in enumerate(zip(rids, gids)):
        np.testing.assert_array_equal(want[r], got[g], err_msg=f"req {i}")
    assert router.handoffs["warm"] >= 1, "no warm handoff observed"
    assert router.handoffs["warm"] + router.handoffs["cold"] == len(
        prompts)
    assert router.all_compile_free(), "a tier compiled post-warmup"
    assert router.migrated_bytes > 0
    for rec in router.handoff_records:
        assert rec["path"] in ("warm", "cold") and rec["ms"] >= 0.0
        assert (rec["bytes"] > 0) == (rec["path"] == "warm")


def test_disagg_token_identity_sharded(disagg_pieces):
    """The oracle at shards=2: a tensor-sharded disaggregated fleet
    (every tier's pools head-sharded over 2 virtual chips) matches the
    single sharded engine — the snapshot path re-device_puts imported
    pages under the pool sharding."""
    import dataclasses as dc

    from horovod_tpu.fleet.router import FleetRouter
    from horovod_tpu.serving import ServingEngine

    cfg, params, serve, _build = disagg_pieces
    sharded = dc.replace(serve, shards=2)

    def build(role="both"):
        return ServingEngine(cfg, params, serve=sharded, role=role)

    prompts = _prompts(21, 6)
    ref = build()
    assert ref.shards == 2
    ref.warmup()
    rids = [ref.submit(p, max_new_tokens=10) for p in prompts]
    want = ref.run()
    router = FleetRouter(build, replicas=1, prefill_replicas=1)
    gids = [router.submit(p, 10) for p in prompts]
    got = router.run_until_drained()
    for i, (r, g) in enumerate(zip(rids, gids)):
        np.testing.assert_array_equal(want[r], got[g], err_msg=f"req {i}")
    assert router.handoffs["warm"] >= 1
    assert router.all_compile_free()


def test_handoff_bytes_modeled_equals_measured(disagg_pieces):
    """comm_model idiom: the modeled kvsnap wire bytes reproduce every
    warm handoff's measured bytes exactly, from the block count the
    record carries and the model config alone."""
    from horovod_tpu.fleet.router import FleetRouter
    from horovod_tpu.ops.comm_model import modeled_kvsnap_bytes

    cfg, _params, serve, build = disagg_pieces
    before = _instr.SERVE_MIGRATED_BYTES.get()
    router = FleetRouter(build, replicas=1, prefill_replicas=1)
    gids = [router.submit(p, 8) for p in _prompts(22, 6)]
    router.run_until_drained()
    assert len(router.results) == len(gids)
    warm = [r for r in router.handoff_records if r["path"] == "warm"]
    assert warm, "need at least one warm handoff to compare"
    for rec in warm:
        m = modeled_kvsnap_bytes(
            rec["blocks"], serve.block_size, cfg.num_layers,
            cfg.num_kv_heads, cfg.head_dim, "float32")
        assert rec["bytes"] == m["wire_bytes"]
    assert router.migrated_bytes == sum(r["bytes"] for r in warm)
    assert _instr.SERVE_MIGRATED_BYTES.get() - before == \
        router.migrated_bytes


# -- satellite: the kvsnap source tag ----------------------------------------


def test_kvsnap_source_tag_names_sender(disagg_pieces):
    _cfg, _params, _serve, build = disagg_pieces
    src, dst = build(role="prefill"), build()
    src.warmup()
    dst.warmup()
    src.snap_source = "prefill7"  # what ServingReplica.spawn sets
    src.submit(np.arange(1, 18, dtype=np.int32), max_new_tokens=4)
    src.run()
    (_stream, snap, _arr), = src.handoffs.values()
    assert snap["source"] == "prefill7"
    # corrupt one verified token: the chain-hash reject names the sender
    bad = dict(snap)
    bad["tokens"] = np.array(snap["tokens"], np.int32).copy()
    bad["tokens"][3] ^= 1
    with pytest.raises(ValueError, match=r"from replica prefill7"):
        dst.import_kv(bad)
    # format reject names it too
    worse = dict(snap)
    worse["format"] = "bogus/9"
    with pytest.raises(ValueError, match=r"from replica prefill7"):
        dst.import_kv(worse)
    # the clean tagged snapshot imports fine
    assert dst.import_kv(dict(snap)) == len(snap["hashes"])


def test_kvsnap_untagged_snapshot_backward_compatible(disagg_pieces):
    _cfg, _params, _serve, build = disagg_pieces
    src, dst = build(), build()
    src.warmup()
    dst.warmup()
    assert src.snap_source is None  # no replica wrapper: untagged
    rid = src.submit(np.arange(2, 19, dtype=np.int32), max_new_tokens=9)
    while not any(s.req.id == rid and s.tokens_in_cache >= 16
                  for s in src.scheduler.running):
        src.step()
    snap = src.export_requests(rids=[rid])[rid][1]
    assert snap is not None and "source" not in snap
    assert dst.import_kv(dict(snap)) == len(snap["hashes"])
    # an untagged corrupt snapshot still rejects — just anonymously
    bad = dict(snap)
    bad["tokens"] = np.array(snap["tokens"], np.int32).copy()
    bad["tokens"][0] ^= 1
    with pytest.raises(ValueError, match=r"mismatch at block 0(?!.*from "
                                         r"replica)"):
        dst.import_kv(bad)
    src.cancel(rid)


# -- satellite: the two-hop deadline filter ----------------------------------


def test_two_hop_deadline_filter(disagg_pieces):
    """A cache-hot prefill replica whose queue ALONE fits the budget
    must still be skipped when queue + handoff + decode delay does not
    — and with no handoff cost on the books, affinity wins as before."""
    from horovod_tpu.fleet.router import FleetRouter

    _cfg, _params, _serve, build = disagg_pieces
    router = FleetRouter(build, replicas=1, prefill_replicas=2)
    template = np.arange(5, 29, dtype=np.int32)
    g0 = router.submit(np.concatenate([template, [3, 4]]), 4)
    p_hot = router._placed[g0].replica
    assert p_hot.tier == "prefill"
    router.run_until_drained()
    assert p_hot.cached_prefix_blocks(template) > 0
    p_cold = next(r for r in router.replicas
                  if r.tier == "prefill" and r is not p_hot)
    # fabricate load on the hot replica: 1 queued request x 0.5 s steps
    p_hot.avg_step_s = 0.5
    p_hot.engine.submit(np.arange(40, 60, dtype=np.int32),
                        max_new_tokens=4)
    assert p_hot.est_queue_delay() >= 0.5
    # no handoff cost booked yet: queue 0.5 fits the 1.0 s budget and
    # affinity routes to the cached replica (the pre-fix behavior)
    router._handoff_ema = None
    now = time.perf_counter()
    g1 = router.submit(np.concatenate([template, [7, 8]]), 4,
                       arrival=now, deadline_s=1.0)
    assert router._placed[g1].replica is p_hot
    # 0.6 s of handoff EMA: 0.5 + 0.6 > 1.0 — the two-hop total blows
    # the budget, so the filter must exclude the hot replica even
    # though its own queue fits
    router._handoff_ema = 0.6
    g2 = router.submit(np.concatenate([template, [9, 1]]), 4,
                       arrival=time.perf_counter(), deadline_s=1.0)
    assert router._placed[g2].replica is p_cold, \
        "deadline filter ignored the handoff + decode hop"
    assert router._two_hop_overhead() == pytest.approx(0.6)
    router.run_until_drained()


# -- satellite: handoff edge cases -------------------------------------------


def test_decode_replica_death_after_handoff(disagg_pieces, monkeypatch,
                                            tmp_path):
    """A decode replica dying mid-decode falls back to the PR-18
    replica-loss recovery: its handed-off requests re-route (watermark
    prepended exactly once), outputs stay bit-identical, and the
    bundle dumped on the chaos path carries the serve.handoff span."""
    from horovod_tpu.fleet.router import FleetRouter
    from horovod_tpu.trace import flight as _flight

    monkeypatch.setenv("HVD_TPU_FLEET_REPLICA_ERRORS", "1")
    monkeypatch.setenv("HVD_TPU_TRACE_BUNDLE_DIR", str(tmp_path))
    _flight._last_dump.clear()
    _cfg, _params, _serve, build = disagg_pieces
    prompts = _prompts(23, 4)
    ref = build()
    ref.warmup()
    rids = [ref.submit(p, max_new_tokens=12) for p in prompts]
    want = ref.run()

    router = FleetRouter(build, replicas=2, prefill_replicas=1)
    gids = [router.submit(p, 12) for p in prompts]
    # run until a decode replica is actually decoding handed-off work
    victim = None
    deadline = time.time() + 60
    while time.time() < deadline:
        router.step()
        victim = next(
            (r for r in router.replicas if r.tier == "decode"
             and r.engine is not None
             and any(len(s.generated) >= 2
                     for s in r.engine.scheduler.running)), None)
        if victim is not None:
            break
    assert victim is not None, "no decode replica reached mid-decode"

    def boom():
        raise RuntimeError("injected decode-step failure")

    victim.engine.step = boom
    got = router.run_until_drained()
    for i, (r, g) in enumerate(zip(rids, gids)):
        np.testing.assert_array_equal(want[r], got[g], err_msg=f"req {i}")
    assert router.recovery, "replica loss must book a recovery"
    assert victim.state == "retired"
    assert router.all_compile_free()
    bundles = list(tmp_path.glob("bundle-replica_loss-*.json"))
    assert bundles, "no flight bundle on the chaos path"
    names = {ev.get("name") for b in bundles
             for ev in _flight.read_bundle(str(b))["trace"]["traceEvents"]}
    assert "serve.handoff" in names, \
        "handoff span missing from the flight recorder"


def test_prefill_retire_while_draining(disagg_pieces):
    """A draining prefill replica finishes its in-flight prefill,
    hands the request off, and only THEN retires — the handoff-aware
    ``drained`` gate keeps the parked snapshot alive until the router
    collects it."""
    from horovod_tpu.fleet.router import FleetRouter

    _cfg, _params, _serve, build = disagg_pieces
    prompts = _prompts(24, 2)
    ref = build()
    ref.warmup()
    rids = [ref.submit(p, max_new_tokens=8) for p in prompts]
    want = ref.run()
    router = FleetRouter(build, replicas=1, prefill_replicas=2)
    gids = [router.submit(p, 8) for p in prompts]
    pre = [r for r in router.replicas if r.tier == "prefill"]
    loaded = next(r for r in pre if r.has_work)
    loaded.drain()
    # step the ENGINE directly (not the router) so the parked handoff
    # is observable before the router's collection pass
    for _ in range(32):
        if loaded.engine.handoffs:
            break
        loaded.engine.step()
    assert loaded.engine.handoffs, "prefill never reached the boundary"
    assert not loaded.has_work
    assert not loaded.drained, \
        "a parked handoff must count as in-flight work"
    got = router.run_until_drained()
    assert loaded.state == "retired"
    for i, (r, g) in enumerate(zip(rids, gids)):
        np.testing.assert_array_equal(want[r], got[g], err_msg=f"req {i}")


def test_hedged_dispatch_within_prefill_tier(disagg_pieces, monkeypatch):
    """Hedging in a disaggregated fleet stays tier-matched (the second
    dispatch lands on the OTHER prefill replica) and resolves
    first-handoff-wins: exactly one copy crosses into the decode tier,
    the loser's parked handoff is discarded."""
    from horovod_tpu.fleet.router import FleetRouter

    monkeypatch.setenv("HVD_TPU_SERVE_HEDGE", "1")
    _cfg, _params, _serve, build = disagg_pieces
    prompt = np.arange(3, 20, dtype=np.int32)
    ref = build()
    ref.warmup()
    rid = ref.submit(prompt, max_new_tokens=6)
    want = ref.run()[rid]

    t = [100.0]
    router = FleetRouter(build, replicas=1, prefill_replicas=2,
                         clock=lambda: t[0])
    router.hedge_budget = 1.0
    router._ttfts.extend([0.001] * 16)  # a stable, tiny p99 estimate
    gid = router.submit(prompt, 6)
    p = router._placed[gid]
    t[0] += 1.0  # stalled far past p99 TTFT, still pre-first-token
    router._maybe_hedge()
    assert p.hedge is not None and p.hedge[0].tier == "prefill"
    assert p.hedge[0] is not p.replica
    got = router.run_until_drained()
    np.testing.assert_array_equal(want, got[gid])
    assert router.hedges["won"] + router.hedges["lost"] == 1
    dec = next(r for r in router.replicas if r.tier == "decode")
    assert dec.engine._next_id == 1, \
        "both hedge copies crossed the tier boundary"


def test_handoff_chaos_corrupt_degrades_cold(disagg_pieces):
    """serve.handoff corruption: every chain-hash verification fails,
    every handoff lands cold — and outputs are STILL token-identical
    (deterministic re-prefill, never wrong tokens)."""
    from horovod_tpu import chaos
    from horovod_tpu.fleet.router import FleetRouter

    _cfg, _params, _serve, build = disagg_pieces
    prompts = _prompts(25, 5)
    ref = build()
    ref.warmup()
    rids = [ref.submit(p, max_new_tokens=8) for p in prompts]
    want = ref.run()
    chaos.configure("serve.handoff:corrupt,prob=1", seed=7)
    try:
        router = FleetRouter(build, replicas=1, prefill_replicas=1)
        gids = [router.submit(p, 8) for p in prompts]
        got = router.run_until_drained()
        fired = chaos.injection_trace()
    finally:
        chaos.clear()
    for i, (r, g) in enumerate(zip(rids, gids)):
        np.testing.assert_array_equal(want[r], got[g], err_msg=f"req {i}")
    assert router.handoffs["warm"] == 0
    assert router.handoffs["cold"] == len(prompts)
    assert router.migrated_bytes == 0
    assert any(ev["site"] == "serve.handoff" for ev in fired)


# -- per-tier scaling --------------------------------------------------------


def test_per_tier_scaling_signals_drive_their_tier(disagg_pieces,
                                                   monkeypatch):
    from horovod_tpu.fleet.policy import Target, TargetTrackingPolicy
    from horovod_tpu.fleet.router import FleetRouter

    _cfg, _params, _serve, build = disagg_pieces
    mk = dict(min_size=1, max_size=3, hysteresis=1, cooldown_s=0.0)
    router = FleetRouter(
        build, replicas=1, prefill_replicas=1,
        policy=TargetTrackingPolicy([Target("p99_ttft", 0.5)], **mk),
        decode_policy=TargetTrackingPolicy(
            [Target("decode_tokens_per_s", 100.0, invert=True)], **mk))
    # TTFT breach + decode floor met: ONLY the prefill tier grows
    monkeypatch.setattr(router, "signals", lambda: {
        "p99_ttft": 1.0, "decode_tokens_per_s": 500.0})
    router._maybe_scale()
    assert router.tier_size("prefill") == 2
    assert router.tier_size("decode") == 1
    assert ("out", 2, "prefill") in router.scale_events
    grown = router.replicas[-1]
    assert grown.tier == "prefill" and grown.engine.role == "prefill"
    # decode floor breach + TTFT healthy: ONLY the decode tier grows
    monkeypatch.setattr(router, "signals", lambda: {
        "p99_ttft": 0.2, "decode_tokens_per_s": 10.0})
    router._maybe_scale()
    assert router.tier_size("decode") >= 2
    assert any(ev[2] == "decode" and ev[0] == "out"
               for ev in router.scale_events if len(ev) == 3)
    assert router.replicas[-1].engine.role == "both"


def test_decode_tokens_rate_signal(disagg_pieces):
    from horovod_tpu.fleet.router import FleetRouter

    _cfg, _params, _serve, build = disagg_pieces
    t = [50.0]
    router = FleetRouter(build, replicas=2, prefill_replicas=1,
                         clock=lambda: t[0])
    assert "decode_tokens_per_s" not in router.signals()  # baseline pin
    router._decode_tokens += 120
    t[0] += 2.0
    s = router.signals()
    # 120 tokens / 2 s / 2 accepting decode replicas
    assert s["decode_tokens_per_s"] == pytest.approx(30.0)


def test_env_knobs_arm_disagg_and_decode_policy(disagg_pieces,
                                                monkeypatch):
    from horovod_tpu.fleet.policy import decode_policy_from_env
    from horovod_tpu.fleet.router import FleetRouter

    _cfg, _params, _serve, build = disagg_pieces
    assert decode_policy_from_env() is None
    monkeypatch.setenv("HVD_TPU_FLEET_DECODE_TPS_FLOOR", "50")
    pol = decode_policy_from_env()
    t = pol.targets()["decode_tokens_per_s"]
    assert t.value == 50.0 and t.invert
    monkeypatch.setenv("HVD_TPU_FLEET_PREFILL_REPLICAS", "1")
    router = FleetRouter(build, replicas=1)
    assert router.disagg and router.decode_policy is not None
    assert router.tier_size("prefill") == 1
    assert router.tier_size("decode") == 1
    assert {r.name for r in router.replicas} == {"decode0", "prefill1"}


def test_endpoint_signal_source_decode_rate(monkeypatch):
    """The scrape-side twin of the router's in-process signal: token
    emissions (latency histogram ``_count``) rated between scrapes,
    per endpoint."""
    from horovod_tpu.fleet.autoscaler import EndpointSignalSource

    t = [10.0]
    src = EndpointSignalSource(["http://a", "http://b"],
                               clock=lambda: t[0])
    name = src.LATENCY + "_count"
    samples = [{(name, ("first",)): 100.0},
               {(name, ("first",)): 400.0}]
    monkeypatch.setattr(src, "_fetch", lambda: dict(samples.pop(0)))
    assert "decode_tokens_per_s" not in src()
    t[0] += 3.0
    out = src()
    # (400 - 100) / 3 s / 2 endpoints
    assert out["decode_tokens_per_s"] == pytest.approx(50.0)
