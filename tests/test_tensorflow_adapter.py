"""TensorFlow adapter tests.

Reference analog: test/parallel/test_tensorflow.py (SURVEY.md §4) —
collectives on tf tensors in eager and tf.function (graph) modes,
DistributedGradientTape, variable broadcast, compression, elastic state.
Single-process world here (per-rank semantics are covered by the launcher
integration tests); these verify the adapter's bridging and wrappers.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import horovod_tpu.tensorflow as hvd  # noqa: E402


def test_allreduce_eager_roundtrip():
    t = tf.reshape(tf.range(6, dtype=tf.float32), (2, 3))
    out = hvd.allreduce(t)
    assert isinstance(out, tf.Tensor)
    assert out.dtype == t.dtype
    np.testing.assert_allclose(out.numpy(), t.numpy())  # world of 1


def test_allreduce_int_dtype_preserved():
    t = tf.range(5, dtype=tf.int64)
    out = hvd.allreduce(t, op=hvd.Sum)
    assert out.dtype == tf.int64
    np.testing.assert_array_equal(out.numpy(), t.numpy())


def test_allreduce_prescale():
    out = hvd.allreduce(tf.ones(3), op=hvd.Sum, prescale_factor=2.0)
    np.testing.assert_allclose(out.numpy(), np.full((3,), 2.0))


def test_allreduce_inside_tf_function():
    @tf.function
    def f(x):
        return hvd.allreduce(x, op=hvd.Sum, name="graph_allreduce")

    x = tf.constant([1.0, 2.0, 3.0])
    out = f(x)
    assert out.shape == x.shape  # shape re-asserted through py_function
    np.testing.assert_allclose(out.numpy(), x.numpy())


def test_grouped_allreduce_eager_and_graph():
    ts = [tf.ones(2), tf.fill((3,), 2.0)]
    outs = hvd.grouped_allreduce(ts)
    assert len(outs) == 2
    np.testing.assert_allclose(outs[1].numpy(), np.full((3,), 2.0))

    @tf.function
    def f(a, b):
        return hvd.grouped_allreduce([a, b], name="graph_grouped")

    outs = f(*ts)
    np.testing.assert_allclose(outs[0].numpy(), np.ones(2))


def test_allgather_broadcast_alltoall():
    t = tf.range(4, dtype=tf.float32)
    np.testing.assert_allclose(hvd.allgather(t).numpy(), t.numpy())
    np.testing.assert_allclose(hvd.broadcast(t, root_rank=0).numpy(),
                               t.numpy())
    received, splits = hvd.alltoall(t)
    np.testing.assert_allclose(received.numpy(), t.numpy())
    assert int(tf.reduce_sum(splits)) == 4


def test_reducescatter_world1():
    t = tf.reshape(tf.range(8, dtype=tf.float32), (4, 2))
    out = hvd.reducescatter(t, op=hvd.Sum)
    np.testing.assert_allclose(out.numpy(), t.numpy())


def test_broadcast_variables():
    vs = [tf.Variable([1.0, 2.0]), tf.Variable(3.0)]
    hvd.broadcast_variables(vs, root_rank=0)
    np.testing.assert_allclose(vs[0].numpy(), [1.0, 2.0])
    np.testing.assert_allclose(vs[1].numpy(), 3.0)


def test_broadcast_and_allgather_object():
    assert hvd.broadcast_object({"a": 1}, root_rank=0) == {"a": 1}
    assert hvd.allgather_object(("x", 2)) == [("x", 2)]


def test_distributed_gradient_tape_matches_local():
    v = tf.Variable([1.0, 2.0, 3.0])
    with tf.GradientTape() as plain:
        loss = tf.reduce_sum(v * v)
    expected = plain.gradient(loss, [v])[0]

    with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = tf.reduce_sum(v * v)
    got = tape.gradient(loss, [v])[0]
    np.testing.assert_allclose(got.numpy(), expected.numpy())


def test_distributed_gradient_tape_fp16_compression():
    v = tf.Variable([1.0, 2.0])
    with hvd.DistributedGradientTape(
            tf.GradientTape(), compression=hvd.Compression.fp16) as tape:
        loss = tf.reduce_sum(v * 3.0)
    g = tape.gradient(loss, [v])[0]
    assert g.dtype == tf.float32  # decompressed back
    np.testing.assert_allclose(g.numpy(), [3.0, 3.0])


def test_distributed_gradient_tape_num_groups():
    vs = [tf.Variable(tf.ones((2,))), tf.Variable(tf.ones((3,))),
          tf.Variable(tf.ones((4,)))]
    with hvd.DistributedGradientTape(
            tf.GradientTape(), num_groups=2) as tape:
        loss = tf.add_n([tf.reduce_sum(v) * (i + 1)
                         for i, v in enumerate(vs)])
    grads = tape.gradient(loss, vs)
    for i, (g, v) in enumerate(zip(grads, vs)):
        np.testing.assert_allclose(g.numpy(), np.full(v.shape, i + 1.0))


def test_distributed_gradient_tape_in_tf_function():
    v = tf.Variable([2.0, 4.0])

    @tf.function
    def step():
        with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
            loss = tf.reduce_sum(v * v)
        return tape.gradient(loss, [v])[0]

    np.testing.assert_allclose(step().numpy(), [4.0, 8.0])


def test_gradient_predivide_factor_world1():
    v = tf.Variable([3.0])
    with hvd.DistributedGradientTape(
            tf.GradientTape(), gradient_predivide_factor=2.0) as tape:
        loss = tf.reduce_sum(v * 2.0)
    g = tape.gradient(loss, [v])[0]
    # world of 1: pre 1/2 then post 2/1 is identity
    np.testing.assert_allclose(g.numpy(), [2.0])


def test_tensorflow_keras_state_roundtrip():
    keras = pytest.importorskip("keras")
    model = keras.Sequential([keras.layers.Dense(2, input_shape=(3,))])
    state = hvd.elastic.TensorFlowKerasState(model=model, epoch=0)
    w0 = [np.array(w) for w in model.get_weights()]
    state.commit()
    model.set_weights([w * 0 + 7.0 for w in w0])
    state.epoch = 5
    state.restore()
    for got, want in zip(model.get_weights(), w0):
        np.testing.assert_allclose(got, want)
    assert state.epoch == 0


def test_join_and_barrier():
    hvd.barrier()
    assert hvd.join() == hvd.rank()


def test_sync_batch_norm_single_worker_matches_bn():
    keras = pytest.importorskip("keras")
    x = np.random.RandomState(0).randn(8, 5).astype(np.float32)
    keras.utils.set_random_seed(0)
    plain = keras.layers.BatchNormalization()
    keras.utils.set_random_seed(0)
    synced = hvd.SyncBatchNormalization()
    out_plain = plain(x, training=True)
    out_sync = synced(x, training=True)
    np.testing.assert_allclose(
        np.asarray(out_plain), np.asarray(out_sync), rtol=1e-5, atol=1e-6
    )


def test_sync_batch_norm_moments_math():
    """The packed [sum, sumsq, count] formulation must reproduce plain
    moments exactly (single process: allreduce is identity, but the
    override path still computes through the global formulation when
    engine.multi_process — emulate by calling _moments internals)."""
    keras = pytest.importorskip("keras")
    from keras import ops

    layer = hvd.SyncBatchNormalization(axis=-1)
    x = np.random.RandomState(1).randn(4, 3, 6).astype(np.float32)
    layer.build(x.shape)
    mean, var = layer._moments(ops.convert_to_tensor(x), None)
    np.testing.assert_allclose(np.asarray(mean), x.mean(axis=(0, 1)),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(var), x.var(axis=(0, 1)),
                               rtol=1e-4, atol=1e-5)


def test_sync_batch_norm_is_differentiable():
    """Gradients must flow through the stats allreduce (the bridge alone
    would silently detach them): parity with plain BN at world 1."""
    keras = pytest.importorskip("keras")
    x = tf.constant(np.random.RandomState(2).randn(6, 4).astype(np.float32))

    def grad_through(layer):
        layer(x, training=True)  # build
        with tf.GradientTape() as tape:
            tape.watch(x)
            y = layer(x, training=True)
            loss = tf.reduce_sum(y * y)
        return tape.gradient(loss, x)

    keras.utils.set_random_seed(0)
    g_plain = grad_through(keras.layers.BatchNormalization(momentum=0.5))
    keras.utils.set_random_seed(0)
    g_sync = grad_through(hvd.SyncBatchNormalization(momentum=0.5))
    assert g_sync is not None, "gradient detached through sync BN"
    np.testing.assert_allclose(
        np.asarray(g_plain), np.asarray(g_sync), rtol=1e-4, atol=1e-5
    )


def test_sync_bn_allreduce_helper_has_gradient():
    """The multi-process stats path rides _allreduce_sum; its custom
    gradient (sum-allreduce of the cotangent) must keep the tape
    connected across the numpy bridge in both eager and graph modes."""
    from horovod_tpu.tensorflow.sync_batch_norm import _allreduce_sum

    x = tf.constant([1.0, 2.0, 3.0])
    with tf.GradientTape() as tape:
        tape.watch(x)
        y = _allreduce_sum(x, "sync_bn_grad_test", None)
        loss = tf.reduce_sum(y * tf.constant([1.0, 10.0, 100.0]))
    g = tape.gradient(loss, x)
    assert g is not None, "custom gradient lost through the bridge"
    np.testing.assert_allclose(g.numpy(), [1.0, 10.0, 100.0])

    @tf.function
    def graph_grad(t):
        with tf.GradientTape() as tape:
            tape.watch(t)
            y = _allreduce_sum(t, "sync_bn_grad_test_graph", None)
            loss = tf.reduce_sum(y)
        return tape.gradient(loss, t)

    np.testing.assert_allclose(graph_grad(x).numpy(), [1.0, 1.0, 1.0])

    # jax flavor: value and grad through the custom_vjp callback
    import jax
    import jax.numpy as jnp
    from horovod_tpu.tensorflow.sync_batch_norm import _jax_allreduce_sum

    f = lambda t: jnp.sum(_jax_allreduce_sum(t, "sync_bn_jax_grad", None)
                          * jnp.asarray([1.0, 10.0, 100.0]))
    g = jax.grad(f)(jnp.asarray([1.0, 2.0, 3.0]))
    np.testing.assert_allclose(np.asarray(g), [1.0, 10.0, 100.0])
    g = jax.grad(lambda t: jax.jit(f)(t))(jnp.asarray([1.0, 2.0, 3.0]))
    np.testing.assert_allclose(np.asarray(g), [1.0, 10.0, 100.0])


def test_local_gradient_aggregation_in_tf_function():
    """Graph-mode backward_passes_per_step: updates land only every Nth
    pass, with the aggregate averaged over the window (reference:
    LocalGradientAggregationHelper)."""
    from horovod_tpu.tensorflow.gradient_aggregation import (
        LocalGradientAggregationHelper,
    )

    v = tf.Variable([10.0])
    opt = tf.keras.optimizers.SGD(1.0)
    agg = LocalGradientAggregationHelper(
        backward_passes_per_step=2,
        allreduce_func=lambda gs: [
            hvd.allreduce(g, op=hvd.Average, name=f"agg_test.{i}")
            for i, g in enumerate(gs)
        ],
    )

    @tf.function
    def step(grad_value):
        grads = [tf.constant([grad_value])]
        grads = agg.compute_gradients(grads)
        agg.apply_gradients(
            lambda: opt.apply_gradients(zip(grads, [v]))
        )

    step(1.0)  # pass 1: accumulate only
    np.testing.assert_allclose(v.numpy(), [10.0])
    step(3.0)  # pass 2: flush -> mean(1, 3) = 2.0, lr 1.0
    np.testing.assert_allclose(v.numpy(), [8.0])
    step(5.0)  # next window
    np.testing.assert_allclose(v.numpy(), [8.0])
    step(7.0)  # flush -> mean(5, 7) = 6.0
    np.testing.assert_allclose(v.numpy(), [2.0])


def test_distributed_gradient_tape_indexed_slices():
    """Embedding-style sparse gradients (IndexedSlices) densify through
    the allreduce with duplicate indices summed (the reference's
    sparse_as_dense=True behavior)."""
    emb = tf.Variable(tf.ones((10, 4)))
    with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
        rows = tf.gather(emb, [1, 3, 3])
        loss = tf.reduce_sum(rows)
    g = tape.gradient(loss, [emb])[0]
    assert not isinstance(g, tf.IndexedSlices)
    dense = np.asarray(g)
    np.testing.assert_allclose(dense[1], np.ones(4))
    np.testing.assert_allclose(dense[3], np.full(4, 2.0))  # dup summed
    np.testing.assert_allclose(dense[0], np.zeros(4))


# -- XLA custom-call bridge (jit_compile=True) -------------------------------
# Reference: tensorflow/xla_mpi_ops.cc — collectives inside a must-compile
# tf.function.  World of one process: allreduce is identity/×size.

def _xla_bridge():
    from horovod_tpu.tensorflow import xla_ops

    if not xla_ops.available():
        pytest.skip("TF XLA bridge unavailable (no toolchain or TF libs)")
    return xla_ops


def test_allreduce_inside_jit_compile():
    _xla_bridge()

    @tf.function(jit_compile=True)
    def f(x):
        return hvd.allreduce(x, op=hvd.Sum, name="jit_allreduce") * 2.0

    x = tf.constant([[1.0, 2.0], [3.0, 4.0]])
    np.testing.assert_allclose(f(x).numpy(), x.numpy() * 2.0)


def test_grouped_allreduce_inside_jit_compile():
    _xla_bridge()

    @tf.function(jit_compile=True)
    def f(a, b):
        x, y = hvd.grouped_allreduce([a, b], op=hvd.Sum, name="jit_group")
        return x + 0.0, y + 0.0

    a = tf.constant([1.0, 2.0])
    b = tf.constant(np.arange(6, dtype=np.float32).reshape(2, 3))
    xa, xb = f(a, b)
    np.testing.assert_allclose(xa.numpy(), a.numpy())
    np.testing.assert_allclose(xb.numpy(), b.numpy())


def test_broadcast_inside_jit_compile():
    _xla_bridge()

    @tf.function(jit_compile=True)
    def f(x):
        return hvd.broadcast(x, root_rank=0, name="jit_bcast")

    x = tf.constant([5.0, 6.0])
    np.testing.assert_allclose(f(x).numpy(), x.numpy())


def test_distributed_gradient_tape_inside_jit_compile():
    _xla_bridge()
    w = tf.Variable([2.0, -1.0])

    @tf.function(jit_compile=True)
    def step(scale):
        with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
            loss = tf.reduce_sum(w * w) * scale
        return tape.gradient(loss, [w])[0]

    g = step(tf.constant(3.0))
    np.testing.assert_allclose(g.numpy(), [12.0, -6.0], rtol=1e-6)


def test_allgather_inside_jit_compile_raises_with_hint():
    _xla_bridge()

    @tf.function(jit_compile=True)
    def f(x):
        return hvd.allgather(x, name="jit_ag")

    with pytest.raises(Exception, match="data-dependent output shape"):
        f(tf.constant([1.0]))


def test_jit_compile_detection_does_not_leak_to_plain_graph():
    # plain tf.function must keep the py_function path (XlaCustomCallV2
    # has no CPU kernel outside compiled clusters)
    _xla_bridge()

    @tf.function
    def f(x):
        return hvd.allreduce(x, op=hvd.Sum, name="plain_graph_after_xla")

    x = tf.constant([7.0])
    np.testing.assert_allclose(f(x).numpy(), [7.0])


def test_jit_average_semantics():
    _xla_bridge()

    @tf.function(jit_compile=True)
    def f(x):
        return hvd.allreduce(x, name="jit_avg")  # default Average

    x = tf.constant([4.0, 8.0])
    np.testing.assert_allclose(f(x).numpy(), x.numpy())


def test_engine_error_in_jit_surfaces_at_next_eager_call(monkeypatch):
    # An engine failure inside a cached compiled step cannot raise
    # through XLA: the callback records it (identity data returned) and
    # the next eager collective re-raises it.  Async main-thread raise is
    # disabled here to test the deferred path deterministically.
    xla_ops = _xla_bridge()
    monkeypatch.setenv("HVD_TPU_TF_XLA_ASYNC_RAISE", "0")

    @tf.function(jit_compile=True)
    def f(x):
        return hvd.allreduce(x, op=hvd.Sum, name="jit_err")

    f(tf.constant([1.0]))  # trace + first run OK

    def boom(*a, **k):
        raise RuntimeError("engine exploded")

    monkeypatch.setattr(xla_ops, "_dispatch", boom)
    out = f(tf.constant([2.0]))  # swallowed: identity data
    np.testing.assert_allclose(out.numpy(), [2.0])
    monkeypatch.undo()
    with pytest.raises(RuntimeError, match="engine exploded"):
        hvd.allreduce(tf.constant([1.0]), name="post_err")
    # and the error is consumed — next call is clean
    np.testing.assert_allclose(
        hvd.allreduce(tf.constant([3.0]), op=hvd.Sum,
                      name="post_err2").numpy(), [3.0])


def test_tf_grouped_allgather_and_reducescatter_single():
    """np=1 degenerate semantics of the grouped tf wrappers (eager +
    plain-graph py_function paths)."""
    import horovod_tpu.tensorflow as hvd_tf

    a = tf.constant([1.0, 2.0, 3.0])
    b = tf.constant([[4.0], [5.0]])
    ga, gb = hvd_tf.grouped_allgather([a, b])
    assert np.allclose(ga.numpy(), a.numpy())
    assert np.allclose(gb.numpy(), b.numpy())
    ra, rb = hvd_tf.grouped_reducescatter([a, b])
    assert np.allclose(ra.numpy(), a.numpy())
    assert np.allclose(rb.numpy(), b.numpy())

    @tf.function  # plain graph (no jit_compile): py_function path
    def graph_fn(x, y):
        return hvd_tf.grouped_reducescatter([x, y])

    ra, rb = graph_fn(a, b)
    assert np.allclose(ra.numpy(), a.numpy())
    assert np.allclose(rb.numpy(), b.numpy())


def test_tf_broadcast_global_variables_raises_with_guidance():
    import horovod_tpu.tensorflow as hvd_tf

    with pytest.raises(RuntimeError, match="broadcast_variables"):
        hvd_tf.broadcast_global_variables(0)


def test_tf_keras_lazy_attribute():
    import horovod_tpu.tensorflow as hvd_tf

    assert hasattr(hvd_tf.keras, "DistributedOptimizer")
