"""Callbacks, compression and checkpoint tests.

Reference analog (SURVEY.md §4): the keras callback coverage of
test/parallel/test_tensorflow2_keras.py (warmup/schedule/metric-average
callbacks), compression coverage inside test_torch.py, and the
checkpoint-resume idiom of §5.4.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu import callbacks as cb
from horovod_tpu import checkpoint as ckpt


def _make_state(lr=0.1):
    import flax.struct

    class S(flax.struct.PyTreeNode):
        step: jax.Array
        params: dict
        opt_state: object
        batch_stats: object = None

    opt = optax.inject_hyperparams(optax.sgd)(learning_rate=lr)
    params = {"w": jnp.ones((3,))}
    return S(step=jnp.zeros((), jnp.int32), params=params,
             opt_state=opt.init(params)), opt


# -- lr plumbing -------------------------------------------------------------

def test_get_set_lr_roundtrip():
    state, opt = _make_state(0.25)
    assert cb.get_lr(state.opt_state) == pytest.approx(0.25)
    new_opt_state = cb.set_lr(state.opt_state, 0.5)
    assert cb.get_lr(new_opt_state) == pytest.approx(0.5)
    # the rewritten lr actually drives the update
    g = {"w": jnp.ones((3,))}
    updates, _ = opt.update(g, new_opt_state, state.params)
    np.testing.assert_allclose(np.asarray(updates["w"]),
                               np.full(3, -0.5), rtol=1e-6)


def test_set_lr_requires_injected_hyperparams():
    opt = optax.sgd(0.1)
    opt_state = opt.init({"w": jnp.ones(2)})
    with pytest.raises(ValueError):
        cb.set_lr(opt_state, 0.5)


# -- warmup callback ---------------------------------------------------------

def test_warmup_callback_ramps_linearly():
    state, _ = _make_state(lr=0.0)
    warmup = cb.LearningRateWarmupCallback(
        target_lr=0.8, warmup_epochs=4, steps_per_epoch=10, initial_lr=0.0
    )
    loop = cb.TrainLoop(state, [warmup])
    lrs = []
    for epoch in range(5):
        loop.on_epoch_begin(epoch)
        for batch in range(10):
            loop.on_batch_begin(batch)
            lrs.append(loop.lr)
        loop.on_epoch_end(epoch)
    # linear: first batch ~0, midpoint ~0.4, after warmup pinned at target
    assert lrs[0] == pytest.approx(0.0)
    assert lrs[20] == pytest.approx(0.4, abs=0.02)
    assert lrs[-1] == pytest.approx(0.8)


def test_schedule_callback_staircase():
    state, _ = _make_state(lr=1.0)
    sched = cb.LearningRateScheduleCallback(
        initial_lr=1.0, multiplier=lambda e: 0.1 ** (e // 2),
        start_epoch=0,
    )
    loop = cb.TrainLoop(state, [sched])
    seen = {}
    for epoch in range(4):
        loop.on_epoch_begin(epoch)
        seen[epoch] = loop.lr
        loop.on_epoch_end(epoch)
    assert seen[0] == pytest.approx(1.0)
    assert seen[1] == pytest.approx(1.0)
    assert seen[2] == pytest.approx(0.1)
    assert seen[3] == pytest.approx(0.1)


def test_metric_average_callback_single_process_identity():
    state, _ = _make_state()
    loop = cb.TrainLoop(state, [cb.MetricAverageCallback()])
    loop.on_epoch_begin(0)
    logs = loop.on_epoch_end(0, {"loss": 2.5, "acc": 0.75, "name": "x"})
    assert logs["loss"] == pytest.approx(2.5)
    assert logs["acc"] == pytest.approx(0.75)
    assert logs["name"] == "x"  # non-numeric passes through


def test_broadcast_callback_runs():
    state, _ = _make_state()
    loop = cb.TrainLoop(state, [cb.BroadcastGlobalVariablesCallback(0)])
    loop.on_epoch_begin(0)  # triggers on_train_begin
    np.testing.assert_allclose(np.asarray(loop.state.params["w"]),
                               np.ones(3))


def test_warmup_schedule_optax():
    sched = cb.warmup_schedule(0.8, warmup_steps=8, initial_lr=0.0)
    assert float(sched(0)) == pytest.approx(0.0)
    assert float(sched(4)) == pytest.approx(0.4)
    assert float(sched(8)) == pytest.approx(0.8)
    assert float(sched(100)) == pytest.approx(0.8)


# -- compression -------------------------------------------------------------

def test_compression_fp16_pytree_roundtrip():
    tree = {"a": jnp.arange(4, dtype=jnp.float32),
            "b": jnp.ones((2,), jnp.int32),
            "c": jnp.ones((3,), jnp.bfloat16)}
    comp, ctx = hvd.Compression.fp16.compress(tree)
    assert comp["a"].dtype == jnp.float16
    assert comp["b"].dtype == jnp.int32  # non-float untouched
    assert comp["c"].dtype == jnp.bfloat16  # already 16-bit: untouched
    out = hvd.Compression.fp16.decompress(comp, ctx)
    assert out["a"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out["a"]), np.arange(4))


def test_distributed_optimizer_with_compression():
    opt = hvd.DistributedOptimizer(optax.sgd(0.1),
                                   compression=hvd.Compression.bf16)
    params = {"w": jnp.ones((4,))}
    opt_state = opt.init(params)
    g = {"w": jnp.full((4,), 0.5)}
    updates, _ = opt.update(g, opt_state, params)
    assert updates["w"].dtype == jnp.float32  # decompressed back
    np.testing.assert_allclose(np.asarray(updates["w"]),
                               np.full(4, -0.05), rtol=1e-2)


# -- checkpoint --------------------------------------------------------------

def test_checkpoint_save_restore_roundtrip(tmp_path):
    state, opt = _make_state(0.3)
    state = state.replace(params={"w": jnp.asarray([1.0, 2.0, 3.0])},
                          step=jnp.asarray(17, jnp.int32))
    path = ckpt.save_checkpoint(str(tmp_path), state, step=17)
    assert path and os.path.exists(path)

    fresh, _ = _make_state(0.3)
    restored = ckpt.restore_checkpoint(str(tmp_path), fresh)
    np.testing.assert_allclose(np.asarray(restored.params["w"]),
                               [1.0, 2.0, 3.0])
    assert int(restored.step) == 17
    # injected lr survives as part of opt_state
    assert cb.get_lr(restored.opt_state) == pytest.approx(0.3)


def test_checkpoint_pruning_and_latest(tmp_path):
    state, _ = _make_state()
    for step in [1, 2, 3, 4, 5]:
        ckpt.save_checkpoint(str(tmp_path), state, step=step, keep=2)
    names = sorted(os.listdir(tmp_path))
    assert names == ["ckpt-4", "ckpt-5"]
    assert ckpt.latest_checkpoint(str(tmp_path)).endswith("ckpt-5")


def test_restore_without_checkpoint_is_identity(tmp_path):
    state, _ = _make_state()
    restored = ckpt.restore_checkpoint(str(tmp_path / "nope"), state)
    assert restored is state


def test_set_lr_is_functional():
    state, _ = _make_state(0.25)
    old = state.opt_state
    new = cb.set_lr(old, 0.5)
    assert cb.get_lr(old) == pytest.approx(0.25)  # input untouched
    assert cb.get_lr(new) == pytest.approx(0.5)


def test_schedule_callback_smooth_without_steps_per_epoch():
    state, _ = _make_state(lr=1.0)
    sched = cb.LearningRateScheduleCallback(
        initial_lr=1.0, multiplier=lambda e: 0.5 ** e, staircase=False,
    )
    loop = cb.TrainLoop(state, [sched])
    loop.on_epoch_begin(0)
    assert loop.lr == pytest.approx(1.0)
    loop.on_epoch_begin(2)
    assert loop.lr == pytest.approx(0.25)  # epoch-granularity fallback


def test_warmup_callback_fractional_epochs_pins_target():
    state, _ = _make_state(lr=0.0)
    warmup = cb.LearningRateWarmupCallback(
        target_lr=0.8, warmup_epochs=2.5, initial_lr=0.0
    )
    loop = cb.TrainLoop(state, [warmup])
    for epoch in range(4):
        loop.on_epoch_begin(epoch)
        loop.on_batch_begin(0)
        loop.on_epoch_end(epoch)
    assert loop.lr == pytest.approx(0.8)
