"""Tensor-parallel / Ulysses / MoE tests on the 8-device CPU mesh.

Reference analog: none — SURVEY.md §2.6 marks TP/SP/EP absent upstream;
these are first-class here, so they get the same per-rank-numerics test
treatment the collectives do (exact agreement with an unsharded
reference computation).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models.transformer import causal_dot_attention
from horovod_tpu.parallel.moe import ExpertParallelMoe
from horovod_tpu.parallel.tensor_parallel import (
    ColumnParallelDense, RowParallelDense, TensorParallelAttention,
    TensorParallelMlp,
)
from horovod_tpu.parallel.ulysses import ulysses_attention

TP = 8


def _mesh(axis="tp", n=TP):
    return Mesh(np.array(jax.devices()[:n]), (axis,))


def test_column_parallel_dense_matches_dense():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 6).astype(np.float32))
    kernel = jnp.asarray(rng.randn(6, 16).astype(np.float32))
    bias = jnp.asarray(rng.randn(16).astype(np.float32))
    mod = ColumnParallelDense(features=16, axis="tp")

    def f(x, k, b):
        return mod.apply({"params": {"kernel": k, "bias": b}}, x)

    out = jax.jit(jax.shard_map(
        f, mesh=_mesh(), in_specs=(P(), P(None, "tp"), P("tp")),
        out_specs=P(None, "tp"), check_vma=False,
    ))(x, kernel, bias)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(x @ kernel + bias), rtol=1e-5
    )


def test_row_parallel_dense_matches_dense():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(4, 16).astype(np.float32))
    kernel = jnp.asarray(rng.randn(16, 6).astype(np.float32))
    bias = jnp.asarray(rng.randn(6).astype(np.float32))
    mod = RowParallelDense(features=6, axis="tp")

    def f(xl, k, b):
        return mod.apply({"params": {"kernel": k, "bias": b}}, xl)

    out = jax.jit(jax.shard_map(
        f, mesh=_mesh(),
        in_specs=(P(None, "tp"), P("tp", None), P()),
        out_specs=P(), check_vma=False,
    ))(x, kernel, bias)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(x @ kernel + bias), rtol=1e-4
    )


def test_tensor_parallel_mlp_matches_dense():
    rng = np.random.RandomState(2)
    d_model, d_ff = 8, 32
    x = jnp.asarray(rng.randn(2, 5, d_model).astype(np.float32))
    wi = jnp.asarray(rng.randn(d_model, d_ff).astype(np.float32) * 0.3)
    bi = jnp.asarray(rng.randn(d_ff).astype(np.float32) * 0.1)
    wo = jnp.asarray(rng.randn(d_ff, d_model).astype(np.float32) * 0.3)
    bo = jnp.asarray(rng.randn(d_model).astype(np.float32) * 0.1)
    mod = TensorParallelMlp(d_model=d_model, d_ff=d_ff, axis="tp")
    params = {"wi": {"kernel": wi, "bias": bi},
              "wo": {"kernel": wo, "bias": bo}}

    def f(x, p):
        return mod.apply({"params": p}, x)

    specs = {"wi": {"kernel": P(None, "tp"), "bias": P("tp")},
             "wo": {"kernel": P("tp", None), "bias": P()}}
    out = jax.jit(jax.shard_map(
        f, mesh=_mesh(), in_specs=(P(), specs), out_specs=P(),
        check_vma=False,
    ))(x, params)
    import flax.linen as nn

    ref = nn.gelu(x @ wi + bi) @ wo + bo
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_tensor_parallel_attention_matches_reference():
    """TP attention == sum over chips of (local-head attention @ local
    proj shard) — computed densely on host from the same weight shards."""
    rng = np.random.RandomState(3)
    tp, b, s, heads, dh = 4, 2, 6, 8, 4
    d_model = heads * dh
    local_h = heads // tp
    x = jnp.asarray(rng.randn(b, s, d_model).astype(np.float32))
    qkv_shards = rng.randn(tp, d_model, 3 * local_h * dh).astype(
        np.float32) * 0.2
    proj_shards = rng.randn(tp, local_h * dh, d_model).astype(
        np.float32) * 0.2

    mod = TensorParallelAttention(num_heads=heads, head_dim=dh, axis="tp")

    def f(x, qkv_k, proj_k):
        p = {"qkv": {"kernel": qkv_k}, "proj": {"kernel": proj_k}}
        return mod.apply({"params": p}, x)

    out = jax.jit(jax.shard_map(
        f, mesh=_mesh(n=tp),
        in_specs=(P(), P(None, "tp"), P("tp", None)),
        out_specs=P(), check_vma=False,
    ))(x, jnp.asarray(np.concatenate(qkv_shards, axis=1)),
       jnp.asarray(np.concatenate(proj_shards, axis=0)))

    # host reference from the identical shards
    ref = np.zeros((b, s, d_model), np.float32)
    for c in range(tp):
        qkv = np.asarray(x) @ qkv_shards[c]  # (b, s, 3*local_h*dh)
        qkv = qkv.reshape(b, s, 3, local_h, dh)
        o = causal_dot_attention(
            jnp.asarray(qkv[:, :, 0]), jnp.asarray(qkv[:, :, 1]),
            jnp.asarray(qkv[:, :, 2]),
        )
        ref += np.asarray(o).reshape(b, s, local_h * dh) @ proj_shards[c]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)


def test_ulysses_matches_full_attention():
    rng = np.random.RandomState(4)
    n, b, s, heads, dh = 8, 2, 16, 8, 4  # s sharded 8-way -> 2 per chip
    q = jnp.asarray(rng.randn(b, s, heads, dh).astype(np.float32))
    k = jnp.asarray(rng.randn(b, s, heads, dh).astype(np.float32))
    v = jnp.asarray(rng.randn(b, s, heads, dh).astype(np.float32))

    def f(q, k, v):
        return ulysses_attention(q, k, v, axis_name="sp")

    out = jax.jit(jax.shard_map(
        f, mesh=_mesh(axis="sp"),
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False,
    ))(q, k, v)
    ref = causal_dot_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("impl", ["dense", "flash"])
def test_ulysses_bidirectional_matches_full_attention(impl):
    """Encoder mode (causal=False) through ulysses: same values as the
    full bidirectional oracle."""
    rng = np.random.RandomState(17)
    b, s, heads, dh = 2, 16, 8, 4
    q = jnp.asarray(rng.randn(b, s, heads, dh).astype(np.float32))
    k = jnp.asarray(rng.randn(b, s, heads, dh).astype(np.float32))
    v = jnp.asarray(rng.randn(b, s, heads, dh).astype(np.float32))

    def f(q, k, v):
        return ulysses_attention(q, k, v, axis_name="sp", impl=impl,
                                 causal=False)

    out = jax.jit(jax.shard_map(
        f, mesh=_mesh(axis="sp"),
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False,
    ))(q, k, v)
    ref = causal_dot_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_ulysses_matches_ring_attention():
    from horovod_tpu.parallel.ring_attention import ring_attention

    rng = np.random.RandomState(5)
    b, s, heads, dh = 1, 16, 8, 4
    q = jnp.asarray(rng.randn(b, s, heads, dh).astype(np.float32))
    k = jnp.asarray(rng.randn(b, s, heads, dh).astype(np.float32))
    v = jnp.asarray(rng.randn(b, s, heads, dh).astype(np.float32))
    mesh = _mesh(axis="sp")
    specs = dict(in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
                 out_specs=P(None, "sp"), check_vma=False)
    u = jax.jit(jax.shard_map(
        lambda a, b_, c: ulysses_attention(a, b_, c, axis_name="sp"),
        mesh=mesh, **specs))(q, k, v)
    r = jax.jit(jax.shard_map(
        lambda a, b_, c: ring_attention(a, b_, c, axis_name="sp"),
        mesh=mesh, **specs))(q, k, v)
    np.testing.assert_allclose(np.asarray(u), np.asarray(r),
                               rtol=1e-4, atol=1e-4)


def test_moe_single_chip_routing():
    """ep=1: the layer must reproduce per-token expert MLP outputs for
    tokens within capacity."""
    rng = np.random.RandomState(6)
    mod = ExpertParallelMoe(num_experts=4, d_model=8, d_ff=16, axis=None,
                            capacity_factor=4.0)
    x = jnp.asarray(rng.randn(2, 6, 8).astype(np.float32))
    params = mod.init(jax.random.PRNGKey(0), x)
    out, aux = mod.apply(params, x)
    assert out.shape == x.shape
    assert np.isfinite(float(aux)) and float(aux) > 0

    # manual reference: route each token through its argmax expert
    p = params["params"]
    tokens = np.asarray(x).reshape(-1, 8)
    logits = tokens @ np.asarray(p["gate"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    idx = probs.argmax(-1)
    gate = probs.max(-1)

    def gelu(a):
        import flax.linen as nn

        return np.asarray(nn.gelu(jnp.asarray(a)))

    ref = np.stack([
        gate[t] * (gelu(tokens[t] @ np.asarray(p["wi"])[idx[t]])
                   @ np.asarray(p["wo"])[idx[t]])
        for t in range(tokens.shape[0])
    ]).reshape(2, 6, 8)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3, atol=1e-3)


def test_moe_expert_parallel_matches_single_chip():
    """The same tokens+weights through ep=4 must equal the ep=1 result."""
    rng = np.random.RandomState(7)
    ep, experts, d, dff = 4, 8, 8, 16
    x = jnp.asarray(rng.randn(2, 8, d).astype(np.float32))
    mod1 = ExpertParallelMoe(num_experts=experts, d_model=d, d_ff=dff,
                             axis=None, capacity_factor=8.0)
    params = mod1.init(jax.random.PRNGKey(1), x)
    ref, aux_ref = mod1.apply(params, x)

    modn = ExpertParallelMoe(num_experts=experts, d_model=d, d_ff=dff,
                             axis="ep", capacity_factor=8.0)
    p = params["params"]

    def f(x, gate, wi, wo):
        return modn.apply(
            {"params": {"gate": gate, "wi": wi, "wo": wo}}, x)

    out, aux = jax.jit(jax.shard_map(
        f, mesh=_mesh(axis="ep", n=ep),
        in_specs=(P(), P(), P("ep"), P("ep")),
        out_specs=(P(), P()), check_vma=False,
    ))(x, p["gate"], p["wi"], p["wo"])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-4)


def test_moe_gradients_match_single_chip():
    """Backward through the expert-parallel dispatch/return all_to_all
    pair: grads from grad-outside-shard_map over ep=4 equal the ep=1
    grads (the same grad-placement rule the pipeline test pins)."""
    rng = np.random.RandomState(10)
    ep, experts, d, dff = 4, 8, 8, 16
    x = jnp.asarray(rng.randn(2, 8, d).astype(np.float32))
    mod1 = ExpertParallelMoe(num_experts=experts, d_model=d, d_ff=dff,
                             axis=None, capacity_factor=8.0)
    params = mod1.init(jax.random.PRNGKey(1), x)
    p = params["params"]

    def ref_loss(gate, wi, wo, x):
        out, aux = mod1.apply(
            {"params": {"gate": gate, "wi": wi, "wo": wo}}, x)
        return (out ** 2).mean() + 0.01 * aux

    ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(
        p["gate"], p["wi"], p["wo"], x)

    modn = ExpertParallelMoe(num_experts=experts, d_model=d, d_ff=dff,
                             axis="ep", capacity_factor=8.0)
    fwd = jax.shard_map(
        lambda g, wi, wo, x: modn.apply(
            {"params": {"gate": g, "wi": wi, "wo": wo}}, x),
        mesh=_mesh(axis="ep", n=ep),
        in_specs=(P(), P("ep"), P("ep"), P()),
        out_specs=(P(), P()), check_vma=False,
    )

    def ep_loss(gate, wi, wo, x):
        out, aux = fwd(gate, wi, wo, x)
        return (out ** 2).mean() + 0.01 * aux

    grads = jax.jit(jax.grad(ep_loss, argnums=(0, 1, 2)))(
        p["gate"], p["wi"], p["wo"], x)
    for g, r in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=1e-3, atol=1e-4)


def test_pipeline_matches_sequential():
    from horovod_tpu.parallel.pipeline import pipeline_apply

    rng = np.random.RandomState(8)
    n_stages, m, mb, d = 4, 6, 3, 5
    ws = jnp.asarray(rng.randn(n_stages, d, d).astype(np.float32) * 0.3)
    bs = jnp.asarray(rng.randn(n_stages, d).astype(np.float32) * 0.1)
    x = jnp.asarray(rng.randn(m, mb, d).astype(np.float32))

    def stage(params, h):
        w, b = params  # per-rank shard keeps a leading stage dim of 1
        return jnp.tanh(h @ w[0] + b[0])

    out = jax.jit(jax.shard_map(
        lambda p, x: pipeline_apply(stage, p, x, num_microbatches=m,
                                    axis="pp"),
        mesh=_mesh(axis="pp", n=n_stages),
        in_specs=((P("pp"), P("pp")), P()), out_specs=P(),
        check_vma=False,
    ))((ws, bs), x)

    ref = x
    for i in range(n_stages):
        ref = jnp.tanh(ref @ ws[i] + bs[i])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_pipeline_gradients_match_sequential():
    """Backward through the GPipe schedule: grads wrt every stage's
    params from jax.grad-through-pipeline_apply equal the sequential
    stack's grads (the dryrun's pp leg checks finiteness only)."""
    from horovod_tpu.parallel.pipeline import pipeline_apply

    rng = np.random.RandomState(9)
    n_stages, m, mb, d = 4, 5, 2, 6
    ws = jnp.asarray(rng.randn(n_stages, d, d).astype(np.float32) * 0.3)
    x = jnp.asarray(rng.randn(m, mb, d).astype(np.float32))

    def stage(w, h):
        return jnp.tanh(h @ w[0])

    # grad OUTSIDE the shard_map (the pipeline_apply docstring's
    # prescription): in-shard_map grad of the replicated output yields
    # incorrect stage grads (corruption shape varies by configuration —
    # no rescaling fixes it)
    pipelined = jax.shard_map(
        lambda w, x: pipeline_apply(stage, w, x, num_microbatches=m,
                                    axis="pp"),
        mesh=_mesh(axis="pp", n=n_stages),
        in_specs=(P("pp"), P()), out_specs=P(),
        check_vma=False,
    )

    def pp_loss(w, x):
        return (pipelined(w, x) ** 2).mean()

    grads = jax.jit(jax.grad(pp_loss))(ws, x)

    def seq_loss(w, x):
        h = x
        for i in range(n_stages):
            h = jnp.tanh(h @ w[i])
        return (h ** 2).mean()

    ref_grads = jax.grad(seq_loss)(ws, x)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(ref_grads),
                               rtol=1e-4, atol=1e-5)


def test_multi_axis_transformer_trains():
    import optax

    from horovod_tpu.parallel import sharded as sh

    mesh = sh.multi_axis_mesh(dp=2, sp=2, tp=2)
    model = sh.MultiAxisTransformer(vocab=32, d_model=16, num_heads=4,
                                    num_layers=1, seq_len=8)
    variables, specs = sh.init_sharded(model, mesh, jax.random.PRNGKey(0),
                                       local_batch=2)
    opt = optax.sgd(0.3, momentum=0.9)
    opt_state, ospecs = sh.init_opt_sharded(opt, variables, mesh, specs)
    step = sh.make_sharded_train_step(model, opt, mesh, specs, ospecs)
    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, 32, (4, 8)))
    tgt = jnp.asarray(rng.randint(0, 32, (4, 8)))
    losses = []
    for _ in range(10):
        variables, opt_state, loss = step(variables, opt_state, tok, tgt)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_param_specs_layout():
    from horovod_tpu.parallel import sharded as sh

    mesh = sh.multi_axis_mesh(dp=2, sp=2, tp=2)
    model = sh.MultiAxisTransformer(vocab=32, d_model=16, num_heads=4,
                                    num_layers=1, seq_len=8)
    variables, specs = sh.init_sharded(model, mesh, jax.random.PRNGKey(0))
    p = specs["params"]
    assert p["block_0"]["attn"]["qkv"]["kernel"] == P(None, "tp")
    assert p["block_0"]["attn"]["proj"]["kernel"] == P("tp", None)
    assert p["block_0"]["mlp"]["wi"]["kernel"] == P(None, "tp")
    assert p["block_0"]["mlp"]["wo"]["kernel"] == P("tp", None)
    assert p["embed"] == P()


def test_init_sharded_tp_shards_differ():
    """tp shards must be DISTINCT random draws (Megatron per-partition
    init) while replicated leaves are identical across all ranks."""
    from horovod_tpu.parallel import sharded as sh

    mesh = sh.multi_axis_mesh(dp=2, sp=2, tp=2)
    model = sh.MultiAxisTransformer(vocab=32, d_model=16, num_heads=4,
                                    num_layers=1, seq_len=8)
    variables, specs = sh.init_sharded(model, mesh, jax.random.PRNGKey(0))
    wi = variables["params"]["block_0"]["mlp"]["wi"]["kernel"]
    shards = [np.asarray(s.data) for s in wi.addressable_shards]
    tp_shards = shards[:2]  # same (dp, sp), tp=0 vs tp=1
    assert not np.array_equal(tp_shards[0], tp_shards[1])
    emb = variables["params"]["embed"]
    eshards = [np.asarray(s.data) for s in emb.addressable_shards]
    assert all(np.array_equal(eshards[0], e) for e in eshards[1:])


def test_ulysses_flash_matches_dense():
    """impl="flash" swaps the pallas kernel into ulysses' local attention;
    numerics must match the dense path."""
    rng = np.random.RandomState(11)
    b, s, heads, dh = 1, 32, 8, 8
    q = jnp.asarray(rng.randn(b, s, heads, dh).astype(np.float32))
    k = jnp.asarray(rng.randn(b, s, heads, dh).astype(np.float32))
    v = jnp.asarray(rng.randn(b, s, heads, dh).astype(np.float32))
    out = jax.jit(jax.shard_map(
        lambda a, b_, c: ulysses_attention(a, b_, c, axis_name="sp",
                                           impl="flash"),
        mesh=_mesh(axis="sp"),
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False,
    ))(q, k, v)
    ref = causal_dot_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_ulysses_windowed_matches_full_attention():
    """window= forwards to ulysses' local attention (full sequence after
    the all-to-all, so the window is already global there)."""
    rng = np.random.RandomState(19)
    b, s, heads, dh = 1, 16, 8, 4
    q = jnp.asarray(rng.randn(b, s, heads, dh).astype(np.float32))
    k = jnp.asarray(rng.randn(b, s, heads, dh).astype(np.float32))
    v = jnp.asarray(rng.randn(b, s, heads, dh).astype(np.float32))
    out = jax.jit(jax.shard_map(
        lambda a, b_, c: ulysses_attention(a, b_, c, axis_name="sp",
                                           window=5),
        mesh=_mesh(axis="sp"),
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False,
    ))(q, k, v)
    ref = causal_dot_attention(q, k, v, window=5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_multi_axis_transformer_ring_flash_windowed_trains():
    """attention_impl='ring_flash', window= through the dp x sp x tp
    trainer (ISSUE 5 end-to-end plumbing): the first-step loss matches
    the ulysses-attention model bit-for-tolerance (both are exact
    windowed attention over the same params) and training descends."""
    import optax

    from horovod_tpu.parallel import sharded as sh

    mesh = sh.multi_axis_mesh(dp=2, sp=2, tp=2)

    def make(impl):
        return sh.MultiAxisTransformer(
            vocab=32, d_model=16, num_heads=4, num_layers=1, seq_len=8,
            attention_impl=impl, window=3)

    model_r, model_u = make("ring_flash"), make("ulysses")
    variables, specs = sh.init_sharded(model_r, mesh,
                                       jax.random.PRNGKey(0),
                                       local_batch=2)
    opt = optax.sgd(0.3, momentum=0.9)
    opt_state, ospecs = sh.init_opt_sharded(opt, variables, mesh, specs)
    step_r = sh.make_sharded_train_step(model_r, opt, mesh, specs, ospecs)
    step_u = sh.make_sharded_train_step(model_u, opt, mesh, specs, ospecs)
    rng = np.random.RandomState(3)
    tok = jnp.asarray(rng.randint(0, 32, (4, 8)))
    tgt = jnp.asarray(rng.randint(0, 32, (4, 8)))

    # the train step donates params/opt_state — copy for the second model
    copy = lambda t: jax.tree_util.tree_map(jnp.copy, t)
    _, _, loss_u = step_u(copy(variables), copy(opt_state), tok, tgt)

    losses = []
    for _ in range(6):
        variables, opt_state, loss = step_r(variables, opt_state, tok, tgt)
        losses.append(float(loss))
    np.testing.assert_allclose(losses[0], float(loss_u),
                               rtol=1e-4, atol=1e-5)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_multi_axis_transformer_rejects_unknown_impl():
    from horovod_tpu.parallel import sharded as sh

    mesh = sh.multi_axis_mesh(dp=2, sp=2, tp=2)
    model = sh.MultiAxisTransformer(
        vocab=32, d_model=16, num_heads=4, num_layers=1, seq_len=8,
        attention_impl="warp")
    with pytest.raises(ValueError, match="attention_impl"):
        sh.init_sharded(model, mesh, jax.random.PRNGKey(0), local_batch=2)
