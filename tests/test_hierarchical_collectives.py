"""Hierarchical (ICI × DCN) collective oracles — ISSUE 7 / ROADMAP item 3.

Three layers, mirroring the implementation:

* topology — slice detection (`HVD_TPU_SLICE_SIZE` override, runtime
  ``slice_index`` attributes, process fallback) feeding
  ``hierarchical_mesh()``;
* SPMD path — ``spmd_ops.hierarchical_allreduce`` (+ the two-level
  reduce-scatter/allgather used by ZeRO) against flat ``psum`` on the
  8-virt-device 2×4 mesh: Sum fp32 BIT-exact with dyadic values (the
  test_zero_optimizer exactness discipline), Average/bf16-wire within
  tolerance, non-divisible sizes exercising the pad path;
* engine/routing — ``CollectiveEngine.hierarchical_allreduce_multi``,
  the ``HVD_TPU_HIERARCHICAL_ALLREDUCE`` gating, and the per-tier byte
  accounting, with an 8-contributor world simulated through the member
  bookkeeping (one real process; jax 0.4.37 CPU cannot run multi-process
  collectives — the SPMD oracle carries the reduction math through the
  shared ``_two_level_sum_leaf`` core).

The modeled-vs-measured byte contract (``ops.comm_model``) is pinned
here too: the model's numbers must equal what the compiled program's
collective inventory actually moves.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.common import basics
from horovod_tpu.common.topology import DCN_AXIS, ICI_AXIS
from horovod_tpu.compression import DcnCompression
from horovod_tpu.ops import collective_ops, spmd_ops
from horovod_tpu.ops.comm_model import (
    measured_tier_bytes,
    modeled_collective_bytes,
)
from horovod_tpu.ops.reduce_ops import ReduceOp

W, N_ICI, N_DCN = 8, 4, 2


def _hmesh():
    return hvd.hierarchical_mesh(num_groups=N_DCN)


def _spmd(fn, mesh=None, out_specs=None):
    return jax.jit(jax.shard_map(
        fn, mesh=mesh or _hmesh(),
        in_specs=P((DCN_AXIS, ICI_AXIS)),
        out_specs=P((DCN_AXIS, ICI_AXIS)) if out_specs is None
        else out_specs,
        check_vma=False,
    ))


def _dyadic(shape, seed=0, scale=8):
    rng = np.random.RandomState(seed)
    return jnp.asarray(
        rng.randint(-4 * scale, 4 * scale + 1, shape).astype(np.float32)
        / scale
    )


# -- topology: slice detection -------------------------------------------


class TestSliceDetection:
    def test_env_override_groups_consecutively(self, monkeypatch):
        topo = basics.topology()
        monkeypatch.setenv("HVD_TPU_SLICE_SIZE", "4")
        assert topo.slice_ids() == [0, 0, 0, 0, 1, 1, 1, 1]
        assert topo.num_slices == 2 and topo.slice_size == 4
        monkeypatch.setenv("HVD_TPU_SLICE_SIZE", "2")
        assert topo.slice_ids() == [0, 0, 1, 1, 2, 2, 3, 3]
        assert topo.num_slices == 4

    def test_env_override_must_divide(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_SLICE_SIZE", "3")
        with pytest.raises(ValueError, match="does not divide"):
            basics.topology().slice_ids()

    def test_default_single_process_is_one_slice(self):
        topo = basics.topology()
        assert topo.slice_ids() == [0] * W
        assert topo.num_slices == 1 and topo.slice_size == W
        assert topo.process_slice_groups() is None

    def test_runtime_slice_index_attr(self):
        from horovod_tpu.common.topology import _detect_slice_ids

        class Dev:
            def __init__(self, s):
                if s is not None:
                    self.slice_index = s

        # detected + renumbered dense in first-appearance order
        assert _detect_slice_ids([Dev(7), Dev(7), Dev(3), Dev(3)]) \
            == [7, 7, 3, 3]
        # missing attribute anywhere -> None (older runtime / CPU)
        assert _detect_slice_ids([Dev(0), Dev(None)]) is None
        # a UNIFORM tag is authoritative (runtime says: one slice),
        # not unknown — it must pre-empt the per-process fallback
        assert _detect_slice_ids([Dev(1), Dev(1)]) == [1, 1]
        # unequal groups cannot form a rectangular mesh -> None
        assert _detect_slice_ids([Dev(0), Dev(0), Dev(1)]) is None

    def test_uniform_runtime_tag_beats_process_fallback(self):
        # multi-host single-slice pod: every device tagged slice_index=0
        # but owned by different processes — the explicit tag wins, no
        # DCN tier is fabricated from host boundaries
        from horovod_tpu.common.topology import Topology

        class Dev:
            def __init__(self, p):
                self.slice_index = 0
                self.process_index = p

        devs = tuple(Dev(i // 2) for i in range(4))
        topo = Topology(devices=devs, local_devices=devs[:2],
                        process_index=0, num_processes=2)
        assert topo.slice_ids() == [0, 0, 0, 0]
        assert topo.num_slices == 1
        # hierarchical_mesh must not re-invent the tier from processes:
        # one authoritative slice -> a (1, world) mesh
        mesh = topo.hierarchical_mesh()
        assert mesh.devices.shape == (1, 4)

    def test_hierarchical_mesh_follows_detected_slices(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_SLICE_SIZE", "2")
        mesh = basics.topology().hierarchical_mesh()
        assert dict(mesh.shape) == {DCN_AXIS: 4, ICI_AXIS: 2}
        # rows ARE the slices: world order grouped in runs of 2
        devs = basics.topology().devices
        assert list(mesh.devices[0]) == list(devs[:2])
        assert list(mesh.devices[3]) == list(devs[6:])


# -- comm_model: modeled and measured bytes ------------------------------


class TestCommModel:
    def test_flat_and_local(self):
        assert modeled_collective_bytes((4,), 1, 1)["algorithm"] == "local"
        flat = modeled_collective_bytes((1024,), 8, 8)
        assert flat == {"ici_bytes": 7168, "dcn_bytes": 0,
                        "wire_dtype": None, "algorithm": "flat"}
        spanning = modeled_collective_bytes((1024,), 8, 1)
        assert spanning["dcn_bytes"] == 7168 and spanning["ici_bytes"] == 0

    def test_hierarchical_and_wire(self):
        m = modeled_collective_bytes((1024,), 8, 4)
        assert m["ici_bytes"] == 2 * 3 * 1024 * 4 // 4
        assert m["dcn_bytes"] == 2 * 1 * 256 * 4 // 2
        w = modeled_collective_bytes((1024,), 8, 4, wire_dtype="bf16")
        assert w["dcn_bytes"] == m["dcn_bytes"] // 2
        assert w["wire_dtype"] == "bfloat16"
        assert modeled_collective_bytes((1024,), 8, 4, "fp16")[
            "dcn_bytes"] == m["dcn_bytes"] // 2

    def test_non_divisible_pads(self):
        m = modeled_collective_bytes((37,), 8, 4)
        assert m["ici_bytes"] == 2 * 3 * 40 * 4 // 4  # padded to 40
        assert m["dcn_bytes"] == 2 * 1 * 10 * 4 // 2

    def test_compressed_hop_is_allgather_stream(self):
        # the compressed DCN hop is an all_gather of wire shards + a
        # local fp32 sum, so its stream is (n_dcn-1)*wire_shard — the
        # psum ring factor 2*(n_dcn-1)/n_dcn would under-model it 2x
        # at n_dcn=4 (they coincide only at n_dcn=2)
        m = modeled_collective_bytes((1024,), 16, 4, wire_dtype="bf16")
        assert m["dcn_bytes"] == 3 * 256 * 2

    def test_mesh_slice_ids_is_row_major(self):
        # the logical id order replica groups use — row == slice, no
        # matter how the physical world order interleaves slices
        from horovod_tpu.ops.comm_model import mesh_slice_ids

        assert mesh_slice_ids(_hmesh()) == [0, 0, 0, 0, 1, 1, 1, 1]
        assert mesh_slice_ids(hvd.hierarchical_mesh(num_groups=4)) \
            == [0, 0, 1, 1, 2, 2, 3, 3]

    def test_ml_dtypes_payloads_dont_crash_the_model(self):
        # fp8 gradients (QAT) route fine; byte accounting must follow
        m = modeled_collective_bytes(
            (1024,), 8, 4, wire_dtype="bf16", dtype="float8_e4m3fn")
        # 1-byte payload over a 2-byte wire is a no-op: psum branch
        assert m["wire_dtype"] is None
        assert m["dcn_bytes"] == 2 * 1 * 256 * 1 // 2
        with pytest.raises(ValueError, match="unknown dtype"):
            modeled_collective_bytes((4,), 8, 4, dtype="not_a_dtype")

    def test_wire_noop_payloads_model_the_psum_branch(self):
        # compress_shard skips int and already-narrow leaves, so the
        # program psums them at full width — the model must follow and
        # echo wire_dtype=None for such legs
        for dt in ("int32", "float16"):
            m = modeled_collective_bytes((1024,), 16, 4, "bf16", dtype=dt)
            item = 4 if dt == "int32" else 2
            assert m["dcn_bytes"] == 2 * 3 * 256 * item // 4
            assert m["wire_dtype"] is None
        # fp64 over a bf16 wire IS compressible
        w = modeled_collective_bytes((1024,), 16, 4, "bf16", dtype="float64")
        assert w["dcn_bytes"] == 3 * 256 * 2
        assert w["wire_dtype"] == "bfloat16"

    def test_invalid_args_raise(self):
        with pytest.raises(ValueError):
            modeled_collective_bytes((4,), 8, 3)  # non-divisor
        with pytest.raises(ValueError):
            modeled_collective_bytes((4,), 0, 1)

    def test_measured_from_synthetic_module(self):
        text = """
    %3 = "stablehlo.reduce_scatter"(%2) <{replica_groups = dense<[[0, 1, 2, 3], [4, 5, 6, 7]]> : tensor<2x4xi64>, scatter_dimension = 0 : i64}> ({
    ^bb0(%arg1: tensor<f32>, %arg2: tensor<f32>):
      %16 = stablehlo.add %arg1, %arg2 : tensor<f32>
      stablehlo.return %16 : tensor<f32>
    }) : (tensor<40xf32>) -> tensor<10xf32>
    %9 = "stablehlo.all_gather"(%8) <{all_gather_dim = 0 : i64, replica_groups = dense<[[0, 4], [1, 5], [2, 6], [3, 7]]> : tensor<4x2xi64>}> : (tensor<1x10xbf16>) -> tensor<2x10xbf16>
"""
        got = measured_tier_bytes(text, [0, 0, 0, 0, 1, 1, 1, 1])
        # rs: 160B over g=4 intra-slice -> 120 ICI; ag: 40B result over
        # cross-slice pairs -> 20 DCN
        assert got["ici_bytes"] == 120 and got["dcn_bytes"] == 20
        kinds = [(o["op"], o["tier"]) for o in got["ops"]]
        assert kinds == [("reduce_scatter", "ici"), ("all_gather", "dcn")]

    def test_measured_equals_modeled_on_real_program(self):
        """The acceptance pin: the model's numbers ARE what the compiled
        two-level program moves (per tier, wire dtype included)."""
        comp = DcnCompression("bfloat16")
        fn = _spmd(functools.partial(
            spmd_ops.hierarchical_allreduce, op=hvd.Sum,
            dcn_compression=comp,
        ))
        x = _dyadic((W, 96))
        slice_ids = [0, 0, 0, 0, 1, 1, 1, 1]
        meas = measured_tier_bytes(fn.lower(x).as_text(), slice_ids)
        model = modeled_collective_bytes(
            (96,), W, N_ICI, wire_dtype="bfloat16")
        assert meas["ici_bytes"] == model["ici_bytes"]
        assert meas["dcn_bytes"] == model["dcn_bytes"]
        # the wire all-gather really is 16-bit on the DCN groups
        dcn_ops = [o for o in meas["ops"] if o["tier"] == "dcn"]
        assert dcn_ops and all(o["op"] == "all_gather" for o in dcn_ops)

    def test_measured_equals_modeled_four_slices(self):
        """The >2-slice pin: at n_dcn=4 the compressed hop's all_gather
        stream is 2x the psum ring factor — modeled must track the
        program, not the uncompressed formula."""
        comp = DcnCompression("bfloat16")
        mesh = hvd.hierarchical_mesh(num_groups=4)
        fn = _spmd(functools.partial(
            spmd_ops.hierarchical_allreduce, op=hvd.Sum,
            dcn_compression=comp,
        ), mesh=mesh)
        x = _dyadic((W, 96))
        slice_ids = [0, 0, 1, 1, 2, 2, 3, 3]
        meas = measured_tier_bytes(fn.lower(x).as_text(), slice_ids)
        model = modeled_collective_bytes(
            (96,), W, 2, wire_dtype="bfloat16")
        assert meas["dcn_bytes"] == model["dcn_bytes"] == 3 * 48 * 2
        assert meas["ici_bytes"] == model["ici_bytes"]


# -- SPMD oracle ---------------------------------------------------------


class TestHierarchicalAllreduceOracle:
    @pytest.mark.parametrize("cols", [32, 37])  # 37: pad path live
    def test_sum_fp32_bit_exact_vs_flat(self, cols):
        x = _dyadic((W, cols))
        hier = _spmd(functools.partial(
            spmd_ops.hierarchical_allreduce, op=hvd.Sum))(x)
        flat = _spmd(
            functools.partial(spmd_ops.allreduce, op=hvd.Sum,
                              axis=(DCN_AXIS, ICI_AXIS)))(x)
        np.testing.assert_array_equal(np.asarray(hier), np.asarray(flat))
        np.testing.assert_array_equal(
            np.asarray(hier)[0], np.asarray(x).sum(0))

    def test_average_and_scale_factors(self):
        x = _dyadic((W, 24), seed=3)
        out = _spmd(functools.partial(
            spmd_ops.hierarchical_allreduce, average=True,
            prescale_factor=0.5, postscale_factor=4.0,
        ))(x)
        ref = np.asarray(x).mean(0) * 2.0
        np.testing.assert_allclose(np.asarray(out)[0], ref, rtol=1e-6)

    def test_bf16_wire_within_tolerance_fp32_accumulation(self):
        rng = np.random.RandomState(7)
        x = jnp.asarray(rng.randn(W, 130).astype(np.float32))
        out = _spmd(functools.partial(
            spmd_ops.hierarchical_allreduce, op=hvd.Sum,
            dcn_compression=DcnCompression("bfloat16"),
        ))(x)
        ref = np.asarray(x, np.float64).sum(0)
        scale = np.abs(ref).max()
        err = np.abs(np.asarray(out, np.float64)[0] - ref).max()
        # one bf16 rounding of the ICI-reduced shard: ~2^-8 relative;
        # fp32 accumulation must not amplify it
        assert err / scale < 2 ** -7, err / scale
        # every replica decompressed identically
        assert np.unique(np.asarray(out), axis=0).shape[0] == 1

    def test_int_leaves_skip_the_wire_cast(self):
        tree = {
            "f": _dyadic((W, 8), seed=5),
            "i": jnp.asarray(
                np.random.RandomState(6).randint(-9, 9, (W, 5)), jnp.int32),
        }
        out = _spmd(functools.partial(
            spmd_ops.hierarchical_allreduce, op=hvd.Sum,
            dcn_compression=DcnCompression("bfloat16"),
        ))(tree)
        np.testing.assert_array_equal(
            np.asarray(out["i"])[0], np.asarray(tree["i"]).sum(0))
        assert out["i"].dtype == jnp.int32

    def test_error_feedback_bounds_repeated_step_bias(self):
        # a value bf16 cannot represent: stateless compression loses the
        # same epsilon EVERY step (bias grows linearly); error feedback
        # carries the epsilon into the next cast so the accumulated sum
        # stays within ONE quantization error of the truth
        val = float(np.float32(1 / 3) + 2.0 ** -12)
        x = jnp.full((W, 16), val, jnp.float32)
        steps = 4

        def run(t, feedback):
            comp = DcnCompression("bfloat16", error_feedback=feedback)
            acc = jnp.zeros_like(t)
            res = None
            for _ in range(steps):
                if feedback:
                    r, res = spmd_ops.hierarchical_allreduce(
                        t, op=hvd.Sum, dcn_compression=comp, residual=res)
                else:
                    r = spmd_ops.hierarchical_allreduce(
                        t, op=hvd.Sum, dcn_compression=comp)
                acc = acc + r
            return acc

        ef = np.asarray(_spmd(functools.partial(run, feedback=True))(x))
        stateless = np.asarray(
            _spmd(functools.partial(run, feedback=False))(x))
        truth = steps * W * val
        ef_err = np.abs(ef - truth).max()
        stateless_err = np.abs(stateless - truth).max()
        assert stateless_err > 0  # the value really is lossy
        assert ef_err < stateless_err / 2, (ef_err, stateless_err)

    def test_rejects_min_max(self):
        with pytest.raises(ValueError, match="Sum/Average"):
            _spmd(functools.partial(
                spmd_ops.hierarchical_allreduce, op=hvd.Min))(
                    _dyadic((W, 4)))


class TestTwoLevelLanding:
    """The ZeRO exchange contract: the two-level reduce-scatter must land
    chunk d*n_ici+i on mesh position (d, i) — exactly the flat psum
    chunk order — so a flat-world ZeroPlan slices identically."""

    def test_reduce_scatter_matches_flat_chunks_bit_exact(self):
        buf = _dyadic((W, W * 5), seed=11)

        def both(t):
            flat = t.reshape(-1)
            shard, _ = spmd_ops._two_level_reduce_scatter_flat(
                flat, ICI_AXIS, DCN_AXIS)
            full = jax.lax.psum(flat, (DCN_AXIS, ICI_AXIS))
            me = (jax.lax.axis_index(DCN_AXIS) * N_ICI
                  + jax.lax.axis_index(ICI_AXIS))
            ref = jax.lax.dynamic_slice_in_dim(
                full, me * (flat.size // W), flat.size // W)
            return jnp.stack([shard, ref])

        out = np.asarray(_spmd(
            both, out_specs=P(None, (DCN_AXIS, ICI_AXIS)))(buf))
        np.testing.assert_array_equal(out[0], out[1])

    def test_all_gather_inverts_the_landing(self):
        buf = _dyadic((W, W * 3), seed=12)

        def roundtrip(t):
            flat = t.reshape(-1)
            shard, _ = spmd_ops._two_level_reduce_scatter_flat(
                flat, ICI_AXIS, DCN_AXIS)
            back = spmd_ops._two_level_all_gather_flat(
                shard, ICI_AXIS, DCN_AXIS)
            return (back - jax.lax.psum(flat, (DCN_AXIS, ICI_AXIS)))[None]

        out = np.asarray(_spmd(roundtrip)(buf))
        np.testing.assert_array_equal(out, np.zeros_like(out))

    def test_compressed_exchange_tolerance(self):
        rng = np.random.RandomState(13)
        buf = jnp.asarray(rng.randn(W, W * 4).astype(np.float32))
        comp = DcnCompression("bfloat16")

        def run(t):
            flat = t.reshape(-1)
            shard, _ = spmd_ops._two_level_reduce_scatter_flat(
                flat, ICI_AXIS, DCN_AXIS, comp)
            return spmd_ops._two_level_all_gather_flat(
                shard, ICI_AXIS, DCN_AXIS)[None]

        out = np.asarray(_spmd(run)(buf), np.float64)
        ref = np.asarray(buf, np.float64).sum(0)
        assert np.abs(out[0] - ref).max() / np.abs(ref).max() < 2 ** -6


class TestZeroHierarchicalParity:
    def _train(self, opt, params, x, y, steps, mesh, batch_spec):
        from tests.test_zero_optimizer import _loss

        @functools.partial(
            jax.shard_map, mesh=mesh,
            in_specs=(P(), batch_spec, batch_spec), out_specs=P(),
            check_vma=False,
        )
        def run(p, xs, ys):
            import optax

            st = opt.init(p)
            for _ in range(steps):
                g = jax.grad(_loss)(p, xs, ys)
                u, st = opt.update(g, st, p)
                p = optax.apply_updates(p, u)
            return p

        return run(params, x, y)

    @pytest.mark.slow
    def test_zero_hierarchical_vs_flat_bit_equal_fp32(self):
        """ISSUE-named oracle: ZeRO-hierarchical vs ZeRO-flat update
        parity — bit-equal with dyadic values (every partial sum of the
        two association orders representable).  Slow-marked (~28s of
        shard_map compilation): tier-1 carries the same exchange math via
        the fast TestTwoLevelLanding bit-exact tests."""
        import optax

        from tests.test_zero_optimizer import (
            _dyadic_batch, _dyadic_params,
        )

        params = _dyadic_params()
        x, y = _dyadic_batch(W * 4)
        inner = optax.adamw(1e-2)
        ph = self._train(
            hvd.ZeroSpmdOptimizer(inner, hierarchical=True),
            params, x, y, 3, _hmesh(), P((DCN_AXIS, ICI_AXIS)),
        )
        pf = self._train(
            hvd.ZeroSpmdOptimizer(inner),
            params, x, y, 3, hvd.world_mesh(), P("hvd"),
        )
        for k in params:
            np.testing.assert_array_equal(
                np.asarray(ph[k]), np.asarray(pf[k]))

    @pytest.mark.slow
    def test_zero_hierarchical_compressed_close_and_residual_state(self):
        import optax

        from tests.test_zero_optimizer import (
            _dyadic_batch, _dyadic_params,
        )

        params = _dyadic_params()
        x, y = _dyadic_batch(W * 4)
        inner = optax.sgd(0.1)
        comp = DcnCompression("bfloat16", error_feedback=True)
        zopt = hvd.ZeroSpmdOptimizer(
            inner, hierarchical=True, dcn_compression=comp)

        @functools.partial(
            jax.shard_map, mesh=_hmesh(),
            in_specs=(P(), P((DCN_AXIS, ICI_AXIS)),
                      P((DCN_AXIS, ICI_AXIS))),
            out_specs=(P(), P((DCN_AXIS, ICI_AXIS))),
            check_vma=False,
        )
        def run(p, xs, ys):
            from tests.test_zero_optimizer import _loss

            st = zopt.init(p)
            assert st.residual is not None  # EF state lives in ZeroState
            for _ in range(3):
                g = jax.grad(_loss)(p, xs, ys)
                u, st = zopt.update(g, st, p)
                p = jax.tree_util.tree_map(lambda a, b: a + b, p, u)
            return p, st.residual[0]

        ph, residual = run(params, x, y)
        pf = self._train(
            hvd.ZeroSpmdOptimizer(inner), params, x, y, 3,
            hvd.world_mesh(), P("hvd"),
        )
        for k in params:
            np.testing.assert_allclose(
                np.asarray(ph[k]), np.asarray(pf[k]), rtol=2e-2, atol=1e-4)
        assert residual.shape[-1] * W >= 13  # per-chip shard of the plan

    def test_spmd_wrapper_rejects_compression_without_hierarchical(self):
        import optax

        with pytest.raises(ValueError, match="hierarchical=True"):
            hvd.ZeroSpmdOptimizer(
                optax.sgd(0.1), dcn_compression=DcnCompression("bfloat16"))


# -- engine routing ------------------------------------------------------


@pytest.fixture
def routed_engine(monkeypatch):
    """The session engine with hierarchical routing ON over a simulated
    2-slice fabric and an 8-contributor member view (every chip its own
    'process' — the lead mask then counts 8 distinct contributions, the
    closest one real process gets to the multi-host data plane on this
    backend)."""
    eng = basics._require_init().engine
    monkeypatch.setenv("HVD_TPU_SLICE_SIZE", "4")
    monkeypatch.setattr(eng.config, "hierarchical_allreduce", True)
    monkeypatch.setattr(eng, "_hier", None)
    monkeypatch.setattr(eng, "_spans_dcn", None)
    monkeypatch.setattr(eng._world_ctx, "lead_slots", tuple(range(W)))
    monkeypatch.setattr(eng._world_ctx, "n", W)
    yield eng
    # drop caches built under the env override
    eng._hier = None
    eng._spans_dcn = None


class TestEngineRouting:
    def test_gating_defaults_off(self):
        eng = basics._require_init().engine
        assert not eng.routes_hierarchical(ReduceOp.SUM)

    def test_gating_needs_slices(self, monkeypatch):
        eng = basics._require_init().engine
        monkeypatch.setattr(eng.config, "hierarchical_allreduce", True)
        monkeypatch.setattr(eng, "_hier", None)
        try:
            assert not eng.routes_hierarchical(ReduceOp.SUM)  # 1 slice
        finally:
            eng._hier = None

    def test_gating_on(self, routed_engine):
        assert routed_engine.routes_hierarchical(ReduceOp.SUM)
        assert routed_engine.routes_hierarchical(ReduceOp.AVERAGE)
        assert not routed_engine.routes_hierarchical(ReduceOp.MIN)

    def test_routed_allreduce_matches_flat(self, routed_engine):
        x = _dyadic((33,), seed=21)
        out = routed_engine.allreduce(x, ReduceOp.SUM)
        np.testing.assert_array_equal(
            np.asarray(out), W * np.asarray(x))
        avg = routed_engine.allreduce(x, ReduceOp.AVERAGE)
        np.testing.assert_allclose(
            np.asarray(avg), np.asarray(x), rtol=1e-6)

    def test_routed_books_tier_bytes(self, routed_engine):
        from horovod_tpu.metrics import instruments as I

        ici0, dcn0 = I.COLLECTIVE_ICI_BYTES.get(), \
            I.COLLECTIVE_DCN_BYTES.get()
        x = jnp.zeros((256,), jnp.float32)
        routed_engine.allreduce(x, ReduceOp.SUM)
        m = modeled_collective_bytes((256,), W, N_ICI)
        assert I.COLLECTIVE_ICI_BYTES.get() - ici0 == m["ici_bytes"]
        assert I.COLLECTIVE_DCN_BYTES.get() - dcn0 == m["dcn_bytes"]

    def test_wire_compression_via_env(self, routed_engine, monkeypatch):
        monkeypatch.setattr(routed_engine.config, "dcn_wire_dtype", "bf16")
        rng = np.random.RandomState(22)
        x = jnp.asarray(rng.randn(64).astype(np.float32))
        out = np.asarray(
            routed_engine.allreduce(x, ReduceOp.SUM), np.float64)
        ref = W * np.asarray(x, np.float64)
        assert np.abs(out - ref).max() / np.abs(ref).max() < 2 ** -7

    def test_multi_fallbacks_return_none(self, routed_engine):
        x = jnp.ones((4,), jnp.float32)
        assert routed_engine.hierarchical_allreduce_multi(
            [x], ReduceOp.MIN) is None
        assert routed_engine.hierarchical_allreduce_multi(
            [jnp.ones((2,), jnp.bool_)], ReduceOp.SUM) is None
        assert routed_engine.hierarchical_allreduce_multi(
            [x], ReduceOp.SUM, max_signatures=0) is None

    def test_multi_fallback_counts_submissions_once(
            self, routed_engine, monkeypatch):
        # a routed attempt that returns None (churn guard / bool leaf)
        # must not book submissions the per-tensor fallback books again
        from horovod_tpu.metrics import instruments as I

        monkeypatch.setattr(
            routed_engine, "hierarchical_allreduce_multi",
            lambda *a, **k: None,
        )
        # pin the per-tensor eager fallback (a live native controller
        # would take the negotiated batch instead — also fine, but the
        # double-count regression lived on the eager path)
        monkeypatch.setattr(collective_ops, "_native",
                            lambda *a, **k: None)
        c0 = I.COLLECTIVES.labels("allreduce", "eager").get()
        b0 = I.COLLECTIVE_BYTES.labels("allreduce").get()
        xs = [_dyadic((5,), seed=41), _dyadic((6,), seed=42)]
        handles = collective_ops.allreduce_multi_async(
            xs, names=["fb.a", "fb.b"], op=hvd.Sum)
        for h in handles:
            h.wait()
        assert I.COLLECTIVES.labels("allreduce", "eager").get() - c0 \
            == len(xs)
        assert I.COLLECTIVE_BYTES.labels("allreduce").get() - b0 \
            == sum(x.nbytes for x in xs)

    def test_multi_batch_does_not_route_across_processes(
            self, routed_engine, monkeypatch):
        # batch composition is rank-local and timing-dependent: in a
        # multi-process world the burst must stay on the negotiated
        # path, never an un-negotiated batched global program
        import dataclasses

        monkeypatch.setattr(
            routed_engine, "topology",
            dataclasses.replace(routed_engine.topology, num_processes=2),
        )
        calls = []
        monkeypatch.setattr(
            routed_engine, "hierarchical_allreduce_multi",
            lambda bufs, *a, **k: calls.append(len(list(bufs))),
        )
        monkeypatch.setattr(collective_ops, "_native",
                            lambda *a, **k: None)
        xs = [_dyadic((5,), seed=51), _dyadic((6,), seed=52)]
        handles = collective_ops.allreduce_multi_async(
            xs, names=["mp.a", "mp.b"], op=hvd.Sum)
        for h, x in zip(handles, xs):
            np.testing.assert_array_equal(
                np.asarray(h.wait()), W * np.asarray(x))
        # the dispatch layer split the burst: each name submits its own
        # rank-symmetric program (the engine's per-tensor fallback ran
        # flat here because the patched attempt returned None)
        assert calls and all(n == 1 for n in calls)

    def test_public_api_and_multi_handles_route(self, routed_engine):
        # through collective_ops: the dispatch layer consults
        # routes_hierarchical and keeps the call on the engine
        xs = [_dyadic((9,), seed=31), _dyadic((17,), seed=32)]
        handles = collective_ops.allreduce_multi_async(
            xs, names=["h.a", "h.b"], op=hvd.Sum)
        for h, x in zip(handles, xs):
            np.testing.assert_array_equal(
                np.asarray(h.wait()), W * np.asarray(x))
        one = hvd.allreduce(xs[0], op=hvd.Sum)
        np.testing.assert_array_equal(
            np.asarray(one), W * np.asarray(xs[0]))
