"""Fake pyspark.sql: just what horovod_tpu.spark.run touches."""

from . import CALLS, _Session


class _Builder:
    def getOrCreate(self):
        CALLS.append(("getOrCreate", None))
        return _Session()


class SparkSession:
    builder = _Builder()
