"""Minimal fake pyspark for contract-testing horovod_tpu.spark.run.

pyspark is not installable in this image (VERDICT r3 item 5), so this
fake pins the exact pyspark API surface the integration calls —
SparkSession.builder.getOrCreate, sparkContext.parallelize(...).barrier()
.mapPartitions(...).collect(), and BarrierTaskContext.get() inside the
task — and records every call so the test can assert the sequence.
Partition tasks execute sequentially in-process (each sees its own
BarrierTaskContext with its partition id), which is exactly what the
contract test needs: the real `task` closure bodies run, not a mock of
them.
"""

CALLS = []  # chronological (event, payload) log the tests assert on


def _reset():
    del CALLS[:]
    BarrierTaskContext._current = None


class BarrierTaskContext:
    _current = None

    def __init__(self, partition_id, n_partitions):
        self._partition_id = partition_id
        self._n = n_partitions

    @classmethod
    def get(cls):
        if cls._current is None:
            raise RuntimeError(
                "BarrierTaskContext.get() outside a barrier task"
            )
        return cls._current

    def partitionId(self):
        return self._partition_id

    def barrier(self):
        CALLS.append(("barrier", self._partition_id))

    def getTaskInfos(self):
        return [_TaskInfo("localhost")] * self._n


class _TaskInfo:
    def __init__(self, address):
        self.address = address


class _BarrierRDD:
    def __init__(self, partitions):
        self._partitions = partitions

    def mapPartitions(self, fn):
        CALLS.append(("mapPartitions", len(self._partitions)))
        return _MappedRDD(self._partitions, fn)


class _MappedRDD:
    def __init__(self, partitions, fn):
        self._partitions = partitions
        self._fn = fn

    def collect(self):
        CALLS.append(("collect", None))
        out = []
        n = len(self._partitions)
        for pid, part in enumerate(self._partitions):
            BarrierTaskContext._current = BarrierTaskContext(pid, n)
            try:
                out.extend(self._fn(iter(part)))
            finally:
                BarrierTaskContext._current = None
        return out


class _RDD:
    def __init__(self, partitions):
        self._partitions = partitions

    def barrier(self):
        CALLS.append(("barrier_rdd", len(self._partitions)))
        return _BarrierRDD(self._partitions)


class _SparkContext:
    def parallelize(self, data, num_partitions):
        data = list(data)
        CALLS.append(("parallelize", (len(data), num_partitions)))
        parts = [
            data[i::num_partitions] for i in range(num_partitions)
        ]
        return _RDD(parts)

    def setLogLevel(self, level):
        CALLS.append(("setLogLevel", level))


class _Session:
    def __init__(self):
        self.sparkContext = _SparkContext()

    def stop(self):
        CALLS.append(("stop", None))
