"""Minimal fake ray for contract-testing RayExecutor's actor path.

ray is not installable in this image (VERDICT r3 item 5); this fake
pins the API surface horovod_tpu.ray uses — ray.init/is_initialized,
@ray.remote, fn.remote(...), ray.get([...]) — executing remote calls
lazily at ray.get() (like real ray's task submission) and recording the
call sequence for the tests to assert.
"""

CALLS = []
_initialized = False


def _reset():
    global _initialized
    del CALLS[:]
    _initialized = False


def is_initialized():
    return _initialized


def init(ignore_reinit_error=False, **kwargs):
    global _initialized
    CALLS.append(("init", {"ignore_reinit_error": ignore_reinit_error,
                           **kwargs}))
    _initialized = True


class ObjectRef:
    def __init__(self, fn, args, kwargs):
        self._thunk = (fn, args, kwargs)


class RemoteFunction:
    def __init__(self, fn, options=None):
        self._fn = fn
        self._options = dict(options or {})

    def remote(self, *args, **kwargs):
        CALLS.append(("task_submit", args))
        return ObjectRef(self._fn, args, kwargs)

    def options(self, **kwargs):
        return RemoteFunction(self._fn, {**self._options, **kwargs})


def remote(fn=None, **options):
    CALLS.append(("remote_decorate",
                  getattr(fn, "__name__", None) or sorted(options)))
    if fn is None:
        return lambda f: RemoteFunction(f, options)
    return RemoteFunction(fn)


def get(refs, timeout=None):
    CALLS.append(("get", len(refs) if isinstance(refs, list) else 1))
    if isinstance(refs, list):
        return [_run(r) for r in refs]
    return _run(refs)


def _run(ref):
    fn, args, kwargs = ref._thunk
    return fn(*args, **kwargs)
