"""Minimal fake `pytorch_lightning` for contract-testing the lightning
estimator.

lightning is not installable in this image; the estimator worker drives
the LightningModule protocol duck-typed, so all the fake must provide
is the base class users subclass: a ``torch.nn.Module`` with the
protocol hooks and a no-op ``log``.  Real torch (installed) supplies
autograd.
"""

import torch


class LightningModule(torch.nn.Module):
    def log(self, name, value, **kwargs):
        pass

    def log_dict(self, metrics, **kwargs):
        pass

    def configure_optimizers(self):
        raise NotImplementedError

    def training_step(self, batch, batch_idx):
        raise NotImplementedError


__version__ = "2.4.0-fake"
