"""Minimal fake `mxnet` for contract-testing horovod_tpu.mxnet.

Real mxnet is not installable in this image (archived upstream; no
wheel for this python).  This fake implements just enough of the
NDArray / gluon.Trainer / optimizer.Optimizer surface for the adapter's
real code paths to execute: NDArray wraps a numpy array with
``asnumpy()`` and in-place ``t[:] = ...`` assignment (the two bridge
primitives), gluon exposes Parameter/Trainer with the ``_allreduce_grads``
hook the DistributedTrainer overrides, and optimizer.Optimizer is the
delegation base DistributedOptimizer wraps.
"""

import numpy as np


class Context:
    def __init__(self, device_type="cpu", device_id=0):
        self.device_type = device_type
        self.device_id = device_id

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    def __eq__(self, other):
        return (isinstance(other, Context)
                and other.device_type == self.device_type
                and other.device_id == self.device_id)


def cpu(device_id=0):
    return Context("cpu", device_id)


def gpu(device_id=0):
    return Context("gpu", device_id)


current_context = cpu


class _NDArrayModule:
    """Stands in for the `mxnet.nd` / `mxnet.ndarray` namespace."""

    class NDArray:
        def __init__(self, data, ctx=None):
            self._data = np.asarray(data)
            self.context = ctx or cpu()

        # -- the two primitives the horovod_tpu bridge relies on --------
        def asnumpy(self):
            return self._data.copy()

        def __setitem__(self, key, value):
            if isinstance(value, _NDArrayModule.NDArray):
                value = value._data
            self._data[key] = np.asarray(value, dtype=self._data.dtype)

        # -- conveniences used by tests / the fake trainer ---------------
        def __getitem__(self, key):
            return _NDArrayModule.NDArray(self._data[key], self.context)

        @property
        def shape(self):
            return self._data.shape

        @property
        def dtype(self):
            return self._data.dtype

        @property
        def size(self):
            return self._data.size

        @property
        def ctx(self):
            return self.context

        def copy(self):
            return _NDArrayModule.NDArray(self._data.copy(), self.context)

        def astype(self, dtype):
            return _NDArrayModule.NDArray(self._data.astype(dtype),
                                          self.context)

        def __repr__(self):
            return f"FakeNDArray({self._data!r})"

    def array(self, obj, ctx=None, dtype=None):
        a = np.asarray(obj, dtype=dtype)
        return self.NDArray(a, ctx)

    def zeros(self, shape, ctx=None, dtype="float32"):
        return self.NDArray(np.zeros(shape, dtype=dtype), ctx)

    def ones(self, shape, ctx=None, dtype="float32"):
        return self.NDArray(np.ones(shape, dtype=dtype), ctx)


nd = _NDArrayModule()
ndarray = nd
NDArray = nd.NDArray


class _OptimizerModule:
    class Optimizer:
        def __init__(self, learning_rate=0.01, rescale_grad=1.0, **kwargs):
            self.learning_rate = learning_rate
            self.rescale_grad = rescale_grad

        def update(self, index, weight, grad, state):
            raise NotImplementedError

        def update_multi_precision(self, index, weight, grad, state):
            self.update(index, weight, grad, state)

        def create_state(self, index, weight):
            return None

        def create_state_multi_precision(self, index, weight):
            return self.create_state(index, weight)

    class SGD(Optimizer):
        def update(self, index, weight, grad, state):
            weight[:] = (weight.asnumpy()
                         - self.learning_rate
                         * self.rescale_grad * grad.asnumpy())

    @staticmethod
    def create(name, **kwargs):
        if isinstance(name, _OptimizerModule.Optimizer):
            return name
        table = {"sgd": _OptimizerModule.SGD}
        return table[str(name).lower()](**kwargs)


optimizer = _OptimizerModule()


class DeferredInitializationError(Exception):
    """mx.gluon.parameter.DeferredInitializationError: parameter shape
    unknown until the first forward pass."""


class _GluonParameterNamespace:
    DeferredInitializationError = DeferredInitializationError


class _GluonModule:
    parameter = _GluonParameterNamespace()

    class Parameter:
        def __init__(self, name, shape=None, grad_req="write",
                     dtype="float32"):
            self.name = name
            self.grad_req = grad_req
            self.dtype = dtype
            self._deferred = shape is None or 0 in tuple(shape)
            if self._deferred:
                self._data, self._grad = None, None
            else:
                self._data = [nd.zeros(shape, dtype=dtype)]
                self._grad = ([nd.zeros(shape, dtype=dtype)]
                              if grad_req != "null" else [])

        def _init_impl(self, data, ctx_list=None):
            """Shape-resolved initialization (what mxnet calls after the
            first forward infers the shape)."""
            self._data = [nd.array(np.asarray(data), dtype=self.dtype)]
            self._grad = ([nd.zeros(self._data[0].shape, dtype=self.dtype)]
                          if self.grad_req != "null" else [])
            self._deferred = False

        def _check_init(self):
            if self._deferred:
                raise DeferredInitializationError(
                    f"Parameter {self.name} has not been initialized yet"
                )

        def data(self, ctx=None):
            self._check_init()
            return self._data[0]

        def grad(self, ctx=None):
            self._check_init()
            return self._grad[0]

        def list_data(self):
            self._check_init()
            return list(self._data)

        def list_grad(self):
            self._check_init()
            return list(self._grad)

        def zero_grad(self):
            for g in self._grad or []:
                g[:] = 0

    class Trainer:
        """Subset of mx.gluon.Trainer: ordered `_params`, an
        `_allreduce_grads` hook between backward and update, and a
        `_scale` folded into the effective gradient."""

        def __init__(self, params, optimizer_, optimizer_params=None,
                     kvstore="device"):
            if hasattr(params, "values"):
                params = list(params.values())
            self._params = list(params)
            opt_params = dict(optimizer_params or {})
            self._optimizer = _OptimizerModule.create(optimizer_,
                                                      **opt_params)
            self._scale = self._optimizer.rescale_grad
            self._kvstore = kvstore
            self._states = [
                self._optimizer.create_state(i, p.data())
                for i, p in enumerate(self._params)
            ]

        @property
        def learning_rate(self):
            return self._optimizer.learning_rate

        def step(self, batch_size, ignore_stale_grad=False):
            self._optimizer.rescale_grad = self._scale / batch_size
            self._allreduce_grads()
            self._update()

        def allreduce_grads(self):
            self._allreduce_grads()

        def _allreduce_grads(self):
            pass  # kvstore sync point; overridden by DistributedTrainer

        def update(self, batch_size, ignore_stale_grad=False):
            self._optimizer.rescale_grad = self._scale / batch_size
            self._update()

        def _update(self):
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    self._optimizer.update(i, p.data(), p.grad(),
                                           self._states[i])


gluon = _GluonModule()

__version__ = "1.9.1-fake"
