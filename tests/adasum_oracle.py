"""NumPy emulation of the Adasum fold + XOR-hypercube pairing shared by
ops/adasum.py (in-jit) and the eager engine — the single no-golden-files
oracle both the spmd tests and the launcher worker validate against
(keeping one copy means a pairing change can't silently desync a test)."""

import numpy as np


def host_adasum(vs):
    def pair(a, b):
        d = float((a * b).sum())
        na = float((a * a).sum())
        nb = float((b * b).sum())
        ca = 1.0 - d / (2.0 * na) if na > 0 else 1.0
        cb = 1.0 - d / (2.0 * nb) if nb > 0 else 1.0
        return ca * a + cb * b

    n = len(vs)
    m = 1
    while m * 2 <= n:
        m *= 2
    excess = n - m
    work = [
        pair(vs[i], vs[m + i]) if i < excess else np.array(vs[i])
        for i in range(m)
    ]
    step = 1
    while step < m:
        work = [pair(work[i], work[i ^ step]) for i in range(m)]
        step <<= 1
    return work[0]
