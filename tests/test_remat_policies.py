"""Configurable activation-remat policy tests (ISSUE 6): policy
resolution/validation, numeric transparency (remat must never change
values, only the memory/compute schedule), and the modeled
activation-bytes arithmetic the bench legs report.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd  # noqa: F401  (session init fixture)
from horovod_tpu.models.transformer import (
    REMAT_POLICIES,
    Transformer,
    TransformerConfig,
    modeled_activation_bytes,
    resolve_remat_policies,
)

CFG_KW = dict(vocab_size=64, num_layers=2, num_heads=2, head_dim=8,
              max_seq_len=16, dtype=jnp.float32)


def _data():
    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, 64, (2, 16)))
    tgt = jnp.asarray(rng.randint(0, 64, (2, 16)))
    return tok, tgt


# -- resolution / validation -------------------------------------------------


def test_resolve_remat_policies():
    assert resolve_remat_policies(None, 3) == ("none",) * 3
    assert resolve_remat_policies("full", 2) == ("full", "full")
    assert resolve_remat_policies(("none", "dots"), 2) == ("none", "dots")
    with pytest.raises(ValueError, match="unknown remat policy"):
        resolve_remat_policies("everything", 2)
    with pytest.raises(ValueError, match="2 entries"):
        resolve_remat_policies(("full",), 2)


def test_config_validates_policy_at_build():
    with pytest.raises(ValueError, match="unknown remat policy"):
        TransformerConfig(remat_policy="bogus", **CFG_KW)
    with pytest.raises(ValueError, match="entries"):
        TransformerConfig(remat_policy=("full",), **CFG_KW)
    # lists normalize to (hashable) tuples — the config stays usable as
    # a static jit argument
    cfg = TransformerConfig(remat_policy=["full", "dots"], **CFG_KW)
    assert cfg.remat_policy == ("full", "dots")
    assert hash(cfg) == hash(
        TransformerConfig(remat_policy=("full", "dots"), **CFG_KW))


def test_legacy_remat_bool_maps_to_dots_no_batch():
    cfg = TransformerConfig(remat=True, **CFG_KW)
    assert cfg.block_remat_policies() == ("dots_no_batch",) * 2
    cfg = TransformerConfig(**CFG_KW)
    assert cfg.block_remat_policies() == ("none",) * 2


# -- numeric transparency ----------------------------------------------------


@pytest.mark.parametrize(
    "policy", ["full", "dots", "dots_no_batch", ("none", "full")]
)
def test_remat_policy_is_numerically_transparent(policy):
    """Remat changes WHAT is recomputed, never the result: loss and
    every gradient must match the no-remat model (same params — the
    lifted transform must not move parameter paths either)."""
    tok, tgt = _data()
    base = TransformerConfig(**CFG_KW)
    params = Transformer(base).init(jax.random.PRNGKey(0), tok)["params"]

    def loss_fn(p, cfg):
        logits = Transformer(cfg).apply({"params": p}, tok)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgt).mean()

    l0, g0 = jax.value_and_grad(loss_fn)(params, base)
    cfg = TransformerConfig(remat_policy=policy, **CFG_KW)
    l1, g1 = jax.value_and_grad(loss_fn)(params, cfg)
    assert np.allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_multi_axis_transformer_remat_trains_and_matches():
    """MultiAxisTransformer threads the same policies: a remat'd model
    must produce the no-remat loss on the same params and still train."""
    from horovod_tpu.parallel import sharded as sh

    mesh = sh.multi_axis_mesh(dp=2, sp=2, tp=2)

    def build(policy):
        return sh.MultiAxisTransformer(
            vocab=32, d_model=16, num_heads=4, num_layers=2, seq_len=8,
            remat_policy=policy,
        )

    variables, specs = sh.init_sharded(
        build(None), mesh, jax.random.PRNGKey(0), local_batch=2)
    opt = optax.sgd(0.3, momentum=0.9)
    opt_state, ospecs = sh.init_opt_sharded(opt, variables, mesh, specs)
    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, 32, (4, 8)))
    tgt = jnp.asarray(rng.randint(0, 32, (4, 8)))

    step_n = sh.make_sharded_train_step(build(None), opt, mesh, specs,
                                        ospecs)
    step_r = sh.make_sharded_train_step(build("full"), opt, mesh, specs,
                                        ospecs)
    copy = jax.tree_util.tree_map(jnp.copy, (variables, opt_state))
    _, _, loss_n = step_n(*copy, tok, tgt)  # donated — use the copy
    v, o, loss_r = step_r(variables, opt_state, tok, tgt)
    np.testing.assert_allclose(float(loss_n), float(loss_r), rtol=1e-5)
    losses = [float(loss_r)]
    for _ in range(5):
        v, o, loss = step_r(v, o, tok, tgt)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


# -- modeled activation bytes ------------------------------------------------


def test_modeled_activation_bytes_arithmetic():
    """Pin the model: per-block saved-tensor accounting for a config
    small enough to hand-check (B=2, S=16, D=16, H=2, Hkv=1, Dh=8,
    F=64, fp32)."""
    cfg = TransformerConfig(num_kv_heads=1, **CFG_KW)
    out = modeled_activation_bytes(cfg, batch=2)
    bsd = 2 * 16 * 16 * 4          # one (B, S, D) fp32 tensor = 2048
    kv = 2 * 2 * 16 * 1 * 8 * 4    # K and V at one kv head = 2048
    f = 2 * 16 * 64 * 4            # one MLP hidden tensor = 8192
    assert out["per_block_bytes"]["none"] == 5 * bsd + kv + 3 * f
    assert out["per_block_bytes"]["dots"] == 5 * bsd + kv + 2 * f
    assert out["per_block_bytes"]["dots_no_batch"] == bsd
    assert out["per_block_bytes"]["full"] == bsd
    # default policy = none on both blocks
    assert out["total_bytes"] == 2 * (5 * bsd + kv + 3 * f)
    assert out["policies"] == ("none", "none")


def test_modeled_activation_bytes_drop_under_each_policy():
    """The ISSUE acceptance: modeled activation bytes DROP under every
    remat policy relative to none, monotonically with policy strength."""
    per = modeled_activation_bytes(
        TransformerConfig(**CFG_KW), batch=4)["per_block_bytes"]
    assert per["none"] > per["dots"] > per["dots_no_batch"]
    assert per["dots_no_batch"] == per["full"]
    # per-block selection sums exactly
    mixed = TransformerConfig(remat_policy=("none", "full"), **CFG_KW)
    assert modeled_activation_bytes(mixed, batch=4)["total_bytes"] == \
        per["none"] + per["full"]


def test_modeled_activation_bytes_tracks_gqa_and_dtype():
    bf16 = modeled_activation_bytes(
        TransformerConfig(**{**CFG_KW, "dtype": jnp.bfloat16}), batch=2)
    fp32 = modeled_activation_bytes(TransformerConfig(**CFG_KW), batch=2)
    assert 2 * bf16["total_bytes"] == fp32["total_bytes"]
    gqa = modeled_activation_bytes(
        TransformerConfig(num_kv_heads=1, **CFG_KW), batch=2)
    mha = modeled_activation_bytes(TransformerConfig(**CFG_KW), batch=2)
    assert gqa["total_bytes"] < mha["total_bytes"]  # K/V shrink by group


def test_policy_names_are_closed():
    """The bench sweep, docs matrix and config validation share one
    registry."""
    assert set(REMAT_POLICIES) == {"none", "dots", "dots_no_batch",
                                   "full"}
