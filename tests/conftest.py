"""Test configuration: force an 8-device virtual CPU mesh.

Reference test-strategy parity (SURVEY.md §4): the reference simulates
multi-node on one box via N processes + Gloo over loopback; the TPU-native
equivalent is one process with N virtual CPU devices
(``--xla_force_host_platform_device_count``) — per-rank semantics are then
exercised through ``hvd.run_per_rank`` (shard_map), reproducing the
``horovodrun -np N pytest`` per-rank pattern in-process.

NOTE: the axon sitecustomize registers a TPU backend before we run, so
setting JAX_PLATFORMS alone is not enough — we must also override the
already-applied jax config (verified: config.update('jax_platforms','cpu')
after registration yields the CPU backend).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Persistent XLA compilation cache: the suite builds hundreds of
# fresh ServingEngine / mesh instances whose programs lower to
# byte-identical HLO, and per-instance jit closures defeat jax's
# in-memory cache — the disk cache dedupes the XLA compile step both
# within a run and across runs on the same machine.  Semantics-free
# (lowering, engine program counters, and StableHLO pins are all
# upstream of the XLA compile).  Opt out: HVD_TPU_TEST_JAX_CACHE=0.
if os.environ.get("HVD_TPU_TEST_JAX_CACHE", "1") != "0":
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR", "/tmp/hvd_tpu_xla_cache")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _hvd_init():
    import horovod_tpu as hvd

    hvd.init()
    assert hvd.size() == 8, (
        f"expected 8 virtual CPU devices, got {hvd.size()} "
        f"(backend={jax.default_backend()})"
    )
    yield
