"""Elastic subsystem tests.

Reference analog (SURVEY.md §4): test/single/ elastic unit coverage plus
test/integration/test_elastic_torch.py's technique — launch the real
launcher with ``--host-discovery-script`` pointing at a generated script
that reads a mutable hosts file; mutate the file / kill -9 worker PIDs to
simulate scale-up and node failure; assert training bookkeeping survived.
"""

import json
import os
import signal
import stat
import subprocess
import sys
import time

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.elastic import ElasticSampler, ObjectState, TpuState
from horovod_tpu.common.exceptions import (
    HorovodInternalError, HostsUpdatedInterrupt,
)
from envguards import requires_multiprocess_collectives

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "integration", "elastic_worker.py")


# -- unit: state ------------------------------------------------------------

def test_object_state_commit_restore():
    import jax.numpy as jnp

    state = ObjectState(weight=jnp.ones((2,)), epoch=0)
    state.weight = state.weight + 5.0
    state.epoch = 3
    state.restore()  # nothing committed since construction
    np.testing.assert_allclose(np.asarray(state.weight), [1.0, 1.0])
    assert state.epoch == 0

    state.weight = state.weight + 2.0
    state.epoch = 7
    state.commit()
    state.weight = state.weight * 100
    state.restore()
    np.testing.assert_allclose(np.asarray(state.weight), [3.0, 3.0])
    assert state.epoch == 7


def test_object_state_snapshots_are_host_arrays():
    import jax
    import jax.numpy as jnp

    state = TpuState(params={"w": jnp.ones((3,))})
    state.commit()
    kind, payload = state._saved["params"]
    assert kind == "__value__"
    assert isinstance(payload["w"], np.ndarray)  # not a jax.Array
    state._materialize_to_host()
    assert not isinstance(state.params["w"], jax.Array)


def test_object_state_sync_single_process():
    state = ObjectState(x=1)
    state.x = 42
    state.sync()  # world has one process: identity, but must re-save
    state.x = 0
    state.restore()
    assert state.x == 42


def test_state_dict_objects_roundtrip():
    sampler = ElasticSampler(10, shuffle=False)
    state = ObjectState(sampler=sampler, epoch=0)
    sampler.record_batch(0, 2)
    state.commit()
    sampler.record_batch(1, 2)
    assert len(sampler.processed_indices) == 4
    state.restore()
    assert len(sampler.processed_indices) == 2
    assert state.sampler is sampler  # restored through load_state_dict


# -- unit: sampler ----------------------------------------------------------

def test_elastic_sampler_shards_and_records():
    s = ElasticSampler(12, shuffle=False)
    s.num_replicas, s.rank = 2, 0
    s._reshard()
    assert len(s) == 6
    assert list(iter(s)) == [0, 1, 2, 3, 4, 5]
    # one global batch of size 2 consumes 2 indices from each replica
    s.record_batch(0, 2)
    assert sorted(s.processed_indices) == [0, 1, 6, 7]
    # resharding over a new world covers exactly the remaining indices
    s.num_replicas, s.rank = 4, 3
    s._reshard()
    remaining = set(range(12)) - {0, 1, 6, 7}
    shards = [list(s._shard_for(r)) for r in range(4)]
    assert set(sum(shards, [])) == remaining
    assert all(len(sh) == 2 for sh in shards)


def test_elastic_sampler_set_epoch_resets_progress():
    s = ElasticSampler(8, shuffle=True, seed=1)
    s.num_replicas, s.rank = 1, 0
    s.record_batch(0, 4)
    assert len(s.processed_indices) == 4
    s.set_epoch(1)
    assert s.processed_indices == []
    assert len(s) == 8
    # epoch shuffles differ
    s2 = ElasticSampler(8, shuffle=True, seed=1)
    s2.num_replicas, s2.rank = 1, 0
    s2.set_epoch(2)
    assert list(iter(s)) != list(iter(s2))


# -- unit: run wrapper ------------------------------------------------------

def test_run_wrapper_restores_then_restarts_on_internal_error(monkeypatch):
    import horovod_tpu.elastic as elastic

    class Restarted(Exception):
        pass

    seen = {}

    def fake_restart(state):
        seen["value_at_restart"] = state.value
        raise Restarted()  # the real one exec-replaces the process

    monkeypatch.setattr(elastic, "elastic_enabled", lambda: True)
    monkeypatch.setattr(elastic, "restart_after_failure", fake_restart)

    state = ObjectState(value=0)

    @elastic.run
    def train(state):
        state.value = 999  # uncommitted progress that must roll back
        raise HorovodInternalError("peer died")

    with pytest.raises(Restarted):
        train(state)
    assert seen["value_at_restart"] == 0  # restored before the restart


def test_run_wrapper_reraises_without_elastic_driver():
    import horovod_tpu.elastic as elastic

    state = ObjectState(value=0)

    @elastic.run
    def train(state):
        state.value = 999
        raise HorovodInternalError("peer died")

    # no driver to re-rendezvous with: the original failure surfaces,
    # with the state rolled back to the last commit
    with pytest.raises(HorovodInternalError):
        train(state)
    assert state.value == 0


def _fired_watchdog(monkeypatch, state, failure, snapshot_timeout=5.0):
    """Drive WorkerNotificationManager._failure_watchdog to the point of
    forced restart (main thread never clears the pending update) and
    capture what snapshot it would persist."""
    from horovod_tpu.elastic import worker as w

    persisted = {}

    def fake_persist(snap):
        persisted["snap"] = snap
        raise SystemExit(0)  # the real one execv-replaces the process

    monkeypatch.setattr(w, "_persist_and_exec", fake_persist)
    monkeypatch.setattr(w, "_FAILURE_GRACE", 0.2)
    monkeypatch.setattr(w, "_PLANNED_SNAPSHOT_TIMEOUT", snapshot_timeout)

    mgr = w.WorkerNotificationManager()
    mgr.watch_state(state)
    mgr._pending_epoch = 1
    mgr._pending_failure = failure
    with pytest.raises(SystemExit):
        mgr._failure_watchdog()
    return persisted["snap"]


def test_watchdog_failure_rolls_back_to_commit(monkeypatch):
    # On failure=True the watchdog must persist the COMMITTED snapshot,
    # never a live one (live materialization could block on the dead
    # collective it is rescuing the worker from).
    state = ObjectState(value=1)
    state.commit()
    state.value = 999  # uncommitted live progress
    snap = _fired_watchdog(monkeypatch, state, failure=True)
    assert snap is not None
    restored = ObjectState(value=0)
    restored._apply_snapshot(snap)
    assert restored.value == 1


def test_watchdog_planned_change_keeps_live_state(monkeypatch):
    # ADVICE round 3 (medium): a planned change's contract is keep-state.
    # The watchdog must attempt a live snapshot so >grace non-collective
    # phases (eval, checkpoint writes) don't silently lose progress.
    state = ObjectState(value=1)
    state.commit()
    state.value = 999
    snap = _fired_watchdog(monkeypatch, state, failure=False)
    restored = ObjectState(value=0)
    restored._apply_snapshot(snap)
    assert restored.value == 999


def test_watchdog_planned_change_falls_back_when_snapshot_blocks(monkeypatch):
    # If the live snapshot itself wedges (main thread really is stuck in a
    # dead collective), the bounded attempt times out and the committed
    # snapshot is used instead.
    state = ObjectState(value=1)
    state.commit()
    state.value = 999

    real_snapshot = state._snapshot

    def blocked_snapshot():
        time.sleep(60)
        return real_snapshot()

    state._snapshot = blocked_snapshot
    snap = _fired_watchdog(
        monkeypatch, state, failure=False, snapshot_timeout=0.3
    )
    restored = ObjectState(value=0)
    restored._apply_snapshot(snap)
    assert restored.value == 1


def test_run_wrapper_keeps_state_on_hosts_updated(monkeypatch):
    import horovod_tpu.elastic as elastic

    monkeypatch.setattr(elastic, "reset_world", lambda state: None)

    state = ObjectState(value=0, attempts=0)

    @elastic.run
    def train(state):
        state.attempts += 1
        if state.attempts == 1:
            state.value = 7  # planned update: state survives un-rolled-back
            raise HostsUpdatedInterrupt(skip_sync=True)
        return state.value

    assert train(state) == 7


# -- integration: real elastic jobs ----------------------------------------

def _write_discovery(tmp_path, hosts_content):
    hosts = tmp_path / "hosts.txt"
    hosts.write_text(hosts_content)
    script = tmp_path / "discover.sh"
    script.write_text(f"#!/bin/sh\ncat {hosts}\n")
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    return hosts, script


def _elastic_cmd(script, logdir, epochs, batches, min_np=1, np_=None,
                 max_np=None, ballast_bytes=None):
    cmd = [sys.executable, "-m", "horovod_tpu.runner",
           "--host-discovery-script", str(script),
           "--min-np", str(min_np)]
    if np_ is not None:
        cmd += ["-np", str(np_)]
    if max_np is not None:
        cmd += ["--max-np", str(max_np)]
    cmd += ["--", sys.executable, WORKER, str(logdir), str(epochs),
            str(batches)]
    if ballast_bytes is not None:
        cmd.append(str(ballast_bytes))
    return cmd


def _elastic_env():
    env = os.environ.copy()
    env["PALLAS_AXON_POOL_IPS"] = ""  # force CPU in children
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)  # one CPU device per worker process
    env["HVD_TPU_ELASTIC_TIMEOUT"] = "90"
    return env


def _read_logs(logdir):
    events = []
    for name in os.listdir(logdir):
        with open(os.path.join(logdir, name)) as f:
            for line in f:
                ev = json.loads(line)
                ev["worker"] = name
                events.append(ev)
    return events


@pytest.mark.integration
@requires_multiprocess_collectives  # workers allreduce across processes
def test_elastic_scale_up(tmp_path):
    """Start at 1 worker, add a slot mid-run, finish at 2 (reference:
    elastic scale-up via discovery-file mutation)."""
    hosts, script = _write_discovery(tmp_path, "localhost:1\n")
    logdir = tmp_path / "logs"
    logdir.mkdir()
    proc = subprocess.Popen(
        _elastic_cmd(script, logdir, epochs=1, batches=120),
        env=_elastic_env(), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    # scale up as soon as worker 0 is demonstrably training alone
    deadline = time.time() + 90
    while time.time() < deadline:
        if any(e["event"] == "batch" and e["batch"] >= 3
               for e in _read_logs(logdir)):
            break
        time.sleep(0.5)
    hosts.write_text("localhost:2\n")
    try:
        out, err = proc.communicate(timeout=240)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, err = proc.communicate()
        pytest.fail(f"elastic scale-up job hung:\n{err[-3000:]}")
    assert proc.returncode == 0, f"stdout:{out[-2000:]}\nstderr:{err[-3000:]}"
    events = _read_logs(logdir)
    dones = [e for e in events if e["event"] == "done"]
    assert len(dones) == 2, f"expected 2 finishers: {dones}"
    assert all(e["world"] == 2 for e in dones)
    assert all(abs(e["weight"] - 120.0) < 1e-6 for e in dones)
    # worker 0 really did run alone before the rescale
    assert any(e["event"] == "batch" and e["world"] == 1 for e in events)


@pytest.mark.integration
def test_terminated_driver_reaps_workers(tmp_path):
    """SIGTERM on the launcher must take the worker fleet down with it
    (regression: the default SIGTERM handler skipped the driver's
    finally-block and orphaned every elastic worker, which then polluted
    later jobs on the host)."""
    hosts, script = _write_discovery(tmp_path, "localhost:2\n")
    logdir = tmp_path / "logs"
    logdir.mkdir()
    proc = subprocess.Popen(
        _elastic_cmd(script, logdir, epochs=1, batches=2000, min_np=2),
        env=_elastic_env(), cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        deadline = time.time() + 90
        pids = []
        while time.time() < deadline and len(pids) < 2:
            pids = [e["pid"] for e in _read_logs(logdir)
                    if e["event"] == "init"]
            time.sleep(0.5)
        assert len(pids) == 2, "workers never initialized"
        proc.terminate()
        proc.wait(timeout=30)
        deadline = time.time() + 15
        while time.time() < deadline:
            alive = []
            for pid in pids:
                try:
                    os.kill(pid, 0)
                    alive.append(pid)
                except OSError:
                    pass
            if not alive:
                return
            time.sleep(0.5)
        for pid in alive:  # clean up before failing
            os.kill(pid, signal.SIGKILL)
        pytest.fail(f"orphaned workers survived driver SIGTERM: {alive}")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


@pytest.mark.integration
@requires_multiprocess_collectives  # workers allreduce across processes
def test_elastic_restart_cost_bounded_at_100mb(tmp_path):
    """A planned membership change with 100 MB of elastic state must
    exec-restart in bounded time, with the disk snapshot (persist +
    restore) a small fraction of it (VERDICT r3 item 3; the measured
    split lives in PERF.md 'elastic restart cost')."""
    hosts, script = _write_discovery(tmp_path, "localhost:2\n")
    logdir = tmp_path / "logs"
    logdir.mkdir()
    proc = subprocess.Popen(
        _elastic_cmd(script, logdir, epochs=1, batches=400, min_np=1,
                     max_np=3, ballast_bytes=100_000_000),
        env=_elastic_env(), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        # both workers training, then a planned scale-up to 3
        deadline = time.time() + 120
        while time.time() < deadline:
            evs = _read_logs(logdir)
            if sum(1 for e in evs
                   if e["event"] == "batch" and e["batch"] >= 3) >= 2:
                break
            time.sleep(0.5)
        else:
            pytest.fail("workers never started training")
        hosts.write_text("localhost:3\n")
        deadline = time.time() + 180
        stats = []
        while time.time() < deadline and not stats:
            stats = [e for e in _read_logs(logdir)
                     if e["event"] == "restart_stats"]
            time.sleep(0.5)
        assert stats, "no restart_stats event after the planned change"
        for s in stats:
            # snapshot really carried the ballast across the restart
            assert s["snapshot_bytes"] > 100_000_000, s
            # disk snapshot must not dominate: pickle+unpickle of 100 MB
            # is sub-second on any local disk; the bound is generous for
            # CI load
            assert s["persist_s"] + s["restore_s"] < 10.0, s
            # end-to-end bound (reboot includes jax import + rendezvous)
            assert s["total_s"] < 60.0, s
    finally:
        proc.terminate()
        try:
            proc.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()


@pytest.mark.integration
@requires_multiprocess_collectives  # workers allreduce across processes
def test_elastic_fault_recovery(tmp_path):
    """Kill -9 a worker mid-training; survivor rolls back to the last
    commit and finishes alone (reference: elastic_common.py's kill-based
    fault injection)."""
    hosts, script = _write_discovery(tmp_path, "localhost:2\n")
    logdir = tmp_path / "logs"
    logdir.mkdir()
    proc = subprocess.Popen(
        _elastic_cmd(script, logdir, epochs=1, batches=120, min_np=1),
        env=_elastic_env(), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    # wait until both workers are demonstrably TRAINING in the 2-world
    # (not merely initialized: under load, compile time can eat a fixed
    # sleep and the kill would land before any world-2 batch, voiding the
    # scenario this test exists for), then kill rank 1's process
    victim_pid = None
    deadline = time.time() + 120
    while time.time() < deadline and victim_pid is None:
        time.sleep(1.0)
        events = _read_logs(logdir)
        if not any(e["event"] == "batch" and e["world"] == 2
                   for e in events):
            continue
        for e in events:
            if e["event"] == "init" and e["rank"] == 1:
                victim_pid = e["pid"]
    assert victim_pid, "rank 1 never trained in the 2-world"
    time.sleep(1)
    os.kill(victim_pid, signal.SIGKILL)
    try:
        out, err = proc.communicate(timeout=240)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, err = proc.communicate()
        pytest.fail(f"elastic fault-recovery job hung:\n{err[-3000:]}")
    assert proc.returncode == 0, f"stdout:{out[-2000:]}\nstderr:{err[-3000:]}"
    events = _read_logs(logdir)
    dones = [e for e in events if e["event"] == "done"]
    assert len(dones) == 1 and dones[0]["world"] == 1
    assert abs(dones[0]["weight"] - 120.0) < 1e-6
    # the survivor recovered via exec-restart: it initialized twice
    # (first in the 2-world, then alone), and trained in both worlds
    survivor = dones[0]["worker"]
    inits = [e for e in events
             if e["event"] == "init" and e["worker"] == survivor]
    assert len(inits) >= 2, inits
    assert any(e["event"] == "batch" and e["world"] == 2 for e in events)
    assert any(e["event"] == "batch" and e["world"] == 1
               and e["worker"] == survivor for e in events)


@pytest.mark.integration
@requires_multiprocess_collectives  # workers allreduce across processes
def test_elastic_scale_down(tmp_path):
    """Start at 2 workers, remove a slot mid-run: the displaced worker
    rendezvouses, takes the "shutdown" reply and exits 0; the survivor
    exec-restarts with live state and finishes every batch alone
    (reference: elastic discovery-driven scale-down)."""
    hosts, script = _write_discovery(tmp_path, "localhost:2\n")
    logdir = tmp_path / "logs"
    logdir.mkdir()
    proc = subprocess.Popen(
        _elastic_cmd(script, logdir, epochs=1, batches=120, min_np=1),
        env=_elastic_env(), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    # shrink once both workers are demonstrably training together
    deadline = time.time() + 120
    trained_together = False
    while time.time() < deadline:
        if any(e["event"] == "batch" and e["world"] == 2
               for e in _read_logs(logdir)):
            trained_together = True
            break
        time.sleep(0.5)
    if not trained_together:
        proc.kill()
        pytest.fail("2-world training never started before the shrink")
    hosts.write_text("localhost:1\n")
    try:
        out, err = proc.communicate(timeout=240)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, err = proc.communicate()
        pytest.fail(f"elastic scale-down job hung:\n{err[-3000:]}")
    assert proc.returncode == 0, f"stdout:{out[-2000:]}\nstderr:{err[-3000:]}"
    events = _read_logs(logdir)
    dones = [e for e in events if e["event"] == "done"]
    assert len(dones) == 1, dones
    assert dones[0]["world"] == 1
    # no lost or duplicated batches across the resize
    assert abs(dones[0]["weight"] - 120.0) < 1e-6
    # the world really was 2 before the shrink and 1 after
    assert any(e["event"] == "batch" and e["world"] == 2 for e in events)
    assert any(e["event"] == "batch" and e["world"] == 1 for e in events)
    # GRACEFUL path, not crash recovery: no worker failed (the displaced
    # worker took the rendezvous "shutdown" reply and exited 0, so the
    # driver logged no nonzero exits and blacklisted nothing)
    assert "failed with exit code" not in err, err[-2000:]
    # user reset callbacks fired on the survivor after the restart
    assert any(e["event"] == "reset" for e in events), events
