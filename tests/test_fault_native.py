"""Native fault-tolerance paths over ctypes: heartbeats + stall inspector.

Technique (established in test_control_auth.py): drive the REAL native
core in a child process via ctypes against a python fake coordinator
speaking the documented wire — no jax, no fleet, deterministic timing.
Children must call ``hvdtpu_shutdown()`` before exiting or the static
destructors abort.
"""

import os
import socket
import struct
import subprocess
import sys
import threading
import time

import pytest

from envguards import native_child_env, native_lib_path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = native_lib_path(REPO)

HB = struct.pack("<I", 0xFFFFFFFF)  # heartbeat frame (length sentinel)


def _require_lib():
    if not os.path.exists(LIB):
        pytest.skip("native core not built")


def _recv_exact(conn, n):
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError(f"EOF after {len(buf)}/{n} bytes")
        buf += chunk
    return buf


def _accept_hello(srv):
    conn, _ = srv.accept()
    conn.settimeout(60)
    hello = _recv_exact(conn, 5)  # rank(4) + auth flag(1)
    assert struct.unpack("<i", hello[:4])[0] == 1
    conn.sendall(b"\x00")  # coordinator: no secret
    return conn


def _read_worker_frame(conn):
    """One negotiation frame's payload, transparently skipping worker
    heartbeats (liveness-only 4-byte frames)."""
    while True:
        (length,) = struct.unpack("<I", _recv_exact(conn, 4))
        if length == 0xFFFFFFFF:
            continue
        return _recv_exact(conn, length)


_CHILD_PRELUDE = """
import ctypes, sys, time
lib = ctypes.CDLL({lib!r})
lib.hvdtpu_init.restype = ctypes.c_int
lib.hvdtpu_init.argtypes = [
    ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
    ctypes.c_double, ctypes.c_longlong, ctypes.c_int, ctypes.c_char_p,
    ctypes.c_double, ctypes.c_double, ctypes.c_int, ctypes.c_char_p,
]
lib.hvdtpu_heartbeat_misses.restype = ctypes.c_longlong
lib.hvdtpu_heartbeat_misses.argtypes = []
"""


@pytest.mark.integration
def test_heartbeat_timeout_names_silent_peer():
    """A coordinator that goes completely silent after the hello (socket
    open, nothing sent — a hung process) must kill the worker's transport
    at the heartbeat deadline, with the miss counted and the cause
    spelled out, instead of blocking forever."""
    _require_lib()
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    code = _CHILD_PRELUDE.format(lib=LIB) + f"""
rc = lib.hvdtpu_init(1, 2, b"127.0.0.1", {port}, 20.0, 1 << 20, 16, b"",
                     0.0, 0.0, 0, b"")
if rc != 0:
    sys.exit(2)
deadline = time.time() + 30
while time.time() < deadline:
    if lib.hvdtpu_loop_dead():
        misses = lib.hvdtpu_heartbeat_misses()
        print("LOOP_DEAD misses=", misses, flush=True)
        lib.hvdtpu_shutdown()
        sys.exit(0 if misses >= 1 else 3)
    time.sleep(0.05)
print("STILL_ALIVE", flush=True)
sys.exit(4)
"""
    env = native_child_env()
    env.pop("HVD_TPU_SECRET", None)
    env["HVD_TPU_HEARTBEAT_INTERVAL"] = "0.5"
    env["HVD_TPU_HEARTBEAT_TIMEOUT"] = "2"
    proc = subprocess.Popen([sys.executable, "-c", code], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)
    try:
        srv.settimeout(30)
        conn = _accept_hello(srv)
        # total silence: never read, never write — just hold the socket
        out, err = proc.communicate(timeout=60)
        assert proc.returncode == 0, (out, err)
        assert "LOOP_DEAD" in out
        assert "sent nothing (not even heartbeats)" in err
        assert "peer rank 0" in err
        conn.close()
    finally:
        srv.close()
        if proc.poll() is None:
            proc.kill()
            proc.wait()


@pytest.mark.integration
def test_heartbeats_prevent_false_positive_on_busy_peer():
    """A peer that produces no negotiation frames for longer than the
    deadline but DOES heartbeat (the long-XLA-compile case) must not be
    declared dead: each heartbeat re-arms the receive deadline."""
    _require_lib()
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    code = _CHILD_PRELUDE.format(lib=LIB) + f"""
rc = lib.hvdtpu_init(1, 2, b"127.0.0.1", {port}, 20.0, 1 << 20, 16, b"",
                     0.0, 0.0, 0, b"")
if rc != 0:
    sys.exit(2)
time.sleep(3.5)  # > HVD_TPU_HEARTBEAT_TIMEOUT of 1.5s
alive = not lib.hvdtpu_loop_dead()
print("ALIVE" if alive else "DEAD", flush=True)
sys.stdout.flush()
time.sleep(1.0)  # coordinator sends a real frame + closes -> loop ends
lib.hvdtpu_shutdown()
sys.exit(0 if alive else 3)
"""
    env = native_child_env()
    env.pop("HVD_TPU_SECRET", None)
    env["HVD_TPU_HEARTBEAT_INTERVAL"] = "0.5"
    env["HVD_TPU_HEARTBEAT_TIMEOUT"] = "1.5"
    proc = subprocess.Popen([sys.executable, "-c", code], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)
    stop = threading.Event()

    def coordinator(conn):
        # heartbeats only — no negotiation frames — for well past the
        # worker's deadline; then one real (empty) frame and EOF so the
        # worker's loop unblocks and hvdtpu_shutdown can join it
        t_end = time.time() + 4.0
        while time.time() < t_end and not stop.is_set():
            try:
                conn.sendall(HB)
            except OSError:
                return
            time.sleep(0.4)
        try:
            conn.sendall(struct.pack("<I", 0))
            conn.close()
        except OSError:
            pass

    try:
        srv.settimeout(30)
        conn = _accept_hello(srv)
        t = threading.Thread(target=coordinator, args=(conn,), daemon=True)
        t.start()
        out, err = proc.communicate(timeout=60)
        stop.set()
        assert proc.returncode == 0, (out, err)
        assert "ALIVE" in out
    finally:
        stop.set()
        srv.close()
        if proc.poll() is None:
            proc.kill()
            proc.wait()


@pytest.mark.integration
def test_stall_shutdown_surfaces_named_tensor_error():
    """Drive a pending tensor past the warning AND shutdown thresholds
    (the coordinator never acknowledges it) and assert FailAllPending
    delivers the error — NAMING the stuck tensor — to the registered
    exec callback, with the loop marked dead (previously only exercised
    implicitly)."""
    _require_lib()
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    code = _CHILD_PRELUDE.format(lib=LIB) + f"""
EXEC_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ctypes.c_int, ctypes.c_double, ctypes.c_double,
    ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
    ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int),
    ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int),
    ctypes.c_int, ctypes.c_char_p,
)
errors = []
def on_exec(user, op, dtype, ps, root, pre, post, ids, n_ids,
            sdims, sndims, exts, extlens, next_, error):
    if error:
        errors.append(error.decode() if isinstance(error, bytes) else error)
cb = EXEC_CB(on_exec)
lib.hvdtpu_set_exec_callback(cb, None)
# warn at 0.3s, hard shutdown at 0.8s; heartbeats off for framing clarity
rc = lib.hvdtpu_init(1, 2, b"127.0.0.1", {port}, 20.0, 1 << 20, 16, b"",
                     0.3, 0.8, 0, b"")
if rc != 0:
    sys.exit(2)
lib.hvdtpu_enqueue.restype = ctypes.c_longlong
lib.hvdtpu_enqueue.argtypes = [
    ctypes.c_longlong, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
    ctypes.POINTER(ctypes.c_longlong), ctypes.c_int, ctypes.c_int,
    ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
    ctypes.c_double, ctypes.c_double,
    ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
]
shape = (ctypes.c_longlong * 1)(4)
rid = lib.hvdtpu_enqueue(7, b"stalled.grad", 0, 6, shape, 1, 0, b"", 0,
                         0, 1.0, 1.0, None, 0)
print("ENQ", rid, flush=True)
deadline = time.time() + 30
while time.time() < deadline:
    if errors and lib.hvdtpu_loop_dead():
        print("ERR:", errors[0], flush=True)
        lib.hvdtpu_shutdown()
        ok = ("stall shutdown" in errors[0]
              and "stalled.grad" in errors[0])
        sys.exit(0 if ok else 3)
    time.sleep(0.05)
print("NO_ERROR", flush=True)
sys.exit(4)
"""
    env = native_child_env()
    env.pop("HVD_TPU_SECRET", None)
    env["HVD_TPU_HEARTBEAT_INTERVAL"] = "0"  # blocking reads: pure stall
    proc = subprocess.Popen([sys.executable, "-c", code], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)
    stop = threading.Event()

    def coordinator(conn):
        # acknowledge every cycle with an EMPTY response list: the
        # worker's tensor is reported but never marked ready, so it ages
        # past both stall thresholds while cycles keep completing
        while not stop.is_set():
            try:
                _read_worker_frame(conn)
                conn.sendall(struct.pack("<I", 0))
            except (OSError, ConnectionError):
                return

    try:
        srv.settimeout(30)
        conn = _accept_hello(srv)
        t = threading.Thread(target=coordinator, args=(conn,), daemon=True)
        t.start()
        out, err = proc.communicate(timeout=60)
        stop.set()
        assert proc.returncode == 0, (out, err)
        assert "stall shutdown" in out and "stalled.grad" in out
        # the warning fired on the way to the shutdown threshold
        assert "possible stall" in err
        conn.close()
    finally:
        stop.set()
        srv.close()
        if proc.poll() is None:
            proc.kill()
            proc.wait()
