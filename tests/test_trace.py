"""Tests for horovod_tpu.trace — the span recorder, Chrome export,
/trace control endpoint, cross-rank merge, flight recorder, and the
analysis ``trace`` pass (ISSUE 15).

The endpoint tests bind an ephemeral port explicitly (tier-1 never
binds a port outside these tests — the exposition opt-in discipline
from test_metrics.py).
"""

import json
import logging
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from horovod_tpu import trace
from horovod_tpu.metrics import exposition
from horovod_tpu.metrics.registry import MetricsRegistry
from horovod_tpu.trace import export as trace_export
from horovod_tpu.trace import flight
from horovod_tpu.utils import profiler


@pytest.fixture(autouse=True)
def _tracing_on():
    """Every test starts (and leaves) the recorder enabled — the
    process default."""
    trace.configure(enabled=True)
    yield
    trace.configure(enabled=True)


# -- recorder core -----------------------------------------------------------


def test_span_event_add_span_record():
    t0 = trace.now()
    with trace.span("train.step", step=7):
        time.sleep(0.002)
    trace.event("chaos.inject", site="elastic.commit", action="kill")
    trace.add_span("serve.queued", trace.now() - 0.25, trace.now(),
                   rid=987654)
    # the retroactive queued span STARTS before t0 — widen the window;
    # other suites' engines may have recorded at these sites too, so
    # select THIS test's records by their args
    recs = trace.snapshot(since=t0 - 0.5)
    step = [r for r in recs if r[0] == "train.step"
            and r[3] == {"step": 7}]
    assert step and step[0][2] >= 0.002 and step[0][4]  # dur + tid
    inject = [r for r in recs if r[0] == "chaos.inject"
              and (r[3] or {}).get("site") == "elastic.commit"]
    assert inject and inject[-1][2] is None  # instant: no duration
    queued = [r for r in recs if r[0] == "serve.queued"
              and (r[3] or {}).get("rid") == 987654]
    assert queued and abs(queued[0][2] - 0.25) < 1e-6


def test_disabled_recorder_records_nothing():
    trace.configure(enabled=False)
    t0 = trace.now()
    with trace.span("train.step", step=1):
        pass
    trace.event("serve.finish", rid=0)
    trace.add_span("serve.queued", t0, trace.now())
    assert trace.snapshot(since=t0) == []
    trace.configure(enabled=True)
    with trace.span("train.step", step=2):
        pass
    assert len(trace.snapshot(since=t0)) == 1


def test_ring_is_bounded_and_keeps_newest():
    r = trace._Ring(8, "t")
    for i in range(20):
        r.append(("s", float(i), 0.0, None))
    recs = r.records()
    assert len(recs) == 8
    assert [rec[1] for rec in recs] == [float(i) for i in range(12, 20)]


def test_main_ring_survives_worker_thread_churn():
    """Regression: ring-registry eviction must retire DEAD threads'
    rings only — 100 short-lived recording threads once evicted the
    main thread's ring, silently losing every later training span."""
    def rec():
        with trace.span("serve.step", kind="decode"):
            pass

    before = len(trace._rings)
    for _ in range(100):
        t = threading.Thread(target=rec)
        t.start()
        t.join()
    t0 = trace.now()
    trace.event("chaos.inject", site="elastic.commit", action="kill")
    assert any(r[0] == "chaos.inject" for r in trace.snapshot(since=t0))
    # dead rings are BOUNDED: the newest 64 are always kept (a
    # just-dead thread's final spans are flight-recorder evidence) and
    # older dead rings retire, so 100 churned threads add at most 64 —
    # while alive threads' rings (other tests may leak parked ones)
    # are never evicted at any age
    assert len(trace._rings) <= before + 67


def test_profiler_span_unifies_into_recorder():
    t0 = trace.now()
    with profiler.span("grad_3", "ENQUEUE"):
        pass
    with profiler.span("ALLREDUCE", "XLA_COMM"):
        pass
    sites = {r[0]: r[3] for r in trace.snapshot(since=t0)}
    assert sites.get("collective.enqueue") == {"name": "grad_3"}
    assert sites.get("collective.exec") == {"name": "ALLREDUCE"}


def test_trace_context_ids_are_unique():
    ids = {trace.new_trace_id() for _ in range(100)}
    assert len(ids) == 100


# -- chrome export -----------------------------------------------------------


def _assert_valid_chrome(doc):
    assert isinstance(doc["traceEvents"], list)
    for e in doc["traceEvents"]:
        assert isinstance(e["name"], str) and "ph" in e
        if e["ph"] == "X":
            assert "ts" in e and "dur" in e
        if e["ph"] == "i":
            assert "ts" in e
    json.dumps(doc)  # must be serializable as-is


def test_chrome_trace_export_shape():
    t0 = trace.now()
    with trace.span("serve.step", kind="decode", batch=2, rids=[0, 1]):
        pass
    trace.event("serve.finish", rid=0, tokens=3)
    doc = trace_export.chrome_trace(since=t0, pid=5)
    _assert_valid_chrome(doc)
    names = [e["name"] for e in doc["traceEvents"]]
    assert "process_name" in names and "thread_name" in names
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert spans and all(e["pid"] == 5 for e in spans)
    # timestamps are epoch microseconds (merge axis)
    assert abs(spans[0]["ts"] / 1e6 - time.time()) < 60


def test_write_dump_roundtrip(tmp_path):
    with trace.span("train.step", step=1):
        pass
    path = trace_export.write_dump(str(tmp_path / "rank0.json"))
    with open(path) as f:
        doc = json.load(f)
    _assert_valid_chrome(doc)
    assert doc["metadata"]["format"].startswith("horovod_tpu.trace/")


# -- cross-rank merge --------------------------------------------------------


def _synthetic_rank_dump(rank, clock_skew_us, steps=(1, 2, 3)):
    events = []
    for s in steps:
        events.append({"name": "train.step", "ph": "X", "pid": 0, "tid": 1,
                       "ts": 1e12 + s * 1e5 + clock_skew_us,
                       "dur": 5e4, "args": {"step": s}})
    events.append({"name": "serve.finish", "ph": "i", "pid": 0, "tid": 1,
                   "ts": 1e12 + clock_skew_us, "args": {"rid": rank}})
    return {"traceEvents": events, "metadata": {"rank": rank}}


def test_merge_ranks_step_boundary_alignment():
    a = _synthetic_rank_dump(0, 0.0)
    b = _synthetic_rank_dump(1, 7.5e6)  # 7.5 s of wall-clock skew
    merged = trace_export.merge_ranks([a, b])
    assert merged["metadata"]["ranks"] == [0, 1]
    off = merged["metadata"]["clock_offsets_us"]["1"]
    assert abs(off + 7.5e6) < 1.0  # skew recovered from step anchors
    starts = {}
    for e in merged["traceEvents"]:
        if e["name"] == "train.step":
            starts.setdefault(e["args"]["step"], []).append(
                (e["pid"], e["ts"]))
    for step, pairs in starts.items():
        ts = {pid: t for pid, t in pairs}
        assert abs(ts[0] - ts[1]) < 1.0  # aligned after the shift
    # non-step events shifted by the same offset (pid stamped too)
    fins = [e for e in merged["traceEvents"] if e["name"] == "serve.finish"]
    assert {e["pid"] for e in fins} == {0, 1}


def test_merge_ranks_without_common_steps_merges_raw():
    a = _synthetic_rank_dump(0, 0.0, steps=(1, 2))
    b = _synthetic_rank_dump(1, 123.0, steps=(8, 9))
    merged = trace_export.merge_ranks([a, b])
    assert merged["metadata"]["clock_offsets_us"]["1"] == 0.0


# -- TTFT decomposition ------------------------------------------------------


def test_request_decomposition_sums_terms():
    recs = [
        ("serve.queued", 0.0, 0.10, {"rid": 4}, "t"),
        ("serve.prefill_chunk", 0.1, 0.20, {"rid": 4, "chunk": 16}, "t"),
        ("serve.prefill_chunk", 0.3, 0.10, {"rid": 4, "chunk": 8}, "t"),
        ("serve.prefill_chunk", 0.3, 9.99, {"rid": 5, "chunk": 8}, "t"),
        ("serve.first_decode", 0.4, 0.05, {"rid": 4}, "t"),
        ("serve.first_token", 0.45, None, {"rid": 4, "ttft": 0.47}, "t"),
    ]
    d = trace_export.request_decomposition(recs, 4)
    assert abs(d["sum_s"] - 0.45) < 1e-9
    assert abs(d["err_s"] - 0.02) < 1e-9
    assert trace_export.request_decomposition(recs, 5) is None  # no TTFT
    # a re-admission's second queued span must not displace the first
    recs.append(("serve.queued", 0.5, 5.0, {"rid": 4}, "t"))
    assert trace_export.request_decomposition(recs, 4)["queued_s"] == 0.10


def test_engine_ttft_decomposition_real_spans():
    """A real (tiny) serving burst: per-request spans decompose TTFT
    within tolerance, and a router-style trace id propagates engine ->
    scheduler -> every span of the request."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from horovod_tpu.models.transformer import (
        Transformer, TransformerConfig,
    )
    from horovod_tpu.serving.engine import ServeConfig, ServingEngine

    cfg = TransformerConfig(
        vocab_size=64, num_layers=1, num_heads=2, head_dim=8,
        max_seq_len=32, dtype=jnp.float32, attention_impl="dot",
        causal=True)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    eng = ServingEngine(cfg, params,
                        serve=ServeConfig(decode_tiers=(1, 2),
                                          token_budget=128))
    t0 = trace.now()
    rid = eng.submit(np.arange(1, 9), 3, trace_id="t0-abc-1")
    eng.run()
    recs = trace.snapshot(since=t0)
    d = trace_export.request_decomposition(recs, rid)
    assert d is not None
    assert d["err_s"] <= max(0.05, 0.5 * d["measured_ttft_s"])
    tagged = [r for r in recs
              if r[3] and r[3].get("trace") == "t0-abc-1"]
    tagged_sites = {r[0] for r in tagged}
    assert "serve.queued" in tagged_sites  # scheduler saw the context
    assert {"serve.first_token", "serve.finish"} <= tagged_sites


# -- the /trace endpoint -----------------------------------------------------


def test_trace_endpoint_roundtrip_and_alias():
    trace_export.register_trace_endpoint()
    with trace.span("train.step", step=42):
        pass
    srv = exposition.MetricsHTTPServer(0, registry=MetricsRegistry())
    try:
        base = f"http://127.0.0.1:{srv.port}"
        for path in ("/trace", "/control/trace"):
            resp = urllib.request.urlopen(base + path, timeout=10)
            assert resp.status == 200
            doc = json.loads(resp.read().decode())
            _assert_valid_chrome(doc)
            assert any(e["name"] == "train.step"
                       for e in doc["traceEvents"])
        # ?since bounds the window: a far-future cut returns no spans
        resp = urllib.request.urlopen(
            f"{base}/trace?since={trace.now() + 1e6}", timeout=10)
        doc = json.loads(resp.read().decode())
        assert all(e["ph"] == "M" for e in doc["traceEvents"])
    finally:
        srv.close()


def test_trace_endpoint_concurrent_scrape_while_recording():
    trace_export.register_trace_endpoint()
    srv = exposition.MetricsHTTPServer(0, registry=MetricsRegistry())
    errors = []
    stop = threading.Event()

    def scrape():
        url = f"http://127.0.0.1:{srv.port}/trace"
        while not stop.is_set():
            try:
                doc = json.loads(
                    urllib.request.urlopen(url, timeout=10).read())
                _assert_valid_chrome(doc)
            except Exception as e:  # noqa: BLE001 - surface in the test
                errors.append(e)
                return

    try:
        threads = [threading.Thread(target=scrape) for _ in range(3)]
        for t in threads:
            t.start()
        for i in range(2000):
            with trace.span("serve.step", kind="decode", batch=i % 8):
                pass
            trace.event("serve.finish", rid=i)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, errors
    finally:
        stop.set()
        srv.close()


def test_deny_remote_gate():
    assert not exposition._deny_remote("127.0.0.1")
    assert not exposition._deny_remote("127.3.2.1")
    assert not exposition._deny_remote("::1")
    assert exposition._deny_remote("10.0.0.5")
    os.environ["HVD_TPU_CONTROL_REMOTE"] = "1"
    try:
        assert not exposition._deny_remote("10.0.0.5")
    finally:
        os.environ.pop("HVD_TPU_CONTROL_REMOTE", None)


def test_trace_endpoint_loopback_only_403(monkeypatch):
    """The PR-13 rule on the NEW endpoint: a non-loopback client gets
    403 (every local connection source-routes from 127.0.0.1, so the
    unit-tested gate is forced remote for the integration half)."""
    trace_export.register_trace_endpoint()
    monkeypatch.setattr(exposition, "_deny_remote", lambda ip: True)
    srv = exposition.MetricsHTTPServer(0, registry=MetricsRegistry())
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/trace", timeout=10)
        assert exc.value.code == 403
        # the read-only scrape surface stays open to everyone
        assert urllib.request.urlopen(
            f"{base}/metrics", timeout=10).status == 200
    finally:
        srv.close()


# -- flight recorder ---------------------------------------------------------


def test_flight_dump_disabled_without_dir(monkeypatch):
    monkeypatch.delenv("HVD_TPU_TRACE_BUNDLE_DIR", raising=False)
    assert flight.maybe_dump("chaos_kill") is None


def test_flight_bundle_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_TPU_TRACE_BUNDLE_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_TPU_TRACE_BUNDLE_SECONDS", "60")
    flight._last_dump.clear()
    flight.note_metrics_baseline()
    from horovod_tpu.metrics import instruments as _instr

    _instr.CHAOS_INJECTIONS.labels("elastic.commit", "kill").inc()
    trace.event("chaos.inject", site="elastic.commit", action="kill")
    path = flight.maybe_dump("chaos_kill", extra={"site": "elastic.commit"})
    assert path and os.path.exists(path)
    bundle = flight.read_bundle(path)
    assert bundle["reason"] == "chaos_kill"
    assert bundle["extra"] == {"site": "elastic.commit"}
    assert any(
        e["name"] == "chaos.inject"
        and e.get("args", {}).get("site") == "elastic.commit"
        for e in bundle["trace"]["traceEvents"])
    # the metric delta since the baseline is in the bundle
    deltas = bundle["metric_deltas"]
    key = [k for k in deltas
           if k.startswith("hvd_tpu_chaos_injections_total")
           and "elastic.commit" in k]
    assert key and deltas[key[0]] == 1.0
    # checksum really guards the payload
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(raw[:-10] + bytes([raw[-10] ^ 0x40]) + raw[-9:])
    with pytest.raises(ValueError):
        flight.read_bundle(path)


def test_flight_dump_rate_limited(tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_TPU_TRACE_BUNDLE_DIR", str(tmp_path))
    flight._last_dump.clear()
    assert flight.maybe_dump("rollback") is not None
    # stacked response paths (rollback -> exec-restart) dump ONCE
    assert flight.maybe_dump("restart") is None


def test_routine_dump_never_suppresses_a_crash_dump(tmp_path, monkeypatch):
    """An autoscaler slo_breach bundle moments before a quarantine must
    NOT cost the black box its crash evidence — the 2 s rate limit is
    per class, and routine never suppresses crash."""
    monkeypatch.setenv("HVD_TPU_TRACE_BUNDLE_DIR", str(tmp_path))
    flight._last_dump.clear()
    assert flight.maybe_dump("slo_breach") is not None
    assert flight.maybe_dump("quarantine") is not None  # crash: dumps
    assert flight.maybe_dump("slo_breach") is None      # routine: limited


def test_flight_bundle_retention_cap(tmp_path, monkeypatch):
    """An oscillating fleet dumps one slo_breach bundle per scale-out —
    the retention cap keeps the newest N so the directory is bounded."""
    monkeypatch.setenv("HVD_TPU_TRACE_BUNDLE_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_TPU_TRACE_BUNDLE_KEEP", "3")
    for i in range(6):
        flight._last_dump.clear()  # bypass the 2 s dedupe
        assert flight.maybe_dump("slo_breach") is not None
    left = sorted(n for n in os.listdir(tmp_path)
                  if n.startswith("bundle-"))
    assert len(left) == 3
    # the NEWEST survive (counter suffix ascends within a process)
    assert all(int(n.rsplit("-", 1)[1].split(".")[0]) >= 4
               for n in left), left


# -- structured logging ------------------------------------------------------


def test_structured_log_context_and_json_formatter():
    from horovod_tpu.utils import logging as hvd_logging

    hvd_logging.set_log_context(rank=3, step=17)
    rec = logging.LogRecord("horovod_tpu", logging.WARNING, "f.py", 1,
                            "hello %s", ("world",), None)
    assert hvd_logging._ContextFilter().filter(rec)
    assert rec.rank == 3 and rec.step == 17 and rec.host
    out = json.loads(hvd_logging._JsonFormatter().format(rec))
    assert out["msg"] == "hello world"
    assert out["rank"] == 3 and out["step"] == 17
    assert out["level"] == "WARNING"
    hvd_logging.set_log_context(rank="-", step="-")


# -- the analysis `trace` pass -----------------------------------------------


def _tree(tmp_path, catalogue_sites, code, doc_sites):
    (tmp_path / "horovod_tpu" / "trace").mkdir(parents=True)
    (tmp_path / "docs").mkdir()
    cat = "SITES = (\n" + "".join(
        f'    "{s}",\n' for s in catalogue_sites) + ")\n"
    (tmp_path / "horovod_tpu" / "trace" / "__init__.py").write_text(cat)
    (tmp_path / "horovod_tpu" / "mod.py").write_text(code)
    rows = "| site | kind |\n|---|---|\n" + "".join(
        f"| `{s}` | span |\n" for s in doc_sites)
    (tmp_path / "docs" / "TRACING.md").write_text(rows)
    return str(tmp_path)


def test_trace_pass_clean_tree(tmp_path):
    from horovod_tpu.analysis import trace_sites

    root = _tree(
        tmp_path, ["train.step", "serve.finish"],
        'from . import trace\n'
        'with trace.span("train.step", step=1):\n'
        '    trace.event("serve.finish")\n',
        ["train.step", "serve.finish"])
    assert trace_sites.run(root) == []


def test_trace_pass_catches_every_drift_class(tmp_path):
    from horovod_tpu.analysis import trace_sites

    root = _tree(
        tmp_path,
        ["train.step", "dead.site", "undocumented.site"],
        'from . import trace\n'
        'trace.event("train.step")\n'
        'trace.event("undocumented.site")\n'
        'trace.add_span("rogue.site", 0, 1)\n',
        ["train.step", "ghost.site"])
    keys = {(f.key, f.file.split("/")[-1])
            for f in trace_sites.run(root)}
    assert ("rogue.site", "mod.py") in keys          # uncatalogued call
    assert ("dead.site", "__init__.py") in keys      # dead catalogue
    assert ("undocumented.site", "__init__.py") in keys  # missing doc row
    assert ("ghost.site", "TRACING.md") in keys      # stale doc row


def test_trace_pass_registered_and_repo_clean():
    from horovod_tpu import analysis

    assert "trace" in analysis.PASSES
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert analysis.PASSES["trace"](repo) == []
