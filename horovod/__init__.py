"""``horovod`` compatibility alias for ``horovod_tpu``.

Reference parity: the reference's public import surface itself
(horovod/__init__.py and its framework submodules — SURVEY.md §2.3/§2.4).
This thin distribution makes the north-star sentence literally true: a
reference-style script with UNCHANGED imports (``import horovod.torch as
hvd``, ``import horovod.tensorflow``, ``from horovod import run``, ...)
runs on the TPU backend, and ``horovodrun`` delegates to ``tpurun``.

Mechanism: a meta-path finder redirects every ``horovod.X`` import to
the already-packaged ``horovod_tpu.X`` module — the SAME module object
is registered under both names, so there is no duplicated module state
(singletons like the controller, handle tables, and process-set
registries stay unique).  No code is copied; this package is one file.
"""

from __future__ import annotations

import importlib
import importlib.abc
import importlib.util
import sys

from horovod_tpu import *  # noqa: F401,F403 — the reference's flat surface
from horovod_tpu import __version__  # noqa: F401


class _AliasLoader(importlib.abc.Loader):
    """Loader that materializes ``horovod.X`` as ``horovod_tpu.X``."""

    def __init__(self, real_name: str):
        self._real_name = real_name

    def create_module(self, spec):
        # returning the real (possibly cached) module makes both names
        # share one module object
        return importlib.import_module(self._real_name)

    def exec_module(self, module):
        pass  # already executed under its real name


class _AliasFinder(importlib.abc.MetaPathFinder):
    def find_spec(self, fullname, path=None, target=None):
        if not fullname.startswith("horovod."):
            return None
        real_name = "horovod_tpu." + fullname[len("horovod."):]
        try:
            real_spec = importlib.util.find_spec(real_name)
        except (ImportError, AttributeError, ValueError):
            return None
        if real_spec is None:
            return None
        return importlib.util.spec_from_loader(
            fullname,
            _AliasLoader(real_name),
            is_package=real_spec.submodule_search_locations is not None,
        )


# idempotent: re-imports (or importlib.reload) must not stack finders
if not any(isinstance(f, _AliasFinder) for f in sys.meta_path):
    sys.meta_path.insert(0, _AliasFinder())

# the reference exposes horovod.run (the launcher package, providing
# horovod.run.run_commandline) under a name that does not textually map
# to horovod_tpu.run — pre-register the alias
sys.modules.setdefault(
    "horovod.run", importlib.import_module("horovod_tpu.runner")
)
