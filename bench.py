#!/usr/bin/env python
"""Headline benchmark: ResNet-50 synthetic-data training throughput.

Reference parity: examples/pytorch/pytorch_synthetic_benchmark.py and
examples/tensorflow2/tensorflow2_synthetic_benchmark.py — the scripts the
reference's own docs point at for measuring img/sec (BASELINE.md).  Same
protocol: synthetic ImageNet-shaped data, warmup then timed steps, report
images/sec.

Baseline constant: the reference repo publishes no absolute number
(BASELINE.md: "user-measured"); the widely reported figure for its
pytorch_synthetic_benchmark on the reference-era flagship (V100, fp32,
batch 32) is ~330 img/sec, which we use as vs_baseline's denominator.

Robustness contract (VERDICT round 1 item 1b): every backend touch happens
in a SUBPROCESS under a hard deadline (a bare in-process ``jax.devices()``
can hang for minutes when the axon tunnel is down — the round-1 failure
mode).  Orchestration: bounded-retry TPU probe → timed TPU attempt →
virtual-CPU fallback, so the run always emits its one JSON line.

Output: ONE JSON line {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import subprocess
import sys
import time

BASELINE_IMG_PER_SEC = 330.0  # reference pytorch_synthetic_benchmark, 1x V100 fp32

# Dense peak bf16 FLOP/s per chip by generation, for the MFU estimate.
PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}
# ResNet-50 fwd @224 is ~4.09 GMACs = ~8.2 GFLOP (mul+add counted
# separately, the standard MFU convention); training step ~= 3x forward.
# Round 2 used 4.1e9 here — the MAC count — which under-stated MFU by 2x.
# Cross-checked against XLA cost analysis: 3.06e12 FLOP/step at batch 128
# = 7.97e9 fwd FLOP/img (PERF.md).  The headline "mfu" field is computed
# from the compiled program's own cost_analysis() when available, with
# this analytic constant as fallback ("mfu_model").
RESNET50_TRAIN_FLOPS_PER_IMG = 3 * 2 * 4.09e9

def peak_flops_for_current_gen():
    """Per-chip dense bf16 peak for the TPU generation the axon tunnel
    reports, or None when unknown (an assumed denominator would mis-state
    MFU by up to ~4.7x across generations)."""
    return PEAK_FLOPS.get(os.environ.get("PALLAS_AXON_TPU_GEN"))


PROBE_TIMEOUT_S = 60
# Overall deadline for the whole bench orchestration.  The driver runs this
# script under an external timeout; if that kills us before the result line
# prints, the round records NOTHING — strictly worse than a CPU fallback.
# Every window below is clipped so a CPU line always lands inside this.
DEADLINE_S = float(os.environ.get("BENCH_DEADLINE_S", 1800))
# Wall-clock budget for the initial probe window.  One axon outage at
# bench time erased round 3's TPU number (VERDICT round 3 weak #1); the
# probe now keeps retrying with backoff for this long before conceding.
PROBE_WINDOW_S = float(os.environ.get("BENCH_PROBE_WINDOW_S", 420))
# After a CPU fallback run, one last TPU attempt is made (the tunnel may
# have recovered while the CPU run burned time) within this extra window.
FINAL_PROBE_WINDOW_S = float(os.environ.get("BENCH_FINAL_PROBE_WINDOW_S", 120))
TPU_RUN_TIMEOUT_S = 330
CPU_RUN_TIMEOUT_S = 150


def probe_backend(window_s: float) -> str:
    """Probe the default JAX backend in a subprocess with a hard
    per-attempt timeout, retrying with backoff until ``window_s`` of
    wall-clock is spent.

    Returns "tpu", "cpu" (a clean deterministic cpu-only answer — no
    retries, no point re-probing later), or "unknown" (failures/hangs
    exhausted the window; the tunnel may recover)."""
    probe = "import jax; d = jax.devices(); assert d; print(d[0].platform)"
    deadline = time.monotonic() + window_s
    attempt = 0
    while True:
        attempt += 1
        per_attempt = min(PROBE_TIMEOUT_S, max(5, deadline - time.monotonic()))
        try:
            out = subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True,
                text=True,
                timeout=per_attempt,
            )
            if out.returncode == 0:
                return "cpu" if "cpu" in out.stdout else "tpu"
            reason = (out.stderr.strip().splitlines() or ["rc=%d" % out.returncode])[-1]
        except subprocess.TimeoutExpired:
            reason = f"probe hung >{per_attempt:.0f}s"
        remaining = deadline - time.monotonic()
        print(
            f"[bench] TPU probe attempt {attempt} failed ({reason}); "
            f"{remaining:.0f}s left in window",
            file=sys.stderr,
        )
        if remaining <= 5:
            return "unknown"
        time.sleep(min(remaining, min(60, 5 * attempt)))


def run_worker(mode: str, timeout_s: int):
    """Run ``bench.py --worker <mode>`` under a deadline.  Returns the JSON
    result line (str) or None — the caller decides which line to print so
    the one-line output contract holds across fallback + re-attempt."""
    env = dict(os.environ)
    if mode == "cpu":
        # prevent axon registration entirely so nothing can hang
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["JAX_PLATFORMS"] = "cpu"
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker", mode],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
        )
    except subprocess.TimeoutExpired as e:
        if e.stderr:
            err = e.stderr if isinstance(e.stderr, str) else e.stderr.decode()
            sys.stderr.write(err[-3000:])
        print(f"[bench] {mode} run hung >{timeout_s}s", file=sys.stderr)
        return None
    sys.stderr.write(out.stderr)
    for line in out.stdout.splitlines():
        if line.startswith("{"):
            return line
    print(f"[bench] {mode} run rc={out.returncode}, no result line", file=sys.stderr)
    return None


def worker(mode: str) -> int:
    """The measured run itself.  mode: 'tpu' (default backend) or 'cpu'."""
    import jax

    if mode == "cpu":
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models.resnet import ResNet50
    from horovod_tpu import training

    hvd.init()
    on_tpu = jax.default_backend() != "cpu"
    if mode == "tpu" and not on_tpu:
        print("[bench] worker asked for tpu but got cpu backend", file=sys.stderr)
        return 1
    batch = 128 if on_tpu else 16
    image_size = 224 if on_tpu else 64
    warmup, iters = (5, 30) if on_tpu else (1, 2)

    # space_to_depth stem: mathematically identical to the classic 7x7/s2
    # stem (equivalence proven by test_space_to_depth_stem_equivalence)
    # but MXU-friendly — measured ~3.5% faster end-to-end (PERF.md)
    model = ResNet50(
        num_classes=1000, dtype=jnp.bfloat16, stem="space_to_depth"
    )
    rng = jax.random.PRNGKey(0)
    images = jnp.asarray(
        np.random.RandomState(0)
        .randn(batch, image_size, image_size, 3)
        .astype(np.float32)
    )
    labels = jnp.asarray(
        np.random.RandomState(1).randint(0, 1000, size=(batch,))
    )

    optimizer = optax.sgd(0.1, momentum=0.9)
    state = training.create_train_state(model, optimizer, rng, images[:2])
    state = training.replicate_state(state)
    step = training.data_parallel_train_step(model, optimizer)

    # AOT-compile once; reuse the executable for the loops (the jit cache
    # is not guaranteed to share an AOT compilation, and compiling twice
    # risks the TPU_RUN_TIMEOUT_S deadline).  XLA's own FLOP count is the
    # self-verifying numerator for MFU (PERF.md documents the cross-check
    # vs the analytic count) — but cost_analysis() describes the
    # SPMD-partitioned *per-device* module, so it is only used as the
    # headline MFU when there is exactly one device (the bench's config);
    # multi-device runs fall back to the analytic model count.
    xla_flops = None
    try:
        step = step.lower(state, images, labels).compile()
    except Exception as e:
        print(f"[bench] AOT compile unavailable: {e}", file=sys.stderr)
    else:
        try:
            ca = step.cost_analysis()
            if isinstance(ca, list):
                ca = ca[0] if ca else None
            if ca:
                xla_flops = float(ca.get("flops", 0)) or None
        except Exception as e:  # best-effort on remote backends
            print(f"[bench] cost_analysis unavailable: {e}", file=sys.stderr)

    for _ in range(warmup):
        state, loss = step(state, images, labels)
    # fetch the scalar (not just block_until_ready): a device->host
    # roundtrip is the only sync some remote backends honor
    float(loss)

    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = step(state, images, labels)
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss), f"non-finite loss {final_loss}"

    img_per_sec = batch * iters / dt
    result = {
        "metric": "resnet50_synthetic_train_throughput",
        "value": round(img_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 3),
        "backend": jax.default_backend(),
        "batch": batch,
        "image_size": image_size,
        "step_time_ms": round(dt / iters * 1e3, 2),
        "n_devices": jax.device_count(),
    }
    if not on_tpu:
        # the record must say WHY it is a CPU number (probe failure or a
        # failed TPU attempt — the orchestrator prints which to stderr);
        # the chip-measured history lives in PERF.md / BENCH_r02.json
        result["note"] = (
            "cpu fallback: the tpu probe or tpu run failed at bench "
            "time; see PERF.md for the chip-measured record"
        )
    gen = os.environ.get("PALLAS_AXON_TPU_GEN")
    if on_tpu and image_size == 224 and gen in PEAK_FLOPS:
        # MFU only when the generation is explicitly known — a guessed
        # peak-FLOPs denominator would mis-state MFU by up to ~4.7x.
        # img_per_sec is aggregate across the data-parallel world, so
        # normalize to per-chip before dividing by per-chip peak.
        peak = PEAK_FLOPS[gen]
        step_s = dt / iters
        mfu_model = round(
            img_per_sec / jax.device_count()
            * RESNET50_TRAIN_FLOPS_PER_IMG / peak, 4
        )
        if xla_flops and jax.device_count() == 1:
            # headline MFU from XLA's measured FLOP count of the compiled
            # step — unambiguous single-chip (per-device == whole-program)
            result["mfu"] = round(xla_flops / step_s / peak, 4)
            result["mfu_model"] = mfu_model
            result["xla_flops_per_step"] = xla_flops
        else:
            result["mfu"] = mfu_model
        result["tpu_gen"] = gen
    print(json.dumps(result))
    return 0


def main() -> int:
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        return worker(sys.argv[2])
    t0 = time.monotonic()

    def remaining() -> float:
        return DEADLINE_S - (time.monotonic() - t0)

    # clip the probe window so a failed probe + CPU fallback still fits
    probe_window = max(
        30.0, min(PROBE_WINDOW_S, remaining() - CPU_RUN_TIMEOUT_S - 30))
    backend = probe_backend(probe_window)
    if backend == "tpu":
        line = run_worker("tpu", TPU_RUN_TIMEOUT_S)
        if line:
            print(line)
            return 0
        print("[bench] TPU attempt failed; falling back to CPU", file=sys.stderr)
    elif backend == "cpu":
        print(
            "[bench] this host's default backend is CPU (deterministic "
            "answer, no retries spent); running the CPU benchmark",
            file=sys.stderr,
        )
    else:
        print(
            f"[bench] TPU probe exhausted its {probe_window:.0f}s retry "
            "window; running the CPU fallback, then re-probing once more",
            file=sys.stderr,
        )
    cpu_line = run_worker("cpu", CPU_RUN_TIMEOUT_S)
    # End-of-run TPU re-attempt — for the hung/unknown probe and for a
    # probe-ok-but-run-failed outage (the tunnel may have recovered while
    # the CPU run burned time); never for a deterministic cpu-only host.
    # A late TPU number beats a CPU fallback every time — but only chase
    # it when a full probe + chip run still fits the deadline; at the
    # margin, banking the CPU line beats risking an empty round.
    if (backend != "cpu"
            and remaining() > FINAL_PROBE_WINDOW_S + TPU_RUN_TIMEOUT_S + 30
            and probe_backend(FINAL_PROBE_WINDOW_S) == "tpu"):
        print("[bench] TPU recovered; re-attempting the chip run", file=sys.stderr)
        line = run_worker("tpu", TPU_RUN_TIMEOUT_S)
        if line:
            print(line)
            return 0
    if cpu_line:
        print(cpu_line)
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
