#!/usr/bin/env python
"""Headline benchmark: ResNet-50 synthetic-data training throughput.

Reference parity: examples/pytorch/pytorch_synthetic_benchmark.py and
examples/tensorflow2/tensorflow2_synthetic_benchmark.py — the scripts the
reference's own docs point at for measuring img/sec (BASELINE.md).  Same
protocol: synthetic ImageNet-shaped data, warmup then timed steps, report
images/sec.

Baseline constant: the reference repo publishes no absolute number
(BASELINE.md: "user-measured"); the widely reported figure for its
pytorch_synthetic_benchmark on the reference-era flagship (V100, fp32,
batch 32) is ~330 img/sec, which we use as vs_baseline's denominator.

Robustness contract (VERDICT round 1 item 1b): every backend touch happens
in a SUBPROCESS under a hard deadline (a bare in-process ``jax.devices()``
can hang for minutes when the axon tunnel is down — the round-1 failure
mode).  Orchestration: bounded-retry TPU probe → timed TPU attempt →
virtual-CPU fallback, so the run always emits its one JSON line.

Real-data modes (round 6): ``--data npy`` / ``--data folder`` feed the
step through the ``horovod_tpu.data`` pipeline (sharded source -> worker
pool -> double-buffered device prefetch) instead of device-resident
tensors, and ``--data synthetic-stream`` pushes the same synthetic
tensors through the pipeline — the A/B that prices the host-feeding path
against the resident headline.  Every mode now reports ``input_wait_ms``
and pipeline stats in the result JSON so BENCH_*.json tracks
input-boundness across rounds alongside ``mfu``.

Output: ONE JSON line {"metric", "value", "unit", "vs_baseline", ...}.
"""

import argparse
import json
import os
import subprocess
import sys
import time

BASELINE_IMG_PER_SEC = 330.0  # reference pytorch_synthetic_benchmark, 1x V100 fp32

# Dense peak bf16 FLOP/s per chip by generation, for the MFU estimate.
PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}
# ResNet-50 fwd @224 is ~4.09 GMACs = ~8.2 GFLOP (mul+add counted
# separately, the standard MFU convention); training step ~= 3x forward.
# Round 2 used 4.1e9 here — the MAC count — which under-stated MFU by 2x.
# Cross-checked against XLA cost analysis: 3.06e12 FLOP/step at batch 128
# = 7.97e9 fwd FLOP/img (PERF.md).  The headline "mfu" field is computed
# from the compiled program's own cost_analysis() when available, with
# this analytic constant as fallback ("mfu_model").
RESNET50_TRAIN_FLOPS_PER_IMG = 3 * 2 * 4.09e9

def peak_flops_for_current_gen():
    """Per-chip dense bf16 peak for the TPU generation the axon tunnel
    reports, or None when unknown (an assumed denominator would mis-state
    MFU by up to ~4.7x across generations)."""
    return PEAK_FLOPS.get(os.environ.get("PALLAS_AXON_TPU_GEN"))


PROBE_TIMEOUT_S = 60
# Overall deadline for the whole bench orchestration.  The driver runs this
# script under an external timeout; if that kills us before the result line
# prints, the round records NOTHING — strictly worse than a CPU fallback.
# Every window below is clipped so a CPU line always lands inside this.
DEADLINE_S = float(os.environ.get("BENCH_DEADLINE_S", 1800))
# Wall-clock budget for the initial probe window.  One axon outage at
# bench time erased round 3's TPU number (VERDICT round 3 weak #1); the
# probe now keeps retrying with backoff for this long before conceding.
PROBE_WINDOW_S = float(os.environ.get("BENCH_PROBE_WINDOW_S", 420))
# After a CPU fallback run, one last TPU attempt is made (the tunnel may
# have recovered while the CPU run burned time) within this extra window.
FINAL_PROBE_WINDOW_S = float(os.environ.get("BENCH_FINAL_PROBE_WINDOW_S", 120))
TPU_RUN_TIMEOUT_S = 330
CPU_RUN_TIMEOUT_S = 150


def probe_backend(window_s: float) -> str:
    """Probe the default JAX backend in a subprocess with a hard
    per-attempt timeout, retrying with backoff until ``window_s`` of
    wall-clock is spent.

    Returns "tpu", "cpu" (a clean deterministic cpu-only answer — no
    retries, no point re-probing later), or "unknown" (failures/hangs
    exhausted the window; the tunnel may recover)."""
    probe = "import jax; d = jax.devices(); assert d; print(d[0].platform)"
    deadline = time.monotonic() + window_s
    attempt = 0
    while True:
        attempt += 1
        per_attempt = min(PROBE_TIMEOUT_S, max(5, deadline - time.monotonic()))
        try:
            out = subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True,
                text=True,
                timeout=per_attempt,
            )
            if out.returncode == 0:
                return "cpu" if "cpu" in out.stdout else "tpu"
            reason = (out.stderr.strip().splitlines() or ["rc=%d" % out.returncode])[-1]
        except subprocess.TimeoutExpired:
            reason = f"probe hung >{per_attempt:.0f}s"
        remaining = deadline - time.monotonic()
        print(
            f"[bench] TPU probe attempt {attempt} failed ({reason}); "
            f"{remaining:.0f}s left in window",
            file=sys.stderr,
        )
        if remaining <= 5:
            return "unknown"
        time.sleep(min(remaining, min(60, 5 * attempt)))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--worker", default=None, choices=["tpu", "cpu"],
                   help="internal: run the measured loop itself")
    p.add_argument("--data", default="synthetic",
                   choices=["synthetic", "synthetic-stream", "npy", "folder"],
                   help="synthetic = device-resident tensors (headline); "
                        "synthetic-stream/npy/folder feed the step through "
                        "the horovod_tpu.data pipeline")
    p.add_argument("--data-path", default=None,
                   help="dataset root for --data npy/folder (npy "
                        "self-seeds a temp dir when omitted)")
    p.add_argument("--batch", type=int, default=None,
                   help="override the per-backend default batch size")
    return p.parse_args(argv)


def run_worker(mode: str, timeout_s: int, args=None):
    """Run ``bench.py --worker <mode>`` under a deadline.  Returns the JSON
    result line (str) or None — the caller decides which line to print so
    the one-line output contract holds across fallback + re-attempt."""
    env = dict(os.environ)
    if mode == "cpu":
        # prevent axon registration entirely so nothing can hang
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, os.path.abspath(__file__), "--worker", mode]
    if args is not None:
        cmd += ["--data", args.data]
        if args.data_path:
            cmd += ["--data-path", args.data_path]
        if args.batch:
            cmd += ["--batch", str(args.batch)]
    try:
        out = subprocess.run(
            cmd,
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
        )
    except subprocess.TimeoutExpired as e:
        if e.stderr:
            err = e.stderr if isinstance(e.stderr, str) else e.stderr.decode()
            sys.stderr.write(err[-3000:])
        print(f"[bench] {mode} run hung >{timeout_s}s", file=sys.stderr)
        return None
    sys.stderr.write(out.stderr)
    for line in out.stdout.splitlines():
        if line.startswith("{"):
            return line
    print(f"[bench] {mode} run rc={out.returncode}, no result line", file=sys.stderr)
    return None


class _EpochFeed:
    """Endless batch stream over a data.DataLoader (epoch after epoch),
    keeping every epoch's prefetcher so pipeline stats aggregate across
    the whole run — the timed window subtracts a snapshot taken at its
    start, so warmup batches never pollute the reported wait."""

    def __init__(self, loader):
        self.loader = loader
        self._iters = []

    def __iter__(self):
        epoch = 0
        while True:
            self.loader.set_epoch(epoch)
            it = iter(self.loader)
            self._iters.append(it)
            for item in it:
                yield item
            epoch += 1

    def stats(self) -> dict:
        totals = {}
        for it in self._iters:
            for k, v in it.stats().items():
                if k == "prefetch_depth":
                    totals[k] = v
                elif not k.endswith("_mean"):  # totals/counts sum cleanly
                    totals[k] = round(totals.get(k, 0) + v, 3)
        n = max(totals.get("batches", 1), 1)
        for key in ("input_wait", "host_produce", "device_put"):
            totals[f"{key}_ms_mean"] = round(
                totals.get(f"{key}_ms_total", 0.0) / n, 3)
        return totals


def _build_feed(args, batch: int, image_size: int, on_tpu: bool):
    """Build the pipeline-fed batch stream for the non-resident modes."""
    import numpy as np
    from horovod_tpu import data

    kind = "synthetic" if args.data == "synthetic-stream" else args.data
    path = args.data_path
    if kind == "npy" and path is None:
        # self-seed: uint8 shards on disk (the realistic storage dtype —
        # decode is astype(float32)/255 on the worker pool), enough for
        # 8 batches; the feed loops epochs so the step count is unbounded
        import atexit
        import shutil
        import tempfile

        n = 8 * batch
        rng = np.random.RandomState(0)
        inputs = rng.randint(0, 256, size=(n, image_size, image_size, 3),
                             dtype=np.uint8)
        labels = rng.randint(0, 1000, size=(n,)).astype(np.int32)
        path = tempfile.mkdtemp(prefix="hvd_tpu_bench_npy_")
        # ~155 MB at the TPU config, and the orchestrator may run up to
        # three workers per bench — always reap the seeded dir at exit
        atexit.register(shutil.rmtree, path, ignore_errors=True)
        data.write_npy_shards(path, inputs, labels, num_shards=4)
        print(f"[bench] seeded {n} uint8 samples into {path}",
              file=sys.stderr)
    loader = data.make_loader(
        kind, path, batch_size=batch, image_size=image_size,
        synthetic_samples=8 * batch,
        # bf16 host cast halves the host->device bytes; the first conv
        # consumes bf16 anyway (model dtype)
        cast="bfloat16" if on_tpu else None,
    )
    return _EpochFeed(loader)


def worker(mode: str, args) -> int:
    """The measured run itself.  mode: 'tpu' (default backend) or 'cpu'."""
    import jax

    if mode == "cpu":
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models.resnet import ResNet50
    from horovod_tpu import training

    hvd.init()
    on_tpu = jax.default_backend() != "cpu"
    if mode == "tpu" and not on_tpu:
        print("[bench] worker asked for tpu but got cpu backend", file=sys.stderr)
        return 1
    batch = args.batch or (128 if on_tpu else 16)
    image_size = 224 if on_tpu else 64
    warmup, iters = (5, 30) if on_tpu else (1, 2)

    # space_to_depth stem: mathematically identical to the classic 7x7/s2
    # stem (equivalence proven by test_space_to_depth_stem_equivalence)
    # but MXU-friendly — measured ~3.5% faster end-to-end (PERF.md)
    model = ResNet50(
        num_classes=1000, dtype=jnp.bfloat16, stem="space_to_depth"
    )
    rng = jax.random.PRNGKey(0)
    feed = None
    if args.data == "synthetic":
        # device-resident tensors: the headline config (input pipeline
        # exonerated as a limiter on this path — PERF.md r4 lever sweep)
        images = jnp.asarray(
            np.random.RandomState(0)
            .randn(batch, image_size, image_size, 3)
            .astype(np.float32)
        )
        labels = jnp.asarray(
            np.random.RandomState(1).randint(0, 1000, size=(batch,))
        )
    else:
        feed = _build_feed(args, batch, image_size, on_tpu)
        feed_iter = iter(feed)
        images, labels = next(feed_iter)

    optimizer = optax.sgd(0.1, momentum=0.9)
    state = training.create_train_state(model, optimizer, rng, images[:2])
    state = training.replicate_state(state)
    step = training.data_parallel_train_step(model, optimizer)

    # AOT-compile once; reuse the executable for the loops (the jit cache
    # is not guaranteed to share an AOT compilation, and compiling twice
    # risks the TPU_RUN_TIMEOUT_S deadline).  XLA's own FLOP count is the
    # self-verifying numerator for MFU (PERF.md documents the cross-check
    # vs the analytic count) — but cost_analysis() describes the
    # SPMD-partitioned *per-device* module, so it is only used as the
    # headline MFU when there is exactly one device (the bench's config);
    # multi-device runs fall back to the analytic model count.
    xla_flops = None
    try:
        step = step.lower(state, images, labels).compile()
    except Exception as e:
        print(f"[bench] AOT compile unavailable: {e}", file=sys.stderr)
    else:
        try:
            ca = step.cost_analysis()
            if isinstance(ca, list):
                ca = ca[0] if ca else None
            if ca:
                xla_flops = float(ca.get("flops", 0)) or None
        except Exception as e:  # best-effort on remote backends
            print(f"[bench] cost_analysis unavailable: {e}", file=sys.stderr)

    if feed is None:
        for _ in range(warmup):
            state, loss = step(state, images, labels)
    else:
        state, loss = step(state, images, labels)  # the batch compile ate
        for _ in range(warmup - 1):
            state, loss = step(state, *next(feed_iter))
    # fetch the scalar (not just block_until_ready): a device->host
    # roundtrip is the only sync some remote backends honor
    float(loss)

    wait0 = feed.stats() if feed is not None else {}
    t0 = time.perf_counter()
    if feed is None:
        for _ in range(iters):
            state, loss = step(state, images, labels)
    else:
        for _ in range(iters):
            state, loss = step(state, *next(feed_iter))
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss), f"non-finite loss {final_loss}"

    img_per_sec = batch * iters / dt
    # input-boundness record (round-6 ask #3): wait accumulated over the
    # TIMED window only, so BENCH_*.json tracks it alongside mfu
    if feed is not None:
        pipeline = feed.stats()
        input_wait_ms = round(
            (pipeline.get("input_wait_ms_total", 0.0)
             - wait0.get("input_wait_ms_total", 0.0)) / iters, 3)
        pipeline["starved_batches"] = int(
            pipeline.get("starved_batches", 0)
            - wait0.get("starved_batches", 0))
        timed = max(
            int(pipeline.pop("batches", 0) - wait0.get("batches", 0)), 1)
        pipeline["timed_batches"] = timed
        # per-batch means over the TIMED window only — whole-run means
        # would fold warmup (incl. the compile step) into the record
        for key in ("host_produce", "device_put"):
            pipeline[f"{key}_ms_mean"] = round(
                (pipeline.get(f"{key}_ms_total", 0.0)
                 - wait0.get(f"{key}_ms_total", 0.0)) / timed, 3)
        for k in ("input_wait_ms_total", "input_wait_ms_mean",
                  "host_produce_ms_total", "device_put_ms_total"):
            pipeline.pop(k, None)
        from horovod_tpu.data import workers as _data_workers

        pipeline["workers"] = _data_workers.default_num_workers()
    else:
        pipeline = {"mode": "device_resident"}
        input_wait_ms = 0.0
    # memory-per-rank record (ISSUE 6): live-buffer accounting plus the
    # optimizer-state split PERF.md's capacity arithmetic reasons about —
    # `opt_state_bytes` is this run's replicated per-rank cost and
    # `opt_state_bytes_zero` the modeled ZeRO-1 shard
    # (horovod_tpu.optim.ZeroDistributedOptimizer) at this world size
    from horovod_tpu.optim import state_bytes as _state_bytes

    world = max(jax.device_count(), 1)
    memory_per_rank = {
        "params_bytes": int(_state_bytes(state.params)),
        "opt_state_bytes": int(_state_bytes(state.opt_state)),
        "opt_state_bytes_zero": int(-(-_state_bytes(state.opt_state)
                                      // world)),
        "world": world,
    }
    try:
        memory_per_rank["live_buffer_bytes"] = int(sum(
            int(getattr(a, "nbytes", 0) or 0) for a in jax.live_arrays()
        ))
    except Exception as e:  # accounting must never sink the bench line
        print(f"[bench] live-array accounting unavailable: {e}",
              file=sys.stderr)
    # per-step gradient-exchange byte record (ISSUE 7): the modeled
    # per-tier traffic of allreducing every parameter gradient once,
    # flat vs this topology's routing (ops/comm_model.py; one entry per
    # tier + the DCN wire dtype the HVD_TPU_* env selects) — what
    # hierarchical routing + DCN compression exist to shrink
    from horovod_tpu.common import basics as _basics
    from horovod_tpu.ops.comm_model import (
        modeled_collective_bytes as _comm_bytes,
    )

    _st = _basics._state
    _topo = _st.topology if _st is not None else None
    n_slices = _topo.num_slices if _topo is not None else 1
    _cfg = _st.config if _st is not None else None
    hier_on = bool(
        _cfg is not None and _cfg.hierarchical_allreduce and n_slices > 1
    )
    wire = None
    if hier_on:
        from horovod_tpu.compression import dcn_compression_from_name

        _comp = dcn_compression_from_name(_cfg.dcn_wire_dtype)
        wire = str(_comp.wire_dtype) if _comp is not None else None
        n_ici = _topo.slice_size
    else:
        n_ici = 1 if n_slices > 1 else world
    comm = {"ici": 0, "dcn": 0}
    try:
        for leaf in jax.tree_util.tree_leaves(state.params):
            m = _comm_bytes(np.shape(leaf), world, n_ici,
                            wire_dtype=wire, dtype=str(leaf.dtype))
            comm["ici"] += m["ici_bytes"]
            comm["dcn"] += m["dcn_bytes"]
    except Exception as e:  # accounting must never sink the bench line
        print(f"[bench] comm-bytes accounting unavailable: {e}",
              file=sys.stderr)
        comm = {"ici": 0, "dcn": 0}
    comm["wire_dtype"] = wire
    comm["routing"] = "hierarchical" if hier_on else "flat"

    result = {
        "metric": "resnet50_synthetic_train_throughput",
        "value": round(img_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 3),
        "backend": jax.default_backend(),
        "batch": batch,
        "image_size": image_size,
        "step_time_ms": round(dt / iters * 1e3, 2),
        "n_devices": jax.device_count(),
        "data": args.data,
        "input_wait_ms": input_wait_ms,
        "input_wait_pct": round(
            100.0 * input_wait_ms / max(dt / iters * 1e3, 1e-9), 2),
        "pipeline": pipeline,
        "memory_per_rank": memory_per_rank,
        "comm_bytes": comm,
    }
    if not on_tpu:
        # the record must say WHY it is a CPU number (probe failure or a
        # failed TPU attempt — the orchestrator prints which to stderr);
        # the chip-measured history lives in PERF.md / BENCH_r02.json
        result["note"] = (
            "cpu fallback: the tpu probe or tpu run failed at bench "
            "time; see PERF.md for the chip-measured record"
        )
    gen = os.environ.get("PALLAS_AXON_TPU_GEN")
    if on_tpu and image_size == 224 and gen in PEAK_FLOPS:
        # MFU only when the generation is explicitly known — a guessed
        # peak-FLOPs denominator would mis-state MFU by up to ~4.7x.
        # img_per_sec is aggregate across the data-parallel world, so
        # normalize to per-chip before dividing by per-chip peak.
        peak = PEAK_FLOPS[gen]
        step_s = dt / iters
        mfu_model = round(
            img_per_sec / jax.device_count()
            * RESNET50_TRAIN_FLOPS_PER_IMG / peak, 4
        )
        if xla_flops and jax.device_count() == 1:
            # headline MFU from XLA's measured FLOP count of the compiled
            # step — unambiguous single-chip (per-device == whole-program)
            result["mfu"] = round(xla_flops / step_s / peak, 4)
            result["mfu_model"] = mfu_model
            result["xla_flops_per_step"] = xla_flops
        else:
            result["mfu"] = mfu_model
        result["tpu_gen"] = gen
    print(json.dumps(result))
    return 0


def main() -> int:
    args = parse_args()
    if args.worker:
        return worker(args.worker, args)
    t0 = time.monotonic()

    def remaining() -> float:
        return DEADLINE_S - (time.monotonic() - t0)

    # clip the probe window so a failed probe + CPU fallback still fits
    probe_window = max(
        30.0, min(PROBE_WINDOW_S, remaining() - CPU_RUN_TIMEOUT_S - 30))
    backend = probe_backend(probe_window)
    if backend == "tpu":
        line = run_worker("tpu", TPU_RUN_TIMEOUT_S, args)
        if line:
            print(line)
            return 0
        print("[bench] TPU attempt failed; falling back to CPU", file=sys.stderr)
    elif backend == "cpu":
        print(
            "[bench] this host's default backend is CPU (deterministic "
            "answer, no retries spent); running the CPU benchmark",
            file=sys.stderr,
        )
    else:
        print(
            f"[bench] TPU probe exhausted its {probe_window:.0f}s retry "
            "window; running the CPU fallback, then re-probing once more",
            file=sys.stderr,
        )
    cpu_line = run_worker("cpu", CPU_RUN_TIMEOUT_S, args)
    # End-of-run TPU re-attempt — for the hung/unknown probe and for a
    # probe-ok-but-run-failed outage (the tunnel may have recovered while
    # the CPU run burned time); never for a deterministic cpu-only host.
    # A late TPU number beats a CPU fallback every time — but only chase
    # it when a full probe + chip run still fits the deadline; at the
    # margin, banking the CPU line beats risking an empty round.
    if (backend != "cpu"
            and remaining() > FINAL_PROBE_WINDOW_S + TPU_RUN_TIMEOUT_S + 30
            and probe_backend(FINAL_PROBE_WINDOW_S) == "tpu"):
        print("[bench] TPU recovered; re-attempting the chip run", file=sys.stderr)
        line = run_worker("tpu", TPU_RUN_TIMEOUT_S, args)
        if line:
            print(line)
            return 0
    if cpu_line:
        print(cpu_line)
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
