#!/usr/bin/env python
"""Headline benchmark: ResNet-50 synthetic-data training throughput.

Reference parity: examples/pytorch/pytorch_synthetic_benchmark.py and
examples/tensorflow2/tensorflow2_synthetic_benchmark.py — the scripts the
reference's own docs point at for measuring img/sec (BASELINE.md).  Same
protocol: synthetic ImageNet-shaped data, warmup then timed steps, report
images/sec.

Baseline constant: the reference repo publishes no absolute number
(BASELINE.md: "user-measured"); the widely reported figure for its
pytorch_synthetic_benchmark on the reference-era flagship (V100, fp32,
batch 32) is ~330 img/sec, which we use as vs_baseline's denominator.

Output: ONE JSON line {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models.resnet import ResNet50
from horovod_tpu import training

BASELINE_IMG_PER_SEC = 330.0  # reference pytorch_synthetic_benchmark, 1x V100 fp32


def main():
    hvd.init()
    on_tpu = jax.default_backend() not in ("cpu",)
    batch = 128 if on_tpu else 16
    image_size = 224 if on_tpu else 64
    warmup, iters = (3, 20) if on_tpu else (1, 2)

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    images = jnp.asarray(
        np.random.RandomState(0)
        .randn(batch, image_size, image_size, 3)
        .astype(np.float32)
    )
    labels = jnp.asarray(
        np.random.RandomState(1).randint(0, 1000, size=(batch,))
    )

    optimizer = optax.sgd(0.1, momentum=0.9)
    state = training.create_train_state(
        model, optimizer, rng, images[:2]
    )
    state = training.replicate_state(state)
    step = training.data_parallel_train_step(model, optimizer)

    for _ in range(warmup):
        state, loss = step(state, images, labels)
    # fetch the scalar (not just block_until_ready): a device->host
    # roundtrip is the only sync some remote backends honor
    float(loss)

    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = step(state, images, labels)
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss), f"non-finite loss {final_loss}"

    img_per_sec = batch * iters / dt
    print(
        json.dumps(
            {
                "metric": "resnet50_synthetic_train_throughput",
                "value": round(img_per_sec, 2),
                "unit": "images/sec",
                "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
