"""MNIST-style training with the MXNet adapter.

Reference parity: examples/mxnet/mxnet_mnist.py — the canonical Gluon
script shape: hvd.init, per-rank data shard, DistributedTrainer over
collect_params-style parameters, parameter broadcast from rank 0,
metric allreduce.  Only the import line differs from the reference.

mxnet is not installable in this image (archived upstream); to run the
example here, put the test fake on the path first:

    PYTHONPATH=tests/_fake_modules tpurun -np 2 \
        python examples/mxnet/mxnet_mnist.py --epochs 1

With a real mxnet install the same script runs unchanged (the fake
implements the subset of the NDArray/gluon API this script uses).
"""

import argparse

import numpy as np

import mxnet as mx

import horovod_tpu.mxnet as hvd


def synthetic_mnist(n=2048, seed=0):
    """Linearly separable blobs in 784-d (no dataset downloads here)."""
    rng = np.random.RandomState(seed)
    centers = rng.randn(10, 784).astype(np.float32) * 2.0
    labels = rng.randint(0, 10, size=n)
    feats = centers[labels] + rng.randn(n, 784).astype(np.float32) * 0.5
    return feats, labels.astype(np.int32)


def build_params(seed):
    """A 784->10 linear classifier as gluon Parameters (the fake gluon
    has no full Block machinery; with real mxnet swap in gluon.nn.Dense
    and net.collect_params())."""
    rng = np.random.RandomState(seed)
    w = mx.gluon.Parameter("weight", shape=(784, 10))
    b = mx.gluon.Parameter("bias", shape=(10,))
    w.data()[:] = (rng.randn(784, 10) * 0.01).astype(np.float32)
    b.data()[:] = np.zeros(10, np.float32)
    return {"weight": w, "bias": b}


def forward(params, x):
    return x @ params["weight"].data().asnumpy() \
        + params["bias"].data().asnumpy()


def softmax_xent_grads(params, x, y):
    """Loss + grads for the linear model (numpy autodiff by hand — the
    fake has no autograd; real mxnet scripts use autograd.record())."""
    logits = forward(params, x)
    z = logits - logits.max(axis=1, keepdims=True)
    p = np.exp(z)
    p /= p.sum(axis=1, keepdims=True)
    n = len(y)
    loss = -np.log(p[np.arange(n), y] + 1e-9).mean()
    # gluon convention: grads are batch SUMS; trainer.step(batch_size)
    # applies the 1/batch
    dlogits = p
    dlogits[np.arange(n), y] -= 1.0
    params["weight"].grad()[:] = (x.T @ dlogits).astype(np.float32)
    params["bias"].grad()[:] = dlogits.sum(axis=0).astype(np.float32)
    return loss


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()

    hvd.init()
    nproc, me = hvd.cross_size(), hvd.cross_rank()

    feats, labels = synthetic_mnist()
    shard = slice(me, len(feats), nproc)  # rank-strided shard
    feats, labels = feats[shard], labels[shard]

    params = build_params(seed=me)  # deliberately divergent init
    hvd.broadcast_parameters(params, root_rank=0)  # …made identical

    trainer = hvd.DistributedTrainer(
        params, "sgd", {"learning_rate": args.lr * nproc}
    )

    steps = len(feats) // args.batch_size
    for epoch in range(args.epochs):
        loss = None
        for s in range(steps):
            sl = slice(s * args.batch_size, (s + 1) * args.batch_size)
            loss = softmax_xent_grads(params, feats[sl], labels[sl])
            trainer.step(args.batch_size)
        avg = hvd.allreduce(
            mx.nd.array(np.array([loss], np.float32)), name="loss"
        )
        if me == 0:
            print(f"epoch {epoch}: loss {float(avg.asnumpy()[0]):.4f}",
                  flush=True)

    # final train accuracy, averaged across ranks
    acc = (forward(params, feats).argmax(axis=1) == labels).mean()
    acc = hvd.allreduce(mx.nd.array(np.array([acc], np.float32)),
                        name="acc")
    if me == 0:
        final = float(acc.asnumpy()[0])
        print(f"final accuracy: {final:.3f}", flush=True)
        assert final > 0.9, f"did not converge: {final}"


if __name__ == "__main__":
    main()
