"""Elastic MNIST-style training.

Reference parity: examples/elastic/pytorch/pytorch_mnist_elastic.py — the
commit/restore/sync elastic loop (SURVEY.md §3.4), JAX flavor.

Run:  tpurun -np 2 --min-np 1 --max-np 4 \
          --host-discovery-script ./discover.sh \
          python examples/jax/jax_elastic_mnist.py
where discover.sh prints the current "host:slots" lines.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models.simple import MLP


def main():
    hvd.init()
    rng = np.random.RandomState(0)
    images = rng.randn(4096, 784).astype(np.float32)
    labels = rng.randint(0, 10, size=(4096,))

    model = MLP(features=(128, 10))
    variables = model.init(jax.random.PRNGKey(0), jnp.ones((1, 784)))
    optimizer = optax.sgd(0.05 * hvd.cross_size(), momentum=0.9)

    sampler = hvd.elastic.ElasticSampler(len(images), shuffle=True)
    state = hvd.elastic.TpuState(
        params=variables["params"],
        opt_state=optimizer.init(variables["params"]),
        sampler=sampler, epoch=0, batch=0,
    )
    # rescale the learning rate when the world resizes (reference idiom)
    state.register_reset_callbacks([lambda: print(
        f"[rank {hvd.rank()}] world resized to {hvd.cross_size()}")])

    @jax.jit
    def train_step(params, opt_state, x, y):
        def loss_fn(p):
            logits = model.apply({"params": p}, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    batch_size = 32

    @hvd.elastic.run
    def train(state):
        while state.epoch < 3:
            if state.sampler.epoch != state.epoch:
                # entering a NEW epoch.  On a mid-epoch resume/resize the
                # restored sampler already carries this epoch's progress;
                # set_epoch here would wipe it and the old batch offset
                # would slice a shard computed for the new world — samples
                # dropped AND duplicated.
                state.sampler.set_epoch(state.epoch)
            # this rank's REMAINING shard for the current world; batch
            # indices restart at 0 relative to it on every (re)entry
            indices = list(state.sampler)
            state.batch = 0
            while state.batch * batch_size < len(indices):
                lo = state.batch * batch_size
                idx = indices[lo:lo + batch_size]
                if not idx:
                    break
                x, y = jnp.asarray(images[idx]), jnp.asarray(labels[idx])
                params, opt_state, loss = train_step(
                    state.params, state.opt_state, x, y)
                # gradients are per-shard; average the step's result via
                # the eager path (small model; big models: shard_map step)
                state.params = hvd.allreduce(params)
                state.opt_state = jax.tree_util.tree_map(
                    lambda a: hvd.allreduce(a) if hasattr(a, "dtype") and
                    jnp.issubdtype(a.dtype, jnp.floating) else a, opt_state)
                state.sampler.record_batch(state.batch, batch_size)
                state.batch += 1
                if state.batch % 8 == 0:
                    state.commit()
            state.batch = 0
            state.epoch += 1
            state.sampler.set_epoch(state.epoch)
            state.commit()
            if hvd.rank() == 0:
                print(f"epoch {state.epoch} done "
                      f"(world={hvd.cross_size()}, loss={float(loss):.3f})")

    train(state)


if __name__ == "__main__":
    main()
